#!/usr/bin/env python
"""Diff two BENCH_*.json artifacts and fail on throughput OR memory
regression.

Usage:
    python scripts/bench_compare.py OLD.json NEW.json [--threshold 0.10]
                                    [--key value]

Compares ``NEW[key]`` against ``OLD[key]`` (default key: ``value``, the
headline events/sec) and exits nonzero when the new number is more than
``threshold`` (default 10%) below the old one.  Also compared, when both
files carry them:

- ``incremental.steady_evps`` and ``stream.evps`` (higher is better — a
  drop >threshold fails, so the streaming config-5 throughput is gated
  exactly like the batch headline);
- the peak-memory metrics ``peak_host_bytes`` / ``peak_device_bytes`` /
  ``stream.peak_resident_visibility_bytes`` (LOWER is better — a rise
  >threshold fails, so a change that silently re-materializes an
  O(N²) slab trips the gate even when throughput improves);
- the finality-latency metrics ``finality.<engine>.ttf_p99`` (p99
  time-to-finality, seconds) and ``finality.<engine>.rtd_mean`` (mean
  rounds-to-decision) for the incremental/batch/streaming engines
  (LOWER is better — deciding the same history later is a latency
  regression even when events/sec holds).

Driver artifacts that wrap the bench line (``{"cmd": ..., "parsed":
{...}}`` — the BENCH_rNN.json files) are unwrapped automatically, so
``bench_compare.py BENCH_r05.json /tmp/BENCH_new.json`` works on the
checked-in history directly.

Everything else (phases, window stats) is printed as an informational
diff.

Opt-in wiring: this is NOT part of tier-1 (bench numbers are machine-
dependent); run it from CI or by hand after a bench run, e.g.::

    python bench.py > /tmp/BENCH_new.json
    python scripts/bench_compare.py BENCH_r05.json /tmp/BENCH_new.json

(A shape-level smoke test lives in tests/test_aux.py so the tool itself
cannot rot.)
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

#: (dotted key, higher_is_better) — memory keys gate in the opposite
#: direction from throughput keys
EXTRA_KEYS = [
    ("incremental.steady_evps", True),
    ("stream.evps", True),
    ("peak_host_bytes", False),
    ("peak_device_bytes", False),
    ("stream.peak_resident_visibility_bytes", False),
    # mesh-streaming artifacts (bench.py --stream --mesh D): throughput
    # and scaling efficiency must not regress, per-device residency and
    # re-pin churn must not grow
    ("stream_mesh.evps", True),
    ("stream_mesh.scaling_efficiency", True),
    ("stream_mesh.peak_device_tiles", False),
    ("stream_mesh.repins", False),
    # adversary-overhead artifacts (bench.py --chaos-overhead): ev/s with
    # an equivocation storm at the full f budget, fault-free ev/s on the
    # same shape, and their ratio (attack/clean — a falling ratio means
    # the adversary path got relatively more expensive)
    ("chaos_overhead.clean_evps", True),
    ("chaos_overhead.attack_evps", True),
    ("chaos_overhead.ratio", True),
    # finality-latency artifacts (the bench `finality` section): p99
    # time-to-finality and mean rounds-to-decision are LOWER-is-better —
    # a change that decides the same history later (more virtual-voting
    # rounds, slower window passes) regresses user-visible latency even
    # when throughput holds
    ("finality.incremental.ttf_p99", False),
    ("finality.incremental.rtd_mean", False),
    ("finality.batch.rtd_mean", False),
    ("finality.streaming.ttf_p99", False),
    ("finality.streaming.rtd_mean", False),
    # real-process cluster artifacts (bench.py --cluster): decided
    # transactions per second across a 5-process loopback cluster, and
    # the merged p99 submission→decided wall latency — throughput must
    # not fall, tail latency must not grow
    ("cluster.tx_per_s", True),
    ("cluster.submit_p99_s", False),
    # production-day soak artifacts (bench.py --soak): acked client
    # tx/s under the composed fault schedule, client-observed p99
    # submit→ack latency, and the number of disruption windows the
    # cluster advanced past — throughput and survival must not fall,
    # tail latency must not grow
    ("soak.tx_per_s", True),
    ("soak.submit_p99_s", False),
    ("soak.disruptions_survived", True),
    # dispatch-profiler artifacts (bench.py --stream): the non-device
    # per-chunk cost (wall minus stage time) the streaming engine pays —
    # LOWER is better; a driver change that adds host work or transfer
    # stalls per chunk regresses it even when evps holds
    ("stream.dispatch_overhead_s", False),
    # dynamic-membership churn artifacts (bench.py --churn): events/sec
    # through the epoch-aware driver over a multi-epoch schedule (higher
    # is better — a restatement or ledger-bookkeeping slowdown shows up
    # here first), the p99 member-axis repack latency at an epoch
    # boundary (LOWER is better — repack is on the live ingest path),
    # and the epoch count (higher is better: a silently-undecided
    # membership tx would *raise* evps while breaking the semantics)
    ("churn.evps", True),
    ("churn.repack_p99_s", False),
    ("churn.epochs", True),
]

#: artifacts whose tracing overhead exceeded this ratio are refused —
#: the profiled sample perturbed the run too much to vouch for its
#: numbers (ISSUE 16 acceptance: tracing keeps stream.evps within 5%)
MAX_TRACE_OVERHEAD_RATIO = 0.05


def unwrap(doc: Dict) -> Dict:
    """Driver artifacts wrap the bench JSON line under ``parsed``."""
    if "value" not in doc and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _get(d: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def lint_gate(new: Dict) -> Optional[str]:
    """Refuse to gate a candidate produced from a tree with lint
    findings.  bench.py stamps ``lint`` (``tpu_swirld.analysis``
    summary) into every artifact; a stamp with findings means the
    number came from code violating the determinism/jit/thread
    invariants and is not comparable.  Artifacts predating the stamp
    (BENCH_r01–r05) pass with a warning — the gate only hardens going
    forward."""
    lint = new.get("lint")
    if lint is None:
        return None
    if isinstance(lint, dict) and lint.get("clean"):
        return None
    return (
        f"candidate tree had lint findings ({lint!r}); run "
        "scripts/lint.sh, fix, and re-bench before gating"
    )


def mc_gate(new: Dict) -> Optional[str]:
    """Refuse to gate a candidate whose model-checker smoke stamp is
    dirty.  bench.py stamps ``mc`` (``tpu_swirld.analysis.mc``
    ``mc_smoke``: the small world explored exhaustively under the full
    invariant catalog) into every artifact; a stamp that is not ``ok``
    means the consensus core the bench exercised violates its own
    invariants, so the number is not comparable.  Artifacts predating
    the stamp pass with a note — the gate only hardens going forward."""
    mc = new.get("mc")
    if mc is None:
        return None
    if isinstance(mc, dict) and mc.get("ok"):
        return None
    return (
        f"candidate tree failed the model-checker smoke ({mc!r}); run "
        "python -m tpu_swirld.analysis mc, fix, and re-bench before gating"
    )


def scale_audit_gate(new: Dict) -> Optional[str]:
    """Refuse to gate a candidate without a *clean* scale-audit stamp.

    bench.py stamps ``scale_audit`` (the jaxpr-level interval/dtype flow
    proof that the kernels are wrap- and bounds-safe at the baseline
    envelope) into every artifact.  Unlike the lint/mc gates, a missing
    stamp also refuses: the audit ships with the stamp, so "missing"
    can only mean the artifact was produced by a stripped bench or the
    stamp was deleted — either way the number is unvouched."""
    sa = new.get("scale_audit")
    if isinstance(sa, dict) and sa.get("clean"):
        return None
    if sa is None:
        return (
            "candidate carries no scale_audit stamp; re-bench with the "
            "current bench.py (python -m tpu_swirld.analysis scale-audit "
            "proves the kernels wrap- and bounds-safe) before gating"
        )
    return (
        f"candidate tree failed the scale audit ({sa!r}); run "
        "python -m tpu_swirld.analysis scale-audit, fix or justify each "
        "finding, and re-bench before gating"
    )


def soak_gate(new: Dict) -> Optional[str]:
    """Refuse a candidate whose soak run went red.  bench.py --soak
    stamps ``soak.verdict_ok`` — the composite verdict (oracle-replay
    bit-parity, liveness past every disruption window, finality-tail
    budget, zero shed-accounting leaks) over the composed chaos
    scenario.  A red soak means the numbers were measured on a cluster
    that lost safety, liveness, or transactions; they are not
    comparable regardless of how good they look.  Artifacts without the
    stamp (non-soak benches) pass untouched."""
    soak = new.get("soak")
    if not isinstance(soak, dict) or "verdict_ok" not in soak:
        return None
    if soak.get("verdict_ok"):
        return None
    return (
        "candidate's soak verdict is red (soak.verdict_ok false): the "
        "cluster lost safety, liveness, finality budget, or shed "
        "accounting under the composed schedule; replay the minimized "
        "schedule doc from scripts/soak_run.py, fix, and re-bench"
    )


def trace_overhead_gate(new: Dict) -> Optional[str]:
    """Refuse a candidate whose own profiled sample shows tracing
    perturbing the streaming run by more than
    :data:`MAX_TRACE_OVERHEAD_RATIO` — its dispatch-overhead and evps
    numbers were measured under observer distortion and are not
    comparable.  Artifacts without the stamp (pre-profiler, or profiling
    disabled) pass untouched."""
    ratio = _get(new, "stream.trace_overhead_ratio")
    if ratio is None or ratio <= MAX_TRACE_OVERHEAD_RATIO:
        return None
    return (
        f"candidate's tracing overhead ratio {ratio:.1%} exceeds "
        f"{MAX_TRACE_OVERHEAD_RATIO:.0%}: the profiled sample perturbed "
        "the run; shrink BENCH_STREAM_PROFILE or fix the profiler cost "
        "and re-bench before gating"
    )


def compare(old: Dict, new: Dict, key: str, threshold: float):
    """Returns (failures, report_lines)."""
    lines = []
    failures = []
    for k, higher_better in [(key, True)] + EXTRA_KEYS:
        ov, nv = _get(old, k), _get(new, k)
        if ov is None or nv is None:
            if k == key:
                failures.append(f"missing key {k!r} in one of the inputs")
            continue
        delta = (nv - ov) / ov if ov else 0.0
        bad = delta < -threshold if higher_better else delta > threshold
        verdict = "ok"
        if bad:
            direction = "below" if higher_better else "above"
            verdict = f"REGRESSION (>{threshold:.0%} {direction} old)"
            failures.append(f"{k}: {ov:.1f} -> {nv:.1f} ({delta:+.1%})")
        lines.append(
            f"{k:<40} {ov:>14.1f} -> {nv:>14.1f}  {delta:+7.1%}  {verdict}"
        )
    op, np_ = old.get("phases") or {}, new.get("phases") or {}
    for k in sorted(set(op) | set(np_)):
        ov, nv = op.get(k), np_.get(k)
        if isinstance(ov, (int, float)) and isinstance(nv, (int, float)):
            lines.append(f"  phase {k:<40} {ov:>12} -> {nv:>12}")
    return failures, lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH json file")
    ap.add_argument("new", help="candidate BENCH json file")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="allowed fractional drop in throughput / rise in "
                         "peak memory (default 0.10 = 10%%)")
    ap.add_argument("--key", default="value",
                    help="headline metric key (default: value)")
    args = ap.parse_args(argv)
    with open(args.old) as f:
        old = unwrap(json.load(f))
    with open(args.new) as f:
        new = unwrap(json.load(f))
    for gate in (lint_gate(new), mc_gate(new), scale_audit_gate(new),
                 trace_overhead_gate(new), soak_gate(new)):
        if gate is not None:
            print(f"\nFAIL: {gate}", file=sys.stderr)
            return 1
    if new.get("lint") is None:
        print("note: candidate carries no lint stamp (pre-analysis "
              "artifact); gating on metrics only", file=sys.stderr)
    if new.get("mc") is None:
        print("note: candidate carries no model-checker stamp "
              "(pre-mc artifact); gating on metrics only", file=sys.stderr)
    failures, lines = compare(old, new, args.key, args.threshold)
    for ln in lines:
        print(ln)
    if failures:
        print("\nFAIL:", "; ".join(failures), file=sys.stderr)
        return 1
    print("\nOK: no throughput or peak-memory regression beyond "
          f"{args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
