#!/usr/bin/env python
"""Production-day soak runner with shrink-on-failure.

Runs the composed chaos scenario from :mod:`tpu_swirld.soak`: an
N-process cluster gossiping through per-link TCP fault proxies, under
heavy-tailed client traffic, while the window schedule interleaves
SIGKILL crashes (+ WAL recovery), partition/heal windows, and a
byzantine equivocation storm served through the proxy seam.  Emits the
composite verdict as JSON; exit status 0 iff green.

    python scripts/soak_run.py --smoke                 # tier-1 composition
    python scripts/soak_run.py --horizon 60 --nodes 5  # the real soak
    python scripts/soak_run.py --smoke --mutate shed-leak
                                                       # must go red + shrink
    python scripts/soak_run.py --replay minimized.schedule.json

On a red verdict the runner ddmin-reduces the schedule to a 1-minimal
replayable failure document (``minimized.schedule.json`` in the
workdir) unless ``--no-shrink`` is given.  Defaults for the unset knobs
come from ``SWIRLD_SOAK_*`` (field > env > default, see
``resolve_soak_settings``).
"""

import argparse
import dataclasses
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_swirld import soak   # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="deterministic tier-1 composition: 1 crash + "
                         "1 partition + 1 attack window, short horizon")
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--horizon", type=float, default=None,
                    help="soak horizon in seconds")
    ap.add_argument("--rate", type=float, default=None,
                    help="aggregate client submissions per second")
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--mutate", choices=sorted(soak.MUTATIONS),
                    default=None,
                    help="inject a seeded defect; the verdict must go red")
    ap.add_argument("--membership", action="store_true",
                    help="DynamicNode cluster + a MembershipWindow "
                         "(restake tx) at 30%% of the horizon — the "
                         "verdict gains an epochs-decided gate")
    ap.add_argument("--no-shrink", action="store_true",
                    help="skip ddmin schedule reduction on a red verdict")
    ap.add_argument("--replay", default=None, metavar="DOC",
                    help="re-run a saved (minimized) schedule doc")
    ap.add_argument("--workdir", default=None,
                    help="soak state dir (default: fresh tempdir)")
    ap.add_argument("--gossip-interval", type=float, default=0.005)
    ap.add_argument("--checkpoint-every", type=float, default=0.5)
    ap.add_argument("--out", default=None, help="verdict JSON path")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="swirld-soak-")

    if args.replay:
        verdict = soak.replay_doc(soak.load_doc(args.replay), workdir)
    else:
        overrides = {
            "seed": args.seed,
            "mutate": args.mutate,
            "net": {
                "gossip_interval_s": args.gossip_interval,
                "checkpoint_every_s": args.checkpoint_every,
            },
        }
        if args.nodes is not None:
            overrides["n_nodes"] = args.nodes
        if args.rate is not None:
            overrides["tx_rate"] = args.rate
        if args.clients is not None:
            overrides["n_clients"] = args.clients
        if args.horizon is not None:
            overrides["horizon_s"] = args.horizon
        elif args.smoke:
            overrides["horizon_s"] = 7.0
        spec = soak.default_spec(workdir, **overrides)
        schedule = soak.smoke_schedule(spec)
        if args.membership:
            schedule = schedule + (
                soak.MembershipWindow(
                    at_s=spec.horizon_s * 0.3, action="restake",
                    member=1, stake=3,
                ),
            )
        spec = dataclasses.replace(
            spec, schedule=schedule, dynamic=args.membership,
        )
        verdict = soak.run_soak(spec)
        if not verdict["ok"] and not args.no_shrink:
            doc = soak.shrink(spec)
            verdict["minimized_doc"] = soak.save_doc(
                doc, os.path.join(workdir, "minimized.schedule.json"),
            )
            verdict["minimized_schedule"] = doc["schedule"]

    verdict["workdir"] = workdir
    text = json.dumps(verdict, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
