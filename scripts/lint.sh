#!/usr/bin/env bash
# Invariant linter entry point: exits non-zero on any finding.
# Usage: scripts/lint.sh [paths...]   (default: the tpu_swirld package)
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m tpu_swirld.analysis lint "${@:-tpu_swirld}"
