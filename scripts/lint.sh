#!/usr/bin/env bash
# Static-analysis entry point: exits non-zero on any finding.
#   1. invariant linter over the package (AST rules SW001..)
#   2. scale audit at the baseline envelope (jaxpr interval/dtype flow,
#      rules SW008-SW011) across all engines
# Usage: scripts/lint.sh [paths...]   (default: the tpu_swirld package)
set -euo pipefail
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m tpu_swirld.analysis lint "${@:-tpu_swirld}"
exec env JAX_PLATFORMS=cpu python -m tpu_swirld.analysis scale-audit --envelope baseline
