#!/usr/bin/env bash
# Static-analysis entry point: exits non-zero on any finding.
#   1. invariant linter over the package (AST rules SW001..)
#   2. scale audit at the baseline envelope (jaxpr interval/dtype flow,
#      rules SW008-SW011) across all engines
#   3. fused-dispatch modules (the megadispatch rounds span and its
#      feeders) must be SW003/SW004-clean with JUSTIFIED suppressions
#      only: a bare "# swirld-lint: disable=SW003" (no "-- why" note) or
#      a file-wide disable in these files fails, mirroring the SW008
#      flow-audit semantics — wall-clock reads or unpinned dtypes inside
#      the fused scan would silently break the async==sync parity and
#      the donation-carry dtype contract.
# Usage: scripts/lint.sh [paths...]   (default: the tpu_swirld package)
set -euo pipefail
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m tpu_swirld.analysis lint "${@:-tpu_swirld}"
env JAX_PLATFORMS=cpu python -m tpu_swirld.analysis scale-audit --envelope baseline
env JAX_PLATFORMS=cpu python - <<'EOF'
import sys

from tpu_swirld.analysis.lint import lint_paths, _suppression_comments

FUSED_MODULES = [
    "tpu_swirld/tpu/pipeline.py",
    "tpu_swirld/tpu/pallas_kernels.py",
    "tpu_swirld/store/streaming.py",
    "tpu_swirld/parallel.py",
]
GUARDED = {"SW003", "wall-clock", "SW004", "dtype-discipline", "all"}

bad = [f.render() for f in lint_paths(FUSED_MODULES, rules=["SW003", "SW004"])]
for path in FUSED_MODULES:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    for lineno, kind, ids, note in _suppression_comments(src):
        if not (ids & GUARDED):
            continue
        if kind == "file" or not note:
            bad.append(
                f"{path}:{lineno}: unjustified suppression of "
                f"{','.join(sorted(ids & GUARDED))} in a fused-dispatch "
                f"module (needs a line disable with a '-- why' note)"
            )
for line in bad:
    print(line)
print(f"fused-kernel SW003/SW004 gate: "
      f"{len(bad)} finding{'s' if len(bad) != 1 else ''}")
sys.exit(1 if bad else 0)
EOF
#   4. soak-plane modules (the TCP fault proxy, the heavy-tailed traffic
#      generator, and the soak orchestrator) must be SW002/SW003-clean
#      with JUSTIFIED suppressions only: these drive real sockets and
#      wall-clock schedules but their fault draws, schedules, and
#      verdicts must replay bit-identically from the seed — an
#      unordered-set walk or an unjustified wall read there breaks
#      ddmin shrink reproducibility.
env JAX_PLATFORMS=cpu python - <<'EOF'
import sys

from tpu_swirld.analysis.lint import lint_paths, _suppression_comments

SOAK_MODULES = [
    "tpu_swirld/net/proxy.py",
    "tpu_swirld/net/traffic.py",
    "tpu_swirld/soak.py",
]
GUARDED = {"SW002", "unordered-iter", "SW003", "wall-clock", "all"}

bad = [f.render() for f in lint_paths(SOAK_MODULES, rules=["SW002", "SW003"])]
for path in SOAK_MODULES:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    for lineno, kind, ids, note in _suppression_comments(src):
        if not (ids & GUARDED):
            continue
        if kind == "file" or not note:
            bad.append(
                f"{path}:{lineno}: unjustified suppression of "
                f"{','.join(sorted(ids & GUARDED))} in a soak-plane "
                f"module (needs a line disable with a '-- why' note)"
            )
for line in bad:
    print(line)
print(f"soak-plane SW002/SW003 gate: "
      f"{len(bad)} finding{'s' if len(bad) != 1 else ''}")
sys.exit(1 if bad else 0)
EOF
