#!/usr/bin/env python
"""Seeded chaos scenario runner emitting a JSON verdict artifact.

Runs one :class:`tpu_swirld.chaos.ChaosSimulation` — lossy/reordering
transport, one scheduled partition + heal, one crash + checkpoint-restart,
optional equivocating forkers — and writes the verdict (safety, liveness,
fault counters) as JSON.  Exit status 0 iff the verdict is ok, so CI can
gate on it directly.

Reproduce any run from its seeds:

    python scripts/chaos_run.py --seed 7 --plan-seed 7 --out verdict.json

Named scenarios (``--scenario``) come from one registry: everything in
:data:`tpu_swirld.adversary.SCENARIOS` — the active-byzantine suite
(``equivocation_storm``, ``censorship``, ``delayed_release``,
``fork_bomb``, ``fork_bomb_overbudget``) plus the storms
(``horizon_storm``: straggler witnesses across a healing partition under
the deterministic expiry horizon; ``overflow_storm``: witness-table
self-healing) — auto-appears here.  ``--scenario list`` prints the
registry; ``--all`` runs every scenario and writes one aggregate verdict
JSON gated on the AND of all verdicts.

The default schedule scales with --turns: partition cuts the first two
members during the middle third; the last member crashes at 1/4 and
restarts at 1/2.  An obs trace with the resilience counters is written
next to the verdict (render with ``python -m tpu_swirld.obs report``).
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_swirld import obs                                    # noqa: E402
from tpu_swirld.adversary import SCENARIOS                    # noqa: E402
from tpu_swirld.chaos import ChaosScenario, ChaosSimulation   # noqa: E402
from tpu_swirld.metrics import Metrics                        # noqa: E402
from tpu_swirld.transport import FaultPlan, LinkFaults, Partition  # noqa: E402


def build_scenario(args) -> ChaosScenario:
    t = args.turns
    plan = FaultPlan(
        seed=args.plan_seed,
        default=LinkFaults(
            drop=args.drop,
            corrupt=args.corrupt,
            duplicate=args.duplicate,
            reorder=args.reorder,
            delay=args.delay,
        ),
        partitions=[Partition(start=t // 3, end=2 * t // 3, group=(0, 1))],
        crashes={args.nodes - 1: [(t // 4, t // 2)]},
    )
    return ChaosScenario(
        n_nodes=args.nodes,
        n_turns=t,
        seed=args.seed,
        n_forkers=args.forkers,
        plan=plan,
        checkpoint_every=args.checkpoint_every,
    )


def _flightrec(args):
    """--flightrec-dir: one black-box recorder per scenario run.  Red
    verdicts (and in-run anomalies: breaker opens, overflow heals, rebase
    storms) auto-dump self-contained post-mortem JSONs into the
    directory; ``None`` when the flag is off keeps recording disabled."""
    if not getattr(args, "flightrec_dir", None):
        return None
    from tpu_swirld.obs.flightrec import FlightRecorder

    return FlightRecorder(dump_dir=args.flightrec_dir)


def flightrec_dumps_by_trace(dump_dir) -> dict:
    """Group the post-mortem dumps in ``dump_dir`` by the trace id each
    one embedded at dump time (PR 16: snapshots carry ``trace_id`` /
    ``node_name``) — a red verdict's forensics index directly to the
    cross-process trace that was in flight.  Dumps predating the field
    (or outside any traced span) group under ``"untraced"``."""
    from tpu_swirld.obs.flightrec import load_dump

    out: dict = {}
    if not dump_dir or not os.path.isdir(dump_dir):
        return out
    for name in sorted(os.listdir(dump_dir)):
        if not (name.startswith("flightrec_") and name.endswith(".json")):
            continue
        path = os.path.join(dump_dir, name)
        try:
            body = load_dump(path)
        except (OSError, ValueError):
            continue
        trace = body.get("trace_id") or "untraced"
        out.setdefault(trace, []).append({
            "path": path,
            "node_name": body.get("node_name"),
            "reason": body.get("reason"),
        })
    return out


def _run_acceptance(args, ckpt_dir, o) -> dict:
    """The composed fault scenario: lossy/reordering transport, one
    scheduled partition + heal, one crash + checkpoint-restart, optional
    equivocating forkers; cross-engine parity over the surviving DAG."""
    sim = ChaosSimulation(
        build_scenario(args), ckpt_dir, metrics=Metrics(o.registry),
        flightrec=_flightrec(args),
    )
    verdict = sim.run()
    # cross-engine parity over the chaos-shaped DAG: the most complete
    # honest node's history replayed through the chosen windowed driver
    # must match batch and oracle
    from tpu_swirld.chaos import _engines_agree

    probe = max(sim._live_honest(), key=lambda n: len(n.hg))
    engines = _engines_agree(probe, engine=args.engine)
    verdict["engines"] = engines
    verdict["ok"] = bool(
        verdict["ok"]
        and engines["batch_oracle_parity"]
        and engines["incremental_batch_parity"]
    )
    if not verdict["ok"] and not verdict.get("flightrec_dump"):
        verdict["flightrec_dump"] = sim.flightrec_postmortem(verdict)
    return verdict


def _adapt(fn):
    """Registry runner -> CLI runner: scenarios registered in
    :data:`tpu_swirld.adversary.SCENARIOS` share the uniform signature
    ``fn(ckpt_dir, seed=, engine=, metrics=, flightrec=)``; ``--seed``
    left at its default passes ``None`` so each scenario keeps its
    pinned seed."""
    def run(args, ckpt_dir, o) -> dict:
        return fn(
            ckpt_dir, seed=args.seed, engine=args.engine,
            metrics=Metrics(o.registry), flightrec=_flightrec(args),
        )
    return run


#: CLI scenario registry: name -> runner(args, ckpt_dir, o).  Everything
#: registered in tpu_swirld.adversary.SCENARIOS (the byzantine strategy
#: suite plus the named storms) auto-appears in --scenario and --all;
#: only the composed acceptance scenario needs the full argparse surface.
RUNNERS = {"acceptance": _run_acceptance}
RUNNERS.update({name: _adapt(fn) for name, fn in SCENARIOS.items()})


def run_scenario(args, ckpt_dir, o) -> dict:
    """One full scenario run under the ambient Obs ``o``; returns the
    verdict dict (shared by the main run, --all, and --sanitize re-runs).
    Every verdict carries a ``flightrec_dump`` key: the post-mortem path
    when a recorder was active and the verdict failed, else ``None``."""
    verdict = RUNNERS[args.scenario](args, ckpt_dir, o)
    verdict.setdefault("flightrec_dump", None)
    return verdict


def _verdict_fingerprint(verdict: dict) -> tuple:
    """The schedule-stable view of a verdict: the ok bit plus the safety
    section (fault counters and timings vary run to run and are not
    determinism claims)."""
    return (
        bool(verdict.get("ok")),
        json.dumps(verdict.get("safety"), sort_keys=True),
    )


def run_sanitized(args, base_verdict: dict) -> dict:
    """--sanitize: re-run the scenario under N seeded yield-injection
    schedules (every run must reproduce the base verdict's safety
    fingerprint) and fuzz the archive worker protocol; the returned
    section folds into the verdict and its ``ok`` gates the exit code."""
    from tpu_swirld.analysis import races

    def rerun(i: int) -> tuple:
        with tempfile.TemporaryDirectory(prefix="chaos-san-") as d:
            with obs.enabled() as o:
                return _verdict_fingerprint(run_scenario(args, d, o))

    rep = races.run_schedules(
        rerun, n_schedules=args.sanitize, seed=args.seed
    )
    base = _verdict_fingerprint(base_verdict)
    stable = bool(
        rep["deterministic"]
        and rep["results"]
        and rep["results"][0] == base
    )
    arch = races.run_archive_schedules(
        n_schedules=max(8, args.sanitize), rows=64, seed=args.seed,
    )
    return {
        "schedules": rep["schedules"],
        "verdicts_stable": stable,
        "all_ok": all(r[0] for r in rep["results"]),
        "archive": {
            k: arch[k]
            for k in (
                "schedules", "digests_identical", "matches_sync", "acyclic",
            )
        },
        "ok": bool(stable and all(r[0] for r in rep["results"])
                   and arch["ok"]),
    }


def run_mc_section(args) -> dict:
    """--mc: fold a model-checker section into the verdict, three legs:

    - *smoke*: exhaustive BFS of the small vanilla world (every invariant
      must hold over the whole space, the POR+symmetry reduction must
      beat the naive baseline);
    - *mutation*: one seeded bug (``fork-blind``) must be caught by its
      expected invariant with a minimized, bit-deterministically
      replaying counterexample — the checker's own end-to-end proof;
    - *parity*: a clean replayable schedule document round-trips through
      :func:`tpu_swirld.chaos.replay_counterexample`, which gates the
      final state on cross-engine ``_engines_agree`` rows under the
      same ``--engine`` the acceptance scenario used.
    """
    from tpu_swirld import crypto
    from tpu_swirld.analysis.mc import counterexample as ce
    from tpu_swirld.analysis.mc.cli import mc_smoke, run_mc
    from tpu_swirld.analysis.mc.world import World
    from tpu_swirld.chaos import replay_counterexample

    smoke = mc_smoke()
    mut = run_mc(mutate="fork-blind")
    cex = mut.get("counterexample") or {}
    mutation = {
        "name": "fork-blind",
        "caught_expected": bool(cex.get("caught_expected")),
        "minimized_len": cex.get("minimized_len"),
        "replay_ok": bool(
            cex.get("replay_reproduced")
            and cex.get("replay_digests_match")
            and cex.get("replay_trace_match")
        ),
    }
    prev = crypto.backend_name()
    crypto.set_backend("sim")
    try:
        w = World(n_honest=3, n_forkers=0, events=3, seed=args.seed or 0)
        sched = [
            ("sync", 1, 0), ("sync", 0, 1), ("sync", 2, 0),
            ("pull", 0, 2), ("pull", 1, 2),
        ]
        doc = ce.emit(w, sched, ce.run_checked(w, sched))
    finally:
        crypto.set_backend(prev)
    parity = replay_counterexample(doc, engine=args.engine)
    parity.pop("violation", None)
    return {
        "smoke": smoke,
        "mutation": mutation,
        "parity": parity,
        "ok": bool(
            smoke["ok"] and mutation["caught_expected"]
            and mutation["replay_ok"] and parity["ok"]
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--scenario",
        choices=("list",) + tuple(RUNNERS),
        default="acceptance",
        help="named scenario to run (default: acceptance, the composed "
        "fault scenario).  'list' prints every registered scenario with "
        "its one-line description and exits; scenarios registered in "
        "tpu_swirld.adversary.SCENARIOS (equivocation_storm, censorship, "
        "delayed_release, fork_bomb, fork_bomb_overbudget, horizon_storm, "
        "overflow_storm) appear here automatically",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="run every registered scenario and write one aggregate "
        "verdict JSON ({scenarios: {name: verdict}, ok: AND of all}); "
        "exit 0 iff every scenario verdict is ok",
    )
    ap.add_argument(
        "--engine",
        choices=("incremental", "streaming", "streaming-mesh"),
        default="incremental",
        help="windowed device driver for the cross-engine parity section: "
        "incremental (IncrementalConsensus, default), streaming "
        "(StreamingConsensus over the slab store — decided rows retire to "
        "the host archive and pruned-history references exercise the "
        "widening rebase), or streaming-mesh (MeshStreamingConsensus — "
        "the same replay with the resident window row-sharded over every "
        "available device; simulate devices with "
        "XLA_FLAGS=--xla_force_host_platform_device_count=8).  The "
        "acceptance scenario gains an 'engines' verdict section; the "
        "storm scenarios replay with the chosen driver.",
    )
    ap.add_argument(
        "--seed", type=int, default=None,
        help="population seed (default: 0 for acceptance; registered "
        "scenarios keep their pinned per-scenario seed)",
    )
    ap.add_argument("--plan-seed", type=int, default=0, help="fault stream seed")
    ap.add_argument("--nodes", type=int, default=6)
    ap.add_argument("--turns", type=int, default=360)
    ap.add_argument("--forkers", type=int, default=1)
    ap.add_argument("--drop", type=float, default=0.2)
    ap.add_argument("--corrupt", type=float, default=0.05)
    ap.add_argument("--duplicate", type=float, default=0.05)
    ap.add_argument("--reorder", type=float, default=0.1)
    ap.add_argument("--delay", type=float, default=0.05)
    ap.add_argument("--checkpoint-every", type=int, default=40)
    ap.add_argument(
        "--sanitize", type=int, nargs="?", const=4, default=0,
        metavar="N",
        help="re-run the scenario under N seeded yield-injection "
        "schedules (race sanitizer) and fuzz the archive worker; folds a "
        "'sanitizer' section into the verdict and fails it on any "
        "schedule-dependent outcome (default N=4; multiplies runtime)",
    )
    ap.add_argument(
        "--mc", action="store_true",
        help="fold a model-checker section into the verdict: exhaustive "
        "smoke world (all invariants over every interleaving, reduction "
        "ratio vs naive), one seeded-bug mutation with a minimized "
        "replaying counterexample, and a clean replayable schedule "
        "document gated on cross-engine parity under --engine",
    )
    ap.add_argument(
        "--flightrec-dir", default=None, metavar="DIR",
        help="enable the black-box flight recorder: every node keeps a "
        "bounded ring of recent activity and a failing verdict (or an "
        "in-run anomaly: circuit-breaker open, overflow heal, rebase "
        "storm) writes a self-contained post-mortem JSON into DIR; the "
        "verdict's 'flightrec_dump' field records the dump path (null "
        "when the run is green or the flag is off).  Ring sizing via "
        "SWIRLD_FLIGHTREC_CAPACITY / SWIRLD_FLIGHTREC_MAX_DUMPS.",
    )
    ap.add_argument("--out", default="chaos_verdict.json")
    args = ap.parse_args(argv)

    if args.scenario == "list":
        for name, fn in RUNNERS.items():
            doc = (
                SCENARIOS[name].__doc__ if name in SCENARIOS else fn.__doc__
            ) or ""
            first = next(
                (ln.strip() for ln in doc.splitlines() if ln.strip()), ""
            )
            print(f"{name:24s} {first}")
        return 0
    if args.seed is None and not args.all and args.scenario == "acceptance":
        args.seed = 0

    if args.all:
        if args.sanitize:
            ap.error("--all and --sanitize are mutually exclusive")
        if args.mc:
            ap.error("--all and --mc are mutually exclusive")
        results = {}
        for name in RUNNERS:
            sub = argparse.Namespace(**{**vars(args), "scenario": name})
            if name == "acceptance" and sub.seed is None:
                sub.seed = 0
            with tempfile.TemporaryDirectory(prefix="chaos-ckpt-") as d:
                with obs.enabled() as o:
                    results[name] = run_scenario(sub, d, o)
            print(f"{name:24s} {'OK' if results[name]['ok'] else 'FAIL'}")
        verdict = {
            "ok": all(v["ok"] for v in results.values()),
            "scenarios": results,
        }
        if args.flightrec_dir:
            verdict["flightrec_dumps_by_trace"] = \
                flightrec_dumps_by_trace(args.flightrec_dir)
        with open(args.out, "w") as f:
            json.dump(verdict, f, indent=2, sort_keys=True)
        print(f"verdict: {'OK' if verdict['ok'] else 'FAIL'} -> {args.out}")
        return 0 if verdict["ok"] else 1

    if args.scenario != "acceptance":
        # the registered scenarios carry their own built-in population /
        # fault schedule; only --seed/--engine parameterize them — say so
        # instead of silently attributing the verdict to knobs that never
        # applied
        print(
            f"note: --scenario {args.scenario} uses its built-in schedule; "
            "only --seed and --engine apply (other knobs ignored)",
            file=sys.stderr,
        )
    with tempfile.TemporaryDirectory(prefix="chaos-ckpt-") as ckpt_dir:
        with obs.enabled() as o:
            # one shared registry: gossip counters, transport fault
            # counters, and pipeline gauges all land in the same trace
            verdict = run_scenario(args, ckpt_dir, o)
        trace_path = os.path.splitext(args.out)[0] + ".trace.jsonl"
        o.save(trace_path)
    if args.sanitize:
        verdict["sanitizer"] = run_sanitized(args, verdict)
        verdict["ok"] = bool(verdict["ok"] and verdict["sanitizer"]["ok"])
    if args.mc:
        verdict["mc"] = run_mc_section(args)
        verdict["ok"] = bool(verdict["ok"] and verdict["mc"]["ok"])
    if args.flightrec_dir:
        verdict["flightrec_dumps_by_trace"] = \
            flightrec_dumps_by_trace(args.flightrec_dir)
    with open(args.out, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    for key in ("safety", "liveness", "horizon", "fork_storm", "round_clamp",
                "adversary", "engines", "sanitizer", "mc", "flightrec_dump",
                "flightrec_dumps_by_trace"):
        if key in verdict:
            print(json.dumps({key: verdict[key]}, sort_keys=True))
    print(f"verdict: {'OK' if verdict['ok'] else 'FAIL'} -> {args.out}")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
