#!/usr/bin/env python
"""Real-process cluster runner emitting a JSON verdict artifact.

Launches N :mod:`tpu_swirld.net.node_proc` OS processes gossiping over
loopback TCP, drives client transaction submissions against them,
optionally SIGKILLs one node mid-run and restarts it from checkpoint +
own-event WAL, and writes the supervisor's verdict (safety vs the
fault-free oracle replay of the union DAG, liveness past the crash
window, tx ledger, per-node startup post-mortems) as JSON.  Exit status
0 iff the verdict is ok, so CI can gate on it directly.

Reproduce any run from its seed (wall-clock scheduling varies; the
safety claim — decided prefixes bit-identical to the oracle — must hold
on every run regardless):

    python scripts/cluster_run.py --nodes 5 --seed 7 \
        --kill 2 --kill-at 2.0 --restart-at 3.5 --out verdict.json

Per-node flight-recorder dumps (written when a restarted node's WAL
shows the previous incarnation died uncleanly) are collected into the
verdict's ``nodes`` section — ``flightrec_dump`` is the dump path, or
``null`` for clean starts.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_swirld.net.cluster import ClusterSpec, run_cluster   # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=5.0,
                    help="traffic window in seconds")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="client submissions per second")
    ap.add_argument("--tx-bytes", type=int, default=64)
    ap.add_argument("--kill", type=int, default=None,
                    help="node index to SIGKILL mid-run")
    ap.add_argument("--kill-at", type=float, default=None,
                    help="seconds into the run to kill")
    ap.add_argument("--restart-at", type=float, default=None,
                    help="seconds into the run to restart the killed node")
    ap.add_argument("--workdir", default=None,
                    help="cluster state dir (default: fresh tempdir)")
    ap.add_argument("--flightrec-dir", default=None,
                    help="post-mortem dump dir (default: workdir/flightrec)")
    ap.add_argument("--gossip-interval", type=float, default=0.005)
    ap.add_argument("--checkpoint-every", type=float, default=0.5)
    ap.add_argument("--max-undecided", type=int, default=None,
                    help="admission-control window override (small values "
                         "force load shedding)")
    ap.add_argument("--out", default=None, help="verdict JSON path")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="swirld-cluster-")
    net = {
        "gossip_interval_s": args.gossip_interval,
        "checkpoint_every_s": args.checkpoint_every,
    }
    if args.max_undecided is not None:
        net["max_undecided"] = args.max_undecided
    spec = ClusterSpec(
        workdir=workdir,
        n_nodes=args.nodes,
        seed=args.seed,
        duration_s=args.duration,
        tx_rate=args.rate,
        tx_bytes=args.tx_bytes,
        kill_index=args.kill,
        kill_at_s=args.kill_at,
        restart_at_s=args.restart_at,
        flightrec_dir=args.flightrec_dir
        or os.path.join(workdir, "flightrec"),
        net=net,
    )
    verdict = run_cluster(spec)
    verdict["workdir"] = workdir
    text = json.dumps(verdict, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
