"""End-to-end tour of tpu_swirld — run with:  python examples/demo.py

Covers the surface a py-swirld user would reach for: the in-process sim,
the consensus outputs, both backends (with bit-parity), byzantine forkers,
visualization export, metrics, and checkpoint/resume.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Force the CPU platform BEFORE any jax work (this machine's sitecustomize
# registers a TPU-tunnel backend whose init can hang; see README).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

from tpu_swirld import viz
from tpu_swirld.checkpoint import load_node, save_node
from tpu_swirld.metrics import Metrics, node_gauges
from tpu_swirld.packing import pack_node
from tpu_swirld.parallel import make_mesh
from tpu_swirld.sim import make_simulation, run_with_divergent_forkers
from tpu_swirld.tpu.pipeline import run_consensus


def main():
    print("== 1. reference-style sim (5 members, 400 gossip turns)")
    sim = make_simulation(5, seed=42)
    sim.nodes[0].metrics = Metrics()
    sim.run(400)
    node = sim.nodes[0]
    print(f"   events={len(node.hg)} ordered={len(node.consensus)} "
          f"max_round={node.max_round}")
    print(f"   gauges: {node_gauges(node)}")
    print(f"   metrics: {node.metrics.snapshot()}")

    print("== 2. device pipeline on the same DAG — bit-identical")
    packed = pack_node(node)
    result = run_consensus(packed, node.config)
    assert [packed.ids[i] for i in result.order] == node.consensus
    print(f"   parity ok; device timings: {result.timings}")

    print("== 3. the same, sharded over an 8-device mesh (psum stake tally)")
    sharded = run_consensus(packed, node.config, mesh=make_mesh(8))
    assert sharded.order == result.order
    print("   sharded == unsharded")

    print("== 4. byzantine equivocation (7 members, 2 divergent forkers)")
    bsim = run_with_divergent_forkers(7, 2, 400, seed=5)
    orders = [n.consensus for n in bsim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0 and all(o[:m] == orders[0][:m] for o in orders)
    forked = sum(
        n.has_fork[f.pk] for n in bsim.nodes for f in bsim.forkers
    )
    print(f"   honest prefix agreement over {m} events; "
          f"fork observations: {forked}")

    print("== 5. visualization export (last rows)")
    lanes = viz.ascii_lanes(node=node, max_height=6)
    print("\n".join("   " + line for line in lanes.splitlines()))

    print("== 6. checkpoint / resume")
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "node.swck")
        save_node(path, node)
        restored = load_node(
            path, sk=node.sk, pk=node.pk, network=sim.network
        )
        assert restored.consensus == node.consensus
        print(f"   restored {len(restored.hg)} events, "
              f"{len(restored.consensus)} ordered — bit-identical")

    print("done.")


if __name__ == "__main__":
    main()
