"""Benchmark: events/sec to consensus-order, TPU pipeline vs CPU oracle.

Driver contract: print ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "phases": {...}}
value       = device-pipeline consensus throughput (events/sec)
vs_baseline = speedup over the pure-Python oracle on the same machine
              (BASELINE.json north star: >= 50x on 64 members / 10k events).
phases      = per-phase wall-clock seconds (tpu_swirld.obs spans) PLUS
              per-phase peak-memory high-water marks
              (``mem_<phase>_host_peak_bytes`` via tracemalloc,
              ``mem_<phase>_device_peak_bytes`` via jax.live_arrays()
              sizes), so the headline has per-stage time AND memory
              attribution.  Top-level ``peak_host_bytes`` /
              ``peak_device_bytes`` carry the run-wide maxima for
              scripts/bench_compare.py regression gating.

An *incremental steady-state* section (tpu_swirld.tpu.pipeline.
IncrementalConsensus) additionally ingests the same events in chunks,
reports ev/s per pass and the first(cold)-vs-steady ratio, and publishes
window_size / pruned_prefix in the phases breakdown plus a structured
"incremental" object in the JSON line.

``--stream`` instead runs the BASELINE config-5 shape (256 members /
100k events; override with BENCH_STREAM_*) through the slab-store
streaming driver (tpu_swirld.store.StreamingConsensus) under a stated
resident tile budget (``--tile-budget``): events are generated as a
stream (host memory O(chunk)), decided rows retire to the host archive,
and the decided-prefix order is parity-checked against a pure-Python
oracle over a subsampled prefix.  The JSON line then reports streaming
ev/s, the tile budget, peak resident visibility bytes, and archive
stats — the config-5 acceptance artifact.

All detail goes to stderr.  Environment knobs:
    BENCH_MEMBERS (64)  BENCH_EVENTS (10000)  BENCH_ORACLE_EVENTS (10000)
    BENCH_TPU_PROBE_TIMEOUT (240 s)  BENCH_FORCE_CPU (unset)
    BENCH_TPU_PROBE_CACHE (.tpu_probe.json)  BENCH_TPU_PROBE_TTL (3600 s)
      — the probe outcome is cached with a TTL so repeated CPU-fallback
      runs skip the 240 s axon-tunnel hang (BENCH_r05.json documents it);
      delete the cache file or set TTL 0 to force a fresh probe.
    BENCH_MEM (1) — 0 disables the tracemalloc/live-array memory monitor.
    BENCH_INC_CHUNK (1000) — incremental ingest chunk; 0 disables the
    incremental section.
    BENCH_STREAM_MEMBERS (256)  BENCH_STREAM_EVENTS (100000)
    BENCH_STREAM_CHUNK (2048)  BENCH_STREAM_ORACLE (4000)
    BENCH_DEFAULT_STREAM_MEMBERS (48)  BENCH_DEFAULT_STREAM_EVENTS (6000)
    BENCH_DEFAULT_STREAM_CHUNK (1024) — the default (no-flags) run's
    always-on scaled-down streaming leg, so stream.evps and
    stream.dispatch_overhead_s land in every artifact (0 events
    disables); fusion/overlap knobs via SWIRLD_FUSE_CHUNKS /
    SWIRLD_DECODE_OVERLAP / SWIRLD_DECODE_QUEUE_DEPTH.
    BENCH_STREAM_REF (20000) — with --mesh: events for the in-run
    single-device reference pass (0 disables); BENCH_STREAM_SINGLE_EVPS
    supplies the reference throughput externally instead (e.g. from a
    prior single-device artifact).
    BENCH_COMPILE_CACHE (unset) — with --stream: persistent jit cache
    directory; a warmed cache removes the window-growth warmup compiles
    (run twice against the same dir, publish the second).
    BENCH_TRACE (unset) — write the full span trace + gauge snapshot to
    this path (JSONL; render with `python -m tpu_swirld.obs report`).

The machine's sitecustomize registers an 'axon' TPU-tunnel PJRT platform
whose initialization has been observed to hang indefinitely; we therefore
probe it in a SUBPROCESS with a hard timeout and fall back to CPU (with the
platform recorded in stderr) rather than hanging the driver.
"""

import argparse
import json
import os
import subprocess
import sys
import time

MEMBERS = int(os.environ.get("BENCH_MEMBERS", "64"))
EVENTS = int(os.environ.get("BENCH_EVENTS", "10000"))
ORACLE_EVENTS = int(os.environ.get("BENCH_ORACLE_EVENTS", "10000"))
PROBE_TIMEOUT = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "240"))
PROBE_CACHE = os.environ.get("BENCH_TPU_PROBE_CACHE", ".tpu_probe.json")
PROBE_TTL = float(os.environ.get("BENCH_TPU_PROBE_TTL", "3600"))
INC_CHUNK = int(os.environ.get("BENCH_INC_CHUNK", "1000"))
MEM = os.environ.get("BENCH_MEM", "1") != "0"

STREAM_MEMBERS = int(os.environ.get("BENCH_STREAM_MEMBERS", "256"))
STREAM_EVENTS = int(os.environ.get("BENCH_STREAM_EVENTS", "100000"))
STREAM_CHUNK = int(os.environ.get("BENCH_STREAM_CHUNK", "2048"))
# 256-member rounds fame-complete every ~4k events and ordering starts
# around 10-12k, so the oracle prefix must reach that deep for the
# decided-prefix order parity to be non-vacuous (the JSON reports
# oracle_decided so a too-shallow override is visible)
STREAM_ORACLE = int(os.environ.get("BENCH_STREAM_ORACLE", "12000"))

# always-on streaming leg of the DEFAULT run, config-scaled down so the
# headline stays cheap: every artifact then carries stream.evps and
# stream.dispatch_overhead_s for bench_compare.py's EXTRA_KEYS gates
# (previously only --stream artifacts had them, so the fused-dispatch
# path could regress invisibly between config-5 soaks).  0 events
# disables the leg; the full config-5 shape remains behind --stream.
# Gossip arrives in batches of 4x the ingest chunk so one ingest call
# spans several deltas — that exercises BOTH the decode-overlap worker
# (multi-slice _chunked_deltas) and the fused rounds scan.
DEFAULT_STREAM_MEMBERS = int(
    os.environ.get("BENCH_DEFAULT_STREAM_MEMBERS", "48")
)
DEFAULT_STREAM_EVENTS = int(
    os.environ.get("BENCH_DEFAULT_STREAM_EVENTS", "6000")
)
DEFAULT_STREAM_CHUNK = int(
    os.environ.get("BENCH_DEFAULT_STREAM_CHUNK", "1024")
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def lint_stamp():
    """Invariant-lint status of the tree this bench ran from, stamped
    into the artifact: bench_compare.py refuses to gate a BENCH_*.json
    whose tree had findings (a number produced by code that violates the
    determinism/jit/thread invariants is not comparable)."""
    try:
        from tpu_swirld.analysis import lint_paths, lint_summary

        pkg = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tpu_swirld"
        )
        return lint_summary(lint_paths([pkg]))
    except Exception as exc:   # the stamp must never sink a bench run
        return {"error": repr(exc)}


def mc_stamp():
    """Model-checker smoke verdict stamped into the artifact: the small
    vanilla world explored exhaustively (every invariant over every
    interleaving) with the POR+symmetry reduction ratio vs the naive
    baseline.  bench_compare.py refuses to gate a candidate whose stamp
    is dirty — a throughput number from a tree whose consensus core
    violates its own invariant catalog is not comparable."""
    try:
        from tpu_swirld.analysis.mc import mc_smoke

        return mc_smoke()
    except Exception as exc:   # the stamp must never sink a bench run
        return {"error": repr(exc)}


def scale_audit_stamp():
    """Scale-audit verdict of the tree this bench ran from, stamped into
    the artifact: the jaxpr-level interval/dtype flow proof that no
    int32 wraps, no gather/slice reads out of bounds, no narrowing
    loses a value, and no padding sentinel collides with live data at
    the baseline envelope.  bench_compare.py refuses to gate a
    candidate whose stamp is dirty *or missing* — a throughput number
    from kernels that are not provably safe at the declared scale is
    not comparable."""
    try:
        from tpu_swirld.analysis import scale_audit_stamp as stamp

        return stamp("baseline")
    except Exception as exc:   # the stamp must never sink a bench run
        return {"error": repr(exc)}


def probe_tpu() -> bool:
    """Can the default (axon/TPU) backend initialize? Probe in a child
    process under a hard timeout so a wedged PJRT init can't hang us.
    The outcome is cached to ``BENCH_TPU_PROBE_CACHE`` with a TTL so
    back-to-back CPU-fallback runs don't each pay the full hang."""
    if os.environ.get("BENCH_FORCE_CPU"):
        return False
    try:
        with open(PROBE_CACHE) as f:
            c = json.load(f)
        age = time.time() - float(c["time"])
        if 0 <= age <= PROBE_TTL:
            log(f"[probe] cached ({PROBE_CACHE}, age {age:.0f}s <= ttl "
                f"{PROBE_TTL:.0f}s): ok={c['ok']}")
            return bool(c["ok"])
    except (OSError, ValueError, KeyError, TypeError):
        pass
    code = (
        "import jax; d = jax.devices(); "
        "import jax.numpy as jnp; "
        "x = jax.jit(lambda a: a @ a)(jnp.ones((128, 128), jnp.bfloat16)); "
        "x.block_until_ready(); print(d[0].platform)"
    )
    ok = False
    try:
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=PROBE_TIMEOUT,
            capture_output=True,
            text=True,
        )
        log(f"[probe] rc={r.returncode} in {time.time()-t0:.0f}s: "
            f"{(r.stdout or r.stderr).strip().splitlines()[-1] if (r.stdout or r.stderr).strip() else ''}")
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        log(f"[probe] TPU backend init exceeded {PROBE_TIMEOUT:.0f}s — falling back to CPU")
    try:
        with open(PROBE_CACHE, "w") as f:
            json.dump(
                {"ok": ok, "time": time.time(),
                 "timeout_s": PROBE_TIMEOUT}, f,
            )
        log(f"[probe] cached outcome -> {PROBE_CACHE} (ttl {PROBE_TTL:.0f}s)")
    except OSError:
        pass
    return ok


def _mem_monitor():
    from tpu_swirld.obs import MemoryMonitor

    return MemoryMonitor(enable_host=MEM)


def run_default():
    tpu_ok = probe_tpu()
    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    log(f"[env] platform={platform} devices={len(jax.devices())}")

    from tpu_swirld import obs as obslib
    from tpu_swirld.metrics import Metrics
    from tpu_swirld.obs.finality import FinalityTracker, record_batch_result
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag
    from tpu_swirld.tpu.pipeline import run_consensus

    # one Obs for the whole bench: depth-0 spans become the published
    # "phases" breakdown; the warm-up pipeline run executes with the Obs
    # ambient so stage/compile attribution and pad-waste gauges land in the
    # registry.  The steady (headline) run is spanned but NOT ambient —
    # per-stage sync would perturb the number being published.
    o = obslib.Obs()
    mon = _mem_monitor()

    n_events = EVENTS if tpu_ok else min(EVENTS, 10000)
    if n_events != EVENTS:
        log(f"[env] CPU fallback: clamping BENCH_EVENTS {EVENTS} -> {n_events}")
    t0 = time.time()
    with o.tracer.span("gossip_gen"), mon.phase("gossip_gen"):
        members, stake, events, keys = generate_gossip_dag(
            MEMBERS, n_events, seed=1
        )
    log(f"[gen] {MEMBERS} members / {n_events} events in {time.time()-t0:.1f}s")

    # ---- CPU oracle denominator (batch consensus pass over a prefix) ----
    n_oracle = min(ORACLE_EVENTS, n_events)
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in events[:n_oracle] if node.add_event(ev)]
    node.metrics = Metrics(registry=o.registry)   # per-phase oracle seconds
    t0 = time.time()
    with o.tracer.span("oracle"), mon.phase("oracle"):
        node.consensus_pass(new_ids)
    t_oracle = time.time() - t0
    oracle_evps = n_oracle / t_oracle
    log(f"[oracle] {n_oracle} events in {t_oracle:.2f}s = {oracle_evps:.0f} ev/s "
        f"(ordered {len(node.consensus)}, max_round {node.max_round})")
    # finality lifecycle, oracle engine: rounds-to-decision is exact per
    # event; the single batch pass makes time-to-finality degenerate
    # (every event shares the pass wall-clock), recorded post-hoc so the
    # tracker never perturbs the timed region
    fin_oracle = FinalityTracker("oracle", registry=o.registry)
    for eid in node.consensus:
        fin_oracle.record_decided(
            eid, node.round[eid], node.round_received[eid],
            birth=0.0, now=t_oracle,
        )
    finality = {"oracle": fin_oracle.summary()}

    # ---- device pipeline (full DAG), parity-checked on the oracle prefix --
    t0 = time.time()
    with o.tracer.span("pack"), mon.phase("pack"):
        packed_prefix = pack_events(events[:n_oracle], members, stake)
        packed_full = pack_events(events, members, stake)
    log(f"[pack] {time.time()-t0:.2f}s")

    if n_oracle == n_events:
        packed_prefix = packed_full
    res_prefix = run_consensus(packed_prefix, node.config)
    parity = (
        [packed_prefix.ids[i] for i in res_prefix.order] == node.consensus
        and all(
            res_prefix.round[i] == node.round[e]
            for i, e in enumerate(node.order_added)
        )
    )
    log(f"[parity] prefix ({n_oracle} ev) order+rounds identical: {parity}")

    t0 = time.time()
    with obslib.enabled(o):           # stage spans + compile attribution
        with o.tracer.span("pipeline_first"), mon.phase("pipeline_first"):
            res = run_consensus(packed_full, node.config)
    t_compile_and_run = time.time() - t0
    t0 = time.time()
    with o.tracer.span("pipeline"), mon.phase("pipeline"):
        # wall-clock only: no per-stage sync
        res = run_consensus(packed_full, node.config)
    t_steady = time.time() - t0
    pipe_evps = n_events / t_steady
    log(f"[pipeline] first {t_compile_and_run:.2f}s, steady {t_steady:.2f}s = "
        f"{pipe_evps:.0f} ev/s (ordered {len(res.order)}, max_round {res.max_round})")
    fin_batch = FinalityTracker("batch", registry=o.registry)
    record_batch_result(fin_batch, res, now=t_steady, birth=0.0)
    finality["batch"] = fin_batch.summary()

    # ---- incremental steady-state mode: chunked ingest, carried state ----
    inc_out = None
    if INC_CHUNK > 0:
        from tpu_swirld.tpu.pipeline import IncrementalConsensus

        inc = IncrementalConsensus(members, stake, node.config)
        # genuine steady-state time-to-finality: births stamp at chunk
        # ingest, decided at the pass that orders them — both on the
        # tracker's wall clock
        inc.finality = FinalityTracker(
            "incremental", clock=time.perf_counter, registry=o.registry
        )
        pass_stats = []
        with o.tracer.span("pipeline_incremental"), \
                mon.phase("pipeline_incremental"):
            for i in range(0, n_events, INC_CHUNK):
                t0 = time.time()
                st = inc.ingest(events[i : i + INC_CHUNK])
                dt = time.time() - t0
                pass_stats.append((dt, st))
                mon.sample("pipeline_incremental")
                log(f"[inc] pass {len(pass_stats)-1}: {st['new_events']} ev "
                    f"in {dt:.3f}s = {st['new_events']/dt:.0f} ev/s "
                    f"window={st['window_size']} pruned={st['pruned_prefix']}"
                    f"{' REBASE' if st['rebased'] else ''}")
        inc_res = inc.result()
        inc_parity = inc_res.order == res.order and (
            list(inc_res.round) == list(res.round)
        )
        # steady = back half of the passes (the front half pays compiles
        # + window warmup).  The denominator for the first-vs-steady
        # ratio is the WARM full-recompute pass above (t_steady) — a
        # stricter baseline than a literally cold first pass, which
        # also pays one-off jit compiles.
        steady_half = pass_stats[len(pass_stats) // 2 :]
        warmed_up = len(steady_half) >= 2 and not any(
            s["rebased"] for _dt, s in steady_half
        )
        if not warmed_up:
            log("[inc] too few passes to reach steady state "
                f"({len(pass_stats)} total) — ratio not meaningful; "
                "lower BENCH_INC_CHUNK or raise BENCH_EVENTS")
        ev_steady = sum(s["new_events"] for _dt, s in steady_half)
        t_inc = sum(dt for dt, _s in steady_half)
        inc_evps = ev_steady / t_inc if (t_inc and warmed_up) else 0.0
        full_pass_evps = pipe_evps
        ratio = inc_evps / full_pass_evps if full_pass_evps else 0.0
        log(f"[inc] steady {inc_evps:.0f} ev/s vs warm full-recompute "
            f"pass {full_pass_evps:.0f} ev/s -> first-vs-steady ratio "
            f"{ratio:.2f}x (parity={inc_parity}, rebases={inc.rebases})")
        inc_out = {
            "chunk": INC_CHUNK,
            "passes": inc.passes,
            "rebases": inc.rebases,
            "full_pass_evps": round(full_pass_evps, 1),
            "steady_evps": round(inc_evps, 1),
            "first_vs_steady": round(ratio, 2),
            "window_size": inc.window_size,
            "pruned_prefix": inc.pruned_prefix,
            "parity": bool(inc_parity),
        }
        finality["incremental"] = inc.finality.summary()

    # ---- always-on streaming leg (config-scaled down) ----
    # Profiled ingest through StreamingConsensus so stream.evps and
    # stream.dispatch_overhead_s land in EVERY artifact; decided output
    # is parity-checked bit-identically against the batch pipeline over
    # the same events.  Both sides of a bench_compare gate measure the
    # same way (profiler ambient), so the numbers are comparable even
    # though the profiler adds per-stage sync.
    stream_out = None
    if DEFAULT_STREAM_EVENTS > 0:
        from tpu_swirld.config import SwirldConfig, resolve_stream_settings
        from tpu_swirld.obs.profile import DispatchProfiler
        from tpu_swirld.sim import stream_gossip_dag
        from tpu_swirld.store import StreamingConsensus

        s_cfg = SwirldConfig(n_members=DEFAULT_STREAM_MEMBERS)
        s_members, s_stake, _s_keys, s_chunks = stream_gossip_dag(
            DEFAULT_STREAM_MEMBERS, DEFAULT_STREAM_EVENTS,
            4 * DEFAULT_STREAM_CHUNK, seed=1,
        )
        s_chunks = list(s_chunks)
        s_events = [ev for ch in s_chunks for ev in ch]
        s_packed = pack_events(s_events, s_members, s_stake)
        with o.tracer.span("stream_default_ref"), \
                mon.phase("stream_default_ref"):
            s_ref = run_consensus(s_packed, s_cfg)

        settings = resolve_stream_settings(s_cfg)

        def _stream_pass(profiler):
            eng = StreamingConsensus(
                s_members, s_stake, s_cfg,
                ingest_chunk=DEFAULT_STREAM_CHUNK,
                window_bucket=2048, prune_min=1024,
            )
            t0 = time.time()
            if profiler is not None:
                with obslib.enabled(obslib.Obs(profiler=profiler)):
                    for ch in s_chunks:
                        eng.ingest(ch)
            else:
                for ch in s_chunks:
                    eng.ingest(ch)
            dt = time.time() - t0
            eng.store.close()
            return eng, dt

        # pass 1 (timed, untraced): the leg's evps + parity.  Pass 2
        # re-runs under the DispatchProfiler on the now-warm jit caches —
        # profiling the cold pass would book every one-off compile into
        # dispatch_overhead_s and drown the per-chunk signal being gated.
        with o.tracer.span("stream_default"), mon.phase("stream_default"):
            eng, t_s = _stream_pass(None)
        prof = DispatchProfiler()
        with o.tracer.span("stream_default_profile"), \
                mon.phase("stream_default_profile"):
            _eng2, _t2 = _stream_pass(prof)
        s_res = eng.result()
        got = [eng.packer.event_id(i) for i in s_res.order]
        want = [s_packed.ids[i] for i in s_ref.order]
        ref_round = {
            s_packed.ids[i]: int(s_ref.round[i]) for i in range(len(s_events))
        }
        s_parity = got == want and all(
            int(s_res.round[i]) == ref_round[eng.packer.event_id(i)]
            for i in range(len(s_events))
        )
        dispatch = prof.summary()
        s_evps = DEFAULT_STREAM_EVENTS / t_s
        log(f"[stream-default] {DEFAULT_STREAM_EVENTS} ev x "
            f"{DEFAULT_STREAM_MEMBERS} members in {t_s:.2f}s = "
            f"{s_evps:.0f} ev/s fuse={settings['fuse_chunks']} "
            f"decode_overlap={settings['decode_overlap']} "
            f"dispatch_overhead={dispatch['dispatch_overhead_s']:.3f}s "
            f"fused_dispatches={dispatch['fused_dispatches']} "
            f"parity={s_parity}")
        stream_out = {
            "evps": round(s_evps, 1),
            # dotted keys bench_compare.py gates directly
            "dispatch_overhead_s": dispatch["dispatch_overhead_s"],
            "members": DEFAULT_STREAM_MEMBERS,
            "events": DEFAULT_STREAM_EVENTS,
            "chunk": DEFAULT_STREAM_CHUNK,
            "fuse_chunks": settings["fuse_chunks"],
            "decode_overlap": settings["decode_overlap"],
            "decoded_off_thread": eng.decoded_off_thread,
            "ordered": len(s_res.order),
            "parity": bool(s_parity),
            "profile": dispatch,
        }

    phases = {k: round(v, 4) for k, v in o.tracer.phase_seconds().items()}
    if inc_out is not None:
        phases["incremental_window_size"] = inc_out["window_size"]
        phases["incremental_pruned_prefix"] = inc_out["pruned_prefix"]
    phases.update(mon.flat())
    log(f"[phases] {json.dumps(phases)}")
    trace_path = os.environ.get("BENCH_TRACE")
    if trace_path:
        o.save(trace_path)
        log(f"[trace] wrote {trace_path} "
            f"(render: python -m tpu_swirld.obs report {trace_path})")

    speedup = pipe_evps / oracle_evps
    out = {
        "metric": (
            f"events/sec to consensus-order @{n_events} events x {MEMBERS} "
            f"members ({platform}); order parity={parity}"
        ),
        "value": round(pipe_evps, 1),
        "unit": "events/s",
        "vs_baseline": round(speedup, 2),
        "phases": phases,
        "peak_host_bytes": mon.peak_host_bytes,
        "peak_device_bytes": mon.peak_device_bytes,
    }
    if inc_out is not None:
        out["incremental"] = inc_out
    if stream_out is not None:
        out["stream"] = stream_out
    out["finality"] = {
        eng: {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in s.items()
        }
        for eng, s in finality.items()
    }
    log(f"[finality] {json.dumps(out['finality'])}")
    out["lint"] = lint_stamp()
    out["mc"] = mc_stamp()
    out["scale_audit"] = scale_audit_stamp()
    print(json.dumps(out), flush=True)
    mon.close()
    if not parity or (inc_out is not None and not inc_out["parity"]) \
            or (stream_out is not None and not stream_out["parity"]):
        sys.exit(1)


def run_stream(tile_budget, tile, mesh_n=0, device_tile_budget=None):
    """BASELINE config-5 shape under a stated resident tile budget.

    ``mesh_n > 0`` runs the row-sharded mesh driver
    (:class:`tpu_swirld.parallel.MeshStreamingConsensus`) over that many
    devices instead — on CPU the devices are simulated
    (``xla_force_host_platform_device_count``), so ``scaling_efficiency``
    measures sharding *overhead* (halo + psum + repins) rather than
    hardware speedup; on a real mesh the same number reads as
    speedup/D.  The single-device reference throughput comes from
    BENCH_STREAM_SINGLE_EVPS when set (e.g. the headline of a prior
    single-device artifact), else an in-run single-device pass over the
    first BENCH_STREAM_REF events of the same stream.
    """
    tpu_ok = probe_tpu()
    if mesh_n and not tpu_ok:
        # must precede the jax import: device count is fixed at init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={mesh_n}"
        )
    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE")
    if cache_dir:
        # persistent jit cache: the streaming warmup's one-off compiles
        # (window growth walks W_pad up its bucket family) dominate the
        # first minutes of a cold run; a warmed cache removes them, which
        # is the deployment steady state (artifact notes the cache)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        log(f"[env] persistent compile cache: {cache_dir}")
    platform = jax.devices()[0].platform
    log(f"[env] platform={platform} devices={len(jax.devices())} "
        f"stream {STREAM_MEMBERS}x{STREAM_EVENTS} chunk={STREAM_CHUNK} "
        f"tile_budget={tile_budget} tile={tile}"
        + (f" mesh={mesh_n} device_tile_budget={device_tile_budget}"
           if mesh_n else ""))

    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.sim import stream_gossip_dag
    from tpu_swirld.store import StreamingConsensus

    mon = _mem_monitor()
    cfg = SwirldConfig(n_members=STREAM_MEMBERS)
    members, stake, keys, chunks = stream_gossip_dag(
        STREAM_MEMBERS, STREAM_EVENTS, STREAM_CHUNK, seed=1
    )
    # the oracle replays only the subsampled prefix — the streaming
    # driver's decided prefix must be bit-identical over it
    n_oracle = min(STREAM_ORACLE, STREAM_EVENTS)
    oracle = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    oracle_buf = []

    if mesh_n:
        from tpu_swirld.parallel import make_mesh, streaming_consensus_for_mesh

        if len(jax.devices()) < mesh_n:
            log(f"[env] only {len(jax.devices())} devices — clamping "
                f"mesh {mesh_n} -> {len(jax.devices())}")
            mesh_n = len(jax.devices())
        mesh = make_mesh(mesh_n)
        inc = streaming_consensus_for_mesh(
            mesh, members, stake, cfg,
            tile_budget=tile_budget, tile=tile,
            device_tile_budget=device_tile_budget,
            ingest_chunk=STREAM_CHUNK, window_bucket=2048, prune_min=1024,
        )
    else:
        inc = StreamingConsensus(
            members, stake, cfg,
            tile_budget=tile_budget, tile=tile,
            ingest_chunk=STREAM_CHUNK, window_bucket=2048, prune_min=1024,
        )
    # finality lifecycle on the stream: births at chunk ingest, decided
    # at the ordering pass; the phase dimension attributes each decided
    # event's latency to window residency vs archive widening vs full
    # rebase (see StreamingConsensus._rebase)
    from tpu_swirld.obs.finality import FinalityTracker

    inc.finality = FinalityTracker("streaming", clock=time.perf_counter)
    n_done = 0
    t_all = time.time()
    with mon.phase("stream"):
        for chunk in chunks:
            if n_done < n_oracle:
                oracle_buf.extend(chunk[: n_oracle - n_done])
            t0 = time.time()
            st = inc.ingest(chunk)
            dt = time.time() - t0
            n_done += len(chunk)
            mon.sample("stream")
            log(f"[stream] {n_done}/{STREAM_EVENTS}: {len(chunk)} ev in "
                f"{dt:.2f}s = {len(chunk)/dt:.0f} ev/s "
                f"window={st['window_size']} pruned={st['pruned_prefix']} "
                f"resident={st['resident_bytes']/1e6:.0f}MB "
                f"archived={st['archived_rows']}"
                f"{' REBASE' if st['rebased'] else ''}")
    t_stream = time.time() - t_all
    stream_evps = n_done / t_stream
    # overlap ratio over the whole run: fraction of the stream wall spent
    # computing rather than blocked behind the archive's spill queue
    # (snapshot stall BEFORE close() — the final flush is off the clock)
    stall = inc.store.archive.stall_seconds
    overlap = max(0.0, min(1.0, (t_stream - stall) / t_stream))
    res = inc.result()
    log(f"[stream] {n_done} ev in {t_stream:.1f}s = {stream_evps:.0f} ev/s; "
        f"ordered {len(res.order)}, max_round {res.max_round}, "
        f"pruned {inc.pruned_prefix}, window {inc.window_size}, "
        f"overlap {overlap:.3f}")
    inc.store.close()       # flush background packing before stats/parity

    with mon.phase("oracle_subsample"):
        new_ids = [ev.id for ev in oracle_buf if oracle.add_event(ev)]
        oracle.consensus_pass(new_ids)
    got = [inc.packer.event_id(i) for i in res.order[: len(oracle.consensus)]]
    order_parity = got == oracle.consensus
    round_parity = all(
        int(res.round[i]) == oracle.round[eid]
        for i, eid in enumerate(oracle.order_added)
    )
    parity = order_parity and round_parity
    log(f"[parity] oracle prefix {n_oracle} ev, decided {len(oracle.consensus)}: "
        f"order={order_parity} rounds={round_parity}")

    stats = inc.store.stats()
    budget_ok = (
        tile_budget is None
        or stats["peak_resident_tiles"] <= tile_budget
    )
    dev_budget_ok = (
        device_tile_budget is None
        or stats["peak_device_tiles"] <= device_tile_budget
    )
    log(f"[store] {json.dumps(stats)} budget_ok={budget_ok}"
        + (f" dev_budget_ok={dev_budget_ok}" if mesh_n else ""))

    # ---- dispatch-level hot-path profile (ROADMAP item 4: measure the
    # per-chunk dispatch / host-device cost instead of guessing).  Two
    # single-device passes over the same stream prefix on the now-warm
    # jit caches: one untraced (the control), one under a
    # DispatchProfiler + tracer — the delta is the measured
    # tracing/profiling overhead, gated <= 5% by bench_compare.py.
    profile_events = int(os.environ.get(
        "BENCH_STREAM_PROFILE", str(3 * STREAM_CHUNK)
    ))
    dispatch_out = None
    if profile_events:
        profile_events = min(profile_events, STREAM_EVENTS)
        from tpu_swirld import obs as obs_mod
        from tpu_swirld.obs.profile import DispatchProfiler

        def _profile_pass(enabled_obs):
            _m3, _s3, _k3, prof_chunks = stream_gossip_dag(
                STREAM_MEMBERS, profile_events, STREAM_CHUNK, seed=1
            )
            eng = StreamingConsensus(
                members, stake, cfg,
                tile_budget=tile_budget, tile=tile,
                ingest_chunk=STREAM_CHUNK, window_bucket=2048,
                prune_min=1024,
            )
            t0 = time.time()
            if enabled_obs is not None:
                with obs_mod.enabled(enabled_obs):
                    for chunk in prof_chunks:
                        eng.ingest(chunk)
            else:
                for chunk in prof_chunks:
                    eng.ingest(chunk)
            dt = time.time() - t0
            eng.store.close()
            return dt

        with mon.phase("stream_profile"):
            t_plain = _profile_pass(None)
            prof = DispatchProfiler()
            t_prof = _profile_pass(obs_mod.Obs(profiler=prof))
        overhead_ratio = max(0.0, (t_prof - t_plain) / t_plain)
        dispatch_out = prof.summary()
        dispatch_out["profiled_events"] = profile_events
        dispatch_out["plain_s"] = round(t_plain, 6)
        dispatch_out["profiled_s"] = round(t_prof, 6)
        dispatch_out["trace_overhead_ratio"] = round(overhead_ratio, 4)
        top = ", ".join(
            f"{t['stage']}={t['seconds']:.3f}s/{t['calls']}x"
            for t in dispatch_out["top_stages"]
        )
        log(f"[dispatch] {profile_events} ev profiled: "
            f"wall={dispatch_out['wall_s']:.3f}s "
            f"stage={dispatch_out['stage_s']:.3f}s "
            f"overhead={dispatch_out['dispatch_overhead_s']:.3f}s "
            f"h2d={dispatch_out['transfers_bytes']['h2d']} "
            f"d2h={dispatch_out['transfers_bytes']['d2h']} "
            f"top[{top}] "
            f"trace_overhead={overhead_ratio:.1%}")

    mesh_out = None
    if mesh_n:
        # single-device reference for the scaling number: an external
        # artifact headline (BENCH_STREAM_SINGLE_EVPS) or an in-run
        # single-device pass over the stream's first BENCH_STREAM_REF
        # events (0 disables; the soak supplies the external number)
        single_evps = float(
            os.environ.get("BENCH_STREAM_SINGLE_EVPS", "0") or 0
        )
        ref_events = int(os.environ.get("BENCH_STREAM_REF", "20000"))
        ref_used = 0
        if not single_evps and ref_events:
            ref_events = min(ref_events, STREAM_EVENTS)
            _m2, _s2, _k2, ref_chunks = stream_gossip_dag(
                STREAM_MEMBERS, ref_events, STREAM_CHUNK, seed=1
            )
            ref = StreamingConsensus(
                members, stake, cfg,
                tile_budget=tile_budget, tile=tile,
                ingest_chunk=STREAM_CHUNK, window_bucket=2048,
                prune_min=1024,
            )
            t0 = time.time()
            with mon.phase("stream_single_ref"):
                for chunk in ref_chunks:
                    ref.ingest(chunk)
            single_evps = ref_events / (time.time() - t0)
            ref_used = ref_events
            ref.store.close()
            log(f"[mesh] single-device reference: {ref_events} ev = "
                f"{single_evps:.0f} ev/s")
        speedup = stream_evps / single_evps if single_evps else 0.0
        efficiency = speedup / mesh_n if mesh_n else 0.0
        log(f"[mesh] {mesh_n} devices: {stream_evps:.0f} ev/s vs single "
            f"{single_evps:.0f} ev/s -> speedup {speedup:.2f}x, "
            f"scaling efficiency {efficiency:.3f} "
            f"(peak_device_tiles={stats['peak_device_tiles']}, "
            f"repins={inc.repins})")
        mesh_out = {
            "devices": mesh_n,
            "evps": round(stream_evps, 1),
            "single_evps": round(single_evps, 1),
            "single_ref_events": ref_used,
            "speedup_vs_single": round(speedup, 3),
            "scaling_efficiency": round(efficiency, 4),
            "peak_device_tiles": stats["peak_device_tiles"],
            "device_tile_budget": device_tile_budget,
            "device_budget_ok": bool(dev_budget_ok),
            "device_resident_tiles": stats["device_resident_tiles"],
            "peak_resident_tiles": stats["peak_resident_tiles"],
            "budget_overruns": stats["budget_overruns"],
            "repins": inc.repins,
            "parity": bool(parity),
        }
    phases = mon.flat()
    out = {
        "metric": (
            f"streaming events/sec to consensus-order "
            f"@{n_done} events x {STREAM_MEMBERS} members ({platform}, "
            f"config-5 shape, tile budget {tile_budget}); "
            f"oracle-prefix parity={parity}"
        ),
        "value": round(stream_evps, 1),
        "unit": "events/s",
        "vs_baseline": 0.0,
        "phases": phases,
        "peak_host_bytes": mon.peak_host_bytes,
        "peak_device_bytes": mon.peak_device_bytes,
        "stream": {
            "evps": round(stream_evps, 1),
            "overlap_ratio": round(overlap, 4),
            "spill_pack_seconds": stats["spill_pack_seconds"],
            "spill_stall_seconds": stats["spill_stall_seconds"],
            "spill_queue_depth_peak": stats["spill_queue_depth_peak"],
            "members": STREAM_MEMBERS,
            "events": n_done,
            "chunk": STREAM_CHUNK,
            "tile": tile,
            "tile_budget": tile_budget,
            "budget_ok": bool(budget_ok),
            "ordered": len(res.order),
            "max_round": int(res.max_round),
            "window_size": inc.window_size,
            "pruned_prefix": inc.pruned_prefix,
            "peak_resident_visibility_bytes": stats["peak_resident_bytes"],
            "peak_resident_tiles": stats["peak_resident_tiles"],
            "archived_rows": stats["archived_rows"],
            "archive_bytes": stats["archive_bytes"],
            "widen_rebases": inc.widen_rebases,
            "full_rebases": inc.full_rebases,
            "oracle_prefix": n_oracle,
            "oracle_decided": len(oracle.consensus),
            "compile_cache": bool(cache_dir),
            "parity": bool(parity),
            # dotted keys bench_compare.py gates directly
            "dispatch_overhead_s": (
                dispatch_out["dispatch_overhead_s"]
                if dispatch_out is not None else None
            ),
            "trace_overhead_ratio": (
                dispatch_out["trace_overhead_ratio"]
                if dispatch_out is not None else None
            ),
            "dispatch": dispatch_out,
        },
        "finality": {
            "streaming": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in inc.finality.summary().items()
            },
        },
    }
    log(f"[finality] {json.dumps(out['finality'])}")
    if mesh_out is not None:
        out["stream_mesh"] = mesh_out
        out["metric"] = out["metric"].replace(
            "streaming events/sec",
            f"mesh-streaming ({mesh_n} dev) events/sec",
        )
    out["lint"] = lint_stamp()
    out["mc"] = mc_stamp()
    out["scale_audit"] = scale_audit_stamp()
    print(json.dumps(out), flush=True)
    mon.close()
    if not parity or not budget_ok or not dev_budget_ok:
        sys.exit(1)


def run_chaos_overhead():
    """--chaos-overhead: device-pipeline throughput under an active
    equivocation storm vs the same shape fault-free, in one JSON line.

    Two DAGs share (members, stake, seed): the attack DAG runs
    ``f = (n-1)//3`` forking creators at high fork probability (the
    in-budget worst case — fork pairs inflate the witness table and the
    identical-set checks), the clean DAG is fault-free.  Each is packed
    and run through ``run_consensus`` once to compile, then timed, and
    the line reports ``chaos_overhead.{clean_evps, attack_evps, ratio}``
    (ratio = attack/clean, higher is better) so bench_compare.py can
    gate adversary-path overhead like any other throughput number.

    Env knobs: BENCH_CHAOS_MEMBERS (32), BENCH_CHAOS_EVENTS (4000),
    BENCH_CHAOS_FORK_PROB (0.4).
    """
    tpu_ok = probe_tpu()
    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    log(f"[env] platform={platform} devices={len(jax.devices())}")

    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag
    from tpu_swirld.tpu.pipeline import run_consensus

    n_members = int(os.environ.get("BENCH_CHAOS_MEMBERS", "32"))
    n_events = int(os.environ.get("BENCH_CHAOS_EVENTS", "4000"))
    fork_prob = float(os.environ.get("BENCH_CHAOS_FORK_PROB", "0.4"))
    f_budget = (n_members - 1) // 3
    config = SwirldConfig(n_members=n_members)

    legs = {}
    for leg, n_forkers in (("clean", 0), ("attack", f_budget)):
        t0 = time.time()
        members, stake, events, _keys = generate_gossip_dag(
            n_members, n_events, seed=2, n_forkers=n_forkers,
            fork_prob=fork_prob if n_forkers else 0.0,
        )
        packed = pack_events(events, members, stake)
        log(f"[{leg}] {n_members} members / {len(events)} events, "
            f"{int(packed.fork_pairs.shape[0])} fork pairs "
            f"({time.time()-t0:.1f}s gen+pack)")
        run_consensus(packed, config)          # compile + warm
        t0 = time.time()
        res = run_consensus(packed, config)
        dt = time.time() - t0
        legs[leg] = {
            "evps": round(len(events) / dt, 1),
            "fork_pairs": int(packed.fork_pairs.shape[0]),
            "overflow_retries": int(res.timings.get("overflow_retries", 0)),
        }
        log(f"[{leg}] {legs[leg]['evps']:.0f} ev/s")

    ratio = legs["attack"]["evps"] / legs["clean"]["evps"]
    out = {
        "metric": "chaos_overhead_evps",
        "value": legs["attack"]["evps"],
        "unit": "events/sec",
        "platform": platform,
        "chaos_overhead": {
            "clean_evps": legs["clean"]["evps"],
            "attack_evps": legs["attack"]["evps"],
            "ratio": round(ratio, 4),
            "n_members": n_members,
            "n_events": n_events,
            "n_forkers": f_budget,
            "fork_prob": fork_prob,
            "fork_pairs": legs["attack"]["fork_pairs"],
            "overflow_retries": legs["attack"]["overflow_retries"],
        },
        "lint": lint_stamp(),
        "mc": mc_stamp(),
        "scale_audit": scale_audit_stamp(),
    }
    print(json.dumps(out), flush=True)


def run_churn():
    """--churn: dynamic-membership throughput + repack tail latency.

    One canonical multi-epoch schedule (a decided LEAVE then a decided
    JOIN — ``tpu_swirld.membership.sim.churn_schedule``) is replayed
    through the epoch-aware incremental driver and timed end to end:
    ``churn.evps`` is schedule events per second *including* ledger
    bookkeeping, epoch adoption, and any restatements.  The member-axis
    repack stage is then sampled BENCH_CHURN_REPACKS times per epoch
    boundary (fresh packer each trial, so every sample pays the real
    add-member + device-pad cost) and ``churn.repack_p99_s`` is the p99
    across all samples.  ``churn.epochs`` pins that the schedule really
    decided its membership txs — a regression that silently stops
    deciding would otherwise *raise* evps.  bench_compare.py gates evps
    and epochs higher-better and repack_p99_s lower-better.

    Env knobs: BENCH_CHURN_NODES (4), BENCH_CHURN_TURNS (700),
    BENCH_CHURN_SEED (0), BENCH_CHURN_REPACKS (30).
    """
    tpu_ok = probe_tpu()
    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    log(f"[env] platform={platform} devices={len(jax.devices())}")

    from tpu_swirld.membership.engine import run_dynamic
    from tpu_swirld.membership.repack import repack_packer
    from tpu_swirld.membership.sim import churn_schedule
    from tpu_swirld.packing import Packer

    n_nodes = int(os.environ.get("BENCH_CHURN_NODES", "4"))
    turns = int(os.environ.get("BENCH_CHURN_TURNS", "700"))
    seed = int(os.environ.get("BENCH_CHURN_SEED", "0"))
    n_repacks = int(os.environ.get("BENCH_CHURN_REPACKS", "30"))

    t0 = time.time()
    events, members, stake, _sim = churn_schedule(
        n_nodes, seed=seed, turns=turns,
    )
    log(f"[churn] {n_nodes} members / {len(events)} events "
        f"({time.time()-t0:.1f}s gossip gen)")

    # warm (jit compiles in the repack stage), then time the driver
    run_dynamic(events, members, stake, engine="incremental", chunk=64)
    t0 = time.time()
    res = run_dynamic(events, members, stake, engine="incremental",
                      chunk=64)
    dt = time.time() - t0
    evps = len(events) / dt
    epochs = res.epochs
    log(f"[churn] {evps:.0f} ev/s, {epochs} epochs, "
        f"{res.restatements} restatements, {len(res.order)} decided")

    # repack tail: fresh packer per trial so each sample pays the full
    # epoch-boundary cost (registry extension + stake swap + device pad)
    samples = []
    for _ in range(max(1, n_repacks)):
        packer = Packer(list(members), list(stake))
        for epoch in res.ledger.epochs[1:]:
            samples.append(repack_packer(packer, epoch).seconds)
    samples.sort()
    p99 = samples[min(len(samples) - 1, int(len(samples) * 0.99))]
    log(f"[churn] repack p99 {p99*1e3:.2f} ms over {len(samples)} samples")

    out = {
        "metric": "churn_evps",
        "value": round(evps, 1),
        "unit": "events/sec through the epoch-aware driver",
        "platform": platform,
        "churn": {
            "evps": round(evps, 1),
            "repack_p99_s": round(p99, 6),
            "epochs": epochs,
            "decided": len(res.order),
            "restatements": res.restatements,
            "repack_samples": len(samples),
            "n_nodes": n_nodes,
            "turns": turns,
            "events": len(events),
        },
        "lint": lint_stamp(),
        "mc": mc_stamp(),
        "scale_audit": scale_audit_stamp(),
    }
    print(json.dumps(out), flush=True)
    if epochs < 3:
        log(f"[churn] FAIL: schedule decided only {epochs} epochs (< 3)")
        sys.exit(1)


def run_cluster():
    """--cluster: real-process loopback cluster throughput + latency.

    Two legs, one JSON line:

    - **chaos leg** — BENCH_CLUSTER_NODES processes over loopback TCP,
      client traffic at BENCH_CLUSTER_RATE tx/s, one node SIGKILLed at
      30% of the window and restarted from checkpoint + WAL at 50%; the
      verdict (safety vs the oracle replay of the union DAG, liveness
      past the crash window) must be green, and the line reports
      ``cluster.{tx_per_s, submit_p50_s, submit_p99_s}`` — decided
      transactions per second and merged submission→decided wall
      latency — for bench_compare.py to gate;
    - **overload leg** — a small cluster with the admission window
      forced to zero under the same rate: every node must shed
      (``SHED:window``) rather than queue unboundedly.  ``shed == 0``
      means backpressure is broken and the bench exits 1.

    Env knobs: BENCH_CLUSTER_NODES (5), BENCH_CLUSTER_DURATION (6.0 s),
    BENCH_CLUSTER_RATE (300 tx/s), BENCH_CLUSTER_TX_BYTES (64),
    BENCH_CLUSTER_SEED (9).
    """
    import tempfile

    from tpu_swirld.net.cluster import ClusterSpec, run_cluster as _run

    n_nodes = int(os.environ.get("BENCH_CLUSTER_NODES", "5"))
    duration = float(os.environ.get("BENCH_CLUSTER_DURATION", "6.0"))
    rate = float(os.environ.get("BENCH_CLUSTER_RATE", "300"))
    tx_bytes = int(os.environ.get("BENCH_CLUSTER_TX_BYTES", "64"))
    seed = int(os.environ.get("BENCH_CLUSTER_SEED", "9"))
    net = {"gossip_interval_s": 0.005, "checkpoint_every_s": 0.5}

    workdir = tempfile.mkdtemp(prefix="swirld-bench-cluster-")
    log(f"[cluster] {n_nodes} processes, {duration}s @ {rate} tx/s, "
        f"kill -9 node 1 at {duration * 0.3:.1f}s, "
        f"restart at {duration * 0.5:.1f}s ({workdir})")
    verdict = _run(ClusterSpec(
        workdir=os.path.join(workdir, "chaos"),
        n_nodes=n_nodes, seed=seed, duration_s=duration,
        tx_rate=rate, tx_bytes=tx_bytes,
        kill_index=1, kill_at_s=duration * 0.3,
        restart_at_s=duration * 0.5,
        flightrec_dir=os.path.join(workdir, "chaos", "flightrec"),
        net=net,
    ))
    tx = verdict["tx"]
    log(f"[cluster] ok={verdict['ok']} decided_tx={tx['decided']} "
        f"({tx['tx_per_s']:.0f} tx/s) p99="
        f"{tx.get('submit_p99', float('nan')):.3f}s")

    log("[overload] 3 processes, admission window forced to 0 "
        "(every submission must shed, none may queue)")
    overload = _run(ClusterSpec(
        workdir=os.path.join(workdir, "overload"),
        n_nodes=3, seed=seed + 1, duration_s=min(duration, 3.0),
        tx_rate=rate, tx_bytes=tx_bytes,
        net=dict(net, max_undecided=0),
    ))
    shed = overload["tx"]["shed"]
    log(f"[overload] ok={overload['ok']} shed={shed} "
        f"acked={overload['tx']['acked']}")

    out = {
        "metric": "cluster_tx_per_s",
        "value": tx["tx_per_s"],
        "unit": "decided tx/sec",
        "platform": "cpu-processes",
        "cluster": {
            "tx_per_s": tx["tx_per_s"],
            "submit_p50_s": tx.get("submit_p50"),
            "submit_p99_s": tx.get("submit_p99"),
            "tx_submitted": tx["submitted"],
            "tx_acked": tx["acked"],
            "tx_failed": tx["failed"],
            "tx_decided": tx["decided"],
            "n_nodes": n_nodes,
            "duration_s": duration,
            "rate": rate,
            "verdict_ok": verdict["ok"],
            "safety": verdict["safety"],
            "liveness": verdict["liveness"],
            "overload_ok": overload["ok"],
            "overload_shed": shed,
            "wal_torn_tail_recovered":
                verdict["counters"]["wal_torn_tail_recovered"],
            # telemetry-plane artifacts: the merged cross-process trace
            # and the supervisor metrics rollup, so BENCH_r*.json is
            # self-describing for the trajectory tooling
            "merged_trace": (verdict.get("trace") or {}).get("merged"),
            "cross_process_traces":
                (verdict.get("trace") or {}).get("cross_process_traces"),
            "metrics_rollup": (verdict.get("metrics") or {}).get("json"),
            "metrics_prom": (verdict.get("metrics") or {}).get("prom"),
            "metrics_nodes_covered":
                (verdict.get("metrics") or {}).get("nodes_covered"),
        },
        "lint": lint_stamp(),
        "mc": mc_stamp(),
        "scale_audit": scale_audit_stamp(),
    }
    print(json.dumps(out), flush=True)
    if not verdict["ok"] or not overload["ok"]:
        log("[cluster] FAIL: verdict not green")
        sys.exit(1)
    if shed == 0:
        log("[overload] FAIL: zero submissions shed — backpressure "
            "is not engaging")
        sys.exit(1)


def run_soak():
    """--soak: the composed production-day scenario as a gated bench.

    One :func:`tpu_swirld.soak.run_soak` pass — BENCH_SOAK_NODES
    processes through per-link TCP fault proxies, heavy-tailed traffic
    from BENCH_SOAK_CLIENTS concurrent clients, and the smoke window
    composition (1 SIGKILL crash + WAL recovery, 1 partition/heal, 1
    byzantine equivocation storm) scaled to BENCH_SOAK_HORIZON — and one
    JSON line with ``soak.{tx_per_s, submit_p99_s,
    disruptions_survived, verdict_ok}`` for bench_compare.py to gate.
    Exit 1 on a red composite verdict.

    Env knobs: BENCH_SOAK_NODES (4), BENCH_SOAK_HORIZON (8.0 s),
    BENCH_SOAK_RATE (150 tx/s), BENCH_SOAK_CLIENTS (3),
    BENCH_SOAK_SEED (3).
    """
    import dataclasses
    import tempfile

    from tpu_swirld import soak as _soak

    n_nodes = int(os.environ.get("BENCH_SOAK_NODES", "4"))
    horizon = float(os.environ.get("BENCH_SOAK_HORIZON", "8.0"))
    rate = float(os.environ.get("BENCH_SOAK_RATE", "150"))
    clients = int(os.environ.get("BENCH_SOAK_CLIENTS", "3"))
    seed = int(os.environ.get("BENCH_SOAK_SEED", "3"))

    workdir = tempfile.mkdtemp(prefix="swirld-bench-soak-")
    log(f"[soak] {n_nodes} processes through per-link fault proxies, "
        f"{horizon}s @ {rate} tx/s from {clients} clients; "
        f"crash + partition + equivocation storm ({workdir})")
    spec = _soak.default_spec(
        workdir, n_nodes=n_nodes, seed=seed, horizon_s=horizon,
        tx_rate=rate, n_clients=clients,
        net={"gossip_interval_s": 0.005, "checkpoint_every_s": 0.5},
    )
    spec = dataclasses.replace(spec, schedule=_soak.smoke_schedule(spec))
    verdict = _soak.run_soak(spec)
    log(f"[soak] ok={verdict['ok']} "
        f"survived={verdict['disruptions_survived']}"
        f"/{verdict['disruptions_total']} "
        f"tx/s={verdict['tx_per_s']:.0f} "
        f"submit_p99={verdict['submit_p99_s']:.3f}s "
        f"equivocations={verdict['adversary']['equivocations_detected']}")

    out = {
        "metric": "soak_tx_per_s",
        "value": verdict["tx_per_s"],
        "unit": "acked tx/sec under composed faults",
        "platform": "cpu-processes",
        "soak": {
            "tx_per_s": verdict["tx_per_s"],
            "submit_p99_s": verdict["submit_p99_s"],
            "disruptions_survived": verdict["disruptions_survived"],
            "disruptions_total": verdict["disruptions_total"],
            "verdict_ok": verdict["ok"],
            "safety": verdict["safety"],
            "finality": verdict["finality"],
            "accounting_balance_ok":
                verdict["accounting"].get("balance_ok"),
            "shed_rate": verdict["accounting"].get("shed_rate"),
            "net_redials": verdict["counters"]["net_redials"],
            "equivocations_detected":
                verdict["adversary"]["equivocations_detected"],
            "proxy_relayed": verdict["proxy"].get("relayed", 0),
            "proxy_partition_blocked":
                verdict["proxy"].get("partition_blocked", 0),
            "n_nodes": n_nodes,
            "horizon_s": horizon,
            "rate": rate,
        },
        "lint": lint_stamp(),
        "mc": mc_stamp(),
        "scale_audit": scale_audit_stamp(),
    }
    print(json.dumps(out), flush=True)
    if not verdict["ok"]:
        log("[soak] FAIL: composite verdict not green")
        sys.exit(1)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--stream", action="store_true",
        help="run the BASELINE config-5 shape (256 members / 100k events; "
        "BENCH_STREAM_* overrides) through the slab-store streaming "
        "driver under --tile-budget instead of the default bench",
    )
    ap.add_argument(
        "--tile-budget", type=int, default=65536,
        help="resident visibility tile budget for --stream (tiles of "
        "--tile x --tile bools; default 65536 = 4 GB bool ceiling at "
        "tile 256 — the config-5 window peaks around ~2.2 GB); "
        "0 = unbounded (account only)",
    )
    ap.add_argument("--tile", type=int, default=256, help="tile side")
    ap.add_argument(
        "--mesh", type=int, default=0, metavar="D",
        help="with --stream: row-shard the resident window over D devices "
        "(simulated on CPU via xla_force_host_platform_device_count) and "
        "report per-device peak tiles + scaling efficiency in a "
        "stream_mesh JSON object",
    )
    ap.add_argument(
        "--device-tile-budget", type=int, default=0,
        help="with --mesh: per-device resident tile bound (widest row "
        "shard); 0 = unbounded (account only)",
    )
    ap.add_argument(
        "--chaos-overhead", action="store_true",
        help="stamp device-pipeline ev/s with an equivocation storm at "
        "the full f=(n-1)//3 budget vs fault-free into a "
        "chaos_overhead JSON object (BENCH_CHAOS_* overrides); "
        "bench_compare.py gates clean/attack ev/s and their ratio",
    )
    ap.add_argument(
        "--cluster", action="store_true",
        help="run a real-process loopback cluster (socket transport, tx "
        "ingestion, kill -9 + checkpoint/WAL recovery) and stamp decided "
        "tx/s + submission→decided p50/p99 into a cluster JSON object "
        "(BENCH_CLUSTER_* overrides); also runs an overload leg that "
        "must shed load (exit 1 on any verdict failure or zero sheds)",
    )
    ap.add_argument(
        "--churn", action="store_true",
        help="run the dynamic-membership churn leg (a decided leave + "
        "join over one gossip schedule through the epoch-aware driver) "
        "and stamp churn.{evps, repack_p99_s, epochs} "
        "(BENCH_CHURN_* overrides); bench_compare.py gates evps/epochs "
        "higher-better and repack p99 lower-better; exit 1 if the "
        "schedule decides fewer than 3 epochs",
    )
    ap.add_argument(
        "--soak", action="store_true",
        help="run the composed production-day soak (per-link TCP fault "
        "proxies, heavy-tailed traffic, crash + partition + equivocation "
        "storm windows) and stamp acked tx/s, client-observed submit "
        "p99, and disruptions survived into a soak JSON object "
        "(BENCH_SOAK_* overrides); exit 1 on a red composite verdict",
    )
    args = ap.parse_args(argv)
    if args.soak:
        run_soak()
    elif args.churn:
        run_churn()
    elif args.cluster:
        run_cluster()
    elif args.chaos_overhead:
        run_chaos_overhead()
    elif args.stream:
        run_stream(
            args.tile_budget or None, args.tile,
            mesh_n=args.mesh,
            device_tile_budget=args.device_tile_budget or None,
        )
    else:
        run_default()


if __name__ == "__main__":
    main()
