"""Scale-envelope abstract interpreter suite (tpu_swirld.analysis.flow).

Four layers, mirroring how the audit earns trust:

- **soundness**: the lattice-soundness property — for every stage a real
  small run of each engine dispatches, replay the observed call through
  the interpreter at concrete-argument intervals and assert the abstract
  output intervals contain every concrete output value (the defining
  property of the abstraction; a transfer function that under-
  approximates fails here before it can hide a real overflow);
- **teeth**: both seeded mutations (an int16-narrowed tally accumulator,
  a dropped index clip) must be *caught*, with the exact rule, file,
  line, and primitive pinpointed — a silently weakened transfer fails;
- **coverage**: every registered transfer function is exercised by the
  catalog plus a micro-trace battery (version-alias groups count as one
  transfer), and every stage name the engines dispatch at runtime maps
  to an audited spec;
- **the gates**: the shipped tree is proven clean at baseline *and* the
  1M-event envelope, suppressions demand justification text, the CLI
  exit codes hold (0 clean / 1 findings / 2 unknown primitive), and the
  bench stamp + bench_compare gate refuse dirty or missing proofs.
"""

import dataclasses
import functools
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from tpu_swirld.analysis.flow import stages
from tpu_swirld.analysis.flow.audit import (
    MUTATIONS,
    _apply_suppressions,
    main as audit_main,
    scale_audit,
    scale_audit_stamp,
)
from tpu_swirld.analysis.flow.envelope import (
    INT32_MAX,
    get_envelope,
    host_envelope_findings,
    preset_names,
)
from tpu_swirld.analysis.flow.interpret import RULE_NAMES, interpret_jaxpr
from tpu_swirld.analysis.flow.lattice import AbsVal, Interval
from tpu_swirld.analysis.flow.transfer import (
    TRANSFERS,
    UnknownPrimitiveError,
    registered_primitives,
)
from tpu_swirld.analysis.lint import Finding

pytestmark = pytest.mark.audit

_ROOT = os.path.join(os.path.dirname(__file__), "..")


@functools.lru_cache(maxsize=None)
def _audit(envelope, mutate=None):
    """One shared audit run per (envelope, mutation) for the module."""
    return scale_audit(envelope, check_coverage=False, mutate=mutate)


@pytest.fixture(scope="module", autouse=True)
def _compile_cache_hygiene():
    # this module traces the full catalog at two envelopes and replays
    # every engine's stages; drop the accumulated executables afterwards
    # so the rest of the suite runs at its usual jit-cache footprint
    yield
    _audit.cache_clear()
    jax.clear_caches()


# ----------------------------------------------------- lattice basics


def test_interval_lattice_ops():
    a, b = Interval(0, 10), Interval(5, 20)
    assert a.join(b) == Interval(0, 20)
    assert a.meet(b) == Interval(5, 10)
    assert Interval(0, 20).covers(a) and not a.covers(b)
    assert Interval(3, 3).is_point


def test_absval_literal_dtype():
    # a Python-int literal must take the jaxpr aval's dtype, not the
    # host default (int64 literals joined against int32 carries was a
    # real analyzer bug at the 1m envelope)
    v = AbsVal.from_literal(np.int32(7))
    assert v.dtype == np.dtype(np.int32) and v.iv == Interval(7, 7)


# ----------------------------------------------------- interpreter regressions


def _interp(fn, structs, ivs):
    closed = jax.make_jaxpr(fn)(*structs)
    findings = []
    res = interpret_jaxpr(closed, ivs, sentinels=(INT32_MAX,),
                          findings=findings)
    return res, findings


def test_negative_index_normalization_not_widened():
    # jnp's negative-index normalization (where(i < 0, i + n, i)) must
    # fold to the in-range branch when the operand interval decides the
    # comparison — joining both arms was the analyzer's biggest source
    # of false SW009s
    def f(x, i):
        return x[jnp.where(i < 0, i + x.shape[0], i)]

    res, findings = _interp(
        f,
        [jax.ShapeDtypeStruct((16,), np.int32),
         jax.ShapeDtypeStruct((), np.int32)],
        [(0, 99), (0, 15)],
    )
    assert not findings
    assert res.outs[0].iv == Interval(0, 99)


def test_roll_remainder_start_proven_in_bounds():
    # jnp.roll lowers to concatenate + dynamic_slice with a floored-mod
    # start; the remainder summary must keep the start inside [0, n]
    def f(x, s):
        return jnp.roll(x, -s)

    res, findings = _interp(
        f,
        [jax.ShapeDtypeStruct((16,), np.int32),
         jax.ShapeDtypeStruct((), np.int32)],
        [(0, 99), (0, 7)],
    )
    assert not findings
    assert res.outs[0].iv == Interval(0, 99)


def test_unknown_primitive_hard_fails():
    # no silent assume-top: an unmodeled primitive refuses, loudly
    def f(x):
        return lax.sin(x)

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4,), np.float32))
    with pytest.raises(UnknownPrimitiveError) as ei:
        interpret_jaxpr(closed, [None])
    assert ei.value.primitive == "sin"


# ----------------------------------------------------- soundness property

_SOUNDNESS_SEEDS = {"batch": (3,), "incremental": (3,),
                    "streaming": (3,), "mesh": (3,)}


def _soundness_violations(engine, seed):
    """Replay every stage call a real run dispatches through the
    interpreter; return containment violations (must be empty)."""
    calls, seen = [], set()

    def collect(name, fn, args, kw):
        if name in seen:
            return
        seen.add(name)
        # snapshot before dispatch: several stages donate their inputs
        calls.append((name, fn, tuple(np.asarray(a) for a in args),
                      dict(kw)))

    stages.observed_stage_names(engine, seed=seed, collect=collect)
    assert calls, f"engine {engine!r} dispatched no stages"

    bad = []
    for name, fn, args, kw in calls:
        closed, ivs = stages.trace_concrete_call(fn, args, kw)
        res = interpret_jaxpr(closed, ivs, stage=name,
                              sentinels=(INT32_MAX,))
        leaves = jax.tree_util.tree_leaves(fn(*args, **kw))
        assert len(leaves) == len(res.outs), name
        for j, (av, leaf) in enumerate(zip(res.outs, leaves)):
            arr = np.asarray(leaf)
            if arr.size == 0:
                continue
            lo, hi = float(arr.min()), float(arr.max())
            if np.isnan(lo) or np.isnan(hi):
                continue
            if not (float(av.iv.lo) <= lo and hi <= float(av.iv.hi)):
                bad.append(f"{name} out[{j}]: abstract {av.iv} misses "
                           f"concrete [{lo}, {hi}] ({arr.dtype})")
    return bad


@pytest.mark.parametrize("engine", stages.ENGINES)
def test_lattice_soundness(engine):
    for seed in _SOUNDNESS_SEEDS[engine]:
        bad = _soundness_violations(engine, seed)
        assert not bad, "\n".join(bad)


@pytest.mark.slow
@pytest.mark.parametrize("engine", stages.ENGINES)
def test_lattice_soundness_seed_sweep(engine):
    for seed in (5, 11, 23):
        bad = _soundness_violations(engine, seed)
        assert not bad, "\n".join(bad)


# ----------------------------------------------------- the shipped tree


def test_baseline_proven_clean():
    rep = _audit("baseline")
    assert rep.exit_code == 0 and rep.clean
    assert not rep.findings and not rep.unjustified and not rep.errors
    # the pipeline's intentional sentinel masking rides on justified
    # suppressions — each must carry its why-safe text
    assert rep.suppressed
    for f, note in rep.suppressed:
        assert note.strip(), f.render()
    assert len(rep.specs) == len(stages.CATALOG)


def test_envelope_1m_proven_clean():
    # the headline guarantee: the full catalog at 2**20 events /
    # 256 members, all engines, exits 0
    rep = _audit("1m")
    assert rep.exit_code == 0 and rep.clean, rep.render()


def test_stage_coverage_no_gaps():
    cmap = stages.coverage_map()
    for engine in stages.ENGINES:
        observed = stages.observed_stage_names(engine)
        assert observed, engine
        gaps = [s for s in observed if s not in cmap]
        assert not gaps, f"{engine}: uncovered stages {gaps}"


# ----------------------------------------------------- transfer coverage

#: micro-traces for primitives the consensus stages don't emit; each
#: probe must exercise its named transfer (version-alias spellings that
#: this jax release never emits — e.g. psum vs psum2, pcast — are
#: covered via the function-identity groups instead)
_BATTERY = [
    ("abs", lambda x: jnp.abs(x), (-5, 5)),
    ("argmin", lambda x: jnp.argmin(x), (0, 7)),
    ("clamp", lambda x: lax.clamp(jnp.int32(0), x, jnp.int32(5)), (-9, 9)),
    ("copy", lambda x: jnp.copy(x), (0, 7)),
    ("cumsum", lambda x: jnp.cumsum(x), (0, 7)),
    ("integer_pow", lambda x: x ** 2, (0, 7)),
    ("le", lambda x: (x <= 3).astype(np.int32), (0, 7)),
    ("pad", lambda x: jnp.pad(x, (1, 1)), (0, 7)),
    ("reduce_min", lambda x: jnp.min(x), (0, 7)),
    ("rev", lambda x: jnp.flip(x), (0, 7)),
    ("xor", lambda x: x ^ 3, (0, 7)),
]


def _battery_exercised():
    ex = set()
    for name, fn, iv in _BATTERY:
        closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8,), np.int32))
        got = set()
        interpret_jaxpr(closed, [iv], exercised=got)
        assert name in got, f"battery probe {name!r} exercised {sorted(got)}"
        ex |= got
    return ex


def test_transfer_registry_fully_exercised():
    # acceptance: every registered transfer is exercised by tests.
    # Names registered for other jax releases' spellings share their
    # transfer function with a spelling this release does emit, so
    # coverage is counted per transfer *function*, not per name.
    exercised = set(_audit("baseline").exercised)
    exercised |= _audit("1m").exercised
    for m in sorted(MUTATIONS):
        exercised |= _audit("baseline", m).exercised
    exercised |= _battery_exercised()

    groups = {}
    for name, fn in TRANSFERS.items():
        groups.setdefault(id(fn), []).append(name)
    missed = [sorted(names) for names in groups.values()
              if not exercised & set(names)]
    assert not missed, f"transfers never exercised: {missed}"
    # the higher-order forms are interpreted structurally, not via the
    # registry — they must be exercised too
    assert {"pjit", "scan", "while", "cond",
            "shard_map"} <= exercised


def test_registered_primitives_listing():
    names = registered_primitives()
    assert names == sorted(names) and len(names) == len(set(names))
    assert {"gather", "scatter", "dynamic_slice", "add", "mul",
            "convert_element_type"} <= set(names)


# ----------------------------------------------------- mutation teeth


def test_mutation_ssm_int16_accumulator_caught():
    rep = _audit("baseline", "ssm-acc-int16")
    assert rep.exit_code == 1 and not rep.clean and not rep.errors
    rules = {f.rule for f in rep.findings}
    assert {"SW010", "SW008"} <= rules
    for f in rep.findings:
        assert f.path.endswith("tpu_swirld/analysis/flow/audit.py")
        assert f.line > 0
    msgs = " ".join(f.message for f in rep.findings)
    assert "convert_element_type" in msgs     # the narrowing cast
    assert "int16" in msgs                    # pinpointed dtype
    # both findings land on the seeded line, not somewhere nearby
    assert len({f.line for f in rep.findings}) == 1


def test_mutation_dropped_clip_caught():
    rep = _audit("baseline", "dropped-clip")
    assert rep.exit_code == 1 and not rep.clean and not rep.errors
    assert {f.rule for f in rep.findings} == {"SW009"}
    (f,) = rep.findings
    assert f.path.endswith("tpu_swirld/analysis/flow/audit.py")
    assert "dynamic_slice" in f.message
    assert rep.mutation == "dropped-clip"


def test_mutations_are_never_suppressible():
    # the seeded defects live in audit.py, which must carry no
    # swirld-lint disables — otherwise the self-test could be silenced
    from tpu_swirld.analysis.lint import suppression_notes

    with open(os.path.join(
            _ROOT, "tpu_swirld", "analysis", "flow", "audit.py")) as fh:
        assert suppression_notes(fh.read()) == {}


def test_unknown_mutation_rejected():
    with pytest.raises(ValueError, match="unknown mutation"):
        scale_audit("baseline", mutate="nope")
    with pytest.raises(ValueError, match="unknown engines"):
        scale_audit("baseline", engines=["gpuzzz"])


# ----------------------------------------------------- suppressions


def test_suppression_requires_justification(tmp_path):
    src = (
        "a = t[i]  # swirld-lint: disable=SW009\n"
        "b = t[j]  # swirld-lint: disable=SW009 -- j is packer-clamped\n"
        "c = t[k]\n"
    )
    p = tmp_path / "frag.py"
    p.write_text(src)

    def fd(line):
        return Finding("SW009", RULE_NAMES["SW009"], str(p), line, 0,
                       "index not provably in bounds")

    kept, suppressed, unjustified = _apply_suppressions(
        [fd(1), fd(2), fd(3)])
    assert [f.line for f in kept] == [3]
    assert [(f.line, note) for f, note in suppressed] == \
        [(2, "j is packer-clamped")]
    assert [f.line for f in unjustified] == [1]
    assert "without justification" in unjustified[0].message


def test_suppression_wrong_rule_does_not_apply(tmp_path):
    p = tmp_path / "frag.py"
    p.write_text("a = t[i]  # swirld-lint: disable=SW008 -- wraps are ok\n")
    f = Finding("SW009", RULE_NAMES["SW009"], str(p), 1, 0, "oob")
    kept, suppressed, unjustified = _apply_suppressions([f])
    assert kept == [f] and not suppressed and not unjustified


# ----------------------------------------------------- envelopes (host side)


def test_envelope_presets():
    assert set(preset_names()) >= {"baseline", "1m", "custom"}
    env = get_envelope("custom", {"events": 123})
    assert env.events == 123 and env.name == "custom"
    with pytest.raises(ValueError, match="unknown envelope fields"):
        get_envelope("custom", {"eventz": 1})
    with pytest.raises(ValueError, match="unknown envelope"):
        get_envelope("2g")


def test_shipped_envelopes_pass_host_checks():
    assert not host_envelope_findings(get_envelope("baseline"))
    assert not host_envelope_findings(get_envelope("1m"))


def test_host_checks_catch_bad_envelopes():
    # a timestamp bound reaching the order sentinel must be SW011
    env = get_envelope("custom", {"t_max": INT32_MAX})
    assert "SW011" in {f.rule for f in host_envelope_findings(env)}
    # stake pushing 3*tot past int32 must be SW008
    env = get_envelope("custom", {"stake_max": 1 << 24})
    assert "SW008" in {f.rule for f in host_envelope_findings(env)}


# ----------------------------------------------------- CLI + stamp + gate


def test_cli_list_rules(capsys):
    assert audit_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("SW008", "SW009", "SW010", "SW011"):
        assert rid in out


def test_cli_clean_baseline_with_coverage(capsys):
    # the full CLI path: catalog + host checks + runtime coverage probe
    # (one engine keeps the probe's compile load out of the suite budget;
    # test_stage_coverage_no_gaps sweeps all four)
    assert audit_main(["--envelope", "baseline", "--engine", "batch"]) == 0
    assert "proven clean" in capsys.readouterr().out


def test_cli_mutation_exits_one(capsys):
    rc = audit_main(["--mutate", "dropped-clip", "--json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["clean"] is False and doc["mutation"] == "dropped-clip"
    assert doc["findings"] and doc["findings"][0]["rule"] == "SW009"


def test_cli_unknown_primitive_exits_two(monkeypatch, capsys):
    def bad_build(env):
        @jax.jit
        def unmodeled(x):
            return lax.sin(x)
        return unmodeled, {}, [stages.ArgDecl((4,), np.float32)]

    spec = stages.StageSpec("synthetic.sin", "synthetic.sin",
                            ("batch",), bad_build)
    monkeypatch.setattr(stages, "specs_for_engines", lambda e: [spec])
    rc = audit_main(["--envelope", "baseline", "--no-coverage"])
    assert rc == 2
    assert "unknown primitive 'sin'" in capsys.readouterr().out


def test_scale_audit_stamp_shape():
    d = scale_audit_stamp("baseline")
    assert d["clean"] is True and d["envelope"] == "baseline"
    assert d["findings"] == 0 and d["errors"] == 0
    assert d["suppressed"] > 0
    assert d["engines"] == list(stages.ENGINES)
    # cached per process: bench stamps several artifacts per run
    assert scale_audit_stamp("baseline") == d


def test_bench_compare_refuses_dirty_or_missing_stamp():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(_ROOT, "scripts", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    clean = {"scale_audit": {"envelope": "baseline", "clean": True}}
    dirty = {"scale_audit": {"envelope": "baseline", "clean": False,
                             "findings": 2}}
    assert mod.scale_audit_gate(clean) is None
    assert "failed the scale audit" in mod.scale_audit_gate(dirty)
    assert "no scale_audit stamp" in mod.scale_audit_gate({})


def test_audit_report_render_and_dict():
    rep = _audit("baseline", "ssm-acc-int16")
    txt = rep.render()
    assert "mutate=ssm-acc-int16" in txt and "finding(s)" in txt
    doc = rep.to_dict()
    assert doc["exit_code"] == 1
    assert doc["specs"] == ["mutation.ssm-acc-int16"]
    assert doc["exercised"] == sorted(rep.exercised)
