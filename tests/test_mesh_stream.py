"""Row-sharded mesh streaming: the resident window split over devices.

The contract is threefold.  **Placement**: the ``anc``/``sees``/``ssm``
slabs must live as genuine ``P(axis, None)`` row shards — (W/D, ·) per
device, never replicated (the whole point is dividing device memory by
the mesh) — and the store's per-device tile accounting must track the
shard, with peaks landing at total/D when the shard divides the tile.
**Parity**: every output is bit-identical to the single-device streaming
driver, the batch pass, and the oracle, through every streaming corner —
widening rebase over archived tiles, forged straggler witnesses below
the frozen vote horizon (the full-rebase fallback), and fork-pair sees
materialization — because the halo-exchange kernel computes exactly the
single-device gathers.  **Budget**: ``device_tile_budget`` bounds the
widest shard exactly like the global budget (strict mode raises).
"""

import jax
import numpy as np
import pytest

from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.packing import pack_events, pack_node
from tpu_swirld.parallel import (
    MeshStreamingConsensus,
    make_mesh,
    make_row_sharded_block_fn,
    streaming_consensus_for_mesh,
)
from tpu_swirld.sim import generate_gossip_dag, make_simulation
from tpu_swirld.store import StreamingConsensus
from tpu_swirld.store.slab import TileBudgetExceeded
from tpu_swirld.tpu.pipeline import run_consensus

from tests.test_incremental import assert_same_result
from tests.test_pipeline import assert_parity

needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def assert_row_sharded(inc, d):
    """Every resident slab is a (W/D, ·) row shard on each device — the
    guard against a spec regression quietly re-replicating the window."""
    slabs = [("anc", inc._anc_d), ("ssm", inc._ssm_d)]
    if inc._sees_d is not inc._anc_d:
        slabs.append(("sees", inc._sees_d))
    for name, arr in slabs:
        shards = arr.addressable_shards
        assert len(shards) == d, f"{name}: {len(shards)} shards, want {d}"
        assert arr.shape[0] % d == 0, name
        for s in shards:
            assert s.data.shape[0] == arr.shape[0] // d, (
                f"{name} shard rows {s.data.shape[0]} != "
                f"{arr.shape[0]}//{d} (replicated or wrong axis?)"
            )
            assert tuple(s.data.shape[1:]) == tuple(arr.shape[1:]), name


def test_mesh_smoke_2dev_row_sharded():
    """Fast tier-1 guard: a tiny history on a 2-device mesh keeps the
    slabs row-sharded end-to-end and stays batch-identical."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    members, stake, events, _keys = generate_gossip_dag(6, 300, seed=9)
    cfg = SwirldConfig(n_members=6)
    inc = streaming_consensus_for_mesh(
        make_mesh(2), members, stake, cfg, chunk=64, window_bucket=256,
        prune_min=64, ingest_chunk=128,
    )
    for i in range(0, len(events), 100):
        st = inc.ingest(events[i : i + 100])
    assert st["mesh_devices"] == 2 and "mesh_repins" in st
    assert_row_sharded(inc, 2)
    s = inc.store.stats()
    assert s["n_shards"] == 2
    # tile granularity: a shard never accounts MORE than the whole slab
    # (strict total/D division is pinned by the 8-device tile test)
    assert s["device_resident_tiles"] <= s["resident_tiles"]
    packed = pack_events(events, members, stake)
    assert_same_result(inc.result(), run_consensus(packed, cfg))


@needs8
def test_mesh_device_tiles_are_total_over_d():
    """When the row shard divides the tile (W/D a tile multiple), the
    per-device peak is exactly total/D — the bench's acceptance number."""
    members, stake, events, _keys = generate_gossip_dag(10, 700, seed=5)
    cfg = SwirldConfig(n_members=10)
    inc = streaming_consensus_for_mesh(
        make_mesh(8), members, stake, cfg, chunk=64, window_bucket=2048,
        prune_min=1024, ingest_chunk=256, tile=256,
    )
    for i in range(0, len(events), 200):
        inc.ingest(events[i : i + 200])
    assert_row_sharded(inc, 8)
    s = inc.store.stats()
    assert inc._w_pad % (256 * 8) == 0     # shard divides the tile
    assert s["device_resident_tiles"] * 8 == s["resident_tiles"]
    assert s["peak_device_tiles"] * 8 == s["peak_resident_tiles"]


@needs8
def test_mesh_streaming_widening_rebase_parity():
    """A stale-view sync referencing long-pruned history: the mesh driver
    answers with the widening rebase (archived tiles re-fetched, rows
    scattered back to their owners through slab_put) and stays
    bit-identical to the single-device streaming driver and batch."""
    members, stake, events, keys = generate_gossip_dag(8, 1600, seed=11)
    cfg = SwirldConfig(n_members=8)
    kw = dict(chunk=64, window_bucket=256, prune_min=64, ingest_chunk=256)
    mesh_inc = streaming_consensus_for_mesh(
        make_mesh(8), members, stake, cfg, **kw
    )
    single = StreamingConsensus(members, stake, cfg, **kw)
    for i in range(0, len(events), 200):
        mesh_inc.ingest(events[i : i + 200])
        single.ingest(events[i : i + 200])
    assert mesh_inc.pruned_prefix > 400
    pk3, sk3 = keys[3]
    head3 = [ev for ev in events if ev.c == pk3][-1]
    old0 = events[100]
    assert 100 < mesh_inc.pruned_prefix
    strag = Event(
        d=b"stale-sync", p=(head3.id, old0.id), t=events[-1].t + 1, c=pk3
    ).signed(sk3)
    full_before = mesh_inc.full_rebases
    mesh_inc.ingest([strag])
    single.ingest([strag])
    assert mesh_inc.widen_rebases == 1
    assert mesh_inc.full_rebases == full_before
    assert mesh_inc.store.archive.fetched_rows > 0
    assert_row_sharded(mesh_inc, 8)        # the widened push re-scattered
    assert_same_result(mesh_inc.result(), single.result())
    packed = pack_events(events + [strag], members, stake)
    assert_same_result(mesh_inc.result(), run_consensus(packed, cfg))


@needs8
def test_mesh_streaming_straggler_witness_full_rebase():
    """A forged straggler WITNESS below the frozen vote horizon routes
    through the exact full-batch fallback; its slab push rides slab_put,
    so the rebuilt window comes back sharded and oracle-identical."""
    from tpu_swirld.sim import make_straggler_event

    sim = make_simulation(5, seed=23)
    sim.run(260)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    lag = sim.nodes[-1]
    strag = make_straggler_event(node, lag.pk, lag.sk, at_round=1)
    inc = streaming_consensus_for_mesh(
        make_mesh(8), node.members, stake, node.config,
        block=64, chunk=32, window_bucket=256, prune_min=64,
    )
    for i in range(0, len(events), 50):
        inc.ingest(events[i : i + 50])
    inc.ingest([strag])
    assert inc.full_rebases >= 1
    assert_row_sharded(inc, 8)
    packed = pack_events(events + [strag], node.members, stake)
    assert_same_result(
        inc.result(), run_consensus(packed, node.config, block=64)
    )


@needs8
def test_mesh_streaming_forks_materialize_sharded_sees():
    """Fork pairs through the sharded window: sees detaches from anc as
    its own row shard, fork poisoning stays exact through the halo
    kernel, and outputs match single-device streaming and the oracle."""
    members, stake, events, _keys = generate_gossip_dag(
        12, 1000, seed=4, n_forkers=4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    cfg = SwirldConfig(n_members=12)
    kw = dict(chunk=64, window_bucket=512, prune_min=128, ingest_chunk=256)
    inc = streaming_consensus_for_mesh(
        make_mesh(8), members, stake, cfg, **kw
    )
    single = StreamingConsensus(members, stake, cfg, **kw)
    for i in range(0, len(events), 250):
        inc.ingest(events[i : i + 250])
        single.ingest(events[i : i + 250])
    assert inc._sees_d is not inc._anc_d   # forks materialized sees
    assert_row_sharded(inc, 8)
    assert_same_result(inc.result(), single.result())
    assert_same_result(inc.result(), run_consensus(packed, cfg))


@needs8
def test_mesh_window_bucket_rounds_to_mesh_multiple():
    """Every row capacity must split evenly over the mesh: a bucket that
    doesn't divide is rounded up, so W_pad % D == 0 always holds."""
    members, stake, events, _keys = generate_gossip_dag(6, 200, seed=2)
    cfg = SwirldConfig(n_members=6)
    inc = streaming_consensus_for_mesh(
        make_mesh(8), members, stake, cfg, chunk=32, window_bucket=260,
        prune_min=64, ingest_chunk=128,
    )
    assert inc._window_bucket % 8 == 0
    inc.ingest(events)
    assert inc._w_pad % 8 == 0
    assert_row_sharded(inc, 8)


@needs8
def test_mesh_device_tile_budget_strict_raises():
    """``device_tile_budget`` bounds the widest row shard like the global
    budget: a growth past it raises in strict mode."""
    members, stake, events, _keys = generate_gossip_dag(8, 600, seed=7)
    cfg = SwirldConfig(n_members=8)
    inc = streaming_consensus_for_mesh(
        make_mesh(8), members, stake, cfg, chunk=64, window_bucket=256,
        prune_min=64, ingest_chunk=128,
        device_tile_budget=1, strict_budget=True,
    )
    with pytest.raises(TileBudgetExceeded):
        for i in range(0, len(events), 100):
            inc.ingest(events[i : i + 100])


@needs8
def test_row_sharded_block_fn_matches_single_device_stage():
    """The halo-exchange kernel alone, against the single-device stage on
    identical inputs (including masked member-table slots and pad
    columns): bit-for-bit equal."""
    import jax.numpy as jnp

    from tpu_swirld.tpu.pipeline import ssm_block_stage

    rng = np.random.default_rng(0)
    n, m, k, c, rows = 512, 6, 8, 64, 128
    sees = jnp.asarray(rng.random((n, n)) < 0.3)
    mt = rng.integers(-1, n, size=(m, k)).astype(np.int32)
    stake = np.ones((m,), np.int32)
    cols = rng.integers(-1, n, size=(c,)).astype(np.int32)
    kern = make_row_sharded_block_fn(make_mesh(8))
    for row0 in (0, 96, n - rows):
        want = ssm_block_stage(
            sees, jnp.asarray(mt), jnp.asarray(stake), jnp.asarray(cols),
            np.int32(row0), rows=rows, tot_stake=int(stake.sum()),
            matmul_dtype_name="float32",
        )
        got = kern(
            sees, jnp.asarray(mt), jnp.asarray(stake), jnp.asarray(cols),
            np.int32(row0), rows=rows, tot_stake=int(stake.sum()),
            matmul_dtype_name="float32",
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@needs8
def test_mesh_chaos_engine_parity():
    """`scripts/chaos_run.py --engine streaming-mesh` rides this path: the
    chaos harness's cross-engine probe with the row-sharded driver."""
    from tpu_swirld.chaos import _engines_agree
    from tpu_swirld.sim import run_with_forkers

    sim = run_with_forkers(n_nodes=6, n_forkers=1, n_turns=180, seed=13)
    node = sim.nodes[0]
    out = _engines_agree(node, engine="streaming-mesh")
    assert out["engine"] == "streaming-mesh"
    assert out["batch_oracle_parity"] and out["incremental_batch_parity"]
    assert out["mesh_devices"] == 8
    assert out["store"]["n_shards"] == 8
