"""End-to-end soak: mixed backends + byzantine equivocation + checkpoint.

One population exercising every subsystem at once: python-backend honest
nodes, a tpu-backend (device pipeline) honest node, two divergent
equivocating forkers, orphan/want-list recovery, a mid-stream checkpoint
restored and replayed.  The protocol claims under test: honest prefix
agreement, fork detection, backend equivalence, restore fidelity.
"""

import dataclasses
import random

import pytest

from tpu_swirld import crypto
from tpu_swirld.checkpoint import load_node, save_node
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.node import Node
from tpu_swirld.sim import DivergentForker


@pytest.mark.slow
def test_mixed_backend_byzantine_soak(tmp_path):
    n_nodes, n_forkers, n_turns = 7, 2, 420
    config = SwirldConfig(n_members=n_nodes, seed=77)
    rng = random.Random(77)
    keys = [crypto.keypair(b"soak-%d" % i) for i in range(n_nodes)]
    members = [pk for pk, _ in keys]
    network, network_want, clock = {}, {}, [0]
    forkers, honest = [], []
    for i, (pk, sk) in enumerate(keys):
        if i < n_forkers:
            f = DivergentForker(
                sk, pk, members, network, network_want, config,
                lambda: clock[0], rng,
            )
            network[pk], network_want[pk] = f.ask_sync, f.ask_events
            forkers.append(f)
        else:
            cfg = config
            if i == n_forkers:   # one honest member runs the device engine
                cfg = dataclasses.replace(
                    config, backend="tpu", block_size=128
                )
            node = Node(
                sk=sk, pk=pk, network=network, members=members, config=cfg,
                clock=lambda: clock[0], network_want=network_want,
            )
            network[pk], network_want[pk] = node.ask_sync, node.ask_events
            honest.append(node)
    honest_pks = [n.pk for n in honest]
    tpu_node = honest[0]
    ckpt = str(tmp_path / "mid.swck")
    for turn in range(n_turns):
        clock[0] += 1
        node = honest[rng.randrange(len(honest))]
        peers = [pk for pk in members if pk != node.pk]
        peer = peers[rng.randrange(len(peers))]
        new_ids = node.sync(peer, b"tx:%d" % turn)
        node.consensus_pass(new_ids)
        if turn == n_turns // 2:
            save_node(ckpt, tpu_node)
        if turn % 3 == 0:
            for f in forkers:
                f.step(honest_pks)

    # 1. honest prefix agreement across backends
    orders = [n.consensus for n in honest]
    m = min(len(o) for o in orders)
    assert m > 0, "consensus must stay live"
    assert all(o[:m] == orders[0][:m] for o in orders)
    # 2. the tpu-backend node ordered events and detected a fork somewhere
    assert len(tpu_node.consensus) > 0
    forker_pks = {f.pk for f in forkers}
    assert any(n.has_fork[p] for n in honest for p in forker_pks)
    # 3. mid-stream checkpoint restores to a python replay with identical
    #    state, and the restored node keeps gossiping
    restored = load_node(
        ckpt, sk=tpu_node.sk, pk=tpu_node.pk, network=network,
        network_want=network_want,
    )
    # the mid-stream state must be a prefix of the live node's final state
    k = len(restored.consensus)
    assert restored.consensus == tpu_node.consensus[:k]
    peer = honest[1].pk
    got = restored.sync(peer, b"resume")
    restored.consensus_pass(got)
    mm = min(len(restored.consensus), len(honest[1].consensus))
    assert restored.consensus[:mm] == honest[1].consensus[:mm]
