"""End-to-end soak: mixed backends + byzantine equivocation + checkpoint.

One population exercising every subsystem at once: python-backend honest
nodes, a tpu-backend (device pipeline) honest node, two divergent
equivocating forkers, orphan/want-list recovery, a mid-stream checkpoint
restored and replayed.  The protocol claims under test: honest prefix
agreement, fork detection, backend equivalence, restore fidelity.
"""

import dataclasses

import pytest

from tpu_swirld.chaos import ChaosScenario, ChaosSimulation
from tpu_swirld.checkpoint import load_node
from tpu_swirld.sim import run_with_divergent_forkers
from tpu_swirld.transport import FaultPlan, LinkFaults, Partition


@pytest.mark.slow
def test_mixed_backend_byzantine_soak(tmp_path):
    n_turns = 420
    ckpt = str(tmp_path / "mid.swck")
    saved = {}

    def node_config(i, base):
        # honest member index 2 (first honest slot) runs the device engine
        if i == 2:
            return dataclasses.replace(base, backend="tpu", block_size=128)
        return base

    def on_turn(turn, honest):
        if turn == n_turns // 2 and not saved:
            from tpu_swirld.checkpoint import save_node

            save_node(ckpt, honest[0])
            saved["done"] = True

    sim = run_with_divergent_forkers(
        7, 2, n_turns, seed=77, fork_every=3,
        node_config=node_config, on_turn=on_turn,
    )
    honest = sim.nodes
    tpu_node = honest[0]
    assert tpu_node._tpu_engine is not None, "device engine must have run"

    # 1. honest prefix agreement across backends
    orders = [n.consensus for n in honest]
    m = min(len(o) for o in orders)
    assert m > 0, "consensus must stay live"
    assert all(o[:m] == orders[0][:m] for o in orders)
    # 2. the tpu-backend node ordered events and a fork was detected
    assert len(tpu_node.consensus) > 0
    forker_pks = {f.pk for f in sim.forkers}
    assert any(n.has_fork[p] for n in honest for p in forker_pks)
    # 3. the mid-stream checkpoint restores to an exact prefix of the live
    #    node's final state and keeps gossiping
    assert saved
    restored = load_node(
        ckpt, sk=tpu_node.sk, pk=tpu_node.pk, network=sim.network,
    )
    k = len(restored.consensus)
    assert restored.consensus == tpu_node.consensus[:k]
    peer = honest[1].pk
    got = restored.sync(peer, b"resume")
    restored.consensus_pass(got)
    mm = min(len(restored.consensus), len(honest[1].consensus))
    assert restored.consensus[:mm] == honest[1].consensus[:mm]


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_mixed_backend_heavy_faults(tmp_path):
    """Long chaos soak: two equivocators, a tpu-backend honest node, two
    partitions, two staggered crash/restart cycles, heavy loss — the
    safety and liveness invariants must hold end to end."""
    plan = FaultPlan(
        seed=11,
        default=LinkFaults(
            drop=0.25, corrupt=0.08, duplicate=0.08, reorder=0.15, delay=0.08,
        ),
        partitions=[
            Partition(start=200, end=320, group=(2, 3)),
            Partition(start=460, end=540, group=(4, 5)),
        ],
        crashes={5: [(150, 260)], 6: [(400, 520)]},
    )
    scenario = ChaosScenario(
        n_nodes=8, n_turns=700, seed=11, n_forkers=2, plan=plan,
        checkpoint_every=60, tpu_node_index=7,
    )
    sim = ChaosSimulation(scenario, str(tmp_path))
    v = sim.run()
    assert v["ok"], v
    assert v["resilience"]["crashes"] == 2
    assert v["resilience"]["restarts"] == 2
    assert v["resilience"]["forks_detected"] >= 1
    assert v["faults"]["drops"] > 0 and v["faults"]["partition_blocked"] > 0
    tpu_node = sim.nodes[7]
    assert tpu_node._tpu_engine is not None, "device engine must have run"
    assert len(tpu_node.consensus) > 0
