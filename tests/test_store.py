"""Slab store + streaming consensus driver: bit-parity, bounded resident
memory, archive checkpointing, and the widening-rebase fetch path.

The streaming driver's contract is the incremental driver's detect-or-
match contract PLUS a memory model: resident visibility state is bounded
by the undecided window (tile budget), decided rows live in the host
archive, and any ingest referencing pruned history must be answered by
re-fetching archived tiles (widening) or by the exact full-batch fallback
— never by a crash, and always bit-identical to one batch pass over the
final delivery order.
"""

import random

import numpy as np
import pytest

from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.packing import chunk_slices, pack_events, pack_node
from tpu_swirld.sim import (
    chunked_ingest_schedule,
    generate_gossip_dag,
    make_simulation,
    make_straggler_event,
    run_with_forkers,
    stream_gossip_dag,
)
from tpu_swirld.store import SlabArchive, SlabStore, StreamingConsensus
from tpu_swirld.store.slab import TileBudgetExceeded, _tiles
from tpu_swirld.tpu.pipeline import run_consensus

from tests.test_incremental import assert_same_result
from tests.test_pipeline import assert_parity


def drive(members, stake, config, chunks, **kw):
    inc = StreamingConsensus(members, stake, config, **kw)
    ordered = []
    for chunk in chunks:
        ordered.extend(inc.ingest(chunk)["ordered"])
    return inc, ordered


def random_chunks(events, seed, sizes=(1, 3, 20, 60, 150)):
    rng = random.Random(seed)
    out, i = [], 0
    while i < len(events):
        c = rng.choice(sizes)
        out.append(events[i : i + c])
        i += c
    return out


# ------------------------------------------------------------------ parity


def test_streaming_parity_oracle_small_sim():
    """Streaming vs batch vs the live oracle on a real gossip sim."""
    sim = make_simulation(5, seed=11)
    sim.run(250)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    packed = pack_node(node)
    inc, ordered = drive(
        node.members, stake, node.config, random_chunks(events, 3),
        block=64, chunk=32, window_bucket=256, prune_min=64,
        ingest_chunk=96,
    )
    res = inc.result()
    ref = run_consensus(packed, node.config, block=64)
    assert_same_result(res, ref)
    assert_parity(node, packed, res)
    assert ordered == res.order and len(res.order) > 0


def test_streaming_parity_random_chunks_with_forks():
    """Fork pairs + randomly sized ingest chunks: commit boundaries and
    the spill/prune cadence must never influence any output."""
    members, stake, events, _keys = generate_gossip_dag(
        12, 1400, seed=4, n_forkers=4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    cfg = SwirldConfig(n_members=12)
    inc, _ = drive(
        members, stake, cfg, random_chunks(events, 7, (2, 30, 90, 200)),
        chunk=64, window_bucket=512, prune_min=128, ingest_chunk=256,
    )
    assert_same_result(inc.result(), run_consensus(packed, cfg))
    assert inc.store.archive.spilled_rows > 0 or inc.pruned_prefix == 0


def test_streaming_parity_straggler_witness():
    """A forged straggler WITNESS deep below the committed frontier (the
    amnesiac/equivocating-laggard shape): the frozen-vote-horizon guard
    must route it through the exact full-batch fallback, with outputs
    bit-identical to one batch pass over the same delivery order."""
    sim = make_simulation(5, seed=23)
    sim.run(260)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    lag = sim.nodes[-1]
    strag = make_straggler_event(node, lag.pk, lag.sk, at_round=1)
    inc, _ = drive(
        node.members, stake, node.config,
        [events[i : i + 50] for i in range(0, len(events), 50)] + [[strag]],
        block=64, chunk=32, window_bucket=256, prune_min=64,
    )
    packed = pack_events(events + [strag], node.members, stake)
    assert_same_result(inc.result(), run_consensus(packed, node.config, block=64))
    assert inc.full_rebases >= 1


def test_streaming_parity_delayed_schedule():
    """Orphan-heavy delayed delivery (chunked_ingest_schedule): the
    documented fallbacks fire and outputs match a batch pass over the
    delivery order."""
    members, stake, events, _keys = generate_gossip_dag(8, 900, seed=6)
    cfg = SwirldConfig(n_members=8)
    chunks = chunked_ingest_schedule(
        events, 90, delay_prob=0.2, max_delay=4, seed=1
    )
    flat = [ev for c in chunks for ev in c]
    assert [ev.id for ev in flat] != [ev.id for ev in events]
    inc, _ = drive(
        members, stake, cfg, chunks,
        block=64, chunk=64, window_bucket=256, prune_min=64,
        ingest_chunk=128,
    )
    assert_same_result(
        inc.result(), run_consensus(pack_events(flat, members, stake), cfg)
    )


# -------------------------------------------------------- widening rebase


def test_streaming_widening_rebase_fetches_archive():
    """A stale-view sync referencing a long-pruned other-parent must be
    answered by the widening rebase — archived tiles re-fetched, NO full
    batch recompute beyond the cold start — and stay bit-identical."""
    members, stake, events, keys = generate_gossip_dag(8, 2000, seed=11)
    cfg = SwirldConfig(n_members=8)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=64, window_bucket=256, prune_min=64,
        ingest_chunk=256,
    )
    for i in range(0, len(events), 200):
        inc.ingest(events[i : i + 200])
    assert inc.pruned_prefix > 500
    pk3, sk3 = keys[3]
    head3 = [ev for ev in events if ev.c == pk3][-1]
    old0 = events[100]            # long received, long pruned
    assert 100 < inc.pruned_prefix
    strag = Event(
        d=b"stale-sync", p=(head3.id, old0.id), t=events[-1].t + 1, c=pk3
    ).signed(sk3)
    full_before = inc.full_rebases
    inc.ingest([strag])
    assert inc.widen_rebases == 1
    assert inc.full_rebases == full_before      # widening answered it
    assert inc.store.archive.fetched_rows > 0
    # a widen is the designed cheap success — it must NOT feed the
    # rebase-storm guard (which would flip to full O(N²) batch passes)
    assert inc._consec_rebases == 0 and not inc.storm_mode
    packed = pack_events(events + [strag], members, stake)
    assert_same_result(inc.result(), run_consensus(packed, cfg))


def test_streaming_widening_then_continue_and_reprune():
    """After a widening the driver must keep streaming: re-admitted rows
    re-prune (idempotent re-spill into the archive) and parity holds over
    continued traffic."""
    members, stake, events, keys = generate_gossip_dag(8, 1500, seed=3)
    cfg = SwirldConfig(n_members=8)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=64, window_bucket=256, prune_min=64,
        ingest_chunk=256,
    )
    for i in range(0, len(events), 150):
        inc.ingest(events[i : i + 150])
    pk0, sk0 = keys[0]
    head0 = [ev for ev in events if ev.c == pk0][-1]
    old = events[60]
    assert 60 < inc.pruned_prefix
    strag = Event(
        d=b"stale", p=(head0.id, old.id), t=events[-1].t + 1, c=pk0
    ).signed(sk0)
    inc.ingest([strag])
    assert inc.widen_rebases == 1
    # continued honest traffic on top of the widened window
    rng = random.Random(2)
    heads = {}
    for ev in events + [strag]:
        heads[ev.c] = ev
    extra, t = [], strag.t
    for j in range(400):
        ci = rng.randrange(8)
        pi = (ci + 1 + rng.randrange(7)) % 8
        pk, sk = keys[ci]
        t += 1
        ev = Event(
            d=b"x%d" % j,
            p=(heads[members[ci]].id, heads[members[pi]].id),
            t=t, c=pk,
        ).signed(sk)
        heads[members[ci]] = ev
        extra.append(ev)
    for i in range(0, len(extra), 150):
        inc.ingest(extra[i : i + 150])
    all_ev = events + [strag] + extra
    assert_same_result(
        inc.result(),
        run_consensus(pack_events(all_ev, members, stake), cfg),
    )
    # the window re-pruned past the widened region
    assert inc.pruned_prefix >= inc.store.archive.n_rows - 400
    assert inc.store.archive.n_rows >= inc.pruned_prefix


# ------------------------------------------------------- bounded residency


def test_resident_visibility_bounded_by_tile_budget_as_n_grows():
    """The acceptance invariant: peak resident visibility bytes scale
    with the undecided window, NOT with total event count — a fixed tile
    budget measured at N=800 admits N=3200 (strict mode: any overrun
    would raise)."""
    cfg = SwirldConfig(n_members=8)
    peaks = {}
    budget = None
    for n in (800, 1600, 3200):
        members, stake, events, _keys = generate_gossip_dag(8, n, seed=2)
        inc = StreamingConsensus(
            members, stake, cfg, chunk=64, window_bucket=256,
            prune_min=64, ingest_chunk=256,
            tile_budget=budget, tile=64,
            strict_budget=budget is not None,
        )
        for i in range(0, n, 200):
            inc.ingest(events[i : i + 200])
        peaks[n] = inc.store.peak_resident_bytes
        assert inc.pruned_prefix > n // 2, "steady state must prune"
        if budget is None:
            budget = inc.store.peak_resident_tiles   # freeze the budget
        else:
            assert inc.store.budget_overruns == 0
            assert inc.store.peak_resident_tiles <= budget
    # 4x the history, same resident footprint
    assert peaks[3200] <= peaks[800]
    # and the archive grew instead
    assert inc.store.archive.n_rows > 1600


def test_tile_accounting_and_strict_budget():
    assert _tiles((256, 256), 256) == 1
    assert _tiles((257, 256), 256) == 2
    assert _tiles((8, 256, 8), 256) == 8       # member-lead axes multiply
    store = SlabStore(budget_tiles=2, tile=256, strict=True)
    store.account("anc", (256, 256))
    assert store.resident_tiles == 1
    assert store.check({"anc": (256, 512)})    # 2 tiles: at budget
    with pytest.raises(TileBudgetExceeded):
        store.check({"anc": (512, 512)})       # 4 tiles: over
    soft = SlabStore(budget_tiles=1, tile=256, strict=False)
    soft.account("anc", (512, 512))
    assert not soft.check({})
    assert soft.budget_overruns == 1


# ------------------------------------------------------ archive mechanics


def test_archive_spill_fetch_roundtrip_exact():
    """Archived rows must equal the batch slab rows they were spilled
    from — including the reconstructed pruned-prefix columns."""
    members, stake, events, _keys = generate_gossip_dag(6, 600, seed=9)
    cfg = SwirldConfig(n_members=6)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=32, window_bucket=256, prune_min=32,
        ingest_chunk=128,
    )
    for i in range(0, len(events), 100):
        inc.ingest(events[i : i + 100])
    arch = inc.store.archive
    assert arch.n_rows > 100
    # ground truth: cold batch ancestry over the full DAG
    from tpu_swirld.tpu.pipeline import prepare_inputs, visibility_stage

    packed = pack_events(events, members, stake)
    arrays, statics, _ = prepare_inputs(packed, cfg, block=64)
    import jax.numpy as jnp

    anc, sees = visibility_stage(
        jnp.asarray(arrays["parents"]), jnp.asarray(arrays["creator"]),
        jnp.asarray(packed.fork_pairs), n_members=6, block=64,
        matmul_dtype_name=statics["matmul_dtype_name"],
    )
    anc = np.asarray(anc)
    sees_np = np.asarray(sees)
    hi = arch.n_rows
    got, got_sees = inc.store.fetch(
        0, hi, 0, hi,
        creator=np.asarray(packed.creator[:hi]),
        fork_pairs=np.asarray(packed.fork_pairs),
        n_members=6,
    )
    assert (got == anc[:hi, :hi]).all()
    assert (got_sees == sees_np[:hi, :hi]).all()


def test_archive_checkpoint_roundtrip_and_digest_tamper(tmp_path):
    from tpu_swirld.checkpoint import load_archive, save_archive

    members, stake, events, _keys = generate_gossip_dag(6, 500, seed=1)
    cfg = SwirldConfig(n_members=6)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=32, window_bucket=256, prune_min=32,
    )
    for i in range(0, len(events), 100):
        inc.ingest(events[i : i + 100])
    arch = inc.store.archive
    assert arch.n_rows > 0 and arch.retired_rounds > 0
    p = tmp_path / "arch.npz"
    save_archive(str(p), arch)
    back = load_archive(str(p))
    assert back.n_rows == arch.n_rows
    assert back.digest() == arch.digest()
    assert back.retired_rounds == arch.retired_rounds
    hi = arch.n_rows
    assert (
        back.fetch(0, hi, 0, hi) == arch.fetch(0, hi, 0, hi)
    ).all()
    # tamper: flip one byte inside one row blob -> ValueError at load
    tampered = SlabArchive()
    tampered._rows = list(arch._rows)
    blob = bytearray(tampered._rows[0])
    blob[-1] ^= 0xFF
    tampered._rows[0] = bytes(blob)
    p2 = tmp_path / "bad.npz"
    # save with the ORIGINAL digest over tampered blobs
    import numpy as _np
    import struct as _struct

    raw = b"".join(
        _struct.pack("<I", len(b)) + b for b in tampered._rows
    )
    _np.savez_compressed(
        p2, format_version=SlabArchive.FORMAT_VERSION,
        n_rows=len(tampered._rows),
        blobs=_np.frombuffer(raw, dtype=_np.uint8),
        round_meta=_np.zeros((0, 2), _np.int64),
        round_flat=_np.zeros((0,), _np.int64),
        digest=_np.frombuffer(arch.digest().encode(), dtype=_np.uint8),
    )
    with pytest.raises(ValueError, match="digest"):
        load_archive(str(p2))


def test_archive_settings_config_and_env(monkeypatch):
    """Archive knobs resolve explicit SwirldConfig field > SWIRLD_ARCHIVE_*
    env var > built-in default, and reach the SlabArchive instance."""
    from tpu_swirld.config import resolve_archive_settings

    monkeypatch.setenv("SWIRLD_ARCHIVE_COMPRESS_LEVEL", "9")
    monkeypatch.setenv("SWIRLD_ARCHIVE_QUEUE_DEPTH", "3")
    monkeypatch.setenv("SWIRLD_ARCHIVE_ASYNC", "0")
    assert resolve_archive_settings(None) == {
        "compress_level": 9, "queue_depth": 3, "async_spill": False,
    }
    for off in ("false", "False", "OFF", "no", ""):
        monkeypatch.setenv("SWIRLD_ARCHIVE_ASYNC", off)
        assert resolve_archive_settings(None)["async_spill"] is False
    monkeypatch.setenv("SWIRLD_ARCHIVE_ASYNC", "1")
    assert resolve_archive_settings(None)["async_spill"] is True
    cfg = SwirldConfig(
        n_members=4, archive_compress_level=2, archive_async=True,
    )
    s = resolve_archive_settings(cfg)
    assert s["compress_level"] == 2          # explicit field wins
    assert s["async_spill"] is True
    assert s["queue_depth"] == 3             # env fills the unset field
    arch = SlabArchive(config=cfg)
    assert arch._level == 2 and arch._async is True and arch.queue_depth == 3


def test_overlapped_vs_serial_ingest_bit_identical():
    """The background packing worker must be unobservable: async and sync
    spilling produce the identical archive blob stream (digest) and the
    drivers' outputs match bit-for-bit — across forks, random chunking,
    and a widening rebase mid-flight."""
    members, stake, events, keys = generate_gossip_dag(
        8, 1600, seed=5, n_forkers=1
    )
    pk0, sk0 = keys[0]
    head0 = [ev for ev in events if ev.c == pk0][-1]
    strag = Event(
        d=b"stale-overlap", p=(head0.id, events[80].id),
        t=events[-1].t + 1, c=pk0,
    ).signed(sk0)
    runs = {}
    for flag in (True, False):
        cfg = SwirldConfig(n_members=8, archive_async=flag)
        inc = StreamingConsensus(
            members, stake, cfg, chunk=64, window_bucket=256,
            prune_min=64, ingest_chunk=256,
        )
        for chunk in random_chunks(events, 13, (5, 40, 120, 250)):
            st = inc.ingest(chunk)
        assert "overlap_ratio" in st and "spill_queue_depth" in st
        assert 80 < inc.pruned_prefix       # the straggler ref is archived
        inc.ingest([strag])
        assert inc.widen_rebases == 1       # widening fired mid-flight
        inc.store.close()                   # flush the packing worker
        runs[flag] = inc
    a, s = runs[True], runs[False]
    assert_same_result(a.result(), s.result())
    assert a.store.archive.n_rows == s.store.archive.n_rows
    assert a.store.archive.digest() == s.store.archive.digest()
    assert_same_result(
        a.result(),
        run_consensus(pack_events(events + [strag], members, stake),
                      SwirldConfig(n_members=8)),
    )


def test_checkpoint_with_nonempty_spill_queue_drains(tmp_path):
    """Drain-barrier regression: a checkpoint taken while spill batches
    are still queued behind a stalled worker must persist every accepted
    row — and the blob stream must equal a synchronous spiller's."""
    import threading

    from tpu_swirld.checkpoint import load_archive, save_archive

    rng = np.random.default_rng(0)
    rows = np.tril(rng.random((64, 64)) < 0.3)
    sync = SlabArchive(async_spill=False)
    sync.spill_full(0, rows)

    arch = SlabArchive(async_spill=True, queue_depth=8)
    gate = threading.Event()
    orig = arch._pack_full_rows

    def gated(start, r):
        gate.wait(10)
        orig(start, r)

    arch._pack_full_rows = gated
    for s in range(0, 64, 16):
        arch.spill_full(s, rows[s : s + 16])
    assert arch.n_rows == 64                # accepted, not yet packed
    assert arch.pending_batches >= 1        # queue genuinely non-empty
    assert arch.committed_rows < arch.n_rows
    threading.Timer(0.2, gate.set).start()
    p = tmp_path / "arch.npz"
    save_archive(str(p), arch)              # the drain barrier waits here
    assert arch.committed_rows == 64
    back = load_archive(str(p))
    assert back.n_rows == 64
    assert back.digest() == sync.digest()   # byte-identical blob stream
    arch.close()


def test_stream_gossip_dag_matches_batch_generator():
    """The streaming generator must produce the identical event stream to
    generate_gossip_dag (same seed), in bounded chunks."""
    members_b, stake_b, events_b, _ = generate_gossip_dag(
        6, 500, seed=8, n_forkers=2
    )
    members_s, stake_s, _keys, chunks = stream_gossip_dag(
        6, 500, 64, seed=8, n_forkers=2
    )
    assert members_s == members_b and stake_s == stake_b
    flat = [ev for c in chunks for ev in c]
    assert [ev.id for ev in flat] == [ev.id for ev in events_b]
    assert chunk_slices(10, 4) == [(0, 4), (4, 8), (8, 10)]


# -------------------------------------------------------- mesh + chaos


def test_streaming_mesh_parity():
    """Tile sharding over the mesh: the streaming driver with the
    member-sharded strongly-sees column kernel stays bit-identical."""
    import jax

    from tpu_swirld.parallel import make_mesh, streaming_consensus_for_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 (virtual) devices")
    members, stake, events, _keys = generate_gossip_dag(10, 700, seed=5)
    cfg = SwirldConfig(n_members=10)
    mesh = make_mesh(4)
    inc = streaming_consensus_for_mesh(
        mesh, members, stake, cfg, chunk=64, window_bucket=256,
        prune_min=64, ingest_chunk=256,
    )
    for i in range(0, len(events), 150):
        inc.ingest(events[i : i + 150])
    packed = pack_events(events, members, stake)
    assert_same_result(inc.result(), run_consensus(packed, cfg))


@pytest.mark.chaos
def test_engines_agree_streaming_on_forked_history():
    """The chaos harness's cross-engine parity probe with the streaming
    driver (scripts/chaos_run.py --engine streaming rides this path)."""
    from tpu_swirld.chaos import _engines_agree

    sim = run_with_forkers(n_nodes=6, n_forkers=1, n_turns=220, seed=13)
    node = sim.nodes[0]
    out = _engines_agree(node, engine="streaming")
    assert out["engine"] == "streaming"
    assert out["batch_oracle_parity"] and out["incremental_batch_parity"]
    assert "store" in out


# ---------------------------------------------------- config-5 scaling


@pytest.mark.slow
def test_config5_proxy_streaming_end_to_end():
    """Config-5 proxy (256 members x ~8k events): the streaming driver
    completes under a fixed tile budget with the decided prefix
    bit-identical to the oracle on a subsampled parity check."""
    from tpu_swirld.oracle.node import Node

    n_events, n_oracle = 8000, 3000
    members, stake, keys, chunks = stream_gossip_dag(
        256, n_events, 2048, seed=1
    )
    cfg = SwirldConfig(n_members=256)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=256, window_bucket=1024,
        prune_min=512, ingest_chunk=2048,
        tile_budget=32768, tile=256, strict_budget=True,
    )
    oracle_buf, n_done = [], 0
    for chunk in chunks:
        if n_done < n_oracle:
            oracle_buf.extend(chunk[: n_oracle - n_done])
        inc.ingest(chunk)
        n_done += len(chunk)
    res = inc.result()
    assert inc.store.budget_overruns == 0
    assert inc.store.peak_resident_tiles <= 32768
    oracle = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in oracle_buf if oracle.add_event(ev)]
    oracle.consensus_pass(new_ids)
    # 256-member ordering starts ~10-12k events in, so at proxy scale the
    # decided prefix may be empty — the rounds loop below is the
    # substantive parity check here; the bigmem full-scale test pins a
    # NON-vacuous decided-prefix order parity (oracle 12k, decided > 0)
    got = [
        inc.packer.event_id(i)
        for i in res.order[: len(oracle.consensus)]
    ]
    assert got == oracle.consensus
    for i, eid in enumerate(oracle.order_added):
        assert int(res.round[i]) == oracle.round[eid]


@pytest.mark.bigmem
@pytest.mark.slow
def test_config5_full_scale_streaming():
    """The real thing — 256 members / 100k events under a fixed budget
    (multi-GB RSS, ~10+ min: bigmem, RUN_BIGMEM=1 to enable).  Asserts
    completion, budget, pruning, and oracle-prefix parity; this is the
    test twin of ``python bench.py --stream``."""
    from tpu_swirld.oracle.node import Node

    n_events, n_oracle = 100_000, 12_000
    members, stake, keys, chunks = stream_gossip_dag(
        256, n_events, 2048, seed=1
    )
    cfg = SwirldConfig(n_members=256)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=256, window_bucket=2048,
        prune_min=1024, ingest_chunk=2048,
        tile_budget=65536, tile=256, strict_budget=True,
    )
    oracle_buf, n_done = [], 0
    for chunk in chunks:
        if n_done < n_oracle:
            oracle_buf.extend(chunk[: n_oracle - n_done])
        inc.ingest(chunk)
        n_done += len(chunk)
    assert n_done == n_events
    assert inc.store.budget_overruns == 0
    assert inc.pruned_prefix > n_events // 2, "must prune at scale"
    res = inc.result()
    oracle = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in oracle_buf if oracle.add_event(ev)]
    oracle.consensus_pass(new_ids)
    assert len(oracle.consensus) > 0, "parity check must be non-vacuous"
    got = [
        inc.packer.event_id(i)
        for i in res.order[: len(oracle.consensus)]
    ]
    assert got == oracle.consensus
    for i, eid in enumerate(oracle.order_added):
        assert int(res.round[i]) == oracle.round[eid]
