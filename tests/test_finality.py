"""Finality observatory: cross-engine latency parity, flight-recorder
determinism, forced post-mortems, and exporter golden formats.

``rounds_to_decision = round_received - round`` is a pure function of
the DAG, so every engine — the Python oracle, the batch device pass,
``IncrementalConsensus``, and ``StreamingConsensus`` — must report the
bit-identical sequence for the same history even though their wall-clock
``time_to_finality`` differs.  The flight recorder is a determinism
surface too: the same scenario + seed must produce byte-identical
post-mortem dumps (``wall_time_s`` stays ``None`` in sims).
"""

import json
import os

import pytest

from tpu_swirld.config import SwirldConfig
from tpu_swirld.obs.finality import FinalityTracker, record_batch_result
from tpu_swirld.obs.flightrec import FlightRecorder, load_dump
from tpu_swirld.obs.registry import Registry
from tpu_swirld.oracle.node import Node
from tpu_swirld.packing import pack_events
from tpu_swirld.sim import generate_gossip_dag
from tpu_swirld.store import StreamingConsensus
from tpu_swirld.tpu.pipeline import IncrementalConsensus, run_consensus


# --------------------------------------------------- cross-engine parity


def _rtd_all_engines(n_members, n_events, seed, n_forkers):
    """Drive the same generated DAG through all four engines and return
    {engine: rtd list} (each in that engine's decided order)."""
    members, stake, events, keys = generate_gossip_dag(
        n_members, n_events, seed=seed, n_forkers=n_forkers
    )
    cfg = SwirldConfig(n_members=n_members)

    # oracle observer: logical clock pinned at 0; birth stamps are the
    # events' own t ticks, so the negative-TTF guard drops every TTF
    # sample and only the pure-DAG rtd sequence records
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False, config=cfg,
    )
    node.finality = FinalityTracker("oracle", clock=lambda: 0)
    new_ids = [ev.id for ev in events if node.add_event(ev)]
    node.consensus_pass(new_ids)

    packed = pack_events(events, members, stake)
    res = run_consensus(packed, cfg, block=64)
    fin_batch = FinalityTracker("batch")
    record_batch_result(fin_batch, res)

    inc = IncrementalConsensus(
        members, stake, cfg, block=64, chunk=64, window_bucket=512,
        prune_min=128,
    )
    inc.finality = FinalityTracker("incremental")
    for i in range(0, len(events), 100):
        inc.ingest(events[i : i + 100])

    st = StreamingConsensus(
        members, stake, cfg, block=64, chunk=64, window_bucket=512,
        prune_min=128, ingest_chunk=128,
    )
    st.finality = FinalityTracker("streaming")
    for i in range(0, len(events), 100):
        st.ingest(events[i : i + 100])

    return {
        "oracle": node.finality,
        "batch": fin_batch,
        "incremental": inc.finality,
        "streaming": st.finality,
    }


@pytest.mark.parametrize(
    "shape",
    [
        pytest.param((8, 400, 3, 2), id="8m-400ev-2forkers"),
        pytest.param((6, 300, 5, 0), id="6m-300ev-honest"),
    ],
)
def test_rounds_to_decision_bit_identical_across_engines(shape):
    """The latency-parity contract: identical rtd sequences everywhere.

    Uses the exact sample lists (not summaries), so a single transposed
    or off-by-one decision anywhere fails loudly."""
    trackers = _rtd_all_engines(*shape)
    ref = trackers["oracle"].rtd
    assert len(ref) > 0, "corpus must decide events or the test is vacuous"
    for engine in ("batch", "incremental", "streaming"):
        assert trackers[engine].rtd == ref, (
            f"{engine} rtd diverges from oracle"
        )
    # the summary digests agree too (same samples -> same stats)
    s_ref = trackers["oracle"].summary()
    for engine in ("batch", "incremental", "streaming"):
        s = trackers[engine].summary()
        assert s["decided"] == s_ref["decided"]
        for k in ("rtd_mean", "rtd_p50", "rtd_p99", "rtd_max"):
            assert s[k] == s_ref[k], f"{engine} {k} != oracle"


def test_summary_distribution_fields():
    fin = FinalityTracker("batch")
    for rtd in (1, 2, 2, 3):
        fin.record_decided(rtd, 0, rtd)
    s = fin.summary()
    assert s["decided"] == 4
    assert s["rtd_mean"] == 2.0
    assert s["rtd_p50"] == 2
    assert s["rtd_max"] == 3
    assert s["undecided"] == 0


def test_negative_ttf_guard_drops_cross_domain_samples():
    """A logical-tick birth meeting a wall-clock 'now' must not poison
    the TTF histogram with negative latencies."""
    fin = FinalityTracker("oracle", clock=lambda: 0.0)
    fin.record_decided(b"e", 1, 2, birth=57.0)   # decided "before" born
    assert fin.rtd == [1]
    assert fin.ttf == []
    fin.record_decided(b"f", 1, 3, birth=0.0, now=2.5)
    assert fin.ttf == [2.5]


# ------------------------------------------- flight-recorder determinism


def _failing_scenario():
    """A run that cannot satisfy liveness: every link drops everything,
    and a partition window adds breaker churn for extra ring traffic."""
    from tpu_swirld.chaos import ChaosScenario
    from tpu_swirld.transport import FaultPlan, LinkFaults, Partition

    return ChaosScenario(
        n_nodes=4, n_turns=10, seed=0, checkpoint_every=5,
        plan=FaultPlan(
            default=LinkFaults(drop=1.0),
            partitions=[Partition(start=1, end=8, group=(0, 1))],
        ),
    )


def _run_failing(tmp_dir):
    from tpu_swirld.chaos import ChaosSimulation

    rec = FlightRecorder(dump_dir=tmp_dir)
    sim = ChaosSimulation(
        _failing_scenario(), os.path.join(tmp_dir, "ckpt"), flightrec=rec,
    )
    verdict = sim.run()
    return sim, rec, verdict


def test_forced_failure_writes_loadable_dump_matching_frontier(tmp_path):
    """The acceptance criterion: a verdict failure dumps a post-mortem
    whose decided frontier matches the live nodes' state exactly."""
    sim, rec, verdict = _run_failing(str(tmp_path))
    assert not verdict["ok"]
    path = verdict["flightrec_dump"]
    assert path is not None and os.path.exists(path)
    doc = load_dump(path)
    assert doc["reason"] == "verdict_failed"
    frontier = doc["decided_frontier"]
    for i, node in sorted(sim.nodes.items()):
        if node is None:
            continue
        row = frontier[f"n{i}"]
        assert row["decided"] == len(node.consensus)
        assert row["consensus_round"] == node.consensus_round
        assert row["events"] == len(node.hg)
    # every node's ring contributed records to the snapshot
    assert set(doc["rings"]) >= {
        f"n{i}" for i, n in sim.nodes.items() if n is not None
    }


def test_flightrec_dumps_byte_identical_across_reruns(tmp_path):
    """Same scenario + same seed + fresh recorders -> byte-identical
    dump files (names and contents; ``wall_time_s`` is None in sims)."""
    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    os.makedirs(dir_a)
    os.makedirs(dir_b)
    _run_failing(dir_a)
    _run_failing(dir_b)
    names_a = sorted(
        f for f in os.listdir(dir_a) if f.startswith("flightrec_")
    )
    names_b = sorted(
        f for f in os.listdir(dir_b) if f.startswith("flightrec_")
    )
    assert names_a == names_b and len(names_a) > 0
    for name in names_a:
        with open(os.path.join(dir_a, name), "rb") as f:
            blob_a = f.read()
        with open(os.path.join(dir_b, name), "rb") as f:
            blob_b = f.read()
        assert blob_a == blob_b, f"{name} differs across identical reruns"
        assert json.loads(blob_a)["wall_time_s"] is None


def test_green_verdict_carries_null_dump_key(tmp_path):
    """Every chaos verdict exposes ``flightrec_dump`` — None on success —
    so downstream tooling never KeyErrors on the happy path."""
    from tpu_swirld.chaos import ChaosScenario, ChaosSimulation

    sim = ChaosSimulation(
        ChaosScenario(n_nodes=4, n_turns=60, seed=1, checkpoint_every=30),
        str(tmp_path / "ckpt"),
        flightrec=FlightRecorder(dump_dir=str(tmp_path)),
    )
    verdict = sim.run()
    assert verdict["ok"]
    assert verdict["flightrec_dump"] is None
    assert not [
        f for f in os.listdir(tmp_path) if f.startswith("flightrec_")
    ]


def test_trigger_without_dump_dir_records_in_memory_only():
    rec = FlightRecorder(dump_dir=None)
    assert rec.trigger("rebase_storm", node="s", detail={"x": 1}) is None
    assert rec.trigger_counts["rebase_storm"] == 1


def test_load_dump_rejects_foreign_json(tmp_path):
    p = tmp_path / "not_a_dump.json"
    p.write_text('{"schema": "something-else/9"}')
    with pytest.raises(ValueError):
        load_dump(str(p))


# --------------------------------------------- exporter golden formats


def test_prometheus_histogram_exposition_golden():
    """Scrape-valid histogram rendering: cumulative ``_bucket`` lines
    with ``le`` upper bounds, the implicit ``+Inf`` bucket, and the
    ``_sum`` / ``_count`` pair — pinned byte-for-byte."""
    reg = Registry()
    h = reg.histogram("lat_seconds", {"stage": "x"}, buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert reg.to_prometheus_text() == (
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{stage="x",le="0.1"} 1\n'
        'lat_seconds_bucket{stage="x",le="1.0"} 2\n'
        'lat_seconds_bucket{stage="x",le="+Inf"} 3\n'
        'lat_seconds_sum{stage="x"} 5.55\n'
        'lat_seconds_count{stage="x"} 3\n'
    )


def test_prometheus_label_escaping_keeps_one_sample_per_line():
    """Backslash, quote, and NEWLINE must all escape — a raw newline in
    a label value would split the sample line and break the scrape."""
    reg = Registry()
    reg.gauge("g", {"msg": 'a"b\\c\nd'}).set(1)
    text = reg.to_prometheus_text()
    lines = text.splitlines()
    assert len(lines) == 2                      # TYPE header + one sample
    assert lines[1] == 'g{msg="a\\"b\\\\c\\nd"} 1'


def test_finality_histograms_land_in_registry():
    reg = Registry()
    fin = FinalityTracker("streaming", clock=lambda: 7.0, registry=reg)
    fin.record_decided(0, 2, 4, birth=3.0, phase="window")
    fin.set_watermark("s0", 1, 3)
    assert reg.value("finality_rounds_to_decision",
                     {"engine": "streaming"}) == 1
    assert reg.value("finality_time_to_finality",
                     {"engine": "streaming", "phase": "window"}) == 1
    assert reg.value("finality_decided_watermark", {"node": "s0"}) == 1


# -------------------------------------------------- bench_compare gating


def test_bench_compare_gates_finality_latency_lower_is_better():
    import scripts.bench_compare as bc

    old = {"value": 100.0,
           "finality": {"incremental": {"ttf_p99": 1.0, "rtd_mean": 2.0}}}
    worse = {"value": 100.0,
             "finality": {"incremental": {"ttf_p99": 1.25, "rtd_mean": 2.0}}}
    failures, _ = bc.compare(old, worse, "value", 0.10)
    assert any("finality.incremental.ttf_p99" in f for f in failures)
    failures, _ = bc.compare(old, old, "value", 0.10)
    assert failures == []


# ----------------------------------------------------- lint-scope pinning


@pytest.mark.parametrize("module", ["obs/finality.py", "obs/flightrec.py"])
def test_sw002_scope_covers_obs_modules(module):
    """The new obs modules iterate consensus-adjacent state; the
    unordered-iteration rule must apply to them."""
    from tpu_swirld.analysis import check_source

    bad = 's = {b"a", b"b"}\nfor x in s:\n    pass\n'
    findings = check_source(bad, module_path=module)
    assert "SW002" in [f.rule for f in findings]


@pytest.mark.parametrize("module", ["obs/finality.py", "obs/flightrec.py"])
def test_sw003_scope_covers_obs_modules(module):
    """Clock discipline: the trackers/recorder take injected clocks and
    must never read wall time themselves (byte-stable sim dumps)."""
    from tpu_swirld.analysis import check_source

    bad = "import time\n\ndef f():\n    return time.time()\n"
    findings = check_source(bad, module_path=module)
    assert "SW003" in [f.rule for f in findings]
