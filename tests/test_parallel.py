"""Sharded pipeline: member-axis SPMD must be bit-identical to the oracle."""

import jax
import pytest

from tpu_swirld.packing import pack_node
from tpu_swirld.parallel import make_mesh
from tpu_swirld.sim import make_simulation
from tpu_swirld.tpu.pipeline import run_consensus

from tests.test_pipeline import assert_parity


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_parity_8_members_8_devices():
    sim = make_simulation(8, seed=21)
    sim.run(400)
    node = sim.nodes[0]
    packed = pack_node(node)
    mesh = make_mesh(8)
    result = run_consensus(packed, node.config, block=64, mesh=mesh)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 virtual devices")
def test_sharded_parity_member_padding():
    """6 members on a 4-device mesh: the member axis must be padded."""
    sim = make_simulation(6, seed=13)
    sim.run(300)
    node = sim.nodes[2]
    packed = pack_node(node)
    mesh = make_mesh(4)
    result = run_consensus(packed, node.config, block=64, mesh=mesh)
    assert_parity(node, packed, result)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_matches_unsharded():
    sim = make_simulation(5, seed=31)
    sim.run(250)
    node = sim.nodes[1]
    packed = pack_node(node)
    a = run_consensus(packed, node.config, block=64)
    b = run_consensus(packed, node.config, block=64, mesh=make_mesh(8))
    assert (a.round == b.round).all()
    assert (a.is_witness == b.is_witness).all()
    assert a.famous == b.famous
    assert a.order == b.order


@pytest.mark.slow
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_config5_shape_256_members_sharded():
    """BASELINE config 5 shape (256 members, member-sharded) at reduced
    event count: sharded(8) == unsharded, and ordering is live.  Full
    100k-event scale additionally needs event-axis blocking (roadmap)."""
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(256, 3000, seed=6)
    packed = pack_events(events, members, stake)
    a = run_consensus(packed, ssm_mode="full")
    b = run_consensus(packed, mesh=make_mesh(8), ssm_mode="full")
    assert (a.round == b.round).all()
    assert a.famous == b.famous
    assert a.order == b.order


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 virtual devices")
def test_incremental_with_mesh_cols_parity():
    """IncrementalConsensus with the member-sharded strongly-sees block
    kernel (shard_map + psum): bit-parity with full recompute, including
    a member count that needs mesh padding (6 members on 4 devices)."""
    from tpu_swirld.parallel import make_ssm_block_fn_for_mesh
    from tpu_swirld.tpu.pipeline import IncrementalConsensus

    sim = make_simulation(6, seed=19)
    sim.run(300)
    node = sim.nodes[0]
    packed = pack_node(node)
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    inc = IncrementalConsensus(
        node.members, stake, node.config, block=64, chunk=64,
        window_bucket=256, prune_min=64,
        ssm_block_fn=make_ssm_block_fn_for_mesh(make_mesh(4)),
    )
    for i in range(0, len(events), 80):
        inc.ingest(events[i : i + 80])
    res = inc.result()
    ref = run_consensus(packed, node.config, block=64)
    assert res.order == ref.order
    assert res.famous == ref.famous
    assert (res.round == ref.round).all()
    assert (res.round_received == ref.round_received).all()
