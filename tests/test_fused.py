"""Fused rounds-span dispatch + decode overlap: exactness pins.

The fused scan (``pipeline.rounds_span_stage``) batches K packed chunks
per dispatch behind a fixpoint witness-column probe, and the streaming
driver's decode worker pre-hashes the next delta's event ids off-thread
behind a drain barrier.  Both are pure latency plays: every output must
be bit-identical to the unfused, synchronous path over ANY chunking,
fork pattern, rebase, or ragged span tail — commit boundaries and
thread scheduling never influence consensus outputs.
"""

import random

import pytest

from tpu_swirld.config import SwirldConfig, resolve_stream_settings
from tpu_swirld.oracle.event import Event
from tpu_swirld.packing import pack_events
from tpu_swirld.sim import generate_gossip_dag, make_simulation, \
    make_straggler_event
from tpu_swirld.store import StreamingConsensus
from tpu_swirld.tpu.pipeline import run_consensus

from tests.test_incremental import assert_same_result


def drive(members, stake, config, chunks, **kw):
    inc = StreamingConsensus(members, stake, config, **kw)
    for chunk in chunks:
        inc.ingest(chunk)
    return inc


def random_chunks(events, seed, sizes=(2, 30, 90, 200)):
    rng = random.Random(seed)
    out, i = [], 0
    while i < len(events):
        c = rng.choice(sizes)
        out.append(events[i : i + c])
        i += c
    return out


# ------------------------------------------------------ fused == unfused


@pytest.mark.parametrize("fuse", [3, 8])
def test_fused_vs_unfused_random_chunks_with_forks(fuse):
    """Fused span dispatch vs the per-chunk loop vs one batch pass over
    forked history with randomly sized ingest chunks: bit-identical.
    fuse=3 keeps the span tail ragged (n_chunks % 3 != 0 for most
    deltas), fuse=8 is the shipped default."""
    members, stake, events, _keys = generate_gossip_dag(
        12, 1400, seed=4, n_forkers=4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    cfg = SwirldConfig(n_members=12)
    chunks = random_chunks(events, 7)
    unfused = drive(
        members, stake, cfg, chunks,
        chunk=64, window_bucket=512, prune_min=128, ingest_chunk=256,
        fuse_chunks=1,
    )
    fused = drive(
        members, stake, cfg, chunks,
        chunk=64, window_bucket=512, prune_min=128, ingest_chunk=256,
        fuse_chunks=fuse,
    )
    assert fused._fuse == fuse and unfused._fuse == 1
    assert_same_result(fused.result(), unfused.result())
    assert_same_result(fused.result(), run_consensus(packed, cfg))


def test_fused_ragged_span_tail():
    """ingest_chunk = 5 scan chunks with fuse_chunks = 4: every delta
    dispatches one full span (k=4) plus a ragged tail span (k=1), each
    with its own static trip count — outputs identical to unfused."""
    members, stake, events, _keys = generate_gossip_dag(8, 1000, seed=9)
    cfg = SwirldConfig(n_members=8)
    chunks = [events[i : i + 320] for i in range(0, len(events), 320)]
    fused = drive(
        members, stake, cfg, chunks,
        chunk=64, window_bucket=512, prune_min=128, ingest_chunk=320,
        fuse_chunks=4,
    )
    unfused = drive(
        members, stake, cfg, chunks,
        chunk=64, window_bucket=512, prune_min=128, ingest_chunk=320,
        fuse_chunks=1,
    )
    assert_same_result(fused.result(), unfused.result())
    assert_same_result(
        fused.result(),
        run_consensus(pack_events(events, members, stake), cfg),
    )


def test_fused_widening_rebase_mid_stream():
    """A stale-view sync referencing long-pruned history while fusion is
    on: the widening rebase re-fetches archived tiles and the fused
    re-extension over the widened window stays bit-identical."""
    members, stake, events, keys = generate_gossip_dag(8, 2000, seed=11)
    cfg = SwirldConfig(n_members=8)
    inc = StreamingConsensus(
        members, stake, cfg, chunk=64, window_bucket=256, prune_min=64,
        ingest_chunk=256, fuse_chunks=4,
    )
    for i in range(0, len(events), 200):
        inc.ingest(events[i : i + 200])
    assert inc.pruned_prefix > 500
    pk3, sk3 = keys[3]
    head3 = [ev for ev in events if ev.c == pk3][-1]
    old0 = events[100]            # long received, long pruned
    strag = Event(
        d=b"stale-sync", p=(head3.id, old0.id), t=events[-1].t + 1, c=pk3
    ).signed(sk3)
    inc.ingest([strag])
    assert inc.widen_rebases == 1
    packed = pack_events(events + [strag], members, stake)
    assert_same_result(inc.result(), run_consensus(packed, cfg))


def test_fused_full_rebase_straggler_witness():
    """A forged straggler witness below the frozen vote horizon routes
    through the exact full-batch fallback with fusion on."""
    sim = make_simulation(5, seed=23)
    sim.run(260)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    lag = sim.nodes[-1]
    strag = make_straggler_event(node, lag.pk, lag.sk, at_round=1)
    inc = drive(
        node.members, stake, node.config,
        [events[i : i + 50] for i in range(0, len(events), 50)] + [[strag]],
        block=64, chunk=32, window_bucket=256, prune_min=64,
        fuse_chunks=8,
    )
    assert inc.full_rebases >= 1
    packed = pack_events(events + [strag], node.members, stake)
    assert_same_result(
        inc.result(), run_consensus(packed, node.config, block=64)
    )


# -------------------------------------------------- decode overlap parity


def test_async_decode_equals_sync_decode_digest():
    """Worker-thread pre-decode vs synchronous decode: identical
    consensus outputs AND identical archive blob digests (the spill
    stream is a function of consensus state only, so thread scheduling
    must not reorder or alter a single blob)."""
    members, stake, events, _keys = generate_gossip_dag(
        10, 1200, seed=2, n_forkers=2
    )
    chunks = random_chunks(events, 5)
    kw = dict(chunk=64, window_bucket=512, prune_min=128, ingest_chunk=128)
    a = drive(
        members, stake,
        SwirldConfig(n_members=10, decode_overlap=True), chunks, **kw
    )
    b = drive(
        members, stake,
        SwirldConfig(n_members=10, decode_overlap=False), chunks, **kw
    )
    assert a.decoded_off_thread > 0       # the worker actually decoded
    assert b.decoded_off_thread == 0
    assert_same_result(a.result(), b.result())
    a.store.close()
    b.store.close()
    assert a.store.archive.digest() == b.store.archive.digest()


class _PoisonEvent:
    """Stand-in whose id computation fails on the decode worker."""

    @property
    def id(self):
        raise RuntimeError("poison id")


def test_decode_worker_failure_reraised_at_barrier():
    """A failure inside the worker's prepare_events surfaces on the
    ingest thread at the drain barrier (future.result()), not as a
    swallowed exception or a hang."""
    members, stake, events, _keys = generate_gossip_dag(8, 400, seed=6)
    inc = StreamingConsensus(
        members, stake,
        SwirldConfig(n_members=8, decode_overlap=True, decode_queue_depth=2),
        chunk=64, window_bucket=256, prune_min=64, ingest_chunk=64,
    )
    poisoned = events[:128] + [_PoisonEvent()]
    with pytest.raises(RuntimeError, match="poison id"):
        inc.ingest(poisoned)


# ------------------------------------------------------- knob resolution


def test_resolve_stream_settings_precedence(monkeypatch):
    """fuse/decode knobs resolve field > env > default, and the ctor
    kwarg wins over the config field for fuse_chunks."""
    monkeypatch.delenv("SWIRLD_FUSE_CHUNKS", raising=False)
    monkeypatch.delenv("SWIRLD_DECODE_OVERLAP", raising=False)
    monkeypatch.delenv("SWIRLD_DECODE_QUEUE_DEPTH", raising=False)
    s = resolve_stream_settings(SwirldConfig(n_members=4))
    assert s == {
        "fuse_chunks": 8, "decode_overlap": True, "decode_queue_depth": 2,
    }
    monkeypatch.setenv("SWIRLD_FUSE_CHUNKS", "3")
    monkeypatch.setenv("SWIRLD_DECODE_OVERLAP", "0")
    s = resolve_stream_settings(SwirldConfig(n_members=4))
    assert s["fuse_chunks"] == 3 and s["decode_overlap"] is False
    cfg = SwirldConfig(n_members=4, fuse_chunks=5, decode_overlap=True)
    s = resolve_stream_settings(cfg)
    assert s["fuse_chunks"] == 5 and s["decode_overlap"] is True
    members, stake, _events, _keys = generate_gossip_dag(4, 8, seed=1)
    inc = StreamingConsensus(members, stake, cfg, fuse_chunks=2)
    assert inc._fuse == 2                 # explicit kwarg beats the field
