"""Byzantine fork handling: detection, fork-aware visibility, liveness."""

from tpu_swirld.oracle.event import Event
from tpu_swirld.sim import make_simulation, run_with_forkers


def make_fork(node, other_pk):
    """Create a sibling of node's head (same self-parent) — a fork pair."""
    head_ev = node.hg[node.head]
    sibling = Event(
        d=b"forked",
        p=(head_ev.self_parent, node.member_events[other_pk][-1]),
        t=head_ev.t + 1,
        c=node.pk,
    ).signed(node.sk)
    return sibling


def test_fork_pair_detected():
    sim = make_simulation(4, seed=5)
    sim.run(40)
    forker = sim.nodes[0]
    honest = sim.nodes[1]
    sibling = make_fork(forker, honest.pk)
    forker.add_event(sibling)
    assert forker.has_fork[forker.pk]
    seqs = list(forker.fork_groups[forker.pk])
    assert len(seqs) == 1
    assert len(forker.fork_groups[forker.pk][seqs[0]]) == 2


def test_forkseen_blocks_seeing():
    sim = make_simulation(4, seed=5)
    sim.run(40)
    forker, honest = sim.nodes[0], sim.nodes[1]
    sibling = make_fork(forker, honest.pk)
    forker.add_event(sibling)
    forker.divide_rounds([sibling.id])
    # an event on top of both branches has fork-seen the forker
    top = Event(
        d=b"", p=(forker.head, forker.member_events[honest.pk][-1]), t=10**6,
        c=forker.pk,
    )
    # build the descendant via honest machinery on the forker node itself:
    # its head and the sibling are both ancestors of nothing yet, so link
    # them through a fresh event seeing both branches.
    a, b = forker.fork_groups[forker.pk][
        list(forker.fork_groups[forker.pk])[0]
    ]
    # the forker's own later head (child of one branch) doesn't yet see both
    assert forker.forkseen(forker.head, forker.pk) or True  # may be False
    # but any event whose ancestors include both branches fork-sees:
    merged_mask_holder = None
    for eid in forker.order_added:
        if forker.in_anc(eid, a) and forker.in_anc(eid, b):
            merged_mask_holder = eid
            break
    if merged_mask_holder is not None:
        assert forker.forkseen(merged_mask_holder, forker.pk)
        assert not forker.sees(merged_mask_holder, a)


def test_sim_with_forkers_stays_consistent():
    # BFT bound: supermajorities need n > 3f (7 > 3*2); once a member's
    # fork is visible its events cannot be strongly seen, so with f too
    # large rounds would (correctly) stop advancing.
    sim = run_with_forkers(n_nodes=7, n_forkers=2, n_turns=700, seed=9)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0, "consensus must stay live under forking members"
    assert all(o[:m] == orders[0][:m] for o in orders)
    # at least one honest node observed a fork
    assert any(
        any(n.has_fork[mpk] for mpk in sim.members) for n in sim.nodes
    )
