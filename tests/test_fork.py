"""Byzantine fork handling: detection, fork-aware visibility, liveness."""

from tpu_swirld.oracle.event import Event
from tpu_swirld.sim import make_simulation, run_with_forkers


def make_fork(node, other_pk):
    """Create a sibling of node's head (same self-parent) — a fork pair."""
    head_ev = node.hg[node.head]
    sibling = Event(
        d=b"forked",
        p=(head_ev.self_parent, node.member_events[other_pk][-1]),
        t=head_ev.t + 1,
        c=node.pk,
    ).signed(node.sk)
    return sibling


def test_fork_pair_detected():
    sim = make_simulation(4, seed=5)
    sim.run(40)
    forker = sim.nodes[0]
    honest = sim.nodes[1]
    sibling = make_fork(forker, honest.pk)
    forker.add_event(sibling)
    assert forker.has_fork[forker.pk]
    seqs = list(forker.fork_groups[forker.pk])
    assert len(seqs) == 1
    assert len(forker.fork_groups[forker.pk][seqs[0]]) == 2


def test_forkseen_blocks_seeing():
    sim = make_simulation(4, seed=5)
    sim.run(40)
    forker, honest = sim.nodes[0], sim.nodes[1]
    sibling = make_fork(forker, honest.pk)
    forker.add_event(sibling)
    forker.divide_rounds([sibling.id])
    # an event on top of both branches has fork-seen the forker
    top = Event(
        d=b"", p=(forker.head, forker.member_events[honest.pk][-1]), t=10**6,
        c=forker.pk,
    )
    # build the descendant via honest machinery on the forker node itself:
    # its head and the sibling are both ancestors of nothing yet, so link
    # them through a fresh event seeing both branches.
    a, b = forker.fork_groups[forker.pk][
        list(forker.fork_groups[forker.pk])[0]
    ]
    # the forker's own later head (child of one branch) doesn't yet see both
    assert forker.forkseen(forker.head, forker.pk) or True  # may be False
    # but any event whose ancestors include both branches fork-sees:
    merged_mask_holder = None
    for eid in forker.order_added:
        if forker.in_anc(eid, a) and forker.in_anc(eid, b):
            merged_mask_holder = eid
            break
    if merged_mask_holder is not None:
        assert forker.forkseen(merged_mask_holder, forker.pk)
        assert not forker.sees(merged_mask_holder, a)


def test_sim_with_forkers_stays_consistent():
    # BFT bound: supermajorities need n > 3f (7 > 3*2); once a member's
    # fork is visible its events cannot be strongly seen, so with f too
    # large rounds would (correctly) stop advancing.
    sim = run_with_forkers(n_nodes=7, n_forkers=2, n_turns=700, seed=9)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0, "consensus must stay live under forking members"
    assert all(o[:m] == orders[0][:m] for o in orders)
    # at least one honest node observed a fork
    assert any(
        any(n.has_fork[mpk] for mpk in sim.members) for n in sim.nodes
    )


def _manual_population(n=4, seed=77):
    """Keys + one observer Node (last member) that we feed hand-built events."""
    from tpu_swirld import crypto
    from tpu_swirld.oracle.node import Node

    keys = [crypto.keypair(b"ez-%d-%d" % (seed, i)) for i in range(n)]
    members = [pk for pk, _ in keys]
    node = Node(
        sk=keys[-1][1], pk=members[-1], network={}, members=members,
        clock=lambda: 0,
    )
    return keys, members, node


def test_strongly_sees_exists_z_rule_on_fork_dag():
    """Pins the normative ∃-z strongly-see rule on a hand-built fork DAG.

    Member B's *tip* (b3) has fork-seen A (both branches of A's fork are
    among its ancestors), so a tip-only rule would not let B act as an
    intermediary towards A's witness w = gA.  But b1, an earlier event on
    B's self-chain, sees w cleanly — the ∃-z rule counts member B.
    """
    keys, members, node = _manual_population()
    (pkA, skA), (pkB, skB), (pkC, skC), (pkD, skD) = keys
    t = [100]

    def mk(creator_i, parents, payload=b""):
        pk, sk = keys[creator_i]
        t[0] += 1
        ev = Event(d=payload, p=parents, t=t[0], c=pk).signed(sk)
        node.add_event(ev)
        return ev.id

    gA = mk(0, ())
    gB = mk(1, ())
    gC = mk(2, ())
    a1 = mk(0, (gA, gB))          # branch 1 of A's fork
    a2 = mk(0, (gA, gC))          # branch 2 (same self-parent gA)
    b1 = mk(1, (gB, gA))          # sees gA cleanly
    b2 = mk(1, (b1, a1))          # sees one branch only
    b3 = mk(1, (b2, a2))          # now fork-sees A
    x1 = mk(3, (node.head, b3))   # D's event on top of everything

    assert node.has_fork[pkA]
    assert node.forkseen(b3, pkA) and not node.forkseen(b1, pkA)
    # tip-only would reject B as intermediary (its tip is poisoned) ...
    assert not node.sees(b3, gA)
    # ... but the ∃-z rule accepts it through b1:
    assert node._sees_through(x1, gA, pkB)
    # A itself is fork-seen by x1, so no event by A can be the z:
    assert node.forkseen(x1, pkA)
    assert not node._sees_through(x1, gA, pkA)
    # C's only ancestor-event of x1 is its genesis, which does not see gA:
    assert not node._sees_through(x1, gA, pkC)
    # D's earliest chain event seeing gA is x1 itself, which fork-sees A:
    assert not node._sees_through(x1, gA, pkD)
    # hence only B (1 of 4 stake) qualifies -> no strong seeing:
    assert not node.strongly_sees(x1, gA)


def test_straggler_witness_registers_deterministically():
    """A witness landing in a fame-complete (frozen) round is a FULL
    citizen under the deterministic expiry horizon: registered in the
    witness tables (so every node and engine computes the identical
    state regardless of arrival order), and tracked as metadata in
    late_witnesses for observability."""
    keys, members, node = _manual_population()
    node._frozen_round = 0  # pretend round 0 fame is complete
    pkA, skA = keys[0]
    ev = Event(d=b"", p=(), t=5, c=pkA).signed(skA)
    node.add_event(ev)
    node.divide_rounds([ev.id])   # genesis witness in frozen round 0
    assert node.is_witness[ev.id]
    assert ev.id in node.wit_slot, "late witness must enter the table"
    assert ev.id in node.wit_list[0]
    assert node.famous[ev.id] is None   # undecided until votes exist
    assert ev.id in node.late_witnesses
    assert node.horizon_violations == 0


def test_divergent_forker_no_crash_and_convergence():
    """VERDICT r4 weak #1 regression: a forker serving different branches
    to different peers must not crash honest nodes (orphan + want-list
    recovery instead of add_event raising), and honest nodes must stay
    prefix-consistent and detect the fork."""
    from tpu_swirld.sim import run_with_divergent_forkers

    sim = run_with_divergent_forkers(7, 2, 600, seed=5)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0, "consensus must stay live under equivocating forkers"
    assert all(o[:m] == orders[0][:m] for o in orders)
    # the fork became visible to at least one honest node
    forker_pks = {f.pk for f in sim.forkers}
    assert any(
        n.has_fork[fpk] for n in sim.nodes for fpk in forker_pks
    ), "divergent branches never met — adversary too weak"
    # and recovery actually exercised the orphan path at least once
    # (divergent suffixes necessarily produce unknown-parent deliveries)


def test_forked_creator_sync_replies_stay_o_delta():
    """Once a creator is known to have forked, sync replies must NOT
    re-send its whole history forever: the reply is the height-hint delta
    plus a bounded fork digest (earliest fork-group siblings + branch
    tips), and a converged asker gets an O(1)-sized reply even while the
    persistent equivocator keeps growing its branches."""
    from tpu_swirld import crypto
    from tpu_swirld.sim import run_with_divergent_forkers

    sim = run_with_divergent_forkers(5, 1, 260, seed=5)
    forker_pk = sim.forkers[0].pk
    server = next(n for n in sim.nodes if n.has_fork[forker_pk])
    asker = next(n for n in sim.nodes if n is not server)
    # converge the asker to the server's store
    for _ in range(12):
        got = asker.pull(server.pk)
        if got:
            asker.consensus_pass(got)
        elif not asker._orphans:
            break
    n_forker_events = len(server.member_events[forker_pk])
    assert n_forker_events >= 20, "equivocator must have a long history"

    hv = b"".join(
        len(asker.member_events[m]).to_bytes(4, "little")
        for m in asker.members
    )
    req = hv + crypto.sign(hv, asker.sk, crypto.DOMAIN_SYNC_REQ)
    reply = server.ask_sync(asker.pk, req)
    events = asker._decode_signed_blob(reply, server.pk)
    assert events is not None
    # old rule: every sync re-shipped all n_forker_events forker events.
    # new rule: delta (empty here) + first fork-group siblings + tips.
    bound = 2 + len(server.branch_tips[forker_pk]) + 4
    assert len(events) <= bound < n_forker_events
    assert len(reply) < n_forker_events * 100  # bytes, not just counts


def test_orphan_buffer_requeues_unknown_parent():
    """Direct unit: delivering a child before its parent parks the child
    and inserts it once the parent arrives."""
    keys, members, node = _manual_population()
    pkA, skA = keys[0]
    t = [50]

    def mk(parents, payload=b""):
        t[0] += 1
        return Event(d=payload, p=parents, t=t[0], c=pkA).signed(skA)

    gA = mk(())
    a1 = Event(d=b"x", p=(gA.id, node.head), t=60, c=pkA).signed(skA)
    a2 = Event(d=b"y", p=(a1.id, node.head), t=61, c=pkA).signed(skA)
    node._ingest([gA, a2], new_ids := [])       # a2's parent a1 unknown
    assert a2.id in node._orphans and a2.id not in node.hg
    node._ingest([a1], new_ids)
    assert a2.id in node.hg and not node._orphans
    assert new_ids == [gA.id, a1.id, a2.id]


def test_malformed_wire_blobs_rejected():
    from tpu_swirld.oracle.event import (
        MalformedEvent, decode_event, encode_event,
    )

    keys, members, node = _manual_population()
    pkA, skA = keys[0]
    ev = Event(d=b"hello", p=(), t=1, c=pkA).signed(skA)
    blob = encode_event(ev)
    # round-trip sanity
    dec, off = decode_event(blob)
    assert dec == ev and off == len(blob)
    import pytest, struct

    for bad in [
        blob[:-1],                       # truncated signature
        blob[:3],                        # truncated length field
        struct.pack("<I", 2**31) + blob[4:],   # oversized body length
        struct.pack("<I", 10) + b"\x07" + b"x" * 9,  # bad parent count
        blob[:4] + b"\xff" + blob[5:],   # parent count byte corrupted
    ]:
        with pytest.raises(MalformedEvent):
            decode_event(bad)
    # a corrupted blob inside a signed sync reply fails signature first;
    # a *validly signed* malformed blob must degrade to a counted
    # rejection (None + bad_replies), never an uncaught exception
    from tpu_swirld import crypto
    evil = blob[:-1]
    reply = evil + crypto.sign(evil, skA, crypto.DOMAIN_SYNC_REPLY)
    before = node.bad_replies
    assert node._decode_signed_blob(reply, pkA) is None
    assert node.bad_replies == before + 1


def test_domain_separation():
    """A signature from one context must not verify in another."""
    from tpu_swirld import crypto

    pk, sk = crypto.keypair(b"dom")
    body = b"some payload"
    s_event = crypto.sign(body, sk, crypto.DOMAIN_EVENT)
    assert crypto.verify(body, s_event, pk, crypto.DOMAIN_EVENT)
    assert not crypto.verify(body, s_event, pk, crypto.DOMAIN_SYNC_REQ)
    assert not crypto.verify(body, s_event, pk, crypto.DOMAIN_SYNC_REPLY)
    assert not crypto.verify(body, s_event, pk, crypto.DOMAIN_WANT)
    assert not crypto.verify(body, s_event, pk)


import pytest


@pytest.mark.slow
def test_divergent_forkers_config4_scale_smoke():
    """64 members / f=21 equivocators, live gossip: honest nodes must not
    crash, must detect forks, and must never diverge (ordering liveness at
    this scale is the TPU pipeline's job — the Python sim only smoke-tests
    the transport; see test_parity_config4_64m_f21)."""
    from tpu_swirld.sim import run_with_divergent_forkers

    sim = run_with_divergent_forkers(64, 21, 200, seed=1, fork_every=10)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert all(o[:m] == orders[0][:m] for o in orders)
    forker_pks = {f.pk for f in sim.forkers}
    assert any(n.has_fork[fpk] for n in sim.nodes for fpk in forker_pks)


def test_invalid_event_in_signed_reply_dropped_not_crash():
    """A byzantine peer can wrap garbage in a validly-signed reply blob;
    honest ingestion must drop it, not raise out of sync()."""
    keys, members, node = _manual_population()
    pkA, skA = keys[0]
    pkB, skB = keys[1]
    good = Event(d=b"", p=(), t=5, c=pkA).signed(skA)
    forged = Event(d=b"evil", p=(), t=6, c=pkA, s=b"\x00" * 64)  # bad sig
    wrong_creator = Event(d=b"", p=(), t=7, c=b"\x01" * 32).signed(skB)
    node._ingest([good, forged, wrong_creator], new_ids := [])
    assert new_ids == [good.id]
    assert forged.id not in node.hg and wrong_creator.id not in node.hg
    # oversized payload is refused at creation/validation time too
    from tpu_swirld.oracle.event import MAX_PAYLOAD
    big = Event(d=b"x" * (MAX_PAYLOAD + 1), p=(), t=8, c=pkA).signed(skA)
    assert not node.is_valid_event(big)


def test_malformed_signed_reply_tolerated_in_pull():
    """A byzantine peer returning garbage with a VALID reply signature must
    not kill the honest gossip loop — pull() counts it and moves on."""
    from tpu_swirld import crypto
    from tpu_swirld.sim import make_simulation

    sim = make_simulation(4, seed=6)
    sim.run(30)
    honest = sim.nodes[0]
    evil = sim.nodes[1]

    def evil_ask_sync(from_pk, req):
        junk = b"\xff" * 37
        return junk + crypto.sign(junk, evil.sk, crypto.DOMAIN_SYNC_REPLY)

    sim.network[evil.pk] = evil_ask_sync
    got = honest.pull(evil.pk)
    assert got == [] and honest.bad_replies == 1
    # and an unsigned-garbage reply too
    sim.network[evil.pk] = lambda from_pk, req: b"\x00" * 10
    assert honest.pull(evil.pk) == [] and honest.bad_replies == 2


def test_orphan_buffer_resists_poisoning():
    """Junk orphans (bad signature) are refused; overflow evicts FIFO
    instead of permanently refusing new orphans."""
    import dataclasses as dc

    keys, members, node = _manual_population()
    pkA, skA = keys[0]
    fake_parent = b"\x99" * 32
    # unsigned junk with unknown parents: must not be parked
    junk = Event(d=b"j", p=(fake_parent, fake_parent), t=9, c=pkA, s=b"\x00" * 64)
    node._ingest([junk], [])
    assert not node._orphans
    # validly-signed orphans beyond the cap evict oldest, not newest
    node.config = dc.replace(node.config, max_orphans=2)
    evs = [
        Event(d=b"o%d" % i, p=(fake_parent, fake_parent), t=20 + i, c=pkA).signed(skA)
        for i in range(3)
    ]
    node._ingest(evs, [])
    assert len(node._orphans) == 2
    assert evs[0].id not in node._orphans
    assert evs[2].id in node._orphans
