"""The pluggable backend='tpu' seam: device-engine nodes must behave
exactly like oracle nodes, including in mixed populations."""

import dataclasses

import jax
import pytest

from tpu_swirld.sim import make_simulation


def _mixed_sim(n_nodes, seed, tpu_indices, mesh_shape=None):
    sim = make_simulation(n_nodes, seed=seed)
    for i in tpu_indices:
        node = sim.nodes[i]
        node.config = dataclasses.replace(
            node.config, backend="tpu", block_size=128, mesh_shape=mesh_shape
        )
    return sim


def test_tpu_backend_node_matches_oracle_nodes():
    """One member runs its consensus passes on the device pipeline; it
    must reach the same consensus as its python-backend peers."""
    sim = _mixed_sim(4, seed=3, tpu_indices=[1])
    sim.run(150)
    tpu_node = sim.nodes[1]
    py_node = sim.nodes[0]
    assert len(tpu_node.consensus) > 0
    m = min(len(tpu_node.consensus), len(py_node.consensus))
    assert tpu_node.consensus[:m] == py_node.consensus[:m]
    # oracle-shaped state is fully populated (viz/metrics/checkpoint seams)
    for eid in tpu_node.order_added:
        assert eid in tpu_node.round
        assert eid in tpu_node.is_witness
    assert tpu_node._tpu_engine is not None
    # identical view => identical full state vs a python replay of its DAG
    from tpu_swirld.oracle.node import Node

    replay = Node(
        sk=tpu_node.sk, pk=tpu_node.pk, network={}, members=sim.members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [
        e for e in tpu_node.order_added
        if replay.add_event(tpu_node.hg[e])
    ]
    replay.consensus_pass(new_ids)
    assert replay.consensus == tpu_node.consensus
    assert replay.round == tpu_node.round
    assert replay.is_witness == tpu_node.is_witness
    assert replay.famous == tpu_node.famous
    assert replay.round_received == tpu_node.round_received
    assert replay.consensus_ts == tpu_node.consensus_ts
    assert replay.wit_list == tpu_node.wit_list
    assert replay.consensus_round == tpu_node.consensus_round


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 virtual devices")
def test_tpu_backend_with_mesh_shape():
    """config.mesh_shape wires the sharded strongly-sees phase."""
    sim = _mixed_sim(4, seed=5, tpu_indices=[2], mesh_shape={"members": 4})
    sim.run(100)
    tpu_node = sim.nodes[2]
    py_node = sim.nodes[3]
    assert tpu_node._tpu_engine.mesh is not None
    m = min(len(tpu_node.consensus), len(py_node.consensus))
    assert m > 0
    assert tpu_node.consensus[:m] == py_node.consensus[:m]


def test_tpu_backend_lazy_batching():
    """tpu_min_batch amortizes device passes; flush() forces one; the
    eventual consensus matches the python peers exactly."""
    sim = _mixed_sim(4, seed=9, tpu_indices=[1])
    node = sim.nodes[1]
    node.config = dataclasses.replace(node.config, tpu_min_batch=40)
    sim.run(120)
    eng = node._tpu_engine
    assert eng is not None
    # the engine genuinely lags the store (strict: lazy batching works)
    assert eng._n_consumed < len(node.order_added)
    eng.flush()
    assert eng._n_consumed == len(node.order_added)
    py_node = sim.nodes[0]
    m = min(len(node.consensus), len(py_node.consensus))
    assert m > 0
    assert node.consensus[:m] == py_node.consensus[:m]
    # full-state equivalence with a python replay after the flush
    from tpu_swirld.oracle.node import Node

    replay = Node(
        sk=node.sk, pk=node.pk, network={}, members=sim.members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [e for e in node.order_added if replay.add_event(node.hg[e])]
    replay.consensus_pass(new_ids)
    assert replay.consensus == node.consensus
    assert replay.round == node.round
