"""Graph utilities: bfs/dfs/toposort."""

import pytest

from tpu_swirld.oracle.graph import bfs, dfs, toposort

#      a
#     / \
#    b   c
#     \ / \
#      d   e
EDGES = {"a": [], "b": ["a"], "c": ["a"], "d": ["b", "c"], "e": ["c"]}
CHILDREN = {"a": ["b", "c"], "b": ["d"], "c": ["d", "e"], "d": [], "e": []}


def test_bfs_visits_all_once():
    seen = list(bfs(["a"], lambda n: CHILDREN[n]))
    assert sorted(seen) == ["a", "b", "c", "d", "e"]
    assert len(set(seen)) == len(seen)
    assert seen[0] == "a"


def test_dfs_visits_all_once():
    seen = list(dfs(["d"], lambda n: EDGES[n]))
    assert sorted(seen) == ["a", "b", "c", "d"]


def test_toposort_parents_first():
    order = toposort(["e", "d", "c", "b", "a"], lambda n: EDGES[n])
    pos = {n: i for i, n in enumerate(order)}
    for node, parents in EDGES.items():
        for p in parents:
            assert pos[p] < pos[node]


def test_toposort_cycle_raises():
    cyc = {"x": ["y"], "y": ["x"]}
    with pytest.raises(ValueError):
        toposort(["x", "y"], lambda n: cyc[n])
