"""The observability subsystem (tpu_swirld.obs): spans, registry, exporters,
pipeline/gossip instrumentation, disabled-mode overhead, report CLI."""

import json
import os
import subprocess
import sys

import pytest

from tpu_swirld import obs, viz
from tpu_swirld.metrics import Metrics, node_gauges
from tpu_swirld.obs.registry import Registry
from tpu_swirld.obs.report import aggregate_spans, gauge_rows, render_report
from tpu_swirld.obs.tracer import NULL_TRACER, Tracer, load_trace
from tpu_swirld.packing import pack_events
from tpu_swirld.sim import generate_gossip_dag, make_simulation


# ------------------------------------------------------------------ tracer


def test_span_nesting_and_jsonl_roundtrip(tmp_path):
    tr = Tracer()
    with tr.span("outer", n=1) as sp:
        with tr.span("inner"):
            pass
        sp.args["extra"] = "x"
    tr.instant("marker", k=2)
    events = tr.events
    # inner closes first, with depth 1; outer has depth 0 and the args
    inner, outer, marker = events
    assert inner["name"] == "inner" and inner["args"]["depth"] == 1
    assert outer["name"] == "outer" and outer["args"]["depth"] == 0
    assert outer["args"]["n"] == 1 and outer["args"]["extra"] == "x"
    assert outer["dur"] >= inner["dur"] >= 0
    assert outer["ts"] <= inner["ts"]          # outer started first
    assert outer["args"]["wall_s"] > 0          # wall clock recorded
    assert marker["ph"] == "i"
    # JSONL round-trip preserves every event
    p = str(tmp_path / "t.jsonl")
    tr.save(p)
    with open(p) as f:
        lines = [l for l in f.read().splitlines() if l]
    assert len(lines) == len(events)
    assert load_trace(p) == events
    # Chrome-wrapped form loads identically
    pc = str(tmp_path / "t.chrome.json")
    tr.save_chrome(pc)
    assert load_trace(pc) == events


def test_phase_seconds_aggregates_depth0():
    tr = Tracer()
    for _ in range(3):
        with tr.span("a"):
            with tr.span("b"):
                pass
    agg = tr.phase_seconds()
    assert set(agg) == {"a"}
    assert agg["a"] > 0


def test_null_tracer_allocates_nothing():
    # the disabled tracer hands out ONE shared no-op span: no per-call
    # allocation, no recorded events
    s1 = NULL_TRACER.span("x", k=1)
    s2 = NULL_TRACER.span("y")
    assert s1 is s2
    with s1:
        pass
    assert NULL_TRACER.events == []


# ---------------------------------------------------------------- registry


def test_registry_prometheus_text_format():
    reg = Registry()
    reg.counter("syncs").inc(3)
    reg.gauge("lag", {"node": "0"}).set(2.5)
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.to_prometheus_text()
    assert "# TYPE syncs counter" in text
    assert "syncs 3" in text
    assert "# TYPE lag gauge" in text
    assert 'lag{node="0"} 2.5' in text
    # histogram: cumulative buckets + +Inf + sum/count
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1.0"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text
    assert "lat_sum 5.55" in text


def test_registry_json_and_identity():
    reg = Registry()
    c1 = reg.counter("n", {"a": "1"})
    c2 = reg.counter("n", {"a": "1"})
    assert c1 is c2                    # same (name, labels) -> same object
    c1.inc(2)
    assert reg.value("n", {"a": "1"}) == 2
    assert reg.value("missing", default=-1) == -1
    with pytest.raises(TypeError):
        reg.gauge("n", {"a": "1"})     # kind mismatch is an error
    d = json.loads(reg.to_json())
    assert d['n{a="1"}'] == {"kind": "counter", "value": 2}


def test_counter_rejects_decrease():
    reg = Registry()
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)


# ----------------------------------------------- pipeline instrumentation


def _small_packed(n_events=300, n_members=6, seed=4):
    members, stake, events, _keys = generate_gossip_dag(
        n_members, n_events, seed=seed
    )
    return pack_events(events, members, stake)


def test_disabled_mode_pipeline_touches_nothing():
    """Acceptance pin: with tracing off, the pipeline must not touch any
    registry or tracer — zero per-event (and even per-stage) obs work."""
    from tpu_swirld.tpu.pipeline import run_consensus

    packed = _small_packed()
    bystander = obs.Obs()              # exists but is never enabled
    assert obs.current() is None
    res = run_consensus(packed, block=64)
    assert len(res.order) > 0
    assert obs.current() is None       # nothing installed an ambient Obs
    assert len(bystander.registry) == 0
    assert bystander.tracer.events == []


def test_enabled_pipeline_records_stages_and_pad_waste():
    from tpu_swirld.tpu.pipeline import run_consensus

    packed = _small_packed()
    with obs.enabled() as o:
        run_consensus(packed, block=64)
    reg = o.registry
    n_pad = ((packed.n + 63) // 64) * 64
    assert reg.value("pipeline_events") == packed.n
    assert reg.value("pipeline_pad_events") == n_pad - packed.n
    assert reg.value("pipeline_ssm_columns_total") > 0
    assert reg.value("pipeline_chunk_scans_total") > 0
    # per-stage seconds with compile/execute attribution exist
    stages = reg.collect("pipeline_stage_seconds")
    names = {dict(k)["stage"] for k in stages}
    assert "pipeline.visibility_stage" in names
    assert "pipeline.rounds_chunk_stage" in names
    assert "pipeline.fame_order_cols_stage" in names
    spans = {e["name"] for e in o.tracer.spans()}
    assert "pipeline.finalize" in spans


def test_enabled_pipeline_span_count_is_stage_granular():
    """Spans scale with stages/chunks, never with events: 4x the events
    must cost far fewer than 4x-minus-stages extra spans (no per-event
    Python-level span overhead even when ENABLED)."""
    from tpu_swirld.tpu.pipeline import run_consensus

    small = _small_packed(n_events=128, n_members=4, seed=7)
    big = _small_packed(n_events=512, n_members=4, seed=7)
    with obs.enabled() as o1:
        run_consensus(small, block=64)
    with obs.enabled() as o2:
        run_consensus(big, block=64)
    n1 = len(o1.tracer.spans())
    n2 = len(o2.tracer.spans())
    # chunked scanning adds ~(N/chunk) spans; per-event spans would add >384
    assert n2 - n1 < 64
    assert n2 < big.n / 4


def test_obs_save_is_repeatable_without_duplicates(tmp_path):
    o = obs.Obs()
    with o.tracer.span("s"):
        pass
    o.registry.counter("c").inc(1)
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    o.save(p1)
    o.registry.counter("c").inc(1)
    o.save(p2)
    # each file: 1 span + 1 counter sample, and the second has fresh values
    e1, e2 = load_trace(p1), load_trace(p2)
    assert len(e1) == 2 and len(e2) == 2
    assert [e["args"]["value"] for e in e2 if e["ph"] == "C"] == [2]
    assert o.tracer.events == [e1[0]]          # tracer itself not mutated


def test_obs_enabled_scope_nests_and_restores():
    assert obs.current() is None
    with obs.enabled() as outer:
        assert obs.current() is outer
        with obs.enabled() as inner:
            assert obs.current() is inner
        assert obs.current() is outer
    assert obs.current() is None


# -------------------------------------------------- gossip + sim plumbing


def test_make_simulation_plumbs_shared_metrics_and_tracer():
    shared = Metrics()
    tr = Tracer()
    sim = make_simulation(4, seed=11, metrics=shared, tracer=tr)
    for n in sim.nodes:
        assert n.metrics is shared
        assert n.tracer is tr
    sim.run(60)
    counts = shared.counts
    assert counts["gossip_syncs"] == 60
    assert counts["gossip_bytes_in"] > 0
    assert counts["gossip_bytes_out"] > 0
    assert counts["gossip_events_received"] > 0
    # oracle phase spans recorded (3 per consensus pass)
    assert len(tr.spans()) == 3 * 60
    # the shim snapshot still has the legacy shape on top of gossip counters
    snap = shared.snapshot()
    assert "s_divide_rounds" in snap and "n_gossip_syncs" in snap


def test_make_simulation_per_node_metrics():
    sim = make_simulation(3, seed=12, metrics=True)
    assert all(n.metrics is not None for n in sim.nodes)
    ms = {id(n.metrics) for n in sim.nodes}
    assert len(ms) == 3                # fresh Metrics per node
    sim.run(30)
    total = sum(n.metrics.counts.get("gossip_syncs", 0) for n in sim.nodes)
    assert total == 30


def test_forker_sims_plumb_metrics():
    from tpu_swirld.sim import run_with_divergent_forkers, run_with_forkers

    shared = Metrics()
    sim = run_with_forkers(5, 1, 80, seed=3, fork_every=5, metrics=shared)
    assert sim.nodes[1].metrics is shared
    assert shared.counts["gossip_syncs"] == 80
    # consistent-order forks propagate through honest gossip -> detections
    assert shared.counts.get("gossip_fork_pairs_detected", 0) > 0

    shared2 = Metrics()
    dsim = run_with_divergent_forkers(5, 1, 60, seed=3, metrics=shared2)
    assert all(n.metrics is shared2 for n in dsim.nodes)
    assert shared2.counts.get("gossip_fork_pairs_detected", 0) > 0


def test_node_gauges_tolerates_partial_nodes():
    class Husk:                        # checkpoint-/backend-shaped stub
        famous = {}

    g = node_gauges(Husk())
    assert g["events"] == 0 and g["orphans_parked"] == 0
    assert g["forks_detected"] == 0 and g["late_witnesses"] == 0
    assert g["horizon_violations"] == 0

    sim = make_simulation(4, seed=2)
    sim.run(60)
    reg = Registry()
    g = node_gauges(sim.nodes[0], registry=reg)
    assert g["events"] == len(sim.nodes[0].hg)
    lab = {"node": sim.nodes[0].pk[:4].hex()}
    assert reg.value("node_events", lab) == g["events"]
    assert g["orphans_parked"] == sim.nodes[0].orphans_parked
    # a shared registry keeps every node distinct (default pk-prefix label)
    for n in sim.nodes[1:]:
        node_gauges(n, registry=reg)
    variants = reg.collect("node_events")
    assert len(variants) == 4


# ----------------------------------------------------------- viz gauges


def test_viz_fame_gauges_annotate_and_register():
    sim = make_simulation(4, seed=5)
    sim.run(100)
    node = sim.nodes[0]
    reg = Registry()
    lanes = viz.ascii_lanes(node=node, registry=reg)
    assert "fame decided/witnesses per round:" in lanes
    dot = viz.to_dot(node=node)
    assert dot.startswith("digraph")
    assert "fame per round:" in dot
    rows = viz.export_state(node=node)
    gauges = viz.fame_gauges(rows)
    # every round with witnesses appears; counts match the export
    wit_rounds = {r["round"] for r in rows if r["witness"]}
    assert set(gauges) == wit_rounds
    r0_decided = sum(
        1 for r in rows
        if r["witness"] and r["round"] == 0 and r["famous"] is not None
    )
    assert gauges[0][0] == r0_decided
    assert reg.value("round_fame_decided", {"round": "0"}) == r0_decided


# ------------------------------------------------------------- report CLI


def test_report_aggregation_pure():
    events = [
        {"name": "a", "ph": "X", "ts": 0, "dur": 1000, "args": {"depth": 0}},
        {"name": "a", "ph": "X", "ts": 2000, "dur": 3000, "args": {"depth": 0}},
        {"name": "b", "ph": "X", "ts": 100, "dur": 500, "args": {"depth": 1}},
        {"name": "g", "ph": "C", "ts": 0, "args": {"value": 7, "round": "1"}},
    ]
    rows = aggregate_spans(events)
    a = next(r for r in rows if r["name"] == "a")
    assert a["calls"] == 2 and a["total_ms"] == 4.0 and a["max_ms"] == 3.0
    g = gauge_rows(events)
    assert g == [{"name": "g", "value": 7, "labels": {"round": "1"}}]
    text = render_report(events)
    assert "phase breakdown" in text and "g{round=1}  7" in text


@pytest.mark.smoke
def test_report_cli_smoke(tmp_path):
    """End-to-end: generate a real trace (sim + pipeline under obs), then
    run the actual `python -m tpu_swirld.obs report` CLI on it."""
    from tpu_swirld.tpu.pipeline import run_consensus

    with obs.enabled() as o:
        sim = make_simulation(4, seed=6, metrics=Metrics(registry=o.registry),
                              tracer=o.tracer)
        sim.run(40)
        from tpu_swirld.packing import pack_node

        run_consensus(pack_node(sim.nodes[0]), sim.config, block=64)
        viz.fame_gauges(
            viz.export_state(node=sim.nodes[0]), registry=o.registry
        )
    path = str(tmp_path / "trace.jsonl")
    o.save(path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "tpu_swirld.obs", "report", path],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stderr
    assert "phase breakdown" in r.stdout
    assert "divide_rounds" in r.stdout          # oracle spans made it
    assert "pipeline.visibility_stage" in r.stdout
    assert "gossip_syncs" in r.stdout           # registry snapshot made it
    assert "round_fame_decided" in r.stdout     # viz gauges made it
