"""The observability subsystem (tpu_swirld.obs): spans, registry, exporters,
pipeline/gossip instrumentation, disabled-mode overhead, report CLI."""

import json
import os
import subprocess
import sys

import pytest

from tpu_swirld import obs, viz
from tpu_swirld.metrics import Metrics, node_gauges
from tpu_swirld.obs.registry import Registry
from tpu_swirld.obs.report import aggregate_spans, gauge_rows, render_report
from tpu_swirld.obs.tracer import NULL_TRACER, Tracer, load_trace
from tpu_swirld.packing import pack_events
from tpu_swirld.sim import generate_gossip_dag, make_simulation


# ------------------------------------------------------------------ tracer


def test_span_nesting_and_jsonl_roundtrip(tmp_path):
    tr = Tracer()
    with tr.span("outer", n=1) as sp:
        with tr.span("inner"):
            pass
        sp.args["extra"] = "x"
    tr.instant("marker", k=2)
    events = tr.events
    # inner closes first, with depth 1; outer has depth 0 and the args
    inner, outer, marker = events
    assert inner["name"] == "inner" and inner["args"]["depth"] == 1
    assert outer["name"] == "outer" and outer["args"]["depth"] == 0
    assert outer["args"]["n"] == 1 and outer["args"]["extra"] == "x"
    assert outer["dur"] >= inner["dur"] >= 0
    assert outer["ts"] <= inner["ts"]          # outer started first
    assert outer["args"]["wall_s"] > 0          # wall clock recorded
    assert marker["ph"] == "i"
    # JSONL round-trip preserves every event
    p = str(tmp_path / "t.jsonl")
    tr.save(p)
    with open(p) as f:
        lines = [l for l in f.read().splitlines() if l]
    assert len(lines) == len(events)
    assert load_trace(p) == events
    # Chrome-wrapped form loads identically
    pc = str(tmp_path / "t.chrome.json")
    tr.save_chrome(pc)
    assert load_trace(pc) == events


def test_phase_seconds_aggregates_depth0():
    tr = Tracer()
    for _ in range(3):
        with tr.span("a"):
            with tr.span("b"):
                pass
    agg = tr.phase_seconds()
    assert set(agg) == {"a"}
    assert agg["a"] > 0


def test_null_tracer_allocates_nothing():
    # the disabled tracer hands out ONE shared no-op span: no per-call
    # allocation, no recorded events
    s1 = NULL_TRACER.span("x", k=1)
    s2 = NULL_TRACER.span("y")
    assert s1 is s2
    with s1:
        pass
    assert NULL_TRACER.events == []


# ---------------------------------------------------------------- registry


def test_registry_prometheus_text_format():
    reg = Registry()
    reg.counter("syncs").inc(3)
    reg.gauge("lag", {"node": "0"}).set(2.5)
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.to_prometheus_text()
    assert "# TYPE syncs counter" in text
    assert "syncs 3" in text
    assert "# TYPE lag gauge" in text
    assert 'lag{node="0"} 2.5' in text
    # histogram: cumulative buckets + +Inf + sum/count
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1.0"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text
    assert "lat_sum 5.55" in text


def test_registry_json_and_identity():
    reg = Registry()
    c1 = reg.counter("n", {"a": "1"})
    c2 = reg.counter("n", {"a": "1"})
    assert c1 is c2                    # same (name, labels) -> same object
    c1.inc(2)
    assert reg.value("n", {"a": "1"}) == 2
    assert reg.value("missing", default=-1) == -1
    with pytest.raises(TypeError):
        reg.gauge("n", {"a": "1"})     # kind mismatch is an error
    d = json.loads(reg.to_json())
    assert d['n{a="1"}'] == {"kind": "counter", "value": 2}


def test_counter_rejects_decrease():
    reg = Registry()
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)


# ----------------------------------------------- pipeline instrumentation


def _small_packed(n_events=300, n_members=6, seed=4):
    members, stake, events, _keys = generate_gossip_dag(
        n_members, n_events, seed=seed
    )
    return pack_events(events, members, stake)


def test_disabled_mode_pipeline_touches_nothing():
    """Acceptance pin: with tracing off, the pipeline must not touch any
    registry or tracer — zero per-event (and even per-stage) obs work."""
    from tpu_swirld.tpu.pipeline import run_consensus

    packed = _small_packed()
    bystander = obs.Obs()              # exists but is never enabled
    assert obs.current() is None
    res = run_consensus(packed, block=64)
    assert len(res.order) > 0
    assert obs.current() is None       # nothing installed an ambient Obs
    assert len(bystander.registry) == 0
    assert bystander.tracer.events == []


def test_enabled_pipeline_records_stages_and_pad_waste():
    from tpu_swirld.tpu.pipeline import run_consensus

    packed = _small_packed()
    with obs.enabled() as o:
        run_consensus(packed, block=64)
    reg = o.registry
    n_pad = ((packed.n + 63) // 64) * 64
    assert reg.value("pipeline_events") == packed.n
    assert reg.value("pipeline_pad_events") == n_pad - packed.n
    assert reg.value("pipeline_ssm_columns_total") > 0
    assert reg.value("pipeline_chunk_scans_total") > 0
    # per-stage seconds with compile/execute attribution exist
    stages = reg.collect("pipeline_stage_seconds")
    names = {dict(k)["stage"] for k in stages}
    assert "pipeline.visibility_stage" in names
    assert "pipeline.rounds_chunk_stage" in names
    assert "pipeline.fame_order_cols_stage" in names
    spans = {e["name"] for e in o.tracer.spans()}
    assert "pipeline.finalize" in spans


def test_enabled_pipeline_span_count_is_stage_granular():
    """Spans scale with stages/chunks, never with events: 4x the events
    must cost far fewer than 4x-minus-stages extra spans (no per-event
    Python-level span overhead even when ENABLED)."""
    from tpu_swirld.tpu.pipeline import run_consensus

    small = _small_packed(n_events=128, n_members=4, seed=7)
    big = _small_packed(n_events=512, n_members=4, seed=7)
    with obs.enabled() as o1:
        run_consensus(small, block=64)
    with obs.enabled() as o2:
        run_consensus(big, block=64)
    n1 = len(o1.tracer.spans())
    n2 = len(o2.tracer.spans())
    # chunked scanning adds ~(N/chunk) spans; per-event spans would add >384
    assert n2 - n1 < 64
    assert n2 < big.n / 4


def test_obs_save_is_repeatable_without_duplicates(tmp_path):
    o = obs.Obs()
    with o.tracer.span("s"):
        pass
    o.registry.counter("c").inc(1)
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    o.save(p1)
    o.registry.counter("c").inc(1)
    o.save(p2)
    # each file: 1 span + 1 counter sample, and the second has fresh values
    e1, e2 = load_trace(p1), load_trace(p2)
    assert len(e1) == 2 and len(e2) == 2
    assert [e["args"]["value"] for e in e2 if e["ph"] == "C"] == [2]
    assert o.tracer.events == [e1[0]]          # tracer itself not mutated


def test_obs_enabled_scope_nests_and_restores():
    assert obs.current() is None
    with obs.enabled() as outer:
        assert obs.current() is outer
        with obs.enabled() as inner:
            assert obs.current() is inner
        assert obs.current() is outer
    assert obs.current() is None


# -------------------------------------------------- gossip + sim plumbing


def test_make_simulation_plumbs_shared_metrics_and_tracer():
    shared = Metrics()
    tr = Tracer()
    sim = make_simulation(4, seed=11, metrics=shared, tracer=tr)
    for n in sim.nodes:
        assert n.metrics is shared
        assert n.tracer is tr
    sim.run(60)
    counts = shared.counts
    assert counts["gossip_syncs"] == 60
    assert counts["gossip_bytes_in"] > 0
    assert counts["gossip_bytes_out"] > 0
    assert counts["gossip_events_received"] > 0
    # oracle phase spans recorded (3 per consensus pass)
    assert len(tr.spans()) == 3 * 60
    # the shim snapshot still has the legacy shape on top of gossip counters
    snap = shared.snapshot()
    assert "s_divide_rounds" in snap and "n_gossip_syncs" in snap


def test_make_simulation_per_node_metrics():
    sim = make_simulation(3, seed=12, metrics=True)
    assert all(n.metrics is not None for n in sim.nodes)
    ms = {id(n.metrics) for n in sim.nodes}
    assert len(ms) == 3                # fresh Metrics per node
    sim.run(30)
    total = sum(n.metrics.counts.get("gossip_syncs", 0) for n in sim.nodes)
    assert total == 30


def test_forker_sims_plumb_metrics():
    from tpu_swirld.sim import run_with_divergent_forkers, run_with_forkers

    shared = Metrics()
    sim = run_with_forkers(5, 1, 80, seed=3, fork_every=5, metrics=shared)
    assert sim.nodes[1].metrics is shared
    assert shared.counts["gossip_syncs"] == 80
    # consistent-order forks propagate through honest gossip -> detections
    assert shared.counts.get("gossip_fork_pairs_detected", 0) > 0

    shared2 = Metrics()
    dsim = run_with_divergent_forkers(5, 1, 60, seed=3, metrics=shared2)
    assert all(n.metrics is shared2 for n in dsim.nodes)
    assert shared2.counts.get("gossip_fork_pairs_detected", 0) > 0


def test_node_gauges_tolerates_partial_nodes():
    class Husk:                        # checkpoint-/backend-shaped stub
        famous = {}

    g = node_gauges(Husk())
    assert g["events"] == 0 and g["orphans_parked"] == 0
    assert g["forks_detected"] == 0 and g["late_witnesses"] == 0
    assert g["horizon_violations"] == 0

    sim = make_simulation(4, seed=2)
    sim.run(60)
    reg = Registry()
    g = node_gauges(sim.nodes[0], registry=reg)
    assert g["events"] == len(sim.nodes[0].hg)
    lab = {"node": sim.nodes[0].pk[:4].hex()}
    assert reg.value("node_events", lab) == g["events"]
    assert g["orphans_parked"] == sim.nodes[0].orphans_parked
    # a shared registry keeps every node distinct (default pk-prefix label)
    for n in sim.nodes[1:]:
        node_gauges(n, registry=reg)
    variants = reg.collect("node_events")
    assert len(variants) == 4


# ----------------------------------------------------------- viz gauges


def test_viz_fame_gauges_annotate_and_register():
    sim = make_simulation(4, seed=5)
    sim.run(100)
    node = sim.nodes[0]
    reg = Registry()
    lanes = viz.ascii_lanes(node=node, registry=reg)
    assert "fame decided/witnesses per round:" in lanes
    dot = viz.to_dot(node=node)
    assert dot.startswith("digraph")
    assert "fame per round:" in dot
    rows = viz.export_state(node=node)
    gauges = viz.fame_gauges(rows)
    # every round with witnesses appears; counts match the export
    wit_rounds = {r["round"] for r in rows if r["witness"]}
    assert set(gauges) == wit_rounds
    r0_decided = sum(
        1 for r in rows
        if r["witness"] and r["round"] == 0 and r["famous"] is not None
    )
    assert gauges[0][0] == r0_decided
    assert reg.value("round_fame_decided", {"round": "0"}) == r0_decided


# ------------------------------------------------------------- report CLI


def test_report_aggregation_pure():
    events = [
        {"name": "a", "ph": "X", "ts": 0, "dur": 1000, "args": {"depth": 0}},
        {"name": "a", "ph": "X", "ts": 2000, "dur": 3000, "args": {"depth": 0}},
        {"name": "b", "ph": "X", "ts": 100, "dur": 500, "args": {"depth": 1}},
        {"name": "g", "ph": "C", "ts": 0, "args": {"value": 7, "round": "1"}},
    ]
    rows = aggregate_spans(events)
    a = next(r for r in rows if r["name"] == "a")
    assert a["calls"] == 2 and a["total_ms"] == 4.0 and a["max_ms"] == 3.0
    g = gauge_rows(events)
    assert g == [{"name": "g", "value": 7, "labels": {"round": "1"}}]
    text = render_report(events)
    assert "phase breakdown" in text and "g{round=1}  7" in text


@pytest.mark.smoke
def test_report_cli_smoke(tmp_path):
    """End-to-end: generate a real trace (sim + pipeline under obs), then
    run the actual `python -m tpu_swirld.obs report` CLI on it."""
    from tpu_swirld.tpu.pipeline import run_consensus

    with obs.enabled() as o:
        sim = make_simulation(4, seed=6, metrics=Metrics(registry=o.registry),
                              tracer=o.tracer)
        sim.run(40)
        from tpu_swirld.packing import pack_node

        run_consensus(pack_node(sim.nodes[0]), sim.config, block=64)
        viz.fame_gauges(
            viz.export_state(node=sim.nodes[0]), registry=o.registry
        )
    path = str(tmp_path / "trace.jsonl")
    o.save(path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "tpu_swirld.obs", "report", path],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stderr
    assert "phase breakdown" in r.stdout
    assert "divide_rounds" in r.stdout          # oracle spans made it
    assert "pipeline.visibility_stage" in r.stdout
    assert "gossip_syncs" in r.stdout           # registry snapshot made it
    assert "round_fame_decided" in r.stdout     # viz gauges made it


# --------------------------------- telemetry plane (PR 16): trace identity


def test_pack_unpack_context_roundtrip_and_errors():
    from tpu_swirld.obs.tracer import (
        TRACE_CTX_LEN, pack_context, unpack_context,
    )

    ctx = pack_context(b"8bytesid", 0xDEADBEEF01)
    assert len(ctx) == TRACE_CTX_LEN
    assert unpack_context(ctx) == (b"8bytesid", 0xDEADBEEF01)
    with pytest.raises(ValueError):
        pack_context(b"short", 1)
    with pytest.raises(ValueError):
        unpack_context(ctx + b"x")
    with pytest.raises(ValueError):
        unpack_context(b"")


def test_span_ids_are_process_unique_and_parenting_crosses_processes():
    """The cluster-trace identity model: every enabled span gets a
    pid-folded unique id; span_under parents a local span beneath a
    remote one via the 16-byte wire context; active_context exports the
    innermost traced span for the transport to stamp."""
    from tpu_swirld.obs.tracer import pack_context, unpack_context

    client = Tracer(pid=1000)
    node = Tracer(pid=3)
    root_ctx = pack_context(b"trace-00", 0)   # parent 0 = trace root
    with client.span_under("client.submit", root_ctx) as root:
        wire = client.active_context()
        assert wire is not None
        tid, parent = unpack_context(wire)
        assert tid == b"trace-00" and parent == root.span_id
    # "another process": a different tracer parents under the wire bytes
    with node.span_under("node.submit", wire) as child:
        inner_wire = node.active_context()
        with node.span("node.inner"):   # plain child inherits the trace
            pass
    ev_root = client.events[-1]
    ev_inner, ev_child = node.events[-2], node.events[-1]
    assert ev_root["args"]["span_id"] == root.span_id
    assert ev_root["args"]["trace"] == b"trace-00".hex()
    assert "parent_span_id" not in ev_root["args"]   # root of the trace
    assert ev_child["args"]["parent_span_id"] == root.span_id
    assert ev_child["args"]["trace"] == ev_root["args"]["trace"]
    assert ev_inner["args"]["parent_span_id"] == child.span_id
    assert ev_inner["args"]["trace"] == ev_root["args"]["trace"]
    # ids never collide across processes: pid lives in the upper bits
    assert root.span_id >> 32 == (1000 & 0xFFFF) + 1
    assert child.span_id >> 32 == 3 + 1
    # outside any span there is nothing to stamp
    assert client.active_context() is None
    assert unpack_context(inner_wire)[1] == child.span_id


def test_tracer_event_cap_counts_drops():
    t = Tracer(max_events=2)
    for i in range(5):
        with t.span("s%d" % i):
            pass
    assert len(t.events) == 2 and t.dropped == 3


def test_untraced_spans_carry_no_trace_keys():
    """The pre-PR span shape is preserved: spans outside any trace emit
    span_id (new, additive) but neither trace nor parent-pointer keys
    beyond the local parent."""
    t = Tracer()
    with t.span("plain_outer"):
        with t.span("plain_inner"):
            pass
    inner, outer = t.events
    assert "trace" not in outer["args"] and "trace" not in inner["args"]
    assert "parent_span_id" not in outer["args"]
    assert inner["args"]["parent_span_id"] == outer["args"]["span_id"]
    assert t.active_context() is None


# ------------------------------------- telemetry plane: dispatch profiler


def test_dispatch_profiler_chunk_accounting_with_injected_clock():
    import numpy as np

    from tpu_swirld.obs.profile import DispatchProfiler

    ticks = iter([100.0, 110.0])   # begin_chunk, end_chunk
    prof = DispatchProfiler(top_k=2, clock=lambda: next(ticks))
    prof.begin_chunk(label="c0")
    # two dispatches: 3s stage A, 2s stage B, 1s gap between them
    prof.record_dispatch("A", 100.0, 103.0,
                         args=(np.zeros(4, dtype=np.uint8),))
    prof.record_dispatch("B", 104.0, 106.0)
    prof.record_dispatch("A", 106.0, 107.0)
    prof.record_transfer("d2h", 32)
    row = prof.end_chunk(n_events=7)
    assert row["label"] == "c0" and row["n_events"] == 7
    assert row["dispatches"] == 3
    assert row["stage_s"] == pytest.approx(6.0)
    assert row["wall_s"] == pytest.approx(10.0)
    assert row["overhead_s"] == pytest.approx(4.0)   # wall - stage
    assert row["gap_s"] == pytest.approx(1.0)        # only B<-A gap
    assert row["h2d_bytes"] == 4 and row["d2h_bytes"] == 32
    s = prof.summary()
    assert s["chunks"] == 1 and s["dispatches"] == 3
    assert s["dispatch_overhead_s"] == pytest.approx(4.0)
    assert s["transfers_bytes"] == {"h2d": 4, "d2h": 32}
    # ranked by total seconds, name-stable
    assert [r["stage"] for r in s["top_stages"]] == ["A", "B"]
    assert s["top_stages"][0]["seconds"] == pytest.approx(4.0)
    assert s["top_stages"][0]["calls"] == 2


def test_dispatch_profiler_gaps_reset_at_chunk_boundaries():
    from tpu_swirld.obs.profile import DispatchProfiler

    ticks = iter([0.0, 10.0, 10.0, 20.0])
    prof = DispatchProfiler(clock=lambda: next(ticks))
    prof.begin_chunk()
    prof.record_dispatch("A", 1.0, 2.0)
    prof.end_chunk()
    prof.begin_chunk()
    # 9 seconds since the last dispatch of chunk 0 — NOT a gap: the
    # wait between chunks is the caller's data generation
    prof.record_dispatch("A", 11.0, 12.0)
    prof.end_chunk()
    assert prof.gap_s_total == 0.0
    assert all(c["gap_s"] == 0.0 for c in prof.chunks)


def test_stage_call_feeds_ambient_profiler_execute_only():
    """The obs.stage_call seam: execute dispatches feed the profiler,
    compiles are excluded (one-time cost), and obs.to_host counts D2H."""
    import numpy as np

    from tpu_swirld.obs.profile import DispatchProfiler

    import jax

    @jax.jit
    def f(x):
        return x + 1

    prof = DispatchProfiler()
    with obs.enabled(obs.Obs(profiler=prof)):
        prof.begin_chunk()
        obs.stage_call("stage.f", f, np.arange(8, dtype=np.int32))  # compile
        obs.stage_call("stage.f", f, np.arange(8, dtype=np.int32))  # execute
        host = obs.to_host(f(np.arange(8, dtype=np.int32)))
        prof.end_chunk(n_events=8)
    assert prof.dispatches == 1          # the compile call was excluded
    assert prof.h2d_bytes == 32          # one numpy arg on the execute
    assert prof.d2h_bytes == host.nbytes
    assert prof.chunks[0]["dispatches"] == 1


# --------------------------------------- telemetry plane: shard merging


def _shard_event(name, pid, ts, wall_s, span_id, trace=None, parent=None):
    args = {"depth": 0, "wall_s": wall_s, "span_id": span_id}
    if trace is not None:
        args["trace"] = trace
    if parent is not None:
        args["parent_span_id"] = parent
    return {"name": name, "ph": "X", "pid": pid, "tid": 0,
            "ts": ts, "dur": 500.0, "args": args}


def test_cluster_trace_merge_rebases_and_links_cross_process(tmp_path):
    from tpu_swirld.obs import cluster_trace

    trace = "aabbccdd00112233"
    # client shard: epoch ~= wall 100.0, root span of the trace
    client = [_shard_event("client.submit", 1000, 0.0, 100.0, 7,
                           trace=trace)]
    # node shard: different epoch (ts 5000 at wall 100.001) — the merger
    # must rebase both onto one timebase before comparing ts
    node = [
        _shard_event("node.submit", 3, 5000.0, 100.001, 99,
                     trace=trace, parent=7),
        _shard_event("node.local", 3, 6000.0, 100.002, 100, parent=99),
    ]
    (tmp_path / "client.trace.jsonl").write_text(
        "\n".join(json.dumps(e) for e in client) + "\n")
    (tmp_path / "node-0.trace.jsonl").write_text(
        "\n".join(json.dumps(e) for e in node) + "\n")
    out_path = str(tmp_path / "merged.trace.json")
    summary = cluster_trace.merge_dir(str(tmp_path), out_path=out_path)
    assert summary["shards"] == [
        str(tmp_path / "client.trace.jsonl"),
        str(tmp_path / "node-0.trace.jsonl"),
    ]
    assert summary["traces"] == 1
    assert summary["cross_process_traces"] == 1
    assert summary["cross_process_trace_ids"] == [trace]
    info = summary["per_trace"][trace]
    assert info["spans"] == 2 and info["pids"] == [0, 1]
    assert info["edges"] == 1 and info["cross_process_edges"] == 1
    with open(out_path) as f:
        merged = json.load(f)["traceEvents"]
    # shard labels became process_name metadata on renumbered pids
    names = {e["pid"]: e["args"]["name"]
             for e in merged if e.get("ph") == "M"}
    assert names == {0: "client", 1: "n0"}
    # rebasing: node.submit lands ~1000us after client.submit, not -5000
    by_name = {e["name"]: e for e in merged if e.get("ph") == "X"}
    delta = by_name["node.submit"]["ts"] - by_name["client.submit"]["ts"]
    assert delta == pytest.approx(1000.0, abs=1.0)
    # the cross-process edge became a flow arrow pair (s on the parent's
    # pid/ts, f on the child's)
    flows = [e for e in merged if e.get("ph") in ("s", "f")]
    assert [(e["ph"], e["pid"]) for e in flows] == [("s", 0), ("f", 1)]
    assert flows[0]["id"] == flows[1]["id"]


def test_cluster_trace_merge_is_pure_and_empty_dir_ok(tmp_path):
    from tpu_swirld.obs import cluster_trace

    s1 = cluster_trace.merge_dir(str(tmp_path))
    assert s1["events"] == 0 and s1["traces"] == 0
    assert s1["cross_process_traces"] == 0


# ------------------------------- telemetry plane: registry sample plane


def test_registry_samples_roundtrip_merge_and_rollup():
    from tpu_swirld.obs.registry import (
        Registry, merge_node_samples, rollup_node_samples,
    )

    def make(node_scale):
        r = Registry()
        r.counter("tx_accepted").inc(10 * node_scale)
        r.gauge("pending_txs").set(3 * node_scale)
        h = r.histogram("ttf_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5 * node_scale)
        return r

    per_node = {
        "n0": make(1).to_samples(),
        "n1": make(2).to_samples(),
    }
    # load_samples round-trips a registry through its sample form
    r2 = Registry()
    r2.load_samples(per_node["n0"])
    assert r2.to_samples() == per_node["n0"]
    # merged exposition: one family, node label per sample
    text = merge_node_samples(per_node).to_prometheus_text()
    assert 'tx_accepted{node="n0"} 10' in text
    assert 'tx_accepted{node="n1"} 20' in text
    assert 'pending_txs{node="n1"} 6' in text
    # cluster rollup: counters and gauges sum, histograms roll count
    rollup = rollup_node_samples(per_node)
    assert rollup["tx_accepted"] == 30
    assert rollup["pending_txs"] == 9
    assert rollup["ttf_seconds_count"] == 4


# ----------------------------------- telemetry plane: report CLI modes


def test_report_degrades_gracefully_on_bench_artifact(tmp_path, capsys):
    """An old BENCH_*.json (plain result doc, pretty-printed) renders
    n/a sections and exits 0 instead of crashing the CLI."""
    from tpu_swirld.obs.report import main as report_main

    path = str(tmp_path / "BENCH_r99.json")
    with open(path, "w") as f:
        json.dump({
            "n": 1, "cmd": "python bench.py", "rc": 0,
            "parsed": {"metric": "events/sec", "value": 123.0,
                       "unit": "events/s"},
        }, f, indent=2)
    rc = report_main(["report", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "n/a" in out and "bench artifact" in out
    assert "events/sec: 123.0 events/s" in out
    # a real (single-line JSONL) trace still renders the normal report
    tpath = str(tmp_path / "t.trace.jsonl")
    t = Tracer()
    with t.span("alpha"):
        pass
    t.save(tpath)
    rc = report_main(["report", tpath])
    out = capsys.readouterr().out
    assert rc == 0 and "alpha" in out and "bench artifact" not in out


def test_report_cluster_dir_renders_fleet_with_na_for_old_reports(
    tmp_path, capsys,
):
    from tpu_swirld.obs.report import main as report_main

    # node-0: a current-shape report; node-1: an old report missing the
    # PR 16 keys (trace_events, finality) — must render n/a, not raise
    with open(tmp_path / "node-0.report.json", "w") as f:
        json.dump({
            "node": "n0", "events": 10, "decided": ["aa"], "decided_tx": 4,
            "unclean_start": False, "trace_events": 12, "trace_dropped": 0,
            "finality": {"decided": 1, "rtd_p50": 3.0, "undecided": 2},
            "counters": {"tx_accepted": 4, "tx_shed_pool": 1,
                         "wal_torn_tail_recovered": 0,
                         "node_circuit_opens": 0},
        }, f)
    with open(tmp_path / "node-1.report.json", "w") as f:
        json.dump({"node": "n1", "events": 8, "decided": [],
                   "counters": {}}, f)
    with open(tmp_path / "metrics.json", "w") as f:
        json.dump({"polls": 2, "nodes": {"n0": [], "n1": []},
                   "rollup": {"tx_accepted": 4.0}}, f)
    rc = report_main(["report", "--cluster-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "cluster fleet (2 node reports)" in out
    assert "n/a" in out                          # node-1's missing keys
    assert "tx_accepted" in out and "polls=2" in out
    assert "shed / backpressure" in out
    assert "WAL recovery" in out
    assert "circuit breaker / retries" in out
    assert "merged cross-process trace" in out   # n/a pointer section
    # an empty dir still renders (all n/a) and exits 0
    empty = tmp_path / "empty"
    empty.mkdir()
    rc = report_main(["report", "--cluster-dir", str(empty)])
    out = capsys.readouterr().out
    assert rc == 0 and "no node-*.report.json" in out


# --------------------------------- telemetry plane: lint scope coverage


def test_lint_scopes_cover_new_obs_modules():
    """obs/cluster_trace.py and obs/profile.py sit inside the SW002 and
    SW003 scopes; profile.py is additionally in the SW003 note scope, so
    its single wall read must carry a justified suppression."""
    from tpu_swirld.analysis.lint import check_source

    set_iter = "def f(s):\n    for x in {1, 2}:\n        pass\n"
    clock = "import time\n\ndef f():\n    return time.perf_counter(){}\n"
    for mod in ("obs/cluster_trace.py", "obs/profile.py"):
        assert any(
            f.rule == "SW002"
            for f in check_source(set_iter, module_path=mod, rules=["SW002"])
        ), mod
        assert any(
            f.rule == "SW003"
            for f in check_source(
                clock.format(""), module_path=mod, rules=["SW003"],
            )
        ), mod
    # note scope: a bare disable is NOT enough in profile.py...
    assert check_source(
        clock.format("   # swirld-lint: disable=SW003"),
        module_path="obs/profile.py", rules=["SW003"],
    )
    # ...a justified one is
    assert check_source(
        clock.format("   # swirld-lint: disable=SW003 -- profiler callsite"),
        module_path="obs/profile.py", rules=["SW003"],
    ) == []
    # and the shipped modules themselves pass the full rule set
    import tpu_swirld.obs as obspkg
    from tpu_swirld.analysis.lint import lint_paths

    base = os.path.dirname(obspkg.__file__)
    findings = lint_paths([
        os.path.join(base, "cluster_trace.py"),
        os.path.join(base, "profile.py"),
    ])
    assert findings == [], [str(f) for f in findings]
