"""Dynamic membership: consensus-agreed MemberEpoch reconfiguration.

Covers the `tpu_swirld/membership/` subsystem end to end:

- the ``MTX1`` wire format (total decode, strict-once-magic-matches);
- the epoch ledger's union-registry + functional-update invariants and
  its tamper-evident checkpoint meta round trip;
- the single-epoch regression pin — every engine's dynamic driver is
  bit-identical to its unmodified native path when no membership tx
  decides;
- cross-engine parity on a schedule with ≥ 2 epoch transitions and a
  fork pair straddling an activation boundary;
- restatement determinism — the final state is a pure function of the
  DAG, independent of ingest granularity and arrival order;
- checkpoint restore re-deriving the active epoch and refusing a
  tampered membership header;
- the soak schedule's ``MembershipWindow`` dict round trip (ddmin
  composability) and the membership gauges;
- SW002/SW007 lint-scope pins over ``membership/``.

The join→attack→vote-out chaos acceptance rides
``tests/test_chaos.py``-style scenario plumbing in
:func:`test_membership_churn_scenario`.
"""

import json
import struct

import pytest

from tpu_swirld import crypto
from tpu_swirld.membership import (
    EpochLedger,
    MemberEpoch,
    MembershipTx,
    JOIN,
    LEAVE,
    RESTAKE,
    decode_tx,
    encode_tx,
    join_payload,
    leave_payload,
    restake_payload,
)
from tpu_swirld.membership.engine import ENGINES, run_all_engines, run_dynamic
from tpu_swirld.membership.sim import (
    churn_schedule,
    make_dynamic_simulation,
    sim_member,
)
from tpu_swirld.oracle.event import Event


# ------------------------------------------------------------ wire format


def test_mtx_roundtrip():
    pk = b"\x01" * 32
    for payload, kind, stake in [
        (join_payload(pk, 7), JOIN, 7),
        (leave_payload(pk), LEAVE, 0),
        (restake_payload(pk, 3), RESTAKE, 3),
    ]:
        tx = decode_tx(payload)
        assert tx == MembershipTx(kind, pk, stake)
        assert encode_tx(tx) == payload


def test_mtx_decode_is_total():
    """Foreign payloads are opaque data; payloads that CLAIM the magic
    and are malformed must be None, never a half-parsed change."""
    pk = b"\x02" * 32
    good = join_payload(pk, 1)
    assert decode_tx(b"") is None
    assert decode_tx(b"client-tx-bytes") is None
    assert decode_tx(b"TXB1" + good[4:]) is None      # wrong magic
    assert decode_tx(good[:-1]) is None               # truncated
    assert decode_tx(good + b"\x00") is None          # trailing bytes
    assert decode_tx(b"MTX1" + bytes([9, 32]) + pk
                     + struct.pack("<I", 1)) is None  # unknown kind
    # a zero-stake JOIN is a no-op by definition; LEAVE must carry 0
    assert decode_tx(b"MTX1" + bytes([JOIN, 32]) + pk
                     + struct.pack("<I", 0)) is None
    assert decode_tx(b"MTX1" + bytes([LEAVE, 32]) + pk
                     + struct.pack("<I", 5)) is None


def test_mtx_encode_bounds():
    pk = b"\x03" * 32
    with pytest.raises(ValueError):
        encode_tx(MembershipTx(9, pk, 1))
    with pytest.raises(ValueError):
        encode_tx(MembershipTx(JOIN, pk, 1 << 32))
    with pytest.raises(ValueError):
        encode_tx(MembershipTx(JOIN, b"", 1))


# ------------------------------------------------------------ epoch ledger


def _keys(n):
    return [crypto.keypair(b"ledger-%d" % i)[0] for i in range(n)]


def test_ledger_union_registry():
    """Joins append rows, leaves zero stake but keep the row: epoch k's
    member list is always a prefix of epoch k+1's."""
    members = _keys(3)
    led = EpochLedger.genesis(members, [1, 1, 1])
    assert len(led.epochs) == 1
    assert led.epochs[0].epoch_id == 0

    joiner = crypto.keypair(b"ledger-join")[0]
    led2 = led.apply(
        decode_tx(join_payload(joiner, 2)), activation=9, carrier=b"c1"
    )
    assert led is not led2 and len(led.epochs) == 1  # functional update
    head = led2.head
    assert head.epoch_id == 1
    assert head.members == tuple(members) + (joiner,)
    assert head.stake == (1, 1, 1, 2)

    led3 = led2.apply(
        decode_tx(leave_payload(members[1])), activation=24, carrier=b"c2"
    )
    head = led3.head
    # row kept, stake zeroed; indices stable forever
    assert head.members == tuple(members) + (joiner,)
    assert head.stake == (1, 0, 1, 2)
    assert head.members_active == 3
    assert head.total_stake == 4
    for lo, hi in zip(led3.epochs, led3.epochs[1:]):
        assert hi.members[: len(lo.members)] == lo.members
        assert hi.activation_round > lo.activation_round

    # round addressing: genesis below first activation, head after
    assert led3.epoch_at(0).epoch_id == 0
    assert led3.epoch_at(led3.epochs[1].activation_round).epoch_id == 1
    assert led3.epoch_at(10**6) is led3.head
    # applied-carrier dedup: re-applying the same carrier is a no-op
    led4 = led3.apply(
        decode_tx(leave_payload(members[1])), activation=34, carrier=b"c2"
    )
    assert led4.same_epochs(led3)


def test_ledger_meta_tamper_refused():
    members = _keys(2)
    led = EpochLedger.genesis(members, [2, 2]).apply(
        decode_tx(restake_payload(members[0], 5)), activation=7, carrier=b"c"
    )
    meta = led.to_meta()
    assert EpochLedger.from_meta(json.loads(json.dumps(meta))).same_epochs(
        led
    )
    # edit an epoch without re-stamping the digest: refused
    bad = json.loads(json.dumps(meta))
    bad["epochs"][1]["stake"][0] = 99
    with pytest.raises(ValueError):
        EpochLedger.from_meta(bad)


# -------------------------------------------------- single-epoch pin


@pytest.mark.parametrize("engine", [e for e in ENGINES if e != "oracle"])
def test_single_epoch_pin(engine):
    """No decided membership tx: every engine's dynamic driver must be
    bit-identical to the unmodified native engine (run_dynamic's
    cross_check raises on any divergence)."""
    from tpu_swirld.sim import make_simulation

    sim = make_simulation(4, seed=2)
    sim.run(100)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    res = run_dynamic(
        events, list(node.members), list(node.config.stakes()),
        node.config, engine=engine, chunk=32, cross_check=True,
    )
    assert res.single_epoch
    assert len(res.ledger.epochs) == 1
    assert res.restatements == 0
    assert res.native_order == res.order
    assert len(res.order) > 0


# ------------------------------------------- multi-epoch engine parity


def _fork_pair(sim, victim):
    """Mint a sibling of ``victim``'s newest event (same parents, same
    seq, different payload) and feed it to every node holding both
    parents — a by_seq fork group straddling whatever epoch boundary the
    caller timed it against."""
    probe = max(sim.nodes, key=lambda x: len(x.hg))
    chain = probe.member_events.get(victim.pk, [])
    if len(chain) < 2:
        return 0
    newest = probe.hg[chain[-1]]
    if not newest.p:
        return 0
    sp, op = newest.p
    sib = Event(
        d=b"fork:%d" % len(chain), p=(sp, op), t=newest.t + 1, c=victim.pk
    ).signed(victim.sk)
    fed = 0
    for node in sim.nodes:
        if sib.id in node.hg or sp not in node.hg or op not in node.hg:
            continue
        if node.add_event(sib):
            node.consensus_pass([sib.id])
            fed += 1
    return fed


def test_cross_engine_parity_two_transitions_with_fork():
    """≥ 2 epoch transitions (restake then vote-out leave) with fork
    pairs straddling the second activation boundary: all five engines
    bit-identical on order + rounds, streaming archive rows span the
    epochs, mesh re-pins per epoch."""
    sim = make_dynamic_simulation(4, seed=5)
    victim = sim.nodes[3]
    sim.tx_schedule[12] = restake_payload(sim_member(4, 5, 2), 5)
    sim.run(90)
    fed = _fork_pair(sim, victim)
    # the LEAVE rides a direct honest sync so the forker can't carry
    # its own removal
    sim.clock[0] += 1
    new_ids = sim.nodes[0].sync(sim.nodes[1].pk, leave_payload(victim.pk))
    sim.nodes[0].consensus_pass(new_ids)
    sim.run(40)
    fed += _fork_pair(sim, victim)
    sim.run(160)

    node0 = max(sim.nodes, key=lambda x: len(x.consensus))
    assert fed > 0
    assert len(node0.ledger.epochs) >= 3
    assert node0.ledger.head.stake_of(victim.pk) == 0
    assert node0.forks_detected > 0

    events = [node0.hg[e] for e in node0.order_added]
    results = run_all_engines(
        events, node0._genesis_members, node0._genesis_stake,
        sim.config, chunk=32,
    )
    assert set(results) == set(ENGINES)
    ref = results["oracle"]
    assert len(ref.ledger.epochs) >= 3
    for res in results.values():
        assert res.order == ref.order
        assert res.rounds == ref.rounds
        assert res.ledger.same_epochs(ref.ledger)
    # streaming rows are epoch-stamped and actually span the epochs
    stamped = results["streaming"].archive_epochs
    assert stamped is not None and len(stamped) == len(ref.order)
    assert len({epoch for _, epoch in stamped}) >= 2
    # mesh re-pins the member axis once per epoch
    pins = results["mesh"].shard_pins
    assert pins is not None and len(pins) == len(ref.ledger.epochs)
    assert len(pins[-1]) == len(ref.ledger.head.members)
    # every device engine repacked once per post-genesis epoch
    for e in ("batch", "incremental", "streaming", "mesh"):
        assert len(results[e].repacks) == len(ref.ledger.epochs) - 1


def test_restatement_determinism():
    """Batch ingest assigns every round before any membership tx
    decides, forcing the restatement path; the result must still be
    bit-identical to the per-event oracle replay, and independent of a
    different (topologically valid) arrival order."""
    events, members, stake, sim = churn_schedule(4, seed=3, turns=420)
    oracle = run_dynamic(
        events, members, stake, sim.config, engine="oracle"
    )
    batch = run_dynamic(
        events, members, stake, sim.config, engine="batch",
        cross_check=False,
    )
    assert len(oracle.ledger.epochs) >= 3
    assert batch.restatements >= 1
    assert batch.order == oracle.order
    assert batch.rounds == oracle.rounds
    assert batch.ledger.same_epochs(oracle.ledger)

    # alternative topo order: Kahn's algorithm draining the ready set in
    # reversed (creator, timestamp) order — same DAG, different arrival
    # sequence
    import collections

    by_id = {e.id: e for e in events}
    children = collections.defaultdict(list)
    indeg = {}
    for ev in events:
        indeg[ev.id] = sum(1 for p in (ev.p or ()) if p in by_id)
        for p in ev.p or ():
            if p in by_id:
                children[p].append(ev.id)
    ready = [e.id for e in events if indeg[e.id] == 0]
    alt = []
    while ready:
        ready.sort(key=lambda i: (by_id[i].c, by_id[i].t), reverse=True)
        x = ready.pop(0)
        alt.append(by_id[x])
        for ch in children[x]:
            indeg[ch] -= 1
            if indeg[ch] == 0:
                ready.append(ch)
    assert [e.id for e in alt] != [e.id for e in events]
    again = run_dynamic(
        alt, members, stake, sim.config, engine="oracle"
    )
    assert again.order == oracle.order
    assert again.rounds == oracle.rounds
    assert again.ledger.same_epochs(oracle.ledger)


# --------------------------------------------------------- checkpointing


def test_checkpoint_epoch_ledger_roundtrip_and_tamper(tmp_path):
    from tpu_swirld.checkpoint import load_node, save_node
    from tpu_swirld.membership.dynamic import DynamicNode

    events, members, stake, sim = churn_schedule(4, seed=1, turns=420)
    node = sim.nodes[0]
    assert len(node.ledger.epochs) >= 2
    path = str(tmp_path / "dyn.ckpt")
    save_node(path, node)

    restored = load_node(path, sk=node.sk, pk=node.pk, network={})
    assert isinstance(restored, DynamicNode)
    assert restored.ledger.same_epochs(node.ledger)
    assert restored.consensus == node.consensus
    assert restored.membership_epoch == node.membership_epoch

    # tamper: re-stamp a *consistent* but wrong ledger into the header —
    # the replay-derived epoch sequence is the only accepted truth
    with open(path, "rb") as f:
        data = f.read()
    (hlen,) = struct.unpack_from("<I", data, 4)
    meta = json.loads(data[8 : 8 + hlen].decode())
    head = node.ledger.head
    forged = EpochLedger(
        epochs=node.ledger.epochs[:-1] + (
            MemberEpoch(
                epoch_id=head.epoch_id,
                activation_round=head.activation_round,
                members=head.members,
                stake=(99,) + head.stake[1:],
            ),
        )
    )
    meta["membership"].update(forged.to_meta())
    header = json.dumps(meta).encode()
    bad = str(tmp_path / "tampered.ckpt")
    with open(bad, "wb") as f:
        f.write(b"SWCK" + struct.pack("<I", len(header)) + header
                + data[8 + hlen:])
    with pytest.raises(ValueError, match="epoch ledger"):
        load_node(bad, sk=node.sk, pk=node.pk, network={})


# ----------------------------------------------- soak window + gauges


def test_membership_window_dict_roundtrip():
    from tpu_swirld.soak import (
        MembershipWindow, window_from_dict, window_to_dict,
    )

    for w in [
        MembershipWindow(at_s=2.5, action="restake", member=1, stake=3),
        MembershipWindow(at_s=4.0, action="leave", member=2),
    ]:
        d = window_to_dict(w)
        assert window_from_dict(json.loads(json.dumps(d))) == w


def test_node_gauges_membership_surface():
    from tpu_swirld.metrics import node_gauges
    from tpu_swirld.sim import make_simulation

    static = make_simulation(4, seed=0)
    static.run(10)
    g = node_gauges(static.nodes[0])
    # static nodes report the trivial single-epoch values (genesis id 0)
    assert g["membership_epoch"] == 0
    assert g["members_active"] == 4
    assert g["stake_total"] == static.nodes[0].tot_stake

    dyn = make_dynamic_simulation(4, seed=0)
    dyn.tx_schedule[10] = restake_payload(sim_member(4, 0, 1), 4)
    dyn.run(150)
    node = max(dyn.nodes, key=lambda x: len(x.consensus))
    g = node_gauges(node)
    assert g["membership_epoch"] == node.ledger.head.epoch_id
    assert g["stake_total"] == node.ledger.head.total_stake


@pytest.mark.smoke
def test_obs_report_membership_section():
    """The report CLI renders the membership gauges in their own
    section (single-trace view) and per-node rows (fleet view)."""
    from tpu_swirld.obs.registry import Registry
    from tpu_swirld.obs.report import render_cluster_report, render_report
    from tpu_swirld.metrics import node_gauges
    from tpu_swirld.sim import make_simulation

    sim = make_simulation(4, seed=0)
    sim.run(10)
    reg = Registry()
    node_gauges(sim.nodes[0], registry=reg)
    events = []
    for s in reg.to_samples():
        args = dict(s.get("labels") or {})
        args["value"] = s["value"]
        events.append({"ph": "C", "name": s["name"], "args": args})
    out = render_report(events)
    assert "== membership (epoch / active members / stake) ==" in out
    section = out.split("== membership")[1]
    for name in ("node_membership_epoch", "node_members_active",
                 "node_stake_total"):
        assert name in section


def test_obs_cluster_report_membership_rows(tmp_path):
    from tpu_swirld.obs.report import render_cluster_report

    with open(tmp_path / "node-0.report.json", "w") as f:
        json.dump({"node": 0, "membership_epoch": 2,
                   "membership_epochs": 3, "members_active": 5,
                   "stake_total": 9, "decided": []}, f)
    out = render_cluster_report(str(tmp_path))
    assert "== membership (per node) ==" in out
    assert "epoch=2 epochs_decided=3 members_active=5 stake_total=9" in out


# --------------------------------------------------- lint-scope pinning


@pytest.mark.analysis
def test_sw002_scope_covers_membership():
    from tpu_swirld.analysis import check_source

    bad = 's = {b"a", b"b"}\nfor x in s:\n    pass\n'
    findings = check_source(bad, module_path="membership/epoch.py")
    assert "SW002" in [f.rule for f in findings]


@pytest.mark.analysis
def test_sw007_scope_covers_membership():
    from tpu_swirld.analysis import check_source

    bad = "def f(x):\n    assert x > 0\n    return x\n"
    findings = check_source(bad, module_path="membership/dynamic.py")
    assert "SW007" in [f.rule for f in findings]


# -------------------------------------------------- chaos acceptance


@pytest.mark.chaos
def test_membership_churn_scenario(tmp_path):
    """join → equivocation storm → voted out, with all five engines
    bit-identical on the surviving DAG (the full acceptance storm; the
    fast tier covers each gate piecewise above)."""
    from tpu_swirld.adversary import SCENARIOS

    v = SCENARIOS["membership_churn"](str(tmp_path))
    assert v["ok"], v
    m = v["membership"]
    assert m["joined"] and m["voted_out"]
    assert m["epochs"] >= 3
    assert m["witness_gating_ok"]
    assert v["engines"]["parity"]
