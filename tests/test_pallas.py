"""Pallas SSM kernel: interpret-mode parity with the XLA ssm_matrix."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation, run_with_forkers
from tpu_swirld.tpu.pallas_kernels import ssm_matrix_pallas
from tpu_swirld.tpu.pipeline import (
    ancestry, forkseen_matrix, sees_matrix, ssm_matrix,
)

INTERPRET = jax.default_backend() != "tpu"


def _sees_from_sim(n_nodes, turns, seed, forkers=0):
    if forkers:
        sim = run_with_forkers(n_nodes, forkers, turns, seed=seed)
    else:
        sim = make_simulation(n_nodes, seed=seed)
        sim.run(turns)
    node = sim.nodes[0]
    packed = pack_node(node)
    n = packed.n
    n_pad = ((n + 127) // 128) * 128
    parents = np.concatenate(
        [packed.parents, np.full((n_pad - n, 2), -1, np.int32)]
    )
    anc = ancestry(jnp.asarray(parents), block=128, matmul_dtype=jnp.float32)
    creator = np.concatenate(
        [packed.creator, np.zeros((n_pad - n,), np.int32)]
    )
    fseen = forkseen_matrix(
        anc, jnp.asarray(packed.fork_pairs), packed.n_members, jnp.float32
    )
    sees = sees_matrix(anc, fseen, jnp.asarray(creator))
    return packed, sees


def test_pallas_ssm_matches_xla():
    packed, sees = _sees_from_sim(5, 220, seed=3)
    tot = int(packed.stake.sum())
    want = ssm_matrix(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32,
    )
    got = ssm_matrix_pallas(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32, tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_pallas_ssm_matches_xla_with_forks_and_stake():
    packed, sees = _sees_from_sim(7, 260, seed=9, forkers=2)
    assert len(packed.fork_pairs) > 0
    tot = int(packed.stake.sum())
    want = ssm_matrix(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32,
    )
    got = ssm_matrix_pallas(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32, tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_full_pipeline_with_pallas_ssm_parity():
    """End-to-end: run_consensus with the Pallas SSM seam, oracle parity."""
    from tpu_swirld.tpu.pipeline import run_consensus
    from tests.test_pipeline import assert_parity

    sim = make_simulation(5, seed=17)
    sim.run(250)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(
        packed, node.config, block=128, use_pallas_ssm=True
    )
    assert_parity(node, packed, result)


def test_pallas_ssm_block_matches_xla_block():
    """The Pallas block kernel must equal the XLA ssm_block_stage exactly
    — same sees-slab gathers, same member hops — at ragged edge shapes:
    a row suffix that is not tile-aligned, a single-column batch, and a
    full-height block."""
    from tpu_swirld.tpu.pallas_kernels import ssm_block_pallas
    from tpu_swirld.tpu.pipeline import ssm_block_stage

    packed, sees = _sees_from_sim(5, 220, seed=3)
    tot = int(packed.stake.sum())
    n = sees.shape[0]
    mt = jnp.asarray(packed.member_table)
    stake = jnp.asarray(packed.stake)
    picks = np.linspace(0, packed.n - 1, 100).astype(np.int32)
    cases = [
        (0, n, np.concatenate([picks, np.full(28, -1, np.int32)])),
        (n - 128, 128, picks[:16]),            # suffix block
        (n - 64, 64, picks[:16]),              # sub-tile suffix
        # odd offset + the driver's minimum column batch (one real column
        # bucketed to 16 — the single-event-chunk shape)
        (32, 96, np.concatenate([picks[:1], np.full(15, -1, np.int32)])),
    ]
    for row0, rows, cols in cases:
        want = ssm_block_stage(
            sees, mt, stake, jnp.asarray(cols), np.int32(row0), rows=rows,
            tot_stake=tot, matmul_dtype_name="float32",
        )
        got = ssm_block_pallas(
            sees, mt, stake, jnp.asarray(cols), np.int32(row0), rows=rows,
            tot_stake=tot, matmul_dtype_name="float32",
            tile_m=128, tile_n=128, interpret=INTERPRET,
        )
        assert (np.asarray(got) == np.asarray(want)).all(), (row0, rows)


def test_pallas_bmm_matches_xla():
    """The tiled boolean-matmul hop (ancestry extension) is exact against
    the straight XLA matmul, including a non-128 contraction axis."""
    from tpu_swirld.tpu.pallas_kernels import bmm_or_pallas
    from tpu_swirld.tpu.pipeline import _bmm

    rng = np.random.default_rng(5)
    for p, q, r in [(128, 128, 256), (64, 96, 128), (128, 64, 512)]:
        a = jnp.asarray(rng.random((p, q)) < 0.1)
        b = jnp.asarray(rng.random((q, r)) < 0.1)
        want = _bmm(a, b, jnp.float32)
        got = bmm_or_pallas(a, b, jnp.float32, interpret=INTERPRET)
        assert (np.asarray(got) == np.asarray(want)).all(), (p, q, r)


def test_incremental_with_pallas_block_parity():
    """IncrementalConsensus with the full Pallas extension-kernel bundle
    (ancestry bmm hop + strongly-sees block) as its hot-path backend:
    bit-parity with full recompute."""
    from tpu_swirld.tpu.pallas_kernels import make_extension_kernels
    from tpu_swirld.tpu.pipeline import IncrementalConsensus, run_consensus

    # 5 members + a forker: the forked fused stage's one-hot hop is only
    # n_members wide, which the bmm grid cannot tile — it must fall back
    # to the XLA matmul instead of crashing (small-network regression)
    sim = run_with_forkers(5, 1, 220, seed=17)
    node = sim.nodes[0]
    packed = pack_node(node)
    assert len(packed.fork_pairs) > 0
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    inc = IncrementalConsensus(
        node.members, stake, node.config, block=64, chunk=64,
        window_bucket=256, prune_min=64,
        extension_kernels=make_extension_kernels(
            interpret=INTERPRET, tile_m=128, tile_n=128
        ),
    )
    for i in range(0, len(events), 80):
        inc.ingest(events[i : i + 80])
    res = inc.result()
    ref = run_consensus(packed, node.config, block=64)
    assert res.order == ref.order
    assert res.famous == ref.famous
    assert (res.round == ref.round).all()
