"""Pallas SSM kernel: interpret-mode parity with the XLA ssm_matrix."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation, run_with_forkers
from tpu_swirld.tpu.pallas_kernels import ssm_matrix_pallas
from tpu_swirld.tpu.pipeline import (
    ancestry, forkseen_matrix, sees_matrix, ssm_matrix,
)

INTERPRET = jax.default_backend() != "tpu"


def _sees_from_sim(n_nodes, turns, seed, forkers=0):
    if forkers:
        sim = run_with_forkers(n_nodes, forkers, turns, seed=seed)
    else:
        sim = make_simulation(n_nodes, seed=seed)
        sim.run(turns)
    node = sim.nodes[0]
    packed = pack_node(node)
    n = packed.n
    n_pad = ((n + 127) // 128) * 128
    parents = np.concatenate(
        [packed.parents, np.full((n_pad - n, 2), -1, np.int32)]
    )
    anc = ancestry(jnp.asarray(parents), block=128, matmul_dtype=jnp.float32)
    creator = np.concatenate(
        [packed.creator, np.zeros((n_pad - n,), np.int32)]
    )
    fseen = forkseen_matrix(
        anc, jnp.asarray(packed.fork_pairs), packed.n_members, jnp.float32
    )
    sees = sees_matrix(anc, fseen, jnp.asarray(creator))
    return packed, sees


def test_pallas_ssm_matches_xla():
    packed, sees = _sees_from_sim(5, 220, seed=3)
    tot = int(packed.stake.sum())
    want = ssm_matrix(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32,
    )
    got = ssm_matrix_pallas(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32, tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_pallas_ssm_matches_xla_with_forks_and_stake():
    packed, sees = _sees_from_sim(7, 260, seed=9, forkers=2)
    assert len(packed.fork_pairs) > 0
    tot = int(packed.stake.sum())
    want = ssm_matrix(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32,
    )
    got = ssm_matrix_pallas(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32, tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_full_pipeline_with_pallas_ssm_parity():
    """End-to-end: run_consensus with the Pallas SSM seam, oracle parity."""
    from tpu_swirld.tpu.pipeline import run_consensus
    from tests.test_pipeline import assert_parity

    sim = make_simulation(5, seed=17)
    sim.run(250)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(
        packed, node.config, block=128, use_pallas_ssm=True
    )
    assert_parity(node, packed, result)
