"""Pallas SSM kernel: interpret-mode parity with the XLA ssm_matrix."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation, run_with_forkers
from tpu_swirld.tpu.pallas_kernels import ssm_matrix_pallas
from tpu_swirld.tpu.pipeline import (
    ancestry, forkseen_matrix, sees_matrix, ssm_matrix,
)

INTERPRET = jax.default_backend() != "tpu"


def _sees_from_sim(n_nodes, turns, seed, forkers=0):
    if forkers:
        sim = run_with_forkers(n_nodes, forkers, turns, seed=seed)
    else:
        sim = make_simulation(n_nodes, seed=seed)
        sim.run(turns)
    node = sim.nodes[0]
    packed = pack_node(node)
    n = packed.n
    n_pad = ((n + 127) // 128) * 128
    parents = np.concatenate(
        [packed.parents, np.full((n_pad - n, 2), -1, np.int32)]
    )
    anc = ancestry(jnp.asarray(parents), block=128, matmul_dtype=jnp.float32)
    creator = np.concatenate(
        [packed.creator, np.zeros((n_pad - n,), np.int32)]
    )
    fseen = forkseen_matrix(
        anc, jnp.asarray(packed.fork_pairs), packed.n_members, jnp.float32
    )
    sees = sees_matrix(anc, fseen, jnp.asarray(creator))
    return packed, sees


def test_pallas_ssm_matches_xla():
    packed, sees = _sees_from_sim(5, 220, seed=3)
    tot = int(packed.stake.sum())
    want = ssm_matrix(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32,
    )
    got = ssm_matrix_pallas(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32, tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_pallas_ssm_matches_xla_with_forks_and_stake():
    packed, sees = _sees_from_sim(7, 260, seed=9, forkers=2)
    assert len(packed.fork_pairs) > 0
    tot = int(packed.stake.sum())
    want = ssm_matrix(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32,
    )
    got = ssm_matrix_pallas(
        sees, jnp.asarray(packed.member_table), jnp.asarray(packed.stake),
        tot, jnp.float32, tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_full_pipeline_with_pallas_ssm_parity():
    """End-to-end: run_consensus with the Pallas SSM seam, oracle parity."""
    from tpu_swirld.tpu.pipeline import run_consensus
    from tests.test_pipeline import assert_parity

    sim = make_simulation(5, seed=17)
    sim.run(250)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(
        packed, node.config, block=128, use_pallas_ssm=True
    )
    assert_parity(node, packed, result)


def test_pallas_ssm_cols_matches_xla_cols():
    """The Pallas column kernel must equal the XLA ssm_cols_stage exactly
    over the same pre-gathered member slabs."""
    from tpu_swirld.tpu.pallas_kernels import ssm_cols_pallas
    from tpu_swirld.tpu.pipeline import member_slabs, ssm_cols_stage

    packed, sees = _sees_from_sim(5, 220, seed=3)
    tot = int(packed.stake.sum())
    a3, b3 = member_slabs(sees, jnp.asarray(packed.member_table))
    n = sees.shape[0]
    cols = np.full((128,), -1, np.int32)
    picks = np.linspace(0, packed.n - 1, 100).astype(np.int32)
    cols[: len(picks)] = picks
    want = ssm_cols_stage(
        a3, b3, jnp.asarray(packed.stake), jnp.asarray(cols),
        tot_stake=tot, matmul_dtype_name="float32",
    )
    got = ssm_cols_pallas(
        a3, b3, jnp.asarray(packed.stake), jnp.asarray(cols),
        tot_stake=tot, matmul_dtype_name="float32",
        tile_m=128, tile_n=128, interpret=INTERPRET,
    )
    assert (np.asarray(got) == np.asarray(want)).all()


def test_incremental_with_pallas_cols_parity():
    """IncrementalConsensus with the Pallas column kernel as its
    strongly-sees backend: bit-parity with full recompute."""
    from tpu_swirld.tpu.pallas_kernels import make_ssm_cols_fn
    from tpu_swirld.tpu.pipeline import IncrementalConsensus, run_consensus

    sim = make_simulation(5, seed=17)
    sim.run(220)
    node = sim.nodes[0]
    packed = pack_node(node)
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    inc = IncrementalConsensus(
        node.members, stake, node.config, block=64, chunk=64,
        window_bucket=256, prune_min=64,
        ssm_cols_fn=make_ssm_cols_fn(interpret=INTERPRET),
    )
    for i in range(0, len(events), 80):
        inc.ingest(events[i : i + 80])
    res = inc.result()
    ref = run_consensus(packed, node.config, block=64)
    assert res.order == ref.order
    assert res.famous == ref.famous
    assert (res.round == ref.round).all()
