"""Static-analysis & sanitizer suite (tpu_swirld.analysis).

Three layers, mirroring the subsystem:

- per-rule fixtures: every linter rule catches a minimal bad snippet and
  passes its fixed twin (plus suppression-comment and scope behavior);
- the acceptance gates: the package itself lints clean (every future PR
  inherits this), the jit auditor pins zero steady-state recompiles and
  zero signature drift at the shape buckets, and the race sanitizer's
  32-schedule archive fuzz holds digest equality + lock-graph acyclicity;
- sanitizer sensitivity: a deliberately-seeded lost-update fixture and an
  opposite-order lock pair must both be *caught*.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

from tpu_swirld.analysis import check_source, lint_paths
from tpu_swirld.analysis import jit_audit, races
from tpu_swirld.analysis.races import (
    Injector, LockOrderGraph, TrackedLock, injection, run_archive_schedules,
    run_schedules,
)

pytestmark = pytest.mark.analysis

_ROOT = os.path.join(os.path.dirname(__file__), "..")
_PKG = os.path.join(_ROOT, "tpu_swirld")


def _rules(findings):
    return [f.rule for f in findings]


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------- per-rule fixtures


def test_sw001_unseeded_rng():
    bad = "import random\nx = random.randrange(10)\n"
    assert "SW001" in _rules(check_source(bad))
    fixed = "import random\nrng = random.Random(7)\nx = rng.randrange(10)\n"
    assert check_source(fixed) == []
    # unseeded constructors are findings; seeded ones are the fix
    assert "SW001" in _rules(check_source("r = random.Random()\n"))
    assert "SW001" in _rules(check_source(
        "import numpy as np\ng = np.random.default_rng()\n"
    ))
    assert check_source(
        "import numpy as np\ng = np.random.default_rng(3)\n"
    ) == []
    assert "SW001" in _rules(check_source(
        "import numpy as np\nx = np.random.rand(4)\n"
    ))


def test_sw002_unordered_iter_scoped():
    bad = 's = {b"a", b"b"}\nfor x in s:\n    pass\n'
    assert "SW002" in _rules(
        check_source(bad, module_path="oracle/node.py")
    )
    # same snippet outside the consensus-critical scope: not a finding
    assert check_source(bad, module_path="sim.py") == []
    fixed = 's = {b"a", b"b"}\nfor x in sorted(s):\n    pass\n'
    assert check_source(fixed, module_path="oracle/node.py") == []
    # order-insensitive consumers are fine; order-sensitive ones are not
    assert check_source(
        "s = set()\nn = len(s)\nm = max(s)\n", module_path="oracle/node.py"
    ) == []
    assert "SW002" in _rules(check_source(
        "s = set()\nl = list(s)\n", module_path="oracle/node.py"
    ))
    assert "SW002" in _rules(check_source(
        "s = set()\nout = []\nout.extend(s)\n",
        module_path="oracle/node.py",
    ))


def test_sw003_wall_clock_scoped():
    bad = "import time\nt = time.time()\ntime.sleep(0.1)\n"
    f = check_source(bad, module_path="transport.py")
    assert _rules(f).count("SW003") == 2
    # the obs layer is allowed to read clocks
    assert check_source(bad, module_path="obs/tracer.py") == []
    fixed = "ticks = 0\nticks += 1\n"
    assert check_source(fixed, module_path="transport.py") == []


def test_sw004_dtype_discipline():
    bad = "import numpy as np\nidx = np.arange(10)\n"
    assert "SW004" in _rules(
        check_source(bad, module_path="tpu/pipeline.py")
    )
    fixed = "import numpy as np\nidx = np.arange(10, dtype=np.int32)\n"
    assert check_source(fixed, module_path="tpu/pipeline.py") == []
    assert "SW004" in _rules(check_source(
        "import numpy as np\nz = np.zeros((2, 2))\n",
        module_path="store/archive.py",
    ))
    # dtype=bool IS np.bool_ (1 byte everywhere) — explicitly allowed
    assert check_source(
        "import numpy as np\nz = np.zeros((2, 2), dtype=bool)\n",
        module_path="store/archive.py",
    ) == []
    assert "SW004" in _rules(check_source(
        "x = y.astype(int)\n", module_path="tpu/pipeline.py"
    ))
    assert "SW004" in _rules(check_source(
        "import numpy as np\nz = np.zeros(4, dtype=int)\n",
        module_path="parallel.py",
    ))
    # out of scope: host-side sim code may use numpy defaults
    assert check_source(bad, module_path="sim.py") == []


_DONATED_STAGE = """\
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def stage(buf, x):
    return buf + x
"""


def test_sw005_donation_read_after_donate():
    bad = _DONATED_STAGE + """
def user(buf, x):
    out = stage(buf, x)
    return buf.sum()
"""
    assert "SW005" in _rules(check_source(bad))
    fixed = _DONATED_STAGE + """
def user(buf, x):
    buf = stage(buf, x)
    return buf.sum()
"""
    assert check_source(fixed) == []


def test_sw005_donation_through_stage_call():
    bad = _DONATED_STAGE + """
from tpu_swirld import obs

def user(self, x):
    out = obs.stage_call("s", stage, self._anc_d, x)
    return self._anc_d.sum()
"""
    assert "SW005" in _rules(check_source(bad))
    # the package idiom: rebind in the same statement
    fixed = _DONATED_STAGE + """
from tpu_swirld import obs

def user(self, x):
    self._anc_d = obs.stage_call("s", stage, self._anc_d, x)
    return self._anc_d.sum()
"""
    assert check_source(fixed) == []


def test_sw006_worker_guarded_attrs():
    bad = """\
import threading

class W:
    def __init__(self):
        self.count = 0

    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        self.count += 1
"""
    assert "SW006" in _rules(check_source(bad))
    fixed = bad.replace(
        "class W:\n",
        'class W:\n    GUARDED_ATTRS = frozenset({"count"})\n\n',
    )
    assert check_source(fixed) == []


def test_sw007_load_bearing_assert_scoped():
    bad = "def f(x):\n    assert x > 0, 'positive'\n    return x\n"
    assert "SW007" in _rules(check_source(bad, module_path="oracle/node.py"))
    assert "SW007" in _rules(check_source(bad, module_path="tpu/pipeline.py"))
    # tests/benches keep their asserts — out of the production scope
    assert check_source(bad, module_path="sim.py") == []
    fixed = (
        "def f(x):\n"
        "    if not x > 0:\n"
        "        raise ValueError('positive')\n"
        "    return x\n"
    )
    assert check_source(fixed, module_path="oracle/node.py") == []


def test_suppression_comment():
    bad = (
        "s = set()\n"
        "for x in s:   # swirld-lint: disable=SW002\n"
        "    pass\n"
    )
    assert check_source(bad, module_path="oracle/node.py") == []
    by_name = (
        "s = set()\n"
        "for x in s:   # swirld-lint: disable=unordered-iter\n"
        "    pass\n"
    )
    assert check_source(by_name, module_path="oracle/node.py") == []


# ------------------------------------------------------ acceptance gates


def test_package_lints_clean():
    """The tier-1 gate from the issue: `python -m tpu_swirld.analysis
    lint tpu_swirld/` exits 0 on this tree — every future PR inherits
    the invariant rules."""
    findings = lint_paths([_PKG])
    assert findings == [], "\n".join(f.render() for f in findings)


@pytest.mark.smoke
def test_lint_cli_smoke(tmp_path):
    """The module CLI: exit 0 on the package, exit 1 on a bad file."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "tpu_swirld.analysis", "lint", _PKG,
         "--json"],
        capture_output=True, text=True, env=env, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout)["clean"] is True
    bad = tmp_path / "tpu_swirld" / "oracle" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("s = set()\nfor x in s:\n    pass\n")
    r = subprocess.run(
        [sys.executable, "-m", "tpu_swirld.analysis", "lint", str(bad)],
        capture_output=True, text=True, env=env, cwd=_ROOT,
    )
    assert r.returncode == 1
    assert "SW002" in r.stdout


def test_static_jit_audit_clean():
    assert jit_audit.static_audit(_ROOT) == []


def test_static_jit_audit_catches_host_sync(tmp_path):
    root = tmp_path
    mod = root / "tpu_swirld" / "tpu" / "pipeline.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import functools, jax\n"
        "import numpy as np\n"
        "@functools.partial(jax.jit)\n"
        "def stage(x):\n"
        "    return np.asarray(x).sum()\n"
    )
    findings = jit_audit.static_audit(str(root))
    assert findings and findings[0]["stage"] == "stage"


def test_find_drift_unit():
    same = ("arr", (4, 4), "int32", False)
    weak = ("arr", (4, 4), "int32", True)
    other = ("arr", (8, 4), "int32", False)
    assert jit_audit._find_drift({"s": [(same,), (same,)]}) == []
    # same shape, weak_type flip -> drift
    drift = jit_audit._find_drift({"s": [(same,), (weak,)]})
    assert len(drift) == 1 and drift[0]["stage"] == "s"
    # different shapes are bucketed, not drift
    assert jit_audit._find_drift({"s": [(same,), (other,)]}) == []


def test_jit_audit_zero_steady_recompiles():
    """The PR-8 shape buckets hold: the audited steady-state window adds
    zero jit-cache entries and every stage keeps a drift-free abstract
    signature (a weak_type flip would recompile at identical shapes)."""
    r = jit_audit.runtime_audit()
    assert r["engine"] == "incremental"
    assert r["steady_compiles"] == {}, r
    assert r["signature_drift"] == [], r
    assert r["ok"] and r["stages_observed"]


def test_jit_audit_rejects_unknown_engine():
    with pytest.raises(ValueError, match="unknown engine"):
        jit_audit.runtime_audit(engine="warp")


@pytest.mark.slow
def test_jit_audit_streaming_engine():
    """--engine streaming: the slab-store retire/fetch stages join the
    audited set and the steady window stays recompile- and drift-free."""
    r = jit_audit.runtime_audit(engine="streaming")
    assert r["engine"] == "streaming"
    assert r["ok"], r


def test_archive_schedule_fuzz_32():
    """The acceptance fuzz: 32 seeded schedules of concurrent
    spill/fetch/checkpoint produce bit-identical digests, match the
    synchronous reference (the async==sync archive pin), and keep the
    lock-order graph acyclic."""
    rep = run_archive_schedules(n_schedules=32)
    assert rep["schedules"] >= 32
    assert rep["digests_identical"], rep
    assert rep["matches_sync"], rep
    assert rep["acyclic"], rep["cycle"]
    assert rep["ok"]


# -------------------------------------------------- sanitizer sensitivity


class RacyCounter:
    """Deliberate lost-update fixture: the read and the write of
    ``value`` are separated by a sanitizer yield point, exactly where an
    unlocked real implementation would have its preemption window."""

    def __init__(self):
        self.value = 0

    def incr(self):
        v = self.value
        races.yield_point("racy.read")
        self.value = v + 1


def test_race_sanitizer_detects_seeded_lost_update():
    def run(i):
        c = RacyCounter()
        gate = threading.Barrier(2)

        def worker():
            gate.wait()
            for _ in range(200):
                c.incr()

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return c.value

    rep = run_schedules(run, n_schedules=8, seed=1)
    lost = any(v != 400 for v in rep["results"])
    assert lost or not rep["deterministic"], (
        f"sanitizer failed to expose the seeded race: {rep}"
    )


def test_lock_order_graph_detects_cycle():
    g = LockOrderGraph()
    a, b = TrackedLock("A", g), TrackedLock("B", g)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cyc = g.cycle()
    assert cyc is not None and set(cyc) >= {"A", "B"}
    # and a consistent order stays acyclic
    g2 = LockOrderGraph()
    a2, b2 = TrackedLock("A", g2), TrackedLock("B", g2)
    for _ in range(2):
        with a2:
            with b2:
                pass
    assert g2.cycle() is None


def test_injector_is_seeded():
    """Same seed -> same injection decisions (schedules replay)."""
    fires = []
    for _ in range(2):
        inj = Injector(seed=42)
        with injection(inj):
            for i in range(100):
                races.yield_point(f"t{i}")
        fires.append(inj.fired)
    assert fires[0] == fires[1] and inj.points == 100


# ------------------------------------------------------- tooling wiring


@pytest.mark.smoke
def test_chaos_run_sanitize_smoke(tmp_path):
    """scripts/chaos_run.py --sanitize: the verdict gains a sanitizer
    section whose schedules all reproduced the base safety verdict."""
    mod = _load_script("chaos_run")
    out = tmp_path / "verdict.json"
    rc = mod.main([
        "--seed", "3", "--plan-seed", "3", "--nodes", "4",
        "--turns", "120", "--forkers", "0", "--checkpoint-every", "40",
        "--sanitize", "2", "--out", str(out),
    ])
    assert rc == 0
    v = json.loads(out.read_text())
    san = v["sanitizer"]
    assert san["schedules"] == 2
    assert san["verdicts_stable"] and san["all_ok"]
    assert san["archive"]["digests_identical"]
    assert san["archive"]["acyclic"]
    assert san["ok"] and v["ok"]


def test_bench_compare_refuses_dirty_lint(tmp_path):
    """bench_compare.py: a candidate stamped with lint findings is not
    gated; a clean stamp is (legacy lint-stamp-less artifacts pass the
    lint gate, though the scale_audit gate is strict — see
    test_bench_compare_refuses_missing_scale_audit)."""
    mod = _load_script("bench_compare")
    sa = {"envelope": "baseline", "clean": True, "findings": 0}
    old = tmp_path / "old.json"
    old.write_text(json.dumps({"value": 100.0}))

    dirty = tmp_path / "dirty.json"
    dirty.write_text(json.dumps({
        "value": 120.0,
        "lint": {"findings": 2, "clean": False, "by_rule": {"SW002": 2}},
        "scale_audit": sa,
    }))
    assert mod.main([str(old), str(dirty)]) == 1

    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps({
        "value": 101.0,
        "lint": {"findings": 0, "clean": True, "by_rule": {}},
        "scale_audit": sa,
    }))
    assert mod.main([str(old), str(clean)]) == 0
    # a lint-stamp-less candidate still passes the *lint* gate
    nostamp = tmp_path / "nostamp.json"
    nostamp.write_text(json.dumps({"value": 100.0, "scale_audit": sa}))
    assert mod.main([str(old), str(nostamp)]) == 0


def test_bench_lint_stamp_shape():
    """bench.py's stamp helper emits the summary shape bench_compare
    gates on, and it is clean on this tree."""
    sys.path.insert(0, _ROOT)
    try:
        import bench
        stamp = bench.lint_stamp()
    finally:
        sys.path.remove(_ROOT)
    assert stamp == {"findings": 0, "clean": True, "by_rule": {}}


def test_bench_compare_refuses_dirty_mc(tmp_path):
    """bench_compare.py: a candidate whose model-checker smoke stamp is
    dirty is not gated; a clean stamp and an mc-stamp-less artifact
    are."""
    mod = _load_script("bench_compare")
    sa = {"envelope": "baseline", "clean": True, "findings": 0}
    old = tmp_path / "old.json"
    old.write_text(json.dumps({"value": 100.0}))

    dirty = tmp_path / "dirty.json"
    dirty.write_text(json.dumps({
        "value": 120.0,
        "mc": {"ok": False, "violations": 1, "exhaustive": True},
        "scale_audit": sa,
    }))
    assert mod.main([str(old), str(dirty)]) == 1

    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps({
        "value": 101.0,
        "mc": {"ok": True, "violations": 0, "exhaustive": True},
        "scale_audit": sa,
    }))
    assert mod.main([str(old), str(clean)]) == 0
    # pre-mc artifacts pass the mc gate on metrics alone
    nostamp = tmp_path / "nostamp.json"
    nostamp.write_text(json.dumps({"value": 100.0, "scale_audit": sa}))
    assert mod.main([str(old), str(nostamp)]) == 0


def test_bench_mc_stamp_shape():
    """bench.py's model-checker stamp: the exhaustive smoke world is
    clean on this tree and carries the ratio bench_compare reports."""
    sys.path.insert(0, _ROOT)
    try:
        import bench
        stamp = bench.mc_stamp()
    finally:
        sys.path.remove(_ROOT)
    assert stamp["ok"], stamp
    assert stamp["exhaustive"] and stamp["violations"] == 0
    assert stamp["state_ratio"] > 2
