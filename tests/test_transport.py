"""Transport layer: fault injection, retry/backoff, circuit breaker, and
malformed-payload hardening.

Everything here is deterministic and sleep-free: fault streams come from
``Random(plan.seed)``, backoff delays are *recorded* (logical time), and
the circuit breaker runs against a fake clock the test advances by hand.
"""

import dataclasses
import random

import pytest

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.metrics import Metrics
from tpu_swirld.sim import make_simulation, run_with_divergent_forkers
from tpu_swirld.transport import (
    CircuitBreaker,
    FaultPlan,
    FaultyTransport,
    LinkFaults,
    MessageDropped,
    Partition,
    PeerPartitioned,
    PeerUnreachable,
    RetryPolicy,
    Transport,
    TransportError,
)

A, B, C = b"A" * 32, b"B" * 32, b"C" * 32


def _echo_net():
    return {pk: (lambda src, req, _pk=pk: b"reply-from-" + _pk) for pk in (A, B, C)}


# ------------------------------------------------------------- base layer


def test_direct_transport_passthrough_and_unknown_peer():
    t = Transport(_echo_net(), {})
    assert t.call(B, A, "sync", b"x") == b"reply-from-" + A
    with pytest.raises(PeerUnreachable):
        t.call(A, b"Z" * 32, "sync", b"x")
    with pytest.raises(PeerUnreachable):
        t.call(A, B, "want", b"x")   # no want endpoint registered


def test_faulty_transport_is_seed_deterministic():
    def run(seed):
        ft = FaultyTransport(
            _echo_net(), {},
            FaultPlan(seed=seed, default=LinkFaults(
                drop=0.3, corrupt=0.2, duplicate=0.1, reorder=0.2, delay=0.1,
            )),
            [A, B, C], clock=lambda: 0,
        )
        out = []
        for i in range(300):
            try:
                out.append(ft.call(A, B, "sync", b"p%d" % i))
            except TransportError as e:
                out.append(type(e).__name__)
        return out, dict(ft.stats)

    assert run(5) == run(5)
    assert run(5)[0] != run(6)[0]
    # every fault class actually fired at these probabilities
    _, stats = run(5)
    for k in ("drops", "corruptions", "duplicates", "reorders", "delays"):
        assert stats[k] > 0, (k, stats)


def test_partition_window_cuts_cross_group_links_only():
    t = [0]
    ft = FaultyTransport(
        _echo_net(), {},
        FaultPlan(partitions=[Partition(start=10, end=20, group=(0, 1))]),
        [A, B, C], clock=lambda: t[0],
    )
    assert ft.call(A, C, "sync", b"x")       # before the window
    t[0] = 10
    assert ft.call(A, B, "sync", b"x")       # same side of the cut
    with pytest.raises(PeerPartitioned):
        ft.call(A, C, "sync", b"x")          # crosses the cut
    with pytest.raises(PeerPartitioned):
        ft.call(C, B, "sync", b"x")
    t[0] = 20
    assert ft.call(A, C, "sync", b"x")       # healed
    assert ft.stats["partition_blocked"] == 2


def test_crashed_peer_is_unreachable_until_restart():
    ft = FaultyTransport(
        _echo_net(), {}, FaultPlan(), [A, B, C], clock=lambda: 0
    )
    ft.set_down(B)
    with pytest.raises(PeerUnreachable):
        ft.call(A, B, "sync", b"x")
    with pytest.raises(PeerUnreachable):
        ft.call(B, A, "sync", b"x")          # a dead node can't call out
    ft.set_up(B)
    assert ft.call(A, B, "sync", b"x")
    assert ft.stats["crash_blocked"] == 2


def test_corruption_mangles_but_never_crashes():
    ft = FaultyTransport(
        _echo_net(), {},
        FaultPlan(seed=1, default=LinkFaults(corrupt=1.0)),
        [A, B], clock=lambda: 0,
    )
    for i in range(100):
        out = ft.call(A, B, "sync", b"payload")
        assert isinstance(out, bytes)
    assert ft.stats["corruptions"] >= 100    # request and/or reply mangled


def test_duplicates_and_delays_surface_without_reorder_knob():
    """Stashed stale replies must drain even when reorder=0 — otherwise
    duplicate/delay faults are silently inert."""
    ft = FaultyTransport(
        _echo_net(), {},
        FaultPlan(seed=2, default=LinkFaults(duplicate=0.4)),
        [A, B], clock=lambda: 0,
    )
    for _ in range(120):
        ft.call(A, B, "sync", b"p")
    assert ft.stats["duplicates"] > 0
    assert ft.stats["reorders"] > 0    # stale deliveries actually happened


# ---------------------------------------------------------- retry policy


def test_retry_policy_exponential_capped_backoff():
    pol = RetryPolicy(attempts=5, backoff_base=1.0, backoff_cap=8.0, jitter=0.0)
    rng = random.Random(0)
    assert [pol.backoff(i, rng) for i in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
    jpol = dataclasses.replace(pol, jitter=0.5)
    for i in range(5):
        d = jpol.backoff(i, rng)
        base = min(8.0, 2.0 ** i)
        assert base <= d <= base * 1.5


class FlakyTransport(Transport):
    """Fails the first ``fail_first`` calls, then delivers reliably."""

    def __init__(self, network, network_want, fail_first=0):
        super().__init__(network, network_want)
        self.fail_first = fail_first
        self.calls = 0

    def call(self, src, dst, channel, payload):
        self.calls += 1
        if self.calls <= self.fail_first:
            raise MessageDropped("flaky")
        return super().call(src, dst, channel, payload)


def _flaky_sim(fail_first, **cfg_kw):
    holder = {}

    def factory(network, network_want, members, clock):
        holder["ft"] = FlakyTransport(network, network_want, fail_first)
        return holder["ft"]

    config = SwirldConfig(n_members=3, retry_jitter=0.0, **cfg_kw)
    sim = make_simulation(
        3, seed=0, config=config, metrics=True, transport_factory=factory
    )
    return sim, holder["ft"]


def test_pull_retries_with_recorded_backoff_no_sleeps():
    sim, ft = _flaky_sim(0, retry_attempts=4)
    sim.run(6)                         # build up some history reliably
    ft.calls, ft.fail_first = 0, 2     # next two transport calls fail
    node, peer = sim.nodes[0], sim.nodes[1].pk
    delays = []
    node._sleep = delays.append
    got = node.pull(peer)
    assert got is not None             # succeeded on the 3rd attempt
    assert ft.calls == 3
    assert node.retries == 2
    assert delays == [1.0, 2.0]        # exponential, jitter-free, logical
    assert node.backoff_total == 3.0   # accumulated on success paths too
    assert node.metrics.counts["gossip_retries"] == 2
    assert node.metrics.counts["gossip_transport_errors"] == 2
    assert node.metrics.registry.value("gossip_backoff_time") == 3.0


def test_pull_gives_up_at_deadline_without_raising():
    sim, ft = _flaky_sim(0, retry_attempts=6, retry_deadline=2.5)
    sim.run(4)
    ft.calls, ft.fail_first = 0, 10**9   # never recovers
    node, peer = sim.nodes[0], sim.nodes[1].pk
    assert node.pull(peer) == []
    # backoff 1 + 2 = 3 would exceed the 2.5 deadline at the 2nd retry
    assert ft.calls == 2
    assert node.metrics.counts["gossip_deadline_exceeded"] == 1
    assert node.backoff_total == 1.0


# -------------------------------------------------------- circuit breaker


def test_circuit_breaker_open_halfopen_close_transitions():
    t = [0]
    br = CircuitBreaker(
        clock=lambda: t[0], failure_threshold=3,
        misbehavior_threshold=4, cooldown=10.0,
    )
    peer = b"P" * 32
    assert br.allow(peer)
    br.record_failure(peer)
    br.record_failure(peer)
    assert br.allow(peer)              # below threshold: still closed
    br.record_failure(peer)            # third strike: open
    assert br.opens == 1
    assert not br.allow(peer)
    assert br.quarantined() == [peer]
    t[0] = 9
    assert not br.allow(peer)          # cooldown not elapsed
    t[0] = 10
    assert br.allow(peer)              # half-open: one probe admitted
    br.record_failure(peer)            # probe failed: re-open, new cooldown
    assert br.opens == 2
    assert not br.allow(peer)
    t[0] = 25
    assert br.allow(peer)              # probe again
    br.record_success(peer)            # probe succeeded: closed
    assert br.allow(peer)
    assert br.quarantined() == []
    # misbehavior strikes open independently of transport failures
    for _ in range(4):
        br.record_misbehavior(peer)
    assert br.opens == 3 and not br.allow(peer)
    # success while fully open must NOT close the circuit
    br.record_success(peer)
    assert not br.allow(peer) or t[0] != 25


def test_misbehavior_strikes_decay_on_clean_replies():
    """Occasional in-flight corruption (counted as misbehavior at decode)
    must not slowly quarantine an honest peer: one clean reply pays down
    one strike."""
    br = CircuitBreaker(
        clock=lambda: 0, failure_threshold=3,
        misbehavior_threshold=4, cooldown=10.0,
    )
    peer = b"Q" * 32
    for _ in range(40):                # 8% corruption-style interleaving
        br.record_misbehavior(peer)
        br.record_success(peer)
        br.record_success(peer)
    assert br.allow(peer) and br.opens == 0
    # a peer serving mostly garbage still out-runs the decay
    for _ in range(8):
        br.record_misbehavior(peer)
    assert br.opens == 1 and not br.allow(peer)


def test_node_fastfails_quarantined_peer_then_recovers():
    sim, ft = _flaky_sim(
        0, retry_attempts=1, breaker_failures=2, breaker_cooldown=5.0
    )
    sim.run(4)
    node, peer = sim.nodes[0], sim.nodes[1].pk
    ft.calls, ft.fail_first = 0, 2
    assert node.pull(peer) == []       # failure 1
    assert node.pull(peer) == []       # failure 2: breaker opens
    assert node.circuit_opens == 1 and node.quarantined_peers == 1
    calls_before = ft.calls
    assert node.pull(peer) == []       # fast-fail: no transport traffic
    assert ft.calls == calls_before
    assert node.metrics.counts["gossip_circuit_fastfail"] == 1
    sim.clock[0] += 5                  # cooldown elapses (logical clock)
    got = node.pull(peer)              # half-open probe, transport healed
    assert got is not None and node.quarantined_peers == 0


def test_fork_detection_feeds_breaker_when_quarantine_enabled():
    sim = run_with_divergent_forkers(
        5, 1, 80, seed=2, fork_every=2,
        node_config=lambda i, base: dataclasses.replace(
            base, quarantine_forkers=True
        ),
    )
    forker_pk = sim.forkers[0].pk
    detecting = [n for n in sim.nodes if n.has_fork[forker_pk]]
    assert detecting, "fork must have been detected"
    assert any(forker_pk in n.breaker.quarantined() for n in detecting)
    # honest members never quarantine each other over forks
    honest_pks = {n.pk for n in sim.nodes}
    for n in sim.nodes:
        assert not honest_pks & set(n.breaker.quarantined())


# ------------------------------------------------- payload hardening


def test_ask_events_rejects_garbage_with_signed_empty_reply():
    sim = make_simulation(2, seed=4)
    sim.run(12)
    asker, server = sim.nodes[0], sim.nodes[1]
    for junk in (b"", b"xx", b"\x00" * 100, b"\xff" * (crypto.SIG_BYTES + 7)):
        before = server.bad_requests
        reply = server.ask_events(asker.pk, junk)
        assert server.bad_requests == before + 1
        events = asker._decode_signed_blob(reply, server.pk)
        assert events == []            # decodes cleanly to zero events
    # a want-list whose payload length is not a hash multiple
    bad = b"\x01" * 33
    req = bad + crypto.sign(bad, asker.sk, crypto.DOMAIN_WANT)
    before = server.bad_requests
    assert asker._decode_signed_blob(
        server.ask_events(asker.pk, req), server.pk
    ) == []
    assert server.bad_requests == before + 1
    # unknown peers are a config error, not payload-dependent: still raise
    with pytest.raises(ValueError):
        server.ask_events(b"Z" * 32, b"anything")


def test_ask_sync_counts_truncated_and_oversized_requests():
    sim = make_simulation(2, seed=4)
    sim.run(6)
    server = sim.nodes[1]
    for junk in (b"", b"short", b"\x00" * (server.config.max_reply_bytes + 1)):
        with pytest.raises(ValueError):
            server.ask_sync(sim.nodes[0].pk, junk)
    assert server.bad_requests == 3


def test_decode_signed_blob_counted_rejection_paths():
    sim = make_simulation(2, seed=9)
    sim.run(10)
    node, peer = sim.nodes[0], sim.nodes[1]
    cases = [
        b"",                                           # shorter than a sig
        b"\x00" * 80,                                  # garbage signature
    ]
    evil = b"\xff" * 21                                # validly signed junk
    cases.append(evil + crypto.sign(evil, peer.sk, crypto.DOMAIN_SYNC_REPLY))
    for i, reply in enumerate(cases, start=1):
        assert node._decode_signed_blob(reply, peer.pk) is None
        assert node.bad_replies == i


def test_reply_size_caps_on_both_sides():
    config = SwirldConfig(n_members=2, max_reply_events=5)
    sim = make_simulation(2, seed=5, config=config)
    sim.run(40)
    a, b = sim.nodes
    # server side: a fresh observer's sync request gets at most 5 events
    hv = b"".join((0).to_bytes(4, "little") for _ in sim.members)
    req = hv + crypto.sign(hv, a.sk, crypto.DOMAIN_SYNC_REQ)
    reply = b.ask_sync(a.pk, req)
    events = a._decode_signed_blob(reply, b.pk)
    assert events is not None and len(events) == 5
    # client side: an over-budget reply is a counted rejection
    small = SwirldConfig(n_members=2, max_reply_bytes=100)
    sim2 = make_simulation(2, seed=5, config=small)
    sim2.run(1)
    big_reply = b"\x00" * 200
    assert sim2.nodes[0]._decode_signed_blob(big_reply, sim2.nodes[1].pk) is None
    assert sim2.nodes[0].bad_replies == 1


def test_pull_survives_nonbytes_and_raising_endpoints():
    """pull() must never raise on peer behavior, even under the default
    reliable Transport: endpoints that throw arbitrary exceptions or
    return non-bytes are failed RPCs / counted garbage, not tracebacks."""
    sim = make_simulation(3, seed=1)
    sim.run(12)
    node, evil = sim.nodes[0], sim.nodes[1]

    def boom(from_pk, req):
        raise TypeError("boom")

    sim.network[evil.pk] = boom
    assert node.pull(evil.pk) == []        # generic raise -> failed RPC
    sim.network[evil.pk] = lambda f, r: None
    before = node.bad_replies
    assert node.pull(evil.pk) == []        # non-bytes -> counted garbage
    assert node.bad_replies == before + 1


def test_orphan_buffer_byte_budget_eviction():
    """Plausible-but-unparentable events are parked under a byte budget,
    not only a count cap — one valid signer cannot balloon memory."""
    from tpu_swirld.oracle.event import Event

    config = SwirldConfig(n_members=2, max_orphan_bytes=4000)
    sim = make_simulation(2, seed=3, config=config)
    a, b = sim.nodes
    orphans = [
        Event(
            d=b"x" * 1000,
            p=(crypto.hash_bytes(b"gone%d" % i), crypto.hash_bytes(b"g2%d" % i)),
            t=50 + i, c=b.pk,
        ).signed(b.sk)
        for i in range(10)
    ]
    a._ingest(orphans, [])
    assert 0 < a.orphans_parked <= 3          # ~1.2 KB each, 4 KB budget
    assert a._orphan_bytes <= config.max_orphan_bytes
    # an event bigger than the whole budget is never parked
    huge = Event(
        d=b"y" * 5000,
        p=(crypto.hash_bytes(b"zz"), crypto.hash_bytes(b"z2")),
        t=99, c=b.pk,
    ).signed(b.sk)
    parked = a.orphans_parked
    a._ingest([huge], [])
    assert a.orphans_parked == parked


def test_sync_reply_cap_recovers_over_multiple_syncs():
    """A capped reply is a topo prefix; repeated syncs converge anyway."""
    config = SwirldConfig(n_members=3, max_reply_events=8)
    sim = make_simulation(3, seed=13, config=config)
    sim.run(120)
    counts = [len(n.hg) for n in sim.nodes]
    assert min(counts) > 30            # gossip stayed live under the cap
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0 and all(o[:m] == orders[0][:m] for o in orders)
