"""Incremental windowed consensus: bit-parity with the oracle and with
full-recompute ``run_consensus`` across chunked-ingest schedules, plus the
steady-state recompile regression (zero new jit-cache entries after
warmup).

The driver's exactness contract is *detect-or-match*: any ingest pattern
its window locality cannot answer exactly (stragglers, pruned parents,
cross-boundary fork pairs) must be answered by a transparent full
recompute — so every schedule here, however hostile, must still produce
outputs identical to one batch pass over the final DAG.
"""

import random

import numpy as np
import pytest

from tpu_swirld import obs as obslib
from tpu_swirld.packing import pack_events, pack_node
from tpu_swirld.sim import (
    chunked_ingest_schedule, generate_gossip_dag, make_simulation,
    run_with_forkers,
)
from tpu_swirld.tpu.pipeline import IncrementalConsensus, run_consensus

from tests.test_pipeline import assert_parity


def assert_same_result(a, b):
    """Field-by-field equality of two ConsensusResults (bit-parity)."""
    assert a.n == b.n
    assert (a.round == b.round).all()
    assert (a.is_witness == b.is_witness).all()
    assert a.famous == b.famous
    assert (a.round_received == b.round_received).all()
    assert (a.consensus_ts == b.consensus_ts).all()
    assert a.order == b.order
    assert a.max_round == b.max_round


def drive(members, stake, config, chunks, **kw):
    inc = IncrementalConsensus(members, stake, config, **kw)
    ordered = []
    for chunk in chunks:
        ordered.extend(inc.ingest(chunk)["ordered"])
    return inc, ordered


def fixed_chunks(events, size):
    return [events[i : i + size] for i in range(0, len(events), size)]


def test_incremental_parity_small_sim():
    sim = make_simulation(5, seed=11)
    sim.run(250)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    packed = pack_node(node)
    inc, ordered = drive(
        node.members, stake, node.config, fixed_chunks(events, 60),
        block=64, chunk=32, window_bucket=256, prune_min=64,
    )
    res = inc.result()
    ref = run_consensus(packed, node.config, block=64)
    assert_same_result(res, ref)
    assert_parity(node, packed, res)           # and vs the oracle itself
    # incrementally committed order == final order (prefix-stable commits)
    assert ordered == res.order
    assert len(res.order) > 0


def test_incremental_parity_random_chunk_sizes():
    """Chunk sizes from 1 event to large, randomized — commit boundaries
    must never influence any output."""
    sim = make_simulation(4, seed=7)
    sim.run(220)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    packed = pack_node(node)
    rng = random.Random(3)
    chunks, i = [], 0
    while i < len(events):
        c = rng.choice([1, 2, 7, 25, 80])
        chunks.append(events[i : i + c])
        i += c
    inc, _ = drive(
        node.members, stake, node.config, chunks,
        block=64, chunk=32, window_bucket=256, prune_min=32,
    )
    assert_same_result(inc.result(), run_consensus(packed, node.config, block=64))


def test_incremental_parity_with_forks():
    """Fork pairs pin pruning (pair members must stay addressable) and
    exercise the forked fame tally — parity must hold throughout."""
    sim = run_with_forkers(n_nodes=7, n_forkers=2, n_turns=300, seed=9)
    node = next(
        n for n in sim.nodes if any(n.has_fork[m] for m in sim.members)
    )
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    packed = pack_node(node)
    assert len(packed.fork_pairs) > 0
    inc, _ = drive(
        node.members, stake, node.config, fixed_chunks(events, 50),
        block=64, chunk=64, window_bucket=256, prune_min=64,
    )
    res = inc.result()
    assert_same_result(res, run_consensus(packed, node.config, block=64))
    assert_parity(node, packed, res)


def test_incremental_parity_fork_heavy_generated_dag():
    members, stake, events, _keys = generate_gossip_dag(
        12, 1200, seed=4, n_forkers=4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    from tpu_swirld.config import SwirldConfig

    cfg = SwirldConfig(n_members=12)
    inc, _ = drive(
        members, stake, cfg, fixed_chunks(events, 150),
        chunk=128, window_bucket=512, prune_min=128,
    )
    assert_same_result(inc.result(), run_consensus(packed, cfg))


def test_incremental_parity_straggler_schedule():
    """Orphan-heavy arrival: events delayed several chunks past their
    creation order arrive with old parents, driving the documented
    window-exit fallbacks — outputs must still be bit-identical."""
    members, stake, events, _keys = generate_gossip_dag(8, 900, seed=6)
    packed = pack_events(events, members, stake)
    from tpu_swirld.config import SwirldConfig

    cfg = SwirldConfig(n_members=8)
    chunks = chunked_ingest_schedule(
        events, 90, delay_prob=0.2, max_delay=4, seed=1
    )
    # the schedule must genuinely reorder deliveries across chunks
    flat = [ev for chunk in chunks for ev in chunk]
    assert [ev.id for ev in flat] != [ev.id for ev in events]
    inc = IncrementalConsensus(
        members, stake, cfg, block=64, chunk=64, window_bucket=256,
        prune_min=64,
    )
    for chunk in chunks:
        inc.ingest(chunk)
    # the incremental packer saw delivery order, so compare against a
    # batch pass over the *same* delivery order
    packed_delivery = pack_events(flat, members, stake)
    assert_same_result(inc.result(), run_consensus(packed_delivery, cfg))


def test_incremental_prunes_decided_prefix():
    """Steady state must actually prune: the carried window stays a small
    fraction of total history once rounds begin completing."""
    members, stake, events, _keys = generate_gossip_dag(8, 1600, seed=2)
    from tpu_swirld.config import SwirldConfig

    cfg = SwirldConfig(n_members=8)
    inc, _ = drive(
        members, stake, cfg, fixed_chunks(events, 200),
        chunk=128, window_bucket=256, prune_min=128,
    )
    assert inc.pruned_prefix > 0
    assert inc.window_size < len(events) // 2
    assert inc.pruned_prefix + inc.window_size == len(events)
    packed = pack_events(events, members, stake)
    assert_same_result(inc.result(), run_consensus(packed, cfg))


def test_incremental_empty_and_noop_ingests():
    inc = IncrementalConsensus([b"m0", b"m1", b"m2"], [1, 1, 1])
    st = inc.ingest([])
    assert st["new_events"] == 0 and st["ordered"] == []
    members, stake, events, _keys = generate_gossip_dag(3, 30, seed=0)
    inc2 = IncrementalConsensus(members, stake, chunk=32, window_bucket=256)
    inc2.ingest(events)
    before = inc2.result()
    inc2.ingest([])                      # no-op pass: state unchanged
    assert_same_result(inc2.result(), before)


def test_incremental_zero_recompiles_after_warmup():
    """Recompile-count regression: once the shape buckets have warmed up,
    the steady-state loop must add ZERO new entries to any stage's jit
    cache (classified by obs.stage_call watching the jit caches grow)."""
    members, stake, events, _keys = generate_gossip_dag(16, 3000, seed=5)
    from tpu_swirld.config import SwirldConfig

    cfg = SwirldConfig(n_members=16)
    inc = IncrementalConsensus(
        members, stake, cfg, chunk=128, window_bucket=512, prune_min=128,
    )
    chunks = fixed_chunks(events, 250)
    warmup = (2 * len(chunks)) // 3
    for chunk in chunks[:warmup]:
        inc.ingest(chunk)
    o = obslib.Obs()
    with obslib.enabled(o):
        for chunk in chunks[warmup:]:
            st = inc.ingest(chunk)
            assert not st["rebased"], "steady state must not rebase"
    compiles = obslib.compile_counts(o.registry)
    assert compiles == {}, f"steady-state loop recompiled: {compiles}"
    # and the steady loop must still be exact
    packed = pack_events(events, members, stake)
    assert_same_result(inc.result(), run_consensus(packed, cfg))


def test_incremental_matches_oracle_incremental_view():
    """The per-pass committed order must be a prefix of the final order
    (commits are irrevocable), and committed outputs must never change
    across later passes."""
    sim = make_simulation(5, seed=23)
    sim.run(260)
    node = sim.nodes[0]
    events = [node.hg[e] for e in node.order_added]
    stake = [node.stake[m] for m in node.members]
    inc = IncrementalConsensus(
        node.members, stake, node.config, block=64, chunk=32,
        window_bucket=256, prune_min=32,
    )
    committed = []
    for chunk in fixed_chunks(events, 40):
        committed.extend(inc.ingest(chunk)["ordered"])
        assert inc.result().order[: len(committed)] == committed
    assert committed == inc.result().order


def test_ssm_block_suffix_cut_matches_full_height():
    """The suffix-row cut of the witness-column adds relies on rows below
    a column's event being structurally unable to strongly-see it: the
    full-height block restricted to those rows must be identically zero,
    so the partial write plus the slab's zero-fill equals the full
    computation."""
    import jax.numpy as jnp

    from tpu_swirld.tpu.pipeline import ssm_block_stage

    members, stake, events, _keys = generate_gossip_dag(8, 200, seed=11)
    packed = pack_events(events, members, stake)
    n = 256
    parents = np.full((n, 2), -1, np.int32)
    parents[: packed.n] = packed.parents
    from tpu_swirld.tpu.pipeline import ancestry

    sees = ancestry(jnp.asarray(parents), block=64,
                    matmul_dtype=jnp.float32)
    mt = np.full((8, 32), -1, np.int32)
    mt[:, : packed.member_table.shape[1]] = packed.member_table
    cols = np.asarray([150, 170, 190], np.int32)
    tot = int(stake if np.isscalar(stake) else np.sum(packed.stake))
    full = np.asarray(ssm_block_stage(
        sees, jnp.asarray(mt), jnp.asarray(packed.stake),
        jnp.asarray(cols), np.int32(0), rows=n, tot_stake=tot,
        matmul_dtype_name="float32",
    ))
    # rows below the earliest column event are all zero -> the suffix
    # write is exact
    assert not full[:150].any()
    suffix = np.asarray(ssm_block_stage(
        sees, jnp.asarray(mt), jnp.asarray(packed.stake),
        jnp.asarray(cols), np.int32(128), rows=128, tot_stake=tot,
        matmul_dtype_name="float32",
    ))
    assert (suffix == full[128:]).all()
