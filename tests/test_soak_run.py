"""Production-day soak: the composed chaos scenario and its tooling.

Tier-1 runs the real smoke composition — a 4-process cluster gossiping
through per-link TCP fault proxies under heavy-tailed client traffic
while the schedule interleaves a byzantine equivocation storm, a
SIGKILL crash (+ WAL recovery), and a partition/heal window — plus the
fast units around the schedule documents, the ddmin shrinker, and the
shed-accounting mutation seam.  The full mutation → red verdict →
shrink → replay loop rides ``-m slow``.

(tests/test_soak.py is the older in-process chaos soak; this file
covers the socket-level composition from ``tpu_swirld.soak``.)
"""

import dataclasses
import json
import os

import pytest

from tpu_swirld import soak
from tpu_swirld.net.traffic import OUTCOMES, TrafficPlan, classify_reply
from tpu_swirld.soak import (
    AttackWindow, CrashWindow, PartitionWindow,
    load_doc, make_doc, replay_doc, save_doc,
    smoke_schedule, spec_from_dict, spec_to_dict,
    window_from_dict, window_to_dict,
)

pytestmark = pytest.mark.soak

_FAST_NET = {"gossip_interval_s": 0.005, "checkpoint_every_s": 0.5}


# ------------------------------------------------------------ units


def test_window_dict_roundtrip_all_kinds():
    windows = [
        CrashWindow(index=1, at_s=2.0, restart_at_s=3.5),
        PartitionWindow(start_s=1.0, end_s=4.0, group=(0, 2)),
        AttackWindow(start_s=0.5, end_s=6.0, index=3, n_branches=3,
                     step_every_s=0.5),
    ]
    for w in windows:
        d = window_to_dict(w)
        assert window_from_dict(json.loads(json.dumps(d))) == w
    with pytest.raises(KeyError):
        window_from_dict({"kind": "meteor-strike"})


def test_schedule_doc_roundtrip(tmp_path):
    spec = soak.default_spec(str(tmp_path), n_nodes=4, seed=9)
    schedule = list(smoke_schedule(spec))
    assert len(schedule) == 3   # attack + crash + partition
    doc = make_doc(spec, schedule, {"accounting_leaked": 7})
    path = save_doc(doc, str(tmp_path / "repro.json"))
    back = load_doc(path)
    assert back["kind"] == soak.DOC_KIND
    assert back["violation"] == {"accounting_leaked": 7}
    spec2 = spec_from_dict(back["spec"], workdir=str(tmp_path / "w2"))
    assert spec2.schedule == tuple(schedule)
    assert spec2.seed == spec.seed and spec2.n_nodes == spec.n_nodes
    # a foreign JSON file is refused, not misinterpreted
    alien = tmp_path / "alien.json"
    alien.write_text('{"kind": "bench"}')
    with pytest.raises(ValueError, match="soak-schedule"):
        load_doc(str(alien))
    # spec round-trip is lossless including the schedule
    assert spec_from_dict(
        json.loads(json.dumps(spec_to_dict(spec2))),
    ) == spec2


def test_classify_reply_covers_the_txpool_grammar():
    assert classify_reply(b"ACK:deadbeef") == "acked"
    assert classify_reply(b"DUP:deadbeef") == "duplicate"
    assert classify_reply(b"SHED:window") == "shed_window"
    assert classify_reply(b"SHED:pool") == "shed_pool"
    assert classify_reply(b"SHED:oversize") == "shed_oversize"
    assert classify_reply(b"garbage") == "unclassified"
    for bucket in ("acked", "duplicate", "shed_window", "shed_pool",
                   "shed_oversize"):
        assert bucket in OUTCOMES


def test_traffic_plan_rejects_undefined_pareto_mean():
    with pytest.raises(ValueError, match="pareto_alpha"):
        TrafficPlan(pareto_alpha=1.0)
    TrafficPlan(pareto_alpha=1.5)   # finite-mean tail is accepted


def test_shed_leak_mutation_drops_exactly_the_window_bucket():
    """The seeded defect: SHED:window replies vanish from the client
    ledger (classify -> None) while every other outcome still counts —
    the uniform shed-accounting balance check must go red on it."""
    net = {}
    leaky, net = soak.MUTATIONS["shed-leak"](net)
    assert leaky(b"SHED:window") is None
    assert leaky(b"SHED:pool") == "shed_pool"
    assert leaky(b"ACK:00ff") == "acked"
    # the mutation also pressures the admission window so the leaked
    # bucket actually fills during a short run
    assert "max_undecided" in net


def test_shrink_ddmin_minimizes_schedule(tmp_path, monkeypatch):
    """ddmin over windows with stubbed probes: failure iff a partition
    window is present -> the doc reduces to exactly that window, and
    each probe ran in its own probe-<n> workdir."""
    spec = soak.default_spec(str(tmp_path), n_nodes=4, seed=5)
    schedule = smoke_schedule(spec)
    spec = dataclasses.replace(spec, schedule=schedule)
    probe_dirs = []

    def fake_run_soak(probe):
        probe_dirs.append(os.path.basename(probe.workdir))
        bad = any(isinstance(w, PartitionWindow) for w in probe.schedule)
        return {
            "ok": not bad,
            "safety": {"oracle_agree": True},
            "liveness": {"advanced_after_heal": not bad},
            "disruptions_survived": 0 if bad else len(probe.schedule),
            "finality": {"ok": True},
            "accounting": {"leaked": 42 if bad else 0,
                           "balance_ok": not bad},
        }

    monkeypatch.setattr(soak, "run_soak", fake_run_soak)
    doc = soak.shrink(spec)
    assert [w["kind"] for w in doc["schedule"]] == ["partition"]
    assert doc["probes"] == len(probe_dirs) >= 2
    assert all(d.startswith("probe-") for d in probe_dirs)
    assert doc["violation"]["accounting_leaked"] == 42
    assert doc["violation"]["liveness_advanced"] is False
    # the reduced doc replays through the same entry point
    verdict = replay_doc(doc, str(tmp_path / "replay"))
    assert verdict["ok"] is False


def test_shrink_refuses_a_green_schedule(tmp_path, monkeypatch):
    spec = soak.default_spec(str(tmp_path), n_nodes=4, seed=5)
    spec = dataclasses.replace(spec, schedule=smoke_schedule(spec))
    monkeypatch.setattr(
        soak, "run_soak",
        lambda probe: {"ok": True, "safety": {}, "liveness": {},
                       "disruptions_survived": 3, "finality": {},
                       "accounting": {}},
    )
    with pytest.raises(ValueError):
        soak.shrink(spec)


# ------------------------------------------- the smoke composition


def test_soak_smoke_composition_survives_every_disruption(tmp_path):
    """The tier-1 production-day smoke: every disruption kind at least
    once (equivocation storm through the proxy seam, kill -9 + WAL
    recovery, partition/heal), under heavy-tailed traffic — composite
    verdict green, liveness past every window, books balanced."""
    spec = soak.default_spec(
        str(tmp_path), n_nodes=4, seed=3, horizon_s=6.5,
        tx_rate=120.0, n_clients=3, net=dict(_FAST_NET),
    )
    spec = dataclasses.replace(spec, schedule=smoke_schedule(spec))
    kinds = {type(w) for w in spec.schedule}
    assert kinds == {AttackWindow, CrashWindow, PartitionWindow}

    v = soak.run_soak(spec)
    assert v["ok"], json.dumps(
        {k: v[k] for k in ("safety", "liveness", "finality",
                           "accounting", "disruptions_survived")},
        default=str,
    )
    # safety: every honest order is a bit-exact oracle prefix
    assert v["safety"]["oracle_agree"] and v["safety"]["prefix_agree"]
    # liveness: decided past EVERY window's end, not just the last heal
    assert v["disruptions_survived"] == v["disruptions_total"] == 3
    # the wire actually went through the interposers, and the partition
    # window actually bit
    assert v["proxy"]["relayed"] > 0
    assert v["proxy"]["partition_blocked"] > 0
    # the storm ran and the honest side convicted it
    assert v["adversary"]["attack_steps"] > 0
    assert v["adversary"]["equivocations_detected"] > 0
    # the SIGKILL victim came back (restarted, unclean start observed)
    victims = [row for row in v["nodes"] if row["restarts"] >= 1]
    assert victims and all(row["unclean_start"] for row in victims)
    # shed accounting balances to the submission count exactly
    assert v["accounting"]["balance_ok"]
    assert v["accounting"]["leaked"] == 0
    assert v["accounting"]["submitted"] > 0


# ------------------------------------------------- the full loop


@pytest.mark.slow
def test_soak_mutation_goes_red_shrinks_and_replays(tmp_path):
    """The teeth: the seeded shed-accounting leak must flip the verdict
    red on accounting alone, ddmin must reduce the schedule to a
    replayable doc, and the doc must reproduce the red verdict."""
    spec = soak.default_spec(
        str(tmp_path), n_nodes=4, seed=3, horizon_s=6.5,
        tx_rate=120.0, n_clients=3, mutate="shed-leak",
        net=dict(_FAST_NET),
    )
    spec = dataclasses.replace(spec, schedule=smoke_schedule(spec))
    v = soak.run_soak(spec)
    assert not v["ok"]
    assert v["accounting"]["leaked"] > 0
    assert not v["accounting"]["balance_ok"]
    # red verdicts dump the flight recorder for post-mortem
    assert v["flightrec_dump"]

    doc = soak.shrink(spec)
    assert doc["schedule"]   # ddmin never returns an empty failure
    assert doc["probes"] >= 1
    path = save_doc(doc, str(tmp_path / "minimized.schedule.json"))
    replay = replay_doc(load_doc(path), str(tmp_path / "replay"))
    assert not replay["ok"]
    assert replay["accounting"]["leaked"] > 0
