"""Real-process cluster: loopback smoke, backpressure overload, and the
kill -9 crash-recovery soak.

Each test launches genuine OS processes (``python -m
tpu_swirld.net.node_proc``) gossiping over loopback TCP and holds them
to the chaos harness's standard: decided prefixes bit-identical to a
fault-free oracle replay of the union DAG (safety) and a decided
frontier that advances past any crash window (liveness).  The 3-process
smoke rides tier-1; the 5-process SIGKILL soak rides ``-m slow``.
"""

import importlib.util
import json
import os

import pytest

from tpu_swirld.net.cluster import ClusterSpec, run_cluster

pytestmark = pytest.mark.cluster

_FAST_NET = {"gossip_interval_s": 0.005, "checkpoint_every_s": 0.5}


def _load_cluster_run():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "cluster_run", os.path.join(root, "scripts", "cluster_run.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cluster_smoke_three_processes_via_cli(tmp_path, capsys):
    """The acceptance path end to end through scripts/cluster_run.py:
    3 node processes, client traffic, green verdict, exit status 0."""
    workdir = str(tmp_path / "cluster")
    out = str(tmp_path / "verdict.json")
    rc = _load_cluster_run().main([
        "--nodes", "3", "--seed", "3", "--duration", "2.5",
        "--rate", "120", "--workdir", workdir,
        "--gossip-interval", "0.005", "--checkpoint-every", "0.5",
        "--out", out,
    ])
    capsys.readouterr()   # the CLI prints the verdict; keep logs quiet
    assert rc == 0
    with open(out) as f:
        v = json.load(f)
    assert v["ok"]
    assert v["safety"]["prefix_agree"] and v["safety"]["oracle_agree"]
    assert v["safety"]["common_prefix_len"] > 0
    assert v["liveness"]["decided_final"] > 0
    # real client traffic was admitted and decided, with latency samples
    assert v["tx"]["acked"] > 0
    assert v["tx"]["decided"] > 0
    assert v["tx"]["submit_count"] > 0
    assert 0 < v["tx"]["submit_p50"] <= v["tx"]["submit_p99"]
    # fault-free run: every node started clean and exited gracefully
    assert v["reports"] == 3
    for row in v["nodes"]:
        assert row["exit_code"] == 0
        assert row["unclean_start"] is False
        assert row["flightrec_dump"] is None
    # the per-node artifacts the verdict was assembled from are on disk
    for i in range(3):
        assert os.path.exists(os.path.join(workdir, f"node-{i}.report.json"))
        assert os.path.exists(os.path.join(workdir, f"node-{i}.events.bin"))


def test_cluster_overload_sheds_instead_of_buffering(tmp_path):
    """Admission control under a zero undecided-window budget: every
    submission is shed with an explicit reply, nothing is buffered, and
    the consensus core stays green underneath."""
    spec = ClusterSpec(
        workdir=str(tmp_path / "overload"),
        n_nodes=3, seed=5, duration_s=1.5, tx_rate=200.0,
        net=dict(_FAST_NET, max_undecided=0),
    )
    v = run_cluster(spec)
    assert v["ok"], v["safety"]
    assert v["tx"]["acked"] == 0
    assert v["tx"]["shed"] > 0
    assert v["counters"]["tx_shed_window"] == v["tx"]["shed"]
    assert v["counters"]["tx_accepted"] == 0


@pytest.mark.slow
def test_cluster_kill9_soak_recovers_from_checkpoint_and_wal(tmp_path):
    """The acceptance scenario: a 5-process cluster survives a mid-run
    SIGKILL — the victim restarts from checkpoint + own-event WAL, dumps
    a startup post-mortem, re-joins via pull-only recovery, and the
    cluster's decided prefixes stay bit-identical to the oracle while
    the frontier advances past the crash window."""
    kill_index = 2
    spec = ClusterSpec(
        workdir=str(tmp_path / "soak"),
        n_nodes=5, seed=7, duration_s=6.0, tx_rate=200.0,
        kill_index=kill_index, kill_at_s=2.0, restart_at_s=3.5,
        flightrec_dir=str(tmp_path / "flightrec"),
        net=_FAST_NET,
    )
    v = run_cluster(spec)
    assert v["ok"], (v["safety"], v["liveness"], v["nodes"])
    assert v["faults"]["killed"] and v["faults"]["restarted"]
    # safety: all five decided orders are oracle prefixes
    assert v["safety"]["prefix_agree"] and v["safety"]["oracle_agree"]
    # liveness: the frontier moved past the heal point
    assert v["liveness"]["decided_final"] > v["liveness"]["decided_at_heal"]
    # the victim's second incarnation saw the unclean WAL and dumped
    victim = v["nodes"][kill_index]
    assert victim["restarts"] == 1
    assert victim["unclean_start"] is True
    assert victim["flightrec_dump"] is not None
    assert os.path.exists(victim["flightrec_dump"])
    assert victim["exit_code"] == 0        # the restart exited cleanly
    # survivors never saw an unclean start
    for row in v["nodes"]:
        if row["index"] != kill_index:
            assert row["unclean_start"] is False
            assert row["flightrec_dump"] is None
    # traffic kept flowing: submissions inside the crash window fail or
    # shed, but decided transactions span the whole run
    assert v["tx"]["acked"] > 0 and v["tx"]["decided"] > 0
