"""Real-process cluster: loopback smoke, backpressure overload, and the
kill -9 crash-recovery soak.

Each test launches genuine OS processes (``python -m
tpu_swirld.net.node_proc``) gossiping over loopback TCP and holds them
to the chaos harness's standard: decided prefixes bit-identical to a
fault-free oracle replay of the union DAG (safety) and a decided
frontier that advances past any crash window (liveness).  The 3-process
smoke rides tier-1; the 5-process SIGKILL soak rides ``-m slow``.
"""

import importlib.util
import json
import os

import pytest

from tpu_swirld.net.cluster import ClusterSpec, run_cluster

pytestmark = pytest.mark.cluster

_FAST_NET = {"gossip_interval_s": 0.005, "checkpoint_every_s": 0.5}


def _load_cluster_run():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "cluster_run", os.path.join(root, "scripts", "cluster_run.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cluster_smoke_three_processes_via_cli(tmp_path, capsys):
    """The acceptance path end to end through scripts/cluster_run.py:
    3 node processes, client traffic, green verdict, exit status 0."""
    workdir = str(tmp_path / "cluster")
    out = str(tmp_path / "verdict.json")
    rc = _load_cluster_run().main([
        "--nodes", "3", "--seed", "3", "--duration", "2.5",
        "--rate", "120", "--workdir", workdir,
        "--gossip-interval", "0.005", "--checkpoint-every", "0.5",
        "--out", out,
    ])
    capsys.readouterr()   # the CLI prints the verdict; keep logs quiet
    assert rc == 0
    with open(out) as f:
        v = json.load(f)
    assert v["ok"]
    assert v["safety"]["prefix_agree"] and v["safety"]["oracle_agree"]
    assert v["safety"]["common_prefix_len"] > 0
    assert v["liveness"]["decided_final"] > 0
    # real client traffic was admitted and decided, with latency samples
    assert v["tx"]["acked"] > 0
    assert v["tx"]["decided"] > 0
    assert v["tx"]["submit_count"] > 0
    assert 0 < v["tx"]["submit_p50"] <= v["tx"]["submit_p99"]
    # fault-free run: every node started clean and exited gracefully
    assert v["reports"] == 3
    for row in v["nodes"]:
        assert row["exit_code"] == 0
        assert row["unclean_start"] is False
        assert row["flightrec_dump"] is None
    # the per-node artifacts the verdict was assembled from are on disk
    for i in range(3):
        assert os.path.exists(os.path.join(workdir, f"node-{i}.report.json"))
        assert os.path.exists(os.path.join(workdir, f"node-{i}.events.bin"))
    _assert_telemetry_plane(workdir, v, n_nodes=3)


def _assert_telemetry_plane(workdir, v, n_nodes):
    """The PR 16 acceptance pins: one merged cross-process trace where at
    least one submission's spans cross >= 2 *node* processes with correct
    parent/child linkage, plus a supervisor metrics rollup covering every
    node."""
    from tpu_swirld.obs import cluster_trace
    from tpu_swirld.obs.registry import Registry

    # --- merged trace: stamped into the verdict and present on disk
    assert v["trace"]["merged"] == os.path.join(workdir, "merged.trace.json")
    assert os.path.exists(v["trace"]["merged"])
    assert v["trace"]["shards"] == n_nodes + 1     # every node + client
    assert v["trace"]["cross_process_traces"] >= 1
    # re-merge (pure function of the shards) for the per-trace digests
    summary = cluster_trace.merge_dir(workdir)
    shard_labels = [
        cluster_trace.shard_label(p) for p in summary["shards"]
    ]
    client_pid = shard_labels.index("client")
    with open(v["trace"]["merged"]) as f:
        merged = json.load(f)["traceEvents"]
    spans = {
        (e["args"]["trace"], e["args"]["span_id"]): e
        for e in merged
        if e.get("ph") == "X" and "trace" in (e.get("args") or {})
    }
    deep = None   # a trace whose spans touch >= 2 distinct node processes
    for trace_id, info in summary["per_trace"].items():
        node_pids = [p for p in info["pids"] if p != client_pid]
        if len(node_pids) >= 2 and "node.serve_sync" in info["names"]:
            deep = trace_id
            break
    assert deep is not None, summary["per_trace"]
    # parent/child linkage, hop by hop: client.submit is the trace root,
    # node.submit parents under it in another process, and the remote
    # serve span parents under the ingress node's gossip.sync span
    by_name = {}
    for (t, _sid), e in spans.items():
        if t == deep:
            by_name.setdefault(e["name"], []).append(e)
    root = by_name["client.submit"][0]
    assert root["pid"] == client_pid
    assert "parent_span_id" not in root["args"]
    submit = by_name["node.submit"][0]
    assert submit["args"]["parent_span_id"] == root["args"]["span_id"]
    assert submit["pid"] != client_pid
    serve = by_name["node.serve_sync"][0]
    sync_parent = spans[(deep, serve["args"]["parent_span_id"])]
    assert sync_parent["name"] == "gossip.sync"
    assert sync_parent["pid"] != serve["pid"]      # a real gossip hop
    assert sync_parent["pid"] != client_pid and serve["pid"] != client_pid
    # --- supervisor metrics plane: rollup covers every node
    assert v["metrics"]["nodes_covered"] == n_nodes
    assert v["metrics"]["polls"] >= 1
    with open(v["metrics"]["json"]) as f:
        doc = json.load(f)
    assert sorted(doc["nodes"]) == [f"n{i}" for i in range(n_nodes)]
    assert doc["rollup"]["tx_accepted"] > 0
    assert doc["rollup"]["hg_events"] > 0
    # the Prometheus exposition parses back through the sample plane and
    # carries one node label per sample
    with open(v["metrics"]["prom"]) as f:
        prom = f.read()
    for i in range(n_nodes):
        assert f'node="n{i}"' in prom
    assert "# TYPE" in prom
    # per-node samples reload losslessly into a registry
    r = Registry()
    for node, samples in doc["nodes"].items():
        r.load_samples(samples, extra_labels={"node": node})
    assert r.value("tx_accepted", {"node": "n0"}) is not None


def test_cluster_overload_sheds_instead_of_buffering(tmp_path):
    """Admission control under a zero undecided-window budget: every
    submission is shed with an explicit reply, nothing is buffered, and
    the consensus core stays green underneath."""
    spec = ClusterSpec(
        workdir=str(tmp_path / "overload"),
        n_nodes=3, seed=5, duration_s=1.5, tx_rate=200.0,
        net=dict(_FAST_NET, max_undecided=0),
    )
    v = run_cluster(spec)
    assert v["ok"], v["safety"]
    assert v["tx"]["acked"] == 0
    assert v["tx"]["shed"] > 0
    assert v["counters"]["tx_shed_window"] == v["tx"]["shed"]
    assert v["counters"]["tx_accepted"] == 0


@pytest.mark.slow
def test_cluster_kill9_soak_recovers_from_checkpoint_and_wal(tmp_path):
    """The acceptance scenario: a 5-process cluster survives a mid-run
    SIGKILL — the victim restarts from checkpoint + own-event WAL, dumps
    a startup post-mortem, re-joins via pull-only recovery, and the
    cluster's decided prefixes stay bit-identical to the oracle while
    the frontier advances past the crash window."""
    kill_index = 2
    spec = ClusterSpec(
        workdir=str(tmp_path / "soak"),
        n_nodes=5, seed=7, duration_s=6.0, tx_rate=200.0,
        kill_index=kill_index, kill_at_s=2.0, restart_at_s=3.5,
        flightrec_dir=str(tmp_path / "flightrec"),
        net=_FAST_NET,
    )
    v = run_cluster(spec)
    assert v["ok"], (v["safety"], v["liveness"], v["nodes"])
    assert v["faults"]["killed"] and v["faults"]["restarted"]
    # safety: all five decided orders are oracle prefixes
    assert v["safety"]["prefix_agree"] and v["safety"]["oracle_agree"]
    # liveness: the frontier moved past the heal point
    assert v["liveness"]["decided_final"] > v["liveness"]["decided_at_heal"]
    # the victim's second incarnation saw the unclean WAL and dumped
    victim = v["nodes"][kill_index]
    assert victim["restarts"] == 1
    assert victim["unclean_start"] is True
    assert victim["flightrec_dump"] is not None
    assert os.path.exists(victim["flightrec_dump"])
    assert victim["exit_code"] == 0        # the restart exited cleanly
    # survivors never saw an unclean start
    for row in v["nodes"]:
        if row["index"] != kill_index:
            assert row["unclean_start"] is False
            assert row["flightrec_dump"] is None
    # traffic kept flowing: submissions inside the crash window fail or
    # shed, but decided transactions span the whole run
    assert v["tx"]["acked"] > 0 and v["tx"]["decided"] > 0
