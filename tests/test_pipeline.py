"""Bit-parity: the device pipeline must match the oracle exactly.

BASELINE.json north star: identical ``round`` / ``witness`` / ``famous`` /
consensus order.  Each test packs a seeded oracle sim and compares every
output, no tolerance.
"""

import pytest

from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation, run_with_forkers
from tpu_swirld.tpu.pipeline import run_consensus


def assert_parity(node, packed, result):
    # precondition: the live node must not have quarantined any straggler
    # witness — the batch pipeline never freezes mid-pass, so parity is
    # only promised for quarantine-free histories.
    assert not node.ancient, "sim produced a quarantined witness; pick a new seed"
    # rounds + witness flags, every event
    for i, eid in enumerate(node.order_added):
        assert result.round[i] == node.round[eid], (
            f"round mismatch at {i}: {result.round[i]} != {node.round[eid]}"
        )
        assert bool(result.is_witness[i]) == bool(node.is_witness[eid]), (
            f"witness mismatch at {i}"
        )
    # fame: over all registered witnesses
    oracle_famous = {
        node.idx[w]: node.famous[w]
        for r, ws in node.wit_list.items()
        for w in ws
    }
    assert result.famous == oracle_famous
    # round received + consensus timestamps for ordered events
    for pos, eid in enumerate(node.consensus):
        i = node.idx[eid]
        assert result.round_received[i] == node.round_received[eid]
        assert result.consensus_ts[i] == node.consensus_ts[eid]
    # the total order itself
    got = [packed.ids[i] for i in result.order]
    assert got == node.consensus


def run_parity(sim_nodes, turns, seed, forkers=0):
    if forkers:
        sim = run_with_forkers(sim_nodes, forkers, turns, seed=seed)
    else:
        sim = make_simulation(sim_nodes, seed=seed)
        sim.run(turns)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0, "test must exercise a non-trivial order"
    return sim, node, result


def test_parity_config1_small():
    """BASELINE config 1 shape: 4-member reference sim."""
    run_parity(4, 200, seed=0)


def test_parity_config1_other_seeds():
    run_parity(4, 250, seed=7)
    run_parity(5, 250, seed=11)


def test_parity_16_members():
    """BASELINE config 2 shape (16 members), reduced turns for CI speed."""
    sim, node, result = run_parity(16, 400, seed=2)
    assert result.max_round >= 2


def test_parity_with_forkers():
    """Fork-aware pipeline: parity on a DAG containing real fork pairs."""
    sim = run_with_forkers(n_nodes=7, n_forkers=2, n_turns=300, seed=9)
    node = next(
        n for n in sim.nodes if any(n.has_fork[m] for m in sim.members)
    )
    packed = pack_node(node)
    assert len(packed.fork_pairs) > 0
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)


def test_parity_weighted_stake():
    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.sim import make_simulation

    cfg = SwirldConfig(n_members=5, stake=(3, 1, 1, 1, 1), seed=4)
    sim = make_simulation(5, seed=4, config=cfg)
    sim.run(250)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)


@pytest.mark.slow
def test_parity_config2_full():
    """Full BASELINE config 2: 16 members / 2k events."""
    sim = make_simulation(16, seed=2)
    sim.run_until_events(2000)
    node = max(sim.nodes, key=lambda n: len(n.hg))
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=128)
    assert_parity(node, packed, result)


def test_parity_config4_shape_small():
    """Config-4 adversary shape at reduced scale (12 members, 4 forkers):
    fork trees deep enough to exercise fame + ordering parity."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(
        12, 1200, seed=4, n_forkers=4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in events if node.add_event(ev)]
    node.consensus_pass(new_ids)
    assert len(node.consensus) > 0, "fame/order must be exercised"
    assert sum(node.has_fork[m] for m in members) > 0
    result = run_consensus(packed, node.config)
    assert_parity(node, packed, result)


@pytest.mark.slow
def test_parity_config4_64m_f21():
    """BASELINE config 4: 64 members, f=21 forkers — fork-detection parity
    at scale (reduced event count: the pure-Python oracle is the limiter)."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(
        64, 4000, seed=4, n_forkers=21
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 100
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in events if node.add_event(ev)]
    node.consensus_pass(new_ids)
    result = run_consensus(packed, node.config)
    assert_parity(node, packed, result)
    assert sum(node.has_fork[m] for m in members) >= 15


def test_columns_mode_matches_full():
    """The column-restricted strongly-sees path must equal the full-matrix
    path exactly (and both equal the oracle)."""
    sim = make_simulation(6, seed=19)
    sim.run(300)
    node = sim.nodes[0]
    packed = pack_node(node)
    a = run_consensus(packed, node.config, block=64, ssm_mode="full")
    b = run_consensus(packed, node.config, block=64, ssm_mode="columns")
    assert (a.round == b.round).all()
    assert (a.is_witness == b.is_witness).all()
    assert a.famous == b.famous
    assert a.order == b.order
    assert (a.round_received == b.round_received).all()
    assert (a.consensus_ts == b.consensus_ts).all()
    assert_parity(node, packed, b)
    assert b.timings["ssm_col_iterations"] < 64, "column loop must converge"


def test_columns_mode_dense_two_member_dag():
    """Degenerate round-per-event DAG (2-member alternating gossip): the
    column loop's retry bound must cover one-round-per-chunk-row density
    (review regression: cap of 64 crashed legal DAGs)."""
    sim = make_simulation(2, seed=0)
    for t in range(400):
        sim.step(t % 2)
    node = sim.nodes[0]
    packed = pack_node(node)
    a = run_consensus(packed, node.config, ssm_mode="full")
    b = run_consensus(packed, node.config, ssm_mode="columns")
    assert a.order == b.order and (a.round == b.round).all()
    assert_parity(node, packed, b)


def test_ssm_mode_validated():
    import pytest as _pytest

    sim = make_simulation(4, seed=1)
    sim.run(40)
    packed = pack_node(sim.nodes[0])
    with _pytest.raises(ValueError):
        run_consensus(packed, ssm_mode="colums")


def test_parity_huge_stake_exact_tally():
    """tot_stake >= 2^24 forces the exact int32 per-creator fame tally
    (the fast f32 path would round) — parity must hold."""
    from tpu_swirld.config import SwirldConfig

    big = 1 << 23
    cfg = SwirldConfig(n_members=4, stake=(big, big, big, big), seed=2)
    sim = make_simulation(4, seed=2, config=cfg)
    sim.run(200)
    node = sim.nodes[0]
    packed = pack_node(node)
    assert int(packed.stake.sum()) >= (1 << 24)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0


def test_parity_three_members_supermajority_edge():
    """n=3: supermajority needs all... 3*2 > 2*3 means 2-of-3 suffices;
    the smallest population where consensus can advance."""
    sim = make_simulation(3, seed=8)
    sim.run(200)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0


def test_pipeline_trivial_dags():
    """Geneses-only and single-member DAGs must not crash either backend."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(4, 4, seed=0)
    packed = pack_events(events, members, stake)   # geneses only
    result = run_consensus(packed, block=64)
    assert list(result.round) == [0, 0, 0, 0]
    assert result.is_witness.all()
    assert result.order == []


def test_parity_small_coin_period():
    """coin_period=2 makes every even vote distance a coin round, so the
    signature coin-bit override constantly feeds the tallies — pinning the
    coin-vote path's parity (rarely reached with the default C=6)."""
    from tpu_swirld.config import SwirldConfig

    for seed in (6, 13):
        cfg = SwirldConfig(n_members=5, coin_period=2, seed=seed)
        sim = make_simulation(5, seed=seed, config=cfg)
        sim.run(350)
        node = sim.nodes[0]
        packed = pack_node(node)
        result = run_consensus(packed, node.config, block=64)
        assert_parity(node, packed, result)
        assert len(node.consensus) > 0
