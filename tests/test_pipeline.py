"""Bit-parity: the device pipeline must match the oracle exactly.

BASELINE.json north star: identical ``round`` / ``witness`` / ``famous`` /
consensus order.  Each test packs a seeded oracle sim and compares every
output, no tolerance.
"""

import pytest

from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation, run_with_forkers
from tpu_swirld.tpu.pipeline import run_consensus


def assert_parity(node, packed, result):
    # No history precondition: the deterministic expiry horizon registers
    # straggler witnesses identically on the live oracle and the batch
    # replay, so parity is promised for EVERY history (the old
    # quarantine-free precondition is gone).
    # rounds + witness flags, every event
    for i, eid in enumerate(node.order_added):
        assert result.round[i] == node.round[eid], (
            f"round mismatch at {i}: {result.round[i]} != {node.round[eid]}"
        )
        assert bool(result.is_witness[i]) == bool(node.is_witness[eid]), (
            f"witness mismatch at {i}"
        )
    # fame: over all registered witnesses
    oracle_famous = {
        node.idx[w]: node.famous[w]
        for r, ws in node.wit_list.items()
        for w in ws
    }
    assert result.famous == oracle_famous
    # round received + consensus timestamps for ordered events
    for pos, eid in enumerate(node.consensus):
        i = node.idx[eid]
        assert result.round_received[i] == node.round_received[eid]
        assert result.consensus_ts[i] == node.consensus_ts[eid]
    # the total order itself
    got = [packed.ids[i] for i in result.order]
    assert got == node.consensus


def run_parity(sim_nodes, turns, seed, forkers=0):
    if forkers:
        sim = run_with_forkers(sim_nodes, forkers, turns, seed=seed)
    else:
        sim = make_simulation(sim_nodes, seed=seed)
        sim.run(turns)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0, "test must exercise a non-trivial order"
    return sim, node, result


def test_parity_config1_small():
    """BASELINE config 1 shape: 4-member reference sim."""
    run_parity(4, 200, seed=0)


def test_parity_config1_other_seeds():
    run_parity(4, 250, seed=7)
    run_parity(5, 250, seed=11)


def test_parity_16_members():
    """BASELINE config 2 shape (16 members), reduced turns for CI speed."""
    sim, node, result = run_parity(16, 400, seed=2)
    assert result.max_round >= 2


def test_parity_with_forkers():
    """Fork-aware pipeline: parity on a DAG containing real fork pairs."""
    sim = run_with_forkers(n_nodes=7, n_forkers=2, n_turns=300, seed=9)
    node = next(
        n for n in sim.nodes if any(n.has_fork[m] for m in sim.members)
    )
    packed = pack_node(node)
    assert len(packed.fork_pairs) > 0
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)


def test_parity_weighted_stake():
    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.sim import make_simulation

    cfg = SwirldConfig(n_members=5, stake=(3, 1, 1, 1, 1), seed=4)
    sim = make_simulation(5, seed=4, config=cfg)
    sim.run(250)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)


@pytest.mark.slow
def test_parity_config2_full():
    """Full BASELINE config 2: 16 members / 2k events."""
    sim = make_simulation(16, seed=2)
    sim.run_until_events(2000)
    node = max(sim.nodes, key=lambda n: len(n.hg))
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=128)
    assert_parity(node, packed, result)


def test_parity_config4_shape_small():
    """Config-4 adversary shape at reduced scale (12 members, 4 forkers):
    fork trees deep enough to exercise fame + ordering parity."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(
        12, 1200, seed=4, n_forkers=4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in events if node.add_event(ev)]
    node.consensus_pass(new_ids)
    assert len(node.consensus) > 0, "fame/order must be exercised"
    assert sum(node.has_fork[m] for m in members) > 0
    result = run_consensus(packed, node.config)
    assert_parity(node, packed, result)


@pytest.mark.slow
def test_parity_config4_64m_f21():
    """BASELINE config 4: 64 members, f=21 forkers — fork-detection parity
    at scale (reduced event count: the pure-Python oracle is the limiter)."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(
        64, 4000, seed=4, n_forkers=21
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 100
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in events if node.add_event(ev)]
    node.consensus_pass(new_ids)
    result = run_consensus(packed, node.config)
    assert_parity(node, packed, result)
    assert sum(node.has_fork[m] for m in members) >= 15


def test_columns_mode_matches_full():
    """The column-restricted strongly-sees path must equal the full-matrix
    path exactly (and both equal the oracle)."""
    sim = make_simulation(6, seed=19)
    sim.run(300)
    node = sim.nodes[0]
    packed = pack_node(node)
    a = run_consensus(packed, node.config, block=64, ssm_mode="full")
    b = run_consensus(packed, node.config, block=64, ssm_mode="columns")
    assert (a.round == b.round).all()
    assert (a.is_witness == b.is_witness).all()
    assert a.famous == b.famous
    assert a.order == b.order
    assert (a.round_received == b.round_received).all()
    assert (a.consensus_ts == b.consensus_ts).all()
    assert_parity(node, packed, b)
    assert b.timings["ssm_col_iterations"] < 64, "column loop must converge"


def test_columns_mode_dense_two_member_dag():
    """Degenerate round-per-event DAG (2-member alternating gossip): the
    column loop's retry bound must cover one-round-per-chunk-row density
    (review regression: cap of 64 crashed legal DAGs)."""
    sim = make_simulation(2, seed=0)
    for t in range(400):
        sim.step(t % 2)
    node = sim.nodes[0]
    packed = pack_node(node)
    a = run_consensus(packed, node.config, ssm_mode="full")
    b = run_consensus(packed, node.config, ssm_mode="columns")
    assert a.order == b.order and (a.round == b.round).all()
    assert_parity(node, packed, b)


def test_ssm_mode_validated():
    import pytest as _pytest

    sim = make_simulation(4, seed=1)
    sim.run(40)
    packed = pack_node(sim.nodes[0])
    with _pytest.raises(ValueError):
        run_consensus(packed, ssm_mode="colums")


def test_parity_huge_stake_exact_tally():
    """tot_stake >= 2^24 forces the exact int32 per-creator fame tally
    (the fast f32 path would round) — parity must hold."""
    from tpu_swirld.config import SwirldConfig

    big = 1 << 23
    cfg = SwirldConfig(n_members=4, stake=(big, big, big, big), seed=2)
    sim = make_simulation(4, seed=2, config=cfg)
    sim.run(200)
    node = sim.nodes[0]
    packed = pack_node(node)
    assert int(packed.stake.sum()) >= (1 << 24)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0


def test_parity_three_members_supermajority_edge():
    """n=3: supermajority needs all... 3*2 > 2*3 means 2-of-3 suffices;
    the smallest population where consensus can advance."""
    sim = make_simulation(3, seed=8)
    sim.run(200)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    assert len(node.consensus) > 0


def test_pipeline_trivial_dags():
    """Geneses-only and single-member DAGs must not crash either backend."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(4, 4, seed=0)
    packed = pack_events(events, members, stake)   # geneses only
    result = run_consensus(packed, block=64)
    assert list(result.round) == [0, 0, 0, 0]
    assert result.is_witness.all()
    assert result.order == []


def test_parity_with_late_straggler_witness():
    """The killer case for the old node-local quarantine: a straggler
    witness landing in a fame-complete round.  The deterministic expiry
    horizon registers it on every engine, so the live node that received
    it LATE must stay bit-identical to a batch replay AND to a fresh
    observer that ingested the whole DAG at once."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.sim import make_straggler_event

    sim = make_simulation(4, seed=0)
    sim.run(220)
    node = sim.nodes[0]
    frozen = node._frozen_round
    assert frozen >= 2, "history must have a committed frontier"
    pk, sk = sim.nodes[1].pk, sim.nodes[1].sk
    ev = make_straggler_event(node, pk, sk, at_round=1)
    assert node.add_event(ev)
    node.consensus_pass([ev.id])
    assert node.round[ev.id] <= frozen
    assert node.is_witness[ev.id]
    assert ev.id in node.late_witnesses, "scenario must exercise the corner"
    assert ev.id in node.wit_slot, "late witness must be fully registered"
    assert node.famous[ev.id] is False, "a true straggler is not famous"
    assert node.horizon_violations == 0
    # batch replay of the same insertion order: bit-identical
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    assert_parity(node, packed, result)
    # a fresh observer ingesting everything at once agrees too (arrival
    # order cannot influence the horizon)
    observer = Node(
        sk=node.sk, pk=node.pk, network={}, members=node.members,
        config=node.config, clock=lambda: 0, create_genesis=False,
    )
    new_ids = [e for e in node.order_added if observer.add_event(node.hg[e])]
    observer.consensus_pass(new_ids)
    assert observer.consensus == node.consensus
    assert all(observer.round[e] == node.round[e] for e in node.order_added)
    assert {w: node.famous[w] for w in node.wit_slot} == {
        w: observer.famous[w] for w in observer.wit_slot
    }


def test_overflow_selfheal_fork_storm_smax():
    """A fork-heavy DAG under an under-provisioned witness-slot capacity
    previously died with RuntimeError("witness table overflow"); the
    self-healing retry must double s_max and finish with full parity."""
    from tpu_swirld.oracle.node import Node
    from tpu_swirld.packing import pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, keys = generate_gossip_dag(
        8, 500, seed=4, n_forkers=3, fork_prob=0.4
    )
    packed = pack_events(events, members, stake)
    assert len(packed.fork_pairs) > 0
    node = Node(
        sk=keys[0][1], pk=members[0], network={}, members=members,
        clock=lambda: 0, create_genesis=False,
    )
    new_ids = [ev.id for ev in events if node.add_event(ev)]
    node.consensus_pass(new_ids)
    result = run_consensus(
        packed, node.config, block=64, s_max=len(members) + 1
    )
    assert result.timings["overflow_retries"] >= 1
    assert_parity(node, packed, result)


def test_overflow_selfheal_round_clamp():
    """An under-provisioned round window (the chain-clamp failure shape)
    must retry unclamped at config.max_rounds instead of fail-stopping,
    on both the columns and the full-matrix paths.

    Why the clamp itself cannot be beaten naturally (so an explicit tight
    r_max is the honest way to drive this path): every promoted round
    needs witnesses from creators holding > 2/3 of stake, so
    sum_m stake_m * W_m > (2/3) * total * R — some member witnesses at
    least ~2/3 of all R rounds — and strongly-seeing each round's last
    witness forces extra "echo" events per round (~2s-2 events per round
    for an s-member quorum), pushing the LONGEST self-chain to >= R for
    every achievable schedule.  Empirically (3-member rotation attempt):
    max_round 74 vs chain 102.  The heal makes the clamp safe even where
    that argument has gaps (weighted stakes, byzantine shapes)."""
    from tpu_swirld.config import SwirldConfig

    cfg = SwirldConfig(n_members=5, stake=(3, 2, 2, 1, 1), seed=4)
    sim = make_simulation(5, seed=4, config=cfg)
    sim.run(320)
    node = sim.nodes[0]
    packed = pack_node(node)
    assert node.max_round >= 8
    a = run_consensus(packed, node.config, block=64, r_max=4)
    assert a.timings["overflow_retries"] >= 1
    assert_parity(node, packed, a)
    b = run_consensus(
        packed, node.config, block=64, r_max=4, ssm_mode="full"
    )
    assert b.timings["overflow_retries"] >= 1
    assert a.order == b.order and (a.round == b.round).all()


def test_overflow_exhausted_raises_corrected_error():
    """When config.max_rounds itself is too small the error must name the
    genuinely exhausted capacity and the knob that raises it."""
    from tpu_swirld.config import SwirldConfig

    cfg = SwirldConfig(n_members=5, max_rounds=4, seed=4)
    sim = make_simulation(5, seed=4)
    sim.run(320)
    node = sim.nodes[0]
    packed = pack_node(node)
    assert node.max_round >= 4
    with pytest.raises(RuntimeError, match="max_rounds"):
        run_consensus(packed, cfg, block=64)


def test_parity_small_coin_period():
    """coin_period=2 makes every even vote distance a coin round, so the
    signature coin-bit override constantly feeds the tallies — pinning the
    coin-vote path's parity (rarely reached with the default C=6)."""
    from tpu_swirld.config import SwirldConfig

    for seed in (6, 13):
        cfg = SwirldConfig(n_members=5, coin_period=2, seed=seed)
        sim = make_simulation(5, seed=seed, config=cfg)
        sim.run(350)
        node = sim.nodes[0]
        packed = pack_node(node)
        result = run_consensus(packed, node.config, block=64)
        assert_parity(node, packed, result)
        assert len(node.consensus) > 0
