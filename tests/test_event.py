"""Event model: serialization round-trip, hashing, signatures."""

from tpu_swirld import crypto
from tpu_swirld.oracle.event import Event, decode_event, encode_event


def make_event(payload=b"tx", parents=(), t=7):
    pk, sk = crypto.keypair(b"seed-1")
    return Event(d=payload, p=parents, t=t, c=pk).signed(sk), pk, sk


def test_id_stable_and_signature_valid():
    ev, pk, _sk = make_event()
    assert len(ev.id) == crypto.HASH_BYTES
    assert ev.id == ev.id
    assert ev.verify()


def test_id_changes_with_content():
    ev1, _, _ = make_event(payload=b"a")
    ev2, _, _ = make_event(payload=b"b")
    assert ev1.id != ev2.id


def test_tampered_signature_fails():
    ev, _, _ = make_event()
    bad = Event(d=ev.d, p=ev.p, t=ev.t, c=ev.c, s=bytes(len(ev.s)))
    assert not bad.verify()


def test_encode_decode_roundtrip():
    g, pk, sk = make_event()
    child = Event(d=b"x" * 100, p=(g.id, g.id), t=99, c=pk).signed(sk)
    blob = encode_event(g) + encode_event(child)
    e1, off = decode_event(blob, 0)
    e2, off = decode_event(blob, off)
    assert off == len(blob)
    assert e1 == g
    assert e2 == child
    assert e2.id == child.id


def test_coin_bit_in_range():
    ev, _, _ = make_event()
    assert ev.coin_bit() in (0, 1)


def test_sim_crypto_backend_roundtrip():
    prev = crypto.backend_name()       # restore whatever the env gave us
    crypto.set_backend("sim")          # (ed25519 needs `cryptography`)
    try:
        pk, sk = crypto.keypair(b"s")
        sig = crypto.sign(b"body", sk)
        assert len(sig) == crypto.SIG_BYTES
        assert crypto.verify(b"body", sig, pk)
        assert not crypto.verify(b"other", sig, pk)
    finally:
        crypto.set_backend(prev)


def test_crypto_randrange_bounds():
    from tpu_swirld import crypto

    import pytest
    for n in (1, 2, 7, 1000):
        for _ in range(20):
            assert 0 <= crypto.randrange(n) < n
    with pytest.raises(ValueError):
        crypto.randrange(0)
