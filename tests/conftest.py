"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Must run before any ``jax`` import (SURVEY.md §4 "Distributed tests": fake a
pod slice with ``xla_force_host_platform_device_count``, the moral
equivalent of the reference's in-process network dict).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
