"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Must run before any ``jax`` import (SURVEY.md §4 "Distributed tests": fake a
pod slice with ``xla_force_host_platform_device_count``, the moral
equivalent of the reference's in-process network dict).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The machine's sitecustomize registers an 'axon' TPU-tunnel PJRT plugin and
# forces jax_platforms="axon,cpu", overriding the env var; initializing the
# axon backend can hang for minutes.  Forcing the config AFTER import (but
# before any backend init) makes the CPU selection stick.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """``bigmem`` tests (multi-GB RSS, config-5 scale) never run in tier-1:
    the tier-1 command only deselects ``slow``, so the exclusion is an
    explicit skip here, lifted by RUN_BIGMEM=1 for machines that opt in."""
    if os.environ.get("RUN_BIGMEM") == "1":
        return
    skip = pytest.mark.skip(reason="bigmem: set RUN_BIGMEM=1 to run")
    for item in items:
        if "bigmem" in item.keywords:
            item.add_marker(skip)
