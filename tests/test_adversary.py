"""Active byzantine adversary suite (tpu_swirld.adversary).

Four layers:

- scenario verdicts: every registered strategy (equivocation storm,
  censorship relay, delayed-release straggler, fork bomb at f and f+1)
  must produce a machine-checked passing verdict — honest decided
  prefixes bit-identical to the fault-free oracle replay, liveness after
  the attack window, the strategy's detection counter fired — with
  cross-engine parity against BOTH windowed drivers (each row also
  carries batch parity, so one run covers all three engines);
- the hardened honest path in isolation: the 3f fork-budget admission
  check and the sync-reply branch-amplification cap;
- transport determinism: per-link ``SeedSequence``-spawned fault streams
  are independent of global call interleaving (PR satellite — the old
  shared ``Random`` made every link's draws schedule-dependent);
- the circuit breaker's half-open probe against a peer serving forged
  blobs: re-open, counted rejection, no exception out of ``pull``.
"""

import pytest

from tpu_swirld import crypto
from tpu_swirld.adversary import SCENARIOS
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.node import Node
from tpu_swirld.sim import build_population
from tpu_swirld.transport import (
    FaultPlan,
    FaultyTransport,
    LinkFaults,
    TransportError,
)

pytestmark = pytest.mark.adversary

#: both windowed drivers; each engine row additionally carries
#: ``batch_oracle_parity``, so asserting over these rows is the
#: all-three-engines verdict the scenario docstrings promise.
ENGINES = ("incremental", "streaming")


def _assert_engine_rows(verdict):
    rows = verdict["engines"]
    assert sorted(r["engine"] for r in rows) == sorted(ENGINES)
    for r in rows:
        assert r["batch_oracle_parity"], r
        assert r["incremental_batch_parity"], r


# ------------------------------------------------------ scenario verdicts


def test_registry_names():
    assert list(SCENARIOS) == [
        "equivocation_storm",
        "censorship",
        "delayed_release",
        "fork_bomb",
        "fork_bomb_overbudget",
        "horizon_storm",
        "overflow_storm",
        "membership_churn",
    ]


def test_equivocation_storm_verdict(tmp_path):
    v = SCENARIOS["equivocation_storm"](str(tmp_path), engine=ENGINES)
    assert v["ok"], v
    adv = v["adversary"]
    assert adv["equivocations_detected"] > 0
    assert adv["budget_exhausted"] == 0
    assert v["liveness"]["advanced_after_heal"]
    _assert_engine_rows(v)


def test_censorship_verdict(tmp_path):
    v = SCENARIOS["censorship"](str(tmp_path), engine=ENGINES)
    assert v["ok"], v
    adv = v["adversary"]
    # the relay's selective silence must be convicted by the
    # served-child-proves-held-parent heuristic
    assert adv["withholding_suspected"] > 0
    assert v["safety"]["prefix_agree"] and v["safety"]["oracle_agree"]
    _assert_engine_rows(v)


def test_delayed_release_verdict(tmp_path):
    v = SCENARIOS["delayed_release"](str(tmp_path), engine=ENGINES)
    assert v["ok"], v
    adv = v["adversary"]
    # the held tail must land below the frozen vote horizon as late
    # witnesses — full DAG citizens, never a horizon violation
    assert adv["late_witnesses"] > 0
    assert adv["horizon_violations"] == 0
    _assert_engine_rows(v)


def test_fork_bomb_at_budget(tmp_path):
    v = SCENARIOS["fork_bomb"](str(tmp_path), engine=ENGINES)
    assert v["ok"], v
    adv = v["adversary"]
    assert adv["n_forkers"] == adv["f_budget"] == 2
    assert adv["equivocations_detected"] > 0
    # at the design point the budget flag must NOT cry wolf
    assert adv["budget_exhausted"] == 0
    assert v["liveness"]["advanced_after_heal"]
    _assert_engine_rows(v)


def test_fork_bomb_overbudget_flagged(tmp_path):
    v = SCENARIOS["fork_bomb_overbudget"](str(tmp_path))
    assert v["ok"], v
    adv = v["adversary"]
    assert adv["n_forkers"] == adv["f_budget"] + 1
    # beyond n > 3f the obligation is detection, not tolerance: the
    # (f+1)-th forked creator must raise the admission flag on honest
    # nodes, and any divergence must be flagged, never silent
    assert adv["budget_exhausted"] > 0
    assert not adv["silent_divergence"]
    assert v["safety"]["prefix_agree"]


# ------------------------------------- hardened honest path, in isolation


def test_fork_budget_admission_check():
    """The (f+1)-th forked creator trips ``budget_exhausted`` on a plain
    node; forked events are still admitted so fork proofs keep flowing."""
    cfg = SwirldConfig(n_members=3, quarantine_forkers=False)
    pop = build_population(3, seed=11)
    (pk_a, sk_a), (pk_f, sk_f), _ = pop.keys
    a = Node(
        sk=sk_a, pk=pk_a, network=pop.network, members=pop.members,
        config=cfg, clock=lambda: pop.clock[0], transport=pop.transport,
    )
    g = Event(d=b"g", p=(), t=0, c=pk_f).signed(sk_f)
    assert a.add_event(g)
    sib0 = Event(d=b"s0", p=(g.id, a.head), t=1, c=pk_f).signed(sk_f)
    sib1 = Event(d=b"s1", p=(g.id, a.head), t=1, c=pk_f).signed(sk_f)
    assert a.add_event(sib0)
    assert a.budget_exhausted == 0
    assert a.add_event(sib1)          # fork pair lands -> still admitted
    # n=3 -> f = 0: the FIRST forked creator is already over budget
    assert a.equivocations_detected == 1
    assert a.budget_exhausted == 1
    assert a.has_fork[pk_f]


def test_sync_reply_branch_amplification_cap():
    """A creator with many live branches cannot amplify sync replies past
    ``config.max_fork_branches`` walked tails (counted, deterministic)."""
    cfg = SwirldConfig(
        n_members=3, max_fork_branches=2, quarantine_forkers=False
    )
    pop = build_population(3, seed=12)
    (pk_s, sk_s), (pk_f, sk_f), (pk_a, sk_a) = pop.keys
    serve = Node(
        sk=sk_s, pk=pk_s, network=pop.network, members=pop.members,
        config=cfg, clock=lambda: pop.clock[0],
        network_want=pop.network_want, transport=pop.transport,
    )
    pop.network[pk_s] = serve.ask_sync
    pop.network_want[pk_s] = serve.ask_events
    g = Event(d=b"g", p=(), t=0, c=pk_f).signed(sk_f)
    serve.add_event(g)
    for i in range(6):   # 6-way fork: 6 live branch tips at seq 1
        sib = Event(
            d=b"s%d" % i, p=(g.id, serve.head), t=1, c=pk_f
        ).signed(sk_f)
        serve.add_event(sib)
    assert len(serve.branch_tips[pk_f]) > cfg.max_fork_branches
    asker = Node(
        sk=sk_a, pk=pk_a, network=pop.network, members=pop.members,
        config=cfg, clock=lambda: pop.clock[0],
        network_want=pop.network_want, transport=pop.transport,
    )
    got = asker.pull(pk_s)
    assert got                              # the pull still delivers
    assert serve.sync_branches_capped >= 1  # and the cap was enforced


# ------------------------------------------- per-link fault determinism


def test_fault_streams_order_independent():
    """Per-link fault outcomes are a pure function of (plan.seed, src,
    dst, per-link call#): reordering traffic across links — or running in
    a fresh process/transport — must not change any link's sequence."""
    members = [bytes([i]) * 32 for i in range(3)]
    network = {m: (lambda src, req: b"reply:" + req) for m in members}
    plan = FaultPlan(
        seed=9,
        default=LinkFaults(
            drop=0.3, corrupt=0.3, duplicate=0.2, reorder=0.2, delay=0.1
        ),
    )
    links = [(0, 1), (1, 0), (0, 2), (2, 1)]

    def outcomes(order):
        ft = FaultyTransport(network, {}, plan, members, lambda: 0)
        results = {link: [] for link in links}
        for s, d in order:
            n = len(results[(s, d)])
            try:
                r = ft.call(members[s], members[d], "sync", b"p%d" % n)
            except TransportError as e:
                r = type(e).__name__.encode()
            results[(s, d)].append(r)
        return results

    grouped = [link for link in links for _ in range(16)]
    interleaved = [link for _ in range(16) for link in links]
    a, b = outcomes(grouped), outcomes(interleaved)
    assert a == b
    # and cross-run: a fresh transport over the same schedule reproduces
    assert outcomes(interleaved) == b


# ------------------------------------- half-open probe vs forged replies


def test_half_open_probe_forged_reply_reopens():
    """An open breaker's single half-open probe answered with a forged
    blob must re-open the circuit and count the rejection — never raise
    out of the pull loop; a later honest probe closes it."""
    pop = build_population(2, seed=13)
    (pk_a, sk_a), (pk_b, sk_b) = pop.keys
    cfg = SwirldConfig(n_members=2)
    a = Node(
        sk=sk_a, pk=pk_a, network=pop.network, members=pop.members,
        config=cfg, clock=lambda: pop.clock[0],
        network_want=pop.network_want, transport=pop.transport,
    )
    pop.network[pk_a] = a.ask_sync
    pop.network_want[pk_a] = a.ask_events

    def forged(src, req):
        return b"\x00" * (crypto.SIG_BYTES + 16)   # valid length, bad sig

    pop.network[pk_b] = forged
    pop.network_want[pk_b] = forged

    br = a.breaker
    br.record_misbehavior(pk_b, weight=br.misbehavior_threshold)
    assert br.opens == 1 and br.state(pk_b) == "open"
    assert a.pull(pk_b) == []                  # fast-fail while open
    bad_before = a.bad_replies

    pop.clock[0] += int(br.cooldown) + 1       # cooldown -> half-open
    assert br.state(pk_b) == "half-open"
    got = a.pull(pk_b)                         # the probe: forged reply
    assert got == []                           # counted, not raised
    assert a.bad_replies == bad_before + 1
    assert br.opens == 2                       # probe failure re-opened
    assert br.state(pk_b) == "open"

    # an honest peer behind the same pk closes the circuit on the next
    # successful probe
    b = Node(
        sk=sk_b, pk=pk_b, network=pop.network, members=pop.members,
        config=cfg, clock=lambda: pop.clock[0],
        network_want=pop.network_want, transport=pop.transport,
    )
    pop.network[pk_b] = b.ask_sync
    pop.network_want[pk_b] = b.ask_events
    pop.clock[0] += int(br.cooldown) + 1
    assert br.state(pk_b) == "half-open"
    a.pull(pk_b)
    assert br.state(pk_b) == "closed"
    assert pk_b not in br.quarantined()


# ----------------------------------------------------- lint-scope pinning


def test_sw002_scope_covers_adversary():
    """adversary.py is consensus-critical: the unordered-iteration rule
    must apply to it (PR satellite — keep the scope pinned)."""
    from tpu_swirld.analysis import check_source

    bad = 's = {b"a", b"b"}\nfor x in s:\n    pass\n'
    findings = check_source(bad, module_path="adversary.py")
    assert "SW002" in [f.rule for f in findings]
