"""Aux subsystems: checkpoint/resume, metrics, viz export (SURVEY §5)."""

import json

from tpu_swirld.checkpoint import (
    load_node, load_packed, save_node, save_packed,
)
from tpu_swirld.metrics import Metrics, node_gauges
from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation
from tpu_swirld import viz


def test_packed_checkpoint_roundtrip(tmp_path):
    sim = make_simulation(4, seed=3)
    sim.run(100)
    packed = pack_node(sim.nodes[0])
    p = str(tmp_path / "dag.npz")
    save_packed(p, packed)
    got = load_packed(p)
    for field in (
        "parents", "creator", "seq", "t", "coin", "stake",
        "fork_pairs", "member_table",
    ):
        assert (getattr(got, field) == getattr(packed, field)).all()
    assert got.ids == packed.ids
    assert got.sigs == packed.sigs


def test_node_checkpoint_resume_and_continue(tmp_path):
    sim = make_simulation(4, seed=8)
    sim.run(150)
    node = sim.nodes[1]
    p = str(tmp_path / "node.swck")
    save_node(p, node)
    restored = load_node(
        p, sk=node.sk, pk=node.pk, network=sim.network,
        network_want={m: n.ask_events for m, n in zip(sim.members, sim.nodes)},
    )
    # bit-identical consensus state after replay
    assert restored.consensus == node.consensus
    assert restored.round == node.round
    assert restored.is_witness == node.is_witness
    assert restored.famous == node.famous
    assert restored.consensus_ts == node.consensus_ts
    # and the restored node keeps working: gossip + consensus continue
    peer = next(m for m in sim.members if m != node.pk)
    new_ids = restored.sync(peer, b"resumed")
    restored.consensus_pass(new_ids)
    assert restored.head in restored.hg


def test_metrics_counters():
    sim = make_simulation(4, seed=2)
    node = sim.nodes[0]
    node.metrics = Metrics()
    sim.run(120)
    snap = node.metrics.snapshot()
    assert snap["n_events_processed"] > 0
    assert snap["s_divide_rounds"] > 0
    assert snap["s_decide_fame"] >= 0
    if node.consensus:
        assert snap["n_events_ordered"] == len(node.consensus)
        assert snap["events_per_sec_to_consensus"] > 0
    g = node_gauges(node)
    assert g["events"] == len(node.hg)
    assert g["decided_round_lag"] >= 0


def test_viz_exports_agree_across_backends():
    from tpu_swirld.tpu.pipeline import run_consensus

    sim = make_simulation(4, seed=5)
    sim.run(150)
    node = sim.nodes[0]
    packed = pack_node(node)
    result = run_consensus(packed, node.config, block=64)
    a = viz.export_state(node=node)
    b = viz.export_state(packed=packed, result=result)
    assert a == b
    # serialized forms render without error
    s = viz.to_json(node=node)
    assert json.loads(s)[0]["creator"] == 0
    dot = viz.to_dot(node=node)
    assert dot.startswith("digraph") and "->" in dot
    lanes = viz.ascii_lanes(node=node)
    assert "m0" in lanes and "height" in lanes


def test_bench_compare_tool(tmp_path):
    """scripts/bench_compare.py: ok within threshold, nonzero on >10%
    throughput regression (opt-in check wiring)."""
    import subprocess
    import sys

    sa = {"envelope": "baseline", "clean": True, "findings": 0}
    old = {"value": 1000.0, "phases": {"pipeline": 1.0},
           "incremental": {"steady_evps": 2000.0}}
    good = {"value": 950.0, "phases": {"pipeline": 1.1},
            "incremental": {"steady_evps": 2100.0}, "scale_audit": sa}
    bad = {"value": 800.0, "phases": {},
           "incremental": {"steady_evps": 2100.0}, "scale_audit": sa}
    po, pg, pb = tmp_path / "o.json", tmp_path / "g.json", tmp_path / "b.json"
    po.write_text(json.dumps(old))
    pg.write_text(json.dumps(good))
    pb.write_text(json.dumps(bad))
    r = subprocess.run(
        [sys.executable, "scripts/bench_compare.py", str(po), str(pg)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "scripts/bench_compare.py", str(po), str(pb)],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "REGRESSION" in r.stdout
    # regression in the incremental metric alone must also fail
    bad_inc = {"value": 1000.0, "phases": {},
               "incremental": {"steady_evps": 1500.0}, "scale_audit": sa}
    pbi = tmp_path / "bi.json"
    pbi.write_text(json.dumps(bad_inc))
    r = subprocess.run(
        [sys.executable, "scripts/bench_compare.py", str(po), str(pbi)],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
