# Copyright 2026 tpu-swirld authors.
"""Explicit-state model checker suite (``-m mc``).

Tier-1 tier: the exhaustive smoke world (n=3, events=2) explored clean
with a >2x partial-order/symmetry reduction, determinism of the
exploration itself, the POR state-coverage proof (reduced exploration
visits the SAME state set as the naive baseline, just over fewer
transitions), every seeded mutation caught by its expected invariant
with a minimized counterexample that replays bit-identically, the
counterexample JSON round-trip through the chaos harness, and the CLI
exit-code contract.

``-m slow`` tier: the events=3 exhaustive configs (vanilla and forker
worlds) — minutes, not seconds.
"""

import json

import pytest

from tpu_swirld import crypto
from tpu_swirld.analysis.mc import (
    INVARIANTS, MUTATIONS, explore, make_world, mc_smoke, run_mc,
)
from tpu_swirld.analysis.mc import counterexample as ce
from tpu_swirld.analysis.mc.cli import main as mc_main
from tpu_swirld.chaos import replay_counterexample

pytestmark = pytest.mark.mc


@pytest.fixture()
def sim_backend():
    """Force the deterministic sim crypto backend for tests that drive
    ``World``/``explore`` directly (``run_mc`` scopes it internally)."""
    prev = crypto.backend_name()
    crypto.set_backend("sim")
    yield
    crypto.set_backend(prev)


# ------------------------------------------------------------ exhaustive


def test_smoke_world_explores_clean_with_reduction():
    rep = mc_smoke()           # n=3, events=2, with the naive baseline
    assert rep["ok"]
    assert rep["exhaustive"]
    assert rep["violations"] == 0
    assert rep["states"] > 1000          # non-trivial space
    # ISSUE acceptance: POR + symmetry shrink the space by >2x
    assert rep["state_ratio"] > 2
    assert rep["transition_ratio"] > 2


def test_exploration_is_deterministic(sim_backend):
    runs = [
        explore(make_world(None, n_honest=3, n_forkers=0, events=2))
        for _ in range(2)
    ]
    assert runs[0].to_dict() == runs[1].to_dict()
    assert runs[0].exhaustive and runs[0].violation is None


def test_por_preserves_state_coverage(sim_backend):
    """Sleep-set POR is sound: it prunes redundant *transitions*, never
    states — the reduced run must visit exactly the naive state count.
    Symmetry (honest-member relabeling) is what shrinks the state set."""
    kw = dict(n_honest=3, n_forkers=0, events=2)
    naive = explore(make_world(None, **kw), por=False, symmetry=False,
                    check_invariants=False)
    por_only = explore(make_world(None, **kw), por=True, symmetry=False,
                       check_invariants=False)
    reduced = explore(make_world(None, **kw), por=True, symmetry=True,
                      check_invariants=False)
    assert naive.exhaustive and por_only.exhaustive and reduced.exhaustive
    assert por_only.states == naive.states
    assert por_only.transitions < naive.transitions
    assert reduced.states < por_only.states


# ------------------------------------------------------------- mutations


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_mutation_caught_with_minimized_replayable_witness(name):
    rep = run_mc(mutate=name, compare=False)
    cex = rep.get("counterexample")
    assert cex is not None, f"mutation {name} produced no violation"
    assert cex["caught_expected"], (
        f"{name}: expected {MUTATIONS[name].expected_invariant}, "
        f"got {cex['violation']['invariant']}"
    )
    assert cex["minimized_len"] <= cex["schedule_len"]
    # the minimized document replays bit-deterministically
    assert cex["replay_reproduced"]
    assert cex["replay_digests_match"]
    assert cex["replay_trace_match"]


def test_counterexample_doc_roundtrip(tmp_path):
    out = tmp_path / "ce.json"
    rep = run_mc(mutate="fork-blind", compare=False, out=str(out))
    doc = json.loads(out.read_text())
    assert doc["kind"] == "mc-counterexample"
    assert doc["world"]["mutate"] == "fork-blind"
    assert doc["violation"]["invariant"] == "fork-budget"
    assert doc["schedule"] == [
        list(a) for a in rep["counterexample"]["document"]["schedule"]
    ]
    # chaos-harness ingestion: replay fidelity gates ok for mutated docs
    chaos_rep = replay_counterexample(str(out))
    assert chaos_rep["kind"] == "mc-replay"
    assert chaos_rep["reproduced"] and chaos_rep["digests_match"]
    assert chaos_rep["ok"]


def test_clean_schedule_doc_parity_probe(sim_backend):
    """A violation-free document is a clean replayable schedule: replay
    asserts it STAYS clean, and the chaos harness adds the cross-engine
    parity rows on the replayed hashgraph."""
    world = make_world(None, n_honest=3, n_forkers=0, events=3)
    schedule = [
        ("sync", 1, 0), ("sync", 0, 1), ("sync", 2, 0),
        ("pull", 0, 2), ("pull", 1, 2),
    ]
    report = ce.run_checked(world, schedule)
    assert report["violation"] is None
    doc = ce.emit(world, schedule, report)
    assert doc["violation"] is None
    rep = replay_counterexample(doc)
    assert rep["violation"] is None
    assert rep["reproduced"] and rep["digests_match"] and rep["trace_match"]
    assert rep["ok"]


# ------------------------------------------------------------------- CLI


def test_cli_exit_codes(tmp_path):
    # clean exhaustive run -> 0
    assert mc_main(["--events", "1", "--no-compare"]) == 0
    # mutation run finds its expected violation -> 1, and saves the doc
    out = tmp_path / "cli_ce.json"
    assert mc_main(["--mutate", "fork-blind", "--out", str(out)]) == 1
    assert json.loads(out.read_text())["violation"]["invariant"] == (
        "fork-budget"
    )
    # state cap hit before exhaustion -> 2 (nothing proven)
    assert mc_main(
        ["--events", "2", "--max-states", "50", "--no-compare"]
    ) == 2
    # unknown mutation is an argparse error
    with pytest.raises(SystemExit):
        mc_main(["--mutate", "no-such-bug"])


def test_catalog_is_well_formed():
    ids = [inv.id for inv in INVARIANTS]
    assert len(ids) == len(set(ids))
    assert {m.expected_invariant for m in MUTATIONS.values()} <= set(ids)
    assert all(inv.kind in ("state", "edge") for inv in INVARIANTS)


# -------------------------------------------------------------- slow tier


@pytest.mark.slow
def test_exhaustive_events3_vanilla():
    rep = run_mc(events=3, compare=False)
    assert rep["explore"]["exhaustive"]
    assert rep["explore"]["violations_found"] == 0
    assert rep["explore"]["states"] > 20_000


@pytest.mark.slow
def test_exhaustive_events3_forker():
    rep = run_mc(n=2, forkers=1, events=3, compare=False)
    assert rep["explore"]["exhaustive"]
    assert rep["explore"]["violations_found"] == 0
