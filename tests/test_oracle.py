"""Oracle consensus: scripted micro-DAGs, sims, determinism, liveness."""

import pytest

from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.sim import make_simulation, test as sim_test


def scripted_rounds(sim, n_layers):
    """Deterministic dense gossip: each layer, node i syncs with (i+1)%n."""
    n = len(sim.nodes)
    for _layer in range(n_layers):
        for i in range(n):
            sim.tick()
            node = sim.nodes[i]
            peer = sim.nodes[(i + 1) % n].pk
            new = node.sync(peer, b"")
            node.consensus_pass(new)


def test_genesis_is_round0_witness():
    sim = make_simulation(4, seed=0)
    for node in sim.nodes:
        assert node.round[node.head] == 0
        assert node.is_witness[node.head]


def test_rounds_advance_under_dense_gossip():
    sim = make_simulation(4, seed=0)
    scripted_rounds(sim, 12)
    node = sim.nodes[0]
    assert node.max_round >= 3
    # every event's round >= parents' rounds, exceeding by at most 1
    for eid, ev in node.hg.items():
        if ev.p:
            pr = max(node.round[ev.p[0]], node.round[ev.p[1]])
            assert node.round[eid] in (pr, pr + 1)
    # witness == first event of creator in its round
    for r, by_creator in node.witnesses.items():
        for c, wids in by_creator.items():
            for w in wids:
                sp = node.hg[w].self_parent
                assert sp is None or node.round[sp] < r


def test_dense_gossip_witnesses_famous_and_ordered():
    sim = make_simulation(4, seed=0)
    scripted_rounds(sim, 16)
    node = sim.nodes[0]
    # early-round witnesses in dense honest gossip are all famous
    for r in (0, 1, 2):
        wids = [w for ws in node.witnesses[r].values() for w in ws]
        assert len(wids) == 4
        assert all(node.famous[w] is True for w in wids)
    assert len(node.consensus) > 0
    # round_received non-decreasing along consensus order
    rr = [node.round_received[x] for x in node.consensus]
    assert rr == sorted(rr)
    # consensus timestamps non-decreasing within a round bucket
    for i in range(1, len(node.consensus)):
        if rr[i] == rr[i - 1]:
            a, b = node.consensus[i - 1], node.consensus[i]
            assert node.consensus_ts[a] <= node.consensus_ts[b]


def test_random_sim_prefix_consistency_and_determinism():
    sim = sim_test(4, 300, seed=1)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 100
    assert all(o[:m] == orders[0][:m] for o in orders)
    sim2 = sim_test(4, 300, seed=1)
    assert sim2.nodes[0].consensus == sim.nodes[0].consensus
    sim3 = sim_test(4, 300, seed=2)
    assert sim3.nodes[0].consensus != sim.nodes[0].consensus


def test_weighted_stake_supermajority():
    cfg = SwirldConfig(n_members=4, stake=(3, 1, 1, 1), seed=0)
    sim = make_simulation(4, seed=0, config=cfg)
    scripted_rounds(sim, 12)
    node = sim.nodes[0]
    assert node.tot_stake == 6
    assert node.max_round >= 2
    assert len(node.consensus) > 0


def test_sixteen_member_sim_reaches_consensus():
    sim = sim_test(16, 1200, seed=7)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0
    assert all(o[:m] == orders[0][:m] for o in orders)


class TestValidation:
    def setup_method(self):
        self.sim = make_simulation(4, seed=3)
        self.node = self.sim.nodes[0]
        self.peer = self.sim.nodes[1]

    def test_unknown_creator_rejected(self):
        from tpu_swirld import crypto

        pk, sk = crypto.keypair(b"outsider")
        ev = Event(d=b"", p=(), t=1, c=pk).signed(sk)
        assert not self.node.is_valid_event(ev)

    def test_bad_signature_rejected(self):
        ev = self.peer.hg[self.peer.head]
        forged = Event(d=ev.d + b"!", p=ev.p, t=ev.t, c=ev.c, s=ev.s)
        assert not self.node.is_valid_event(forged)

    def test_missing_parent_rejected(self):
        node = self.node
        ev = Event(
            d=b"", p=(node.head, b"\x00" * 32), t=5, c=node.pk
        ).signed(node.sk)
        assert not node.is_valid_event(ev)

    def test_wrong_selfparent_creator_rejected(self):
        node, peer = self.node, self.peer
        # give node the peer's genesis so the parent exists locally
        node.add_event(peer.hg[peer.head])
        ev = Event(
            d=b"", p=(peer.head, node.head), t=5, c=node.pk
        ).signed(node.sk)
        assert not node.is_valid_event(ev)

    def test_other_parent_same_creator_rejected(self):
        node = self.node
        ev = Event(d=b"", p=(node.head, node.head), t=5, c=node.pk).signed(
            node.sk
        )
        assert not node.is_valid_event(ev)

    def test_add_is_idempotent(self):
        ev = self.peer.hg[self.peer.head]
        assert self.node.add_event(ev) is True
        assert self.node.add_event(ev) is False

    def test_bad_sync_request_signature_rejected(self):
        with pytest.raises(ValueError):
            self.node.ask_sync(self.peer.pk, b"\x00" * 100)
