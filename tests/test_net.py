"""The net layer's unit surface: framing, WAL durability, tx ingestion,
the SW003 justified-suppression scope, and the socket-transport parity
suite (same schedule over the in-process Transport and a loopback
SocketTransport must decide bit-identical prefixes).

Everything here runs in-process or over loopback sockets owned by the
test; the real-process cluster lives in tests/test_cluster.py.
"""

import collections
import os
import random
import socket
import struct
import threading

import pytest

from tpu_swirld import crypto
from tpu_swirld.analysis.lint import check_source
from tpu_swirld.config import SwirldConfig, resolve_net_settings
from tpu_swirld.net import frame
from tpu_swirld.net.frame import FrameError, allocate_ports
from tpu_swirld.net.ingest import TxPool, decode_batch, encode_batch
from tpu_swirld.net.transport import SocketTransport
from tpu_swirld.net.wal import MAGIC, TAG_EVENT, OwnEventWal
from tpu_swirld.net.node_proc import NodeServer, startup_postmortem
from tpu_swirld.obs.flightrec import FlightRecorder, load_dump
from tpu_swirld.obs.tracer import pack_context
from tpu_swirld.oracle.event import Event, encode_event
from tpu_swirld.oracle.node import Node
from tpu_swirld.net.proxy import FaultyProxy, ProxyFleet
from tpu_swirld.transport import (
    CHANNEL_SYNC, DeliveryTimeout, FaultPlan, LinkFaults, Partition,
    PeerUnreachable, Transport,
)

# ------------------------------------------------------------- framing


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frame_request_reply_roundtrip():
    a, b = _pair()
    try:
        frame.send_request(a, frame.KIND_SYNC, b"S" * 32, b"payload-bytes")
        kind, src, payload, trace = frame.recv_request(b)
        assert (kind, src, payload, trace) == (
            frame.KIND_SYNC, b"S" * 32, b"payload-bytes", b"",
        )
        frame.send_reply(b, frame.STATUS_OK, b"reply-bytes")
        assert frame.recv_reply(a) == (frame.STATUS_OK, b"reply-bytes")
        # empty src and empty payload are legal frames
        frame.send_request(a, frame.KIND_PING, b"", b"")
        assert frame.recv_request(b) == (frame.KIND_PING, b"", b"", b"")
    finally:
        a.close()
        b.close()


def _expect_frame_error(raw, recv_fn, **kw):
    """Feed raw bytes to a receiver on a fresh pair (a FrameError can
    fire before the body is drained, so cases never share a stream)."""
    a, b = _pair()
    try:
        a.sendall(raw)
        with pytest.raises(FrameError):
            recv_fn(b, **kw)
    finally:
        a.close()
        b.close()


def test_frame_rejects_oversized_and_garbage_lengths():
    # a garbage length prefix must raise BEFORE any allocation
    _expect_frame_error(
        struct.pack("<I", frame.MAX_FRAME_BYTES + 1), frame.recv_request,
    )
    # a request frame too short to hold its own header
    _expect_frame_error(struct.pack("<I", 1) + b"\x00", frame.recv_request)
    # src length overrunning the frame body
    body = frame._REQ_HEAD.pack(frame.KIND_SYNC, 500) + b"short"
    _expect_frame_error(
        struct.pack("<I", len(body)) + body, frame.recv_request,
    )
    # zero-length reply frame cannot hold a status byte
    _expect_frame_error(struct.pack("<I", 0), frame.recv_reply)
    # per-call max_frame tightens the ceiling below the default
    a, b = _pair()
    try:
        frame.send_request(a, frame.KIND_SYNC, b"", b"x" * 100)
        with pytest.raises(FrameError):
            frame.recv_request(b, max_frame=50)
    finally:
        a.close()
        b.close()


def test_frame_eof_mid_frame_is_connection_error():
    a, b = _pair()
    a.sendall(struct.pack("<I", 10) + b"abc")   # promises 10, sends 3
    a.close()
    try:
        with pytest.raises(ConnectionError):
            frame.recv_request(b)
    finally:
        b.close()


def test_frame_trace_context_roundtrip():
    """A traced frame carries its 16-byte context between src and
    payload; the receiver masks the flag off the kind byte."""
    ctx = pack_context(b"trace-id", 0x1234)
    a, b = _pair()
    try:
        frame.send_request(a, frame.KIND_SUBMIT, b"S" * 8, b"tx", trace=ctx)
        assert frame.recv_request(b) == (frame.KIND_SUBMIT, b"S" * 8,
                                         b"tx", ctx)
        # empty src / empty payload still frame correctly with a trace
        frame.send_request(a, frame.KIND_SYNC, b"", b"", trace=ctx)
        assert frame.recv_request(b) == (frame.KIND_SYNC, b"", b"", ctx)
    finally:
        a.close()
        b.close()
    # a wrong-sized context is the sender's bug, refused before the wire
    a, b = _pair()
    try:
        with pytest.raises(ValueError):
            frame.send_request(a, frame.KIND_SYNC, b"", b"x", trace=b"short")
    finally:
        a.close()
        b.close()
    # a flagged frame too short for its context is connection garbage
    body = frame._REQ_HEAD.pack(frame.KIND_SYNC | frame.TRACE_FLAG, 0) + b"123"
    _expect_frame_error(
        struct.pack("<I", len(body)) + body, frame.recv_request,
    )


def test_frame_old_header_parses_under_new_decoder():
    """Wire compat, old sender -> new receiver: a hand-built pre-trace
    frame (no flag, no context) decodes exactly as before with an empty
    trace — untraced frames are byte-identical to the old format."""
    src, payload = b"oldpk", b"old-payload"
    body = frame._REQ_HEAD.pack(frame.KIND_SYNC, len(src)) + src + payload
    a, b = _pair()
    try:
        a.sendall(struct.pack("<I", len(body)) + body)
        assert frame.recv_request(b) == (frame.KIND_SYNC, src, payload, b"")
        # and the new sender's untraced output IS that old byte layout
        frame.send_request(a, frame.KIND_SYNC, src, payload)
    finally:
        a.close()
    try:
        raw = frame.recv_exact(b, 4 + len(body))
        assert raw == struct.pack("<I", len(body)) + body
    finally:
        b.close()


def _pre_trace_recv_request(sock, max_frame=frame.MAX_FRAME_BYTES):
    """The decoder as it shipped BEFORE the trace-context header: no
    flag masking — a flagged kind byte surfaces verbatim.  Kept as a
    test stub to pin how an old node reacts to a new traced frame."""
    (nbytes,) = struct.unpack("<I", frame.recv_exact(sock, 4))
    if nbytes < frame._REQ_HEAD.size or nbytes > max_frame:
        raise FrameError(f"bad request frame length {nbytes}")
    body = frame.recv_exact(sock, nbytes)
    kind, src_len = frame._REQ_HEAD.unpack_from(body)
    off = frame._REQ_HEAD.size + src_len
    if off > len(body):
        raise FrameError(f"request src overruns frame ({src_len} bytes)")
    return kind, body[frame._REQ_HEAD.size:off], body[off:]


def test_frame_new_header_rejected_cleanly_by_pre_trace_decoder():
    """Wire compat, new sender -> old receiver: the flagged kind byte
    decodes to an *unknown* kind (0x80 | kind), which every dispatch
    layer rejects via its documented unknown-kind ``ValueError`` path —
    a clean REJECT, never a misparse into a real request."""
    ctx = pack_context(b"trace-id", 7)
    a, b = _pair()
    try:
        frame.send_request(a, frame.KIND_SUBMIT, b"pk", b"tx-bytes",
                           trace=ctx)
        kind, src, payload = _pre_trace_recv_request(b)
        assert kind == (frame.KIND_SUBMIT | frame.TRACE_FLAG)
        known = {frame.KIND_SYNC, frame.KIND_WANT, frame.KIND_SUBMIT,
                 frame.KIND_STATUS, frame.KIND_STOP, frame.KIND_PING,
                 frame.KIND_METRICS}
        assert kind not in known   # -> the unknown-kind REJECT path
        # framing itself stays sound: src parses, the context rides
        # inside what the old decoder sees as payload, nothing misaligns
        assert src == b"pk"
        assert payload == ctx + b"tx-bytes"
        with pytest.raises(ValueError):
            raise ValueError("unknown kind %d" % kind)
    finally:
        a.close()
        b.close()


def test_wal_records_carry_no_trace_bytes(tmp_path):
    """Trace ids are transport-only: the WAL byte stream is a pure
    function of the events — appending under an active traced span
    writes byte-identical records, and torn-tail recovery of such a file
    hands back events with nothing trace-related attached."""
    from tpu_swirld.obs.tracer import Tracer

    pk, sk = crypto.keypair(b"wal-trace-free")
    evs = _own_events(pk, sk, 3)
    plain, traced = str(tmp_path / "plain.wal"), str(tmp_path / "traced.wal")
    w = OwnEventWal(plain, pk=pk)
    for ev in evs:
        w.append(ev)
    w.close()
    tr = Tracer(pid=9)
    ctx = pack_context(b"\xabtrace!!", 0)
    with tr.span_under("gossip.sync", ctx):
        w2 = OwnEventWal(traced, pk=pk)
        for ev in evs:
            w2.append(ev)
        w2.close()
    with open(plain, "rb") as f:
        plain_bytes = f.read()
    with open(traced, "rb") as f:
        traced_bytes = f.read()
    assert plain_bytes == traced_bytes
    assert ctx not in traced_bytes and b"\xabtrace!!" not in traced_bytes
    # torn-tail recovery of the traced-context file: same durable prefix,
    # and recovered events expose exactly the Event surface — no trace
    with open(traced, "wb") as f:
        f.write(traced_bytes[:-3])
    t = OwnEventWal(traced, pk=pk)
    assert t.torn_tail_recovered == 1
    assert [e.id for e in t.events] == [e.id for e in evs[:-1]]
    assert not any(hasattr(e, "trace") for e in t.events)
    t.close()


def test_allocate_ports_distinct_and_bindable():
    ports = allocate_ports(8)
    assert len(set(ports)) == 8
    for p in ports:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", p))
        s.close()


# ------------------------------------------------------- own-event WAL


def _own_events(pk, sk, n, tag=b"w"):
    return [
        Event(
            d=tag + b"-%d" % i,
            p=(crypto.hash_bytes(tag + b"p%d" % i),
               crypto.hash_bytes(tag + b"q%d" % i)),
            t=i, c=pk,
        ).signed(sk)
        for i in range(n)
    ]


def test_wal_roundtrip_and_clean_marker_semantics(tmp_path):
    pk, sk = crypto.keypair(b"wal-owner")
    path = str(tmp_path / "own.wal")
    w = OwnEventWal(path, pk=pk)
    assert not w.existed and not w.unclean
    evs = _own_events(pk, sk, 3)
    for ev in evs:
        w.append(ev)
    w.mark_clean()
    # reopen: clean shutdown observed, events intact, marker consumed
    w2 = OwnEventWal(path, pk=pk)
    assert w2.existed and w2.clean_shutdown and not w2.unclean
    assert [e.id for e in w2.events] == [e.id for e in evs]
    assert w2.torn_tail_recovered == 0
    w2.close()
    # the reopen truncated the marker away: a third open without a new
    # mark_clean sees an unclean shutdown — "clean" only ever holds
    # between a graceful stop and the next start
    w3 = OwnEventWal(path, pk=pk)
    assert w3.unclean and not w3.clean_shutdown
    assert [e.id for e in w3.events] == [e.id for e in evs]
    w3.close()


def test_wal_torn_tail_truncation_at_every_offset(tmp_path):
    """kill -9 tears the last append at an arbitrary byte: recovery must
    keep exactly the durable prefix at EVERY possible cut point, count
    the torn tail, and let appending resume from the cut."""
    pk, sk = crypto.keypair(b"wal-torn")
    path = str(tmp_path / "torn.wal")
    w = OwnEventWal(path, pk=pk)
    evs = _own_events(pk, sk, 3)
    for ev in evs:
        w.append(ev)
    w.close()
    with open(path, "rb") as f:
        data = f.read()
    last_rec = bytes([TAG_EVENT]) + encode_event(evs[-1])
    last_start = len(data) - len(last_rec)
    assert data[last_start:] == last_rec
    prefix_ids = [e.id for e in evs[:-1]]
    for cut in range(last_start, len(data)):
        torn_path = str(tmp_path / ("cut-%d.wal" % cut))
        with open(torn_path, "wb") as f:
            f.write(data[:cut])
        t = OwnEventWal(torn_path, pk=pk)
        assert [e.id for e in t.events] == prefix_ids, cut
        # a cut exactly on the record boundary is a whole-record loss,
        # not torn bytes; every other offset is a detected torn tail
        assert t.torn_tail_recovered == (0 if cut == last_start else 1), cut
        assert t.unclean
        # appending resumes cleanly from the truncated prefix
        extra = _own_events(pk, sk, 1, tag=b"extra")[0]
        t.append(extra)
        t.close()
        t2 = OwnEventWal(torn_path, pk=pk)
        assert [e.id for e in t2.events] == prefix_ids + [extra.id], cut
        assert t2.torn_tail_recovered == 0
        t2.close()


def test_wal_corrupt_tail_and_foreign_creator_recovered(tmp_path):
    pk, sk = crypto.keypair(b"wal-corrupt")
    path = str(tmp_path / "c.wal")
    w = OwnEventWal(path, pk=pk)
    evs = _own_events(pk, sk, 2)
    for ev in evs:
        w.append(ev)
    w.close()
    with open(path, "rb") as f:
        data = f.read()
    # bit-rot inside the last record body: decodes-but-unverifiable (or
    # undecodable) — either way the valid prefix is events[:-1]
    last_rec = bytes([TAG_EVENT]) + encode_event(evs[-1])
    flip_at = len(data) - len(last_rec) // 2
    flipped = bytearray(data)
    flipped[flip_at] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(flipped))
    t = OwnEventWal(path, pk=pk)
    assert [e.id for e in t.events] == [evs[0].id]
    assert t.torn_tail_recovered == 1
    t.close()
    # a record naming a foreign creator can only mean corruption: an own-
    # event WAL never holds another member's history
    other_pk, other_sk = crypto.keypair(b"other-member")
    foreign = _own_events(other_pk, other_sk, 1, tag=b"f")[0]
    path2 = str(tmp_path / "f.wal")
    w2 = OwnEventWal(path2, pk=pk)
    w2.append(evs[0])
    w2.close()
    with open(path2, "ab") as f:
        f.write(bytes([TAG_EVENT]) + encode_event(foreign))
    t2 = OwnEventWal(path2, pk=pk)
    assert [e.id for e in t2.events] == [evs[0].id]
    assert t2.torn_tail_recovered == 1
    t2.close()
    # a whole-file mangle (bad magic) recovers to an empty WAL
    path3 = str(tmp_path / "m.wal")
    with open(path3, "wb") as f:
        f.write(b"NOTAWAL" + b"\x00" * 40)
    t3 = OwnEventWal(path3, pk=pk)
    assert t3.events == [] and t3.torn_tail_recovered == 1
    t3.append(evs[0])
    t3.close()
    t4 = OwnEventWal(path3, pk=pk)
    assert [e.id for e in t4.events] == [evs[0].id]
    t4.close()


def test_wal_clean_marker_mid_file_is_torn_state(tmp_path):
    pk, sk = crypto.keypair(b"wal-mid")
    path = str(tmp_path / "mid.wal")
    w = OwnEventWal(path, pk=pk)
    evs = _own_events(pk, sk, 2)
    w.append(evs[0])
    w.mark_clean()
    # bytes after a "clean" marker mean the file kept growing after a
    # supposedly-final close: torn state, recover the prefix
    with open(path, "ab") as f:
        f.write(bytes([TAG_EVENT]) + encode_event(evs[1]))
    t = OwnEventWal(path, pk=pk)
    assert [e.id for e in t.events] == [evs[0].id]
    assert t.torn_tail_recovered == 1 and not t.clean_shutdown
    t.close()


def test_wal_rewrite_prunes_atomically(tmp_path):
    pk, sk = crypto.keypair(b"wal-prune")
    path = str(tmp_path / "p.wal")
    w = OwnEventWal(path, pk=pk)
    evs = _own_events(pk, sk, 4)
    for ev in evs:
        w.append(ev)
    w.rewrite(evs[2:])          # checkpoint covered the first two
    assert [e.id for e in w.events] == [e.id for e in evs[2:]]
    tail = _own_events(pk, sk, 1, tag=b"t")[0]
    w.append(tail)              # appending still works post-rewrite
    w.close()
    t = OwnEventWal(path, pk=pk)
    assert [e.id for e in t.events] == [evs[2].id, evs[3].id, tail.id]
    assert t.torn_tail_recovered == 0
    t.close()
    assert not os.path.exists(path + ".tmp")


# ------------------------------------------------------- tx ingestion


def test_txpool_ack_dup_and_batch_roundtrip():
    pool = TxPool(max_pool=100, batch_bytes=1 << 16, max_tx_bytes=1024)
    ok, reply = pool.submit(b"hello")
    txid = crypto.hash_bytes(b"hello")
    assert ok and reply == b"ACK:" + txid.hex().encode()
    ok2, reply2 = pool.submit(b"hello")
    assert not ok2 and reply2 == b"DUP:" + txid.hex().encode()
    pool.submit(b"world")
    batch = pool.next_batch()
    assert decode_batch(batch) == [b"hello", b"world"]
    assert pool.next_batch() == b""        # drained
    # batched txs stay deduplicated after the drain
    ok3, reply3 = pool.submit(b"hello")
    assert not ok3 and reply3.startswith(b"DUP:")
    c = pool.counters
    assert c["tx_submitted"] == 4 and c["tx_accepted"] == 2
    assert c["tx_duplicate"] == 2 and c["tx_batched"] == 2


def test_txpool_shed_oversize_pool_and_window():
    window = [0]
    pool = TxPool(
        max_pool=2, batch_bytes=1 << 16, max_tx_bytes=8,
        max_undecided=10, window_fn=lambda: window[0],
    )
    assert pool.submit(b"x" * 9) == (False, b"SHED:oversize")
    assert pool.submit(b"") == (False, b"SHED:oversize")
    window[0] = 11                          # behind on consensus: shed
    assert pool.submit(b"a") == (False, b"SHED:window")
    window[0] = 10                          # at the threshold: admit
    assert pool.submit(b"a")[0]
    assert pool.submit(b"b")[0]
    assert pool.submit(b"c") == (False, b"SHED:pool")
    c = pool.counters
    assert c["tx_shed_oversize"] == 2
    assert c["tx_shed_window"] == 1
    assert c["tx_shed_pool"] == 1
    assert len(pool.pending) == 2


def test_txpool_batch_size_cap_and_fifo_order():
    pool = TxPool(max_pool=100, batch_bytes=64, max_tx_bytes=1024)
    txs = [b"tx-%02d" % i + b"y" * 10 for i in range(10)]
    for tx in txs:
        assert pool.submit(tx)[0]
    drained = []
    while pool.pending:
        batch = pool.next_batch()
        assert len(batch) <= 64
        drained.extend(decode_batch(batch))
    assert drained == txs                   # FIFO across batches
    assert pool.counters["tx_batches"] >= 2


def test_txpool_oversized_single_tx_still_ships():
    """One tx bigger than batch_bytes must still drain (a batch always
    ships >= 1 tx) — otherwise it wedges the FIFO head forever."""
    pool = TxPool(max_pool=10, batch_bytes=16, max_tx_bytes=1024)
    big = b"B" * 100
    assert pool.submit(big)[0]
    assert decode_batch(pool.next_batch()) == [big]


def test_decode_batch_total_on_garbage():
    assert decode_batch(b"") == []
    assert decode_batch(b"tx:0:1") == []           # legacy sim payload
    assert decode_batch(b"TXB1") == []             # truncated header
    assert decode_batch(b"TXB1\x02\x00\x04\x00\x00\x00abcd") == []
    good = encode_batch([b"a", b"bb"])
    assert decode_batch(good) == [b"a", b"bb"]
    assert decode_batch(good[:-1]) == []           # torn tail
    assert decode_batch(encode_batch([])) == []


# ----------------------------------------- SW003 justified suppression


_CLOCK_SRC = "import time\n\ndef f():\n    return time.monotonic(){}\n"


def _sw003(module_path, suffix, prefix=""):
    return check_source(
        prefix + _CLOCK_SRC.format(suffix),
        module_path=module_path, rules=["SW003"],
    )


def test_sw003_net_scope_requires_justified_suppression():
    # net/ is in scope: an unsuppressed wall-clock read is a finding
    assert len(_sw003("net/x.py", "")) == 1
    # a bare line disable no longer suppresses inside net/
    assert len(_sw003("net/x.py", "   # swirld-lint: disable=SW003")) == 1
    # a justified suppression (``-- why``) does
    assert _sw003(
        "net/x.py",
        "   # swirld-lint: disable=SW003 -- deployment-edge deadline",
    ) == []
    # a note for a DIFFERENT rule id does not cover SW003
    assert len(_sw003(
        "net/x.py", "   # swirld-lint: disable=SW001 -- wrong rule",
    )) == 1
    # disable-file never counts in the note scope: the wall-clock
    # surface must stay enumerable line by line
    assert len(_sw003(
        "net/x.py", "", prefix="# swirld-lint: disable-file=SW003\n",
    )) == 1


def test_sw003_note_scope_is_pinned_to_net():
    # outside the rule's scope entirely: no finding to suppress
    assert _sw003("sim.py", "") == []
    # in scope but outside note_scope: the old bare-disable semantics
    # still hold (no churn on existing suppressions)
    assert len(_sw003("transport.py", "")) == 1
    assert _sw003("transport.py", "   # swirld-lint: disable=SW003") == []


def test_net_package_wall_clock_surface_is_exactly_frame():
    """The shipped net/ package passes its own gate: every wall-clock
    read lives in frame.py behind a justified suppression."""
    import tpu_swirld.net as netpkg
    from tpu_swirld.analysis.lint import lint_paths

    findings = lint_paths(
        [os.path.dirname(netpkg.__file__)], rules=["SW003"],
    )
    assert findings == [], [str(f) for f in findings]


# -------------------------------------------------- socket transport


def _serve_node(node, port):
    def dispatch(kind, src, payload, trace=b""):
        if kind == frame.KIND_SYNC:
            return frame.STATUS_OK, node.ask_sync(src, payload)
        if kind == frame.KIND_WANT:
            return frame.STATUS_OK, node.ask_events(src, payload)
        raise ValueError("unknown kind %d" % kind)

    return NodeServer("127.0.0.1", port, dispatch, frame.MAX_FRAME_BYTES)


def test_socket_transport_parity_with_in_process_transport():
    """Same members, same seed, same schedule — the in-process Transport
    and a loopback SocketTransport must decide bit-identical prefixes
    (the wire is a delivery detail, never a consensus input)."""
    n, turns, seed = 3, 60, 11
    config = SwirldConfig(n_members=n, seed=seed)
    keys = [crypto.keypair(b"parity-%d" % i) for i in range(n)]
    members = [pk for pk, _ in keys]

    # reference: the in-process dict-of-endpoints transport
    clock = [0]
    network, network_want = {}, {}
    ref_transport = Transport(network, network_want)
    ref_nodes = []
    for pk, sk in keys:
        node = Node(
            sk=sk, pk=pk, network=network, members=members, config=config,
            clock=lambda: clock[0], network_want=network_want,
            transport=ref_transport,
        )
        network[pk] = node.ask_sync
        network_want[pk] = node.ask_events
        ref_nodes.append(node)

    # candidate: the same nodes behind loopback TCP
    ports = allocate_ports(n)
    clock2 = [0]
    settings = resolve_net_settings()
    sock_nodes, servers, transports = [], [], []
    try:
        for i, (pk, sk) in enumerate(keys):
            st = SocketTransport(settings=settings, src=pk)
            for j, pk_j in enumerate(members):
                if j != i:
                    st.register(pk_j, "127.0.0.1", ports[j])
            node = Node(
                sk=sk, pk=pk, network={}, members=members, config=config,
                clock=lambda: clock2[0], transport=st,
            )
            transports.append(st)
            sock_nodes.append(node)
        for i, node in enumerate(sock_nodes):
            servers.append(_serve_node(node, ports[i]))

        # one seeded schedule, two delivery layers: identical draws
        rng_a, rng_b = random.Random(seed), random.Random(seed)
        for t in range(turns):
            clock[0] = t + 1
            i = rng_a.randrange(n)
            node = ref_nodes[i]
            peer = rng_a.choice([m for m in members if m != node.pk])
            new = node.sync(peer, b"tx:%d" % t)
            if new:
                node.consensus_pass(new)
        for t in range(turns):
            clock2[0] = t + 1
            i = rng_b.randrange(n)
            node = sock_nodes[i]
            peer = rng_b.choice([m for m in members if m != node.pk])
            new = node.sync(peer, b"tx:%d" % t)
            if new:
                node.consensus_pass(new)
    finally:
        for s in servers:
            s.close()
        for st in transports:
            st.close()

    ref_orders = [list(nd.consensus) for nd in ref_nodes]
    sock_orders = [list(nd.consensus) for nd in sock_nodes]
    assert min(len(o) for o in ref_orders) > 0
    assert sock_orders == ref_orders
    # the decided EVENTS (not just ids) are bit-identical too
    for ref, cand in zip(ref_nodes, sock_nodes):
        for eid in ref.consensus:
            assert encode_event(ref.hg[eid]) == encode_event(cand.hg[eid])
    # real traffic flowed over the wire
    assert all(st.stats["calls"] > 0 for st in transports)


def test_socket_transport_error_plane_mapping():
    pk_self, _ = crypto.keypair(b"err-self")
    pk_peer, _ = crypto.keypair(b"err-peer")
    settings = resolve_net_settings()
    settings["connect_timeout_s"] = 0.5
    settings["call_timeout_s"] = 0.3

    # no address registered at all
    st = SocketTransport(settings=settings, src=pk_self)
    with pytest.raises(PeerUnreachable):
        st.call(pk_self, pk_peer, CHANNEL_SYNC, b"x")
    assert st.endpoint(pk_peer, CHANNEL_SYNC) is None

    # nothing listening on the port: connect refused -> PeerUnreachable
    (port,) = allocate_ports(1)
    st.register(pk_peer, "127.0.0.1", port)
    assert st.endpoint(pk_peer, CHANNEL_SYNC) == ("127.0.0.1", port)
    with pytest.raises(PeerUnreachable):
        st.call(pk_self, pk_peer, CHANNEL_SYNC, b"x")
    assert st.stats["connect_failures"] >= 1

    # a listener that never replies: deadline -> DeliveryTimeout
    silent = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    silent.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    silent.bind(("127.0.0.1", 0))
    silent.listen(1)
    st.register(pk_peer, "127.0.0.1", silent.getsockname()[1])
    try:
        with pytest.raises(DeliveryTimeout):
            st.call(pk_self, pk_peer, CHANNEL_SYNC, b"x")
        assert st.stats["timeouts"] == 1
    finally:
        silent.close()
        st.close()


def test_socket_transport_status_reject_and_error_planes():
    """STATUS_REJECT resurfaces as the endpoints' documented ValueError
    (counted bad reply, never retried); STATUS_ERROR is retryable."""
    (port,) = allocate_ports(1)
    mode = {"raise": ValueError("bad request payload")}

    def dispatch(kind, src, payload, trace=b""):
        raise mode["raise"]

    server = NodeServer("127.0.0.1", port, dispatch, frame.MAX_FRAME_BYTES)
    pk_self, _ = crypto.keypair(b"rej-self")
    pk_peer, _ = crypto.keypair(b"rej-peer")
    st = SocketTransport(settings=resolve_net_settings(), src=pk_self)
    st.register(pk_peer, "127.0.0.1", port)
    try:
        with pytest.raises(ValueError, match="bad request payload"):
            st.call(pk_self, pk_peer, CHANNEL_SYNC, b"x")
        assert st.stats["rejects"] == 1
        mode["raise"] = RuntimeError("server bug")
        with pytest.raises(PeerUnreachable, match="server error"):
            st.call(pk_self, pk_peer, CHANNEL_SYNC, b"x")
        assert st.stats["peer_errors"] == 1
    finally:
        server.close()
        st.close()


def test_socket_transport_redials_stale_cached_connection():
    """A cached connection killed server-side is redialed once,
    transparently — a restarted peer costs one redial, not a failure."""
    (port,) = allocate_ports(1)

    # a one-shot first incarnation: serves one request, closes the conn
    # AND the listener (so the "restarted" server can re-bind the port)
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind(("127.0.0.1", port))
    ls.listen(1)

    def one_shot():
        conn, _addr = ls.accept()
        _kind, _src, payload, _trace = frame.recv_request(conn)
        frame.send_reply(conn, frame.STATUS_OK, b"pong:" + payload)
        conn.close()
        ls.close()

    t = threading.Thread(target=one_shot, daemon=True)
    t.start()

    pk_self, _ = crypto.keypair(b"redial-self")
    pk_peer, _ = crypto.keypair(b"redial-peer")
    st = SocketTransport(settings=resolve_net_settings(), src=pk_self)
    st.register(pk_peer, "127.0.0.1", port)
    server = None
    try:
        assert st.call(pk_self, pk_peer, CHANNEL_SYNC, b"a") == b"pong:a"
        t.join(5)
        assert not t.is_alive()

        def dispatch(kind, src, payload, trace=b""):
            return frame.STATUS_OK, b"pong:" + payload

        server = NodeServer(
            "127.0.0.1", port, dispatch, frame.MAX_FRAME_BYTES,
        )
        # the cached conn is dead; the call must redial, not fail
        assert st.call(pk_self, pk_peer, CHANNEL_SYNC, b"b") == b"pong:b"
    finally:
        if server is not None:
            server.close()
        st.close()
        ls.close()


# ------------------------------------------------ startup post-mortem


def test_startup_postmortem_dumps_only_on_unclean_wal(tmp_path):
    pk, sk = crypto.keypair(b"pm-owner")
    path = str(tmp_path / "pm.wal")
    w = OwnEventWal(path, pk=pk)
    for ev in _own_events(pk, sk, 2):
        w.append(ev)
    w.mark_clean()
    dump_dir = str(tmp_path / "dumps")
    os.makedirs(dump_dir)
    # clean shutdown: no dump
    clean = OwnEventWal(path, pk=pk)
    rec = FlightRecorder(dump_dir=dump_dir, wall_clock=lambda: 0.0)
    assert startup_postmortem(clean, rec, "n0") is None
    clean.close()
    # that reopen consumed the marker; the next open is unclean — the
    # previous incarnation "died" without a graceful stop
    unclean = OwnEventWal(path, pk=pk)
    assert unclean.unclean
    dump = startup_postmortem(unclean, rec, "n0")
    assert dump is not None and os.path.exists(dump)
    doc = load_dump(dump)
    assert doc["reason"] == "unclean_shutdown"
    assert rec.trigger_counts["unclean_shutdown"] == 1
    unclean.close()
    # no dump dir: the trigger is recorded but returns no path
    rec2 = FlightRecorder(dump_dir=None)
    unclean2 = OwnEventWal(path, pk=pk)
    assert startup_postmortem(unclean2, rec2, "n1") is None
    assert rec2.trigger_counts["unclean_shutdown"] == 1
    unclean2.close()


def test_node_server_worker_threads_keep_no_state():
    """SW006 surface check: NodeServer's worker threads must not store
    mutable state on self — everything flows through the dispatch
    closure (the lock discipline the analysis suite audits)."""
    (port,) = allocate_ports(1)
    seen = []
    done = threading.Event()

    def dispatch(kind, src, payload, trace=b""):
        seen.append((kind, src, payload, trace))
        done.set()
        return frame.STATUS_OK, b"ok"

    server = NodeServer("127.0.0.1", port, dispatch, frame.MAX_FRAME_BYTES)
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.settimeout(5.0)
            frame.send_request(s, frame.KIND_PING, b"me", b"probe")
            assert frame.recv_reply(s) == (frame.STATUS_OK, b"ok")
        assert done.wait(5)
        assert seen == [(frame.KIND_PING, b"me", b"probe", b"")]
    finally:
        server.close()

# ------------------------------------------------- socket fault proxy


def _echo_node(port):
    def dispatch(kind, src, payload, trace=b""):
        return frame.STATUS_OK, b"pong:" + payload

    return NodeServer("127.0.0.1", port, dispatch, frame.MAX_FRAME_BYTES)


def _proxy_call(addr, payload, timeout=5.0):
    with socket.create_connection(tuple(addr), timeout=timeout) as s:
        s.settimeout(timeout)
        frame.send_request(s, frame.KIND_SYNC, b"tester", payload)
        return frame.recv_reply(s)


def test_faulty_proxy_clean_relay():
    """A fault-free plan relays frames bit-intact in both directions."""
    (up_port,) = allocate_ports(1)
    server = _echo_node(up_port)
    stats = collections.Counter()
    proxy = FaultyProxy(
        0, 1, ("127.0.0.1", up_port), FaultPlan(seed=5),
        clock=lambda: 0.0, count=lambda k: stats.update([k]),
    )
    try:
        for i in range(3):
            status, reply = _proxy_call(proxy.addr, b"hello-%d" % i)
            assert (status, reply) == (frame.STATUS_OK, b"pong:hello-%d" % i)
        assert stats["relayed"] == 3
        assert stats["drops"] == 0 and stats["partition_blocked"] == 0
    finally:
        proxy.close()
        server.close()


def test_faulty_proxy_partition_blocks_then_heals():
    """Inside a scheduled partition window the proxy eats the frame and
    tears the connection; once the injected clock passes the window the
    same link relays again — no proxy restart, no reconfiguration."""
    (up_port,) = allocate_ports(1)
    server = _echo_node(up_port)
    stats = collections.Counter()
    now = [5.0]
    plan = FaultPlan(
        seed=5, partitions=[Partition(start=0.0, end=10.0, group=(0,))],
    )
    proxy = FaultyProxy(
        0, 1, ("127.0.0.1", up_port), plan,
        clock=lambda: now[0], count=lambda k: stats.update([k]),
    )
    try:
        with socket.create_connection(tuple(proxy.addr), timeout=5) as s:
            s.settimeout(5.0)
            frame.send_request(s, frame.KIND_SYNC, b"t", b"blocked")
            with pytest.raises((ConnectionError, FrameError)):
                frame.recv_reply(s)
        assert stats["partition_blocked"] == 1
        assert stats["relayed"] == 0
        now[0] = 10.0   # heal: start <= t < end no longer holds
        status, reply = _proxy_call(proxy.addr, b"after")
        assert (status, reply) == (frame.STATUS_OK, b"pong:after")
        assert stats["relayed"] == 1
    finally:
        proxy.close()
        server.close()


def test_faulty_proxy_drop_and_reset_semantics():
    """drop=1.0 loses the request BEFORE the upstream sees it; reset=1.0
    tears the client connection AFTER the upstream processed the request
    (the redial-after-success hazard the transport must absorb)."""
    (up_port,) = allocate_ports(1)
    seen = []

    def dispatch(kind, src, payload, trace=b""):
        seen.append(payload)
        return frame.STATUS_OK, b"ok"

    server = NodeServer("127.0.0.1", up_port, dispatch, frame.MAX_FRAME_BYTES)
    stats = collections.Counter()

    def mk(lf):
        return FaultyProxy(
            0, 1, ("127.0.0.1", up_port), FaultPlan(seed=7, default=lf),
            clock=lambda: 0.0, count=lambda k: stats.update([k]),
        )

    dropper = mk(LinkFaults(drop=1.0))
    try:
        with socket.create_connection(tuple(dropper.addr), timeout=5) as s:
            s.settimeout(5.0)
            frame.send_request(s, frame.KIND_SYNC, b"t", b"lost")
            with pytest.raises((ConnectionError, FrameError)):
                frame.recv_reply(s)
        assert stats["drops"] >= 1 and seen == []
    finally:
        dropper.close()

    resetter = mk(LinkFaults(reset=1.0))
    try:
        with socket.create_connection(tuple(resetter.addr), timeout=5) as s:
            s.settimeout(5.0)
            frame.send_request(s, frame.KIND_SYNC, b"t", b"processed")
            with pytest.raises((ConnectionError, FrameError)):
                frame.recv_reply(s)
        assert stats["resets"] >= 1
        assert seen == [b"processed"]   # the destination DID apply it
    finally:
        resetter.close()
        server.close()


def test_proxy_fleet_routes_every_directed_link():
    """One proxy per directed pair, each with its own port; frames sent
    to addr_for(i, j) land on upstream j; shared stats aggregate."""
    ports = allocate_ports(2)
    servers = [_echo_node(p) for p in ports]
    fleet = ProxyFleet(FaultPlan(seed=3), 2, ports)
    try:
        addrs = {
            (i, j): fleet.addr_for(i, j)
            for i in range(2) for j in range(2) if i != j
        }
        assert len(set(addrs.values())) == 2   # distinct listeners
        assert set(addrs.values()).isdisjoint(
            {("127.0.0.1", p) for p in ports}
        )
        for (i, j), addr in sorted(addrs.items()):
            status, reply = _proxy_call(addr, b"link-%d-%d" % (i, j))
            assert (status, reply) == (
                frame.STATUS_OK, b"pong:link-%d-%d" % (i, j),
            )
        assert fleet.stats["relayed"] == 2
    finally:
        fleet.close()
        for s in servers:
            s.close()


def test_socket_transport_reprobe_bridges_restart_gap():
    """satellite: a peer mid-restart kills the cached connection AND has
    no listener bound yet.  The transparent redial's cold connect fails;
    the bounded re-probe (redial_probe_s) must bridge the gap so the
    call succeeds with one redial + one probe instead of surfacing a
    spurious PeerUnreachable."""
    (port,) = allocate_ports(1)
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind(("127.0.0.1", port))
    ls.listen(1)

    def one_shot():
        conn, _addr = ls.accept()
        _kind, _src, payload, _trace = frame.recv_request(conn)
        frame.send_reply(conn, frame.STATUS_OK, b"pong:" + payload)
        conn.close()
        ls.close()   # the dying incarnation's listener goes away too

    threading.Thread(target=one_shot, daemon=True).start()

    pk_self, _ = crypto.keypair(b"probe-self")
    pk_peer, _ = crypto.keypair(b"probe-peer")
    settings = resolve_net_settings()
    settings["redial_probe_s"] = 1.0
    st = SocketTransport(settings=settings, src=pk_self)
    st.register(pk_peer, "127.0.0.1", port)

    reborn = {}

    def rebind_later():
        frame.sleep(0.35)   # the restart gap: no listener during it

        def dispatch(kind, src, payload, trace=b""):
            return frame.STATUS_OK, b"pong2:" + payload

        reborn["server"] = NodeServer(
            "127.0.0.1", port, dispatch, frame.MAX_FRAME_BYTES,
        )

    try:
        assert st.call(pk_self, pk_peer, CHANNEL_SYNC, b"a") == b"pong:a"
        t = threading.Thread(target=rebind_later, daemon=True)
        t.start()
        # cached conn is dead, listener absent: redial fails its cold
        # connect, the probe waits out the gap, the retry lands
        assert st.call(pk_self, pk_peer, CHANNEL_SYNC, b"b") == b"pong2:b"
        t.join(5)
        assert st.stats["redials"] >= 1
        assert st.stats["redial_probes"] == 1
    finally:
        st.close()
        if "server" in reborn:
            reborn["server"].close()


def test_wal_torn_tail_recovery_under_active_partition():
    """satellite: kill -9 tears the WAL tail while the survivor's only
    link is partitioned.  Recovery of the durable prefix is purely local
    (needs no network); gossip through the healed link then backfills
    the missing other-parents so every recovered event rejoins the DAG."""
    n, seed = 2, 23
    config = SwirldConfig(n_members=n, seed=seed)
    keys = [crypto.keypair(b"walpart-%d" % i) for i in range(n)]
    members = [pk for pk, _ in keys]

    # ---- phase A: a genuine own-event chain, appended like node_proc
    clock = [0]
    network, network_want = {}, {}
    transport = Transport(network, network_want)
    nodes = []
    for pk, sk in keys:
        node = Node(
            sk=sk, pk=pk, network=network, members=members, config=config,
            clock=lambda: clock[0], network_want=network_want,
            transport=transport,
        )
        network[pk] = node.ask_sync
        network_want[pk] = node.ask_events
        nodes.append(node)
    import tempfile
    wal_path = os.path.join(
        tempfile.mkdtemp(prefix="swirld-walpart-"), "n0.wal",
    )
    wal = OwnEventWal(wal_path, pk=members[0])
    wal.append(nodes[0].hg[nodes[0].head])   # durable genesis
    for t in range(4):
        clock[0] = t + 1
        new = nodes[0].sync(members[1], b"tx:%d" % t)
        if new:
            nodes[0].consensus_pass(new)
        wal.append(nodes[0].hg[nodes[0].head])
    n_appended = len(wal.events)
    wal.close()   # no mark_clean: this incarnation "dies"
    with open(wal_path, "r+b") as f:
        f.truncate(os.path.getsize(wal_path) - 3)   # torn mid-record

    # ---- phase B: restart behind a partitioned proxy link
    (peer_port,) = allocate_ports(1)
    server = _serve_node(nodes[1], peer_port)
    now = [0.0]
    plan = FaultPlan(
        seed=seed, partitions=[Partition(start=0.0, end=100.0, group=(0,))],
    )
    pstats = collections.Counter()
    proxy = FaultyProxy(
        0, 1, ("127.0.0.1", peer_port), plan,
        clock=lambda: now[0], count=lambda k: pstats.update([k]),
    )
    st = SocketTransport(settings=resolve_net_settings(), src=members[0])
    st.register(members[1], proxy.addr[0], proxy.addr[1])
    try:
        # torn-tail recovery is local: durable prefix, counted tear —
        # with the only peer link dead
        wal2 = OwnEventWal(wal_path, pk=members[0])
        assert wal2.unclean
        assert wal2.torn_tail_recovered == 1
        assert len(wal2.events) == n_appended - 1
        clock2 = [100]
        node0b = Node(
            sk=keys[0][1], pk=members[0], network={}, members=members,
            config=config, clock=lambda: clock2[0], transport=st,
        )
        wal_ids = []
        node0b._ingest(wal2.events, wal_ids)   # node_proc's boot replay
        if wal_ids:
            node0b.consensus_pass(wal_ids)
        # the link is down: pull degrades to an empty delta (it never
        # raises on peer behavior) — recovery above already held
        assert node0b.sync(members[1], b"during-partition") == []
        assert pstats["partition_blocked"] >= 1
        assert st.stats["conn_errors"] >= 1
        # the WAL keeps accepting appends during the partition
        wal2.append(node0b.hg[node0b.head])
        # heal: the same link carries gossip again; node1's events
        # backfill the recovered chain's other-parents.  The clock jump
        # also clears any breaker cooldown the dead link accrued.
        now[0] = 100.0
        clock2[0] = 1000
        new = node0b.sync(members[1], b"post-heal")
        assert new
        missing = [e.id for e in wal2.events if e.id not in node0b.hg]
        assert missing == []   # every recovered event rejoined the DAG
        wal2.close()
    finally:
        st.close()
        proxy.close()
        server.close()
