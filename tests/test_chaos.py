"""Chaos harness: the acceptance scenario, crash-recovery fidelity, the
rebase-storm guard, and checkpoint handoff into the incremental pipeline.

The smoke scenario here is the PR's acceptance gate: >=20% drop,
reordering, one partition + heal, one crash + checkpoint-restart, one
equivocating forker — completing with every honest node's decided order
bit-identical to a prefix of the fault-free oracle replay, decided rounds
advancing after heal, and zero uncaught exceptions (the run finishing IS
the assertion; nothing in the gossip path may raise on peer behavior).
"""

import dataclasses

import pytest

from tpu_swirld import obs as obslib
from tpu_swirld.chaos import ChaosScenario, ChaosSimulation
from tpu_swirld.checkpoint import load_node, load_packed, save_node, save_packed
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.packing import pack_events
from tpu_swirld.sim import generate_gossip_dag
from tpu_swirld.tpu.pipeline import IncrementalConsensus, run_consensus
from tpu_swirld.transport import FaultPlan, LinkFaults, Partition

from tests.test_incremental import assert_same_result


def _acceptance_scenario(seed=3):
    plan = FaultPlan(
        seed=seed,
        default=LinkFaults(
            drop=0.2, corrupt=0.05, duplicate=0.05, reorder=0.1, delay=0.05,
        ),
        partitions=[Partition(start=80, end=140, group=(0, 1))],
        crashes={4: [(60, 120)]},
    )
    return ChaosScenario(
        n_nodes=5, n_turns=240, seed=seed, n_forkers=1, plan=plan,
        checkpoint_every=40,
    )


@pytest.mark.chaos
def test_chaos_smoke_acceptance_scenario(tmp_path):
    v = ChaosSimulation(_acceptance_scenario(), str(tmp_path)).run()
    # safety: bit-identical decided prefixes, equal to the oracle replay
    assert v["safety"]["prefix_agree"], v
    assert v["safety"]["oracle_agree"], v
    assert v["safety"]["common_prefix_len"] > 0
    # liveness: decided rounds advanced after the partition healed and the
    # crashed node restarted from its checkpoint
    assert v["liveness"]["advanced_after_heal"], v
    # every scheduled fault class actually fired
    f = v["faults"]
    assert f["drops"] > 0 and f["partition_blocked"] > 0
    assert f["crash_blocked"] > 0 and f["reorders"] > 0
    r = v["resilience"]
    assert r["crashes"] == 1 and r["restarts"] == 1
    assert r["retries"] > 0 and r["backoff_total"] > 0
    assert r["forks_detected"] >= 1       # the equivocator was caught
    assert v["ok"], v


@pytest.mark.chaos
def test_chaos_run_reproducible_from_seeds(tmp_path):
    """The whole verdict — fault counts included — replays from seeds."""
    v1 = ChaosSimulation(_acceptance_scenario(), str(tmp_path / "a")).run()
    v2 = ChaosSimulation(_acceptance_scenario(), str(tmp_path / "b")).run()
    assert v1 == v2


@pytest.mark.chaos
def test_chaos_crash_restart_reconverges_bit_identical(tmp_path):
    """The restarted node's decided order must be byte-equal to a prefix
    of the never-crashed nodes' — restore + gossip replay is exact."""
    sim = ChaosSimulation(_acceptance_scenario(seed=8), str(tmp_path))
    v = sim.run()
    assert v["ok"], v
    crashed = sim.nodes[4]
    survivor = sim.nodes[2]
    k = min(len(crashed.consensus), len(survivor.consensus))
    assert k > 0
    assert crashed.consensus[:k] == survivor.consensus[:k]


def test_chaos_whole_cluster_outage_is_dead_air_not_crash(tmp_path):
    """Overlapping crash windows covering every honest member must play
    out as dead-air turns, not a mid-run exception."""
    sc = ChaosScenario(
        n_nodes=2, n_turns=80, seed=1,
        plan=FaultPlan(crashes={0: [(5, 20)], 1: [(5, 20)]}),
        checkpoint_every=4,
    )
    v = ChaosSimulation(sc, str(tmp_path)).run()
    assert v["resilience"]["crashes"] == 2
    assert v["resilience"]["restarts"] == 2
    assert v["safety"]["prefix_agree"]


def test_chaos_scenario_validation(tmp_path):
    bad = ChaosScenario(
        n_nodes=4, n_turns=50, seed=0,
        plan=FaultPlan(partitions=[Partition(start=10, end=60, group=(0,))]),
    )
    with pytest.raises(ValueError):
        ChaosSimulation(bad, str(tmp_path))
    bad2 = ChaosScenario(
        n_nodes=4, n_turns=50, seed=0, plan=FaultPlan(crashes={1: [(0, 10)]})
    )
    with pytest.raises(ValueError):
        ChaosSimulation(bad2, str(tmp_path))


@pytest.mark.chaos
def test_forking_adversary_rides_faulty_transport():
    """Byzantine fork injection and network faults compose through one
    transport: the sim helpers accept a faulty transport_factory."""
    from tpu_swirld.sim import run_with_forkers
    from tpu_swirld.transport import FaultyTransport

    def factory(network, network_want, members, clock):
        return FaultyTransport(
            network, network_want,
            FaultPlan(seed=5, default=LinkFaults(drop=0.15, reorder=0.1)),
            members, clock,
        )

    sim = run_with_forkers(
        5, 1, 220, seed=5, fork_every=6, transport_factory=factory
    )
    assert sim.transport.stats["drops"] > 0
    forker_pk = sim.nodes[0].pk
    assert any(n.has_fork[forker_pk] for n in sim.nodes)
    orders = [n.consensus for n in sim.nodes]
    m = min(len(o) for o in orders)
    assert m > 0 and all(o[:m] == orders[0][:m] for o in orders)


@pytest.mark.smoke
@pytest.mark.chaos
def test_chaos_run_cli_smoke(tmp_path):
    """scripts/chaos_run.py: seeded run -> JSON verdict artifact + trace,
    exit 0 on an ok verdict, and the report CLI renders the resilience
    section from the emitted trace."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "verdict.json"
    r = subprocess.run(
        [
            sys.executable, "scripts/chaos_run.py",
            # seed re-pinned when fault streams moved to per-link
            # SeedSequence spawns (seed 3's schedule starves one node)
            "--seed", "1", "--plan-seed", "1", "--nodes", "5",
            "--turns", "240", "--forkers", "1",
            "--out", str(out),
        ],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stdout + r.stderr
    v = json.loads(out.read_text())
    assert v["ok"] and v["safety"]["oracle_agree"]
    trace = tmp_path / "verdict.trace.jsonl"
    assert trace.exists()
    r2 = subprocess.run(
        [sys.executable, "-m", "tpu_swirld.obs", "report", str(trace)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "resilience" in r2.stdout
    assert "transport_drops_total" in r2.stdout


# ------------------------------------------------- horizon / overflow storms


@pytest.mark.chaos
def test_horizon_storm_all_engines_bit_identical(tmp_path):
    """The acceptance scenario for the deterministic expiry horizon: a
    minority member signs against its stale view through a partition; at
    heal its straggler tail lands below the majority's committed frontier.
    Every honest node must register the stragglers identically and the
    probe node's live state must be bit-identical to a batch device replay
    and an incremental drive — the history the old node-local quarantine
    excluded from parity suites entirely."""
    from tpu_swirld.chaos import run_horizon_storm

    v = run_horizon_storm(str(tmp_path))
    h = v["horizon"]
    assert h["late_witnesses"] > 0, "the straggler corner must actually fire"
    assert h["horizon_violations"] == 0
    assert h["batch_oracle_parity"]
    assert h["incremental_batch_parity"]
    assert v["safety"]["prefix_agree"] and v["safety"]["oracle_agree"]
    assert v["liveness"]["advanced_after_heal"]
    assert v["ok"], v


@pytest.mark.chaos
def test_overflow_storm_cli_selfheals_with_parity(tmp_path):
    """scripts/chaos_run.py --scenario overflow_storm: both self-healing
    legs (fork-storm s_max doubling, round-clamp unclamped retry) complete
    with parity and an ok JSON verdict."""
    import importlib.util
    import json
    import os

    spec = importlib.util.spec_from_file_location(
        "chaos_run", os.path.join(os.path.dirname(__file__), "..",
                                  "scripts", "chaos_run.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "overflow_verdict.json"
    rc = mod.main(["--scenario", "overflow_storm", "--out", str(out)])
    assert rc == 0
    v = json.loads(out.read_text())
    assert v["fork_storm"]["overflow_retries"] >= 1
    assert v["fork_storm"]["parity"]
    assert v["round_clamp"]["overflow_retries"] >= 1
    assert v["round_clamp"]["parity"]
    assert v["ok"], v


# ------------------------------------------------------ rebase-storm guard


def _straggler_flood(n_events=600, n_floods=8, seed=6):
    """A decided-and-pruned main stream followed by a flood of ancient
    fork leaves: every flood event names long-pruned parents, so each
    un-guarded ingest pays a detected rebase."""
    members, stake, events, keys = generate_gossip_dag(8, n_events, seed=seed)
    by_creator = {}
    for ev in events:
        by_creator.setdefault(ev.c, []).append(ev)
    floods = []
    for k in range(n_floods):
        ci = k % 8
        pk, sk = keys[ci]
        old_self = by_creator[pk][2 + (k % 3)]
        old_other = by_creator[members[(ci + 1) % 8]][2]
        floods.append(
            Event(
                d=b"straggler:%d" % k, p=(old_self.id, old_other.id),
                t=old_self.t + 1, c=pk,
            ).signed(sk)
        )
    return members, stake, events, floods


def _drive_flood(members, stake, events, floods, **kw):
    cfg = SwirldConfig(n_members=len(members))
    inc = IncrementalConsensus(
        members, stake, cfg, block=64, chunk=64, window_bucket=256,
        prune_min=64, **kw,
    )
    for i in range(0, len(events), 100):
        inc.ingest(events[i : i + 100])
    for f in floods:
        inc.ingest(f if isinstance(f, list) else [f])
    return inc


def test_rebase_storm_guard_caps_consecutive_rebases():
    members, stake, events, floods = _straggler_flood()
    # control: guard disabled — the flood thrashes one rebase per pass
    control = _drive_flood(members, stake, events, floods, storm_threshold=0)
    assert control.max_consecutive_rebases >= len(floods) - 1
    assert control.storm_entries == 0
    # guarded: consecutive *detected* rebases are capped at the threshold;
    # the guard then holds full-recompute mode through the cooldown
    with obslib.enabled() as o:
        guarded = _drive_flood(
            members, stake, events, floods, storm_threshold=3, storm_cooldown=4
        )
    assert guarded.max_consecutive_rebases <= 3
    assert guarded.storm_entries >= 1
    assert guarded.storm_rebases >= 1
    # the fallback decision is visible in the obs gauges
    reg = o.registry
    assert reg.value("incremental_storm_rebases_total") == guarded.storm_rebases
    assert reg.value("incremental_storm_mode") is not None
    assert reg.value("incremental_consecutive_rebases") is not None
    # and the guard never bends the exactness contract
    cfg = SwirldConfig(n_members=len(members))
    delivery = list(events) + list(floods)
    ref = run_consensus(pack_events(delivery, members, stake), cfg, block=64)
    assert_same_result(guarded.result(), ref)
    assert_same_result(control.result(), ref)


def test_storm_guard_exits_after_cooldown_on_clean_traffic():
    """Hysteresis: once the flood stops, the cooldown drains and clean
    incremental passes resume (storm mode must not latch forever)."""
    members, stake, events, floods = _straggler_flood(n_floods=4)
    cfg = SwirldConfig(n_members=len(members))
    inc = IncrementalConsensus(
        members, stake, cfg, block=64, chunk=64, window_bucket=256,
        prune_min=64, storm_threshold=2, storm_cooldown=2,
    )
    for i in range(0, 500, 100):
        inc.ingest(events[i : i + 100])
    for f in floods:
        inc.ingest([f])
    assert inc.storm_entries >= 1
    # clean tail traffic: the remaining honest events, small chunks
    stats = None
    for i in range(500, len(events), 25):
        stats = inc.ingest(events[i : i + 25])
    assert stats is not None and not stats["storm_mode"]
    assert not stats["rebased"]        # incremental path re-admitted
    delivery = events[:500] + floods + events[500:]
    ref = run_consensus(pack_events(delivery, members, stake), cfg, block=64)
    assert_same_result(inc.result(), ref)


# --------------------------------------------- checkpoint handoff fidelity


def test_checkpoint_packed_roundtrip_into_incremental_pipeline(tmp_path):
    """save_packed/load_packed must hand the incremental driver's packed
    state to a cold batch pass bit-identically (crash-recovery for the
    device pipeline: restore the packed DAG, recompute, same outputs)."""
    members, stake, events, _keys = generate_gossip_dag(6, 400, seed=9)
    cfg = SwirldConfig(n_members=6)
    inc = IncrementalConsensus(
        members, stake, cfg, block=64, chunk=64, window_bucket=256,
        prune_min=64,
    )
    for i in range(0, len(events), 80):
        inc.ingest(events[i : i + 80])
    path = str(tmp_path / "inc.npz")
    save_packed(path, inc.packer.pack())
    restored = load_packed(path)
    assert_same_result(inc.result(), run_consensus(restored, cfg, block=64))


def test_checkpoint_horizon_digest_verified_on_restore(tmp_path):
    """save_node embeds the decided-prefix digest; load_node must verify
    the replay re-decides that exact prefix, and fail LOUDLY on a
    tampered checkpoint instead of resuming from diverged state."""
    import json
    import struct

    from tpu_swirld.sim import make_simulation

    sim = make_simulation(3, seed=21)
    sim.run(80)
    node = sim.nodes[0]
    assert len(node.consensus) > 0
    path = str(tmp_path / "n.swck")
    save_node(path, node)
    restored = load_node(path, sk=node.sk, pk=node.pk, network={})
    assert restored.consensus == node.consensus
    assert restored._frozen_round == node._frozen_round

    # tamper with the recorded digest -> restore must refuse
    with open(path, "rb") as f:
        data = f.read()
    (hlen,) = struct.unpack_from("<I", data, 4)
    meta = json.loads(data[8 : 8 + hlen].decode())
    meta["order_digest"] = "00" * 32
    header = json.dumps(meta).encode()
    with open(path, "wb") as f:
        f.write(b"SWCK" + struct.pack("<I", len(header)) + header
                + data[8 + hlen:])
    with pytest.raises(ValueError, match="diverged"):
        load_node(path, sk=node.sk, pk=node.pk, network={})


def test_checkpoint_node_restore_preserves_resilience_surface(tmp_path):
    """load_node must come back with the full transport stack attached:
    breaker, retry policy, and the transport it is handed."""
    from tpu_swirld.sim import make_simulation
    from tpu_swirld.transport import Transport

    sim = make_simulation(3, seed=21)
    sim.run(60)
    node = sim.nodes[0]
    path = str(tmp_path / "n.swck")
    save_node(path, node)
    transport = Transport(sim.network, {})
    restored = load_node(
        path, sk=node.sk, pk=node.pk, network=sim.network,
        transport=transport,
    )
    assert restored.consensus == node.consensus
    assert restored.transport is transport
    assert restored.breaker is not None
    assert restored.retry_policy.attempts == node.retry_policy.attempts
    got = restored.pull(sim.nodes[1].pk)
    restored.consensus_pass(got)
    m = min(len(restored.consensus), len(sim.nodes[1].consensus))
    assert restored.consensus[:m] == sim.nodes[1].consensus[:m]
