"""Packing layer: dense arrays must faithfully mirror the oracle's DAG."""

import numpy as np

from tpu_swirld.packing import pack_node
from tpu_swirld.sim import make_simulation, run_with_forkers


def closure_from_parents(parents: np.ndarray) -> np.ndarray:
    """Reference reflexive-transitive closure (slow host loop)."""
    n = parents.shape[0]
    anc = np.zeros((n, n), dtype=bool)
    for i in range(n):
        anc[i, i] = True
        for p in parents[i]:
            if p >= 0:
                anc[i] |= anc[p]
    return anc


def test_pack_node_mirrors_oracle():
    sim = make_simulation(4, seed=3)
    sim.run(120)
    node = sim.nodes[0]
    packed = pack_node(node)

    assert packed.n == len(node.order_added)
    for i, eid in enumerate(node.order_added):
        ev = node.hg[eid]
        assert packed.ids[i] == eid
        assert packed.creator[i] == node.member_index[ev.c]
        assert packed.seq[i] == node.seq[eid]
        assert packed.t[i] == ev.t
        assert packed.coin[i] == (ev.coin_bit() & 1)
        if ev.p:
            assert packed.parents[i, 0] == node.idx[ev.p[0]]
            assert packed.parents[i, 1] == node.idx[ev.p[1]]
        else:
            assert tuple(packed.parents[i]) == (-1, -1)

    # parents strictly before children (topo order invariant)
    idxs = np.arange(packed.n)
    assert (packed.parents < idxs[:, None]).all()

    # ancestor closure from packed parents == oracle bitmasks
    anc = closure_from_parents(packed.parents)
    for i, eid in enumerate(node.order_added):
        mask = node.anc[eid]
        bits = np.array([(mask >> j) & 1 for j in range(packed.n)], dtype=bool)
        assert (anc[i] == bits).all()

    # member table covers each member's events in order
    for ci, m in enumerate(node.members):
        want = [node.idx[e] for e in node.member_events[m]]
        got = [int(v) for v in packed.member_table[ci] if v >= 0]
        assert got == want


def test_pack_fork_pairs_match_oracle_groups():
    sim = run_with_forkers(n_nodes=7, n_forkers=2, n_turns=200, seed=9)
    # find a node that saw a fork
    node = next(
        n for n in sim.nodes if any(n.has_fork[m] for m in sim.members)
    )
    packed = pack_node(node)
    want = set()
    for m in node.members:
        ci = node.member_index[m]
        for _seq, ids in node.fork_groups[m].items():
            idxs = sorted(node.idx[e] for e in ids)
            for a_i in range(len(idxs)):
                for b_i in range(a_i + 1, len(idxs)):
                    want.add((ci, idxs[a_i], idxs[b_i]))
    got = {(int(r[0]), int(r[1]), int(r[2])) for r in packed.fork_pairs}
    assert got == want
    assert len(want) > 0


def test_incremental_append_equals_one_shot():
    sim = make_simulation(4, seed=1)
    sim.run(60)
    node = sim.nodes[1]
    from tpu_swirld.packing import Packer, pack_events

    stake = [node.stake[m] for m in node.members]
    inc = Packer(node.members, stake)
    # append in two batches, with idempotent re-append of the first half
    events = [node.hg[e] for e in node.order_added]
    half = len(events) // 2
    inc.extend(events[:half])
    inc.extend(events[:half])      # idempotent
    inc.extend(events)             # completes the rest
    a = inc.pack()
    b = pack_events(events, node.members, stake)
    assert a.n == b.n
    for field in ("parents", "creator", "seq", "t", "coin", "member_table"):
        assert (getattr(a, field) == getattr(b, field)).all()
    assert a.ids == b.ids


def test_packer_pack_reuses_buffers_incrementally():
    """Satellite contract: pack() snapshots views of the packer's
    amortized buffers instead of rebuilding every slab — consecutive
    packs share memory, and incremental extends stay prefix-identical
    to a from-scratch pack."""
    from tpu_swirld.packing import Packer, pack_events
    from tpu_swirld.sim import generate_gossip_dag

    members, stake, events, _keys = generate_gossip_dag(4, 300, seed=1)
    p = Packer(members, stake)
    p.extend(events[:200])
    a = p.pack()
    b = p.pack()
    # no appends between packs -> the big per-event slabs share memory
    for name in ("parents", "creator", "seq", "t", "coin"):
        assert np.shares_memory(getattr(a, name), getattr(b, name)), name
    # appends past a snapshot never mutate it
    snap_parents = a.parents.copy()
    snap_table = a.member_table.copy()
    p.extend(events[200:])
    c = p.pack()
    assert (a.parents == snap_parents).all()
    assert (a.member_table == snap_table).all()
    # incremental result == one-shot pack of the same stream
    full = pack_events(events, members, stake)
    assert c.n == full.n
    assert (c.parents == full.parents).all()
    assert (c.creator == full.creator).all()
    assert (c.seq == full.seq).all()
    assert (c.t == full.t).all()
    assert (c.coin == full.coin).all()
    assert (c.member_table == full.member_table).all()
    assert (c.fork_pairs == full.fork_pairs).all()
    assert c.ids == full.ids
