"""Production-day soak: every fault class at once, one composite verdict.

Each robustness subsystem has its own harness — chaos (in-process
crash/partition), the adversary suite (byzantine strategies), the
real-process cluster (kill -9 + WAL recovery), the flight recorder.
This module composes them into the capstone scenario (ROADMAP item 5):
an N-process cluster gossiping through the socket-level fault injector
(:mod:`tpu_swirld.net.proxy`), under heavy-tailed client traffic
(:mod:`tpu_swirld.net.traffic`), while a declarative *schedule* of
windows interleaves

- **crashes** — :class:`CrashWindow`: SIGKILL at ``at_s``, restart from
  checkpoint + own-event WAL at ``restart_at_s``;
- **partitions** — :class:`PartitionWindow`: every proxied link crossing
  ``group``'s boundary blocked for the window, then healed;
- **byzantine attacks** — :class:`AttackWindow`: a PR 10 adversary
  strategy (:class:`~tpu_swirld.adversary.EquivocationStorm`) run by the
  orchestrator in a reserved member slot, gossiping with honest nodes
  *through the proxy seam* like any other member.

The composite verdict is the union of every harness's bar, judged from
the evidence the processes leave on disk:

- **safety** — every honest decided order is bit-identical to a prefix
  of a fault-free oracle replay of the union event log;
- **liveness** — the decided frontier advanced past EVERY disruption
  window (per-window marks, not just the last heal);
- **finality** — merged submission→decided p99 within
  ``finality_budget_s``;
- **accounting** — zero shed-accounting leaks: every submitted tx lands
  in exactly one ledger bucket and no reply goes unclassified;
- **reports** — every honest node wrote its final report and exited 0.

A red verdict triggers the flight recorder (black box post-mortem) and
— via :func:`shrink` — auto-reduces through the PR 11 ddmin pipeline to
a 1-minimal *replayable schedule document* (``save_doc`` /
``load_doc`` / :func:`replay_doc`), so the failure ships as a small
deterministic repro instead of a 10-minute log pile.

``MUTATIONS`` holds seeded defect injections that must flip the verdict
red (the soak's own regression test): ``shed-leak`` reintroduces the
classifier bug where ``SHED:window`` replies silently vanish from the
per-client ledger.

Knobs resolve field > ``SWIRLD_SOAK_*`` env > default via
:func:`tpu_swirld.config.resolve_soak_settings`.  Wall time flows
through :func:`tpu_swirld.net.frame.now` / :func:`~tpu_swirld.net.
frame.sleep` only — the supervisor of real OS processes lives at the
deployment edge, same as the rest of ``net/``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
from typing import Dict, List, Optional, Tuple

from tpu_swirld.adversary import EquivocationStorm
from tpu_swirld.analysis.mc.counterexample import ddmin
from tpu_swirld.chaos import (
    liveness_section, oracle_replay, safety_section, verdict_ok,
)
from tpu_swirld.config import (
    SwirldConfig, resolve_net_settings, resolve_soak_settings,
)
from tpu_swirld.net import frame
from tpu_swirld.net.cluster import (
    ClusterSpec, ClusterSupervisor, collect_node_state, observer_keypair,
)
from tpu_swirld.net.node_proc import NodeServer
from tpu_swirld.net.traffic import (
    TrafficGenerator, TrafficPlan, classify_reply,
)
from tpu_swirld.net.transport import SocketTransport
from tpu_swirld.obs.finality import merged_dist
from tpu_swirld.obs.flightrec import FlightRecorder
from tpu_swirld.obs.registry import Registry
from tpu_swirld.sim import member_keys
from tpu_swirld.transport import FaultPlan, Partition, TransportError

DOC_KIND = "soak-schedule"
DOC_VERSION = 1


# --------------------------------------------------------------- schedule

@dataclasses.dataclass(frozen=True)
class CrashWindow:
    """SIGKILL node ``index`` at ``at_s``; restart at ``restart_at_s``."""

    index: int
    at_s: float
    restart_at_s: float


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """Block every proxied link crossing ``group`` for the window."""

    start_s: float
    end_s: float
    group: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class AttackWindow:
    """Run a byzantine strategy in member slot ``index`` for the window.

    The slot is reserved (never launched as an honest process); the
    orchestrator serves the adversary's gossip endpoints on the slot's
    port and steps the strategy every ``step_every_s`` inside the
    window.  ``strategy`` names the driver (currently
    ``equivocation-storm``)."""

    start_s: float
    end_s: float
    index: int
    strategy: str = "equivocation-storm"
    n_branches: int = 2
    step_every_s: float = 0.25


@dataclasses.dataclass(frozen=True)
class MembershipWindow:
    """Inject one membership transaction at ``at_s``: the orchestrator
    sends an ``MTX1`` blob (``KIND_MTX``) to the first reachable honest
    node, which rides it on its next gossip event; the change decides
    and activates through ordinary consensus.

    Scheduling any membership window flips the cluster to
    :class:`~tpu_swirld.membership.dynamic.DynamicNode` processes.
    ``action`` is ``restake`` (member's stake becomes ``stake``) or
    ``leave`` (stake zeroed; the slot's process keeps gossiping as a
    zero-stake participant — its events order but carry no vote).
    ``join`` is not a soak action: a fresh member would need a fresh
    process slot, which the fixed-fleet supervisor cannot mint."""

    at_s: float
    action: str = "restake"
    member: int = 1
    stake: int = 3


_WINDOW_KINDS = {
    "crash": CrashWindow,
    "partition": PartitionWindow,
    "attack": AttackWindow,
    "membership": MembershipWindow,
}


def window_to_dict(w) -> Dict:
    """JSON-serializable window (tagged with its ``kind``)."""
    for kind, cls in _WINDOW_KINDS.items():
        if isinstance(w, cls):
            d = dataclasses.asdict(w)
            d["kind"] = kind
            return d
    raise ValueError(f"unknown window type {type(w).__name__}")


def window_from_dict(d: Dict):
    d = dict(d)
    cls = _WINDOW_KINDS[d.pop("kind")]
    if "group" in d:
        d["group"] = tuple(d["group"])
    return cls(**d)


def window_end_s(w) -> float:
    """When the disruption is over (the liveness mark's anchor)."""
    if isinstance(w, CrashWindow):
        return w.restart_at_s
    if isinstance(w, MembershipWindow):
        return w.at_s
    return w.end_s


# -------------------------------------------------------------------- spec

@dataclasses.dataclass
class SoakSpec:
    """One soak run: cluster shape + traffic shape + window schedule."""

    workdir: str
    n_nodes: int = 4
    seed: int = 0
    horizon_s: float = 8.0
    tx_rate: float = 150.0
    n_clients: int = 3
    tx_bytes: int = 64
    pareto_alpha: float = 1.5
    burst_every_s: float = 1.5
    burst_len: int = 20
    reconnect_every_s: float = 2.0
    finality_budget_s: float = 6.0
    schedule: Tuple = ()
    mutate: Optional[str] = None
    net: Dict = dataclasses.field(default_factory=dict)
    flightrec_dir: Optional[str] = None
    #: DynamicNode cluster; auto-set when the schedule holds any
    #: MembershipWindow (kept explicit so ddmin removing the last
    #: membership window still replays the same node class)
    dynamic: bool = False


def default_spec(workdir: str, config=None, **overrides) -> SoakSpec:
    """A :class:`SoakSpec` from the resolved ``SWIRLD_SOAK_*`` knobs
    (field > env > default), ``overrides`` winning over everything."""
    s = resolve_soak_settings(config)
    spec = SoakSpec(
        workdir=workdir,
        n_nodes=s["nodes"],
        horizon_s=s["horizon_s"],
        tx_rate=s["tx_rate"],
        n_clients=s["clients"],
        tx_bytes=s["tx_bytes"],
        pareto_alpha=s["pareto_alpha"],
        finality_budget_s=s["finality_budget_s"],
    )
    return dataclasses.replace(spec, **overrides) if overrides else spec


def smoke_schedule(spec: SoakSpec) -> Tuple:
    """The deterministic tier-1 composition: one SIGKILL crash, one
    partition/heal through the socket proxy, one byzantine attack window
    — each closing with >=20% of the horizon left so the liveness marks
    have room to advance."""
    h = spec.horizon_s
    return (
        AttackWindow(
            start_s=0.5, end_s=h * 0.8, index=spec.n_nodes - 1,
        ),
        CrashWindow(index=1, at_s=h * 0.25, restart_at_s=h * 0.45),
        PartitionWindow(start_s=h * 0.55, end_s=h * 0.75, group=(0,)),
    )


# --------------------------------------------------------------- mutations

def _mutate_shed_leak(net: Dict):
    """Reintroduce the shed-accounting bug: ``SHED:window`` replies fall
    out of the per-client ledger.  The admission window is pinned tight
    so window sheds actually occur while consensus still advances — the
    verdict must go red via the accounting leak alone."""
    def leaky(reply: bytes) -> Optional[str]:
        if reply == b"SHED:window":
            return None
        return classify_reply(reply)
    net = dict(net)
    net.setdefault("max_undecided", 48)
    return leaky, net


#: name -> mutator(net) -> (classify, net); each must flip the composite
#: verdict red on the smoke schedule (exercised by the acceptance test)
MUTATIONS = {"shed-leak": _mutate_shed_leak}


# ---------------------------------------------------------- adversary host

class _AdversaryHost:
    """One :class:`AttackWindow`'s byzantine member, run in-orchestrator.

    Duck-types the :class:`~tpu_swirld.chaos.ChaosSimulation` surface
    the PR 10 drivers read (``keys`` / ``clock`` / ``rng`` / ``network``
    / ``network_want`` / ``members`` / ``config`` / ``transport``), but
    the transport is a real :class:`SocketTransport` registered to every
    honest peer *through the proxy fleet* — the adversary's forks cross
    the same interposed links as honest gossip.  A :class:`NodeServer`
    on the slot's real port serves the strategy's branch views to honest
    askers (the per-link proxies upstream to it).

    Deadlock-free by the same argument as honest nodes: the host lock is
    held across the strategy's outbound pulls, but honest gossip loops
    release their runtime lock around socket I/O, so an honest server
    can always answer us while its own loop waits on our server.
    """

    def __init__(
        self,
        spec: SoakSpec,
        window: AttackWindow,
        sup: ClusterSupervisor,
        settings: Dict,
        byz_indices: Tuple[int, ...],
    ):
        if window.strategy != "equivocation-storm":
            raise ValueError(f"unknown attack strategy {window.strategy!r}")
        self.window = window
        self.keys = member_keys(spec.n_nodes, spec.seed)
        self.members = [pk for pk, _ in self.keys]
        self.config = SwirldConfig(n_members=spec.n_nodes, seed=spec.seed)
        self.clock = [0]
        self.rng = random.Random((spec.seed << 8) ^ 0x50AC ^ window.index)
        self.network: Dict = {}
        self.network_want: Dict = {}
        st = SocketTransport(
            settings=settings, src=self.members[window.index],
        )
        for j, pk in enumerate(self.members):
            if j != window.index:
                h, p = sup.fleet.addr_for(window.index, j)
                st.register(pk, h, p)
        self.transport = st
        self.lock = threading.Lock()
        self.honest_pks = [
            pk for j, pk in enumerate(self.members) if j not in byz_indices
        ]
        self.storm = EquivocationStorm(
            self, window.index, n_branches=window.n_branches,
        )
        self.steps = 0
        self._next_step = window.start_s
        self.server = NodeServer(
            sup.spec.host, sup.ports[window.index], self._dispatch,
            frame.MAX_FRAME_BYTES,
        )

    def _dispatch(self, kind, src, payload, trace):
        if kind == frame.KIND_PING:
            return frame.STATUS_OK, b"pong"
        if kind == frame.KIND_SYNC:
            with self.lock:
                return frame.STATUS_OK, self.storm.ask_sync(src, payload)
        if kind == frame.KIND_WANT:
            with self.lock:
                return frame.STATUS_OK, self.storm.ask_events(src, payload)
        raise ValueError(f"byzantine slot rejects request kind {kind}")

    def maybe_step(self, elapsed_s: float) -> None:
        w = self.window
        if (
            elapsed_s < w.start_s or elapsed_s >= w.end_s
            or elapsed_s < self._next_step
        ):
            return
        self._next_step = elapsed_s + w.step_every_s
        with self.lock:
            self.clock[0] += 1
            try:
                # the storm only swallows ValueError internally; proxied
                # links can also surface transport/socket errors (e.g.
                # a partition window covering the byzantine slot)
                self.storm.step(self.clock[0], self.honest_pks)
                self.steps += 1
            except (TransportError, ValueError, OSError):
                pass

    def close(self) -> None:
        self.server.close()
        self.transport.close()


# --------------------------------------------------------------- orchestra

def _mtx_payload(w: MembershipWindow, members: List[bytes]) -> bytes:
    from tpu_swirld.membership.txs import leave_payload, restake_payload

    if w.action == "restake":
        return restake_payload(members[w.member], w.stake)
    if w.action == "leave":
        return leave_payload(members[w.member])
    raise ValueError(f"unknown membership action {w.action!r}")


def _decided_min(sup: ClusterSupervisor, indices: List[int]) -> int:
    """The lagging decided frontier over the reachable honest nodes."""
    decided = []
    for i in indices:
        try:
            decided.append(sup.client.status(i)["decided"])
        except (OSError, ValueError, KeyError):
            pass
    return min(decided) if decided else 0


def run_soak(spec: SoakSpec) -> Dict:
    """Drive one soak run end to end; returns the composite verdict.

    Never raises on node/verdict behavior — setup failures (ports,
    spawn, readiness) do raise.
    """
    os.makedirs(spec.workdir, exist_ok=True)
    schedule = list(spec.schedule)
    attacks = [w for w in schedule if isinstance(w, AttackWindow)]
    crashes = [w for w in schedule if isinstance(w, CrashWindow)]
    partitions = [w for w in schedule if isinstance(w, PartitionWindow)]
    memberships = [w for w in schedule if isinstance(w, MembershipWindow)]
    dynamic = spec.dynamic or bool(memberships)
    byz = tuple(sorted({w.index for w in attacks}))
    plan = FaultPlan(
        seed=spec.seed,
        partitions=[
            Partition(start=w.start_s, end=w.end_s, group=tuple(w.group))
            for w in partitions
        ],
    )
    classify = classify_reply
    net = dict(spec.net)
    if spec.mutate:
        classify, net = MUTATIONS[spec.mutate](net)
    flightrec_dir = spec.flightrec_dir or os.path.join(
        spec.workdir, "flightrec",
    )
    cspec = ClusterSpec(
        workdir=spec.workdir,
        n_nodes=spec.n_nodes,
        seed=spec.seed,
        duration_s=spec.horizon_s,
        tx_rate=0.0,   # the traffic generator drives load, not run_cluster
        tx_bytes=spec.tx_bytes,
        flightrec_dir=flightrec_dir,
        net=net,
        proxy_plan=plan,
        external_indices=byz,
        dynamic=dynamic,
    )
    honest = cspec.managed_indices()
    sup = ClusterSupervisor(cspec)
    hosts: List[_AdversaryHost] = []
    marks = [
        {
            "window": window_to_dict(w),
            "end_s": window_end_s(w),
            "decided_at_end": None,
        }
        for w in schedule
    ]
    traffic: Optional[TrafficGenerator] = None
    try:
        # adversary slots serve from the start (honest nodes gossip to
        # every member from boot; a refused byzantine port would just
        # feed their circuit breakers noise)
        node_settings = resolve_net_settings()
        node_settings.update(net)
        for w in attacks:
            hosts.append(_AdversaryHost(spec, w, sup, node_settings, byz))
        for i in honest:
            sup._write_node_spec(i)
            sup.launch(i)
        sup.wait_ready(honest)
        sup.fleet.start_clock()   # window clocks count from here
        t0 = frame.now()
        traffic = TrafficGenerator(
            TrafficPlan(
                seed=spec.seed,
                duration_s=spec.horizon_s,
                n_clients=spec.n_clients,
                rate=spec.tx_rate,
                tx_bytes=spec.tx_bytes,
                pareto_alpha=spec.pareto_alpha,
                burst_every_s=spec.burst_every_s,
                burst_len=spec.burst_len,
                reconnect_every_s=spec.reconnect_every_s,
            ),
            cspec.host, sup.ports, targets=list(honest),
            classify=classify,
        )
        traffic.start()
        pending_kills = sorted(crashes, key=lambda w: w.at_s)
        pending_restarts: List[CrashWindow] = []
        pending_mtx = sorted(memberships, key=lambda w: w.at_s)
        member_pks = [pk for pk, _ in member_keys(spec.n_nodes, spec.seed)]
        mtx_sent = 0
        down: set = set()
        poll_gap = cspec.metrics_poll_s if cspec.metrics_poll_s > 0 else None
        next_poll = t0 + (poll_gap or 0.0)
        while frame.now() - t0 < spec.horizon_s:
            el = frame.now() - t0
            while pending_kills and el >= pending_kills[0].at_s:
                w = pending_kills.pop(0)
                proc = sup.procs.get(w.index)
                if proc is not None and proc.poll() is None:
                    sup.kill(w.index)
                down.add(w.index)
                traffic.retarget([i for i in honest if i not in down])
                pending_restarts.append(w)
                pending_restarts.sort(key=lambda c: c.restart_at_s)
            while pending_restarts and el >= pending_restarts[0].restart_at_s:
                w = pending_restarts.pop(0)
                if w.index in down:
                    sup.restart(w.index)
                    down.discard(w.index)
                traffic.retarget([i for i in honest if i not in down])
            # membership injection: one KIND_MTX to the first reachable
            # honest node; an all-unreachable tick just retries — the
            # window fires late rather than silently dropping the tx
            while pending_mtx and el >= pending_mtx[0].at_s:
                w = pending_mtx[0]
                sent = False
                for i in honest:
                    if i in down:
                        continue
                    try:
                        st, _ = sup.client.call(
                            i, frame.KIND_MTX, _mtx_payload(w, member_pks),
                        )
                    except (OSError, ValueError):
                        continue
                    if st == frame.STATUS_OK:
                        sent = True
                        break
                if not sent:
                    break
                pending_mtx.pop(0)
                mtx_sent += 1
            for h in hosts:
                h.maybe_step(el)
            for m in marks:
                if m["decided_at_end"] is None and el >= m["end_s"]:
                    m["decided_at_end"] = _decided_min(
                        sup, [i for i in honest if i not in down],
                    )
            if poll_gap is not None and frame.now() >= next_poll:
                next_poll += poll_gap
                sup.poll_metrics()
            frame.sleep(0.02)
        traffic.stop()
        traffic.join(timeout_s=10.0)
        for w in pending_restarts:   # crash window ran past the horizon
            if w.index in down:
                sup.restart(w.index)
                down.discard(w.index)
        for m in marks:
            if m["decided_at_end"] is None:
                m["decided_at_end"] = _decided_min(
                    sup, [i for i in honest if i not in down],
                )
        if poll_gap is not None:
            sup.poll_metrics()
    finally:
        for h in hosts:
            h.close()
        sup.stop_all()
        if traffic is not None:
            traffic.stop()
    return _soak_verdict(
        spec, cspec, sup, traffic, marks, flightrec_dir, hosts,
        mtx_sent=mtx_sent,
    )


def _soak_verdict(
    spec: SoakSpec,
    cspec: ClusterSpec,
    sup: ClusterSupervisor,
    traffic: Optional[TrafficGenerator],
    marks: List[Dict],
    flightrec_dir: str,
    hosts: Optional[List[_AdversaryHost]] = None,
    mtx_sent: int = 0,
) -> Dict:
    honest = cspec.managed_indices()
    members = [pk for pk, _ in member_keys(spec.n_nodes, spec.seed)]
    config = SwirldConfig(n_members=spec.n_nodes, seed=spec.seed)
    reports, union, nodes = collect_node_state(
        spec.workdir, honest, sup.exit_codes, sup.restarts,
    )
    orders = [
        [bytes.fromhex(e) for e in rep["decided"]]
        for _, rep in sorted(reports.items())
    ]
    oracle_cls = None
    if cspec.dynamic:
        from tpu_swirld.membership.dynamic import DynamicNode

        oracle_cls = DynamicNode
    if union and orders:
        oracle = oracle_replay(
            union, members, config, observer_keypair(spec.seed),
            node_cls=oracle_cls,
        )
        safety = safety_section(orders, oracle)
    else:
        safety = {
            "prefix_agree": False, "oracle_agree": False,
            "common_prefix_len": 0, "oracle_len": 0,
        }
    decided_final = min((len(o) for o in orders), default=0)
    # per-window liveness: the frontier must move past EVERY disruption,
    # not just the last heal
    for m in marks:
        m["advanced"] = decided_final > (m["decided_at_end"] or 0)
    last_end = max((m["end_s"] for m in marks), default=0.0)
    last_mark = max(marks, key=lambda m: m["end_s"], default=None) \
        if marks else None
    liveness = liveness_section(
        decided_final,
        last_mark["decided_at_end"] if last_mark else None,
        heal_turn=min(last_end, spec.horizon_s),
    )
    liveness["windows"] = marks
    disruptions_survived = sum(1 for m in marks if m["advanced"])
    latency = merged_dist(
        [rep.get("ttf_samples", []) for rep in reports.values()], "submit",
    )
    finality = {
        "submit_p99_s": latency.get("submit_p99", 0.0),
        "budget_s": spec.finality_budget_s,
        "samples": latency.get("submit_count", 0),
        "ok": latency.get("submit_p99", 0.0) <= spec.finality_budget_s,
    }
    accounting = traffic.report() if traffic is not None else {
        "balance_ok": False, "submitted": 0, "leaked": 0,
    }
    reports_ok = (
        len(reports) == len(honest)
        and all(c == 0 for c in sup.exit_codes.values())
    )
    counters: Dict[str, float] = {}
    for name in ("tx_shed_window", "tx_shed_pool", "tx_shed_oversize",
                 "tx_duplicate", "tx_accepted", "tx_submitted",
                 "wal_torn_tail_recovered",
                 "net_redials", "net_redial_probes",
                 "node_equivocations_detected", "node_budget_exhausted"):
        counters[name] = sum(
            rep["counters"].get(name, 0) for rep in reports.values()
        )
    # membership: every injected tx must have decided and activated on
    # every surviving honest node — epochs = genesis + one per sent tx.
    # (A dynamic cluster with no windows pins the single-epoch case.)
    epochs_min = min(
        (rep.get("membership_epochs", 1) for rep in reports.values()),
        default=0,
    )
    membership = {
        "dynamic": bool(cspec.dynamic),
        "mtx_sent": mtx_sent,
        "epochs_min": epochs_min,
        "epochs_expected": 1 + mtx_sent,
        "active_epoch_min": min(
            (rep.get("membership_epoch", 1) for rep in reports.values()),
            default=0,
        ),
        "ok": (not cspec.dynamic) or epochs_min >= 1 + mtx_sent,
    }
    ok = (
        verdict_ok(safety, liveness)
        and disruptions_survived == len(marks)
        and finality["ok"]
        and bool(accounting.get("balance_ok"))
        and reports_ok
        and membership["ok"]
    )
    # soak gauges + the black box: a red verdict dumps its own forensics
    registry = Registry()
    registry.gauge("soak_tx_per_s").set(accounting.get("tx_per_s", 0.0))
    registry.gauge("soak_submit_p99_s").set(
        accounting.get("submit_p99_s", 0.0))
    registry.gauge("soak_disruptions_survived").set(disruptions_survived)
    registry.gauge("soak_decided_final").set(decided_final)
    registry.gauge("soak_verdict_ok").set(1 if ok else 0)
    flightrec_dump = None
    if not ok:
        rec = FlightRecorder(
            dump_dir=flightrec_dir, wall_clock=frame.now,
            node_name="soak-orchestrator",
        )
        flightrec_dump = rec.trigger(
            "soak_verdict_failed",
            detail={
                "safety_ok": bool(
                    safety["prefix_agree"] and safety["oracle_agree"]),
                "liveness_ok": bool(liveness["advanced_after_heal"]),
                "disruptions_survived": disruptions_survived,
                "disruptions_total": len(marks),
                "finality_ok": finality["ok"],
                "accounting_ok": bool(accounting.get("balance_ok")),
                "reports_ok": reports_ok,
                "membership_ok": membership["ok"],
            },
            decided_frontier=decided_final,
            registry=registry,
        )
    return {
        "ok": ok,
        "spec": spec_to_dict(spec),
        "safety": safety,
        "liveness": liveness,
        "finality": finality,
        "accounting": accounting,
        "disruptions_survived": disruptions_survived,
        "disruptions_total": len(marks),
        "membership": membership,
        "tx_per_s": accounting.get("tx_per_s", 0.0),
        "submit_p99_s": accounting.get("submit_p99_s", 0.0),
        "counters": counters,
        "proxy": dict(sup.fleet.stats) if sup.fleet is not None else {},
        "adversary": {
            "byzantine_indices": sorted(
                {w["window"]["index"] for w in marks
                 if w["window"]["kind"] == "attack"}
            ),
            "attack_steps": sum(h.steps for h in (hosts or [])),
            "equivocations_detected": counters[
                "node_equivocations_detected"],
        },
        "nodes": nodes,
        "reports": len(reports),
        "flightrec_dump": flightrec_dump,
        "mutate": spec.mutate,
    }


# ----------------------------------------------------- shrink + replay doc

def spec_to_dict(spec: SoakSpec) -> Dict:
    d = dataclasses.asdict(spec)
    d["schedule"] = [window_to_dict(w) for w in spec.schedule]
    return d


def spec_from_dict(d: Dict, workdir: Optional[str] = None) -> SoakSpec:
    d = dict(d)
    d["schedule"] = tuple(
        window_from_dict(w) for w in d.get("schedule", ())
    )
    if workdir is not None:
        d["workdir"] = workdir
    return SoakSpec(**d)


def make_doc(
    spec: SoakSpec, schedule: List, violation: Optional[Dict],
) -> Dict:
    """The minimized replayable failure document."""
    return {
        "kind": DOC_KIND,
        "version": DOC_VERSION,
        "spec": spec_to_dict(
            dataclasses.replace(spec, schedule=tuple(schedule)),
        ),
        "schedule": [window_to_dict(w) for w in schedule],
        "violation": violation,
    }


def save_doc(doc: Dict, path: str) -> str:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    return path


def load_doc(path: str) -> Dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("kind") != DOC_KIND:
        raise ValueError(f"not a {DOC_KIND} doc: {path}")
    return doc


def replay_doc(doc: Dict, workdir: str) -> Dict:
    """Re-run a (minimized) schedule doc in a fresh workdir."""
    spec = spec_from_dict(doc["spec"], workdir=workdir)
    return run_soak(spec)


def shrink(spec: SoakSpec) -> Dict:
    """ddmin the red run's window schedule to a 1-minimal failure.

    Each probe re-runs the soak in its own ``probe-<n>`` workdir with a
    candidate sub-schedule; the reduced doc records the last observed
    violation summary.  Raises ``ValueError`` (from :func:`ddmin`) if
    the full schedule does not actually fail — callers should only
    shrink after a red verdict.
    """
    probes = [0]
    last_violation: Dict = {}

    def red(cand: List) -> bool:
        probes[0] += 1
        probe = dataclasses.replace(
            spec,
            workdir=os.path.join(spec.workdir, f"probe-{probes[0]:02d}"),
            schedule=tuple(cand),
        )
        v = run_soak(probe)
        if not v["ok"]:
            last_violation.clear()
            last_violation.update({
                "safety": v["safety"],
                "liveness_advanced": v["liveness"]["advanced_after_heal"],
                "disruptions_survived": v["disruptions_survived"],
                "finality_ok": v["finality"]["ok"],
                "accounting_leaked": v["accounting"].get("leaked", 0),
                "accounting_ok": bool(
                    v["accounting"].get("balance_ok")),
            })
        return not v["ok"]

    minimal = ddmin(list(spec.schedule), red)
    doc = make_doc(spec, minimal, dict(last_violation) or None)
    doc["probes"] = probes[0]
    return doc
