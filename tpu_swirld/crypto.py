"""Crypto backend: event hashing, keypairs, detached signatures.

The reference reaches libsodium through a Python binding for exactly three
primitives: ed25519 keypair/sign/verify and a generic hash (SURVEY.md §2
component 11 — "no C++ parity obligation beyond crypto").  This module
provides the same three primitives behind a small interface:

- Hashing is BLAKE2b-256 from ``hashlib`` (same algorithm family as
  libsodium's ``crypto_generichash``).
- Signatures use real Ed25519 via the ``cryptography`` package when it is
  importable.  Otherwise a clearly-labelled *simulation* scheme is used:
  ``sig = BLAKE2b(pub || body)``, publicly recomputable.  It preserves the
  properties the protocol logic actually consumes — determinism, fixed
  64-byte width, verifiability, and pseudo-random bits for coin rounds —
  but offers **no** unforgeability; it exists so the framework runs in
  hermetic environments with no crypto library.  The backend is pluggable
  per-process via :func:`set_backend`.

Coin-round bits are taken from the middle byte of the signature on both
backends, mirroring the reference's "pseudo-random bit from the middle of
the signature" (SURVEY.md §2 component 7).
"""

from __future__ import annotations

import hashlib
from typing import Tuple

HASH_BYTES = 32
SIG_BYTES = 64


def hash_bytes(data: bytes) -> bytes:
    """BLAKE2b-256 generic hash (event IDs, whitening)."""
    return hashlib.blake2b(data, digest_size=HASH_BYTES).digest()


class SimSigner:
    """Deterministic, verifiable, NON-SECURE simulation signatures."""

    name = "sim"

    def keypair(self, seed: bytes) -> Tuple[bytes, bytes]:
        sk = hashlib.blake2b(b"sk" + seed, digest_size=32).digest()
        pk = hashlib.blake2b(b"pk" + sk, digest_size=32).digest()
        return pk, sk

    def sign(self, body: bytes, sk: bytes) -> bytes:
        pk = hashlib.blake2b(b"pk" + sk, digest_size=32).digest()
        return hashlib.blake2b(pk + body, digest_size=SIG_BYTES).digest()

    def verify(self, body: bytes, sig: bytes, pk: bytes) -> bool:
        return sig == hashlib.blake2b(pk + body, digest_size=SIG_BYTES).digest()


class Ed25519Signer:
    """Real Ed25519 via the ``cryptography`` package (if importable)."""

    name = "ed25519"

    def __init__(self):
        from cryptography.hazmat.primitives.asymmetric import ed25519 as _ed

        self._ed = _ed
        self._pub_cache = {}

    def keypair(self, seed: bytes) -> Tuple[bytes, bytes]:
        sk_seed = hashlib.blake2b(b"sk" + seed, digest_size=32).digest()
        priv = self._ed.Ed25519PrivateKey.from_private_bytes(sk_seed)
        from cryptography.hazmat.primitives import serialization as ser

        pk = priv.public_key().public_bytes(
            ser.Encoding.Raw, ser.PublicFormat.Raw
        )
        return pk, sk_seed

    def sign(self, body: bytes, sk: bytes) -> bytes:
        priv = self._ed.Ed25519PrivateKey.from_private_bytes(sk)
        return priv.sign(body)

    def verify(self, body: bytes, sig: bytes, pk: bytes) -> bool:
        key = self._pub_cache.get(pk)
        if key is None:
            key = self._ed.Ed25519PublicKey.from_public_bytes(pk)
            self._pub_cache[pk] = key
        try:
            key.verify(sig, body)
            return True
        except Exception:
            return False


def _default_backend():
    try:
        return Ed25519Signer()
    except Exception:
        return SimSigner()


_BACKEND = _default_backend()


def set_backend(name: str) -> None:
    """Select the signature backend: ``"ed25519"`` or ``"sim"``."""
    global _BACKEND
    if name == "ed25519":
        _BACKEND = Ed25519Signer()
    elif name == "sim":
        _BACKEND = SimSigner()
    else:
        raise ValueError(f"unknown crypto backend {name!r}")


def backend_name() -> str:
    return _BACKEND.name


def keypair(seed: bytes) -> Tuple[bytes, bytes]:
    """Deterministic (pub, priv) keypair from a seed."""
    return _BACKEND.keypair(seed)


# Domain-separation tags: the same key signs event bodies, sync requests,
# sync replies, and want-list requests — each message type gets its own
# prefix so a signature can never be replayed across contexts.
DOMAIN_EVENT = b"EVNT:"
DOMAIN_SYNC_REQ = b"SYNQ:"
DOMAIN_SYNC_REPLY = b"SYNR:"
DOMAIN_WANT = b"WANT:"


def sign(body: bytes, sk: bytes, domain: bytes = b"") -> bytes:
    return _BACKEND.sign(domain + body, sk)


def verify(body: bytes, sig: bytes, pk: bytes, domain: bytes = b"") -> bool:
    return _BACKEND.verify(domain + body, sig, pk)


def coin_bit(sig: bytes) -> int:
    """Pseudo-random coin-round bit: low bit of the signature's middle byte."""
    return sig[len(sig) // 2] & 1


def randrange(n: int) -> int:
    """Uniform int in [0, n) from the OS CSPRNG (the reference's
    crypto-safe ``randrange`` in ``utils.py`` — used for peer selection
    outside deterministic simulations)."""
    import secrets

    if n <= 0:
        raise ValueError("randrange needs n > 0")
    return secrets.randbelow(n)
