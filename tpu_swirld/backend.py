"""The pluggable ``backend='tpu'`` consensus engine for a live Node.

BASELINE.json pins the seam: the TPU path is "gated behind the existing
``Node.divide_rounds``/``decide_fame``/``find_order`` interface as a
pluggable ``backend='tpu'`` strategy" consuming the same gossip-sync
deltas.  This module implements that gate:

- a :class:`TpuEngine` owns an incremental :class:`~tpu_swirld.packing.
  Packer` mirroring the node's event store;
- each ``consensus_pass`` appends the sync delta and re-runs the batched
  device pipeline over the full packed DAG (consensus outputs are pure
  functions of the DAG, so batch == incremental — the same purity argument
  the oracle relies on);
- the device outputs are written back into the node's oracle-shaped state
  (``round`` / ``is_witness`` / ``wit_list`` / ``famous`` /
  ``round_received`` / ``consensus_ts`` / ``consensus`` / ``transactions``)
  so everything downstream (viz export, metrics gauges, checkpointing,
  other members gossiping with this node) is backend-agnostic.

``config.mesh_shape`` (e.g. ``{"members": 8}``) runs the strongly-sees
phase shard_map'd over a device mesh; ``config.block_size`` sets the
ancestry tile.  A python-backend and a tpu-backend node interoperate in
one simulation and reach identical consensus prefixes
(``tests/test_backend.py``).

Caveat (documented, inherent to full-batch replay): each pass recomputes
from the whole DAG, so per-sync cost grows with history; for long-lived
nodes run passes periodically or at checkpoints.  The oracle remains the
low-latency per-sync engine; the device backend is the throughput engine.
"""

from __future__ import annotations

from typing import List, Optional

from tpu_swirld.packing import Packer
from tpu_swirld.tpu.pipeline import ConsensusResult, run_consensus


class TpuEngine:
    """Device-pipeline consensus engine bound to one Node."""

    def __init__(self, node):
        self.node = node
        stake = [node.stake[m] for m in node.members]
        self.packer = Packer(node.members, stake)
        self.mesh = None
        if node.config.mesh_shape:
            from tpu_swirld.parallel import make_mesh

            n_dev = 1
            for v in node.config.mesh_shape.values():
                n_dev *= int(v)
            self.mesh = make_mesh(n_dev)
        self.last_result: Optional[ConsensusResult] = None
        self._n_consumed = 0
        self._violations_seen: set = set()  # famous late witnesses already
        #   counted into node.horizon_violations (fame may decide on a
        #   LATER pass than the one that registered the witness)

    def consensus_pass(self, new_ids: List[bytes], force: bool = False) -> None:
        node = self.node
        for eid in node.order_added[len(self.packer):]:
            self.packer.append(node.hg[eid])
        # lazy batching: amortize the batch replay over >= tpu_min_batch
        # new events (identical eventual output — consensus is a pure
        # function of the DAG — just computed later)
        pending = len(self.packer) - self._n_consumed
        if pending == 0 or len(self.packer) == 0:
            return    # up to date: nothing a replay could change
        if not force and pending < max(1, node.config.tpu_min_batch):
            return
        self._n_consumed = len(self.packer)
        packed = self.packer.pack()
        result = run_consensus(
            packed,
            node.config,
            block=node.config.block_size,
            mesh=self.mesh,
        )
        self.last_result = result
        self._write_back(packed, result)

    def flush(self) -> None:
        """Run any pending events through a device pass now, ignoring the
        lazy-batch threshold (no-op when already up to date)."""
        self.consensus_pass([], force=True)

    def _write_back(self, packed, result: ConsensusResult) -> None:
        """Mirror device outputs into the node's oracle-shaped state.

        The deterministic expiry horizon (see the oracle module docstring)
        registers every witness on both engines, so the write-back is a
        plain overwrite; for observability parity with python-backend
        nodes, witnesses that landed at or below the previously committed
        frontier are recorded in ``node.late_witnesses``.
        """
        node = self.node
        ids = packed.ids
        prev_frozen = node._frozen_round
        prev_wits = set(node.wit_slot)
        node.round = {ids[i]: int(result.round[i]) for i in range(packed.n)}
        node.is_witness = {
            ids[i]: bool(result.is_witness[i]) for i in range(packed.n)
        }
        node.max_round = result.max_round
        node.famous = {
            ids[i]: v for i, v in result.famous.items()
        }
        # witness tables in slot order (device slot order == topo order)
        node.wit_list = {}
        node.wit_slot = {}
        node.witnesses = {}
        for i in sorted(result.famous):
            eid = ids[i]
            r = int(result.round[i])
            slots = node.wit_list.setdefault(r, [])
            node.wit_slot[eid] = len(slots)
            slots.append(eid)
            node.witnesses.setdefault(r, {}).setdefault(
                node.hg[eid].c, []
            ).append(eid)
            if r <= prev_frozen and eid not in prev_wits:
                node.late_witnesses.append(eid)
                if node.metrics is not None:
                    node.metrics.count("consensus_late_witnesses")
        # same contract as the oracle path: a FAMOUS late witness
        # (impossible under n > 3f) is surfaced, never silently absorbed.
        # Checked over ALL known late witnesses every pass — fame may
        # decide on a later pass than the one that registered the witness.
        for eid in node.late_witnesses:
            if node.famous.get(eid) and eid not in self._violations_seen:
                self._violations_seen.add(eid)
                node.horizon_violations += 1
                if node.metrics is not None:
                    node.metrics.count("consensus_horizon_violations")
        # ordering state
        node.round_received = {}
        node.consensus_ts = {}
        consensus: List[bytes] = []
        for i in result.order:
            eid = ids[i]
            node.round_received[eid] = int(result.round_received[i])
            node.consensus_ts[eid] = int(result.consensus_ts[i])
            consensus.append(eid)
        node.consensus = consensus
        node.transactions = [node.hg[e].d for e in consensus]
        node.tbd = [e for e in node.order_added if e not in node.round_received]
        # fame-complete prefix (the rounds order extraction consumed)
        r = 0
        while True:
            ws = node.wit_list.get(r)
            if not ws or node.max_round < r + 2:
                break
            if any(node.famous[w] is None for w in ws):
                break
            r += 1
        node.consensus_round = r
        node._frozen_round = r - 1
