"""Gossip transport abstraction + seeded fault injection.

The simulation historically routed gossip through a dict of bound
``ask_sync`` methods — a perfectly reliable function call, which means
none of the failure modes the consensus math is designed to survive
(drops, delays, partitions, crashes, garbage replies) were ever
exercised.  This module formalizes the seam between :class:`~tpu_swirld.
sim.Simulation` and :class:`~tpu_swirld.oracle.node.Node`:

- :class:`Transport` — the delivery interface (and its reliable
  implementation): ``call(src, dst, channel, payload) -> reply``.
  Endpoints stay registered in the same ``network`` / ``network_want``
  dicts the sim already maintains, so the reliable path is byte-for-byte
  the legacy behavior.
- :class:`FaultPlan` / :class:`LinkFaults` / :class:`Partition` — a
  *seeded, declarative* fault schedule: per-link drop / corrupt /
  duplicate / reorder / delay probabilities, scheduled partitions (cut
  links crossing a group boundary during a logical-time window), and
  crash windows interpreted by the chaos driver.
- :class:`FaultyTransport` — applies a :class:`FaultPlan` around the
  reliable call: requests and replies can be dropped (raises a
  :class:`TransportError` subclass — the caller's retry/backoff path),
  corrupted (truncation / bit flips — the caller's counted-rejection
  path), duplicated or held back and re-delivered stale (idempotent
  ingest), and links can be severed by partitions or peer crashes.
- :class:`RetryPolicy` — bounded retry with exponential backoff +
  jitter and a per-peer deadline; pure arithmetic over an injected RNG
  so tests drive it with a fake clock and zero sleeps.
- :class:`CircuitBreaker` — per-peer failure/misbehavior accounting
  with open → cooldown → half-open-probe → close transitions;
  persistently failing or equivocating peers are quarantined (fed by
  the node's fork-detection bookkeeping when
  ``config.quarantine_forkers`` is set).

Every fault is drawn from a per-directed-link RNG stream derived from
``plan.seed`` via ``numpy.random.SeedSequence`` spawn keys — hash-stable
and independent of the order links first carry traffic — so a chaos run
is reproducible from ``(population seed, plan seed)`` alone and a link's
fault history is a pure function of ``(plan.seed, src, dst, call#)``.
"""

from __future__ import annotations

import collections
import dataclasses
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_swirld import obs

CHANNEL_SYNC = "sync"
CHANNEL_WANT = "want"


class TransportError(Exception):
    """Base of every delivery failure (the retryable class of errors)."""


class PeerUnreachable(TransportError):
    """No route to the peer: unregistered, crashed, or it rejected us."""


class PeerPartitioned(TransportError):
    """The link is cut by a scheduled partition window."""


class MessageDropped(TransportError):
    """The request or reply was lost in flight."""


class DeliveryTimeout(TransportError):
    """The reply was delayed past the caller's patience (it may still be
    delivered stale on a later call over the same link)."""


class Transport:
    """Reliable delivery over the sim's endpoint dicts (the legacy path).

    ``network`` maps pk -> ``ask_sync`` endpoint, ``network_want`` maps
    pk -> ``ask_events`` endpoint; both dicts are shared with the sim and
    may gain endpoints after construction (registration order is
    unchanged from the pre-transport code).
    """

    def __init__(
        self,
        network: Dict[bytes, Callable],
        network_want: Optional[Dict[bytes, Callable]] = None,
    ):
        self.network = network
        self.network_want = network_want if network_want is not None else {}
        # Deterministic step hook: invoked as ``on_call(src, dst, channel)``
        # for every delivery attempt that reaches an endpoint lookup.  The
        # model checker (analysis.mc) installs a recorder here so a
        # counterexample replay can prove, byte-for-byte, that the same
        # schedule produces the same wire activity.  Must be a pure
        # observer — raising or mutating node state here is undefined.
        self.on_call: Optional[Callable[[bytes, bytes, str], None]] = None

    def endpoint(self, dst: bytes, channel: str) -> Optional[Callable]:
        table = self.network if channel == CHANNEL_SYNC else self.network_want
        return table.get(dst)

    def call(self, src: bytes, dst: bytes, channel: str, payload: bytes) -> bytes:
        if self.on_call is not None:
            self.on_call(src, dst, channel)
        fn = self.endpoint(dst, channel)
        if fn is None:
            raise PeerUnreachable(f"no {channel} endpoint for peer")
        try:
            return fn(src, payload)
        except (TransportError, ValueError):
            # ValueError is the endpoints' documented rejection signal
            # (counted as a bad reply by the caller); transport errors
            # pass through untouched
            raise
        except Exception as e:
            # anything else a (byzantine or buggy) endpoint throws is a
            # failed RPC, never a traceback in the caller's gossip loop
            raise PeerUnreachable(
                f"peer endpoint error: {type(e).__name__}"
            ) from e

    def close(self) -> None:
        """Release delivery resources.  A no-op for the in-process
        transports; the socket transport (:class:`tpu_swirld.net.
        transport.SocketTransport`) overrides it to drop its per-peer
        connections — callers tear any transport down uniformly."""


# --------------------------------------------------------------- fault plan


@dataclasses.dataclass
class LinkFaults:
    """Per-link fault probabilities (each sampled independently per call).

    ``drop`` is sampled twice — once for the request, once for the reply —
    so the end-to-end loss rate of a link with ``drop=p`` is ``1-(1-p)^2``.
    ``corrupt`` mangles bytes (truncation, bit flips, or emptying) without
    losing the call; ``duplicate`` re-delivers a copy of the reply stale on
    a later call; ``reorder`` swaps the fresh reply with a previously
    stashed one; ``delay`` holds the fresh reply back entirely (the caller
    times out; the reply arrives stale later).

    The last three knobs only have meaning on a real wire and are applied
    by the socket interposer (:mod:`tpu_swirld.net.proxy`), never by the
    in-process :class:`FaultyTransport`: ``reset`` is the probability of
    a hard connection teardown after the server already processed the
    request (the redial-after-success hazard), ``delay_s`` is the hold
    applied when a ``delay`` fault fires on a stream (an in-process delay
    is a stashed stale reply instead), and ``throttle_bps`` > 0 paces
    relayed bytes to that budget.  All default off, so every existing
    in-process plan is byte-identical.
    """

    drop: float = 0.0
    corrupt: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    delay: float = 0.0
    reset: float = 0.0
    delay_s: float = 0.0
    throttle_bps: float = 0.0


@dataclasses.dataclass(frozen=True)
class Partition:
    """Cut every link crossing ``group``'s boundary while
    ``start <= clock < end``.  ``group`` holds member *indices* (resolved
    against the transport's member list, so plans can be written before
    keys exist)."""

    start: int
    end: int
    group: Tuple[int, ...]


@dataclasses.dataclass
class FaultPlan:
    """A seeded, declarative fault schedule for one chaos scenario.

    ``default`` applies to every link; ``links`` overrides per
    ``(src_index, dst_index)`` directed pair.  ``crashes`` maps a member
    index to ``[(down_turn, up_turn), ...]`` windows — interpreted by the
    chaos driver (which owns checkpoint/restore), while the transport
    exposes the resulting downtime via :attr:`FaultyTransport.down`.
    """

    seed: int = 0
    default: LinkFaults = dataclasses.field(default_factory=LinkFaults)
    links: Dict[Tuple[int, int], LinkFaults] = dataclasses.field(
        default_factory=dict
    )
    partitions: List[Partition] = dataclasses.field(default_factory=list)
    crashes: Dict[int, List[Tuple[int, int]]] = dataclasses.field(
        default_factory=dict
    )

    def faults_for(self, src_i: int, dst_i: int) -> LinkFaults:
        return self.links.get((src_i, dst_i), self.default)

    def partitioned(self, src_i: int, dst_i: int, t: int) -> bool:
        for p in self.partitions:
            if p.start <= t < p.end:
                if (src_i in p.group) != (dst_i in p.group):
                    return True
        return False

    def heal_time(self) -> int:
        """The first tick with no scheduled partition or crash window."""
        ends = [p.end for p in self.partitions]
        ends += [up for ws in self.crashes.values() for _, up in ws]
        return max(ends, default=0)


class FaultyTransport(Transport):
    """A :class:`Transport` that applies a :class:`FaultPlan`.

    ``clock`` supplies logical time (the sim's turn counter) for
    partition windows; ``members`` resolves pk -> index for the plan's
    index-keyed knobs.  Each directed link draws from its own RNG stream,
    keyed ``SeedSequence(plan.seed, spawn_key=(src_i+1, dst_i+1))`` — the
    hash-stable spawn construction, so a link's fault sequence never
    depends on which other links happened to carry traffic first (the old
    single shared ``Random(plan.seed)`` made every link's draws a
    function of global call interleaving).

    Fault counters accumulate in :attr:`stats` and, when an ambient
    :func:`tpu_swirld.obs.current` registry is enabled, as
    ``transport_<name>_total`` counters (rendered by the report CLI's
    resilience section).
    """

    def __init__(
        self,
        network: Dict[bytes, Callable],
        network_want: Optional[Dict[bytes, Callable]],
        plan: FaultPlan,
        members: Sequence[bytes],
        clock: Callable[[], int],
    ):
        super().__init__(network, network_want)
        self.plan = plan
        self.clock = clock
        self.member_index = {m: i for i, m in enumerate(members)}
        self.down: set = set()          # crashed pks (driver-maintained)
        self._link_rngs: Dict[Tuple[int, int], np.random.Generator] = {}
        self._pending: Dict[Tuple[bytes, bytes, str], collections.deque] = {}
        self.stats: Dict[str, int] = collections.defaultdict(int)

    # ------------------------------------------------------------- helpers

    def _link_rng(self, src_i: int, dst_i: int) -> np.random.Generator:
        """The directed link's private fault stream.  Spawn keys are
        offset by 1 so unknown members (index -1) get a valid stream."""
        key = (src_i, dst_i)
        rng = self._link_rngs.get(key)
        if rng is None:
            rng = np.random.default_rng(
                np.random.SeedSequence(
                    self.plan.seed, spawn_key=(src_i + 1, dst_i + 1)
                )
            )
            self._link_rngs[key] = rng
        return rng

    def _count(self, name: str, delta: int = 1) -> None:
        self.stats[name] += delta
        o = obs.current()
        if o is not None:
            o.registry.counter(f"transport_{name}_total").inc(delta)

    @staticmethod
    def _corrupt(data: bytes, r: np.random.Generator) -> bytes:
        """Truncate, bit-flip, or empty the message — never crash."""
        mode = int(r.integers(3))
        if not data or mode == 0:
            return data[: int(r.integers(len(data) + 1))]  # truncation
        if mode == 1:
            i = int(r.integers(len(data)))
            return data[:i] + bytes([data[i] ^ (1 << int(r.integers(8)))]) + data[i + 1:]
        return b""                                         # total garbage

    def set_down(self, pk: bytes) -> None:
        self.down.add(pk)

    def set_up(self, pk: bytes) -> None:
        self.down.discard(pk)

    # ---------------------------------------------------------------- call

    def call(self, src: bytes, dst: bytes, channel: str, payload: bytes) -> bytes:
        t = int(self.clock())
        if src in self.down or dst in self.down:
            self._count("crash_blocked")
            raise PeerUnreachable("peer is down")
        si = self.member_index.get(src, -1)
        di = self.member_index.get(dst, -1)
        if self.plan.partitioned(si, di, t):
            self._count("partition_blocked")
            raise PeerPartitioned(f"link cut at t={t}")
        lf = self.plan.faults_for(si, di)
        r = self._link_rng(si, di)
        if r.random() < lf.drop:
            self._count("drops")
            raise MessageDropped("request lost")
        req = payload
        if r.random() < lf.corrupt:
            self._count("corruptions")
            req = self._corrupt(req, r)
        try:
            reply = super().call(src, dst, channel, req)
        except TransportError:
            raise
        except Exception:
            # the peer rejected the (possibly mangled) request; a real
            # network shows the caller a failed RPC, not a traceback
            self._count("peer_errors")
            raise PeerUnreachable("peer rejected the request")
        if r.random() < lf.drop:
            self._count("drops")
            raise MessageDropped("reply lost")
        if r.random() < lf.corrupt:
            self._count("corruptions")
            reply = self._corrupt(reply, r)
        key = (src, dst, channel)
        queue = self._pending.setdefault(key, collections.deque(maxlen=8))
        if r.random() < lf.duplicate:
            self._count("duplicates")
            queue.append(reply)
        if r.random() < lf.delay:
            self._count("delays")
            queue.append(reply)
            raise DeliveryTimeout("reply delayed past deadline")
        # stashed stale replies (duplicates / delayed deliveries) surface
        # on later calls at a rate matching whichever fault stashed them —
        # so duplicate/delay are not inert when reorder is 0; the fresh
        # reply is stashed in exchange, never lost
        drain_p = max(lf.reorder, lf.duplicate, lf.delay)
        if queue and r.random() < drain_p:
            self._count("reorders")
            queue.append(reply)
            return queue.popleft()
        return reply


# ------------------------------------------------------------ retry policy


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + jitter and a deadline.

    All quantities are *logical* time (the sim's tick unit); nothing here
    sleeps — the caller decides what to do with each computed delay
    (record it, advance a fake clock, or actually sleep in a real
    deployment).
    """

    attempts: int = 3          # total call attempts (1 = no retry)
    backoff_base: float = 1.0  # first retry delay
    backoff_cap: float = 8.0   # per-retry delay ceiling
    jitter: float = 0.5        # uniform extra in [0, jitter * delay]
    deadline: float = 16.0     # total backoff budget per peer per pull

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Delay before retry number ``attempt`` (0-based)."""
        d = min(self.backoff_cap, self.backoff_base * (2.0 ** attempt))
        if self.jitter > 0:
            d += d * self.jitter * rng.random()
        return d


# ---------------------------------------------------------- circuit breaker

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half-open"


class CircuitBreaker:
    """Per-peer quarantine for persistently failing or misbehaving peers.

    Two strike counters per peer: *failures* (transport errors — retried,
    possibly transient; reset on any success) and *misbehavior* (garbage
    at the decode layer: bad reply signatures, validly-signed malformed
    blobs, or detected equivocation fed in by the node's fork
    bookkeeping; decays one strike per clean reply, since in-flight
    corruption is indistinguishable from peer garbage).  Either crossing
    its threshold opens the circuit: calls to the peer fail fast until
    ``cooldown`` logical ticks pass, after which ONE probe call is
    allowed (half-open); success closes the circuit, failure re-opens it
    for another cooldown.
    """

    def __init__(
        self,
        clock: Callable[[], int],
        failure_threshold: int = 4,
        misbehavior_threshold: int = 12,
        cooldown: float = 24.0,
    ):
        self._clock = clock
        self.failure_threshold = max(1, failure_threshold)
        self.misbehavior_threshold = max(1, misbehavior_threshold)
        self.cooldown = cooldown
        self._failures: Dict[bytes, int] = {}
        self._misbehavior: Dict[bytes, int] = {}
        self._opened_at: Dict[bytes, float] = {}
        self._probing: set = set()
        self.opens = 0             # lifetime count of open transitions
        self.on_open: Optional[Callable[[bytes], None]] = None
        # ^ observer seam: called with the peer on every open transition
        # (the flight recorder hooks this to dump a post-mortem)

    def state(self, peer: bytes) -> str:
        t0 = self._opened_at.get(peer)
        if t0 is None:
            return _CLOSED
        if self._clock() - t0 >= self.cooldown:
            return _HALF_OPEN
        return _OPEN

    def allow(self, peer: bytes) -> bool:
        """May we call this peer now?  (Half-open admits one probe.)"""
        s = self.state(peer)
        if s == _CLOSED:
            return True
        if s == _HALF_OPEN:
            self._probing.add(peer)
            return True
        return False

    def _open(self, peer: bytes) -> None:
        self._opened_at[peer] = self._clock()
        self._failures[peer] = 0
        self._misbehavior[peer] = 0
        self._probing.discard(peer)
        self.opens += 1
        if self.on_open is not None:
            self.on_open(peer)

    def record_failure(self, peer: bytes) -> None:
        if peer in self._opened_at:
            if peer in self._probing:       # failed half-open probe
                self._open(peer)
            return
        n = self._failures.get(peer, 0) + 1
        self._failures[peer] = n
        if n >= self.failure_threshold:
            self._open(peer)

    def record_misbehavior(self, peer: bytes, weight: int = 1) -> None:
        if peer in self._opened_at:
            if peer in self._probing:
                self._open(peer)
            return
        n = self._misbehavior.get(peer, 0) + weight
        self._misbehavior[peer] = n
        if n >= self.misbehavior_threshold:
            self._open(peer)

    def record_success(self, peer: bytes) -> None:
        self._failures[peer] = 0
        # misbehavior decays one strike per clean reply: in-flight
        # corruption on a lossy link is indistinguishable from peer
        # garbage at the decode layer, and without decay those strikes
        # would slowly quarantine an honest peer.  A real byzantine peer
        # serving mostly garbage still out-runs the decay (and detected
        # equivocation strikes with the full threshold at once).
        m = self._misbehavior.get(peer, 0)
        if m > 0:
            self._misbehavior[peer] = m - 1
        if peer in self._opened_at and peer in self._probing:
            del self._opened_at[peer]       # probe succeeded: close
            self._probing.discard(peer)

    def quarantined(self) -> List[bytes]:
        """Peers whose circuit is currently open (incl. half-open)."""
        return [p for p in self._opened_at]
