"""Dense packing: hash-addressed event DAG -> index arrays for the device.

SURVEY.md §7 step 2 / BASELINE.json north star: events and their parent
pointers are packed into a dense ``(N, 2)`` int32 index array plus creator /
seq / timestamp / coin-bit vectors in topological (insertion) order.  The
packer is append-only and incremental: gossip-sync deltas append to the same
:class:`Packer`, and :meth:`Packer.pack` snapshots the arrays the pipeline
consumes (``tpu_swirld.tpu.pipeline``).

Everything here is host-side numpy — the device never touches hashes.  The
hash <-> index mapping (``ids``) and the raw signatures (``sigs``, for the
order-extraction whitening hash) stay on the host.

Fork bookkeeping: the oracle detects forks per ``(creator, seq)`` group
(minimal fork pairs always share them — see the spec block in
``tpu_swirld.oracle.node``).  The packer mirrors that: every unordered pair
of distinct events by one creator at one seq becomes a ``fork_pairs`` row
``(member, idx_a, idx_b)``; the device computes ``forkseen[x, m]`` as an OR
of ``anc[x, a] & anc[x, b]`` over that member's rows.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tpu_swirld.oracle.event import Event


@dataclasses.dataclass
class PackedDAG:
    """Snapshot of a packed event DAG (topo order, genesis parents = -1)."""

    n: int                     # number of events
    n_members: int
    parents: np.ndarray        # int32[N, 2]; -1 for genesis
    creator: np.ndarray        # int32[N]; member index
    seq: np.ndarray            # int32[N]; self-chain height
    t: np.ndarray              # int64[N]; creation timestamps
    coin: np.ndarray           # uint8[N]; signature middle bit (coin rounds)
    stake: np.ndarray          # int32[M]
    fork_pairs: np.ndarray     # int32[G, 3]: (member, idx_a, idx_b)
    member_table: np.ndarray   # int32[M, K]: event idx per member, -1 pad
    ids: List[bytes]           # event id per index (host only)
    sigs: List[bytes]          # signature per index (host only)

    @property
    def max_events_per_member(self) -> int:
        return self.member_table.shape[1]

    def index_of(self, eid: bytes) -> int:
        return self.ids.index(eid)


class Packer:
    """Append-only incremental packer (one per consensus engine instance).

    Columns live in amortized-doubling numpy buffers written in place by
    :meth:`append`, so :meth:`pack` is O(1) in the already-packed prefix:
    it snapshots read-only *views* of the buffers instead of rebuilding
    every slab from the python lists (the old behaviour made each steady-
    state repack O(N)).  Appends only ever write *past* the snapshotted
    length and buffer growth reallocates rather than resizing in place, so
    earlier snapshots stay valid forever.
    """

    _INIT_CAP = 256

    def __init__(self, members: Sequence[bytes], stake: Sequence[int]):
        if len(members) != len(stake):
            raise ValueError("members and stake length mismatch")
        self.members: List[bytes] = list(members)
        self.member_index: Dict[bytes, int] = {m: i for i, m in enumerate(members)}
        self.stake = np.asarray(stake, dtype=np.int32)
        self.idx: Dict[bytes, int] = {}         # event id -> index
        self._n = 0
        cap = self._INIT_CAP
        self._parents = np.full((cap, 2), -1, dtype=np.int32)
        self._creator = np.zeros((cap,), dtype=np.int32)
        self._seq = np.zeros((cap,), dtype=np.int32)
        self._t = np.zeros((cap,), dtype=np.int64)
        self._coin = np.zeros((cap,), dtype=np.uint8)
        self._ids: List[bytes] = []
        self._sigs: List[bytes] = []
        self._member_counts = np.zeros((len(members),), dtype=np.int32)
        self._by_seq: List[Dict[int, List[int]]] = [{} for _ in members]
        self._k = 1                              # member_table column capacity
        self._member_table = np.full((len(members), self._k), -1, dtype=np.int32)
        self._fork_pairs = np.zeros((0, 3), dtype=np.int32)
        self._n_fork_pairs = 0
        self.packs = 0                           # observability: pack() calls

    def __len__(self) -> int:
        return self._n

    # ---- dynamic membership (epoch repack seam: membership.repack)

    def add_member(self, pk: bytes) -> int:
        """Append one member row (a decided JOIN): the member axis only
        ever *extends*, so existing event indices, fork pairs, and every
        snapshot stay valid.  Returns the new member index."""
        if pk in self.member_index:
            return self.member_index[pk]
        i = len(self.members)
        self.members.append(pk)
        self.member_index[pk] = i
        counts = np.zeros((i + 1,), dtype=np.int32)
        counts[:i] = self._member_counts
        self._member_counts = counts
        self._by_seq.append({})
        table = np.full((i + 1, self._k), -1, dtype=np.int32)
        table[:i] = self._member_table
        self._member_table = table
        stake = np.zeros((i + 1,), dtype=np.int32)
        stake[:i] = self.stake
        self.stake = stake
        return i

    def set_stake(self, stake: Sequence[int]) -> None:
        """Swap the stake vector (a decided LEAVE/RESTAKE or an epoch
        activation).  Length must match the member axis."""
        if len(stake) != len(self.members):
            raise ValueError("stake length != member count")
        self.stake = np.asarray(stake, dtype=np.int32)

    def _grow(self, need: int) -> None:
        cap = self._parents.shape[0]
        if need <= cap:
            return
        new_cap = max(cap * 2, need)

        def regrow(a, fill):
            out = np.full((new_cap,) + a.shape[1:], fill, a.dtype)
            out[: self._n] = a[: self._n]
            return out

        self._parents = regrow(self._parents, -1)
        self._creator = regrow(self._creator, 0)
        self._seq = regrow(self._seq, 0)
        self._t = regrow(self._t, 0)
        self._coin = regrow(self._coin, 0)

    def _grow_member_table(self, k: int) -> None:
        if k <= self._k:
            return
        new_k = max(self._k * 2, k)
        out = np.full((len(self.members), new_k), -1, dtype=np.int32)
        out[:, : self._k] = self._member_table
        self._member_table = out
        self._k = new_k

    def _push_fork_pair(self, row: Tuple[int, int, int]) -> None:
        g = self._n_fork_pairs
        if g >= self._fork_pairs.shape[0]:
            new_cap = max(8, self._fork_pairs.shape[0] * 2)
            out = np.full((new_cap, 3), -1, dtype=np.int32)
            out[:g] = self._fork_pairs[:g]
            self._fork_pairs = out
        self._fork_pairs[g] = row
        self._n_fork_pairs = g + 1

    def append(self, ev: Event) -> int:
        """Pack one event (parents must already be packed).  Idempotent."""
        return self.append_prepared(ev, ev.id)

    def append_prepared(self, ev: Event, eid: bytes) -> int:
        """:meth:`append` with the event id already computed — the
        decode-overlap worker hashes ids off-thread (``prepare_events``)
        and the main thread packs here without re-hashing.  All packer
        mutation stays on the calling thread."""
        existing = self.idx.get(eid)
        if existing is not None:
            return existing
        ci = self.member_index.get(ev.c)
        if ci is None:
            raise ValueError("unknown creator")
        i = self._n
        self._grow(i + 1)
        if ev.p:
            sp = self.idx.get(ev.p[0])
            op = self.idx.get(ev.p[1])
            if sp is None or op is None:
                raise ValueError("parent not packed (append in topo order)")
            seq = int(self._seq[sp]) + 1
            self._parents[i] = (sp, op)
        else:
            seq = 0
            self._parents[i] = (-1, -1)
        self.idx[eid] = i
        self._creator[i] = ci
        self._seq[i] = seq
        self._t[i] = int(ev.t)
        self._coin[i] = ev.coin_bit() & 1
        self._ids.append(eid)
        self._sigs.append(ev.s)
        slot = int(self._member_counts[ci])
        self._grow_member_table(slot + 1)
        self._member_table[ci, slot] = i
        self._member_counts[ci] = slot + 1
        group = self._by_seq[ci].setdefault(seq, [])
        for other in group:            # every prior same-(creator, seq) event
            self._push_fork_pair((ci, other, i))
        group.append(i)
        # publish last: every row/side-table write above used the local
        # index, so a concurrent len()/pack() reader (telemetry, the
        # decode-overlap driver's invariant checks) never observes a
        # half-written event at position _n - 1
        self._n = i + 1
        return i

    def extend(self, events: Iterable[Event]) -> List[int]:
        return [self.append(ev) for ev in events]

    def extend_prepared(self, pairs: Iterable[Tuple[Event, bytes]]) -> List[int]:
        """Pack a pre-decoded delta: ``pairs`` as produced by
        :func:`prepare_events` (typically on a worker thread)."""
        return [self.append_prepared(ev, eid) for ev, eid in pairs]

    # ---- bounded read-only views (the incremental driver's surface:
    # keeps the buffer layout private to this file; same freeze contract
    # as pack())

    def window_view(self, start: int, stop: Optional[int] = None):
        """Read-only ``(parents, creator, coin, t)`` column views for the
        packed events [start, stop) — an ingest delta."""
        stop = self._n if stop is None else stop
        return (
            self._ro(self._parents[start:stop]),
            self._ro(self._creator[start:stop]),
            self._ro(self._coin[start:stop]),
            self._ro(self._t[start:stop]),
        )

    @property
    def n_fork_pairs(self) -> int:
        return self._n_fork_pairs

    def fork_pairs_view(self, start: int = 0) -> np.ndarray:
        """Read-only fork-pair rows [start, n_fork_pairs)."""
        return self._ro(self._fork_pairs[start : self._n_fork_pairs])

    def sig(self, i: int) -> bytes:
        return self._sigs[i]

    def event_id(self, i: int) -> bytes:
        return self._ids[i]

    @staticmethod
    def _ro(view: np.ndarray) -> np.ndarray:
        """Freeze a buffer view: snapshots share memory with the live
        packer, so in-place mutation by a consumer must be an error, not
        silent corruption of every other outstanding snapshot."""
        view = view[:]
        view.flags.writeable = False
        return view

    def pack(self) -> PackedDAG:
        n = self._n
        m = len(self.members)
        k = max(int(self._member_counts.max(initial=0)), 1)
        self.packs += 1
        return PackedDAG(
            n=n,
            n_members=m,
            parents=self._ro(self._parents[:n]),
            creator=self._ro(self._creator[:n]),
            seq=self._ro(self._seq[:n]),
            t=self._ro(self._t[:n]),
            coin=self._ro(self._coin[:n]),
            stake=self.stake.copy(),
            # the member table is the one slab a future append may write
            # *inside* (a member's next slot can sit below another member's
            # column high-water mark), so it is copied; it is O(N/M * M) =
            # O(N) int32 but tiny next to the O(N) views above being free
            fork_pairs=self._fork_pairs[: self._n_fork_pairs].copy(),
            member_table=self._member_table[:, :k].copy(),
            ids=list(self._ids),
            sigs=list(self._sigs),
        )


def chunk_slices(n: int, chunk: int) -> List[Tuple[int, int]]:
    """Chunk-aligned ``[start, stop)`` slices covering ``[0, n)``.

    Every piece except the last is exactly ``chunk`` long, so a consumer
    that pads each piece to a ``chunk`` multiple (the device scan stages)
    wastes padding on at most one piece per delta.  Any split of a
    topologically ordered stream is itself topologically valid, so the
    slices can be ingested independently.
    """
    if chunk <= 0:
        raise ValueError("chunk must be positive")
    return [(s, min(n, s + chunk)) for s in range(0, n, chunk)]


def prepare_events(events: Sequence[Event]) -> List[Tuple[Event, bytes]]:
    """Gossip decode for a delta: compute each event's id (a content
    hash — the dominant host cost of packing) without touching any
    shared state.  Pure function of the events, so it can run on the
    streaming driver's decode worker while the device executes the
    previous chunk; the main thread packs the result with
    :meth:`Packer.extend_prepared`."""
    return [(ev, ev.id) for ev in events]


def pack_events(
    events: Sequence[Event],
    members: Sequence[bytes],
    stake: Optional[Sequence[int]] = None,
) -> PackedDAG:
    """Pack a topologically ordered event sequence in one shot."""
    if stake is None:
        stake = [1] * len(members)
    p = Packer(members, stake)
    p.extend(events)
    return p.pack()


def pack_node(node) -> PackedDAG:
    """Pack an oracle :class:`~tpu_swirld.oracle.node.Node`'s full DAG in its
    insertion (topo) order — the order its own consensus state was built in,
    which the parity tests compare against."""
    events = [node.hg[eid] for eid in node.order_added]
    stake = [node.stake[m] for m in node.members]
    return pack_events(events, node.members, stake)
