"""Dense packing: hash-addressed event DAG -> index arrays for the device.

SURVEY.md §7 step 2 / BASELINE.json north star: events and their parent
pointers are packed into a dense ``(N, 2)`` int32 index array plus creator /
seq / timestamp / coin-bit vectors in topological (insertion) order.  The
packer is append-only and incremental: gossip-sync deltas append to the same
:class:`Packer`, and :meth:`Packer.pack` snapshots the arrays the pipeline
consumes (``tpu_swirld.tpu.pipeline``).

Everything here is host-side numpy — the device never touches hashes.  The
hash <-> index mapping (``ids``) and the raw signatures (``sigs``, for the
order-extraction whitening hash) stay on the host.

Fork bookkeeping: the oracle detects forks per ``(creator, seq)`` group
(minimal fork pairs always share them — see the spec block in
``tpu_swirld.oracle.node``).  The packer mirrors that: every unordered pair
of distinct events by one creator at one seq becomes a ``fork_pairs`` row
``(member, idx_a, idx_b)``; the device computes ``forkseen[x, m]`` as an OR
of ``anc[x, a] & anc[x, b]`` over that member's rows.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tpu_swirld.oracle.event import Event


@dataclasses.dataclass
class PackedDAG:
    """Snapshot of a packed event DAG (topo order, genesis parents = -1)."""

    n: int                     # number of events
    n_members: int
    parents: np.ndarray        # int32[N, 2]; -1 for genesis
    creator: np.ndarray        # int32[N]; member index
    seq: np.ndarray            # int32[N]; self-chain height
    t: np.ndarray              # int64[N]; creation timestamps
    coin: np.ndarray           # uint8[N]; signature middle bit (coin rounds)
    stake: np.ndarray          # int32[M]
    fork_pairs: np.ndarray     # int32[G, 3]: (member, idx_a, idx_b)
    member_table: np.ndarray   # int32[M, K]: event idx per member, -1 pad
    ids: List[bytes]           # event id per index (host only)
    sigs: List[bytes]          # signature per index (host only)

    @property
    def max_events_per_member(self) -> int:
        return self.member_table.shape[1]

    def index_of(self, eid: bytes) -> int:
        return self.ids.index(eid)


class Packer:
    """Append-only incremental packer (one per consensus engine instance)."""

    def __init__(self, members: Sequence[bytes], stake: Sequence[int]):
        if len(members) != len(stake):
            raise ValueError("members and stake length mismatch")
        self.members: List[bytes] = list(members)
        self.member_index: Dict[bytes, int] = {m: i for i, m in enumerate(members)}
        self.stake = np.asarray(stake, dtype=np.int32)
        self.idx: Dict[bytes, int] = {}         # event id -> index
        self._parents: List[Tuple[int, int]] = []
        self._creator: List[int] = []
        self._seq: List[int] = []
        self._t: List[int] = []
        self._coin: List[int] = []
        self._ids: List[bytes] = []
        self._sigs: List[bytes] = []
        self._member_events: List[List[int]] = [[] for _ in members]
        self._by_seq: List[Dict[int, List[int]]] = [{} for _ in members]
        self._fork_pairs: List[Tuple[int, int, int]] = []

    def __len__(self) -> int:
        return len(self._ids)

    def append(self, ev: Event) -> int:
        """Pack one event (parents must already be packed).  Idempotent."""
        eid = ev.id
        existing = self.idx.get(eid)
        if existing is not None:
            return existing
        ci = self.member_index.get(ev.c)
        if ci is None:
            raise ValueError("unknown creator")
        i = len(self._ids)
        if ev.p:
            sp = self.idx.get(ev.p[0])
            op = self.idx.get(ev.p[1])
            if sp is None or op is None:
                raise ValueError("parent not packed (append in topo order)")
            seq = self._seq[sp] + 1
            self._parents.append((sp, op))
        else:
            seq = 0
            self._parents.append((-1, -1))
        self.idx[eid] = i
        self._creator.append(ci)
        self._seq.append(seq)
        self._t.append(int(ev.t))
        self._coin.append(ev.coin_bit() & 1)
        self._ids.append(eid)
        self._sigs.append(ev.s)
        self._member_events[ci].append(i)
        group = self._by_seq[ci].setdefault(seq, [])
        for other in group:            # every prior same-(creator, seq) event
            self._fork_pairs.append((ci, other, i))
        group.append(i)
        return i

    def extend(self, events: Iterable[Event]) -> List[int]:
        return [self.append(ev) for ev in events]

    def pack(self) -> PackedDAG:
        n = len(self._ids)
        m = len(self.members)
        k = max((len(ev) for ev in self._member_events), default=0)
        k = max(k, 1)
        member_table = np.full((m, k), -1, dtype=np.int32)
        for ci, evs in enumerate(self._member_events):
            member_table[ci, : len(evs)] = evs
        fork_pairs = (
            np.asarray(self._fork_pairs, dtype=np.int32).reshape(-1, 3)
            if self._fork_pairs
            else np.zeros((0, 3), dtype=np.int32)
        )
        return PackedDAG(
            n=n,
            n_members=m,
            parents=np.asarray(self._parents, dtype=np.int32).reshape(n, 2),
            creator=np.asarray(self._creator, dtype=np.int32),
            seq=np.asarray(self._seq, dtype=np.int32),
            t=np.asarray(self._t, dtype=np.int64),
            coin=np.asarray(self._coin, dtype=np.uint8),
            stake=self.stake.copy(),
            fork_pairs=fork_pairs,
            member_table=member_table,
            ids=list(self._ids),
            sigs=list(self._sigs),
        )


def pack_events(
    events: Sequence[Event],
    members: Sequence[bytes],
    stake: Optional[Sequence[int]] = None,
) -> PackedDAG:
    """Pack a topologically ordered event sequence in one shot."""
    if stake is None:
        stake = [1] * len(members)
    p = Packer(members, stake)
    p.extend(events)
    return p.pack()


def pack_node(node) -> PackedDAG:
    """Pack an oracle :class:`~tpu_swirld.oracle.node.Node`'s full DAG in its
    insertion (topo) order — the order its own consensus state was built in,
    which the parity tests compare against."""
    events = [node.hg[eid] for eid in node.order_added]
    stake = [node.stake[m] for m in node.members]
    return pack_events(events, node.members, stake)
