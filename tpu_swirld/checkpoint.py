"""Checkpoint / resume of the packed DAG and consensus state.

SURVEY.md §5: the reference keeps everything in RAM and dies with the
process; the build owes save/restore.  Two granularities:

- :func:`save_packed` / :func:`load_packed` — the dense device-input arrays
  (plus host-side ids/sigs) as a single ``.npz``.  No pickle anywhere:
  hashes and signatures are fixed-width, so they serialize as uint8
  matrices; payload bytes are length-prefix packed.
- :func:`save_node` / :func:`load_node` — full engine state via the wire
  format: the event log in topo order (``encode_event`` blobs).  Restore
  replays the log through validation + one batch consensus pass, which by
  the purity of the consensus functions reconstructs bit-identical
  ``round`` / ``witness`` / ``famous`` / order state; the node then
  resumes gossiping.

The replay-purity contract is only sound because the expiry horizon is
deterministic (``tpu_swirld.oracle.node`` module docstring): under the old
node-local quarantine, a node that had quarantined a straggler witness
would replay its own checkpoint WITHOUT the quarantine (the batch replay
never freezes mid-pass) and restart disagreeing with its pre-crash self.
With the deterministic rule the horizon survives restart by construction;
the checkpoint additionally carries the decided-order length and a digest
of the decided prefix, and :func:`load_node` verifies the replay
re-decides that exact prefix — so checkpoint corruption or consensus-rule
drift fails loudly at restore time instead of diverging silently later.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
from typing import Callable, Dict, List, Optional

import numpy as np

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import decode_event, encode_event
from tpu_swirld.oracle.node import Node
from tpu_swirld.packing import PackedDAG

FORMAT_VERSION = 1


def _pack_bytes_list(items: List[bytes]) -> np.ndarray:
    """Length-prefixed flat uint8 array (no pickle)."""
    blob = b"".join(struct.pack("<I", len(b)) + b for b in items)
    return np.frombuffer(blob, dtype=np.uint8)


def _unpack_bytes_list(arr: np.ndarray) -> List[bytes]:
    blob = arr.tobytes()
    out, off = [], 0
    while off < len(blob):
        (n,) = struct.unpack_from("<I", blob, off)
        off += 4
        out.append(blob[off : off + n])
        off += n
    return out


def save_packed(path: str, packed: PackedDAG) -> None:
    np.savez_compressed(
        path,
        format_version=FORMAT_VERSION,
        n=packed.n,
        n_members=packed.n_members,
        parents=packed.parents,
        creator=packed.creator,
        seq=packed.seq,
        t=packed.t,
        coin=packed.coin,
        stake=packed.stake,
        fork_pairs=packed.fork_pairs,
        member_table=packed.member_table,
        ids=np.frombuffer(b"".join(packed.ids), dtype=np.uint8),
        sigs=_pack_bytes_list(packed.sigs),
    )


def load_packed(path: str) -> PackedDAG:
    z = np.load(path)
    if int(z["format_version"]) != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {z['format_version']}")
    ids_flat = z["ids"].tobytes()
    h = crypto.HASH_BYTES
    return PackedDAG(
        n=int(z["n"]),
        n_members=int(z["n_members"]),
        parents=z["parents"],
        creator=z["creator"],
        seq=z["seq"],
        t=z["t"],
        coin=z["coin"],
        stake=z["stake"],
        fork_pairs=z["fork_pairs"],
        member_table=z["member_table"],
        ids=[ids_flat[i : i + h] for i in range(0, len(ids_flat), h)],
        sigs=_unpack_bytes_list(z["sigs"]),
    )


def save_archive(path: str, archive) -> None:
    """Persist a :class:`~tpu_swirld.store.archive.SlabArchive` (the
    streaming driver's decided-row store) as one ``.npz``: compressed row
    blobs, the retired-round ledger, and a BLAKE2b digest of the blob
    stream.  No pickle."""
    archive.save(path)


def load_archive(path: str):
    """Restore an archive and **verify its digest** — corruption or
    tampering raises ``ValueError`` at restore time rather than feeding
    wrong ancestry into a later widening rebase (the same fail-loudly
    contract :func:`load_node` applies to the decided prefix)."""
    from tpu_swirld.store.archive import SlabArchive

    return SlabArchive.load(path)


def save_node(path: str, node: Node) -> None:
    """Write the node's full event log (wire format) + config + members."""
    log = b"".join(encode_event(node.hg[e]) for e in node.order_added)
    cfg = dataclasses.asdict(node.config)
    cfg["stake"] = list(node.config.stakes())
    meta = {
        "format_version": FORMAT_VERSION,
        "config": cfg,
        "members": [m.hex() for m in node.members],
        "n_events": len(node.order_added),
        # horizon integrity: the committed frontier at save time and a
        # digest of the decided prefix; load_node verifies the replay
        # re-decides this exact prefix (replay purity made checkable)
        "decided": len(node.consensus),
        "frontier": node._frozen_round,
        "order_digest": crypto.hash_bytes(b"".join(node.consensus)).hex(),
    }
    ledger = getattr(node, "ledger", None)
    if ledger is not None:
        # dynamic membership: the epoch ledger rides the header so a
        # restore can verify the replay re-derives the identical epoch
        # sequence.  The node is rebuilt from the GENESIS member set —
        # the registry (meta["members"] above) regrows from decided
        # joins during replay, exactly as it grew live.
        meta["membership"] = {
            **ledger.to_meta(),
            "genesis_members": [m.hex() for m in node._genesis_members],
            "delay": node.membership_delay,
        }
    header = json.dumps(meta).encode()
    # atomic replace: a process killed (kill -9) mid-checkpoint must
    # leave either the previous checkpoint or the new one intact — a
    # torn half-file would fail the restart that most needs it
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(b"SWCK" + struct.pack("<I", len(header)) + header + log)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_node(
    path: str,
    sk: bytes,
    pk: bytes,
    network: Dict[bytes, Callable],
    network_want: Optional[Dict[bytes, Callable]] = None,
    clock: Optional[Callable[[], int]] = None,
    transport=None,
) -> Node:
    """Rebuild a node from a checkpoint: replay the validated event log and
    run one batch consensus pass (bit-identical by purity).

    ``transport`` re-attaches the restored node to a shared delivery layer
    (the crash-recovery path: a restarted node rejoins the same
    — possibly faulty — network it crashed out of and replays forward via
    gossip).
    """
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != b"SWCK":
        raise ValueError("not a tpu_swirld checkpoint")
    (hlen,) = struct.unpack_from("<I", data, 4)
    meta = json.loads(data[8 : 8 + hlen].decode())
    if meta["format_version"] != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {meta['format_version']}")
    cfg_dict = dict(meta["config"])
    cfg_dict["stake"] = tuple(cfg_dict["stake"])
    cfg = SwirldConfig(**cfg_dict)
    members = [bytes.fromhex(m) for m in meta["members"]]
    membership = meta.get("membership")
    node_cls = Node
    if membership is not None:
        from tpu_swirld.membership.dynamic import DynamicNode

        node_cls = DynamicNode
        # rebuild from the genesis member set; decided joins regrow the
        # registry during replay
        members = [bytes.fromhex(m) for m in membership["genesis_members"]]
    node = node_cls(
        sk=sk, pk=pk, network=network, members=members, config=cfg,
        clock=clock, create_genesis=False, network_want=network_want,
        transport=transport,
    )
    off = 8 + hlen
    new_ids = []
    while off < len(data):
        ev, off = decode_event(data, off)
        if node.add_event(ev):
            new_ids.append(ev.id)
    node.consensus_pass(new_ids)
    if node._tpu_engine is not None:
        # a backend='tpu' node with a lazy-batch threshold must still come
        # back fully computed — the restore contract is bit-identical state
        node._tpu_engine.flush()
    # horizon integrity (older checkpoints without the fields skip this):
    # the replay must re-decide at least the checkpointed frontier, and
    # the decided prefix must be byte-identical to what was saved
    decided = int(meta.get("decided", 0))
    digest = meta.get("order_digest")
    if digest is not None:
        if len(node.consensus) < decided:
            raise ValueError(
                f"checkpoint replay regressed the horizon: re-decided "
                f"{len(node.consensus)} < checkpointed {decided}"
            )
        frontier = int(meta.get("frontier", node._frozen_round))
        if node._frozen_round < frontier:
            raise ValueError(
                f"checkpoint replay regressed the frontier: re-froze "
                f"round {node._frozen_round} < checkpointed {frontier}"
            )
        got = crypto.hash_bytes(b"".join(node.consensus[:decided])).hex()
        if got != digest:
            raise ValueError(
                "checkpoint replay diverged from the saved decided prefix "
                "(corrupt checkpoint or consensus-rule drift)"
            )
    if membership is not None:
        from tpu_swirld.membership.epoch import EpochLedger

        # from_meta itself refuses an internally inconsistent document
        # (epochs edited without re-stamping the digest); the comparison
        # refuses a consistent-but-wrong ledger (digest re-stamped, or
        # drift in the activation rule) — either way the replay-derived
        # epoch sequence is the only accepted truth
        saved_ledger = EpochLedger.from_meta(membership)
        if not saved_ledger.same_epochs(node.ledger):
            raise ValueError(
                "checkpoint epoch ledger does not match the replay-"
                "derived ledger (tampered membership header or "
                "activation-rule drift)"
            )
    return node
