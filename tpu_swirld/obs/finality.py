"""Per-event finality lifecycle tracking: rounds-to-decision and
time-to-finality.

The whitepaper's virtual-voting pipeline decides fame over multiple
rounds; throughput alone does not show how *long* an event waits between
creation and its consensus slot.  This module tracks the full lifecycle:

- **birth**: the logical tick an event was created (oracle: the event's
  own ``t`` stamp) or the tick its ingest chunk entered the driver
  (batch/incremental/streaming — creation stamps are not wall-aligned
  with the driver's clock there);
- **rounds_to_decision**: ``round_received - round`` — a pure function
  of the DAG, so it is *engine-independent*: oracle, batch,
  ``IncrementalConsensus`` and ``StreamingConsensus`` must report
  bit-identical sequences for the same history (pinned by tests);
- **time_to_finality**: decided tick minus birth tick — logical ticks in
  simulations, wall-clock seconds in the bench (whatever the injected
  ``clock`` measures);
- **gossip propagation**: creation tick → first *remote* arrival, via
  the oracle ingest seam;
- **decided watermarks**: per-node gauges of the decided frontier.

Clock discipline: this module never reads wall time itself.  A clock is
an injected zero-arg callable — the simulation's logical tick counter or
``time.perf_counter`` from the bench driver.  The wall-clock lint rule
(SW003) covers this file, so any direct ``time.*`` call is a finding.

One tracker per engine; trackers mirror observations into the ambient
:class:`~tpu_swirld.obs.registry.Registry` (when given one) as
``finality_*`` histograms/gauges and keep exact sample lists for the
bench ``finality`` JSON section (:meth:`FinalityTracker.summary`).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence

#: buckets for rounds-to-decision (small integers; ``le`` semantics)
ROUNDS_BUCKETS = (
    0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 10.0, 15.0, 20.0, 30.0,
    50.0, 100.0,
)

#: buckets for time-to-finality / gossip propagation: spans sub-second
#: wall-clock latencies (bench) and integer logical-tick counts (sim)
TICKS_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 25.0,
    50.0, 100.0, 250.0, 500.0, 1000.0,
)


def percentile(sorted_samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted sample list."""
    if not sorted_samples:
        raise ValueError("no samples")
    n = len(sorted_samples)
    rank = max(1, min(n, math.ceil(q * n)))
    return sorted_samples[rank - 1]


def _dist(samples: List[float], prefix: str, out: Dict) -> None:
    s = sorted(samples)
    out[f"{prefix}_mean"] = sum(s) / len(s)
    out[f"{prefix}_p50"] = percentile(s, 0.50)
    out[f"{prefix}_p99"] = percentile(s, 0.99)
    out[f"{prefix}_max"] = s[-1]


def merged_dist(sample_lists: Sequence[Sequence[float]], prefix: str) -> Dict:
    """Mean/p50/p99/max over the *union* of several trackers' raw
    samples.  The cluster harness runs one tracker per OS process;
    cluster-level submission→decided percentiles must rank the merged
    samples, not average per-node percentiles (averaging percentiles is
    statistically meaningless).  ``{}`` when no samples exist."""
    merged = [float(x) for samples in sample_lists for x in samples]
    out: Dict = {}
    if merged:
        _dist(merged, prefix, out)
        out[f"{prefix}_count"] = len(merged)
    return out


class FinalityTracker:
    """Lifecycle tracker for one engine's decided events.

    Args:
      engine: label for the registry ``engine=`` dimension
        (``"oracle"``, ``"batch"``, ``"incremental"``, ``"streaming"``).
      clock: zero-arg callable giving the current tick; logical in sims,
        wall-clock in the bench.  ``None`` disables time-to-finality
        (rounds-to-decision still records — it needs no clock).
      registry: optional :class:`~tpu_swirld.obs.registry.Registry` to
        mirror observations into (``finality_*`` metric families).
    """

    def __init__(
        self,
        engine: str,
        clock: Optional[Callable[[], float]] = None,
        registry=None,
    ):
        self.engine = str(engine)
        self._clock = clock
        self._registry = registry
        self._births: Dict = {}          # key -> birth tick (undecided)
        self.rtd: List[int] = []         # rounds-to-decision, decided order
        self.ttf: List[float] = []       # time-to-finality, decided order
        self.gossip: List[float] = []    # creation -> first remote arrival
        self.phases: Dict[str, int] = {}  # streaming latency attribution
        self.watermarks: Dict[str, Dict] = {}
        self._gossip_seen = set()
        self._h_rtd = None               # cached registry handles
        self._h_ttf: Dict[Optional[str], object] = {}
        self._h_gossip = None

    # ------------------------------------------------------------- clock

    def now(self, now=None):
        if now is not None:
            return now
        return self._clock() if self._clock is not None else None

    # ------------------------------------------------------------- births

    def mark_birth(self, key, tick=None) -> None:
        """Stamp ``key``'s birth tick once (idempotent on re-offer)."""
        if key not in self._births:
            t = self.now(tick)
            if t is not None:
                self._births[key] = t

    def mark_births(self, lo: int, hi: int, tick=None) -> None:
        """Stamp integer-index keys ``lo..hi-1`` (driver ingest chunks)."""
        t = self.now(tick)
        if t is None:
            return
        births = self._births
        for k in range(int(lo), int(hi)):
            if k not in births:
                births[k] = t

    # ------------------------------------------------------------ decided

    def record_decided(
        self, key, round_, round_received, birth=None, now=None,
        phase: Optional[str] = None,
    ) -> None:
        """One event reached its consensus slot.

        ``rounds_to_decision = round_received - round`` is recorded
        always; ``time_to_finality`` only when a birth tick is known
        (explicit ``birth`` wins, else the stamp from
        :meth:`mark_birth`) *and* a current tick is available.
        """
        rtd = int(round_received) - int(round_)
        self.rtd.append(rtd)
        if birth is None:
            birth = self._births.pop(key, None)
        else:
            self._births.pop(key, None)
        ttf = None
        if birth is not None:
            t = self.now(now)
            if t is not None:
                ttf = float(t) - float(birth)
                if ttf < 0:
                    # decided-before-born can only mean the birth stamp
                    # and the clock live in different domains (logical
                    # tick vs wall seconds) — drop rather than poison
                    ttf = None
                else:
                    self.ttf.append(ttf)
        if phase is not None:
            self.phases[phase] = self.phases.get(phase, 0) + 1
        reg = self._registry
        if reg is not None:
            if self._h_rtd is None:
                self._h_rtd = reg.histogram(
                    "finality_rounds_to_decision",
                    {"engine": self.engine}, buckets=ROUNDS_BUCKETS,
                )
            self._h_rtd.observe(rtd)
            if ttf is not None:
                h = self._h_ttf.get(phase)
                if h is None:
                    labels = {"engine": self.engine}
                    if phase is not None:
                        labels["phase"] = phase
                    h = self._h_ttf[phase] = reg.histogram(
                        "finality_time_to_finality", labels,
                        buckets=TICKS_BUCKETS,
                    )
                h.observe(ttf)

    # ------------------------------------------------------------- gossip

    def record_gossip_arrival(self, eid, created_tick, now=None) -> None:
        """First *remote* arrival of ``eid``: creation -> here latency.

        Deduplicated per event id — later duplicate deliveries (gossip
        fans out) do not re-observe.
        """
        if eid in self._gossip_seen:
            return
        self._gossip_seen.add(eid)
        t = self.now(now)
        if t is None or created_tick is None:
            return
        d = float(t) - float(created_tick)
        self.gossip.append(d)
        reg = self._registry
        if reg is not None:
            if self._h_gossip is None:
                self._h_gossip = reg.histogram(
                    "finality_gossip_propagation", buckets=TICKS_BUCKETS,
                )
            self._h_gossip.observe(d)

    # ---------------------------------------------------------- watermark

    def set_watermark(self, label: str, decided: int, round_=None) -> None:
        """Per-node decided frontier: events ordered (+ last decided
        round when known)."""
        wm = {"decided": int(decided)}
        if round_ is not None:
            wm["round"] = int(round_)
        self.watermarks[str(label)] = wm
        reg = self._registry
        if reg is not None:
            reg.gauge(
                "finality_decided_watermark", {"node": str(label)}
            ).set(decided)
            if round_ is not None:
                reg.gauge(
                    "finality_decided_round", {"node": str(label)}
                ).set(round_)

    # ------------------------------------------------------------ summary

    def summary(self) -> Dict:
        """Bench-ready digest: decided count, rounds-to-decision
        mean/p50/p99/max, time-to-finality mean/p50/p99/max (same unit
        as the injected clock), phase attribution and gossip stats."""
        out: Dict = {"engine": self.engine, "decided": len(self.rtd)}
        if self.rtd:
            _dist([float(r) for r in self.rtd], "rtd", out)
        if self.ttf:
            _dist(self.ttf, "ttf", out)
        if self.phases:
            out["phases"] = dict(sorted(self.phases.items()))
        if self.gossip:
            _dist(self.gossip, "gossip", out)
            out["gossip_samples"] = len(self.gossip)
        out["undecided"] = len(self._births)
        return out


def record_batch_result(
    tracker: FinalityTracker, result, now=None, birth=None
) -> None:
    """Record every decided event of a batch
    :class:`~tpu_swirld.tpu.pipeline.ConsensusResult` into ``tracker``
    in consensus order.

    The batch engine decides a whole history in one pass, so its
    time-to-finality is degenerate: every event shares the pass-end
    tick; pass ``birth`` (the pass-start tick) to record the uniform
    pass latency, or leave both ``None`` for rounds-only recording.
    """
    rd = result.round
    rr = result.round_received
    t = tracker.now(now)
    for gi in result.order:
        gi = int(gi)
        tracker.record_decided(
            gi, int(rd[gi]), int(rr[gi]), birth=birth, now=t,
        )
