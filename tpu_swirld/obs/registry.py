"""Counter / gauge / histogram registry with Prometheus-text and JSON export.

The protocol-gauge half of the observability subsystem (the span half lives
in :mod:`tpu_swirld.obs.tracer`).  Design constraints, in order:

1. *Zero cost when nobody holds a registry* — metric objects are created
   lazily by the instrumented call sites only when an enabled registry is
   in scope; the disabled path never allocates (see ``obs.current()``).
2. *Exact* — counters and gauges are plain Python ints/floats, no sampling.
3. *Exportable* — ``to_prometheus_text()`` renders the standard text
   exposition format (``# TYPE`` headers, ``name{label="v"} value`` lines);
   ``to_json()`` renders a stable dict for BENCH-style JSON artifacts.

Metric identity is ``(name, sorted(labels))``; the same call site with the
same labels always returns the same object, so hot loops may cache the
metric handle themselves if they want to skip the dict lookup.
"""

from __future__ import annotations

import json
import math
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

# default histogram buckets: exponential, suited to seconds-scale latencies
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

LabelDict = Optional[Dict[str, str]]


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _label_key(labels: LabelDict) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(items: Tuple[Tuple[str, str], ...]) -> str:
    if not items:
        return ""
    # Prometheus text exposition: label values escape backslash, quote,
    # AND newline (a raw newline would split the sample line and break
    # the scrape)
    body = ",".join(
        '%s="%s"' % (
            k,
            v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n"),
        )
        for k, v in items
    )
    return "{%s}" % body


class Counter:
    """Monotonically increasing value (float to allow seconds totals)."""

    kind = "counter"

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError("counters only go up; use a Gauge")
        self.value += delta


class Gauge:
    """Point-in-time value; settable in any direction."""

    kind = "gauge"

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, delta: float = 1.0) -> None:
        self.value += delta

    def dec(self, delta: float = 1.0) -> None:
        self.value -= delta


class Histogram:
    """Fixed-bucket histogram tracking count / sum / min / max.

    Buckets are cumulative upper bounds (Prometheus ``le`` semantics); the
    implicit ``+Inf`` bucket is always present.
    """

    kind = "histogram"

    __slots__ = ("name", "labels", "buckets", "bucket_counts", "count",
                 "sum", "min", "max")

    def __init__(
        self,
        name: str,
        labels: Tuple[Tuple[str, str], ...] = (),
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets))
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.bucket_counts[i] += 1
                return
        self.bucket_counts[-1] += 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


class Registry:
    """Get-or-create store of metrics, keyed by (name, labels)."""

    def __init__(self) -> None:
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._kinds: Dict[str, type] = {}   # one kind per name, all labels

    def __len__(self) -> int:
        return len(self._metrics)

    def _get(self, cls, name: str, labels: LabelDict, **kw):
        name = _sanitize(name)
        kind = self._kinds.get(name)
        if kind is None:
            self._kinds[name] = cls
        elif kind is not cls:
            # a name must have ONE kind across every label set, or the
            # Prometheus exposition (one # TYPE header per name) is invalid
            raise TypeError(
                f"metric {name!r} already registered as {kind.__name__}"
            )
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            m = cls(name, key[1], **kw)
            self._metrics[key] = m
        return m

    def counter(self, name: str, labels: LabelDict = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: LabelDict = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(
        self,
        name: str,
        labels: LabelDict = None,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        h = self._get(Histogram, name, labels, buckets=buckets)
        if h.buckets != tuple(sorted(buckets)):
            # _get only applies buckets on first creation; a silent
            # mismatch would scatter observations across wrong buckets
            raise ValueError(
                f"histogram {name!r} already registered with different "
                f"buckets"
            )
        return h

    # ------------------------------------------------------------- queries

    def metrics(self) -> List[object]:
        """All metrics, sorted by (name, labels) for stable export order."""
        return [self._metrics[k] for k in sorted(self._metrics)]

    def collect(self, name: str) -> Dict[Tuple[Tuple[str, str], ...], object]:
        """All label-variants of one metric name."""
        name = _sanitize(name)
        return {
            k[1]: m for k, m in self._metrics.items() if k[0] == name
        }

    def value(self, name: str, labels: LabelDict = None, default=None):
        """Read a metric's value without creating it."""
        m = self._metrics.get((_sanitize(name), _label_key(labels)))
        if m is None:
            return default
        return m.count if isinstance(m, Histogram) else m.value

    # ------------------------------------------------------------ exporters

    def to_prometheus_text(self, prefix: str = "") -> str:
        """Standard Prometheus text exposition format."""
        prefix = _sanitize(prefix) if prefix else ""
        lines: List[str] = []
        seen_type: set = set()
        for m in self.metrics():
            full = prefix + m.name
            if full not in seen_type:
                lines.append(f"# TYPE {full} {m.kind}")
                seen_type.add(full)
            lab = m.labels
            if isinstance(m, Histogram):
                cum = 0
                for ub, c in zip(m.buckets, m.bucket_counts):
                    cum += c
                    items = lab + (("le", repr(float(ub))),)
                    lines.append(f"{full}_bucket{_render_labels(items)} {cum}")
                cum += m.bucket_counts[-1]
                items = lab + (("le", "+Inf"),)
                lines.append(f"{full}_bucket{_render_labels(items)} {cum}")
                lines.append(f"{full}_sum{_render_labels(lab)} {_fmt(m.sum)}")
                lines.append(f"{full}_count{_render_labels(lab)} {m.count}")
            else:
                lines.append(f"{full}{_render_labels(lab)} {_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_dict(self) -> Dict[str, Dict]:
        """JSON-ready snapshot: one entry per (name, labels) variant."""
        out: Dict[str, Dict] = {}
        for m in self.metrics():
            key = m.name + _render_labels(m.labels)
            if isinstance(m, Histogram):
                out[key] = {
                    "kind": m.kind,
                    "count": m.count,
                    "sum": round(m.sum, 9),
                    "mean": round(m.mean, 9),
                    "min": None if m.count == 0 else round(m.min, 9),
                    "max": None if m.count == 0 else round(m.max, 9),
                }
            else:
                out[key] = {"kind": m.kind, "value": _num(m.value)}
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    # ------------------------------------------------- structured samples

    def to_samples(self) -> List[Dict]:
        """Structured, JSON-ready sample list that survives a wire hop
        and reloads losslessly via :meth:`load_samples` — the body of a
        cluster ``KIND_METRICS`` reply.  Unlike :meth:`to_dict` (whose
        keys are rendered label strings), labels stay a dict so a
        receiver can re-label (e.g. add ``node=...``) before merging."""
        out: List[Dict] = []
        for m in self.metrics():
            s: Dict = {
                "name": m.name, "kind": m.kind, "labels": dict(m.labels),
            }
            if isinstance(m, Histogram):
                s["buckets"] = [float(b) for b in m.buckets]
                s["bucket_counts"] = list(m.bucket_counts)
                s["count"] = m.count
                s["sum"] = m.sum
                s["min"] = None if m.count == 0 else m.min
                s["max"] = None if m.count == 0 else m.max
            else:
                s["value"] = m.value
            out.append(s)
        return out

    def load_samples(
        self, samples: List[Dict], extra_labels: LabelDict = None,
    ) -> None:
        """Merge :meth:`to_samples` output into this registry, optionally
        re-labeled (``extra_labels``).  Counters and histograms *add*
        (so loading N node snapshots yields cluster totals when the
        extra labels are omitted); gauges overwrite."""
        for s in samples:
            labels = dict(s.get("labels") or {})
            labels.update(extra_labels or {})
            kind = s.get("kind")
            if kind == "counter":
                self.counter(s["name"], labels).inc(float(s["value"]))
            elif kind == "gauge":
                self.gauge(s["name"], labels).set(float(s["value"]))
            elif kind == "histogram":
                h = self.histogram(
                    s["name"], labels, buckets=tuple(s["buckets"]),
                )
                counts = [int(c) for c in s["bucket_counts"]]
                if len(counts) != len(h.bucket_counts):
                    raise ValueError(
                        f"histogram {s['name']!r}: bucket count mismatch"
                    )
                for i, c in enumerate(counts):
                    h.bucket_counts[i] += c
                h.count += int(s["count"])
                h.sum += float(s["sum"])
                if s.get("min") is not None and s["min"] < h.min:
                    h.min = float(s["min"])
                if s.get("max") is not None and s["max"] > h.max:
                    h.max = float(s["max"])
            else:
                raise ValueError(f"unknown sample kind {kind!r}")


def merge_node_samples(per_node: Dict[str, List[Dict]]) -> "Registry":
    """One merged registry from per-node sample lists: every sample is
    re-labeled with its ``node``, so the Prometheus exposition carries
    the whole fleet without name collisions."""
    merged = Registry()
    for node in sorted(per_node):
        merged.load_samples(per_node[node], extra_labels={"node": node})
    return merged


def rollup_node_samples(per_node: Dict[str, List[Dict]]) -> Dict[str, float]:
    """Cluster-wide scalar rollup: counters and gauges summed across
    nodes per (name, labels) — the at-a-glance fleet totals the verdict
    and the report CLI render."""
    totals: Dict[str, float] = {}
    for node in sorted(per_node):
        for s in per_node[node]:
            if s.get("kind") == "histogram":
                key = s["name"] + _render_labels(
                    _label_key(s.get("labels") or {})
                ) + "_count"
                totals[key] = totals.get(key, 0.0) + float(s["count"])
            else:
                key = s["name"] + _render_labels(
                    _label_key(s.get("labels") or {})
                )
                totals[key] = totals.get(key, 0.0) + float(s["value"])
    return {k: _num(v) for k, v in sorted(totals.items())}


def _num(v: float):
    """Render integral floats as ints (counters are usually counts)."""
    return int(v) if float(v).is_integer() else round(v, 9)


def _fmt(v: float) -> str:
    return str(_num(v))
