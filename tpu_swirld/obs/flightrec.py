"""Black-box flight recorder: bounded per-node rings + post-mortem dumps.

Aviation-style observability for the consensus core: every node keeps a
bounded ring of its most recent activity (spans, counter deltas,
ingested-event digests, turn marks), and whenever something goes wrong —
a chaos/adversary verdict fails, an overflow heal fires, a circuit
breaker opens, a rebase storm triggers — the recorder writes one
*self-contained* post-mortem JSON: ring contents, the ambient registry
snapshot, the active config, and the decided frontier of every node.  A
red verdict thereby ships its own forensic bundle; no re-run needed.

Design constraints:

- *near-zero steady-state overhead*: recording is one dict append onto a
  ``deque(maxlen=capacity)``; nothing is serialized, hashed beyond an
  8-byte event-id prefix, or written to disk until a trigger fires;
- *bounded*: rings hold the last ``capacity`` entries per node and at
  most ``max_dumps`` dump files are ever written per recorder, so a
  trigger storm cannot fill the disk;
- *deterministic*: the recorder never reads wall time itself — the
  logical clock is an injected callable (the sim's turn counter), and
  the optional ``wall_clock`` stays ``None`` in simulations, so the same
  seed and trigger produce a byte-identical dump.  The wall-clock lint
  rule (SW003) covers this file.

Sizing knobs resolve field > ``SWIRLD_FLIGHTREC_*`` env var > default
via :func:`tpu_swirld.config.resolve_flightrec_settings`
(``SWIRLD_FLIGHTREC_CAPACITY``, ``SWIRLD_FLIGHTREC_MAX_DUMPS``,
``SWIRLD_FLIGHTREC_DIR``).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional

from tpu_swirld.config import resolve_flightrec_settings

#: dump-file schema tag; bump on incompatible layout changes
SCHEMA = "tpu-swirld-flightrec/1"

#: trigger reasons wired in-tree (callers may add their own)
REASONS = (
    "verdict_failed", "overflow_heal", "breaker_open", "rebase_storm",
    "unclean_shutdown",
)


def _digest(eid) -> str:
    """Short stable digest of an event id (already a hash — 8-byte
    prefix is plenty for ring forensics)."""
    if isinstance(eid, (bytes, bytearray)):
        return bytes(eid[:8]).hex()
    return str(eid)[:16]


class FlightRecorder:
    """Bounded multi-node ring recorder with trigger-driven dumps.

    Args:
      capacity: ring entries kept per node (field>env>default: 256).
      dump_dir: where post-mortems land; ``None`` (the resolved default)
        records in memory only — :meth:`trigger` then returns ``None``.
      max_dumps: dump files written before further triggers only mark
        the ring (field>env>default: 16).
      clock: zero-arg logical-tick callable (sim turn counter); stamps
        every ring entry.  ``None`` → entries carry ``tick: None``.
      wall_clock: optional zero-arg wall-time callable for bench-side
        dumps; **leave None in simulations** so dumps stay byte-stable.
      config: optional :class:`~tpu_swirld.config.SwirldConfig` — both
        the knob source and the config echoed into dumps.
      node_name: process identity stamped into every dump so shards
        from different node processes correlate against the merged
        cluster timeline.
      trace_provider: optional zero-arg callable returning the hex id of
        the currently active trace (e.g. ``Tracer.active_trace_hex``) —
        dumps record which cross-process trace was in flight when the
        trigger fired.  Stays ``None`` in simulations (byte-stable
        dumps).
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        dump_dir: Optional[str] = None,
        max_dumps: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
        wall_clock: Optional[Callable[[], float]] = None,
        config=None,
        node_name: Optional[str] = None,
        trace_provider: Optional[Callable[[], Optional[str]]] = None,
    ):
        s = resolve_flightrec_settings(config)
        self.capacity = int(capacity if capacity is not None
                            else s["capacity"])
        self.max_dumps = int(max_dumps if max_dumps is not None
                             else s["max_dumps"])
        self.dump_dir = dump_dir if dump_dir is not None else s["dump_dir"]
        self._clock = clock
        self._wall = wall_clock
        self._config = config
        self.node_name = node_name
        self._trace_provider = trace_provider
        self._rings: Dict[str, collections.deque] = {}
        self.records_total = 0
        self.trigger_counts: Dict[str, int] = {}
        self.dumps: List[str] = []
        self._seq = 0

    # ------------------------------------------------------------ record

    def _ring(self, node) -> collections.deque:
        key = str(node)
        r = self._rings.get(key)
        if r is None:
            r = self._rings[key] = collections.deque(maxlen=self.capacity)
        return r

    def _tick(self):
        return self._clock() if self._clock is not None else None

    def record(self, node, kind: str, **fields) -> None:
        """Append one ring entry for ``node`` (the steady-state hot
        path: one dict build + deque append, no I/O)."""
        self.records_total += 1
        entry = {"kind": kind, "tick": self._tick()}
        entry.update(fields)
        self._ring(node).append(entry)

    def record_ingest(self, node, eid) -> None:
        """Digest of an event accepted into ``node``'s hashgraph."""
        self.record(node, "ingest", eid=_digest(eid))

    def record_counter(self, node, name: str, delta) -> None:
        """A counter moved (record the delta, not the absolute — rings
        replay as increments)."""
        self.record(node, "counter", name=str(name), delta=delta)

    def record_span(self, node, name: str, dur) -> None:
        """A completed span's duration (same unit as the clock)."""
        self.record(node, "span", name=str(name), dur=dur)

    def record_turn(self, node, turn: int, **fields) -> None:
        """Per-turn mark (decided watermark, new-event count, ...)."""
        self.record(node, "turn", turn=int(turn), **fields)

    # ----------------------------------------------------------- trigger

    def trigger(
        self,
        reason: str,
        node=None,
        detail=None,
        decided_frontier=None,
        registry=None,
    ) -> Optional[str]:
        """An anomaly fired: mark the ring and, when a ``dump_dir`` is
        configured and the ``max_dumps`` budget allows, write a
        post-mortem.  Returns the dump path or ``None``."""
        self.trigger_counts[reason] = self.trigger_counts.get(reason, 0) + 1
        self.record(node if node is not None else "_global", "trigger",
                    reason=str(reason), detail=detail)
        if registry is not None:
            registry.counter(
                "flightrec_triggers_total", {"reason": str(reason)}
            ).inc()
        path = None
        if self.dump_dir is not None:
            path = self.dump(
                reason, detail=detail, decided_frontier=decided_frontier,
                registry=registry,
            )
        return path

    # -------------------------------------------------------------- dump

    def snapshot(
        self, reason: str, detail=None, decided_frontier=None,
        registry=None,
    ) -> Dict:
        """The self-contained post-mortem body (also what :meth:`dump`
        writes).  Key order is canonical via ``sort_keys`` at write
        time; ``wall_time_s`` is ``None`` unless a wall clock was
        injected, so sim dumps are byte-stable."""
        cfg = None
        if self._config is not None:
            if dataclasses.is_dataclass(self._config):
                cfg = dataclasses.asdict(self._config)
            else:
                cfg = dict(getattr(self._config, "__dict__", {}) or {})
            if isinstance(cfg, dict):
                cfg = {
                    k: (list(v) if isinstance(v, tuple) else v)
                    for k, v in cfg.items()
                    if isinstance(v, (int, float, str, bool, tuple,
                                      list, type(None)))
                }
        return {
            "schema": SCHEMA,
            "reason": str(reason),
            "seq": self._seq,
            "node_name": self.node_name,
            "trace_id": (
                self._trace_provider()
                if self._trace_provider is not None else None
            ),
            "logical_tick": self._tick(),
            "wall_time_s": self._wall() if self._wall is not None else None,
            "capacity": self.capacity,
            "records_total": self.records_total,
            "trigger_counts": dict(sorted(self.trigger_counts.items())),
            "detail": detail,
            "config": cfg,
            "decided_frontier": decided_frontier,
            "registry": registry.to_dict() if registry is not None else None,
            "rings": {
                node: list(ring)
                for node, ring in sorted(self._rings.items())
            },
        }

    def dump(
        self, reason: str, detail=None, decided_frontier=None,
        registry=None,
    ) -> Optional[str]:
        """Write one post-mortem JSON; respects ``max_dumps``."""
        if self.dump_dir is None or len(self.dumps) >= self.max_dumps:
            return None
        self._seq += 1
        body = self.snapshot(
            reason, detail=detail, decided_frontier=decided_frontier,
            registry=registry,
        )
        os.makedirs(self.dump_dir, exist_ok=True)
        path = os.path.join(
            self.dump_dir, f"flightrec_{self._seq:03d}_{reason}.json"
        )
        with open(path, "w") as f:
            json.dump(body, f, indent=2, sort_keys=True)
            f.write("\n")
        self.dumps.append(path)
        if registry is not None:
            registry.counter("flightrec_dumps_total").inc()
            registry.gauge("flightrec_records_total").set(self.records_total)
        return path

    def summary(self) -> Dict:
        """Verdict-ready digest (dump paths, trigger counts, ring sizes)."""
        return {
            "records_total": self.records_total,
            "nodes": len(self._rings),
            "trigger_counts": dict(sorted(self.trigger_counts.items())),
            "dumps": list(self.dumps),
        }


def load_dump(path: str) -> Dict:
    """Load and schema-check a post-mortem written by :meth:`dump`."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: not a flight-recorder dump "
            f"(schema={doc.get('schema')!r}, want {SCHEMA!r})"
        )
    return doc


def wire_node(node, rec: FlightRecorder, label: str) -> None:
    """Attach ``rec`` to an oracle node: ingest digests flow into the
    ring and the node's circuit breaker reports open transitions as
    ``breaker_open`` triggers."""
    node.flightrec = rec
    node.flightrec_label = str(label)
    breaker = getattr(node, "breaker", None)
    if breaker is not None:
        def _on_open(peer, _rec=rec, _label=str(label)):
            _rec.trigger(
                "breaker_open", node=_label,
                detail={"peer": _digest(peer)},
            )
        breaker.on_open = _on_open
