import sys

from tpu_swirld.obs.report import main

sys.exit(main(sys.argv[1:]))
