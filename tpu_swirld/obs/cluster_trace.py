"""Cluster trace merger: per-process JSONL shards -> one Chrome timeline.

Every cluster process (each ``node_proc`` runtime plus the supervisor's
client tracer) writes its own Chrome-trace JSONL shard.  Span ``ts``
values are *per-process* monotonic microseconds — meaningless across
processes — but every span also records its wall-clock start in
``args.wall_s``.  The merger rebases each shard onto the shared wall
clock (per-shard offset = median of ``wall_s*1e6 - ts`` over its spans,
robust to a few clock-step outliers), renumbers ``pid`` so Perfetto
shows one lane per process, and emits Chrome *flow* events (``ph: "s"``
/ ``ph: "f"``) for every parent/child span edge that crosses a process
boundary — the visual arrows that turn N shards into one causal story:
client submit → node submit → gossip sync → remote serve → decided.

Span identity: ``args.span_id`` is process-unique (the tracer folds its
pid into the id's upper bits), ``args.parent_span_id`` points at the
parent span — possibly in another shard — and ``args.trace`` is the hex
trace id carried across the wire by the frame header's 16-byte context
(:mod:`tpu_swirld.net.frame`).  Nothing here reads a clock: the merger
is a pure function of the shard files, so merging is reproducible.

CLI::

    python -m tpu_swirld.obs.cluster_trace <cluster-workdir> \
        [-o merged.trace.json]

writes the wrapped ``{"traceEvents": [...]}`` form Perfetto opens
directly and prints a per-trace summary (span count, processes touched,
cross-process edges).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Tuple

from tpu_swirld.obs.tracer import load_trace

#: shard filename suffix every cluster process uses
SHARD_SUFFIX = ".trace.jsonl"


def shard_label(path: str) -> str:
    """Process label from a shard filename: ``node-3.trace.jsonl`` ->
    ``n3``, ``client.trace.jsonl`` -> ``client``."""
    base = os.path.basename(path)
    stem = base[:-len(SHARD_SUFFIX)] if base.endswith(SHARD_SUFFIX) else base
    if stem.startswith("node-"):
        return "n" + stem[len("node-"):]
    return stem


def find_shards(dirpath: str) -> List[Tuple[str, str]]:
    """Sorted ``(label, path)`` shard list in a cluster workdir."""
    out = []
    for name in sorted(os.listdir(dirpath)):
        if name.endswith(SHARD_SUFFIX):
            path = os.path.join(dirpath, name)
            out.append((shard_label(path), path))
    return out


def _shard_offset_us(events: List[Dict]) -> Optional[float]:
    """Per-shard rebase offset: median of ``wall_s*1e6 - ts`` over spans
    (median, not mean — a wall-clock step mid-run must not skew every
    other span)."""
    deltas = []
    for e in events:
        if e.get("ph") in ("X", "i"):
            wall = (e.get("args") or {}).get("wall_s")
            if wall is not None:
                deltas.append(wall * 1e6 - e.get("ts", 0.0))
    if not deltas:
        return None
    deltas.sort()
    return deltas[len(deltas) // 2]


def merge_events(shards: List[Tuple[str, List[Dict]]]) -> List[Dict]:
    """Merge labeled shards into one event list on a shared timebase.

    Returns Chrome trace events: per-process metadata, every shard event
    rebased with ``pid`` = shard index, plus flow ``s``/``f`` pairs for
    cross-process parent/child span edges.
    """
    merged: List[Dict] = []
    offsets: List[Optional[float]] = []
    for _label, events in shards:
        offsets.append(_shard_offset_us(events))
    known = [o for o in offsets if o is not None]
    base = min(known) if known else 0.0

    # pass 1: rebase + index spans by (trace, span_id)
    span_at: Dict[Tuple[str, int], Dict] = {}
    for pid, (label, events) in enumerate(shards):
        merged.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        off = offsets[pid]
        shift = (off - base) if off is not None else 0.0
        for e in events:
            e2 = dict(e, pid=pid, ts=round(e.get("ts", 0.0) + shift, 3))
            merged.append(e2)
            args = e2.get("args") or {}
            if e2.get("ph") == "X" and "span_id" in args and "trace" in args:
                span_at[(args["trace"], args["span_id"])] = e2

    # pass 2: flow arrows for edges whose parent lives in another shard
    flow_id = 0
    flows: List[Dict] = []
    for key in sorted(span_at):
        child = span_at[key]
        cargs = child["args"]
        parent_id = cargs.get("parent_span_id")
        if parent_id is None:
            continue
        parent = span_at.get((cargs["trace"], parent_id))
        if parent is None or parent["pid"] == child["pid"]:
            continue
        flow_id += 1
        common = {"name": "trace", "cat": "trace", "id": flow_id}
        flows.append(dict(
            common, ph="s", pid=parent["pid"], tid=parent.get("tid", 0),
            ts=parent["ts"],
        ))
        flows.append(dict(
            common, ph="f", bp="e", pid=child["pid"],
            tid=child.get("tid", 0), ts=child["ts"],
        ))
    merged.extend(flows)
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    return merged


def trace_summaries(merged: List[Dict]) -> Dict[str, Dict]:
    """Per-trace digest of a merged timeline: span count, processes
    touched, and the resolved parent/child edges (cross-process edges
    separately — the acceptance signal that propagation worked)."""
    spans: Dict[Tuple[str, int], Dict] = {}
    for e in merged:
        args = e.get("args") or {}
        if e.get("ph") == "X" and "trace" in args and "span_id" in args:
            spans[(args["trace"], args["span_id"])] = e
    out: Dict[str, Dict] = {}
    for key in sorted(spans):
        trace, _sid = key
        e = spans[key]
        t = out.setdefault(trace, {
            "spans": 0, "pids": [], "names": [],
            "edges": 0, "cross_process_edges": 0,
        })
        t["spans"] += 1
        if e["pid"] not in t["pids"]:
            t["pids"].append(e["pid"])
        if e["name"] not in t["names"]:
            t["names"].append(e["name"])
        parent_id = (e.get("args") or {}).get("parent_span_id")
        if parent_id is not None:
            parent = spans.get((trace, parent_id))
            if parent is not None:
                t["edges"] += 1
                if parent["pid"] != e["pid"]:
                    t["cross_process_edges"] += 1
    for t in out.values():
        t["pids"].sort()
    return out


def merge_dir(dirpath: str, out_path: Optional[str] = None) -> Dict:
    """Merge every shard in ``dirpath``; write the wrapped Chrome form
    when ``out_path`` is given.  Returns a JSON-ready summary."""
    shard_files = find_shards(dirpath)
    shards = [(label, load_trace(path)) for label, path in shard_files]
    merged = merge_events(shards)
    traces = trace_summaries(merged)
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump({"traceEvents": merged}, f)
    cross = sorted(
        t for t, info in traces.items() if len(info["pids"]) >= 2
    )
    return {
        "shards": [path for _label, path in shard_files],
        "events": len(merged),
        "out": out_path,
        "traces": len(traces),
        "cross_process_traces": len(cross),
        "cross_process_trace_ids": cross[:32],
        "per_trace": traces,
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_swirld.obs.cluster_trace",
        description="Merge per-process trace shards into one timeline.",
    )
    p.add_argument("workdir", help="cluster workdir holding *.trace.jsonl")
    p.add_argument("-o", "--out", default=None,
                   help="write merged {'traceEvents': ...} JSON here")
    args = p.parse_args(argv)
    summary = merge_dir(args.workdir, out_path=args.out)
    brief = {k: v for k, v in summary.items() if k != "per_trace"}
    print(json.dumps(brief, indent=2, sort_keys=True))
    for trace in sorted(summary["per_trace"]):
        info = summary["per_trace"][trace]
        print(
            f"trace {trace}: {info['spans']} spans over "
            f"{len(info['pids'])} process(es), "
            f"{info['cross_process_edges']} cross-process edge(s)"
        )
    return 0


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))
