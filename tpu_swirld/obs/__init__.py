"""Observability subsystem: structured spans + protocol gauges (SURVEY §5).

SURVEY §5 lists metrics/telemetry among the aux subsystems the reference
never had ("no logging, no metrics, no persistence — state dies with the
process"); this package is the real implementation the ad-hoc
``tpu_swirld.metrics`` counters grew into.  Three pieces:

- :mod:`tpu_swirld.obs.tracer` — a nested-span tracer with wall-clock +
  monotonic timestamps and JSONL export in Chrome trace-event form
  (``chrome://tracing`` / Perfetto compatible after ``[...]`` wrapping).
- :mod:`tpu_swirld.obs.registry` — counters / gauges / histograms with
  Prometheus-text and JSON exporters.
- :mod:`tpu_swirld.obs.report` — the ``python -m tpu_swirld.obs report``
  CLI rendering a phase-breakdown table + protocol gauges from a trace.
- :mod:`tpu_swirld.obs.finality` — per-event lifecycle tracking:
  rounds-to-decision / time-to-finality histograms, decided watermarks,
  gossip-propagation latency (``finality_*`` metric families).
- :mod:`tpu_swirld.obs.flightrec` — the black-box flight recorder:
  bounded per-node rings of recent activity, dumped as self-contained
  post-mortem JSON when a verdict fails / breaker opens / overflow heals
  / rebase storm triggers (``flightrec_*`` metric families).

Instrumented layers: oracle phases (``oracle/node.py::consensus_pass``),
gossip (sync round-trips / payload bytes / events-per-sync / fork
detections), the device pipeline stages (``tpu/pipeline.py`` — per-stage
compile-vs-execute time, pad waste, strongly-sees column and chunk-scan
counts), and the mesh path (``parallel.py``).  For device-internal
profiling beyond stage granularity use ``metrics.trace_consensus`` (XProf).

Enabling
--------

Everything is **disabled by default with near-zero overhead**: the hot
paths check a module global (``obs.current() is None``) and touch neither
tracer nor registry when it is unset.  Enable around a region::

    from tpu_swirld import obs

    with obs.enabled() as o:                 # or o = obs.enable()
        run_consensus(packed, config)
    o.save("/tmp/swirld.trace.jsonl")        # spans + registry snapshot
    print(o.registry.to_prometheus_text())

then render with ``python -m tpu_swirld.obs report /tmp/swirld.trace.jsonl``.

Per-node oracle counters remain opt-in via ``node.metrics = Metrics()``
(now a thin shim over :class:`Registry`) and ``node.tracer = Tracer()``;
``sim.make_simulation(..., metrics=..., tracer=...)`` wires whole
simulations.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from tpu_swirld.obs.finality import (  # noqa: F401
    FinalityTracker, record_batch_result,
)
from tpu_swirld.obs.flightrec import (  # noqa: F401
    FlightRecorder, load_dump,
)
from tpu_swirld.obs.memory import (  # noqa: F401
    MemoryMonitor, device_live_bytes,
)
from tpu_swirld.obs.profile import DispatchProfiler  # noqa: F401
from tpu_swirld.obs.registry import (  # noqa: F401
    Counter, Gauge, Histogram, Registry,
)
from tpu_swirld.obs.tracer import (  # noqa: F401
    NULL_TRACER, NullTracer, Tracer, load_trace,
)


class Obs:
    """A tracer + registry bundle — the unit ``enable()`` installs.
    An optional :class:`~tpu_swirld.obs.profile.DispatchProfiler` rides
    along; when present, every :func:`stage_call` feeds it."""

    def __init__(
        self,
        tracer: Optional[Tracer] = None,
        registry: Optional[Registry] = None,
        profiler: Optional[DispatchProfiler] = None,
    ):
        self.tracer = tracer if tracer is not None else Tracer()
        self.registry = registry if registry is not None else Registry()
        self.profiler = profiler

    def save(self, path: str) -> None:
        """Write the trace plus the registry snapshot (as Chrome counter
        samples) so one file carries both timing and gauges.  The tracer
        itself is not mutated — repeated saves snapshot fresh values
        instead of accumulating stale duplicates."""
        import json as _json

        from tpu_swirld.obs.registry import Histogram as _H, _num

        events = list(self.tracer.events)
        for m in self.registry.metrics():
            labels = {k: v for k, v in m.labels}
            if isinstance(m, _H):
                events.append(
                    self.tracer.counter_event(
                        m.name + "_count", m.count, labels
                    )
                )
                events.append(
                    self.tracer.counter_event(
                        m.name + "_sum", round(m.sum, 9), labels
                    )
                )
            else:
                events.append(
                    self.tracer.counter_event(m.name, _num(m.value), labels)
                )
        with open(path, "w") as f:
            for e in events:
                f.write(_json.dumps(e) + "\n")


_current: Optional[Obs] = None


def current() -> Optional[Obs]:
    """The ambient Obs, or None when observability is disabled (default).

    Hot paths gate on this: ``o = obs.current(); if o is not None: ...`` —
    one global read on the disabled path, nothing else.
    """
    return _current


def enable(obs: Optional[Obs] = None) -> Obs:
    """Install (and return) the ambient Obs."""
    global _current
    _current = obs if obs is not None else Obs()
    return _current


def disable() -> Optional[Obs]:
    """Clear the ambient Obs; returns the one that was active."""
    global _current
    prev, _current = _current, None
    return prev


@contextlib.contextmanager
def enabled(obs: Optional[Obs] = None):
    """Scoped enable: ``with obs.enabled() as o: ...`` (restores the
    previous ambient Obs on exit, so scopes nest)."""
    global _current
    prev = _current
    o = obs if obs is not None else Obs()
    _current = o
    try:
        yield o
    finally:
        _current = prev


@contextlib.contextmanager
def phase_scope(metrics, tracer, name: str):
    """Combined per-phase scope: times into ``metrics`` (a
    :class:`tpu_swirld.metrics.Metrics`) and/or spans into ``tracer``,
    either of which may be None.  The all-None case is never constructed
    by callers (they branch first), but stays correct."""
    if tracer is not None and metrics is not None:
        with tracer.span(name), metrics.phase(name):
            yield
    elif tracer is not None:
        with tracer.span(name):
            yield
    elif metrics is not None:
        with metrics.phase(name):
            yield
    else:
        yield


# Audit seam: tpu_swirld.analysis.jit_audit installs a callback here to
# record every stage call's abstract signature (shape/dtype/weak_type per
# arg) without touching values.  None in production — one global read.
_stage_observer = None


def set_stage_observer(cb) -> None:
    """Install (or clear, with None) the stage-call observer: called as
    ``cb(name, fn, args, kw)`` before every observed stage dispatch."""
    global _stage_observer
    _stage_observer = cb


def stage_call(name: str, fn, *args, **kw):
    """Run a jitted stage under the ambient Obs (no-op pass-through when
    disabled): spans the call, blocks on the result so the span measures
    device completion, and classifies the call as ``compile`` vs
    ``execute`` by watching the jit cache grow.

    Enabling observability therefore synchronizes stage boundaries —
    that's the point (per-stage attribution); leave it disabled for
    maximum-overlap production runs.
    """
    return _stage_call(name, 1, fn, args, kw)


def stage_call_fused(name: str, fused_chunks: int, fn, *args, **kw):
    """:func:`stage_call` for a megadispatch covering ``fused_chunks``
    packed scan chunks (the fused rounds span): identical tracing and
    compile/execute classification, but the dispatch profiler is told
    the dispatch amortizes over ``fused_chunks`` chunks so the single
    inter-dispatch gap is attributed per chunk (gap / K) instead of
    making the gap distribution look artificially clean."""
    return _stage_call(name, max(1, int(fused_chunks)), fn, args, kw)


def _stage_call(name: str, fused_chunks: int, fn, args, kw):
    so = _stage_observer
    if so is not None:
        so(name, fn, args, kw)
    o = current()
    if o is None:
        return fn(*args, **kw)
    import jax

    c0 = _jit_cache_size(fn)
    t0 = time.perf_counter()
    with o.tracer.span(name) as sp:
        out = fn(*args, **kw)
        out = jax.block_until_ready(out)
        t1 = time.perf_counter()
        dt = t1 - t0
        kind = "execute"
        if c0 >= 0 and _jit_cache_size(fn) > c0:
            kind = "compile"
        sp.args["kind"] = kind   # inside the span: lands in the event
    reg = o.registry
    reg.counter("pipeline_stage_seconds", {"stage": name, "kind": kind}).inc(dt)
    reg.counter("pipeline_stage_calls", {"stage": name, "kind": kind}).inc()
    if o.profiler is not None and kind == "execute":
        # compiles are one-time cost, not steady-state dispatch overhead
        o.profiler.record_dispatch(
            name, t0, t1, args=args, fused_chunks=fused_chunks
        )
    return out


def to_host(x, copy: bool = False):
    """Pull a (device) array to host numpy, counting the D2H bytes into
    the ambient dispatch profiler — the driver's pull sites route
    through here so ``transfers_bytes.d2h`` reflects every round-trip.
    ``copy=True`` forces a mutable owned copy (``np.array`` semantics
    for mirrors mutated in place)."""
    import numpy as _np

    arr = _np.array(x) if copy else _np.asarray(x)
    o = current()
    if o is not None and o.profiler is not None:
        o.profiler.record_transfer("d2h", arr.nbytes)
    return arr


def _jit_cache_size(fn) -> int:
    try:
        return fn._cache_size()
    except Exception:
        return -1


def compile_counts(registry) -> dict:
    """Per-stage count of jit-cache-growing calls recorded by
    :func:`stage_call` (``kind == "compile"``) in ``registry``.

    The steady-state recompile regression test wraps a warm loop in a
    fresh Obs and asserts this comes back empty — i.e. the loop added
    zero new entries to any stage's jit cache.
    """
    out: dict = {}
    for m in registry.metrics():
        if m.name != "pipeline_stage_calls":
            continue
        labels = dict(m.labels)
        if labels.get("kind") == "compile":
            stage = labels.get("stage", "?")
            out[stage] = out.get(stage, 0) + int(m.value)
    return out
