"""Per-phase peak-memory high-water marks (host + device).

``bench.py`` (and anything else that wants attribution) wraps each phase
in :meth:`MemoryMonitor.phase`:

- **host** — ``tracemalloc``: the per-phase *peak* traced allocation
  (``reset_peak`` at phase entry, ``get_traced_memory()[1]`` at exit), so
  a transient spike inside a phase is caught even though it is freed
  before the phase ends.  Tracing costs ~1.3-2x on allocation-heavy host
  code; callers that publish timing headlines should disable it for the
  timed region (``BENCH_MEM=0``) or accept the overhead.
- **device** — the live-buffer census ``sum(a.nbytes for a in
  jax.live_arrays())``, sampled at phase exit and at every explicit
  :meth:`sample` call; the recorded value is the max sample.  This is a
  sampling bound, not an allocator high-water mark — call ``sample()``
  inside long phases (the streaming driver's per-pass stats do) to
  tighten it.

Results land in ``self.phases`` and, when an ambient Obs is enabled, in
``mem_host_peak_bytes{phase=...}`` / ``mem_device_peak_bytes{phase=...}``
gauges.  :meth:`flat` renders the ``phases``-JSON-ready dict bench
publishes.
"""

from __future__ import annotations

import contextlib
import tracemalloc
from typing import Dict


def device_live_bytes() -> int:
    """Total bytes of live device buffers (CPU backend: host RAM that XLA
    owns — still the quantity a real accelerator would have resident)."""
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:
            pass
    return total


class MemoryMonitor:
    """Collect per-phase host/device peaks (see module doc)."""

    def __init__(self, enable_host: bool = True):
        self.enable_host = enable_host
        self.phases: Dict[str, Dict[str, int]] = {}
        self._started_tracing = False
        if enable_host and not tracemalloc.is_tracing():
            tracemalloc.start()
            self._started_tracing = True

    @contextlib.contextmanager
    def phase(self, name: str):
        if self.enable_host and tracemalloc.is_tracing():
            tracemalloc.reset_peak()
        rec = self.phases.setdefault(
            name, {"host_peak_bytes": 0, "device_peak_bytes": 0}
        )
        try:
            yield self
        finally:
            if self.enable_host and tracemalloc.is_tracing():
                _cur, peak = tracemalloc.get_traced_memory()
                rec["host_peak_bytes"] = max(
                    rec["host_peak_bytes"], int(peak)
                )
            self._sample_device(rec)
            self._export(name, rec)

    def sample(self, name: str) -> None:
        """Extra device sample inside a long phase (tightens the bound)."""
        rec = self.phases.setdefault(
            name, {"host_peak_bytes": 0, "device_peak_bytes": 0}
        )
        self._sample_device(rec)

    def _sample_device(self, rec: Dict[str, int]) -> None:
        rec["device_peak_bytes"] = max(
            rec["device_peak_bytes"], device_live_bytes()
        )

    def _export(self, name: str, rec: Dict[str, int]) -> None:
        from tpu_swirld import obs

        o = obs.current()
        if o is None:
            return
        g = o.registry
        g.gauge("mem_host_peak_bytes", {"phase": name}).set(
            rec["host_peak_bytes"]
        )
        g.gauge("mem_device_peak_bytes", {"phase": name}).set(
            rec["device_peak_bytes"]
        )

    # ------------------------------------------------------------ report

    @property
    def peak_host_bytes(self) -> int:
        return max(
            (r["host_peak_bytes"] for r in self.phases.values()), default=0
        )

    @property
    def peak_device_bytes(self) -> int:
        return max(
            (r["device_peak_bytes"] for r in self.phases.values()), default=0
        )

    def flat(self) -> Dict[str, int]:
        """``{"mem_<phase>_host_peak_bytes": ..., ...}`` for a flat
        phases-JSON merge."""
        out: Dict[str, int] = {}
        for name, rec in self.phases.items():
            out[f"mem_{name}_host_peak_bytes"] = rec["host_peak_bytes"]
            out[f"mem_{name}_device_peak_bytes"] = rec["device_peak_bytes"]
        return out

    def close(self) -> None:
        if self._started_tracing and tracemalloc.is_tracing():
            tracemalloc.stop()
            self._started_tracing = False
