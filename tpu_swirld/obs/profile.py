"""Dispatch-level hot-path profiler over the ``obs.stage_call`` seam.

ROADMAP item 4 attributes the streaming-vs-batch gap to "per-chunk
dispatch and host-device round-trips" — this module measures instead of
guesses.  Attach a :class:`DispatchProfiler` to the ambient
:class:`~tpu_swirld.obs.Obs` and every ``stage_call`` dispatch feeds it:

- *per-dispatch device/wall time* — ``stage_call`` already blocks on the
  result, so span duration is dispatch + device completion;
- *args-ready→dispatch latency* — the host-side gap between one
  dispatch finishing and the next starting (Python driver overhead,
  host work, transfer stalls) — the part a fused batch pipeline never
  pays;
- *host↔device transfer bytes* — numpy (host) arguments entering a
  stage count as H2D; driver pulls through :func:`tpu_swirld.obs.
  to_host` count as D2H.

Chunk accounting: drivers bracket each ingest with :meth:`begin_chunk`
/ :meth:`end_chunk`; the difference between a chunk's wall time and the
sum of its stage times is ``dispatch_overhead_s`` — exactly the
non-device cost the streaming engine pays per chunk.  :meth:`summary`
emits the per-chunk breakdown plus a ranked top-k stage cost list;
``bench.py --stream`` publishes it (and ``scripts/bench_compare.py``
gates ``stream.dispatch_overhead_s`` lower-is-better).

Clock discipline (SW003): this module reads wall time at exactly ONE
callsite (:func:`_wall`, behind a justified suppression); tests may
inject a fake clock for determinism.  ``record_dispatch`` timestamps
arrive from the caller and are merely subtracted.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import numpy as np


def _wall() -> float:
    """The profiler's single wall read (monotonic seconds)."""
    return time.perf_counter()   # swirld-lint: disable=SW003 -- the dispatch profiler's one timing callsite: measuring real host/device wall cost is its entire purpose; it observes, never steers, consensus

#: ranked stage list length in summaries
DEFAULT_TOP_K = 3


def _host_arg_bytes(args) -> int:
    """Bytes of *host* (numpy) array arguments — the H2D upload a
    dispatch implies.  Device-resident arrays (jax.Array) don't count."""
    total = 0
    for a in args:
        if isinstance(a, np.ndarray):
            total += a.nbytes
    return total


class DispatchProfiler:
    """Accumulates per-dispatch and per-chunk cost for one run.

    Args:
      top_k: length of the ranked stage list in :meth:`summary`.
      clock: zero-arg monotonic-seconds callable for chunk walls
        (injectable for tests); defaults to the module's single wall
        read.  Must share a timebase with the timestamps handed to
        :meth:`record_dispatch` (``stage_call`` uses ``perf_counter``).
    """

    def __init__(self, top_k: int = DEFAULT_TOP_K,
                 clock: Optional[Callable[[], float]] = None):
        self.top_k = int(top_k)
        self._clock = clock if clock is not None else _wall
        self._stage_s: Dict[str, float] = {}
        self._stage_calls: Dict[str, int] = {}
        self.dispatches = 0
        self.fused_dispatches = 0     # dispatches covering >1 scan chunk
        self.fused_chunks_total = 0   # scan chunks covered by those
        self.stage_s_total = 0.0
        self.gap_s_total = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.chunks: List[Dict] = []
        # per-chunk *effective* gap samples: a K-chunk megadispatch's one
        # inter-dispatch gap amortizes over K chunk boundaries, so it
        # contributes K samples of gap/K — without this, fusing makes the
        # gap distribution look artificially clean (one giant dispatch
        # instead of K per-chunk ones)
        self._gap_samples: List[float] = []
        self._last_end: Optional[float] = None
        self._chunk: Optional[Dict] = None

    # --------------------------------------------------------- chunk marks

    def begin_chunk(self, label: Optional[str] = None) -> None:
        """Open a chunk scope (one streaming/incremental ingest)."""
        self._chunk = {
            "label": label if label is not None else len(self.chunks),
            "t0": self._clock(),
            "stage_s": 0.0, "dispatches": 0, "gap_s": 0.0,
            "fused_chunks": 0, "h2d_bytes": 0, "d2h_bytes": 0,
        }
        # gaps never span a chunk boundary: the wait between chunks is
        # the caller's (data generation), not dispatch overhead
        self._last_end = None

    def end_chunk(self, n_events: int = 0) -> Optional[Dict]:
        """Close the open chunk; returns its breakdown row."""
        c = self._chunk
        if c is None:
            return None
        self._chunk = None
        wall = self._clock() - c.pop("t0")
        c["wall_s"] = round(wall, 6)
        c["overhead_s"] = round(max(0.0, wall - c["stage_s"]), 6)
        c["stage_s"] = round(c["stage_s"], 6)
        c["gap_s"] = round(c["gap_s"], 6)
        c["n_events"] = int(n_events)
        self.chunks.append(c)
        self._last_end = None
        return c

    # ----------------------------------------------------------- recording

    def record_dispatch(self, stage: str, t0: float, t1: float,
                        args=(), fused_chunks: int = 1) -> None:
        """One ``stage_call`` completed: ``t0``/``t1`` are its start/end
        on the caller's monotonic clock; ``args`` are the stage's
        positional arguments (scanned for host arrays — H2D bytes).
        ``fused_chunks > 1`` marks a megadispatch whose single
        inter-dispatch gap amortizes over that many scan chunks: the gap
        contributes ``fused_chunks`` effective samples of ``gap / K`` so
        per-chunk gap statistics stay comparable across fusion levels."""
        dt = max(0.0, t1 - t0)
        k = max(1, int(fused_chunks))
        self.dispatches += 1
        if k > 1:
            self.fused_dispatches += 1
            self.fused_chunks_total += k
        self.stage_s_total += dt
        self._stage_s[stage] = self._stage_s.get(stage, 0.0) + dt
        self._stage_calls[stage] = self._stage_calls.get(stage, 0) + 1
        gap = 0.0
        if self._last_end is not None:
            gap = max(0.0, t0 - self._last_end)
            self.gap_s_total += gap
            self._gap_samples.extend([gap / k] * k)
        self._last_end = t1
        h2d = _host_arg_bytes(args)
        self.h2d_bytes += h2d
        c = self._chunk
        if c is not None:
            c["stage_s"] += dt
            c["dispatches"] += 1
            c["gap_s"] += gap
            c["h2d_bytes"] += h2d
            if k > 1:
                c["fused_chunks"] += k

    def record_transfer(self, direction: str, nbytes: int) -> None:
        """An explicit host↔device copy outside dispatch args
        (``direction`` is ``"d2h"`` or ``"h2d"``)."""
        nbytes = int(nbytes)
        if direction == "d2h":
            self.d2h_bytes += nbytes
            if self._chunk is not None:
                self._chunk["d2h_bytes"] += nbytes
        else:
            self.h2d_bytes += nbytes
            if self._chunk is not None:
                self._chunk["h2d_bytes"] += nbytes

    # ------------------------------------------------------------- queries

    def top_stages(self, k: Optional[int] = None) -> List[Dict]:
        """Stages ranked by total seconds (descending; name breaks
        ties deterministically)."""
        k = self.top_k if k is None else int(k)
        ranked = sorted(
            self._stage_s, key=lambda s: (-self._stage_s[s], s),
        )
        return [
            {
                "stage": s,
                "seconds": round(self._stage_s[s], 6),
                "calls": self._stage_calls.get(s, 0),
            }
            for s in ranked[:k]
        ]

    def gap_quantiles(self) -> Dict:
        """Per-chunk *effective* inter-dispatch gap distribution (p50 /
        p99 / max, seconds).  Fused dispatches contribute K samples of
        ``gap / K`` each, so the quantiles compare across fusion levels."""
        g = self._gap_samples
        if not g:
            return {"p50": 0.0, "p99": 0.0, "max": 0.0}
        s = sorted(g)
        q = lambda p: s[min(len(s) - 1, int(p * (len(s) - 1) + 0.5))]  # noqa: E731
        return {
            "p50": round(q(0.50), 6),
            "p99": round(q(0.99), 6),
            "max": round(s[-1], 6),
        }

    def summary(self) -> Dict:
        """The ``bench.py --stream`` dispatch-breakdown object."""
        wall = sum(c["wall_s"] for c in self.chunks)
        overhead = sum(c["overhead_s"] for c in self.chunks)
        return {
            "chunks": len(self.chunks),
            "dispatches": self.dispatches,
            "fused_dispatches": self.fused_dispatches,
            "fused_chunks": self.fused_chunks_total,
            "wall_s": round(wall, 6),
            "stage_s": round(self.stage_s_total, 6),
            "dispatch_overhead_s": round(overhead, 6),
            "gap_s": round(self.gap_s_total, 6),
            "gap_per_chunk": self.gap_quantiles(),
            "transfers_bytes": {
                "h2d": self.h2d_bytes, "d2h": self.d2h_bytes,
            },
            "top_stages": self.top_stages(),
            "per_chunk": list(self.chunks),
        }
