"""``python -m tpu_swirld.obs report`` — render a trace file as tables.

Consumes the JSONL (or Chrome-wrapped) trace written by
:meth:`tpu_swirld.obs.Obs.save` / :meth:`tpu_swirld.obs.tracer.Tracer.save`
and prints:

1. a *phase breakdown* — per span name: calls, total/mean/max milliseconds,
   and percent of the total traced depth-0 time, nested names indented by
   their recorded depth;
2. the *protocol gauges* — every counter sample (``ph: "C"``) embedded in
   the trace, i.e. the registry snapshot at save time — split into
   protocol / store / cluster-traffic (tx ingestion, WAL recovery) /
   finality (rounds-to-decision, time-to-finality, decided watermarks) /
   flight-recorder (trigger + dump counters) / membership (epoch, active
   members, total stake) / resilience sections.

Two additional modes (PR 16):

- pointing the CLI at an old ``BENCH_*.json`` *bench artifact* (a plain
  JSON result doc, not a trace) renders every section as ``n/a`` with
  the artifact's own metric line, and exits 0 — it must never traceback
  on the repo's own historical outputs;
- ``--cluster-dir <workdir>`` renders the *fleet view* from a cluster
  run's on-disk leavings (``node-*.report.json``, ``metrics.json``,
  ``merged.trace.json``): per-node fleet table, finality, shed /
  backpressure, WAL recovery, circuit-breaker sections, and the
  supervisor metrics rollup.  Missing keys render ``n/a`` — old report
  versions stay readable.

Pure stdlib + pure functions over the event list, so the CLI can be smoke-
tested cheaply (``tests/test_obs.py``) and never rots silently.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tpu_swirld.obs.tracer import load_trace


def aggregate_spans(events: List[Dict]) -> List[Dict]:
    """Group ``ph == "X"`` events by (depth, name) preserving first-seen
    order within a depth; returns rows with calls/total/mean/max ms."""
    rows: Dict[Tuple[int, str], Dict] = {}
    order: List[Tuple[int, str]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        depth = int(e.get("args", {}).get("depth", 0))
        key = (depth, e["name"])
        row = rows.get(key)
        if row is None:
            row = {
                "name": e["name"], "depth": depth, "calls": 0,
                "total_ms": 0.0, "max_ms": 0.0,
            }
            rows[key] = row
            order.append(key)
        dur_ms = float(e.get("dur", 0.0)) / 1000.0
        row["calls"] += 1
        row["total_ms"] += dur_ms
        row["max_ms"] = max(row["max_ms"], dur_ms)
    # sort: depth-0 rows by total desc, children right after their depth
    # cannot be reconstructed without parent links — keep stable order
    # within depth, depth-0 first-seen order preserved.
    out = [rows[k] for k in order]
    for row in out:
        row["mean_ms"] = row["total_ms"] / row["calls"]
    return out


def gauge_rows(events: List[Dict]) -> List[Dict]:
    """Counter samples (``ph == "C"``): the registry snapshot lines."""
    rows = []
    for e in events:
        if e.get("ph") != "C":
            continue
        args = dict(e.get("args", {}))
        value = args.pop("value", None)
        rows.append({"name": e["name"], "value": value, "labels": args})
    return rows


# The chaos/resilience failure surface gets its own report section so a
# fault-injected run's health reads at a glance: transport fault counters
# (FaultyTransport), retry/backoff/circuit-breaker counters (Node), the
# adversary-detection counters (equivocation / withholding / 3f budget),
# and the incremental driver's storm-guard decision gauges.
_RESILIENCE_PREFIXES = (
    "transport_",
    "adversary_",
    "node_equivocations",
    "node_withholding",
    "node_budget_exhausted",
    "node_sync_branches_capped",
    "gossip_transport_errors",
    "gossip_retries",
    "gossip_backoff",
    "gossip_deadline",
    "gossip_circuit",
    "gossip_bad_",
    "gossip_sync_branches_capped",
    "incremental_storm",
    "incremental_consecutive_rebases",
    "consensus_late_witnesses",
    "consensus_horizon_violations",
    "pipeline_overflow_retries",
    "node_bad_",
    "node_retries",
    "node_backoff",
    "node_quarantined",
    "node_circuit",
    "node_late_witnesses",
    "node_horizon_violations",
)


def is_resilience_row(g: Dict) -> bool:
    return any(g["name"].startswith(p) for p in _RESILIENCE_PREFIXES)


# The slab store / streaming-overlap surface gets its own section: tile
# budget pressure, archive spill/fetch traffic, the background packing
# queue, and the compute-vs-wall overlap ratio of the streaming driver.
_STORE_PREFIXES = (
    "store_",
    "stream_overlap",
)


def is_store_row(g: Dict) -> bool:
    return any(g["name"].startswith(p) for p in _STORE_PREFIXES)


# The real-cluster traffic surface: tx ingestion/backpressure counters
# (TxPool), durable-WAL recovery counters, and the socket transport's
# byte/timeout counters already covered by transport_ above.
_NET_PREFIXES = (
    "tx_",
    "wal_",
    "net_",
)


def is_net_row(g: Dict) -> bool:
    return any(g["name"].startswith(p) for p in _NET_PREFIXES)


# The dynamic-membership surface (membership/): the epoch governing the
# round frontier, the live member count, and the epoch's total stake —
# published by metrics.node_gauges for static and dynamic nodes alike
# (static nodes report the trivial single-epoch values).
_MEMBERSHIP_PREFIXES = (
    "node_membership_",
    "node_members_active",
    "node_stake_total",
    "membership_",
)


def is_membership_row(g: Dict) -> bool:
    return any(g["name"].startswith(p) for p in _MEMBERSHIP_PREFIXES)


# The finality lifecycle surface: rounds-to-decision / time-to-finality
# histogram rows (per engine, with the streaming phase dimension),
# gossip-propagation latency, and per-node decided-watermark gauges.
_FINALITY_PREFIXES = ("finality_",)

# The black-box flight recorder: trigger counters by reason and the
# dump/record totals stamped at dump time.
_FLIGHTREC_PREFIXES = ("flightrec_",)


def is_finality_row(g: Dict) -> bool:
    return any(g["name"].startswith(p) for p in _FINALITY_PREFIXES)


def is_flightrec_row(g: Dict) -> bool:
    return any(g["name"].startswith(p) for p in _FLIGHTREC_PREFIXES)


def render_report(events: List[Dict]) -> str:
    spans = aggregate_spans(events)
    gauges = gauge_rows(events)
    lines: List[str] = []
    total_top = sum(r["total_ms"] for r in spans if r["depth"] == 0)
    lines.append("== phase breakdown ==")
    if spans:
        lines.append(
            f"{'span':<44} {'calls':>6} {'total_ms':>10} {'mean_ms':>9} "
            f"{'max_ms':>9} {'%top':>6}"
        )
        for r in spans:
            name = "  " * r["depth"] + r["name"]
            pct = (
                f"{100.0 * r['total_ms'] / total_top:5.1f}%"
                if r["depth"] == 0 and total_top > 0
                else ""
            )
            lines.append(
                f"{name:<44} {r['calls']:>6} {r['total_ms']:>10.3f} "
                f"{r['mean_ms']:>9.3f} {r['max_ms']:>9.3f} {pct:>6}"
            )
    else:
        lines.append("(no spans in trace)")
    resilience = [g for g in gauges if is_resilience_row(g)]
    store = [
        g for g in gauges
        if is_store_row(g) and not is_resilience_row(g)
    ]
    net = [
        g for g in gauges
        if is_net_row(g)
        and not is_resilience_row(g) and not is_store_row(g)
    ]
    finality = [
        g for g in gauges
        if is_finality_row(g)
        and not is_resilience_row(g) and not is_store_row(g)
        and not is_net_row(g)
    ]
    flightrec = [
        g for g in gauges
        if is_flightrec_row(g)
        and not is_resilience_row(g) and not is_store_row(g)
        and not is_net_row(g)
    ]
    membership = [
        g for g in gauges
        if is_membership_row(g)
        and not is_resilience_row(g) and not is_store_row(g)
        and not is_net_row(g)
        and not is_finality_row(g) and not is_flightrec_row(g)
    ]
    protocol = [
        g for g in gauges
        if not is_resilience_row(g) and not is_store_row(g)
        and not is_net_row(g)
        and not is_finality_row(g) and not is_flightrec_row(g)
        and not is_membership_row(g)
    ]
    lines.append("")
    lines.append("== protocol gauges ==")
    if protocol:
        width = max(len(_gauge_name(g)) for g in protocol)
        for g in protocol:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    else:
        lines.append("(no counter samples in trace)")
    if store:
        lines.append("")
        lines.append("== store (tile budget / archive / spill overlap) ==")
        width = max(len(_gauge_name(g)) for g in store)
        for g in store:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    if net:
        lines.append("")
        lines.append("== cluster traffic (tx ingestion / WAL recovery) ==")
        width = max(len(_gauge_name(g)) for g in net)
        for g in net:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    if finality:
        lines.append("")
        lines.append("== finality (rounds-to-decision / time-to-finality"
                     " / watermarks) ==")
        width = max(len(_gauge_name(g)) for g in finality)
        for g in finality:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    if flightrec:
        lines.append("")
        lines.append("== flight recorder (triggers / dumps) ==")
        width = max(len(_gauge_name(g)) for g in flightrec)
        for g in flightrec:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    if membership:
        lines.append("")
        lines.append("== membership (epoch / active members / stake) ==")
        width = max(len(_gauge_name(g)) for g in membership)
        for g in membership:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    if resilience:
        lines.append("")
        lines.append("== resilience (faults / retries / quarantine) ==")
        width = max(len(_gauge_name(g)) for g in resilience)
        for g in resilience:
            lines.append(f"{_gauge_name(g):<{width}}  {g['value']}")
    return "\n".join(lines)


def _gauge_name(g: Dict) -> str:
    if g["labels"]:
        lab = ",".join(f"{k}={v}" for k, v in sorted(g["labels"].items()))
        return f"{g['name']}{{{lab}}}"
    return g["name"]


# ------------------------------------------------------- artifact detection

def classify_artifact(path: str) -> Tuple[str, object]:
    """``("trace", events)`` for a trace file, ``("bench", obj)`` for a
    bench result artifact (any plain JSON document that isn't trace
    events — the old ``BENCH_*.json`` files the CLI must not crash on)."""
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{") and '"traceEvents"' not in stripped[:200]:
        try:
            obj = json.loads(stripped)
        except json.JSONDecodeError:
            obj = None
        # a lone single-line trace event is still a trace ("ph" marks it)
        if isinstance(obj, dict) and "ph" not in obj:
            return "bench", obj
    return "trace", load_trace(path)


def render_bench_stub(path: str, obj: Dict) -> str:
    """The graceful non-trace rendering: every trace section present but
    ``n/a``, plus whatever headline metric the artifact itself carries."""
    lines = [
        f"(not a trace: bench artifact {os.path.basename(path)})",
        "",
        "== phase breakdown ==",
        "n/a",
        "",
        "== protocol gauges ==",
        "n/a",
    ]
    parsed = obj.get("parsed")
    if isinstance(parsed, dict):
        parsed = [parsed]
    if isinstance(parsed, list):
        lines.append("")
        lines.append("== bench artifact metrics ==")
        for row in parsed:
            if not isinstance(row, dict):
                continue
            metric = row.get("metric", "n/a")
            value = row.get("value", "n/a")
            unit = row.get("unit", "")
            lines.append(f"{metric}: {value} {unit}".rstrip())
    return "\n".join(lines)


# ------------------------------------------------------------ cluster view

def _fmt(v, nd: int = 3) -> str:
    if v is None:
        return "n/a"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _counter_section(lines: List[str], title: str, reports: List[Dict],
                     names: Tuple[str, ...]) -> None:
    """One fleet counter table: per-counter per-node values + total,
    ``n/a`` where a node's report predates the counter."""
    lines.append("")
    lines.append(f"== {title} ==")
    for name in names:
        vals = [
            (r.get("counters") or {}).get(name) for r in reports
        ]
        known = [v for v in vals if v is not None]
        total = sum(known) if known else None
        per_node = " ".join(
            f"{r.get('node', '?')}={_fmt(v, 0)}"
            for r, v in zip(reports, vals)
        )
        lines.append(f"{name:<28} total={_fmt(total, 0):<8} {per_node}")


def render_cluster_report(dirpath: str) -> str:
    """The fleet view over a cluster workdir's on-disk artifacts."""
    reports: List[Dict] = []
    for name in sorted(os.listdir(dirpath)):
        if name.startswith("node-") and name.endswith(".report.json"):
            try:
                with open(os.path.join(dirpath, name)) as f:
                    reports.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                continue
    lines: List[str] = [f"== cluster fleet ({len(reports)} node reports) =="]
    if not reports:
        lines.append("n/a (no node-*.report.json found)")
    else:
        lines.append(
            f"{'node':<6} {'events':>7} {'decided':>8} {'decided_tx':>10} "
            f"{'unclean':>8} {'trace_ev':>9} {'dropped':>8}"
        )
        for r in reports:
            lines.append(
                f"{_fmt(r.get('node')):<6} {_fmt(r.get('events')):>7} "
                f"{_fmt(len(r['decided']) if 'decided' in r else None):>8} "
                f"{_fmt(r.get('decided_tx')):>10} "
                f"{_fmt(r.get('unclean_start')):>8} "
                f"{_fmt(r.get('trace_events')):>9} "
                f"{_fmt(r.get('trace_dropped')):>8}"
            )
        lines.append("")
        lines.append("== finality (per node) ==")
        for r in reports:
            fin = r.get("finality") or {}
            lines.append(
                f"{_fmt(r.get('node')):<6} decided={_fmt(fin.get('decided'))}"
                f" rtd_p50={_fmt(fin.get('rtd_p50'))}"
                f" rtd_p99={_fmt(fin.get('rtd_p99'))}"
                f" ttf_p50={_fmt(fin.get('ttf_p50'))}"
                f" ttf_p99={_fmt(fin.get('ttf_p99'))}"
                f" undecided={_fmt(fin.get('undecided'))}"
            )
        lines.append("")
        lines.append("== membership (per node) ==")
        for r in reports:
            lines.append(
                f"{_fmt(r.get('node')):<6}"
                f" epoch={_fmt(r.get('membership_epoch'))}"
                f" epochs_decided={_fmt(r.get('membership_epochs'))}"
                f" members_active={_fmt(r.get('members_active'))}"
                f" stake_total={_fmt(r.get('stake_total'))}"
            )
        _counter_section(
            lines, "shed / backpressure", reports,
            ("tx_submitted", "tx_accepted", "tx_duplicate",
             "tx_shed_pool", "tx_shed_window", "tx_shed_oversize"),
        )
        _counter_section(
            lines, "WAL recovery", reports,
            ("wal_torn_tail_recovered",),
        )
        _counter_section(
            lines, "circuit breaker / retries", reports,
            ("node_circuit_opens", "node_retries",
             "node_bad_replies", "node_bad_requests"),
        )
    metrics_path = os.path.join(dirpath, "metrics.json")
    lines.append("")
    lines.append("== supervisor metrics rollup ==")
    if os.path.exists(metrics_path):
        try:
            with open(metrics_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = {}
        lines.append(
            f"polls={_fmt(doc.get('polls'))} "
            f"nodes={_fmt(len(doc.get('nodes', {})) or None, 0)}"
        )
        rollup = doc.get("rollup") or {}
        for key in sorted(rollup):
            lines.append(f"{key:<44} {_fmt(rollup[key])}")
        if not rollup:
            lines.append("n/a (empty rollup)")
    else:
        lines.append("n/a (no metrics.json — supervisor polling off?)")
    merged = os.path.join(dirpath, "merged.trace.json")
    lines.append("")
    lines.append("== merged cross-process trace ==")
    if os.path.exists(merged):
        lines.append(merged)
        lines.append(
            "(open in Perfetto; re-summarize with "
            "python -m tpu_swirld.obs.cluster_trace "
            f"{dirpath})"
        )
    else:
        lines.append("n/a (no merged.trace.json — run "
                     f"python -m tpu_swirld.obs.cluster_trace {dirpath})")
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_swirld.obs",
        description="tpu_swirld observability tooling",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="render a trace file as tables")
    rep.add_argument("trace", nargs="?", default=None,
                     help="JSONL (or Chrome-wrapped) trace file")
    rep.add_argument("--cluster-dir", default=None,
                     help="render the fleet view of a cluster workdir "
                          "instead of a single trace")
    args = ap.parse_args(argv)
    if args.cmd == "report":
        if args.cluster_dir is not None:
            print(render_cluster_report(args.cluster_dir))
            return 0
        if args.trace is None:
            ap.error("a trace file (or --cluster-dir) is required")
        kind, payload = classify_artifact(args.trace)
        if kind == "bench":
            print(render_bench_stub(args.trace, payload))
            return 0
        print(render_report(payload))
        return 0
    return 2
