"""Nested-span tracer with JSONL export (Chrome trace-event compatible).

Each finished span becomes one JSON object — one per line in the exported
file — using the Chrome trace-event "complete" form (``ph: "X"``)::

    {"name": "oracle.divide_rounds", "ph": "X", "pid": 0, "tid": 0,
     "ts": 12.5, "dur": 834.2, "args": {"depth": 1, "wall_s": 1754...}}

``ts``/``dur`` are microseconds on the tracer's *monotonic* clock
(``time.perf_counter`` relative to the tracer epoch — immune to wall-clock
steps); the wall-clock start time rides in ``args.wall_s`` so traces can be
correlated with external logs.  ``args.depth`` records the nesting level at
emit time (Chrome infers nesting from ts/dur overlap; the report CLI uses
the explicit depth).  A file of these lines loads directly into
``chrome://tracing`` / Perfetto after wrapping in ``[...]`` —
:func:`save_chrome` writes that wrapped form, :meth:`Tracer.save` the JSONL.

Disabled mode: :data:`NULL_TRACER` answers every ``span()`` call with one
shared no-op context manager — no allocation, no timestamps, nothing
recorded — so instrumentation can unconditionally ``with tracer.span(...)``
once it holds *a* tracer.  Call sites that may hold ``None`` instead should
branch (``if tracer is not None``), which is the pattern the hot paths use.

Trace identity (cluster mode): every enabled span gets a process-unique
``span_id`` (upper bits derived from the tracer ``pid`` so ids from
different node processes never collide in a merged timeline).  A span may
additionally belong to a *trace* — an 8-byte id carried across process
boundaries inside the 16-byte wire context built by :func:`pack_context`
(trace id + parent span id).  :meth:`Tracer.span_under` opens a span whose
parent lives in another process; :meth:`Tracer.active_context` exports the
innermost traced span as wire bytes for the transport to stamp onto
outgoing frames.  ``obs/cluster_trace.py`` reassembles the shards.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Dict, List, Optional, Tuple

#: wire size of a packed trace context (8-byte trace id + u64 span id)
TRACE_CTX_LEN = 16

_CTX = struct.Struct("<8sQ")


def pack_context(trace_id: bytes, span_id: int) -> bytes:
    """Pack an (8-byte trace id, span id) pair into wire bytes."""
    if len(trace_id) != 8:
        raise ValueError(f"trace id must be 8 bytes, got {len(trace_id)}")
    return _CTX.pack(trace_id, span_id)


def unpack_context(ctx: bytes) -> Tuple[bytes, int]:
    """Inverse of :func:`pack_context`; raises ``ValueError`` on bad size."""
    if len(ctx) != TRACE_CTX_LEN:
        raise ValueError(
            f"trace context must be {TRACE_CTX_LEN} bytes, got {len(ctx)}"
        )
    return _CTX.unpack(ctx)


class _SpanHandle:
    """Mutable args bag yielded by ``Tracer.span`` — mutate ``args`` inside
    the ``with`` block to attach data to the emitted event."""

    __slots__ = (
        "name", "args", "_t0_mono", "_wall_s",
        "span_id", "trace_id", "parent_id",
    )

    def __init__(self, name: str, args: Dict, t0_mono: float, wall_s: float,
                 trace_id: Optional[bytes] = None,
                 parent_id: Optional[int] = None):
        self.name = name
        self.args = args
        self._t0_mono = t0_mono
        self._wall_s = wall_s
        self.span_id = 0
        self.trace_id = trace_id
        self.parent_id = parent_id


class _NullSpan:
    """Shared no-op context manager (also serves as a null span handle)."""

    __slots__ = ()

    @property
    def args(self) -> Dict:
        # a fresh throwaway dict per access: annotation writes vanish
        # instead of accumulating in (or leaking through) shared state
        return {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every call returns the one shared no-op span."""

    __slots__ = ()
    enabled = False
    events: List[Dict] = []

    def span(self, name: str, **args):
        return _NULL_SPAN

    def span_under(self, name: str, ctx=None, **args):
        return _NULL_SPAN

    def instant(self, name: str, **args) -> None:
        pass

    def active_context(self):
        return None

    def active_trace_hex(self):
        return None

    def save(self, path: str) -> None:
        raise RuntimeError("NullTracer records nothing; nothing to save")


NULL_TRACER = NullTracer()


class _SpanCtx:
    """The live span context manager (one allocation per enabled span)."""

    __slots__ = ("_tracer", "_handle")

    def __init__(self, tracer: "Tracer", handle: _SpanHandle):
        self._tracer = tracer
        self._handle = handle

    def __enter__(self) -> _SpanHandle:
        h = self._handle
        t = self._tracer
        h.span_id = t._new_span_id()
        if t._stack:
            top = t._stack[-1]
            # inherit trace identity / local parent from the enclosing span
            # unless a remote parent context was given explicitly
            if h.trace_id is None:
                h.trace_id = top.trace_id
            if h.parent_id is None:
                h.parent_id = top.span_id
        h._wall_s = time.time()
        h._t0_mono = time.perf_counter()   # re-stamped at entry, not creation
        t._stack.append(h)
        return h

    def __exit__(self, *exc):
        t = self._tracer
        h = t._stack.pop()
        end = time.perf_counter()
        args = dict(
            h.args, depth=len(t._stack), wall_s=round(h._wall_s, 6),
            span_id=h.span_id,
        )
        if h.parent_id is not None:
            args["parent_span_id"] = h.parent_id
        if h.trace_id is not None:
            args["trace"] = h.trace_id.hex()
        t._append(
            {
                "name": h.name,
                "ph": "X",
                "pid": t.pid,
                "tid": t.tid,
                "ts": round((h._t0_mono - t._epoch_mono) * 1e6, 3),
                "dur": round((end - h._t0_mono) * 1e6, 3),
                "args": args,
            }
        )
        return False


class Tracer:
    """Collects spans + instant events; exports JSONL / Chrome traces."""

    enabled = True

    def __init__(self, pid: int = 0, tid: int = 0,
                 max_events: Optional[int] = None):
        self.pid = pid
        self.tid = tid
        self.events: List[Dict] = []
        self.dropped = 0
        self.max_events = max_events
        self._stack: List[_SpanHandle] = []
        self._epoch_mono = time.perf_counter()
        self._epoch_wall = time.time()
        self._span_seq = 0

    # ------------------------------------------------------------ recording

    def _new_span_id(self) -> int:
        """Process-unique span id: pid in the upper bits, a sequence number
        below, so shards from different node processes never collide."""
        self._span_seq += 1
        return (((self.pid & 0xFFFF) + 1) << 32) | self._span_seq

    def _append(self, event: Dict) -> None:
        """Record one event, honoring the optional ``max_events`` cap
        (long soaks keep bounded memory; drops are counted, not silent)."""
        if self.max_events is not None and len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(event)

    def span(self, name: str, **args) -> _SpanCtx:
        """Context manager timing a nested span.  Yields a handle whose
        ``.args`` dict can be mutated to annotate the emitted event."""
        return _SpanCtx(
            self, _SpanHandle(name, args, time.perf_counter(), time.time())
        )

    def span_under(self, name: str, ctx: Optional[bytes] = None,
                   **args) -> _SpanCtx:
        """Like :meth:`span`, but parented under a *wire* trace context
        (16 bytes from :func:`pack_context`, e.g. received in a frame
        header).  ``None``/empty ctx degrades to a plain :meth:`span`; a
        zero parent span id means "root of the trace"."""
        if not ctx:
            return self.span(name, **args)
        trace_id, parent = unpack_context(ctx)
        return _SpanCtx(
            self,
            _SpanHandle(
                name, args, time.perf_counter(), time.time(),
                trace_id=trace_id, parent_id=parent if parent else None,
            ),
        )

    def active_context(self) -> Optional[bytes]:
        """Wire context of the innermost *traced* open span (16 bytes), or
        ``None`` when no open span carries a trace id.  This is what the
        socket transport stamps onto outgoing frames."""
        for h in reversed(self._stack):
            if h.trace_id is not None:
                return pack_context(h.trace_id, h.span_id)
        return None

    def active_trace_hex(self) -> Optional[str]:
        """Hex trace id of the innermost traced open span, or ``None``
        (flight-recorder dumps embed this for cross-shard correlation)."""
        for h in reversed(self._stack):
            if h.trace_id is not None:
                return h.trace_id.hex()
        return None

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (Chrome ``ph: "i"``)."""
        args = dict(args, depth=len(self._stack))
        args.setdefault("wall_s", round(time.time(), 6))
        trace = self.active_trace_hex()
        if trace is not None:
            args.setdefault("trace", trace)
        self._append(
            {
                "name": name,
                "ph": "i",
                "pid": self.pid,
                "tid": self.tid,
                "ts": round((time.perf_counter() - self._epoch_mono) * 1e6, 3),
                "s": "t",
                "args": args,
            }
        )

    def counter_event(
        self, name: str, value: float, labels: Optional[Dict] = None
    ) -> Dict:
        """Build (without recording) a Chrome counter sample (``ph: "C"``)
        — ``Obs.save`` uses these to embed the registry snapshot in the
        trace file without mutating the tracer."""
        args: Dict = {}
        for k, v in (labels or {}).items():
            # "value" is reserved for the sample itself; don't conflate
            args["label_value" if k == "value" else k] = v
        args["value"] = value
        return {
            "name": name,
            "ph": "C",
            "pid": self.pid,
            "ts": round((time.perf_counter() - self._epoch_mono) * 1e6, 3),
            "args": args,
        }

    def counter(
        self, name: str, value: float, labels: Optional[Dict] = None
    ) -> None:
        """Record a Chrome counter sample."""
        self._append(self.counter_event(name, value, labels))

    @property
    def depth(self) -> int:
        return len(self._stack)

    # -------------------------------------------------------------- queries

    def spans(self) -> List[Dict]:
        return [e for e in self.events if e.get("ph") == "X"]

    def phase_seconds(self, depth: int = 0) -> Dict[str, float]:
        """Total seconds per span name at one nesting depth — the
        phase-breakdown aggregation bench.py publishes."""
        out: Dict[str, float] = {}
        for e in self.spans():
            if e["args"].get("depth") == depth:
                out[e["name"]] = out.get(e["name"], 0.0) + e["dur"] / 1e6
        return out

    # ------------------------------------------------------------ export/io

    def save(self, path: str) -> None:
        """JSONL: one Chrome trace event per line."""
        with open(path, "w") as f:
            for e in self.events:
                f.write(json.dumps(e) + "\n")

    def save_chrome(self, path: str) -> None:
        """The ``{"traceEvents": [...]}`` wrapped form chrome://tracing and
        Perfetto open directly."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events}, f)


def load_trace(path: str) -> List[Dict]:
    """Read a trace written by :meth:`Tracer.save` (JSONL) or
    :meth:`Tracer.save_chrome` (wrapped JSON) back into an event list."""
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{") and '"traceEvents"' in stripped[:200]:
        return json.loads(stripped)["traceEvents"]
    if stripped.startswith("["):
        return json.loads(stripped)
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    return events
