"""Nested-span tracer with JSONL export (Chrome trace-event compatible).

Each finished span becomes one JSON object — one per line in the exported
file — using the Chrome trace-event "complete" form (``ph: "X"``)::

    {"name": "oracle.divide_rounds", "ph": "X", "pid": 0, "tid": 0,
     "ts": 12.5, "dur": 834.2, "args": {"depth": 1, "wall_s": 1754...}}

``ts``/``dur`` are microseconds on the tracer's *monotonic* clock
(``time.perf_counter`` relative to the tracer epoch — immune to wall-clock
steps); the wall-clock start time rides in ``args.wall_s`` so traces can be
correlated with external logs.  ``args.depth`` records the nesting level at
emit time (Chrome infers nesting from ts/dur overlap; the report CLI uses
the explicit depth).  A file of these lines loads directly into
``chrome://tracing`` / Perfetto after wrapping in ``[...]`` —
:func:`save_chrome` writes that wrapped form, :meth:`Tracer.save` the JSONL.

Disabled mode: :data:`NULL_TRACER` answers every ``span()`` call with one
shared no-op context manager — no allocation, no timestamps, nothing
recorded — so instrumentation can unconditionally ``with tracer.span(...)``
once it holds *a* tracer.  Call sites that may hold ``None`` instead should
branch (``if tracer is not None``), which is the pattern the hot paths use.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional


class _SpanHandle:
    """Mutable args bag yielded by ``Tracer.span`` — mutate ``args`` inside
    the ``with`` block to attach data to the emitted event."""

    __slots__ = ("name", "args", "_t0_mono", "_wall_s")

    def __init__(self, name: str, args: Dict, t0_mono: float, wall_s: float):
        self.name = name
        self.args = args
        self._t0_mono = t0_mono
        self._wall_s = wall_s


class _NullSpan:
    """Shared no-op context manager (also serves as a null span handle)."""

    __slots__ = ()

    @property
    def args(self) -> Dict:
        # a fresh throwaway dict per access: annotation writes vanish
        # instead of accumulating in (or leaking through) shared state
        return {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every call returns the one shared no-op span."""

    __slots__ = ()
    enabled = False
    events: List[Dict] = []

    def span(self, name: str, **args):
        return _NULL_SPAN

    def instant(self, name: str, **args) -> None:
        pass

    def save(self, path: str) -> None:
        raise RuntimeError("NullTracer records nothing; nothing to save")


NULL_TRACER = NullTracer()


class _SpanCtx:
    """The live span context manager (one allocation per enabled span)."""

    __slots__ = ("_tracer", "_handle")

    def __init__(self, tracer: "Tracer", handle: _SpanHandle):
        self._tracer = tracer
        self._handle = handle

    def __enter__(self) -> _SpanHandle:
        h = self._handle
        h._wall_s = time.time()
        h._t0_mono = time.perf_counter()   # re-stamped at entry, not creation
        self._tracer._stack.append(h)
        return h

    def __exit__(self, *exc):
        t = self._tracer
        h = t._stack.pop()
        end = time.perf_counter()
        t.events.append(
            {
                "name": h.name,
                "ph": "X",
                "pid": t.pid,
                "tid": t.tid,
                "ts": round((h._t0_mono - t._epoch_mono) * 1e6, 3),
                "dur": round((end - h._t0_mono) * 1e6, 3),
                "args": dict(
                    h.args, depth=len(t._stack), wall_s=round(h._wall_s, 6)
                ),
            }
        )
        return False


class Tracer:
    """Collects spans + instant events; exports JSONL / Chrome traces."""

    enabled = True

    def __init__(self, pid: int = 0, tid: int = 0):
        self.pid = pid
        self.tid = tid
        self.events: List[Dict] = []
        self._stack: List[_SpanHandle] = []
        self._epoch_mono = time.perf_counter()
        self._epoch_wall = time.time()

    # ------------------------------------------------------------ recording

    def span(self, name: str, **args) -> _SpanCtx:
        """Context manager timing a nested span.  Yields a handle whose
        ``.args`` dict can be mutated to annotate the emitted event."""
        return _SpanCtx(
            self, _SpanHandle(name, args, time.perf_counter(), time.time())
        )

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (Chrome ``ph: "i"``)."""
        self.events.append(
            {
                "name": name,
                "ph": "i",
                "pid": self.pid,
                "tid": self.tid,
                "ts": round((time.perf_counter() - self._epoch_mono) * 1e6, 3),
                "s": "t",
                "args": dict(args, depth=len(self._stack)),
            }
        )

    def counter_event(
        self, name: str, value: float, labels: Optional[Dict] = None
    ) -> Dict:
        """Build (without recording) a Chrome counter sample (``ph: "C"``)
        — ``Obs.save`` uses these to embed the registry snapshot in the
        trace file without mutating the tracer."""
        args: Dict = {}
        for k, v in (labels or {}).items():
            # "value" is reserved for the sample itself; don't conflate
            args["label_value" if k == "value" else k] = v
        args["value"] = value
        return {
            "name": name,
            "ph": "C",
            "pid": self.pid,
            "ts": round((time.perf_counter() - self._epoch_mono) * 1e6, 3),
            "args": args,
        }

    def counter(
        self, name: str, value: float, labels: Optional[Dict] = None
    ) -> None:
        """Record a Chrome counter sample."""
        self.events.append(self.counter_event(name, value, labels))

    @property
    def depth(self) -> int:
        return len(self._stack)

    # -------------------------------------------------------------- queries

    def spans(self) -> List[Dict]:
        return [e for e in self.events if e.get("ph") == "X"]

    def phase_seconds(self, depth: int = 0) -> Dict[str, float]:
        """Total seconds per span name at one nesting depth — the
        phase-breakdown aggregation bench.py publishes."""
        out: Dict[str, float] = {}
        for e in self.spans():
            if e["args"].get("depth") == depth:
                out[e["name"]] = out.get(e["name"], 0.0) + e["dur"] / 1e6
        return out

    # ------------------------------------------------------------ export/io

    def save(self, path: str) -> None:
        """JSONL: one Chrome trace event per line."""
        with open(path, "w") as f:
            for e in self.events:
                f.write(json.dumps(e) + "\n")

    def save_chrome(self, path: str) -> None:
        """The ``{"traceEvents": [...]}`` wrapped form chrome://tracing and
        Perfetto open directly."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events}, f)


def load_trace(path: str) -> List[Dict]:
    """Read a trace written by :meth:`Tracer.save` (JSONL) or
    :meth:`Tracer.save_chrome` (wrapped JSON) back into an event list."""
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{") and '"traceEvents"' in stripped[:200]:
        return json.loads(stripped)["traceEvents"]
    if stripped.startswith("["):
        return json.loads(stripped)
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    return events
