"""tpu_swirld — a TPU-native hashgraph-consensus framework.

A from-scratch reimplementation of the capabilities of the reference
pure-Python hashgraph prototype (upstream layout: ``swirld.py`` /
``utils.py`` / ``viz.py``; see SURVEY.md — the reference mount was empty,
so SURVEY.md + BASELINE.json pin the spec), redesigned TPU-first:

- ``tpu_swirld.oracle`` — the pure-Python reference ``Node`` (events,
  validation, signed gossip sync, ``divide_rounds`` / ``decide_fame`` /
  ``find_order``).  It is the bit-exactness oracle for the device path.
- ``tpu_swirld.packing`` — dense append-only packer: hash-DAG -> index
  arrays (``parents: int32[N,2]``, creator, seq, timestamps, coin bits).
- ``tpu_swirld.tpu`` — the batched JAX/XLA consensus pipeline: blockwise
  boolean-matmul ancestry, fork-aware ``see``, member-hop strongly-see
  (MXU matmuls), witness/round scan, fame fixed point with coin rounds,
  order extraction.  Bit-identical to the oracle by construction.
- ``tpu_swirld.parallel`` — SPMD sharding of the pipeline over a
  ``jax.sharding.Mesh`` (members and event-blocks axes) with psum /
  all_gather collectives.
- ``tpu_swirld.sim`` — in-process multi-node gossip simulation harness
  (the reference's ``test(n_nodes, n_turns)``), plus a byzantine
  fork-injecting adversary.
"""

from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.node import Node
from tpu_swirld.oracle.event import Event

__version__ = "0.3.0"

__all__ = ["SwirldConfig", "Node", "Event", "__version__"]
