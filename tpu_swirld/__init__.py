"""tpu_swirld — a TPU-native hashgraph-consensus framework.

A from-scratch reimplementation of the capabilities of the reference
pure-Python hashgraph prototype (upstream layout: ``swirld.py`` /
``utils.py`` / ``viz.py``; see SURVEY.md — the reference mount was empty,
so SURVEY.md + BASELINE.json pin the spec), redesigned TPU-first:

- ``tpu_swirld.oracle`` — the pure-Python reference ``Node`` (events,
  validation, signed gossip sync with orphan/want-list recovery,
  ``divide_rounds`` / ``decide_fame`` / ``find_order``).  It is the
  bit-exactness oracle for the device path.
- ``tpu_swirld.packing`` — dense append-only packer: hash-DAG -> index
  arrays (``parents: int32[N,2]``, creator, seq, timestamps, coin bits,
  fork pairs, per-member tables).
- ``tpu_swirld.tpu`` — the batched JAX/XLA consensus pipeline: blockwise
  boolean-matmul ancestry, fork-aware ``see``, member-hop strongly-see
  (MXU matmuls), witness/round scan, fame fixed point with coin rounds,
  order extraction.  Bit-identical to the oracle (pinned by parity tests
  on every BASELINE config shape).
- ``tpu_swirld.parallel`` — SPMD sharding of the pipeline over a
  ``jax.sharding.Mesh`` member axis with ``psum`` stake aggregation.
- ``tpu_swirld.sim`` — in-process multi-node gossip simulation harness
  (the reference's ``test(n_nodes, n_turns)``), synthetic DAG generation
  at benchmark scale, and two byzantine adversaries (consistent-order
  fork injection + divergent equivocation).
- ``tpu_swirld.store`` — the tiled slab store: a host-side append-only
  archive of decided visibility rows, a fixed tile-budget accounting
  surface (``resident_tiles`` / ``spill`` / ``fetch``), and the
  ``StreamingConsensus`` driver whose resident device memory is bounded
  by the undecided window (BASELINE config 5 at full scale).
- ``tpu_swirld.checkpoint`` — packed-DAG, full-node, and slab-archive
  save/restore (digest-verified).
- ``tpu_swirld.metrics`` — per-phase timers, protocol gauges, profiler.
- ``tpu_swirld.viz`` — per-event state export (both backends), JSON /
  Graphviz / ASCII renderers.

Consensus entry points: ``Node.consensus_pass`` (``backend='python'``)
and ``tpu_swirld.tpu.run_consensus`` (``backend='tpu'``) consume the same
gossip-delta / packed-DAG inputs and produce identical ``round`` /
``witness`` / ``famous`` / consensus-order outputs (BASELINE north star).
"""

from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.node import Node

__version__ = "0.5.0"

__all__ = ["SwirldConfig", "Node", "Event", "__version__"]
