"""Real-process cluster: sockets, tx ingestion, crash-recovery chaos.

Everything before this package runs the protocol *in one process*: the
sim's "network" is a dict of bound methods, the chaos harness injects
faults by editing that dict, and kill -9 is simulated by dropping a node
object.  This package is the deployment edge — the same
:class:`~tpu_swirld.oracle.node.Node`, unchanged, over real TCP between
real OS processes that really die:

- :mod:`~tpu_swirld.net.frame` — length-prefixed framing, ephemeral-port
  allocation, and the net layer's only wall-clock reads;
- :mod:`~tpu_swirld.net.transport` — :class:`SocketTransport`, the
  :class:`~tpu_swirld.transport.Transport` seam over per-peer TCP with
  the in-process error planes preserved (certified bit-identical by the
  parity suite);
- :mod:`~tpu_swirld.net.ingest` — :class:`TxPool` client submission with
  dedup, size caps, and undecided-window backpressure;
- :mod:`~tpu_swirld.net.wal` — :class:`OwnEventWal`, the fsync'd
  own-event log with torn-tail recovery and the clean-shutdown marker;
- :mod:`~tpu_swirld.net.node_proc` — the per-process runtime (server +
  gossip loop + checkpointing + startup post-mortem), run as
  ``python -m tpu_swirld.net.node_proc spec.json``;
- :mod:`~tpu_swirld.net.cluster` — the supervisor: launches N node
  processes, drives client traffic, injects SIGKILL, restarts, and
  renders the same safety/liveness verdict as :mod:`tpu_swirld.chaos`.
"""

from tpu_swirld.net.frame import allocate_ports
from tpu_swirld.net.ingest import TxPool, decode_batch, encode_batch
from tpu_swirld.net.transport import SocketTransport
from tpu_swirld.net.wal import OwnEventWal

__all__ = [
    "OwnEventWal",
    "SocketTransport",
    "TxPool",
    "allocate_ports",
    "decode_batch",
    "encode_batch",
]
