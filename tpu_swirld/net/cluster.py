"""Cluster supervisor: N node processes, client traffic, kill -9 chaos.

The in-process chaos harness (:mod:`tpu_swirld.chaos`) controls both
sides of every fault; this module gives up that control.  It launches N
:mod:`~tpu_swirld.net.node_proc` runtimes as *separate OS processes*
gossiping over loopback TCP, drives real client transaction submissions
against them, SIGKILLs one mid-run (the kernel, not the harness, picks
the torn byte), restarts it from its checkpoint + own-event WAL, and
then holds the survivors to the exact standard the in-process harness
pins:

- **safety** — every node's decided order is bit-identical to a prefix
  of a fault-free oracle replay of the union DAG
  (:func:`tpu_swirld.chaos.oracle_replay` over the per-process event
  logs — the same function, the same verdict sections);
- **liveness** — the decided frontier advances past the crash window
  (:func:`tpu_swirld.chaos.liveness_section`).

The verdict also carries the tx ledger (submitted / acked / shed /
duplicate / decided, cluster tx/s-to-finality, merged p50/p99
submission→decided latency via
:func:`tpu_swirld.obs.finality.merged_dist`) and each node's startup
post-mortem path (``flightrec_dump``, ``None`` for clean starts) — a
red verdict ships its own forensics.

Telemetry plane (PR 16): the supervisor is also the cluster's
observability hub.  Every client submission opens a root span whose
16-byte trace context (:func:`tpu_swirld.obs.tracer.pack_context`) rides
the SUBMIT frame header, so one transaction's journey — client → TxPool
→ gossip hops → decided — reassembles into a single causally-linked
timeline via :func:`tpu_swirld.obs.cluster_trace.merge_dir` (written to
``merged.trace.json`` in the workdir).  On an injected-clock cadence
(``metrics_poll_s``) the supervisor polls every node's registry snapshot
over ``KIND_METRICS`` frames and, post-run, writes ``metrics.json``
(per-node samples + cluster rollup) and ``metrics.prom`` (merged
Prometheus exposition).  The verdict carries both under ``trace`` /
``metrics``; ``python -m tpu_swirld.obs report --cluster-dir`` renders
the fleet view.

``scripts/cluster_run.py`` is the CLI wrapper; ``python bench.py
--cluster`` benches the same harness.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from tpu_swirld import crypto
from tpu_swirld.chaos import (
    liveness_section, oracle_replay, safety_section, verdict_ok,
)
from tpu_swirld.config import SwirldConfig
from tpu_swirld.net import frame
from tpu_swirld.net.frame import allocate_ports
from tpu_swirld.net.node_proc import derive_paths
from tpu_swirld.net.proxy import ProxyFleet
from tpu_swirld.net.traffic import classify_reply
from tpu_swirld.obs import cluster_trace
from tpu_swirld.obs.finality import merged_dist
from tpu_swirld.obs.registry import merge_node_samples, rollup_node_samples
from tpu_swirld.obs.tracer import Tracer, pack_context
from tpu_swirld.oracle.event import Event, MalformedEvent, decode_event
from tpu_swirld.sim import member_keys


@dataclasses.dataclass
class ClusterSpec:
    """One supervised cluster run: topology + traffic + fault schedule.

    ``kill_index``/``kill_at_s`` SIGKILL one node mid-run;
    ``restart_at_s`` relaunches it from the same spec (checkpoint + WAL
    recovery).  ``net`` overrides land in every node's
    :func:`~tpu_swirld.config.resolve_net_settings` dict (stripped key
    names, e.g. ``{"gossip_interval_s": 0.005}``).

    ``proxy_plan`` (a :class:`~tpu_swirld.transport.FaultPlan`) routes
    every node-to-node gossip link through a per-link
    :class:`~tpu_swirld.net.proxy.FaultyProxy` interposer; the
    supervisor's own control plane stays direct.  ``external_indices``
    reserves member slots the supervisor must NOT launch, probe, or
    count toward reports — the soak harness runs byzantine adversaries
    in those slots itself.
    """

    workdir: str
    n_nodes: int = 5
    seed: int = 0
    duration_s: float = 4.0
    tx_rate: float = 200.0          # client submissions per second
    tx_bytes: int = 64
    kill_index: Optional[int] = None
    kill_at_s: Optional[float] = None
    restart_at_s: Optional[float] = None
    flightrec_dir: Optional[str] = None
    metrics_poll_s: float = 1.0     # KIND_METRICS snapshot cadence (<=0 off)
    host: str = "127.0.0.1"
    ready_timeout_s: float = 30.0
    stop_timeout_s: float = 60.0
    net: Dict = dataclasses.field(default_factory=dict)
    proxy_plan: Optional[object] = None
    external_indices: Tuple[int, ...] = ()
    #: launch DynamicNode processes (consensus-decided membership); the
    #: soak harness sets this whenever a MembershipWindow is scheduled
    dynamic: bool = False

    def managed_indices(self) -> List[int]:
        """Member slots this supervisor launches and holds to account."""
        return [
            i for i in range(self.n_nodes)
            if i not in self.external_indices
        ]


class ClusterClient:
    """Cached per-node client connections for the supervisor's control
    plane (submit / status / ping / stop).  One transparent redial per
    call — a restarted node invalidates its cached connection exactly
    once."""

    def __init__(self, host: str, ports: List[int], timeout: float = 5.0):
        self.host = host
        self.ports = ports
        self.timeout = timeout
        self._conns: Dict[int, socket.socket] = {}

    def _drop(self, i: int) -> None:
        sock = self._conns.pop(i, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def call(
        self, i: int, kind: int, payload: bytes = b"",
        trace: bytes = b"",
    ) -> Tuple[int, bytes]:
        """One request/reply exchange with node ``i``; raises ``OSError``
        when the node is unreachable (e.g. inside the crash window).
        ``trace`` (16 bytes or empty) rides the frame header so the node
        can parent its handling span under the client's."""
        for attempt in (0, 1):
            sock = self._conns.get(i)
            reused = sock is not None
            if sock is None:
                sock = socket.create_connection(
                    (self.host, self.ports[i]), timeout=self.timeout,
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(self.timeout)
                self._conns[i] = sock
            try:
                frame.send_request(sock, kind, b"", payload, trace=trace)
                return frame.recv_reply(sock)
            except (ConnectionError, OSError):
                self._drop(i)
                if reused and attempt == 0:
                    continue
                raise
        raise OSError("unreachable")   # pragma: no cover

    def status(self, i: int) -> Dict:
        _status, reply = self.call(i, frame.KIND_STATUS)
        return json.loads(reply.decode())

    def close(self) -> None:
        for i in list(self._conns):
            self._drop(i)


def observer_keypair(seed: int) -> Tuple[bytes, bytes]:
    """The oracle-replay observer's keypair: derived off the member
    namespace (``member-<seed>-<i>``) so it can never collide with a
    real member identity."""
    return crypto.keypair(b"cluster-observer-%d" % seed)


def collect_node_state(
    workdir: str,
    indices: List[int],
    exit_codes: Dict[int, Optional[int]],
    restarts: Dict[int, int],
) -> Tuple[Dict[int, Dict], Dict[bytes, "Event"], List[Dict]]:
    """Gather what each node left on disk: ``(reports, union, rows)``.

    ``reports`` maps index -> the node's final report JSON, ``union`` is
    the union DAG over every node's ``events.bin`` (oracle-replay
    input), ``rows`` are the per-node verdict rows.  Shared by the
    cluster verdict and the soak orchestrator so both judge runs from
    the identical evidence."""
    reports: Dict[int, Dict] = {}
    union: Dict[bytes, Event] = {}
    rows: List[Dict] = []
    for i in indices:
        paths = derive_paths(workdir, i)
        row: Dict = {
            "index": i,
            "exit_code": exit_codes.get(i),
            "restarts": restarts.get(i, 0),
            "flightrec_dump": None,
        }
        if os.path.exists(paths["report"]):
            with open(paths["report"]) as f:
                rep = json.load(f)
            reports[i] = rep
            row.update({
                "decided": len(rep["decided"]),
                "decided_tx": rep["decided_tx"],
                "events": rep["events"],
                "unclean_start": rep["unclean_start"],
                "flightrec_dump": rep["flightrec_dump"],
                "counters": rep["counters"],
            })
        else:
            row["missing_report"] = True
        if os.path.exists(paths["events"]):
            for ev in read_event_log(paths["events"]):
                union.setdefault(ev.id, ev)
        rows.append(row)
    return reports, union, rows


def read_event_log(path: str) -> List[Event]:
    """Decode a node's ``events.bin`` dump (``encode_event`` blobs,
    concatenated in topo order); stops at the first malformed byte."""
    with open(path, "rb") as f:
        data = f.read()
    out: List[Event] = []
    off = 0
    while off < len(data):
        try:
            ev, off = decode_event(data, off)
        except MalformedEvent:
            break
        out.append(ev)
    return out


class ClusterSupervisor:
    """Owns the process fleet for one :class:`ClusterSpec` run."""

    def __init__(self, spec: ClusterSpec):
        self.spec = spec
        os.makedirs(spec.workdir, exist_ok=True)
        if spec.flightrec_dir:
            os.makedirs(spec.flightrec_dir, exist_ok=True)
        self.ports = allocate_ports(spec.n_nodes, spec.host)
        # socket-level fault injection: one TCP interposer per directed
        # gossip link, sharing the in-process FaultPlan vocabulary
        self.fleet: Optional[ProxyFleet] = None
        if spec.proxy_plan is not None:
            self.fleet = ProxyFleet(
                spec.proxy_plan, spec.n_nodes, self.ports, host=spec.host,
            )
        self.procs: Dict[int, subprocess.Popen] = {}
        self.exit_codes: Dict[int, Optional[int]] = {}
        self.restarts: Dict[int, int] = {}
        self.client = ClusterClient(spec.host, self.ports)
        self._logs: List = []
        # the supervisor's own trace shard: pid 1000 keeps its span ids
        # (pid folded into the upper bits) clear of any node index
        self.tracer = Tracer(pid=1000)
        self.metrics_samples: Dict[str, List[Dict]] = {}
        self.metrics_polls = 0

    # ----------------------------------------------------------- processes

    def _spec_path(self, i: int) -> str:
        return os.path.join(self.spec.workdir, f"node-{i}.spec.json")

    def _write_node_spec(self, i: int) -> str:
        spec = self.spec
        path = self._spec_path(i)
        doc = {
            "index": i,
            "n_nodes": spec.n_nodes,
            "seed": spec.seed,
            "host": spec.host,
            "ports": self.ports,
            "workdir": spec.workdir,
            "flightrec_dir": spec.flightrec_dir,
            # orphan safety net: a node outliving its supervisor
            # (supervisor crash, wedged stop) self-terminates
            "duration_s": spec.duration_s * 3 + 60.0,
            "net": spec.net,
            "dynamic": spec.dynamic,
        }
        if self.fleet is not None:
            doc["peer_addrs"] = {
                str(j): list(self.fleet.addr_for(i, j))
                for j in range(spec.n_nodes) if j != i
            }
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def launch(self, i: int) -> None:
        paths = derive_paths(self.spec.workdir, i)
        if os.path.exists(paths["ready"]):
            os.remove(paths["ready"])
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"   # node procs never touch a device
        # the child runs with cwd=workdir; make the package importable
        # regardless of how the supervisor itself found it
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_root
        )
        log = open(os.path.join(self.spec.workdir, f"node-{i}.log"), "ab")
        self._logs.append(log)
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "tpu_swirld.net.node_proc",
             self._spec_path(i)],
            stdout=log, stderr=log, env=env, cwd=self.spec.workdir,
        )

    def wait_ready(self, indices: List[int]) -> None:
        deadline = frame.now() + self.spec.ready_timeout_s
        pending = list(indices)
        while pending:
            i = pending[0]
            paths = derive_paths(self.spec.workdir, i)
            ready = False
            if os.path.exists(paths["ready"]):
                try:
                    self.client.call(i, frame.KIND_PING)
                    ready = True
                except OSError:
                    ready = False
            if ready:
                pending.pop(0)
                continue
            proc = self.procs.get(i)
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"node {i} exited with {proc.returncode} before ready"
                    f" (see node-{i}.log)"
                )
            if frame.now() > deadline:
                raise RuntimeError(f"node {i} not ready in time")
            frame.sleep(0.05)

    def kill(self, i: int) -> None:
        """The real thing: SIGKILL, no cleanup, torn state on disk."""
        os.kill(self.procs[i].pid, signal.SIGKILL)
        self.procs[i].wait()
        self.exit_codes[i] = self.procs[i].returncode
        self.client._drop(i)

    def restart(self, i: int) -> None:
        self.launch(i)
        self.wait_ready([i])
        self.restarts[i] = self.restarts.get(i, 0) + 1

    # ----------------------------------------------------------- telemetry

    def poll_metrics(self) -> int:
        """One metrics sweep: ask every live node for its registry
        snapshot (``KIND_METRICS``).  Unreachable nodes (crash window)
        are skipped — the latest snapshot per node label wins, so a
        restarted node overwrites its pre-crash sample set.  Returns the
        number of nodes that answered."""
        answered = 0
        for i in range(self.spec.n_nodes):
            proc = self.procs.get(i)
            if proc is None or proc.poll() is not None:
                continue
            try:
                _status, reply = self.client.call(i, frame.KIND_METRICS)
                snap = json.loads(reply.decode())
            except (OSError, ValueError):
                continue
            self.metrics_samples[snap.get("node", f"n{i}")] = \
                snap.get("samples", [])
            answered += 1
        if answered:
            self.metrics_polls += 1
        return answered

    def write_telemetry(self) -> Tuple[Dict, Dict]:
        """Post-run telemetry artifacts in the workdir:

        - ``client.trace.jsonl`` — the supervisor's trace shard;
        - ``merged.trace.json`` — all shards merged onto one timebase
          with cross-process flow arrows (Perfetto-openable);
        - ``metrics.json`` — per-node registry samples + cluster rollup;
        - ``metrics.prom`` — merged Prometheus exposition (``node``
          label per sample).

        Returns the verdict's ``(trace, metrics)`` sections."""
        wd = self.spec.workdir
        self.tracer.save(os.path.join(wd, "client.trace.jsonl"))
        merged_path = os.path.join(wd, "merged.trace.json")
        merged = cluster_trace.merge_dir(wd, out_path=merged_path)
        trace_section = {
            "merged": merged_path,
            "shards": len(merged["shards"]),
            "events": merged["events"],
            "traces": merged["traces"],
            "cross_process_traces": merged["cross_process_traces"],
            "cross_process_trace_ids": merged["cross_process_trace_ids"],
        }
        metrics_json = os.path.join(wd, "metrics.json")
        metrics_prom = os.path.join(wd, "metrics.prom")
        rollup = rollup_node_samples(self.metrics_samples)
        with open(metrics_json, "w") as f:
            json.dump({
                "polls": self.metrics_polls,
                "nodes": self.metrics_samples,
                "rollup": rollup,
            }, f, indent=2, sort_keys=True)
        with open(metrics_prom, "w") as f:
            f.write(merge_node_samples(self.metrics_samples)
                    .to_prometheus_text())
        metrics_section = {
            "json": metrics_json,
            "prom": metrics_prom,
            "polls": self.metrics_polls,
            "nodes_covered": len(self.metrics_samples),
        }
        return trace_section, metrics_section

    def stop_all(self) -> None:
        for i, proc in self.procs.items():
            if proc.poll() is None:
                try:
                    self.client.call(i, frame.KIND_STOP)
                except OSError:
                    pass
        for i, proc in self.procs.items():
            if proc.poll() is None:
                try:
                    proc.wait(timeout=self.spec.stop_timeout_s)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            self.exit_codes[i] = proc.returncode
        self.client.close()
        if self.fleet is not None:
            self.fleet.close()
        for log in self._logs:
            try:
                log.close()
            except OSError:
                pass


def run_cluster(spec: ClusterSpec) -> Dict:
    """Launch, drive, fault, recover, verdict.  Returns the verdict doc
    (see module docstring); never raises on node behavior — setup
    failures (ports, spawn, readiness) do raise."""
    sup = ClusterSupervisor(spec)
    managed = spec.managed_indices()
    for i in managed:
        sup._write_node_spec(i)
        sup.launch(i)
    tx = {
        "submitted": 0, "acked": 0, "shed": 0, "duplicate": 0,
        "failed": 0, "shed_window": 0, "shed_pool": 0,
        "shed_oversize": 0, "unclassified": 0,
    }
    killed = False
    restarted = False
    decided_at_heal: Optional[int] = None
    heal_wall_s: Optional[float] = None
    try:
        sup.wait_ready(managed)
        if sup.fleet is not None:
            sup.fleet.start_clock()   # partition windows count from here
        t0 = frame.now()
        t_end = t0 + spec.duration_s
        gap = 1.0 / spec.tx_rate if spec.tx_rate > 0 else None
        poll_gap = spec.metrics_poll_s if spec.metrics_poll_s > 0 else None
        next_submit = t0
        next_poll = t0 + (poll_gap or 0.0)
        k = 0
        while frame.now() < t_end:
            now = frame.now()
            if poll_gap is not None and now >= next_poll:
                next_poll += poll_gap
                sup.poll_metrics()
            if (
                not killed
                and spec.kill_index is not None
                and spec.kill_at_s is not None
                and now - t0 >= spec.kill_at_s
            ):
                sup.kill(spec.kill_index)
                killed = True
            if (
                killed and not restarted
                and spec.restart_at_s is not None
                and now - t0 >= spec.restart_at_s
            ):
                sup.restart(spec.kill_index)
                restarted = True
                heal_wall_s = frame.now() - t0
                decided = []
                for i in managed:
                    try:
                        decided.append(sup.client.status(i)["decided"])
                    except OSError:
                        pass
                decided_at_heal = min(decided) if decided else 0
            if gap is not None and now >= next_submit:
                next_submit += gap
                target = managed[k % len(managed)]
                payload = (b"tx-%08d:" % k).ljust(spec.tx_bytes, b"x")
                k += 1
                tx["submitted"] += 1
                # root of the transaction's trace: trace id = first 8
                # bytes of the tx id, parent 0 — the node's handling
                # span (and every gossip hop after it) parents under
                # this via the frame header's 16-byte context
                ctx = pack_context(crypto.hash_bytes(payload)[:8], 0)
                with sup.tracer.span_under(
                    "client.submit", ctx, node=target,
                ) as sp:
                    try:
                        _status, reply = sup.client.call(
                            target, frame.KIND_SUBMIT, payload,
                            trace=sup.tracer.active_context() or b"",
                        )
                    except OSError:
                        tx["failed"] += 1   # crash window: expected
                        sp.args["outcome"] = "failed"
                        continue
                    # uniform per-kind accounting: all three shed kinds
                    # land in their own bucket AND the aggregate, so the
                    # overload leg's shed rate is exact even when the
                    # sheds are SHED:window during a partition
                    bucket = classify_reply(reply) or "unclassified"
                    tx[bucket] = tx.get(bucket, 0) + 1
                    if bucket.startswith("shed_"):
                        tx["shed"] += 1
                    sp.args["outcome"] = bucket
            frame.sleep(min(0.002, gap or 0.002))
        # closing sweep with every node up: the rollup covers the fleet
        if poll_gap is not None:
            sup.poll_metrics()
    finally:
        sup.stop_all()
    # node trace shards land on clean shutdown — merge after stop_all
    try:
        trace_section, metrics_section = sup.write_telemetry()
    except (OSError, ValueError) as e:   # torn shard from a crash window
        trace_section = {"error": str(e)}
        metrics_section = {"error": str(e), "polls": sup.metrics_polls,
                           "nodes_covered": len(sup.metrics_samples)}
    return _verdict(
        spec, sup, tx,
        killed=killed, restarted=restarted,
        decided_at_heal=decided_at_heal, heal_wall_s=heal_wall_s,
        trace_section=trace_section, metrics_section=metrics_section,
    )


def _verdict(
    spec: ClusterSpec,
    sup: ClusterSupervisor,
    tx: Dict,
    killed: bool,
    restarted: bool,
    decided_at_heal: Optional[int],
    heal_wall_s: Optional[float],
    trace_section: Optional[Dict] = None,
    metrics_section: Optional[Dict] = None,
) -> Dict:
    """Assemble the safety/liveness verdict from the per-node reports
    and event logs left on disk."""
    members = [pk for pk, _ in member_keys(spec.n_nodes, spec.seed)]
    config = SwirldConfig(n_members=spec.n_nodes, seed=spec.seed)
    reports, union, nodes = collect_node_state(
        spec.workdir, spec.managed_indices(),
        sup.exit_codes, sup.restarts,
    )
    orders = [
        [bytes.fromhex(e) for e in rep["decided"]]
        for _, rep in sorted(reports.items())
    ]
    if union and orders:
        oracle = oracle_replay(
            union, members, config, observer_keypair(spec.seed),
        )
        safety = safety_section(orders, oracle)
    else:
        safety = {
            "prefix_agree": False, "oracle_agree": False,
            "common_prefix_len": 0, "oracle_len": 0,
        }
    decided_final = min((len(o) for o in orders), default=0)
    liveness = liveness_section(
        decided_final, decided_at_heal, heal_turn=heal_wall_s or 0,
    )
    n_managed = len(spec.managed_indices())
    expected_reports = n_managed if (restarted or not killed) \
        else n_managed - 1
    clean_exits = all(
        c == 0 for i, c in sup.exit_codes.items()
        if not (killed and not restarted and i == spec.kill_index)
    )
    ok = (
        verdict_ok(safety, liveness)
        and len(reports) >= expected_reports
        and clean_exits
    )
    ttf_lists = [rep.get("ttf_samples", []) for rep in reports.values()]
    latency = merged_dist(ttf_lists, "submit")
    tx_decided = max(
        (rep["decided_tx"] for rep in reports.values()), default=0,
    )
    out_tx = dict(tx)
    out_tx["decided"] = tx_decided
    out_tx["tx_per_s"] = (
        tx_decided / spec.duration_s if spec.duration_s > 0 else 0.0
    )
    out_tx.update(latency)
    shed_counters = {}
    for name in ("tx_shed_window", "tx_shed_pool", "tx_shed_oversize",
                 "tx_duplicate", "tx_accepted", "tx_submitted",
                 "wal_torn_tail_recovered",
                 "net_redials", "net_redial_probes"):
        shed_counters[name] = sum(
            rep["counters"].get(name, 0) for rep in reports.values()
        )
    return {
        "spec": {
            "n_nodes": spec.n_nodes, "seed": spec.seed,
            "duration_s": spec.duration_s, "tx_rate": spec.tx_rate,
            "kill_index": spec.kill_index, "kill_at_s": spec.kill_at_s,
            "restart_at_s": spec.restart_at_s,
        },
        "ok": ok,
        "safety": safety,
        "liveness": liveness,
        "faults": {
            "killed": killed,
            "restarted": restarted,
            "heal_wall_s": heal_wall_s,
        },
        "tx": out_tx,
        "counters": shed_counters,
        "proxy": dict(sup.fleet.stats) if sup.fleet is not None else {},
        "nodes": nodes,
        "reports": len(reports),
        "trace": trace_section or {},
        "metrics": metrics_section or {},
    }
