"""Heavy-tailed client traffic against a live cluster (ROADMAP 2a).

The supervisor's built-in driver submits on a fixed metronome; real
production load is nothing like that.  This module models the three
shapes that actually break ingestion paths:

- **Pareto inter-arrival** — heavy-tailed gaps (seeded, per client):
  long quiet stretches punctuated by clumps, so the TxPool's admission
  window sees feast-and-famine instead of a steady drip.  Gaps are drawn
  as ``base_gap * (alpha - 1) * pareto(alpha)`` so the mean stays at
  ``base_gap`` (= ``n_clients / rate``) while the tail index is
  ``alpha`` — ``alpha <= 1`` would have infinite mean and is rejected.
- **burst trains** — every ``burst_every_s`` a client fires
  ``burst_len`` back-to-back submissions with no pacing, the overload
  leg that exercises ``SHED:window`` / ``SHED:pool`` shedding.
- **reconnect storms** — every ``reconnect_every_s`` a client tears
  down ALL its cached connections and redials, the thundering-herd
  pattern after an LB failover; counted in the ledger as
  ``reconnects``.

Every client thread owns a seeded RNG stream
(``SeedSequence(plan.seed, spawn_key=(client_i + 1,))``), so the
submission *schedule* is deterministic per seed; only wall-clock
interleaving with the cluster varies.

The ledger is the accounting half of the soak verdict: every submitted
transaction must land in exactly one outcome bucket (acked / duplicate /
shed_window / shed_pool / shed_oversize / failed / unclassified), and
the soak's "zero shed-accounting leaks" section asserts both that the
buckets sum back to ``submitted`` and that ``unclassified`` is zero.
The classifier is injectable precisely so the seeded red-verdict
mutation can silently un-count one shed kind and the leak detector must
catch it.

Time flows through injectable ``clock``/``sleep`` seams (defaulting to
the net layer's :func:`frame.now`/:func:`frame.sleep`), so this module
itself is SW003-clean and tests can drive it on a fake clock.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from tpu_swirld.net import frame

#: ledger buckets every submission must land in exactly one of
OUTCOMES = (
    "acked", "duplicate", "shed_window", "shed_pool", "shed_oversize",
    "failed", "unclassified",
)


def classify_reply(reply: bytes) -> Optional[str]:
    """Map a TxPool submit reply onto its ledger bucket.

    The pool's documented reply grammar is ``ACK:<hex>`` / ``DUP:<hex>``
    / ``SHED:window`` / ``SHED:pool`` / ``SHED:oversize``; anything else
    is ``unclassified`` (a leak the verdict refuses).  All three shed
    kinds are counted uniformly — the satellite fix for the cluster
    ledger lumping every non-ACK into one bucket.
    """
    if reply.startswith(b"ACK:"):
        return "acked"
    if reply.startswith(b"DUP:"):
        return "duplicate"
    if reply == b"SHED:window":
        return "shed_window"
    if reply == b"SHED:pool":
        return "shed_pool"
    if reply == b"SHED:oversize":
        return "shed_oversize"
    return "unclassified"


@dataclasses.dataclass
class TrafficPlan:
    """One seeded traffic shape: who submits, how fast, how bursty."""

    seed: int = 0
    duration_s: float = 4.0
    n_clients: int = 3
    rate: float = 150.0             # aggregate target submissions/s
    tx_bytes: int = 64
    pareto_alpha: float = 1.5       # tail index; <=1 rejected (inf mean)
    burst_every_s: float = 1.5      # 0 disables burst trains
    burst_len: int = 20
    reconnect_every_s: float = 2.0  # 0 disables reconnect storms
    max_latency_samples: int = 4096

    def __post_init__(self):
        if self.pareto_alpha <= 1.0:
            raise ValueError(
                f"pareto_alpha must be > 1 for a finite mean gap, "
                f"got {self.pareto_alpha}"
            )
        if self.n_clients < 1:
            raise ValueError("n_clients must be >= 1")


class _Client:
    """One client thread's connection cache + seeded schedule."""

    def __init__(self, gen: "TrafficGenerator", ci: int):
        self.gen = gen
        self.ci = ci
        self.rng = np.random.default_rng(
            np.random.SeedSequence(gen.plan.seed, spawn_key=(ci + 1,))
        )
        self._conns: Dict[int, socket.socket] = {}

    def _drop(self, i: int) -> None:
        sock = self._conns.pop(i, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _conn(self, i: int) -> socket.socket:
        sock = self._conns.get(i)
        if sock is None:
            sock = socket.create_connection(
                (self.gen.host, self.gen.ports[i]),
                timeout=self.gen.timeout_s,
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self.gen.timeout_s)
            self._conns[i] = sock
        return sock

    def storm(self) -> None:
        """Reconnect storm: tear down every cached connection; the next
        submission per target redials cold."""
        for i in list(self._conns):
            self._drop(i)
        self.gen._bump("reconnects")

    def submit_one(self, k: int) -> None:
        """One submission to the round-robin target; one transparent
        redial on a torn cached connection."""
        gen = self.gen
        target = gen.targets[(self.ci + k) % len(gen.targets)]
        payload = (
            b"soak-%02d-%08d:" % (self.ci, k)
        ).ljust(gen.plan.tx_bytes, b"s")
        t_sent = gen.clock()
        gen._bump("submitted")
        for attempt in (0, 1):
            sock = self._conns.get(target)
            reused = sock is not None
            try:
                if sock is None:
                    sock = self._conn(target)
                frame.send_request(sock, frame.KIND_SUBMIT, b"", payload)
                _status, reply = frame.recv_reply(sock)
            except (ConnectionError, OSError):
                self._drop(target)
                if reused and attempt == 0:
                    continue
                gen._bump("failed")
                return
            break
        else:   # pragma: no cover
            gen._bump("failed")
            return
        bucket = gen.classify(reply)
        if bucket in OUTCOMES and bucket != "unclassified":
            gen._bump(bucket)
            if bucket == "acked":
                gen._latency(gen.clock() - t_sent)
        elif bucket is not None:
            gen._bump("unclassified")
        # bucket is None: the tx silently falls out of the ledger — the
        # shed-accounting leak the soak verdict's balance check exists
        # to catch (exercised by the seeded red-verdict mutation)

    def run(self) -> None:
        gen = self.gen
        plan = gen.plan
        base_gap = plan.n_clients / plan.rate if plan.rate > 0 else None
        t0 = gen.clock()
        t_end = t0 + plan.duration_s
        next_burst = (
            t0 + plan.burst_every_s if plan.burst_every_s > 0 else None
        )
        next_storm = (
            t0 + plan.reconnect_every_s
            if plan.reconnect_every_s > 0 else None
        )
        k = 0
        while gen.clock() < t_end and not gen._stopping.is_set():
            now = gen.clock()
            if next_storm is not None and now >= next_storm:
                next_storm += plan.reconnect_every_s
                self.storm()
            if next_burst is not None and now >= next_burst:
                next_burst += plan.burst_every_s
                for _ in range(plan.burst_len):
                    self.submit_one(k)
                    k += 1
                continue   # no pacing inside a burst train
            self.submit_one(k)
            k += 1
            if base_gap is None:
                break   # rate 0: bursts/storms only
            # heavy-tailed gap with mean base_gap: pareto(a) has mean
            # 1/(a-1), so scale by (a-1)
            gap = (
                base_gap * (plan.pareto_alpha - 1.0)
                * float(self.rng.pareto(plan.pareto_alpha))
            )
            gen.sleep(min(gap, plan.duration_s))
        for i in list(self._conns):
            self._drop(i)


class TrafficGenerator:
    """Drive a :class:`TrafficPlan` against live node submit ports.

    Args:
      plan: the seeded traffic shape.
      host / ports: node submit listeners (index-aligned with the
        cluster spec).
      targets: node indices to submit to — the soak passes only honest,
        currently-live indices.
      classify: reply -> ledger bucket (injectable for the red-verdict
        mutation); ``None`` return = the tx leaks from the ledger.
      clock / sleep: time seams, default :func:`frame.now` /
        :func:`frame.sleep`.

    :meth:`start` launches one thread per client; :meth:`join` waits for
    the horizon; :meth:`report` returns the ledger + rates at any point
    (thread-safe snapshot).
    """

    def __init__(
        self,
        plan: TrafficPlan,
        host: str,
        ports: Sequence[int],
        targets: Sequence[int],
        classify: Callable[[bytes], Optional[str]] = classify_reply,
        clock: Callable[[], float] = frame.now,
        sleep: Callable[[float], None] = frame.sleep,
        timeout_s: float = 5.0,
    ):
        if not targets:
            raise ValueError("traffic needs at least one target node")
        self.plan = plan
        self.host = host
        self.ports = list(ports)
        self.targets = list(targets)
        self.classify = classify
        self.clock = clock
        self.sleep = sleep
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._ledger: Dict[str, int] = {
            "submitted": 0, "reconnects": 0,
            **{k: 0 for k in OUTCOMES},
        }
        self._ack_latencies: List[float] = []
        self._stopping = threading.Event()
        self._threads: List[threading.Thread] = []
        self._t0: Optional[float] = None
        self._t1: Optional[float] = None

    # ------------------------------------------------------------- ledger

    def _bump(self, key: str, delta: int = 1) -> None:
        with self._lock:
            self._ledger[key] = self._ledger.get(key, 0) + delta

    def _latency(self, dt: float) -> None:
        with self._lock:
            if len(self._ack_latencies) < self.plan.max_latency_samples:
                self._ack_latencies.append(dt)

    def retarget(self, targets: Sequence[int]) -> None:
        """Swap the live target set (e.g. exclude a crashed node)."""
        if targets:
            self.targets = list(targets)

    # ------------------------------------------------------------ driving

    def start(self) -> None:
        self._t0 = self.clock()
        for ci in range(self.plan.n_clients):
            t = threading.Thread(
                target=_Client(self, ci).run,
                name=f"traffic-client-{ci}", daemon=True,
            )
            self._threads.append(t)
            t.start()

    def join(self, timeout_s: Optional[float] = None) -> None:
        deadline = (
            self.clock() + timeout_s if timeout_s is not None else None
        )
        for t in self._threads:
            left = (
                None if deadline is None
                else max(0.0, deadline - self.clock())
            )
            t.join(left)
        self._stopping.set()
        self._t1 = self.clock()

    def stop(self) -> None:
        self._stopping.set()

    # ------------------------------------------------------------- report

    def report(self) -> Dict:
        """Ledger snapshot + derived rates.

        ``balance_ok`` is the leak detector: the outcome buckets must
        sum back to ``submitted``.  ``leaked`` is how many transactions
        fell out of the ledger (always 0 unless the classifier is
        broken — exactly what the soak mutation arranges).
        """
        with self._lock:
            ledger = dict(self._ledger)
            lat = sorted(self._ack_latencies)
        t1 = self._t1 if self._t1 is not None else self.clock()
        elapsed = max(1e-9, (t1 - self._t0) if self._t0 else 0.0)
        accounted = sum(ledger[k] for k in OUTCOMES)
        shed = (
            ledger["shed_window"] + ledger["shed_pool"]
            + ledger["shed_oversize"]
        )

        def pct(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        return {
            **ledger,
            "shed": shed,
            "accounted": accounted,
            "leaked": ledger["submitted"] - accounted,
            "balance_ok": (
                ledger["submitted"] == accounted
                and ledger["unclassified"] == 0
            ),
            "elapsed_s": elapsed,
            "tx_per_s": ledger["acked"] / elapsed,
            "shed_rate": shed / max(1, ledger["submitted"]),
            "submit_p50_s": pct(0.50),
            "submit_p99_s": pct(0.99),
            "latency_samples": len(lat),
        }
