"""Durable own-event WAL with torn-tail recovery.

The chaos harness keeps each member's own-event WAL in memory (the
driver owns both sides of the crash).  A real process killed with
``kill -9`` needs the same guarantee on disk: a signer must never lose
its own signing history, or its restart re-signs at an old sequence
number and equivocates against its own lost tip (the amnesia fork the
chaos module docstring describes).

File layout::

    b"SWAL1" | record*
    record   = <B tag> body
    tag 1    = own event (encode_event blob)
    tag 2    = clean-shutdown marker (no body; always the last byte)

Records are appended with flush+fsync *before* the event's id is
gossiped, so anything a peer may have seen from us is durable.  A crash
mid-append leaves a torn tail: :class:`OwnEventWal` recovers by decoding
records until the first one that is truncated, malformed, fails
signature verification, or names a foreign creator — the valid prefix
is kept, the torn bytes are truncated away (counted in
``torn_tail_recovered``), and appending resumes from the cut.  This can
only drop the *last* record(s), which by the write ordering were never
gossiped — so recovery never loses an event a peer could hold against
us.

The clean-shutdown marker drives the flight recorder: a WAL that exists
but does not end in the marker means the previous process died
uncleanly, and the restarted process dumps a post-mortem at startup
(:func:`tpu_swirld.net.node_proc.startup_postmortem`).  Re-opening for
append truncates the marker away, so a WAL is only ever "clean" between
a graceful stop and the next start.
"""

from __future__ import annotations

import os
from typing import List, Optional

from tpu_swirld.oracle.event import (
    Event, MalformedEvent, decode_event, encode_event,
)

MAGIC = b"SWAL1"
TAG_EVENT = 1
TAG_CLEAN = 2


class OwnEventWal:
    """Append-only durable log of one member's self-signed events.

    Args:
      path: WAL file (created with just the magic if absent).
      pk: the owning member's public key; records naming any other
        creator are treated as corruption (the WAL holds *own* events
        only, so a foreign creator can only mean torn/overwritten bytes).

    Attributes:
      events: the recovered valid prefix, in append order.
      existed: the file predated this open (a restart, not a cold start).
      clean_shutdown: the recovered tail carried the clean marker.
      torn_tail_recovered: 1 if a torn/corrupt tail was truncated away.
    """

    def __init__(self, path: str, pk: Optional[bytes] = None):
        self.path = path
        self.pk = pk
        self.events: List[Event] = []
        self.existed = os.path.exists(path)
        self.clean_shutdown = False
        self.torn_tail_recovered = 0
        valid_end = len(MAGIC)
        bad_magic = False
        if self.existed:
            with open(path, "rb") as f:
                data = f.read()
            if data[:len(MAGIC)] != MAGIC:
                # foreign or totally mangled file: everything is tail,
                # including the header — rewrite from scratch
                self.torn_tail_recovered = 1
                bad_magic = True
            else:
                valid_end = self._scan(data)
        # (re)write from the valid prefix: a torn tail (or a stale clean
        # marker) is truncated away so appends resume from sound bytes
        mode = "r+b" if (self.existed and not bad_magic) else "wb"
        self._f = open(path, mode)
        if mode == "wb":
            self._f.write(MAGIC)
            self._f.flush()
            os.fsync(self._f.fileno())
        else:
            self._f.seek(valid_end)
            self._f.truncate(valid_end)

    def _scan(self, data: bytes) -> int:
        """Decode records; returns the byte offset of the valid prefix
        end (the clean marker, when present, is NOT part of the prefix —
        reopening consumes it)."""
        off = len(MAGIC)
        while off < len(data):
            tag = data[off]
            if tag == TAG_CLEAN:
                # a marker anywhere but the final byte means the file
                # was appended to after a "clean" close — torn state
                if off + 1 == len(data):
                    self.clean_shutdown = True
                else:
                    self.torn_tail_recovered = 1
                return off
            if tag != TAG_EVENT:
                self.torn_tail_recovered = 1
                return off
            try:
                ev, nxt = decode_event(data, off + 1)
            except MalformedEvent:
                self.torn_tail_recovered = 1
                return off
            if not ev.verify() or (self.pk is not None and ev.c != self.pk):
                # decodes but does not verify: corrupt-not-truncated
                # tail (bit rot / partial overwrite), same recovery
                self.torn_tail_recovered = 1
                return off
            self.events.append(ev)
            off = nxt
        return off

    @property
    def unclean(self) -> bool:
        """The previous process died without a graceful stop."""
        return self.existed and not self.clean_shutdown

    def append(self, ev: Event) -> None:
        """Durably log one own event (flush + fsync **before** the
        caller gossips it — the ordering the recovery proof needs)."""
        self._f.write(bytes([TAG_EVENT]) + encode_event(ev))
        self._f.flush()
        os.fsync(self._f.fileno())
        self.events.append(ev)

    def rewrite(self, events: List[Event]) -> None:
        """Atomically replace the log (checkpoint pruning: entries the
        checkpoint already covers are dropped, tmp + ``os.replace`` so a
        crash mid-prune leaves either the old or the new file whole)."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(MAGIC + b"".join(
                bytes([TAG_EVENT]) + encode_event(ev) for ev in events
            ))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "r+b")
        self._f.seek(0, os.SEEK_END)
        self.events = list(events)

    def mark_clean(self) -> None:
        """Graceful-stop marker; the WAL is closed afterwards."""
        self._f.write(bytes([TAG_CLEAN]))
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()
