"""The :class:`~tpu_swirld.transport.Transport` seam over real TCP.

:class:`SocketTransport` speaks the length-prefixed frame protocol of
:mod:`tpu_swirld.net.frame` to per-peer addresses, mapping socket
reality back onto the exact error planes the node's gossip loop already
handles — so :meth:`Node._transport_call`'s retry/backoff, the
:class:`~tpu_swirld.transport.CircuitBreaker`, and the counted-rejection
path all work unchanged over a real network:

- connect failure / reset / EOF / bad frame → :class:`PeerUnreachable`
  (retryable; the cached connection is dropped and the first retry
  reconnects);
- reply deadline exceeded → :class:`DeliveryTimeout` (retryable — the
  reply may arrive stale; the connection is dropped so a late reply can
  never be mis-paired with the next request);
- ``STATUS_REJECT`` reply → ``ValueError`` (the endpoints' documented
  rejection signal: counted as a bad reply, breaker misbehavior strike,
  never retried);
- ``STATUS_ERROR`` reply → :class:`PeerUnreachable` (the server failed
  internally; retryable).

Reply *bytes* are untrusted either way: the caller's hardened
``_decode_signed_blob`` path verifies signatures and bounds exactly as
it does against the in-process fault injector, which is what the
parity suite (same schedule, both transports, bit-identical decided
prefixes) certifies.

Connections are cached per destination and re-dialed lazily.  One
request/reply exchange is in flight per connection — the node's gossip
loop is single-threaded, so no framing interleave is possible.  Real
deadlines come from the ``SWIRLD_NET_*`` knobs
(:func:`~tpu_swirld.config.resolve_net_settings`).

Peer restarts are a two-step race: the dead cached connection triggers
one transparent redial (counted in ``stats["redials"]``), and when that
redial's *connect* also fails — the restarting peer's new listener is
not bound yet — one bounded re-probe (``redial_probe_s``, counted in
``stats["redial_probes"]``) runs before the call surfaces as
:class:`PeerUnreachable`.  The cluster verdict mirrors the totals as
``net_redials`` so a soak run can assert reconnect behavior.
"""

from __future__ import annotations

import collections
import socket
from typing import Dict, Optional, Tuple

from tpu_swirld import obs
from tpu_swirld.config import resolve_net_settings
from tpu_swirld.net import frame
from tpu_swirld.transport import (
    CHANNEL_SYNC, DeliveryTimeout, PeerUnreachable, Transport,
)

_CHANNEL_KIND = {
    CHANNEL_SYNC: frame.KIND_SYNC,
}


class SocketTransport(Transport):
    """Per-peer TCP delivery for one node process.

    Args:
      addrs: pk -> ``(host, port)`` for every reachable peer (grow via
        :meth:`register`).
      settings: a :func:`~tpu_swirld.config.resolve_net_settings` dict;
        ``None`` resolves from the environment.
      src: this node's pk, stamped into request frames (the server uses
        it for the gossip endpoints' ``src`` argument).
    """

    def __init__(
        self,
        addrs: Optional[Dict[bytes, Tuple[str, int]]] = None,
        settings: Optional[Dict] = None,
        src: bytes = b"",
    ):
        super().__init__({}, {})
        self.addrs: Dict[bytes, Tuple[str, int]] = dict(addrs or {})
        self.settings = dict(settings) if settings else resolve_net_settings()
        self.src = src
        self._conns: Dict[bytes, socket.socket] = {}
        self.stats: Dict[str, int] = collections.defaultdict(int)
        #: zero-arg callable -> 16-byte trace context (or None/b"") used
        #: to stamp outgoing frames.  Defaults to the ambient obs
        #: tracer's innermost traced span; node_proc overrides it with a
        #: lock-safe snapshot because its server threads share the
        #: tracer with the gossip loop.
        self.trace_provider = None

    def _trace_ctx(self) -> bytes:
        if self.trace_provider is not None:
            return self.trace_provider() or b""
        o = obs.current()
        if o is not None:
            ctx = o.tracer.active_context()
            if ctx:
                return ctx
        return b""

    # ------------------------------------------------------------ plumbing

    def register(self, pk: bytes, host: str, port: int) -> None:
        self.addrs[pk] = (host, port)

    def _count(self, name: str, delta: int = 1) -> None:
        self.stats[name] += delta
        o = obs.current()
        if o is not None:
            o.registry.counter(f"transport_{name}_total").inc(delta)

    def endpoint(self, dst: bytes, channel: str):
        """A peer's address doubles as its endpoint handle: the node's
        want-availability probe (``transport.endpoint(peer, WANT) is not
        None``) answers "reachable" for any registered peer — the socket
        server serves both channels on one port."""
        return self.addrs.get(dst)

    def close(self) -> None:
        for sock in self._conns.values():
            try:
                sock.close()
            except OSError:
                pass
        self._conns.clear()

    def _drop(self, dst: bytes) -> None:
        sock = self._conns.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _connect(self, dst: bytes, addr: Tuple[str, int]) -> socket.socket:
        try:
            sock = socket.create_connection(
                addr, timeout=self.settings["connect_timeout_s"],
            )
        except OSError as e:
            self._count("connect_failures")
            raise PeerUnreachable(
                f"connect to {addr[0]}:{addr[1]} failed: "
                f"{type(e).__name__}"
            ) from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self.settings["call_timeout_s"])
        self._conns[dst] = sock
        return sock

    # ---------------------------------------------------------------- call

    def call(
        self, src: bytes, dst: bytes, channel: str, payload: bytes,
    ) -> bytes:
        if self.on_call is not None:
            self.on_call(src, dst, channel)
        addr = self.addrs.get(dst)
        if addr is None:
            raise PeerUnreachable(f"no address for peer on {channel}")
        kind = _CHANNEL_KIND.get(channel, frame.KIND_WANT)
        max_frame = self.settings["max_frame_bytes"]
        trace = self._trace_ctx()
        # one transparent redial: a cached connection may have died
        # (server restart, idle reset) — that is not a peer failure yet
        for attempt in (0, 1):
            sock = self._conns.get(dst)
            reused = sock is not None
            if sock is None:
                try:
                    sock = self._connect(dst, addr)
                except PeerUnreachable:
                    if attempt == 0:
                        raise   # cold connect failed: peer genuinely away
                    # redial window: the peer that just closed our cached
                    # connection is likely mid-restart (its old listener
                    # is down, the new one not yet bound).  One bounded
                    # re-probe turns that race into a deterministic
                    # reconnect instead of a spurious PeerUnreachable.
                    self._count("redial_probes")
                    frame.sleep(self.settings["redial_probe_s"])
                    sock = self._connect(dst, addr)
            try:
                frame.send_request(
                    sock, kind, src or self.src, payload, trace=trace,
                )
                status, reply = frame.recv_reply(sock, max_frame)
            except socket.timeout as e:
                # drop the connection: a stale reply surfacing on the
                # next request would be mis-paired
                self._drop(dst)
                self._count("timeouts")
                raise DeliveryTimeout(
                    f"no reply within "
                    f"{self.settings['call_timeout_s']}s"
                ) from e
            except (ConnectionError, OSError) as e:
                self._drop(dst)
                if reused and attempt == 0:
                    self._count("redials")
                    continue   # stale cached conn: redial once
                self._count("conn_errors")
                raise PeerUnreachable(
                    f"connection to peer failed: {type(e).__name__}"
                ) from e
            self._count("calls")
            self._count("bytes_out", len(payload))
            self._count("bytes_in", len(reply))
            if status == frame.STATUS_OK:
                return reply
            if status == frame.STATUS_REJECT:
                # the endpoints' documented rejection signal crosses the
                # wire as a status byte and resurfaces as the same
                # ValueError the in-process path raises
                self._count("rejects")
                raise ValueError(
                    reply[:256].decode("utf-8", "replace")
                    or "peer rejected request"
                )
            self._count("peer_errors")
            raise PeerUnreachable(
                f"peer reported server error: "
                f"{reply[:256].decode('utf-8', 'replace')}"
            )
        raise PeerUnreachable("unreachable")   # pragma: no cover
