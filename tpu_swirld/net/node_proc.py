"""One cluster node as an OS process: server, gossip loop, durability.

``python -m tpu_swirld.net.node_proc spec.json`` runs a single member of
a real-process cluster (:mod:`tpu_swirld.net.cluster` writes the spec
and supervises N of these).  The runtime wires the unchanged
:class:`~tpu_swirld.oracle.node.Node` to the real world:

- a :class:`NodeServer` accepts framed TCP requests and dispatches them
  — gossip (``ask_sync`` / ``ask_events``), client tx submission into a
  :class:`~tpu_swirld.net.ingest.TxPool`, status probes, graceful stop;
- a gossip loop picks seeded-random peers, drains tx batches into event
  payloads via :class:`~tpu_swirld.net.transport.SocketTransport`, runs
  consensus, and records each decided transaction into a
  :class:`~tpu_swirld.obs.finality.FinalityTracker` (submission →
  decided wall latency);
- every own event is fsync'd into an :class:`~tpu_swirld.net.wal.
  OwnEventWal` *before* it can be gossiped, and the node checkpoints
  periodically (atomic :func:`~tpu_swirld.checkpoint.save_node`), so a
  ``kill -9`` at any instant restarts into checkpoint + WAL replay +
  pull-only recovery without ever equivocating against its own past;
- a WAL that exists but lacks the clean-shutdown marker means the
  previous incarnation died: :func:`startup_postmortem` dumps a flight-
  recorder post-mortem before the node rejoins.

Locking: ONE lock guards all node/pool/tracker state.  The gossip loop
holds it for a whole turn, but :class:`_YieldingTransport` releases it
around every blocking socket call (and the installed ``node._sleep``
releases it around real backoff sleeps), so server threads serve
incoming gossip while this node waits on the wire — two nodes syncing
into each other cannot deadlock.

The import chain stays jax-free (oracle node + checkpoint + obs), so a
node process starts in milliseconds and never touches an accelerator.
"""

from __future__ import annotations

import collections
import json
import os
import random
import socket
import threading
from typing import Dict, List, Optional, Tuple

from tpu_swirld import crypto
from tpu_swirld.checkpoint import load_node, save_node
from tpu_swirld.config import SwirldConfig, resolve_net_settings
from tpu_swirld.net import frame
from tpu_swirld.net.ingest import TxPool, decode_batch
from tpu_swirld.net.transport import SocketTransport
from tpu_swirld.net.wal import OwnEventWal
from tpu_swirld.obs.finality import FinalityTracker
from tpu_swirld.obs.flightrec import FlightRecorder
from tpu_swirld.obs.registry import Registry
from tpu_swirld.obs.tracer import Tracer
from tpu_swirld.oracle.event import encode_event
from tpu_swirld.oracle.node import Node
from tpu_swirld.sim import member_keys

REPORT_VERSION = 2

#: trace-shard memory bounds: spans kept per node process, and how many
#: submitted-tx trace contexts are remembered for decided correlation
TRACE_MAX_EVENTS = 200_000
TX_TRACE_CAP = 4096


def derive_paths(workdir: str, index: int) -> Dict[str, str]:
    """Per-node file layout inside the cluster workdir — shared
    vocabulary between this runtime and the supervisor."""
    stem = os.path.join(workdir, f"node-{index}")
    return {
        "ckpt": stem + ".swck",
        "wal": stem + ".wal",
        "report": stem + ".report.json",
        "events": stem + ".events.bin",
        "ready": stem + ".ready",
        "trace": stem + ".trace.jsonl",
    }


def startup_postmortem(
    wal: OwnEventWal, rec: FlightRecorder, label: str,
) -> Optional[str]:
    """Dump a post-mortem when the WAL shows an unclean shutdown.

    The previous incarnation died without writing the clean marker — the
    one moment a black box earns its keep.  Returns the dump path, or
    ``None`` when the shutdown was clean (or no dump dir / budget).
    """
    if not wal.unclean:
        return None
    return rec.trigger(
        "unclean_shutdown",
        node=label,
        detail={
            "wal_path": wal.path,
            "wal_events": len(wal.events),
            "torn_tail_recovered": wal.torn_tail_recovered,
        },
    )


class _YieldingTransport:
    """Transport wrapper that releases the runtime lock around blocking
    socket I/O.  The gossip loop owns the lock for a whole turn; without
    this, a server thread handling a peer's sync would wait on the lock
    while our own outbound call waits on that peer's equally-blocked
    loop — a distributed deadlock.  ``call`` is only ever invoked with
    the lock held (by the gossip loop's turn)."""

    def __init__(self, inner: SocketTransport, lock: threading.Lock):
        self.inner = inner
        self.lock = lock

    def endpoint(self, dst: bytes, channel: str):
        return self.inner.endpoint(dst, channel)

    def call(self, src: bytes, dst: bytes, channel: str, payload: bytes):
        self.lock.release()
        try:
            return self.inner.call(src, dst, channel, payload)
        finally:
            self.lock.acquire()

    def close(self) -> None:
        self.inner.close()


class NodeServer:
    """Framed-TCP server: one accept loop, one daemon thread per
    connection, every request answered through one ``dispatch``
    callable.  All mutable runtime state lives behind the dispatch
    closure's lock — worker threads store nothing on ``self``, so the
    SW006 audit surface is empty by construction."""

    def __init__(self, host: str, port: int, dispatch, max_frame: int):
        self._dispatch = dispatch
        self._max_frame = max_frame
        self._stopping = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self._accept_thread = threading.Thread(
            target=self._serve, daemon=True,
        )
        self._accept_thread.start()

    def _serve(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return   # listener closed: shutdown
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True,
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                kind, src, payload, trace = frame.recv_request(
                    conn, self._max_frame,
                )
                try:
                    status, reply = self._dispatch(kind, src, payload, trace)
                except ValueError as e:
                    # the endpoints' documented rejection plane: counted
                    # by the caller as a bad reply, never retried
                    status, reply = frame.STATUS_REJECT, str(e).encode()
                except Exception as e:   # server bug: retryable plane
                    status, reply = (
                        frame.STATUS_ERROR,
                        f"{type(e).__name__}: {e}".encode()[:512],
                    )
                frame.send_reply(conn, status, reply)
        except (ConnectionError, OSError):
            pass   # client went away (incl. frame garbage): drop conn
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass


class NodeRuntime:
    """The per-process composition: durable node + pool + server + loop.

    Built from a *spec* dict (see :func:`main`): the member index, the
    shared ``(n_nodes, seed)`` identity rule, the full host/port
    topology, and the workdir holding this node's checkpoint/WAL/report
    files.  The constructor performs the whole crash-recovery sequence —
    WAL scan (+ startup post-mortem), checkpoint restore, WAL replay —
    and :meth:`run` serves until a STOP request or the optional duration
    elapses, then checkpoints, writes the report, and marks the WAL
    clean.
    """

    def __init__(self, spec: Dict):
        self.spec = spec
        self.index = int(spec["index"])
        self.n_nodes = int(spec["n_nodes"])
        self.seed = int(spec.get("seed", 0))
        self.host = spec.get("host", "127.0.0.1")
        self.ports: List[int] = [int(p) for p in spec["ports"]]
        self.workdir = spec["workdir"]
        self.paths = derive_paths(self.workdir, self.index)
        self.settings = resolve_net_settings()
        self.settings.update(spec.get("net") or {})
        self.duration_s = spec.get("duration_s")
        self.label = f"n{self.index}"

        keys = member_keys(self.n_nodes, self.seed)
        self.pk, self.sk = keys[self.index]
        self.members = [pk for pk, _ in keys]
        self.config = SwirldConfig(n_members=self.n_nodes, seed=self.seed)

        self.lock = threading.Lock()
        self.stop = threading.Event()

        # --- telemetry: per-process trace shard + metrics registry --------
        # All tracer/registry mutation happens under self.lock (dispatch
        # and the gossip turn both hold it), so server threads and the
        # loop interleave spans without torn state.
        self.tracer = Tracer(pid=self.index, max_events=TRACE_MAX_EVENTS)
        self.registry = Registry()
        #: txid -> wire trace context for txs submitted to THIS node,
        #: so the decided marker closes the trace where it began
        self._tx_traces: "collections.OrderedDict[bytes, bytes]" = (
            collections.OrderedDict()
        )
        #: context stamped onto outgoing gossip frames for the *current*
        #: traced turn (read lock-free by the transport while the lock is
        #: yielded around socket I/O — a bytes snapshot, not stack state)
        self._gossip_ctx = b""

        # --- durability: WAL scan + startup post-mortem -------------------
        self.wal = OwnEventWal(self.paths["wal"], pk=self.pk)
        self.unclean_start = self.wal.unclean
        self.flightrec = FlightRecorder(
            dump_dir=spec.get("flightrec_dir"),
            wall_clock=frame.now,
            config=self.config,
            node_name=self.label,
            trace_provider=self.tracer.active_trace_hex,
        )
        self.flightrec_dump = startup_postmortem(
            self.wal, self.flightrec, self.label,
        )

        # --- transport + node (checkpoint restore when one exists) -------
        sock_transport = SocketTransport(settings=self.settings, src=self.pk)
        sock_transport.trace_provider = lambda: self._gossip_ctx
        # optional per-peer address overrides: the soak supervisor routes
        # node-to-node links through its FaultyProxy fleet by pointing
        # each peer at the matching link proxy instead of the real port
        peer_addrs = {
            int(k): (v[0], int(v[1]))
            for k, v in (spec.get("peer_addrs") or {}).items()
        }
        for j, pk_j in enumerate(self.members):
            if j != self.index:
                h, p = peer_addrs.get(j, (self.host, self.ports[j]))
                sock_transport.register(pk_j, h, p)
        self.transport = sock_transport
        yielding = _YieldingTransport(sock_transport, self.lock)
        self.dynamic = bool(spec.get("dynamic"))
        #: queued MTX1 blobs (KIND_MTX): each rides one gossip event's
        #: payload whole — membership txs are never batched with client
        #: txs, because decode_tx reads the full event payload
        self._pending_mtx: List[bytes] = []
        self.restored = os.path.exists(self.paths["ckpt"])
        if self.restored:
            # a dynamic node's checkpoint carries its membership header,
            # so load_node restores the right class on its own
            self.node = load_node(
                self.paths["ckpt"], sk=self.sk, pk=self.pk, network={},
                transport=yielding,
            )
        elif self.dynamic:
            from tpu_swirld.membership.dynamic import DynamicNode

            self.node = DynamicNode(
                sk=self.sk, pk=self.pk, network={}, members=self.members,
                config=self.config, transport=yielding,
            )
        else:
            self.node = Node(
                sk=self.sk, pk=self.pk, network={}, members=self.members,
                config=self.config, transport=yielding,
            )
            # the genesis is durable before anything can be gossiped.
            # On a crash *before the first checkpoint* the WAL already
            # starts with this exact genesis (the lamport-clock genesis
            # is bit-deterministic) — appending again would put it after
            # the real tail and defeat the pull-only recovery guard.
            if not self.wal.events:
                self.wal.append(self.node.hg[self.node.head])
        # real backoff: Node records logical delays; scale them onto the
        # wall clock, capped so a long breaker cooldown cannot stall a
        # whole gossip turn.  Runs with the lock held — yield it.
        tick_s = float(self.settings["retry_tick_s"])

        def _net_sleep(ticks: float) -> None:
            self.lock.release()
            try:
                frame.sleep(min(ticks * tick_s, 0.5))
            finally:
                self.lock.acquire()

        self.node._sleep = _net_sleep

        # --- WAL replay: events since the last checkpoint -----------------
        wal_ids: List[bytes] = []
        self.node._ingest(self.wal.events, wal_ids)
        if wal_ids:
            self.node.consensus_pass(wal_ids)

        # --- tx ingestion + finality tracking -----------------------------
        self.pool = TxPool(
            max_pool=self.settings["tx_pool_txs"],
            batch_bytes=self.settings["tx_batch_bytes"],
            max_tx_bytes=self.settings["tx_max_bytes"],
            max_undecided=self.settings["max_undecided"],
            window_fn=lambda: self.node.undecided_window,
        )
        self.tracker = FinalityTracker(
            "cluster", clock=frame.now, registry=self.registry,
        )
        self.decided_txids: set = set()
        self.decided_tx = 0
        self._decided_watermark = 0
        self._rng = random.Random(
            int.from_bytes(
                crypto.hash_bytes(b"netproc" + self.pk)[:8], "little",
            )
            ^ self.seed
        )
        self.server: Optional[NodeServer] = None

    # ------------------------------------------------------------ dispatch

    def dispatch(self, kind: int, src: bytes, payload: bytes,
                 trace: bytes = b"") -> Tuple[int, bytes]:
        """Serve one framed request (called from server threads); a
        non-empty ``trace`` is the sender's 16-byte span context — the
        handler's span becomes its cross-process child."""
        if kind == frame.KIND_PING:
            return frame.STATUS_OK, b"pong"
        if kind == frame.KIND_STOP:
            self.stop.set()
            return frame.STATUS_OK, b"stopping"
        if kind == frame.KIND_SUBMIT:
            with self.lock:
                with self.tracer.span_under("node.submit", trace) as sp:
                    accepted, reply = self.pool.submit(payload)
                    sp.args["outcome"] = (
                        reply.split(b":", 1)[0].decode("ascii", "replace")
                    )
                    # remember THIS span's context: the gossip turn that
                    # drains the tx parents under it, extending the trace
                    own_ctx = self.tracer.active_context()
                if accepted:
                    txid = crypto.hash_bytes(payload)
                    self.tracker.mark_birth(txid)
                    if own_ctx:
                        self._remember_trace(txid, own_ctx)
            return frame.STATUS_OK, reply
        if kind == frame.KIND_MTX:
            if not hasattr(self.node, "ledger"):
                return frame.STATUS_ERR, b"MTX:static-cluster"
            from tpu_swirld.membership.txs import decode_tx
            if decode_tx(payload) is None:
                return frame.STATUS_ERR, b"MTX:malformed"
            with self.lock:
                self._pending_mtx.append(payload)
            return frame.STATUS_OK, b"MTX:queued"
        if kind == frame.KIND_STATUS:
            with self.lock:
                body = json.dumps(self.status()).encode()
            return frame.STATUS_OK, body
        if kind == frame.KIND_METRICS:
            with self.lock:
                body = json.dumps(self.metrics_snapshot()).encode()
            return frame.STATUS_OK, body
        if kind == frame.KIND_SYNC:
            with self.lock:
                if trace:
                    with self.tracer.span_under(
                        "node.serve_sync", trace,
                    ) as sp:
                        reply = self.node.ask_sync(src, payload)
                        sp.args["reply_bytes"] = len(reply)
                    return frame.STATUS_OK, reply
                return frame.STATUS_OK, self.node.ask_sync(src, payload)
        if kind == frame.KIND_WANT:
            with self.lock:
                if trace:
                    with self.tracer.span_under(
                        "node.serve_want", trace,
                    ) as sp:
                        reply = self.node.ask_events(src, payload)
                        sp.args["reply_bytes"] = len(reply)
                    return frame.STATUS_OK, reply
                return frame.STATUS_OK, self.node.ask_events(src, payload)
        raise ValueError(f"unknown request kind {kind}")

    def _remember_trace(self, txid: bytes, trace: bytes) -> None:
        """Bounded txid -> submit-context map (oldest evicted first)."""
        self._tx_traces[txid] = trace
        while len(self._tx_traces) > TX_TRACE_CAP:
            self._tx_traces.popitem(last=False)

    # -------------------------------------------------------------- status

    def status(self) -> Dict:
        """Supervisor probe body (caller holds the lock)."""
        node = self.node
        return {
            "index": self.index,
            "pk": self.pk.hex(),
            "events": len(node.hg),
            "decided": len(node.consensus),
            "decided_tx": self.decided_tx,
            "undecided_window": node.undecided_window,
            "pending_txs": len(self.pool.pending),
            "recovering": self._recovering(),
            "unclean_start": self.unclean_start,
            "flightrec_dump": self.flightrec_dump,
            "membership_epoch": getattr(node, "membership_epoch", 0),
            "pending_mtx": len(self._pending_mtx),
        }

    def metrics_snapshot(self) -> Dict:
        """Registry snapshot body for :data:`frame.KIND_METRICS` (caller
        holds the lock): the live counters from pool / transport / node
        are synced into the registry as gauges first, so the supervisor
        sees one structured sample stream per node."""
        reg = self.registry
        node = self.node
        for k in sorted(self.pool.counters):
            reg.gauge(k).set(self.pool.counters[k])
        for k in sorted(self.transport.stats):
            reg.gauge(f"net_{k}").set(self.transport.stats[k])
        reg.gauge("node_retries").set(node.retries)
        reg.gauge("node_bad_replies").set(node.bad_replies)
        reg.gauge("node_bad_requests").set(node.bad_requests)
        reg.gauge("node_circuit_opens").set(node.circuit_opens)
        reg.gauge("hg_events").set(len(node.hg))
        reg.gauge("decided_events").set(len(node.consensus))
        reg.gauge("decided_tx").set(self.decided_tx)
        reg.gauge("pending_txs").set(len(self.pool.pending))
        reg.gauge("undecided_window").set(node.undecided_window)
        reg.gauge("wal_torn_tail_recovered").set(
            self.wal.torn_tail_recovered
        )
        reg.gauge("trace_events").set(len(self.tracer.events))
        reg.gauge("membership_epoch").set(
            getattr(node, "membership_epoch", 0))
        reg.gauge("members_active").set(
            getattr(node, "members_active", len(node.members)))
        reg.gauge("stake_total").set(
            getattr(node, "stake_total", node.tot_stake))
        return {
            "node": self.label,
            "index": self.index,
            "samples": reg.to_samples(),
        }

    def _recovering(self) -> bool:
        """Pre-crash tip not yet re-reached: pull-only until it is, so
        this node never signs below its own durable history (the
        amnesia-fork guard the chaos harness pins in-process)."""
        return bool(
            self.wal.events
            and self.node.head != self.wal.events[-1].id
        )

    # ----------------------------------------------------------- main loop

    def _turn(self) -> None:
        """One gossip turn (caller holds the lock)."""
        node = self.node
        peers = [m for m in self.members if m != self.pk]
        peer = self._rng.choice(peers)
        if self._recovering():
            got = node.pull(peer)
            if got:
                node.consensus_pass(got)
        else:
            # a batch is only drained when the sync will actually create
            # an event (sync is a no-op until the peer is known) — a
            # batch fed to a no-op sync would be silently dropped.  A
            # queued membership tx takes the turn's payload slot whole
            # (client batches wait one turn): decode_tx reads the full
            # event payload, so an MTX1 blob can never share an event
            # with a client batch.
            mtx = None
            if not node.member_events[peer]:
                batch = b""
            elif self._pending_mtx:
                mtx = batch = self._pending_mtx.pop(0)
            else:
                batch = self.pool.next_batch()
            prev_head = node.head
            ctx = self._batch_trace(batch)
            if ctx:
                with self.tracer.span_under("gossip.sync", ctx) as sp:
                    sp.args["peer"] = peer[:4].hex()
                    sp.args["batch_bytes"] = len(batch)
                    # snapshot for the transport to stamp onto the
                    # outgoing frames of this turn (read without lock)
                    self._gossip_ctx = self.tracer.active_context() or b""
                    try:
                        self._sync_step(peer, batch)
                    finally:
                        self._gossip_ctx = b""
            else:
                self._sync_step(peer, batch)
            if mtx is not None and node.head == prev_head:
                # the sync minted no event (transport failure, circuit
                # breaker): the membership tx must not vanish — requeue
                # it for the next turn
                self._pending_mtx.insert(0, mtx)
        self._record_decided()

    def _sync_step(self, peer: bytes, batch: bytes) -> None:
        """The durable sync body (caller holds the lock)."""
        node = self.node
        prev_head = node.head
        new_ids = node.sync(peer, batch)
        if node.head != prev_head:
            # durable BEFORE any peer can observe it: the lock is
            # held until after this fsync completes
            self.wal.append(node.hg[node.head])
        if new_ids:
            node.consensus_pass(new_ids)

    def _batch_trace(self, batch: bytes) -> bytes:
        """Submit-span context of the first traced tx in ``batch`` (the
        turn that first gossips a traced submission joins its trace)."""
        if not batch or not self._tx_traces:
            return b""
        for tx in decode_batch(batch):
            ctx = self._tx_traces.get(crypto.hash_bytes(tx))
            if ctx:
                return ctx
        return b""

    def _record_decided(self) -> None:
        """Walk newly decided events; record each decided transaction's
        submission→decided latency (birth known only for txs submitted
        to this node)."""
        node = self.node
        t = frame.now()
        while self._decided_watermark < len(node.consensus):
            eid = node.consensus[self._decided_watermark]
            self._decided_watermark += 1
            for tx in decode_batch(node.hg[eid].d):
                txid = crypto.hash_bytes(tx)
                if txid in self.decided_txids:
                    continue
                self.decided_txids.add(txid)
                self.decided_tx += 1
                self.tracker.record_decided(
                    txid,
                    node.round.get(eid, 0),
                    node.round_received.get(eid, 0),
                    now=t,
                )
                ctx = self._tx_traces.pop(txid, None)
                if ctx is not None:
                    # zero-length marker span closing the trace on the
                    # node that accepted the submission
                    with self.tracer.span_under("tx.decided", ctx) as sp:
                        sp.args["round_received"] = (
                            node.round_received.get(eid, 0)
                        )

    def _checkpoint(self) -> None:
        """Atomic checkpoint + WAL prune (caller holds the lock): after
        ``save_node`` covers everything in the store, only own events
        the store does *not* hold (none, for a live node) stay in the
        WAL — so the WAL is always exactly the tail since the last
        checkpoint."""
        save_node(self.paths["ckpt"], self.node)
        self.wal.rewrite(
            [ev for ev in self.wal.events if ev.id not in self.node.hg]
        )

    def run(self) -> int:
        self.server = NodeServer(
            self.host, self.ports[self.index], self.dispatch,
            int(self.settings["max_frame_bytes"]),
        )
        # readiness marker: the server socket is accepting
        with open(self.paths["ready"], "w") as f:
            json.dump({"index": self.index, "pid": os.getpid()}, f)
        t0 = frame.now()
        interval = float(self.settings["gossip_interval_s"])
        ckpt_every = float(self.settings["checkpoint_every_s"])
        next_ckpt = t0 + ckpt_every
        try:
            while not self.stop.is_set():
                if (
                    self.duration_s is not None
                    and frame.now() - t0 >= float(self.duration_s)
                ):
                    break
                with self.lock:
                    self._turn()
                    if frame.now() >= next_ckpt:
                        self._checkpoint()
                        next_ckpt = frame.now() + ckpt_every
                frame.sleep(interval)
        finally:
            self.server.close()
        with self.lock:
            self._record_decided()
            self._checkpoint()
            self._write_report()
            self._write_trace()
            self.wal.mark_clean()
        self.transport.close()
        return 0

    def _write_trace(self) -> None:
        """Per-process Chrome-trace JSONL shard (caller holds the lock);
        ``obs/cluster_trace.py`` merges one per node + the supervisor's
        client shard into the cluster timeline."""
        self.tracer.save(self.paths["trace"])

    # -------------------------------------------------------------- report

    def _write_report(self) -> None:
        node = self.node
        counters: Dict[str, float] = dict(self.pool.counters)
        counters["wal_torn_tail_recovered"] = self.wal.torn_tail_recovered
        counters.update(
            {f"net_{k}": v for k, v in sorted(self.transport.stats.items())}
        )
        counters["node_retries"] = node.retries
        counters["node_bad_replies"] = node.bad_replies
        counters["node_bad_requests"] = node.bad_requests
        counters["node_circuit_opens"] = node.circuit_opens
        counters["node_equivocations_detected"] = \
            node.equivocations_detected
        counters["node_budget_exhausted"] = node.budget_exhausted
        report = {
            "report_version": REPORT_VERSION,
            "node": self.label,
            "index": self.index,
            "pk": self.pk.hex(),
            "seed": self.seed,
            "trace": self.paths["trace"],
            "trace_events": len(self.tracer.events),
            "trace_dropped": self.tracer.dropped,
            "restored": self.restored,
            "unclean_start": self.unclean_start,
            "flightrec_dump": self.flightrec_dump,
            "decided": [e.hex() for e in node.consensus],
            "decided_tx": self.decided_tx,
            "events": len(node.hg),
            "membership_epoch": getattr(node, "membership_epoch", 0),
            "membership_epochs": (
                len(node.ledger.epochs)
                if hasattr(node, "ledger") else 1
            ),
            "members_active": getattr(
                node, "members_active", len(node.members)),
            "stake_total": getattr(node, "stake_total", node.tot_stake),
            "counters": counters,
            "finality": self.tracker.summary(),
            "ttf_samples": list(self.tracker.ttf),
        }
        with open(self.paths["events"], "wb") as f:
            f.write(
                b"".join(encode_event(node.hg[e]) for e in node.order_added)
            )
        tmp = self.paths["report"] + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        os.replace(tmp, self.paths["report"])


def main(argv: List[str]) -> int:
    if len(argv) != 1:
        raise SystemExit(
            "usage: python -m tpu_swirld.net.node_proc spec.json"
        )
    with open(argv[0]) as f:
        spec = json.load(f)
    return NodeRuntime(spec).run()


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))
