"""Transaction ingestion: submission pool, dedup, batches, backpressure.

The whitepaper's events carry opaque transaction payloads; until now the
sim invented them (``b"tx:%d:%d"``).  This module is the client front
door: :class:`TxPool` admits raw transaction bytes, deduplicates them by
BLAKE2b id, queues them FIFO, and drains them into size-capped *batches*
that ride event payloads through the ordinary gossip path — so a
transaction is decided exactly when the event carrying it reaches its
consensus slot, and submission→decided latency is measurable with the
existing :class:`~tpu_swirld.obs.finality.FinalityTracker`.

Admission control is *backpressure, not buffering*: a node whose
undecided window (events in store minus events decided — the gauge
``node_undecided_window``) exceeds ``max_undecided`` is behind on
consensus, and accepting more transactions only grows an unbounded
queue.  It sheds instead: the submitter gets an explicit ``SHED:window``
reply and retries elsewhere/later.  A full pool (``SHED:pool``) and an
oversized tx (``SHED:oversize``) shed the same way.  Every outcome is a
counted reply the client can parse:

- ``ACK:<txid hex>`` — admitted; will ride the next batch.
- ``DUP:<txid hex>`` — already pending or already batched; idempotent.
- ``SHED:window`` / ``SHED:pool`` / ``SHED:oversize`` — not admitted;
  nothing retained; safe to retry against another node.

Batch wire format (an event payload)::

    b"TXB1" <H count> (<I len> tx)*

``decode_batch`` is total: payloads that are not batches (the sim's
legacy ``b"tx:..."`` strings, a byzantine member's garbage) decode to
``[]`` rather than raising — batch decoding sits on the gossip ingest
path where every byte is adversary-controlled.
"""

from __future__ import annotations

import collections
import struct
from typing import Callable, Dict, List, Optional, Tuple

from tpu_swirld import crypto

BATCH_MAGIC = b"TXB1"
_BHEAD = struct.Struct("<H")
_BLEN = struct.Struct("<I")

#: counter names exported by :attr:`TxPool.counters`
COUNTERS = (
    "tx_submitted", "tx_accepted", "tx_duplicate",
    "tx_shed_window", "tx_shed_pool", "tx_shed_oversize",
    "tx_batches", "tx_batched",
)


def encode_batch(txs: List[bytes]) -> bytes:
    return BATCH_MAGIC + _BHEAD.pack(len(txs)) + b"".join(
        _BLEN.pack(len(tx)) + tx for tx in txs
    )


def decode_batch(payload: bytes) -> List[bytes]:
    """Inverse of :func:`encode_batch`; total (garbage → ``[]``)."""
    if not payload.startswith(BATCH_MAGIC):
        return []
    off = len(BATCH_MAGIC)
    if off + _BHEAD.size > len(payload):
        return []
    (count,) = _BHEAD.unpack_from(payload, off)
    off += _BHEAD.size
    out: List[bytes] = []
    for _ in range(count):
        if off + _BLEN.size > len(payload):
            return []
        (n,) = _BLEN.unpack_from(payload, off)
        off += _BLEN.size
        if off + n > len(payload):
            return []
        out.append(payload[off:off + n])
        off += n
    return out


class TxPool:
    """FIFO submission pool with dedup, size caps, and window shedding.

    Args:
      max_pool: pending-transaction cap (``SHED:pool`` beyond it).
      batch_bytes: max encoded-payload bytes per batch drain.
      max_tx_bytes: per-transaction size cap (``SHED:oversize``).
      max_undecided: undecided-window threshold (``SHED:window``).
      window_fn: zero-arg gauge read (``node.undecided_window``);
        ``None`` disables window shedding (unit tests).
      dedup_cap: decided/batched tx ids remembered for dedup (FIFO
        forgetting — an old id resubmitted after 2^17 successors is
        re-admitted, which is idempotent downstream anyway).
    """

    def __init__(
        self,
        max_pool: int = 4096,
        batch_bytes: int = 64 << 10,
        max_tx_bytes: int = 16 << 10,
        max_undecided: int = 2048,
        window_fn: Optional[Callable[[], int]] = None,
        dedup_cap: int = 1 << 17,
    ):
        self.max_pool = int(max_pool)
        self.batch_bytes = int(batch_bytes)
        self.max_tx_bytes = int(max_tx_bytes)
        self.max_undecided = int(max_undecided)
        self.window_fn = window_fn
        self.pending: "collections.OrderedDict[bytes, bytes]" = (
            collections.OrderedDict()
        )
        self._seen: "collections.OrderedDict[bytes, None]" = (
            collections.OrderedDict()
        )
        self._dedup_cap = int(dedup_cap)
        self.counters: Dict[str, int] = {name: 0 for name in COUNTERS}

    def _remember(self, txid: bytes) -> None:
        self._seen[txid] = None
        while len(self._seen) > self._dedup_cap:
            self._seen.popitem(last=False)

    def submit(self, tx: bytes) -> Tuple[bool, bytes]:
        """Admit one raw transaction; returns ``(accepted, reply)``
        where ``reply`` is the wire answer the submitter sees."""
        self.counters["tx_submitted"] += 1
        if len(tx) > self.max_tx_bytes or not tx:
            self.counters["tx_shed_oversize"] += 1
            return False, b"SHED:oversize"
        txid = crypto.hash_bytes(tx)
        if txid in self.pending or txid in self._seen:
            self.counters["tx_duplicate"] += 1
            return False, b"DUP:" + txid.hex().encode()
        if self.window_fn is not None and (
            self.window_fn() > self.max_undecided
        ):
            self.counters["tx_shed_window"] += 1
            return False, b"SHED:window"
        if len(self.pending) >= self.max_pool:
            self.counters["tx_shed_pool"] += 1
            return False, b"SHED:pool"
        self.pending[txid] = tx
        self.counters["tx_accepted"] += 1
        return True, b"ACK:" + txid.hex().encode()

    def next_batch(self) -> bytes:
        """Drain up to ``batch_bytes`` of pending txs into one encoded
        batch payload (``b""`` when nothing is pending — the caller
        gossips an empty payload exactly like the legacy sim)."""
        if not self.pending:
            return b""
        txs: List[bytes] = []
        size = len(BATCH_MAGIC) + _BHEAD.size
        while self.pending:
            txid, tx = next(iter(self.pending.items()))
            need = _BLEN.size + len(tx)
            if txs and size + need > self.batch_bytes:
                break
            self.pending.popitem(last=False)
            self._remember(txid)
            txs.append(tx)
            size += need
        self.counters["tx_batches"] += 1
        self.counters["tx_batched"] += len(txs)
        return encode_batch(txs)
