"""Socket-level fault injection: a per-link TCP interposer fleet.

The in-process :class:`~tpu_swirld.transport.FaultyTransport` applies a
seeded :class:`~tpu_swirld.transport.FaultPlan` around a function call;
this module applies the SAME plan vocabulary to real TCP connections, so
the PR 3 fault schedule — per-link drop / corrupt / duplicate / reorder
/ delay probabilities and scheduled :class:`~tpu_swirld.transport.
Partition` windows — now exercises the genuine network machinery:
:class:`~tpu_swirld.net.transport.SocketTransport` redials, the node's
``RetryPolicy`` backoff, circuit breakers, and WAL/crash recovery under
actual connection loss.

Topology: one :class:`FaultyProxy` per *directed* link ``src -> dst``
listens on its own ephemeral port and relays length-prefixed frames to
the destination node's real port.  The cluster supervisor hands node
``src`` a ``peer_addrs`` map pointing every peer at the matching link
proxy, so all node-to-node gossip crosses an interposer while the
supervisor's own control plane (submit / status / stop) stays direct.

Fault semantics on a stream (vs the in-process call):

- **partition** — a frame arriving while ``plan.partitioned(src, dst,
  clock())`` holds closes the connection; the caller sees a connection
  error (its retryable plane) until the window heals.
- **drop** — a TCP stream cannot lose one message and stay framed, so a
  dropped request or reply tears the connection down; the caller redials.
- **corrupt** — the frame body is mangled with the exact
  :meth:`FaultyTransport._corrupt` modes (truncate / bit-flip / empty)
  and re-length-prefixed, surfacing as the receiver's documented
  bad-frame or counted-rejection path.
- **duplicate / reorder / delay(prob)** — stale-reply semantics matching
  the in-process transport: replies are stashed per link and swapped in
  for fresh ones, preserving one-reply-per-request framing (the caller's
  idempotent-ingest path absorbs staleness).
- **reset** — hard teardown AFTER the destination processed the request:
  the redial-after-success hazard only a real socket can produce.
- **delay_s / throttle_bps** — real held/paced bytes via the net layer's
  clock seam (:func:`tpu_swirld.net.frame.sleep`).

Every draw comes from a per-directed-link RNG stream keyed
``SeedSequence(plan.seed, spawn_key=(src_i + 1, dst_i + 1))`` — the same
hash-stable construction as the in-process injector, so a link's fault
sequence is a pure function of ``(plan.seed, src, dst, frame#)``.  The
clock is injected (the fleet's default counts wall seconds from
:meth:`ProxyFleet.start_clock` via :func:`frame.now`), so this module
never reads wall time directly and stays SW003-clean.
"""

from __future__ import annotations

import collections
import socket
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_swirld.net import frame
from tpu_swirld.transport import FaultPlan, FaultyTransport

#: reply hold when a ``delay`` fault fires and the plan gives no delay_s
DEFAULT_DELAY_S = 0.05

#: stale replies stashed per link (mirrors FaultyTransport._pending)
STASH_DEPTH = 8

#: upstream connect/read deadline: a wedged destination must not pin a
#: relay thread forever (the caller's own call timeout is shorter)
UPSTREAM_TIMEOUT_S = 30.0


def _recv_raw(sock: socket.socket, max_frame: int) -> bytes:
    """One whole length-prefixed frame body (without the prefix)."""
    (nbytes,) = frame._LEN.unpack(frame.recv_exact(sock, 4))
    if nbytes < 1 or nbytes > max_frame:
        raise frame.FrameError(f"bad relayed frame length {nbytes}")
    return frame.recv_exact(sock, nbytes)


def _send_raw(sock: socket.socket, body: bytes) -> None:
    sock.sendall(frame._LEN.pack(len(body)) + body)


class FaultyProxy:
    """One directed link's TCP interposer.

    Accepts connections on its own listener, relays request frames to
    ``upstream`` and reply frames back, applying the link's
    :class:`LinkFaults` and the plan's partition windows per frame.  All
    connections on the link share one seeded RNG stream and one
    stale-reply stash (lock-guarded), so the fault sequence follows
    frame-arrival order on the link, not per-connection history.
    """

    #: mutable state the accept/relay threads share under ``_lock``
    #: (SW006 lock-discipline): the open-connection roster close() must
    #: sweep, and the stale-reply stash the duplicate/swap faults
    #: exchange across connections.
    GUARDED_ATTRS = frozenset({"_conns", "_stash"})

    def __init__(
        self,
        src_i: int,
        dst_i: int,
        upstream: Tuple[str, int],
        plan: FaultPlan,
        clock: Callable[[], float],
        count: Callable[[str], None],
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame: int = frame.MAX_FRAME_BYTES,
    ):
        self.src_i = src_i
        self.dst_i = dst_i
        self.upstream = upstream
        self.plan = plan
        self.clock = clock
        self._count = count
        self.max_frame = max_frame
        self._rng = np.random.default_rng(
            np.random.SeedSequence(
                plan.seed, spawn_key=(src_i + 1, dst_i + 1),
            )
        )
        self._lock = threading.Lock()
        self._stash: collections.deque = collections.deque(
            maxlen=STASH_DEPTH,
        )
        self._stopping = threading.Event()
        self._conns: List[socket.socket] = []
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr: Tuple[str, int] = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._serve, daemon=True,
        )
        self._accept_thread.start()

    # ----------------------------------------------------------- threads

    def _serve(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return   # listener closed: shutdown
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._relay, args=(conn,), daemon=True,
            ).start()

    def _verdict(self, body_len: int) -> Dict:
        """Sample this frame's fate (lock-held: the RNG and stash are
        shared across every connection the link carries)."""
        lf = self.plan.faults_for(self.src_i, self.dst_i)
        r = self._rng
        return {
            "partitioned": self.plan.partitioned(
                self.src_i, self.dst_i, self.clock(),
            ),
            "drop_req": r.random() < lf.drop,
            "corrupt_req": r.random() < lf.corrupt,
            "drop_rep": r.random() < lf.drop,
            "corrupt_rep": r.random() < lf.corrupt,
            "duplicate": r.random() < lf.duplicate,
            "swap": r.random() < max(lf.reorder, lf.duplicate, lf.delay),
            "delay": r.random() < lf.delay,
            "reset": r.random() < lf.reset,
            "hold_s": lf.delay_s or DEFAULT_DELAY_S,
            "throttle_s": (
                body_len / lf.throttle_bps if lf.throttle_bps > 0 else 0.0
            ),
        }

    def _relay(self, client: socket.socket) -> None:
        upstream: Optional[socket.socket] = None
        try:
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stopping.is_set():
                req = _recv_raw(client, self.max_frame)
                with self._lock:
                    v = self._verdict(len(req))
                    if v["corrupt_req"]:
                        req = FaultyTransport._corrupt(req, self._rng)
                if v["partitioned"]:
                    self._count("partition_blocked")
                    return
                if v["drop_req"]:
                    self._count("drops")
                    return
                if v["corrupt_req"]:
                    self._count("corruptions")
                if v["delay"]:
                    self._count("delays")
                    frame.sleep(v["hold_s"])
                if v["throttle_s"] > 0:
                    self._count("throttled")
                    frame.sleep(v["throttle_s"])
                if not req:
                    return   # corruption emptied the frame: dead link
                if upstream is None:
                    upstream = socket.create_connection(
                        self.upstream, timeout=UPSTREAM_TIMEOUT_S,
                    )
                    upstream.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1,
                    )
                    upstream.settimeout(UPSTREAM_TIMEOUT_S)
                _send_raw(upstream, req)
                rep = _recv_raw(upstream, self.max_frame)
                if v["reset"]:
                    # the destination DID process the request; the caller
                    # sees a torn connection — redial-after-success
                    self._count("resets")
                    return
                if v["drop_rep"]:
                    self._count("drops")
                    return
                with self._lock:
                    if v["corrupt_rep"]:
                        self._count("corruptions")
                        rep = FaultyTransport._corrupt(rep, self._rng)
                    if v["duplicate"]:
                        self._count("duplicates")
                        self._stash.append(rep)
                    if self._stash and v["swap"]:
                        # a previously stashed reply surfaces stale; the
                        # fresh one is stashed in exchange, never lost
                        self._count("reorders")
                        self._stash.append(rep)
                        rep = self._stash.popleft()
                if not rep:
                    return
                # count BEFORE the send: once the caller holds the reply
                # the counter is already visible (stats never lag an
                # observed response)
                self._count("relayed")
                _send_raw(client, rep)
        except (ConnectionError, OSError):
            pass   # either side went away: drop the pair
        finally:
            for s in (client, upstream):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass

    def close(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class ProxyFleet:
    """Every directed link of an ``n_nodes`` cluster, interposed.

    ``upstream_ports[j]`` is node ``j``'s real listener; the fleet
    allocates one proxy port per ordered pair and the supervisor routes
    node ``i``'s view of peer ``j`` through :meth:`addr_for(i, j)
    <addr_for>`.  Partition windows are evaluated against the injected
    ``clock`` (or the fleet's own run-relative seconds clock, armed by
    :meth:`start_clock` — before arming it reads ``-1.0`` so no window
    with a non-negative start can fire during node boot).

    Counters aggregate fleet-wide in :attr:`stats` (``relayed``,
    ``drops``, ``corruptions``, ``delays``, ``duplicates``, ``reorders``,
    ``resets``, ``throttled``, ``partition_blocked``).
    """

    def __init__(
        self,
        plan: FaultPlan,
        n_nodes: int,
        upstream_ports: List[int],
        host: str = "127.0.0.1",
        clock: Optional[Callable[[], float]] = None,
        max_frame: int = frame.MAX_FRAME_BYTES,
    ):
        self.plan = plan
        self.host = host
        self._t0: Optional[float] = None
        self.clock = clock if clock is not None else self._elapsed
        self.stats: Dict[str, int] = collections.defaultdict(int)
        self._stats_lock = threading.Lock()
        self.proxies: Dict[Tuple[int, int], FaultyProxy] = {}
        for i in range(n_nodes):
            for j in range(n_nodes):
                if i == j:
                    continue
                self.proxies[(i, j)] = FaultyProxy(
                    i, j, (host, upstream_ports[j]), plan,
                    clock=self.clock, count=self._count, host=host,
                    max_frame=max_frame,
                )

    def _count(self, name: str) -> None:
        with self._stats_lock:
            self.stats[name] += 1

    def _elapsed(self) -> float:
        return -1.0 if self._t0 is None else frame.now() - self._t0

    def start_clock(self) -> None:
        """Arm the partition clock: window times are seconds from now."""
        self._t0 = frame.now()

    def addr_for(self, src_i: int, dst_i: int) -> Tuple[str, int]:
        """Where node ``src_i`` should dial to reach peer ``dst_i``."""
        return self.proxies[(src_i, dst_i)].addr

    def close(self) -> None:
        for key in sorted(self.proxies):
            self.proxies[key].close()
