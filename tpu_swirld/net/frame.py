"""Length-prefixed TCP framing + the net layer's wall-clock seam.

Wire format (all integers little-endian):

- request:  ``<I nbytes> <B kind> <H len(src_pk)> src_pk [trace] payload``
- reply:    ``<I nbytes> <B status> payload``

``nbytes`` counts everything after the length prefix, so one
``recv_exact(4)`` + ``recv_exact(nbytes)`` pair reads a whole frame.

Trace context (version-gated): when the high bit of the kind byte
(:data:`TRACE_FLAG`) is set, a fixed 16-byte trace context (8-byte trace
id + u64 parent span id, see :mod:`tpu_swirld.obs.tracer`) sits between
``src_pk`` and the payload.  Untraced frames are byte-identical to the
pre-trace wire format, so an old sender interoperates with a new
receiver unchanged; a traced frame hitting an *old* receiver decodes to
an unknown kind (e.g. ``0x81``) and is rejected by the dispatch layer's
documented unknown-kind path — a clean REJECT, never a misparse.
Both directions are bounds-checked against a max-frame knob before any
allocation, so a garbage length prefix from a byzantine peer cannot make
the receiver allocate gigabytes (:class:`FrameError` — an ``OSError``
subclass, i.e. a connection-level failure, never a traceback).

Request *kinds* cover the gossip seam (sync / want — the two
:mod:`tpu_swirld.transport` channels) plus the cluster control plane
(client tx submission, status probes, graceful stop).  Reply *status*
separates the three error planes the in-process :class:`~tpu_swirld.
transport.Transport` already distinguishes: ``STATUS_OK`` carries the
endpoint's reply bytes, ``STATUS_REJECT`` is the endpoint's documented
``ValueError`` rejection (counted as a bad reply by the caller, never
retried), and ``STATUS_ERROR`` is a server-side failure (mapped to
:class:`~tpu_swirld.transport.PeerUnreachable`, retryable).

Wall time: this module also owns the net layer's ONLY direct wall-clock
reads (:func:`now` / :func:`sleep`).  Everything else under ``net/``
calls these, so the SW003 justified-suppression surface stays two lines
wide and the justification is stated where the clock is read.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import List, Tuple

#: request kinds
KIND_SYNC = 1       # gossip sync channel (Transport CHANNEL_SYNC)
KIND_WANT = 2       # gossip want channel (Transport CHANNEL_WANT)
KIND_SUBMIT = 3     # client transaction submission (payload = raw tx)
KIND_STATUS = 4     # JSON status probe (supervisor liveness/watermarks)
KIND_STOP = 5       # graceful shutdown request
KIND_PING = 6       # readiness probe
KIND_METRICS = 7    # registry snapshot poll (supervisor metrics plane)
KIND_MTX = 8        # membership transaction (payload = MTX1 blob; the
                    # node rides it on its next gossip event — dynamic-
                    # membership clusters only)

#: kind-byte high bit: a 16-byte trace context follows src_pk
TRACE_FLAG = 0x80
KIND_MASK = 0x7F

#: wire size of the optional trace context (mirrors obs.tracer)
TRACE_CTX_LEN = 16

#: reply status
STATUS_OK = 0       # payload = endpoint reply bytes
STATUS_REJECT = 1   # endpoint ValueError: counted bad reply, not retried
STATUS_ERROR = 2    # server-side failure: retryable (PeerUnreachable)

#: default ceiling on one frame's body; must admit a max sync reply
#: (config.max_reply_bytes = 16 MiB) plus framing overhead
MAX_FRAME_BYTES = (1 << 24) + (1 << 16)

_REQ_HEAD = struct.Struct("<BH")
_LEN = struct.Struct("<I")


class FrameError(OSError):
    """A malformed or oversized frame: connection-level garbage, torn
    down like any other socket failure (the peer may be byzantine)."""


def now() -> float:
    """Monotonic wall seconds — the net layer's single clock read."""
    return time.monotonic()   # swirld-lint: disable=SW003 -- real socket deadlines and tx latency need wall time; net/ is the deployment edge, outside the logical-time consensus core


def sleep(seconds: float) -> None:
    """Real sleep for gossip pacing and scaled retry backoff."""
    if seconds > 0:
        time.sleep(seconds)   # swirld-lint: disable=SW003 -- real gossip pacing/backoff must block wall time; net/ is the deployment edge, outside the logical-time consensus core


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ``ConnectionError`` on EOF."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 16))
        if not chunk:
            raise ConnectionError(f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_request(
    sock: socket.socket, kind: int, src: bytes, payload: bytes,
    trace: bytes = b"",
) -> None:
    """Send one request frame; a non-empty ``trace`` (exactly
    :data:`TRACE_CTX_LEN` bytes) sets :data:`TRACE_FLAG` on the kind
    byte and rides between ``src`` and ``payload``."""
    if trace:
        if len(trace) != TRACE_CTX_LEN:
            raise ValueError(
                f"trace context must be {TRACE_CTX_LEN} bytes, "
                f"got {len(trace)}"
            )
        body = (_REQ_HEAD.pack(kind | TRACE_FLAG, len(src))
                + src + trace + payload)
    else:
        body = _REQ_HEAD.pack(kind, len(src)) + src + payload
    sock.sendall(_LEN.pack(len(body)) + body)


def recv_request(
    sock: socket.socket, max_frame: int = MAX_FRAME_BYTES,
) -> Tuple[int, bytes, bytes, bytes]:
    """Returns ``(kind, src_pk, payload, trace)`` where ``trace`` is the
    16-byte context for flagged frames, else ``b""``; raises on EOF /
    bad frame."""
    (nbytes,) = _LEN.unpack(recv_exact(sock, 4))
    if nbytes < _REQ_HEAD.size or nbytes > max_frame:
        raise FrameError(f"bad request frame length {nbytes}")
    body = recv_exact(sock, nbytes)
    kind_raw, src_len = _REQ_HEAD.unpack_from(body)
    kind = kind_raw & KIND_MASK
    off = _REQ_HEAD.size + src_len
    if off > len(body):
        raise FrameError(f"request src overruns frame ({src_len} bytes)")
    src = body[_REQ_HEAD.size:off]
    trace = b""
    if kind_raw & TRACE_FLAG:
        if off + TRACE_CTX_LEN > len(body):
            raise FrameError("traced request missing its 16-byte context")
        trace = body[off:off + TRACE_CTX_LEN]
        off += TRACE_CTX_LEN
    payload = body[off:]
    return kind, src, payload, trace


def send_reply(sock: socket.socket, status: int, payload: bytes) -> None:
    body = struct.pack("<B", status) + payload
    sock.sendall(_LEN.pack(len(body)) + body)


def recv_reply(
    sock: socket.socket, max_frame: int = MAX_FRAME_BYTES,
) -> Tuple[int, bytes]:
    """Returns ``(status, payload)``; raises on EOF / bad frame."""
    (nbytes,) = _LEN.unpack(recv_exact(sock, 4))
    if nbytes < 1 or nbytes > max_frame:
        raise FrameError(f"bad reply frame length {nbytes}")
    body = recv_exact(sock, nbytes)
    return body[0], body[1:]


def allocate_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    """``n`` distinct ephemeral ports: bind port 0, read the kernel's
    pick back, release.  All sockets stay open until every port is
    chosen so the kernel cannot hand the same port out twice; parallel
    CI runs each get their own ports and never collide on a hardcoded
    base.  (The usual bind-0 race — another process grabbing the port
    between release and re-bind — is closed by SO_REUSEADDR plus the
    supervisor re-binding immediately.)"""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()
