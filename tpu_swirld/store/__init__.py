"""Tiled slab store: memory-bounded visibility state for streaming consensus.

The batch pipeline materializes ``bool[N, N]`` ancestry/sees slabs — ~10 GB
at BASELINE config 5 scale (256 members / 100k events), which is why that
config was unreachable (VERDICT r05 "event-axis blocking is roadmap text").
DAG-BFT systems scale by never holding the whole DAG's reachability state
resident: they commit and garbage-collect a decided prefix so live state is
proportional to the *undecided frontier* (Bullshark, arxiv 2209.05633;
"DAGs for the Masses", arxiv 2506.13998).  This package brings that memory
model to the device engine:

- :class:`~tpu_swirld.store.archive.SlabArchive` — an append-only,
  checkpointable host-side column archive of *decided* ancestry rows
  (zlib-packed bitmaps; sees rows are derived on fetch from the global
  fork-pair ledger, so only one slab is archived).
- :class:`~tpu_swirld.store.slab.SlabStore` — the fixed tile-budget API
  (``resident_tiles`` / ``spill`` / ``fetch``): accounts the device-resident
  window slabs in ``tile``-sized row/column tiles, spills decided rows into
  the archive, fetches archived rows back (reconstructing fork-aware sees),
  and enforces an optional hard budget.
- :class:`~tpu_swirld.store.streaming.StreamingConsensus` — the streaming
  driver: extends :class:`~tpu_swirld.tpu.pipeline.IncrementalConsensus`
  with bounded-chunk ingest, spill-on-prune / spill-on-rebase, and an
  archive-backed **widening rebase** that re-fetches archived tiles when a
  delta references pruned history (instead of recomputing — or crashing on
  — the full DAG).

Peak resident visibility memory becomes O(window²) instead of O(N²): a
config-5-shaped run completes on CPU under a fixed tile budget, with the
decided-prefix order bit-identical to the Python oracle.
"""

from tpu_swirld.store.archive import SlabArchive  # noqa: F401
from tpu_swirld.store.slab import SlabStore, TileBudgetExceeded  # noqa: F401
from tpu_swirld.store.streaming import StreamingConsensus  # noqa: F401

__all__ = [
    "SlabArchive",
    "SlabStore",
    "TileBudgetExceeded",
    "StreamingConsensus",
]
