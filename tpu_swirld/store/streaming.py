"""Streaming consensus driver: bounded-chunk ingest over the slab store.

:class:`StreamingConsensus` extends :class:`~tpu_swirld.tpu.pipeline.
IncrementalConsensus` with the memory model of prefix-committing DAG-BFT
systems (Bullshark-style garbage collection): device state is bounded by
the **undecided window**, decided rows retire into the
:class:`~tpu_swirld.store.archive.SlabArchive` instead of vanishing, and
the fallback paths *re-fetch archived tiles* instead of recomputing — or
dying on — the full DAG.

What changes relative to the parent driver:

- **Bounded ingest** — any delta is split into ``ingest_chunk``-sized
  pieces (chunk-aligned via :func:`tpu_swirld.packing.chunk_slices`), so a
  cold start over a 100k-event history never triggers a 100k-wide batch
  rebase: the first chunk rebases at chunk scale and the rest stream.
- **Spill on retire** — the ``_on_prune`` / ``_on_roll`` / ``_on_rebase``
  hooks archive every decided ancestry row (full global bitmap,
  compressed) and every retired witness round before the parent driver
  drops them.
- **Widening rebase** — when a delta references pruned history (a parent
  below the prune boundary, a fork pair naming an archived event), the
  driver *widens the window back down* to the referenced index: archived
  ancestry rows are fetched, fork-aware sees is re-derived from the global
  fork-pair ledger, the prefix columns of the retained rows are
  reconstructed from parent rows (``anc(e) ∩ [0, lo) = ∪ anc(parents) ∩
  [0, lo)``), and the ordinary extension pass resumes.  Cost is
  O(widened-window²), not O(N²).
- **Full-rebase fallback stays exact** — round stragglers below the frozen
  vote horizon (which could change a committed fame tally) still take the
  parent's full batch rebase: that is the one detect-or-match case whose
  re-vote genuinely needs committed-round state, and it cannot occur for
  honest gossip traffic (the deterministic expiry horizon / ``n > 3f``).
  It re-fetches nothing and remains O(N²) — the documented corner.

Exactness: identical to the parent contract — every committed output is
bit-identical to a cold batch pass (and the oracle) over the same packed
history, for every ingest schedule.  Widening reconstructs exactly the
state the driver would have had with a lower prune boundary; ancestry and
sees are pure DAG functions, so archived rows equal recomputed rows.
"""

from __future__ import annotations

import collections
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from tpu_swirld import obs
from tpu_swirld.config import resolve_stream_settings
from tpu_swirld.packing import chunk_slices, prepare_events
from tpu_swirld.store.slab import SlabStore
from tpu_swirld.tpu.pipeline import (
    IncrementalConsensus,
    _bucket,
)


class StreamingConsensus(IncrementalConsensus):
    """Memory-bounded streaming driver (see module doc).

    Extra keyword arguments over :class:`IncrementalConsensus`:

    - ``store`` — a :class:`~tpu_swirld.store.slab.SlabStore`; default a
      fresh one built from ``tile_budget`` / ``tile`` / ``strict_budget``.
    - ``tile_budget`` — resident visibility tile budget (None = account
      only); ``strict_budget=True`` raises ``TileBudgetExceeded`` instead
      of counting an overrun.
    - ``ingest_chunk`` — max events per internal pass (rounded up to the
      device scan chunk); bounds both the cold-start rebase width and the
      per-pass extension work.
    """

    def __init__(
        self,
        members,
        stake=None,
        config=None,
        *,
        store: Optional[SlabStore] = None,
        tile_budget: Optional[int] = None,
        tile: int = 256,
        strict_budget: bool = False,
        ingest_chunk: int = 1024,
        **kw,
    ):
        super().__init__(members, stake, config, **kw)
        self.store = (
            store
            if store is not None
            else SlabStore(
                tile_budget, tile=tile, strict=strict_budget,
                config=self.config,
            )
        )
        self._ingest_chunk = _bucket(max(ingest_chunk, 1), self._chunk)
        # decode overlap: pre-hash the NEXT ingest chunk's event ids on a
        # worker thread while the device executes the current one.
        # Results are bit-identical either way — the worker computes a
        # pure function (prepare_events) and every handoff goes through a
        # drain barrier (future.result(), which also re-raises worker
        # failures); all packer mutation stays on the ingest thread.
        _ss = resolve_stream_settings(self.config)
        self._decode_overlap = bool(_ss["decode_overlap"])
        self._decode_depth = max(1, int(_ss["decode_queue_depth"]))
        self._staged: Optional[List] = None  # pre-decoded next chunk
        self.decoded_off_thread = 0          # observability: events decoded
                                             # on the worker
        self._round_hi = 0          # next global round to ledger-retire
        self._widen_answered = False
        self.flightrec_label = "streaming"
        # latency attribution: a pass's decided events are stamped with
        # how the pass got to decide them — pure window residency
        # ("window"), an archive-widening rebase ("widened"), or the full
        # batch fallback ("full"); see IncrementalConsensus._stats
        self._latency_phase = "window"
        self._latency_phase_default = "window"
        self.widen_rebases = 0      # rebases answered by window widening
        self.full_rebases = 0       # rebases that paid the batch pass

    # ---------------------------------------------------- bounded ingest

    def ingest(self, events=()) -> Dict:
        """Split the delta into bounded chunks and stream them through the
        parent pass.  Commit boundaries never influence outputs (the
        parent's contract), so the split is pure memory hygiene: the
        cold-start rebase and every extension pass stay chunk-sized."""
        arch = self.store.archive
        t0 = time.perf_counter()
        stall0 = arch.stall_seconds
        events = list(events)
        if len(events) <= self._ingest_chunk:
            st, n_chunks = super().ingest(events), 1
        else:
            merged: Optional[Dict] = None
            n_chunks = 0
            for chunk_ev in self._chunked_deltas(events):
                st = super().ingest(chunk_ev)
                n_chunks += 1
                if merged is None:
                    merged = st
                else:
                    merged["new_events"] += st["new_events"]
                    merged["ordered"] = merged["ordered"] + st["ordered"]
                    merged["rebased"] = merged["rebased"] or st["rebased"]
                    merged["storm_mode"] = (
                        merged["storm_mode"] or st["storm_mode"]
                    )
                    merged["seconds"] += st["seconds"]
                    for k in ("window_size", "pruned_prefix"):
                        merged[k] = st[k]
            st = merged
        wall = max(time.perf_counter() - t0, 1e-9)
        stall = arch.stall_seconds - stall0
        # overlap ratio: the fraction of the ingest wall during which the
        # driver was computing rather than blocked behind the spill queue
        # (1.0 = archival fully off the critical path)
        overlap = max(0.0, min(1.0, (wall - stall) / wall))
        return self._finish_stats(st, n_chunks, overlap)

    def _finish_stats(self, st: Dict, n_chunks: int, overlap: float) -> Dict:
        self._account()
        arch = self.store.archive
        st["ingest_chunks"] = n_chunks
        st["fuse_chunks"] = self._fuse
        st["decode_overlap"] = self._decode_overlap
        st["resident_bytes"] = self.resident_visibility_bytes
        st["archived_rows"] = arch.n_rows
        st["overlap_ratio"] = round(overlap, 4)
        st["spill_queue_depth"] = arch.pending_batches
        o = obs.current()
        if o is not None:
            g = o.registry
            g.gauge("stream_overlap_ratio").set(st["overlap_ratio"])
            g.gauge("store_spill_queue_depth").set(st["spill_queue_depth"])
        return st

    # ----------------------------------------------------- decode overlap

    def _chunked_deltas(self, events: List):
        """Yield the delta's ingest chunks in order.  With decode overlap
        on, one worker thread runs :func:`~tpu_swirld.packing.
        prepare_events` (event-id hashing — the dominant host decode
        cost) up to ``decode_queue_depth`` chunks ahead of the chunk the
        device is executing.  Each yield first drains the worker's future
        for that chunk (``future.result()`` — the barrier that also
        re-raises any worker failure on the ingest thread) and stages the
        pre-decoded pairs for :meth:`_pack_delta`; the worker never
        touches the packer or any driver state, so async and sync
        ingestion are bit-identical by construction."""
        slices = chunk_slices(len(events), self._ingest_chunk)
        if not (self._decode_overlap and len(slices) > 1):
            for s, e in slices:
                yield events[s:e]
            return
        with ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="swirld-decode"
        ) as ex:
            futs = collections.deque()
            it = iter(slices)

            def submit_next():
                nxt = next(it, None)
                if nxt is not None:
                    futs.append(
                        ex.submit(prepare_events, events[nxt[0]:nxt[1]])
                    )

            for _ in range(min(self._decode_depth, len(slices))):
                submit_next()
            while futs:
                pairs = futs.popleft().result()   # drain barrier
                submit_next()                     # keep the queue full
                self._staged = pairs
                self.decoded_off_thread += len(pairs)
                try:
                    yield [ev for ev, _ in pairs]
                finally:
                    self._staged = None

    def _pack_delta(self, events) -> None:
        # consume the staged pre-decode when it matches this delta; any
        # other path (rebase replays, direct super().ingest calls, the
        # sync fallback) packs — and hashes — on this thread as before
        staged, self._staged = self._staged, None
        if staged is not None and len(staged) == len(events):
            self.packer.extend_prepared(staged)
        else:
            super()._pack_delta(events)

    def _account(self) -> None:
        if not self._initialized:
            return
        s = self.store
        s.account("anc", self._anc_d.shape)
        if self._sees_d is not self._anc_d:
            s.account("sees", self._sees_d.shape)
        else:
            s.drop("sees")
        s.account("ssm", self._ssm_d.shape)

    def _ensure_row_capacity(self, need: int) -> None:
        if need > self._w_pad:
            self._check_budget(self._next_row_pad(need, self._window_bucket))
        super()._ensure_row_capacity(need)

    def _check_budget(self, w_pad: int) -> bool:
        shapes = {
            "anc": (w_pad, w_pad),
            "ssm": (w_pad, self._wcol_cap),
        }
        if self._initialized and self._sees_d is not self._anc_d:
            shapes["sees"] = (w_pad, w_pad)
        return self.store.check(shapes)

    def _materialize_sees(self) -> None:
        # budget the sees slab coming into existence (first fork pair)
        self.store.check({"sees": (self._w_pad, self._w_pad)})
        super()._materialize_sees()
        self._account()

    def _add_columns(self, events) -> None:
        # budget the ssm column-store growth before the parent commits it
        # (shapes predicted with the parent's own _next_col_cap policy)
        if events:
            batch = _bucket(len(events), 16)
            if self._n_cols + batch > self._wcol_cap:
                new_cap = self._next_col_cap(
                    self._n_cols, batch, self._wcol_cap
                )
                self.store.check({"ssm": (self._w_pad, new_cap)})
        super()._add_columns(events)

    def _stats(self, n_new, ordered, t0, *, rebased,
               count_storm=True, storm=False):
        # a widening-answered rebase is the streaming driver's designed
        # cheap success, not a failed incremental attempt — it must not
        # feed the rebase-storm guard (which would flip the driver into
        # full O(N²) batch passes, defeating the memory bound)
        if rebased and self._widen_answered:
            count_storm = False
        self._widen_answered = False
        return super()._stats(
            n_new, ordered, t0, rebased=rebased, count_storm=count_storm,
            storm=storm,
        )

    # -------------------------------------------------- retirement hooks

    def _on_prune(self, d: int, w_used: int) -> None:
        lo = self._lo
        if lo + d <= self.store.archive.n_rows:
            return      # re-prune of rows re-admitted by a widening
        # a lazy device slice, NOT np.asarray: the archive's background
        # worker pulls + packs it off the critical path (the slice is its
        # own buffer, so the donated prune roll that follows is safe)
        rows = self._anc_d[:d, :w_used]
        parents = np.asarray(self.packer.window_view(lo, lo + d)[0])
        self.store.spill(lo, parents, rows)

    def _on_roll(self, dr: int) -> None:
        lo, base = self._lo, self._r_base
        for k in range(dr):
            r = base + k
            if r < self._round_hi:
                continue
            evs, fam, dec = [], [], []
            for s in range(self._s_cap):
                e = int(self._tab_np[k, s])
                if e < 0:
                    continue
                evs.append(lo + e)
                fam.append(int(self._famous_np[k, s]))
                dl = int(self._dec_np[k, s])
                dec.append(base + dl if dl >= 0 else -1)
            self.store.archive.retire_round(r, evs, fam, dec)
        self._round_hi = max(self._round_hi, base + dr)

    def _on_rebase(self, packed, out, aux) -> None:
        """Reconcile the archive with a batch rebase: the batch slab holds
        full global ancestry rows, so newly pruned rows archive without
        reconstruction, and newly committed rounds land in the ledger."""
        arch = self.store.archive
        lo = self._lo
        if lo > arch.n_rows:
            # slice on device: pull only the newly decided rows, not the
            # whole bool[N, N] slab (lazy — the pack worker materializes)
            rows = aux["anc"][arch.n_rows : lo]
            self.store.spill_full(arch.n_rows, rows)
        tabf = out["wit_table"]
        famf = out["famous"].reshape(tabf.shape)
        decf = out["fame_decided_at"].reshape(tabf.shape)
        for r in range(self._round_hi, min(self._r_base, tabf.shape[0])):
            evs, fam, dec = [], [], []
            for s in range(tabf.shape[1]):
                e = int(tabf[r, s])
                if e < 0:
                    continue
                evs.append(e)
                fam.append(int(famf[r, s]))
                dec.append(int(decf[r, s]))
            arch.retire_round(r, evs, fam, dec)
        self._round_hi = max(self._round_hi, self._r_base)

    # ---------------------------------------------------- rebase routing

    def _rebase(self) -> List[int]:
        """Widen-or-full: re-fetch archived tiles when the trigger is a
        pruned-history reference; pay the batch pass only for round
        stragglers below the committed horizon (and cold starts)."""
        if self._initialized and self._storm_left == 0:
            target = self._widen_target()
            if target is not None and self._try_widen(target):
                if not self._needs_rebase_pre():
                    n_new = len(self.packer) - self._n_done
                    ordered, need = self._extend_pass(n_new)
                    if not need:
                        self.widen_rebases += 1
                        self._widen_answered = True
                        self._latency_phase = "widened"
                        o = obs.current()
                        if o is not None:
                            o.registry.counter(
                                "store_widen_rebases_total"
                            ).inc()
                        return ordered
        self.full_rebases += 1
        self._latency_phase = "full"
        return super()._rebase()

    def _widen_target(self) -> Optional[int]:
        """The prune boundary a widening must reach to answer the pending
        delta, or None when only a full batch rebase is exact (late
        genesis, parent rounds below the committed round window)."""
        p = self.packer
        lo, n0, n1 = self._lo, self._n_done, len(p)
        if n1 <= n0:
            return None
        new_par = np.asarray(p.window_view(n0, n1)[0])
        live = new_par >= 0
        if self._r_base > 0 and (~live[:, 0]).any():
            return None                      # late genesis straggler
        lo2 = lo
        if live.any():
            lo2 = min(lo2, int(new_par[live].min()))
        # parent-round horizon via the *global* round mirror — valid for
        # every processed parent, pruned or resident (events chaining to
        # in-delta parents are covered by round monotonicity, exactly as
        # in the parent's _needs_rebase_pre)
        both_old = live[:, 0] & (new_par < n0).all(axis=1)
        if both_old.any():
            pg = np.where(both_old[:, None], new_par, 0)
            r0 = np.maximum(
                self._round_g[pg[:, 0]], self._round_g[pg[:, 1]]
            )
            if int(r0[both_old].min()) < self._r_base:
                return None                  # committed-round straggler
        if p.n_fork_pairs > self._g_done:
            pairs = np.asarray(p.fork_pairs_view(self._g_done))
            lo2 = min(lo2, int(pairs[:, 1:].min()))
        if lo2 >= lo or lo2 < 0:
            return None       # nothing pruned is referenced (mid-pass
        return lo2            # overflow / straggler guard) -> full path

    def _try_widen(self, lo2: int) -> bool:
        """Rebuild the carried window at the lower boundary ``lo2``,
        re-fetching archived ancestry/sees rows and reconstructing the
        retained rows' pruned-prefix columns.  Exact: every re-fetched or
        reconstructed value is a pure DAG function of the same history the
        device originally computed it from."""
        lo, hi = self._lo, self._n_done
        delta = lo - lo2
        arch = self.store.archive
        if lo > arch.n_rows:
            return False                     # archive gap: full rebase
        w_used = hi - lo
        w2 = w_used + delta
        new_pad = max(
            self._w_pad,
            _bucket(w2 + 2 * self._chunk, self._window_bucket),
        )
        self._check_budget(new_pad)          # strict mode raises here
        has_forks = self._fork_np.shape[0] > 0
        # warm the archive's decompression cache while the device pulls
        # below drain — the widening's fetch then hits hot rows
        arch.prefetch(lo2, lo)
        # ---- host pulls of the live window (profiler-counted D2H)
        anc_cur = obs.to_host(self._anc_d)
        sees_cur = obs.to_host(self._sees_d) if has_forks else anc_cur
        ssm_cur = obs.to_host(self._ssm_d)
        # ---- re-fetch archived rows over global columns [lo2, hi),
        # decompressing straight into the widened slab (anc_pre is a view
        # of anc_w — no intermediate delta x w2 copy)
        creators_g = np.asarray(self.packer.window_view(0, hi)[1])
        fp_g = np.asarray(self.packer.fork_pairs_view(0))
        anc_w = np.zeros((new_pad, new_pad), dtype=bool)
        anc_pre, sees_pre = self.store.fetch(
            lo2, lo, lo2, hi,
            creator=creators_g[lo2:hi] if has_forks else None,
            fork_pairs=fp_g,
            n_members=self._m,
            out=anc_w[:delta, :w2],
        )
        # ---- reconstruct the retained rows' prefix columns [lo2, lo):
        # anc(e) ∩ [lo2, lo) = ∪_parents anc(p) ∩ [lo2, lo) for e >= lo
        # (parents below lo2 contribute nothing there — topo order)
        par_g = np.asarray(self.packer.window_view(lo, hi)[0])
        pb = np.zeros((w_used, delta), dtype=bool)
        for i in range(w_used):
            for p in par_g[i]:
                p = int(p)
                if p < lo2:
                    continue
                if p < lo:
                    pb[i] |= anc_pre[p - lo2, :delta]
                else:
                    pb[i] |= pb[p - lo]
        # ---- assemble the widened slabs (prefix rows already in place)
        anc_w[delta : delta + w_used, :delta] = pb
        anc_w[delta : delta + w_used, delta : delta + w_used] = (
            anc_cur[:w_used, :w_used]
        )
        if has_forks:
            sees_w = np.zeros((new_pad, new_pad), dtype=bool)
            sees_w[:delta, :w2] = sees_pre
            sees_w[delta : delta + w_used, delta : delta + w_used] = (
                sees_cur[:w_used, :w_used]
            )
            # fork poisoning of the reconstructed prefix: the one shared
            # implementation of the rule (pairs with a member outside
            # [lo2, hi) cannot poison these rows — their second member is
            # newer than every row here); only the prefix columns are
            # taken, the retained columns keep the device-computed values
            from tpu_swirld.store.archive import SlabArchive

            derived = SlabArchive.derive_sees(
                anc_w[delta : delta + w_used, :w2], lo2,
                creators_g[lo2:hi], fp_g, self._m,
            )
            sees_w[delta : delta + w_used, :delta] = derived[:, :delta]
        # ---- ssm column store: rows shift down; re-admitted rows are
        # never queried (scans read only scanned rows / witness rows)
        ssm_w = np.zeros((new_pad, self._wcol_cap), dtype=bool)
        ssm_w[delta : delta + w_used] = ssm_cur[:w_used]
        # ---- rebuild host mirrors at the widened boundary
        self._w_pad = new_pad
        self._alloc_mirrors(new_pad)
        pg2, cre2, coin2, t2 = self.packer.window_view(lo2, hi)
        pg2 = np.asarray(pg2, dtype=np.int64)
        self._parents_w[:w2] = np.where(pg2 >= lo2, pg2 - lo2, -1)
        self._creator_w[:w2] = cre2
        self._coin_w[:w2] = coin2
        self._t_w[:w2] = t2
        self._rnd_w[:w2] = self._round_g[lo2:hi]
        self._wits_w[:w2] = self._wits_g[lo2:hi]
        self._recv_w[:w2] = self._rr_g[lo2:hi] >= 0
        self._recompute_depth(w2)
        self._rebuild_member_table(w2)
        # vetted fork pairs remapped to lo2 (_g_done untouched: the
        # pending delta's pairs are admitted by the extension pass)
        if self._g_done > 0:
            fp = np.asarray(
                self.packer.fork_pairs_view(0)[: self._g_done],
                dtype=np.int64,
            )
            self._fork_np = np.stack(
                [fp[:, 0], fp[:, 1] - lo2, fp[:, 2] - lo2], axis=1
            ).astype(np.int32)
        else:
            self._fork_np = np.zeros((0, 3), np.int32)
        # witness table entries and column store shift by delta
        self._tab_np = np.where(
            self._tab_np >= 0, self._tab_np + delta, -1
        ).astype(np.int32)
        ce = np.where(
            self._col_events >= 0, self._col_events + delta, -1
        ).astype(np.int32)
        self._col_events = ce
        for pos in range(self._n_cols):
            if ce[pos] >= 0:
                self._colpos_w[ce[pos]] = pos
        # ---- push to device (sees keeps aliasing anc while fork-free);
        # the slab_put seam scatters rows to their owning devices when a
        # mesh driver installed a sharded placement
        self._ars_cache = self._ars_key = None
        self._anc_d = self._put(anc_w)
        self._sees_d = (
            self._put(sees_w) if has_forks else self._anc_d
        )
        self._ssm_d = self._put(ssm_w)
        self._lo = lo2
        self._rows_hi = w2
        self._account()
        return True
