"""The fixed tile-budget API over the resident visibility slabs.

The streaming driver's device state is three window slabs — ancestry
``bool[W, W]``, sees ``bool[W, W]`` (aliasing ancestry, zero extra
bytes, until the first fork pair), and strongly-sees columns
``bool[W, C]``; the extension kernels gather per-member rows straight
from sees, so no separate gather slabs exist.  :class:`SlabStore`
accounts them in ``tile × tile`` tiles, exposes the ``resident_tiles`` /
``spill`` / ``fetch`` surface the driver consumes, and (optionally,
``strict=True``) refuses window growth past ``budget_tiles``: row
capacity, ssm column capacity, sees materialization, and widening
rebases are all budget-checked before they commit.  The one
exempt path is the full-batch rebase fallback (straggler witnesses below
the frozen vote horizon, late genesis): it allocates batch-scale slabs by
design and cannot occur for honest traffic; its footprint still lands in
``peak_resident_*`` after the fact.

``spill`` retires decided rows into the :class:`~tpu_swirld.store.archive.
SlabArchive`; ``fetch`` re-admits archived rows, reconstructing the
fork-aware sees values from the global fork-pair ledger.  Both are exact:
ancestry/sees are pure DAG functions, so a row's archived value equals
what a cold batch pass would recompute.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from tpu_swirld import obs
from tpu_swirld.store.archive import SlabArchive


class TileBudgetExceeded(RuntimeError):
    """Raised (``strict`` mode) when a window growth or widening rebase
    would push the resident slab tiles past the configured budget."""


def _tiles(shape: Tuple[int, ...], tile: int) -> int:
    """Tile count of one slab: product of per-axis ceil(dim / tile) over
    the last two axes, times any leading (member) axes."""
    if not shape:
        return 0
    lead = 1
    for d in shape[:-2]:
        lead *= d
    grid = 1
    for d in shape[-2:]:
        grid *= -(-d // tile)
    return lead * grid


@dataclasses.dataclass
class _Slab:
    shape: Tuple[int, ...]
    itemsize: int

    @property
    def nbytes(self) -> int:
        n = self.itemsize
        for d in self.shape:
            n *= d
        return n


class SlabStore:
    """Tile accounting + budget + archive orchestration (see module doc).

    ``budget_tiles``: total resident visibility tiles allowed (``None`` =
    unbounded, accounting only).  ``strict``: raise
    :class:`TileBudgetExceeded` on a growth that would exceed the budget;
    otherwise the overflow is counted (``budget_overruns``) and the run
    continues — the honest-traffic invariant is asserted by tests, the
    hard stop is an opt-in for deployments that prefer fail-stop to
    swap-death.
    """

    def __init__(
        self,
        budget_tiles: Optional[int] = None,
        *,
        tile: int = 256,
        strict: bool = False,
        archive: Optional[SlabArchive] = None,
        config=None,
        n_shards: int = 1,
        device_budget_tiles: Optional[int] = None,
    ):
        self.tile = int(tile)
        self.budget_tiles = budget_tiles
        self.strict = strict
        self.archive = (
            archive if archive is not None else SlabArchive(config=config)
        )
        self._slabs: Dict[str, _Slab] = {}
        self.budget_overruns = 0
        self.peak_resident_tiles = 0
        self.peak_resident_bytes = 0
        # mesh placement: the window (row) axis of every slab is split
        # evenly over ``n_shards`` devices, so the per-device residency is
        # the tile count of one row shard; ``device_budget_tiles`` bounds
        # that (strict/counted exactly like the global budget)
        self.n_shards = max(1, int(n_shards))
        self.device_budget_tiles = device_budget_tiles
        self.peak_device_tiles = 0

    def close(self) -> None:
        """Flush and stop the archive's background packing worker."""
        self.archive.close()

    # --------------------------------------------------------- accounting

    def account(self, name: str, shape: Tuple[int, ...], itemsize: int = 1):
        """Register/refresh one resident slab's shape (driver calls this
        whenever a slab is (re)allocated or grown)."""
        self._slabs[name] = _Slab(tuple(int(d) for d in shape), itemsize)
        self._touch()

    def drop(self, name: str) -> None:
        """Forget a slab that no longer exists (e.g. ``sees`` while it
        aliases ``anc`` on a fork-free history)."""
        self._slabs.pop(name, None)
        self._touch()

    @property
    def resident_tiles(self) -> int:
        return sum(_tiles(s.shape, self.tile) for s in self._slabs.values())

    @property
    def resident_bytes(self) -> int:
        return sum(s.nbytes for s in self._slabs.values())

    def _shard_shape(self, shape: Tuple[int, ...]) -> Tuple[int, ...]:
        """One device's row shard of a slab: the leading axis split over
        ``n_shards`` (ceil — the last device may hold a short shard, the
        budget is written for the widest one)."""
        if not shape or self.n_shards == 1:
            return shape
        return (-(-shape[0] // self.n_shards),) + tuple(shape[1:])

    @property
    def device_resident_tiles(self) -> int:
        """Resident tiles on the widest device under the row sharding."""
        return sum(
            _tiles(self._shard_shape(s.shape), self.tile)
            for s in self._slabs.values()
        )

    def check(self, prospective: Dict[str, Tuple[int, ...]]) -> bool:
        """Would the slabs, with ``prospective`` shape overrides, fit the
        budget?  In ``strict`` mode an overflow raises; otherwise it is
        counted and ``False`` returned."""
        if self.budget_tiles is None and self.device_budget_tiles is None:
            return True
        total = 0
        dev_total = 0
        for name, slab in self._slabs.items():
            shape = prospective.get(name, slab.shape)
            total += _tiles(shape, self.tile)
            dev_total += _tiles(self._shard_shape(shape), self.tile)
        for name, shape in prospective.items():
            if name not in self._slabs:
                total += _tiles(shape, self.tile)
                dev_total += _tiles(self._shard_shape(shape), self.tile)
        over = []
        if self.budget_tiles is not None and total > self.budget_tiles:
            over.append(
                f"resident slabs would need {total} tiles "
                f"(budget {self.budget_tiles}, tile {self.tile})"
            )
        if (
            self.device_budget_tiles is not None
            and dev_total > self.device_budget_tiles
        ):
            over.append(
                f"per-device shard would need {dev_total} tiles "
                f"(device budget {self.device_budget_tiles}, "
                f"{self.n_shards} shards, tile {self.tile})"
            )
        if not over:
            return True
        self.budget_overruns += 1
        o = obs.current()
        if o is not None:
            o.registry.counter("store_budget_overruns_total").inc()
        if self.strict:
            raise TileBudgetExceeded(
                "; ".join(over) + "; raise the budget or lower the ingest "
                "chunk / prune threshold"
            )
        return False

    def _touch(self) -> None:
        rt, rb = self.resident_tiles, self.resident_bytes
        dt = self.device_resident_tiles
        self.peak_resident_tiles = max(self.peak_resident_tiles, rt)
        self.peak_resident_bytes = max(self.peak_resident_bytes, rb)
        self.peak_device_tiles = max(self.peak_device_tiles, dt)
        o = obs.current()
        if o is not None:
            g = o.registry
            g.gauge("store_resident_tiles").set(rt)
            g.gauge("store_resident_bytes").set(rb)
            if self.n_shards > 1:
                g.gauge("store_device_resident_tiles").set(dt)

    # ------------------------------------------------------ spill / fetch

    def spill(self, lo: int, parents: np.ndarray, rows: np.ndarray) -> int:
        """Retire decided window rows ``[lo, lo + d)`` into the archive
        (see :meth:`SlabArchive.spill`)."""
        added = self.archive.spill(lo, parents, rows)
        o = obs.current()
        if o is not None and added:
            o.registry.counter("store_spilled_rows_total").inc(added)
        return added

    def spill_full(self, start: int, rows: np.ndarray) -> int:
        added = self.archive.spill_full(start, rows)
        o = obs.current()
        if o is not None and added:
            o.registry.counter("store_spilled_rows_total").inc(added)
        return added

    def fetch(
        self,
        lo: int,
        hi: int,
        col_lo: int,
        col_hi: int,
        *,
        creator: Optional[np.ndarray] = None,
        fork_pairs: Optional[np.ndarray] = None,
        n_members: int = 0,
        out: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Re-admit archived rows ``[lo, hi)`` over columns ``[col_lo,
        col_hi)``.  Returns ``(anc_rows, sees_rows)``; sees is derived
        when ``creator`` (global creator index per column) is given, else
        ``None``.  ``out`` decompresses ancestry straight into a caller
        buffer (see :meth:`SlabArchive.fetch`)."""
        anc = self.archive.fetch(lo, hi, col_lo, col_hi, out=out)
        sees = None
        if creator is not None:
            fp = (
                fork_pairs
                if fork_pairs is not None
                else np.zeros((0, 3), np.int32)
            )
            sees = SlabArchive.derive_sees(
                anc, col_lo, creator, fp, n_members
            )
        return anc, sees

    # ------------------------------------------------------------- report

    def stats(self) -> Dict:
        a = self.archive
        return {
            "tile": self.tile,
            "budget_tiles": self.budget_tiles,
            "resident_tiles": self.resident_tiles,
            "resident_bytes": self.resident_bytes,
            "peak_resident_tiles": self.peak_resident_tiles,
            "peak_resident_bytes": self.peak_resident_bytes,
            "n_shards": self.n_shards,
            "device_budget_tiles": self.device_budget_tiles,
            "device_resident_tiles": self.device_resident_tiles,
            "peak_device_tiles": self.peak_device_tiles,
            "budget_overruns": self.budget_overruns,
            "archived_rows": a.n_rows,
            "archive_bytes": a.archive_bytes,
            "spills": a.spills,
            "fetches": a.fetches,
            "spilled_rows": a.spilled_rows,
            "fetched_rows": a.fetched_rows,
            "spill_pack_seconds": round(a.busy_seconds, 4),
            "spill_stall_seconds": round(a.stall_seconds, 4),
            "spill_queue_depth_peak": a.max_queue_depth,
        }
