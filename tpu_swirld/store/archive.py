"""Append-only host-side column archive of decided ancestry rows.

The streaming driver retires (spills) every event below the decided
frontier here; the device keeps only the undecided window resident.  Each
archived row ``e`` is the event's **full global ancestry bitmap** over
columns ``[0, e]`` (reflexive, topo order ⇒ nothing newer is an ancestor),
stored as a zlib-compressed ``np.packbits`` blob — gossip-DAG ancestry rows
are almost-all-ones below a recent horizon, so they compress to a few
percent of the raw ``N²/8`` bytes.

Rows arrive in two shapes:

- :meth:`spill` — *window rows* from the live driver, covering only the
  retained columns ``[lo, hi)``.  The prefix ``[0, lo)`` was pruned from
  the device slab earlier; it is reconstructed exactly from the parents'
  archived rows (``anc(e) ∩ [0, lo) = (anc(p1) ∪ anc(p2)) ∩ [0, lo)``,
  since ``e ≥ lo``) — the rows are appended in topo order, so parents are
  always already archived or earlier in the same batch.
- :meth:`spill_full` — full-width rows straight from a batch rebase's
  ``bool[N, N]`` slab (no reconstruction needed).

Sees rows are **not** archived: ``sees(e, j) = anc(e, j) & ~forkseen(e,
c(j))`` is derived on :meth:`fetch` from the archived ancestry row plus
the global fork-pair ledger (the packer keeps every pair forever, and a
pair discovered after ``e`` was archived cannot poison ``e`` — its second
member is newer than ``e``, so ``e`` never descends from it).  Archiving
one slab instead of two halves the archive.

Background packing
------------------

Packing (device pull + prefix reconstruction + ``packbits`` + zlib) runs
on a **background worker thread** behind a *bounded* spill queue, so the
streaming driver's critical path pays only an enqueue: while the device
extends the window for chunk ``k``, the worker compresses chunk ``k−1``'s
retired rows.  Exactness is preserved by a **drain barrier**: every read
of archived bytes (:meth:`fetch`, :meth:`digest`, :meth:`save`) first
waits for the queue to empty, so the visible archive is always the one a
synchronous spiller would have built — same rows, same blob stream, same
digest.  ``n_rows`` counts *accepted* rows (committed + queued), which is
the contiguity frontier the spiller and the widening rebase reason about.
A full queue blocks the spiller (backpressure, counted in
``stall_seconds``); a worker failure is re-raised on the next archive
operation rather than swallowed.  ``async_spill=False`` (or
``SWIRLD_ARCHIVE_ASYNC=0``) degrades to the fully synchronous behavior —
bit-identical output either way.

The streaming driver's **decode-overlap** worker
(:meth:`tpu_swirld.store.streaming.StreamingConsensus._chunked_deltas`)
is this protocol's ingest-side mirror: a bounded queue of pure
`prepare_events` jobs ahead of the device, a drain barrier at every
handoff (which re-raises worker failures), and a sync fallback that is
bit-identical by construction.  Audit changes to either against both.

Rows decompressed for parent-prefix reconstruction or fetches are kept in
a bounded LRU cache (parents of spilled rows are almost always recent, so
the hit rate is high), and :meth:`prefetch` warms that cache in the
background so a widening rebase's re-fetch overlaps the device pulls that
precede it.

The archive is checkpointable (:meth:`save` / :meth:`load`, no pickle)
and carries a running BLAKE2b digest of the appended blobs; ``load``
verifies it, so a corrupt archive fails loudly at restore time instead of
poisoning a later widening rebase.
"""

from __future__ import annotations

import collections
import queue
import struct
import threading
import time
import zlib
from typing import List, Optional

import numpy as np

from tpu_swirld import crypto, obs
from tpu_swirld.config import resolve_archive_settings

#: LRU capacity (decompressed rows) for the reconstruction/fetch cache
_ROW_CACHE_ENTRIES = 1024

# Schedule-fuzz seam: tpu_swirld.analysis.races installs a yield injector
# here to perturb client/worker interleavings at the tagged points below.
# None in production — each point costs one global read + None check.
_injector = None


def set_injector(inj) -> None:
    global _injector
    _injector = inj


def _yp(tag: str) -> None:
    inj = _injector
    if inj is not None:
        inj.point(tag)


class SlabArchive:
    """Append-only archive of decided ancestry rows (see module doc)."""

    #: archive format version (bump on layout changes)
    FORMAT_VERSION = 1

    #: every mutable attribute the pack worker shares with the client
    #: thread (SW006 lock-discipline): the spill queue itself, the blob
    #: list / byte counter / row cache it packs into behind the drain
    #: barrier, the failure slot, and the busy-time counter.  Audit any
    #: addition here against the queue/barrier protocol in the module doc.
    GUARDED_ATTRS = frozenset({
        "_q", "_rows", "_cache", "_committed_bytes", "_worker_err",
        "busy_seconds",
    })

    def __init__(
        self,
        compress_level: Optional[int] = None,
        *,
        queue_depth: Optional[int] = None,
        async_spill: Optional[bool] = None,
        config=None,
    ):
        s = resolve_archive_settings(config)
        self._rows: List[bytes] = []       # zlib(packbits(row over [0, e]))
        self._rounds: List[tuple] = []     # retired-round ledger
        self._level = (
            compress_level if compress_level is not None
            else s["compress_level"]
        )
        self.queue_depth = (
            queue_depth if queue_depth is not None else s["queue_depth"]
        )
        self._async = (
            async_spill if async_spill is not None else s["async_spill"]
        )
        self.spills = 0                    # spill batches accepted
        self.fetches = 0                   # fetch calls served
        self.spilled_rows = 0              # rows newly archived (accepted)
        self.fetched_rows = 0              # rows decompressed for callers
        self.skipped_rows = 0              # re-spills of already-archived rows
        self._n_accepted = 0               # committed + queued rows
        self._committed_bytes = 0
        self._cache: "collections.OrderedDict[int, np.ndarray]" = (
            collections.OrderedDict()
        )
        # background packing worker (lazily started on first async spill)
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._worker_err: Optional[BaseException] = None
        self.busy_seconds = 0.0            # worker time spent packing
        self.stall_seconds = 0.0           # caller time blocked on the queue
        self.max_queue_depth = 0           # high-water mark of queued batches

    # ------------------------------------------------------------- basics

    @property
    def n_rows(self) -> int:
        """Archived prefix length: rows ``[0, n_rows)`` are archived (or
        accepted into the spill queue — the drain barrier makes the
        distinction unobservable to readers)."""
        return self._n_accepted

    @property
    def committed_rows(self) -> int:
        """Rows physically packed (``n_rows`` minus the queue backlog)."""
        return len(self._rows)

    @property
    def archive_bytes(self) -> int:
        """Total compressed payload bytes currently committed (queued
        batches land here once the worker packs them)."""
        return self._committed_bytes

    @property
    def pending_batches(self) -> int:
        return self._q.qsize() if self._q is not None else 0

    def _row_bool(self, e: int) -> np.ndarray:
        """Decompress row ``e`` to a bool[e + 1] ancestry bitmap (LRU
        cached — parents of spilled rows and widening re-fetches are
        heavily repeated)."""
        cached = self._cache.get(e)
        if cached is not None:
            self._cache.move_to_end(e)
            return cached
        _yp("archive.cache.miss")
        raw = np.frombuffer(zlib.decompress(self._rows[e]), dtype=np.uint8)
        row = np.unpackbits(raw, count=e + 1).astype(bool)
        row.flags.writeable = False
        self._cache[e] = row
        if len(self._cache) > _ROW_CACHE_ENTRIES:
            self._cache.popitem(last=False)
        return row

    def _append_bool(self, row: np.ndarray) -> None:
        _yp("archive.append")
        blob = zlib.compress(np.packbits(row).tobytes(), self._level)
        self._rows.append(blob)
        self._committed_bytes += len(blob)

    # ------------------------------------------------- background worker

    def _make_queue(self, maxsize: int) -> queue.Queue:
        """Seam for analysis.races: the sanitized subclass returns a queue
        whose internal lock participates in the lock-order graph."""
        return queue.Queue(maxsize=maxsize)

    def _ensure_worker(self) -> queue.Queue:
        if self._q is None:
            self._q = self._make_queue(max(1, int(self.queue_depth)))
            self._worker = threading.Thread(
                target=self._worker_loop, name="slab-archive-pack",
                daemon=True,
            )
            self._worker.start()
        return self._q

    def _worker_loop(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                _yp("archive.worker.item")
                t0 = time.perf_counter()
                kind, args = item
                if kind == "spill":
                    self._pack_window_rows(*args)
                elif kind == "spill_full":
                    self._pack_full_rows(*args)
                elif kind == "prefetch":
                    lo, hi = args
                    for e in range(max(0, lo), min(hi, len(self._rows))):
                        self._row_bool(e)
                self.busy_seconds += time.perf_counter() - t0
            except BaseException as exc:  # re-raised at the next barrier
                if self._worker_err is None:
                    self._worker_err = exc
            finally:
                self._q.task_done()

    def _drain(self) -> None:
        """Barrier: wait until every queued batch is packed, then re-raise
        any worker failure.  All reads of archived content go through
        here, so async and sync spilling are observationally identical."""
        _yp("archive.drain")
        if self._q is not None and (
            self._q.unfinished_tasks or not self._q.empty()
        ):
            t0 = time.perf_counter()
            self._q.join()
            self.stall_seconds += time.perf_counter() - t0
        if self._worker_err is not None:
            err, self._worker_err = self._worker_err, None
            raise RuntimeError("archive pack worker failed") from err

    def _enqueue(self, item) -> None:
        q = self._ensure_worker()
        _yp("archive.enqueue")
        self.max_queue_depth = max(self.max_queue_depth, q.qsize() + 1)
        o = obs.current()
        if o is not None:
            o.registry.gauge("store_spill_queue_depth").set(q.qsize() + 1)
        if q.full():
            t0 = time.perf_counter()
            q.put(item)
            self.stall_seconds += time.perf_counter() - t0
        else:
            q.put(item)

    def close(self) -> None:
        """Stop the worker after packing everything queued (idempotent)."""
        if self._q is not None:
            self._drain()
            self._q.put(None)
            self._worker.join()
            self._q = None
            self._worker = None

    # -------------------------------------------------------------- spill

    def spill(self, lo: int, parents, rows) -> int:
        """Archive window rows for global events ``[lo, lo + d)``.

        ``rows`` is bool[d, w] over retained columns ``[lo, lo + w)``
        (numpy or a lazily-materialized device array — async mode pulls it
        on the worker, off the caller's critical path); ``parents`` is the
        int32[d, 2] *global* parent indices of those events (-1 genesis).
        Rows already archived (``e < n_rows`` — possible after a widening
        rebase re-admitted them) are skipped: ancestry is a pure DAG
        function, so the archived copy is already the exact value.
        Returns the number of rows newly accepted.
        """
        d = int(rows.shape[0])
        if lo + d <= self.n_rows or d == 0:
            self.skipped_rows += d
            return 0
        if lo > self.n_rows:
            raise ValueError(
                f"non-contiguous spill: rows [{lo}, {lo + d}) after "
                f"{self.n_rows}"
            )
        added = lo + d - self.n_rows
        self.skipped_rows += d - added
        self._n_accepted = lo + d
        if self._async:
            self._enqueue(("spill", (lo, np.asarray(parents), rows)))
        else:
            self._pack_window_rows(lo, np.asarray(parents), rows)
        self.spills += 1
        self.spilled_rows += added
        self._record_gauges()
        return added

    def _pack_window_rows(self, lo: int, parents: np.ndarray, rows) -> None:
        rows = np.asarray(rows)
        for i in range(rows.shape[0]):
            e = lo + i
            if e < len(self._rows):
                continue
            if e != len(self._rows):
                raise ValueError(
                    f"non-contiguous spill: row {e} after {len(self._rows)}"
                )
            full = np.zeros(e + 1, dtype=bool)
            # pruned-prefix columns [0, lo) come from the parents' rows
            # (earlier-archived, or appended earlier in this same batch);
            # retained columns [lo, e] come straight from the device slab,
            # which already includes the parent closure there
            for p in parents[i]:
                p = int(p)
                if p < 0:
                    continue
                cut = min(p + 1, lo)
                if cut > 0:
                    full[:cut] |= self._row_bool(p)[:cut]
            full[lo : e + 1] = rows[i, : e - lo + 1]
            self._append_bool(full)

    def spill_full(self, start: int, rows) -> int:
        """Archive full-width rows for global events ``[start, start+d)``
        from a batch slab (bool[d, n] over global columns ``[0, n)``)."""
        d = int(rows.shape[0])
        if start + d <= self.n_rows or d == 0:
            self.skipped_rows += d
            return 0
        if start > self.n_rows:
            raise ValueError(
                f"non-contiguous spill: rows [{start}, {start + d}) after "
                f"{self.n_rows}"
            )
        added = start + d - self.n_rows
        self.skipped_rows += d - added
        self._n_accepted = start + d
        if self._async:
            self._enqueue(("spill_full", (start, rows)))
        else:
            self._pack_full_rows(start, rows)
        self.spills += 1
        self.spilled_rows += added
        self._record_gauges()
        return added

    def _pack_full_rows(self, start: int, rows) -> None:
        rows = np.asarray(rows)
        for i in range(rows.shape[0]):
            e = start + i
            if e < len(self._rows):
                continue
            if e != len(self._rows):
                raise ValueError(
                    f"non-contiguous spill: row {e} after {len(self._rows)}"
                )
            self._append_bool(rows[i, : e + 1])

    # -------------------------------------------------------------- fetch

    def prefetch(self, lo: int, hi: int) -> None:
        """Warm the decompressed-row cache for rows ``[lo, hi)`` in the
        background (best-effort: a no-op in sync mode or beyond the
        committed prefix).  A widening rebase calls this before its device
        pulls so decompression overlaps them."""
        if not self._async or hi <= lo:
            return
        lo = max(lo, hi - _ROW_CACHE_ENTRIES)   # cache-bounded window
        self._enqueue(("prefetch", (lo, hi)))
        o = obs.current()
        if o is not None:
            o.registry.counter("store_prefetches_total").inc()

    def fetch(
        self, lo: int, hi: int, col_lo: int, col_hi: int,
        out: "np.ndarray" = None,
    ) -> np.ndarray:
        """Re-admit archived ancestry rows ``[lo, hi)`` over columns
        ``[col_lo, col_hi)`` as a dense bool matrix (zero beyond each
        row's own index — topo order).  Drains the spill queue first.
        ``out`` decompresses straight into a caller buffer (e.g. the
        widening rebase's assembled slab, which ``slab_put`` then
        scatters to the mesh) instead of allocating an intermediate —
        must be bool, ``(hi - lo, col_hi - col_lo)``, zero-filled."""
        if hi > self.n_rows:
            raise ValueError(
                f"fetch [{lo}, {hi}) exceeds archived prefix {self.n_rows}"
            )
        self._drain()
        o = obs.current()
        span = (
            o.tracer.span("store.archive_fetch") if o is not None
            else _NULL_CTX
        )
        with span:
            if out is None:
                out = np.zeros((hi - lo, col_hi - col_lo), dtype=bool)
            elif out.shape != (hi - lo, col_hi - col_lo):
                raise ValueError(
                    f"out shape {out.shape} != "
                    f"{(hi - lo, col_hi - col_lo)}"
                )
            for i, e in enumerate(range(lo, hi)):
                row = self._row_bool(e)
                a = min(col_hi, e + 1)
                if a > col_lo:
                    out[i, : a - col_lo] = row[col_lo:a]
        self.fetches += 1
        self.fetched_rows += hi - lo
        if o is not None:
            o.registry.counter("store_fetches_total").inc()
            o.registry.counter("store_fetched_rows_total").inc(hi - lo)
        return out

    @staticmethod
    def derive_sees(
        anc_rows: np.ndarray,
        col_lo: int,
        creator: np.ndarray,
        fork_pairs: np.ndarray,
        n_members: int,
    ) -> np.ndarray:
        """Fork-aware visibility for fetched rows: ``sees = anc &
        ~forkseen[:, creator(col)]``.

        ``anc_rows`` is bool[d, c] over global columns ``[col_lo, col_lo +
        c)``; ``creator`` the global creator indices of those columns;
        ``fork_pairs`` the **global** int32[G, 3] ledger.  Pairs with a
        member outside the column span cannot poison these rows (the
        fetched rows never descend from anything outside ``[0, col_lo +
        c)``, and members below ``col_lo`` were below every archived
        row's own pruned prefix — the packer pins pairs above the prune
        boundary, so the span always covers every applicable pair).
        """
        d, c = anc_rows.shape
        fseen = np.zeros((d, n_members), dtype=bool)
        for m, a, b in fork_pairs:
            a, b = int(a) - col_lo, int(b) - col_lo
            if 0 <= a < c and 0 <= b < c:
                fseen[:, int(m)] |= anc_rows[:, a] & anc_rows[:, b]
        return anc_rows & ~fseen[:, creator]

    # ------------------------------------------------------- round ledger

    # The witness-round ledger mirrors the visibility archive at round
    # granularity: when the driver rolls a fame-complete round out of its
    # retained window, the row lands here (global round, witness event
    # indices in registration order, famous flags, decided_at).  It is
    # report/checkpoint metadata — the widening rebase never re-votes
    # committed rounds (a straggler below the frozen horizon takes the
    # full-rebase path instead).

    def retire_round(
        self, rnd: int, events, famous, decided_at
    ) -> None:
        self._rounds.append(
            (int(rnd), list(map(int, events)), list(map(int, famous)),
             list(map(int, decided_at)))
        )

    @property
    def retired_rounds(self) -> int:
        return len(self._rounds)

    # --------------------------------------------------------- checkpoint

    def digest(self) -> str:
        """BLAKE2b over the blob stream (order-sensitive).  Drains the
        spill queue first so the digest covers every accepted row."""
        self._drain()
        h = b""
        for b in self._rows:
            h = crypto.hash_bytes(h + crypto.hash_bytes(b))
        return h.hex()

    def save(self, path: str) -> None:
        """Single ``.npz``, no pickle: length-prefixed blob stream +
        round ledger + digest.  Drains the spill queue first (a
        checkpoint taken while spills are in flight persists them)."""
        self._drain()
        blob = b"".join(
            struct.pack("<I", len(b)) + b for b in self._rows
        )
        rounds = self._rounds
        rmeta = []
        rflat: List[int] = []
        for rnd, evs, fam, dec in rounds:
            rmeta.append((rnd, len(evs)))
            for e, f, dc in zip(evs, fam, dec):
                rflat.extend((e, f, dc))
        # write through a file object: np.savez_compressed appends ".npz"
        # to bare string paths, which would break save(p)/load(p) round
        # trips for any other suffix
        with open(path, "wb") as f:
            np.savez_compressed(
                f,
                format_version=self.FORMAT_VERSION,
                n_rows=len(self._rows),
                blobs=np.frombuffer(blob, dtype=np.uint8),
                round_meta=np.asarray(rmeta, dtype=np.int64).reshape(-1, 2),
                round_flat=np.asarray(rflat, dtype=np.int64),
                digest=np.frombuffer(self.digest().encode(), dtype=np.uint8),
            )

    @classmethod
    def load(cls, path: str) -> "SlabArchive":
        """Restore and **verify**: a digest mismatch (tampered or corrupt
        archive) raises ``ValueError`` instead of silently feeding wrong
        ancestry into a later widening rebase."""
        z = np.load(path)
        if int(z["format_version"]) != cls.FORMAT_VERSION:
            raise ValueError(
                f"unsupported archive version {int(z['format_version'])}"
            )
        arch = cls()
        blob = z["blobs"].tobytes()
        off = 0
        while off < len(blob):
            (ln,) = struct.unpack_from("<I", blob, off)
            off += 4
            arch._rows.append(blob[off : off + ln])
            off += ln
        arch._n_accepted = len(arch._rows)
        arch._committed_bytes = sum(len(b) for b in arch._rows)
        if arch.n_rows != int(z["n_rows"]):
            raise ValueError(
                f"archive truncated: {arch.n_rows} rows, header says "
                f"{int(z['n_rows'])}"
            )
        want = z["digest"].tobytes().decode()
        got = arch.digest()
        if got != want:
            raise ValueError(
                "archive digest mismatch (corrupt or tampered checkpoint)"
            )
        rmeta = z["round_meta"]
        rflat = z["round_flat"]
        pos = 0
        for rnd, cnt in rmeta:
            evs, fam, dec = [], [], []
            for _ in range(int(cnt)):
                e, f, dc = rflat[pos : pos + 3]
                evs.append(int(e))
                fam.append(int(f))
                dec.append(int(dc))
                pos += 3
            arch.retire_round(int(rnd), evs, fam, dec)
        return arch

    # ---------------------------------------------------------------- obs

    def _record_gauges(self) -> None:
        o = obs.current()
        if o is None:
            return
        g = o.registry
        g.gauge("store_archived_rows").set(self.n_rows)
        g.counter("store_spills_total").inc()


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_CTX = _NullCtx()
