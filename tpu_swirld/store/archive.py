"""Append-only host-side column archive of decided ancestry rows.

The streaming driver retires (spills) every event below the decided
frontier here; the device keeps only the undecided window resident.  Each
archived row ``e`` is the event's **full global ancestry bitmap** over
columns ``[0, e]`` (reflexive, topo order ⇒ nothing newer is an ancestor),
stored as a zlib-compressed ``np.packbits`` blob — gossip-DAG ancestry rows
are almost-all-ones below a recent horizon, so they compress to a few
percent of the raw ``N²/8`` bytes.

Rows arrive in two shapes:

- :meth:`spill` — *window rows* from the live driver, covering only the
  retained columns ``[lo, hi)``.  The prefix ``[0, lo)`` was pruned from
  the device slab earlier; it is reconstructed exactly from the parents'
  archived rows (``anc(e) ∩ [0, lo) = (anc(p1) ∪ anc(p2)) ∩ [0, lo)``,
  since ``e ≥ lo``) — the rows are appended in topo order, so parents are
  always already archived or earlier in the same batch.
- :meth:`spill_full` — full-width rows straight from a batch rebase's
  ``bool[N, N]`` slab (no reconstruction needed).

Sees rows are **not** archived: ``sees(e, j) = anc(e, j) & ~forkseen(e,
c(j))`` is derived on :meth:`fetch` from the archived ancestry row plus
the global fork-pair ledger (the packer keeps every pair forever, and a
pair discovered after ``e`` was archived cannot poison ``e`` — its second
member is newer than ``e``, so ``e`` never descends from it).  Archiving
one slab instead of two halves the archive.

The archive is checkpointable (:meth:`save` / :meth:`load`, no pickle)
and carries a running BLAKE2b digest of the appended blobs; ``load``
verifies it, so a corrupt archive fails loudly at restore time instead of
poisoning a later widening rebase.
"""

from __future__ import annotations

import struct
import zlib
from typing import List

import numpy as np

from tpu_swirld import crypto, obs


class SlabArchive:
    """Append-only archive of decided ancestry rows (see module doc)."""

    #: archive format version (bump on layout changes)
    FORMAT_VERSION = 1

    def __init__(self, compress_level: int = 1):
        self._rows: List[bytes] = []       # zlib(packbits(row over [0, e]))
        self._rounds: List[tuple] = []     # retired-round ledger
        self._level = compress_level
        self.spills = 0                    # spill batches accepted
        self.fetches = 0                   # fetch calls served
        self.spilled_rows = 0              # rows newly archived
        self.fetched_rows = 0              # rows decompressed for callers
        self.skipped_rows = 0              # re-spills of already-archived rows

    # ------------------------------------------------------------- basics

    @property
    def n_rows(self) -> int:
        """Archived prefix length: rows ``[0, n_rows)`` are archived."""
        return len(self._rows)

    @property
    def archive_bytes(self) -> int:
        """Total compressed payload bytes currently held."""
        return sum(len(b) for b in self._rows)

    def _row_bool(self, e: int) -> np.ndarray:
        """Decompress row ``e`` to a bool[e + 1] ancestry bitmap."""
        raw = np.frombuffer(zlib.decompress(self._rows[e]), dtype=np.uint8)
        return np.unpackbits(raw, count=e + 1).astype(bool)

    def _append_bool(self, row: np.ndarray) -> None:
        self._rows.append(
            zlib.compress(np.packbits(row).tobytes(), self._level)
        )

    # -------------------------------------------------------------- spill

    def spill(
        self, lo: int, parents: np.ndarray, rows: np.ndarray
    ) -> int:
        """Archive window rows for global events ``[lo, lo + d)``.

        ``rows`` is bool[d, w] over retained columns ``[lo, lo + w)``;
        ``parents`` is the int32[d, 2] *global* parent indices of those
        events (-1 genesis).  Rows already archived (``e < n_rows`` —
        possible after a widening rebase re-admitted them) are skipped:
        ancestry is a pure DAG function, so the archived copy is already
        the exact value.  Returns the number of rows newly archived.
        """
        d = rows.shape[0]
        if lo + d <= self.n_rows or d == 0:
            self.skipped_rows += d
            return 0
        added = 0
        for i in range(d):
            e = lo + i
            if e < self.n_rows:
                self.skipped_rows += 1
                continue
            if e != self.n_rows:
                raise ValueError(
                    f"non-contiguous spill: row {e} after {self.n_rows}"
                )
            full = np.zeros(e + 1, dtype=bool)
            # pruned-prefix columns [0, lo) come from the parents' rows
            # (earlier-archived, or appended earlier in this same batch);
            # retained columns [lo, e] come straight from the device slab,
            # which already includes the parent closure there
            for p in parents[i]:
                p = int(p)
                if p < 0:
                    continue
                cut = min(p + 1, lo)
                if cut > 0:
                    full[:cut] |= self._row_bool(p)[:cut]
            full[lo : e + 1] = rows[i, : e - lo + 1]
            self._append_bool(full)
            added += 1
        self.spills += 1
        self.spilled_rows += added
        self._record_gauges()
        return added

    def spill_full(self, start: int, rows: np.ndarray) -> int:
        """Archive full-width rows for global events ``[start, start+d)``
        from a batch slab (bool[d, n] over global columns ``[0, n)``)."""
        added = 0
        for i in range(rows.shape[0]):
            e = start + i
            if e < self.n_rows:
                self.skipped_rows += 1
                continue
            if e != self.n_rows:
                raise ValueError(
                    f"non-contiguous spill: row {e} after {self.n_rows}"
                )
            self._append_bool(rows[i, : e + 1])
            added += 1
        if added:
            self.spills += 1
            self.spilled_rows += added
            self._record_gauges()
        return added

    # -------------------------------------------------------------- fetch

    def fetch(
        self, lo: int, hi: int, col_lo: int, col_hi: int
    ) -> np.ndarray:
        """Re-admit archived ancestry rows ``[lo, hi)`` over columns
        ``[col_lo, col_hi)`` as a dense bool matrix (zero beyond each
        row's own index — topo order)."""
        if hi > self.n_rows:
            raise ValueError(
                f"fetch [{lo}, {hi}) exceeds archived prefix {self.n_rows}"
            )
        out = np.zeros((hi - lo, col_hi - col_lo), dtype=bool)
        for i, e in enumerate(range(lo, hi)):
            row = self._row_bool(e)
            a = min(col_hi, e + 1)
            if a > col_lo:
                out[i, : a - col_lo] = row[col_lo:a]
        self.fetches += 1
        self.fetched_rows += hi - lo
        o = obs.current()
        if o is not None:
            o.registry.counter("store_fetches_total").inc()
            o.registry.counter("store_fetched_rows_total").inc(hi - lo)
        return out

    @staticmethod
    def derive_sees(
        anc_rows: np.ndarray,
        col_lo: int,
        creator: np.ndarray,
        fork_pairs: np.ndarray,
        n_members: int,
    ) -> np.ndarray:
        """Fork-aware visibility for fetched rows: ``sees = anc &
        ~forkseen[:, creator(col)]``.

        ``anc_rows`` is bool[d, c] over global columns ``[col_lo, col_lo +
        c)``; ``creator`` the global creator indices of those columns;
        ``fork_pairs`` the **global** int32[G, 3] ledger.  Pairs with a
        member outside the column span cannot poison these rows (the
        fetched rows never descend from anything outside ``[0, col_lo +
        c)``, and members below ``col_lo`` were below every archived
        row's own pruned prefix — the packer pins pairs above the prune
        boundary, so the span always covers every applicable pair).
        """
        d, c = anc_rows.shape
        fseen = np.zeros((d, n_members), dtype=bool)
        for m, a, b in fork_pairs:
            a, b = int(a) - col_lo, int(b) - col_lo
            if 0 <= a < c and 0 <= b < c:
                fseen[:, int(m)] |= anc_rows[:, a] & anc_rows[:, b]
        return anc_rows & ~fseen[:, creator]

    # ------------------------------------------------------- round ledger

    # The witness-round ledger mirrors the visibility archive at round
    # granularity: when the driver rolls a fame-complete round out of its
    # retained window, the row lands here (global round, witness event
    # indices in registration order, famous flags, decided_at).  It is
    # report/checkpoint metadata — the widening rebase never re-votes
    # committed rounds (a straggler below the frozen horizon takes the
    # full-rebase path instead).

    def retire_round(
        self, rnd: int, events, famous, decided_at
    ) -> None:
        self._rounds.append(
            (int(rnd), list(map(int, events)), list(map(int, famous)),
             list(map(int, decided_at)))
        )

    @property
    def retired_rounds(self) -> int:
        return len(self._rounds)

    # --------------------------------------------------------- checkpoint

    def digest(self) -> str:
        """BLAKE2b over the blob stream (order-sensitive)."""
        h = b""
        for b in self._rows:
            h = crypto.hash_bytes(h + crypto.hash_bytes(b))
        return h.hex()

    def save(self, path: str) -> None:
        """Single ``.npz``, no pickle: length-prefixed blob stream +
        round ledger + digest."""
        blob = b"".join(
            struct.pack("<I", len(b)) + b for b in self._rows
        )
        rounds = self._rounds
        rmeta = []
        rflat: List[int] = []
        for rnd, evs, fam, dec in rounds:
            rmeta.append((rnd, len(evs)))
            for e, f, dc in zip(evs, fam, dec):
                rflat.extend((e, f, dc))
        # write through a file object: np.savez_compressed appends ".npz"
        # to bare string paths, which would break save(p)/load(p) round
        # trips for any other suffix
        with open(path, "wb") as f:
            np.savez_compressed(
                f,
                format_version=self.FORMAT_VERSION,
                n_rows=self.n_rows,
                blobs=np.frombuffer(blob, dtype=np.uint8),
                round_meta=np.asarray(rmeta, dtype=np.int64).reshape(-1, 2),
                round_flat=np.asarray(rflat, dtype=np.int64),
                digest=np.frombuffer(self.digest().encode(), dtype=np.uint8),
            )

    @classmethod
    def load(cls, path: str) -> "SlabArchive":
        """Restore and **verify**: a digest mismatch (tampered or corrupt
        archive) raises ``ValueError`` instead of silently feeding wrong
        ancestry into a later widening rebase."""
        z = np.load(path)
        if int(z["format_version"]) != cls.FORMAT_VERSION:
            raise ValueError(
                f"unsupported archive version {int(z['format_version'])}"
            )
        arch = cls()
        blob = z["blobs"].tobytes()
        off = 0
        while off < len(blob):
            (ln,) = struct.unpack_from("<I", blob, off)
            off += 4
            arch._rows.append(blob[off : off + ln])
            off += ln
        if arch.n_rows != int(z["n_rows"]):
            raise ValueError(
                f"archive truncated: {arch.n_rows} rows, header says "
                f"{int(z['n_rows'])}"
            )
        want = z["digest"].tobytes().decode()
        got = arch.digest()
        if got != want:
            raise ValueError(
                "archive digest mismatch (corrupt or tampered checkpoint)"
            )
        rmeta = z["round_meta"]
        rflat = z["round_flat"]
        pos = 0
        for rnd, cnt in rmeta:
            evs, fam, dec = [], [], []
            for _ in range(int(cnt)):
                e, f, dc = rflat[pos : pos + 3]
                evs.append(int(e))
                fam.append(int(f))
                dec.append(int(dc))
                pos += 3
            arch.retire_round(int(rnd), evs, fam, dec)
        return arch

    # ---------------------------------------------------------------- obs

    def _record_gauges(self) -> None:
        o = obs.current()
        if o is None:
            return
        g = o.registry
        g.gauge("store_archived_rows").set(self.n_rows)
        g.gauge("store_archive_bytes").set(self.archive_bytes)
        g.counter("store_spills_total").inc()
