"""In-process multi-node gossip simulation harness.

The reference achieves "multi-node without a cluster" by keeping every node
in one interpreter and routing gossip through a dict of bound ``ask_sync``
methods (SURVEY.md §4).  Same pattern here, formalized: deterministic seeded
peer selection, a shared logical clock, and a byzantine fork-injecting
adversary (BASELINE.json config 4).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.node import Node


@dataclasses.dataclass
class Simulation:
    """A population of in-process nodes plus the shared gossip 'network'."""

    config: SwirldConfig
    nodes: List[Node]
    network: Dict[bytes, Callable]
    rng: random.Random
    clock: List[int]

    @property
    def members(self) -> List[bytes]:
        return [n.pk for n in self.nodes]

    def tick(self) -> int:
        self.clock[0] += 1
        return self.clock[0]

    def step(self, node_i: Optional[int] = None) -> List[bytes]:
        """One gossip turn: a (random) node syncs with a random other peer
        and runs the consensus pass.  Returns the new event ids."""
        if node_i is None:
            node_i = self.rng.randrange(len(self.nodes))
        node = self.nodes[node_i]
        peers = [pk for pk in self.members if pk != node.pk]
        peer = peers[self.rng.randrange(len(peers))]
        payload = b"tx:%d:%d" % (node_i, self.clock[0])
        new_ids = node.sync(peer, payload)
        node.consensus_pass(new_ids)
        return new_ids

    def run(self, n_turns: int) -> None:
        for _ in range(n_turns):
            self.step()

    def run_until_events(self, n_events: int, max_turns: int = 10**7) -> None:
        """Gossip until some node knows >= n_events events."""
        turns = 0
        while max(len(n.hg) for n in self.nodes) < n_events:
            self.step()
            turns += 1
            if turns > max_turns:
                raise RuntimeError("simulation did not reach target events")


def make_simulation(
    n_nodes: int,
    seed: int = 0,
    config: Optional[SwirldConfig] = None,
) -> Simulation:
    """Build keypairs, the shared network dict, and N nodes (the reference's
    ``test(n_nodes, n_turns)`` setup)."""
    config = config or SwirldConfig(n_members=n_nodes, seed=seed)
    if config.n_members != n_nodes:
        raise ValueError("config.n_members != n_nodes")
    rng = random.Random(seed)
    keys = [crypto.keypair(b"member-%d-%d" % (seed, i)) for i in range(n_nodes)]
    members = [pk for pk, _ in keys]
    network: Dict[bytes, Callable] = {}
    clock = [0]
    nodes: List[Node] = []
    for pk, sk in keys:
        node = Node(
            sk=sk,
            pk=pk,
            network=network,
            members=members,
            config=config,
            clock=lambda: clock[0],
        )
        network[pk] = node.ask_sync
        nodes.append(node)
    sim = Simulation(config=config, nodes=nodes, network=network, rng=rng, clock=clock)
    # shared logical clock advances every turn so timestamps vary
    orig_step = sim.step

    def step_with_tick(node_i: Optional[int] = None):
        sim.tick()
        return orig_step(node_i)

    sim.step = step_with_tick  # type: ignore[method-assign]
    return sim


def test(n_nodes: int, n_turns: int, seed: int = 0) -> Simulation:
    """The reference's module-level smoke-test driver."""
    sim = make_simulation(n_nodes, seed=seed)
    sim.run(n_turns)
    return sim


class ForkingAdversary:
    """Byzantine members that fork: they occasionally create TWO events with
    the same self-parent and gossip different branches to different peers
    (BASELINE.json config 4: f forkers out of n).

    The adversary drives a forker's key directly (it doesn't use the honest
    ``Node.sync`` path for its own event creation), injecting its forked
    events into honest nodes via their public ``ask_sync``-fed event feed —
    here simulated by direct insertion through a crafted sync reply.
    """

    def __init__(self, sim: Simulation, forker_indices: List[int], fork_every: int = 5):
        self.sim = sim
        self.forkers = forker_indices
        self.fork_every = max(1, fork_every)
        self._count = 0

    def maybe_fork(self) -> None:
        """Every ``fork_every`` calls, one forker creates a fork pair."""
        self._count += 1
        if self._count % self.fork_every:
            return
        fi = self.forkers[self._count // self.fork_every % len(self.forkers)]
        node = self.sim.nodes[fi]
        if node.head is None or not node.hg[node.head].p:
            return
        head_ev = node.hg[node.head]
        others = [pk for pk in self.sim.members if pk != node.pk]
        op = None
        for pk in others:
            if node.member_events[pk]:
                op = node.member_events[pk][-1]
                break
        if op is None or op == head_ev.other_parent:
            return
        # a sibling of the current head: same self-parent, different other-parent
        sibling = Event(
            d=b"fork", p=(head_ev.self_parent, op), t=node._now(), c=node.pk
        ).signed(node.sk)
        try:
            node.add_event(sibling)
            node.divide_rounds([sibling.id])
        except (ValueError, AssertionError):
            return


def run_with_forkers(
    n_nodes: int,
    n_forkers: int,
    n_turns: int,
    seed: int = 0,
    fork_every: int = 7,
) -> Simulation:
    """Config-4-style run: honest gossip with periodic fork injection."""
    sim = make_simulation(n_nodes, seed=seed)
    adversary = ForkingAdversary(sim, list(range(n_forkers)), fork_every)
    for _ in range(n_turns):
        sim.step()
        adversary.maybe_fork()
    return sim


def generate_gossip_dag(
    n_members: int,
    n_events: int,
    seed: int = 0,
    stake: Optional[List[int]] = None,
):
    """Directly synthesize a valid random-gossip DAG (no per-node stores).

    Produces the same *shape* of history as the in-process sim — per-member
    self-chains stitched by random cross-member other-parents — but in
    O(n_events) work, so BASELINE configs 3+ (64 members / 10k events) can
    be generated in seconds.  Used by ``bench.py`` and the graft entry.

    Returns ``(members, stake, events, keys)`` with ``events`` in topo
    order and ``keys`` the (pk, sk) pairs (so callers can build observer or
    member nodes for the same population).
    """
    rng = random.Random(seed)
    keys = [crypto.keypair(b"dag-%d-%d" % (seed, i)) for i in range(n_members)]
    members = [pk for pk, _ in keys]
    stake = list(stake) if stake is not None else [1] * n_members
    events: List[Event] = []
    heads: List[Event] = []
    t = 0
    for pk, sk in keys:
        t += 1
        ev = Event(d=b"", p=(), t=t, c=pk).signed(sk)
        events.append(ev)
        heads.append(ev)
    while len(events) < n_events:
        ci = rng.randrange(n_members)
        pi = rng.randrange(n_members - 1)
        if pi >= ci:
            pi += 1
        pk, sk = keys[ci]
        t += 1
        ev = Event(
            d=b"tx:%d" % len(events),
            p=(heads[ci].id, heads[pi].id),
            t=t,
            c=pk,
        ).signed(sk)
        events.append(ev)
        heads[ci] = ev
    return members, stake, events, keys
