"""In-process multi-node gossip simulation harness.

The reference achieves "multi-node without a cluster" by keeping every node
in one interpreter and routing gossip through a dict of bound ``ask_sync``
methods (SURVEY.md §4).  Same pattern here, formalized: deterministic seeded
peer selection, a shared logical clock, and a byzantine fork-injecting
adversary (BASELINE.json config 4).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Tuple

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.metrics import Metrics
from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.node import Node
from tpu_swirld.transport import Transport


def attach_obs(
    node: Node, metrics=None, tracer=None, finality=None, flightrec=None,
    label: Optional[str] = None,
) -> None:
    """Wire observability into one node.

    ``metrics``: a shared :class:`~tpu_swirld.metrics.Metrics` instance
    (all nodes aggregate into one registry), ``True`` for a fresh per-node
    ``Metrics()``, or ``None`` to leave disabled.  ``tracer``: a
    :class:`~tpu_swirld.obs.Tracer` shared by every node it is given to
    (spans carry no node id — pass one tracer per node for per-node
    timelines), or ``None``.

    ``finality``: ``True`` builds a per-node
    :class:`~tpu_swirld.obs.finality.FinalityTracker` on the node's own
    logical clock (engine ``"oracle"``, registry shared via ``metrics``
    when given), or pass a prebuilt tracker.  Trackers are per-node state
    (gossip first-arrival dedup, decided watermarks) even when they share
    one registry.  ``flightrec``: a shared
    :class:`~tpu_swirld.obs.flightrec.FlightRecorder`; the node's ingest
    digests land in its ring under ``label`` and the circuit breaker's
    open transitions fire ``breaker_open`` triggers.  ``label`` defaults
    to a pk prefix.
    """
    if label is None:
        label = "n-" + node.pk[:4].hex()
    if metrics:            # falsy (None/False) means disabled
        node.metrics = Metrics() if metrics is True else metrics
    if tracer:
        node.tracer = tracer
    if finality:
        if finality is True:
            from tpu_swirld.obs.finality import FinalityTracker

            registry = (
                node.metrics.registry
                if node.metrics is not None else None
            )
            finality = FinalityTracker(
                "oracle", clock=node._clock, registry=registry,
            )
        node.finality = finality
        node.flightrec_label = label
    if flightrec:
        from tpu_swirld.obs.flightrec import wire_node

        wire_node(node, flightrec, label)


def member_keys(n_nodes: int, seed: int = 0) -> List[Tuple[bytes, bytes]]:
    """Deterministic member keypairs for ``(n_nodes, seed)`` — the ONE
    key-derivation rule (see :class:`Population`).  Factored out so a
    cluster node process (:mod:`tpu_swirld.net.node_proc`), holding only
    its index and the shared seed, derives the same identities as the
    in-process harnesses and the oracle replay."""
    return [
        crypto.keypair(b"member-%d-%d" % (seed, i)) for i in range(n_nodes)
    ]


@dataclasses.dataclass
class Population:
    """Shared bootstrap of a gossip population: deterministic member
    keys, the endpoint dicts, the logical clock, the transport, and the
    seeded RNG.  This is the ONE place key derivation lives —
    :func:`make_simulation`, :func:`run_with_divergent_forkers`, and the
    chaos harness all build on it, so checkpoints and oracle replays
    always agree on member identities for a given seed."""

    keys: List[Tuple[bytes, bytes]]
    members: List[bytes]
    network: Dict[bytes, Callable]
    network_want: Dict[bytes, Callable]
    clock: List[int]
    transport: Transport
    rng: random.Random


def build_population(
    n_nodes: int,
    seed: int = 0,
    transport_factory: Optional[Callable] = None,
) -> Population:
    """Derive keys and wire the (initially empty) gossip network.

    ``transport_factory(network, network_want, members, clock)`` builds
    the delivery layer; default is the reliable in-process
    :class:`~tpu_swirld.transport.Transport`.
    """
    rng = random.Random(seed)
    keys = member_keys(n_nodes, seed)
    members = [pk for pk, _ in keys]
    network: Dict[bytes, Callable] = {}
    network_want: Dict[bytes, Callable] = {}
    clock = [0]
    if transport_factory is not None:
        transport = transport_factory(
            network, network_want, members, lambda: clock[0]
        )
    else:
        transport = Transport(network, network_want)
    return Population(
        keys=keys, members=members, network=network,
        network_want=network_want, clock=clock, transport=transport, rng=rng,
    )


@dataclasses.dataclass
class Simulation:
    """A population of in-process nodes plus the shared gossip 'network'."""

    config: SwirldConfig
    nodes: List[Node]
    network: Dict[bytes, Callable]
    rng: random.Random
    clock: List[int]
    transport: Optional[Transport] = None

    @property
    def members(self) -> List[bytes]:
        return [n.pk for n in self.nodes]

    def tick(self) -> int:
        self.clock[0] += 1
        return self.clock[0]

    def step(self, node_i: Optional[int] = None) -> List[bytes]:
        """One gossip turn: a (random) node syncs with a random other peer
        and runs the consensus pass.  Returns the new event ids."""
        if node_i is None:
            node_i = self.rng.randrange(len(self.nodes))
        node = self.nodes[node_i]
        peers = [pk for pk in self.members if pk != node.pk]
        peer = peers[self.rng.randrange(len(peers))]
        payload = b"tx:%d:%d" % (node_i, self.clock[0])
        new_ids = node.sync(peer, payload)
        node.consensus_pass(new_ids)
        return new_ids

    def run(self, n_turns: int) -> None:
        for _ in range(n_turns):
            self.step()

    def run_until_events(self, n_events: int, max_turns: int = 10**7) -> None:
        """Gossip until some node knows >= n_events events."""
        turns = 0
        while max(len(n.hg) for n in self.nodes) < n_events:
            self.step()
            turns += 1
            if turns > max_turns:
                raise RuntimeError("simulation did not reach target events")


def make_simulation(
    n_nodes: int,
    seed: int = 0,
    config: Optional[SwirldConfig] = None,
    metrics=None,
    tracer=None,
    transport_factory: Optional[Callable] = None,
    finality=None,
    flightrec=None,
) -> Simulation:
    """Build keypairs, the shared network dict, and N nodes (the reference's
    ``test(n_nodes, n_turns)`` setup).

    ``metrics=`` / ``tracer=`` (see :func:`attach_obs`) wire gossip counters
    and phase spans into every node at construction time — no post-hoc
    patching.  Pass one shared ``Metrics`` to aggregate the population's
    gossip traffic into a single registry.  ``finality=True`` gives every
    node its own lifecycle tracker on the shared logical clock (merged
    into the ``metrics`` registry when given); ``flightrec=`` shares one
    :class:`~tpu_swirld.obs.flightrec.FlightRecorder` across the
    population (rings keyed ``n0..n{N-1}``).

    ``transport_factory(network, network_want, members, clock)`` builds the
    shared delivery layer (default: the reliable in-process
    :class:`~tpu_swirld.transport.Transport`); pass a
    :class:`~tpu_swirld.transport.FaultyTransport` builder to inject
    network faults into an otherwise-ordinary simulation.
    """
    config = config or SwirldConfig(n_members=n_nodes, seed=seed)
    if config.n_members != n_nodes:
        raise ValueError("config.n_members != n_nodes")
    pop = build_population(n_nodes, seed, transport_factory)
    clock = pop.clock
    nodes: List[Node] = []
    for i, (pk, sk) in enumerate(pop.keys):
        node = Node(
            sk=sk,
            pk=pk,
            network=pop.network,
            members=pop.members,
            config=config,
            clock=lambda: clock[0],
            network_want=pop.network_want,
            transport=pop.transport,
        )
        attach_obs(
            node, metrics, tracer, finality=finality, flightrec=flightrec,
            label=f"n{i}",
        )
        pop.network[pk] = node.ask_sync
        pop.network_want[pk] = node.ask_events
        nodes.append(node)
    sim = Simulation(
        config=config, nodes=nodes, network=pop.network, rng=pop.rng,
        clock=clock, transport=pop.transport,
    )
    # shared logical clock advances every turn so timestamps vary
    orig_step = sim.step

    def step_with_tick(node_i: Optional[int] = None):
        sim.tick()
        return orig_step(node_i)

    sim.step = step_with_tick  # type: ignore[method-assign]
    return sim


def test(n_nodes: int, n_turns: int, seed: int = 0) -> Simulation:
    """The reference's module-level smoke-test driver."""
    sim = make_simulation(n_nodes, seed=seed)
    sim.run(n_turns)
    return sim


class ForkingAdversary:
    """*Consistent-order* fork injection: a forker occasionally creates a
    sibling of its own head (same self-parent) in its own store, whence
    both branches propagate to every peer through the same honest
    ``ask_sync`` path in one arrival order.

    This exercises fork *detection and tolerance* on a DAG every node sees
    identically.  It does NOT create divergent per-peer views — for the
    byzantine equivocation case (different branches served to different
    peers) use :class:`DivergentForker` / :func:`run_with_divergent_forkers`.
    """

    def __init__(self, sim: Simulation, forker_indices: List[int], fork_every: int = 5):
        self.sim = sim
        self.forkers = forker_indices
        self.fork_every = max(1, fork_every)
        self._count = 0

    def maybe_fork(self) -> None:
        """Every ``fork_every`` calls, one forker creates a fork pair."""
        self._count += 1
        if self._count % self.fork_every:
            return
        fi = self.forkers[self._count // self.fork_every % len(self.forkers)]
        node = self.sim.nodes[fi]
        if node.head is None or not node.hg[node.head].p:
            return
        head_ev = node.hg[node.head]
        others = [pk for pk in self.sim.members if pk != node.pk]
        op = None
        for pk in others:
            if node.member_events[pk]:
                op = node.member_events[pk][-1]
                break
        if op is None or op == head_ev.other_parent:
            return
        # a sibling of the current head: same self-parent, different other-parent
        sibling = Event(
            d=b"fork", p=(head_ev.self_parent, op), t=node._now(), c=node.pk
        ).signed(node.sk)
        try:
            node.add_event(sibling)
            node.divide_rounds([sibling.id])
        except (ValueError, AssertionError):
            return


def run_with_forkers(
    n_nodes: int,
    n_forkers: int,
    n_turns: int,
    seed: int = 0,
    fork_every: int = 7,
    metrics=None,
    tracer=None,
    transport_factory: Optional[Callable] = None,
) -> Simulation:
    """Config-4-style run: honest gossip with periodic fork injection.
    ``metrics=`` / ``tracer=`` as in :func:`make_simulation` — fork-pair
    detections land in ``gossip_fork_pairs_detected``.  The adversary
    injects forks into its own store, so fork *propagation* rides
    whatever transport the sim was built with — pass a faulty
    ``transport_factory`` to compose byzantine + network faults."""
    sim = make_simulation(
        n_nodes, seed=seed, metrics=metrics, tracer=tracer,
        transport_factory=transport_factory,
    )
    adversary = ForkingAdversary(sim, list(range(n_forkers)), fork_every)
    for _ in range(n_turns):
        sim.step()
        adversary.maybe_fork()
    return sim


class DivergentForker:
    """A genuinely equivocating byzantine member: it maintains TWO branch
    views of its own chain and serves *different branches to different
    peers* through its public ``ask_sync`` / ``ask_events`` endpoints
    (BASELINE config 4's adversary model).

    Each branch is a full honest :class:`Node` sharing the forker's key;
    peers are pinned to a branch on first contact.  ``step()`` advances
    both branches: each pulls from a random honest peer (receiving real
    gossip) and extends its own self-chain — producing fork pairs at every
    sequence number.  Honest nodes first receive one branch, later learn
    of the other through third parties (orphan + want-list recovery), and
    must detect the fork and converge without crashing.
    """

    def __init__(
        self,
        sk: bytes,
        pk: bytes,
        members: List[bytes],
        network: Dict[bytes, Callable],
        network_want: Dict[bytes, Callable],
        config: SwirldConfig,
        clock: Callable[[], int],
        rng: random.Random,
        transport: Optional[Transport] = None,
    ):
        self.pk = pk
        self.sk = sk
        self.rng = rng
        # the branch nodes ride the same transport as honest members, so
        # byzantine equivocation composes with injected network faults
        # (drops/partitions hit the forker's pulls too)
        self.branches = [
            Node(
                sk=sk, pk=pk, network=network, members=members,
                config=config, clock=clock, network_want=network_want,
                transport=transport,
            )
            for _ in range(2)
        ]
        # both branches created the identical deterministic genesis; track
        # per-branch heads explicitly (ingesting the sibling branch back
        # from honest gossip must not move a branch's own tip)
        self._heads = [br.head for br in self.branches]
        self._route: Dict[bytes, int] = {}

    def _branch_for(self, peer_pk: bytes) -> Node:
        b = self._route.get(peer_pk)
        if b is None:
            b = len(self._route) % 2
            self._route[peer_pk] = b
        return self.branches[b]

    def ask_sync(self, from_pk: bytes, req: bytes) -> bytes:
        return self._branch_for(from_pk).ask_sync(from_pk, req)

    def ask_events(self, from_pk: bytes, req: bytes) -> bytes:
        return self._branch_for(from_pk).ask_events(from_pk, req)

    def step(self, honest_peers: List[bytes]) -> None:
        """Advance both branches: pull real gossip, extend the fork."""
        for bi, br in enumerate(self.branches):
            peer = honest_peers[self.rng.randrange(len(honest_peers))]
            try:
                br.pull(peer)
            except ValueError:
                pass
            op = br.member_events[peer][-1] if br.member_events[peer] else None
            if op is None:
                continue
            ev = Event(
                d=b"branch:%d:%d" % (bi, len(br.hg)),
                p=(self._heads[bi], op),
                t=br._now(),
                c=self.pk,
            ).signed(self.sk)
            br.add_event(ev)
            self._heads[bi] = ev.id


@dataclasses.dataclass
class DivergentSimulation:
    """Honest nodes + equivocating forkers sharing one gossip network."""

    config: SwirldConfig
    nodes: List[Node]                  # honest nodes only
    forkers: List[DivergentForker]
    network: Dict[bytes, Callable]
    rng: random.Random
    clock: List[int]
    members: List[bytes]
    transport: Optional[Transport] = None


def run_with_divergent_forkers(
    n_nodes: int,
    n_forkers: int,
    n_turns: int,
    seed: int = 0,
    fork_every: int = 3,
    node_config: Optional[Callable[[int, SwirldConfig], SwirldConfig]] = None,
    on_turn: Optional[Callable[[int, List[Node]], None]] = None,
    metrics=None,
    tracer=None,
    transport_factory: Optional[Callable] = None,
) -> DivergentSimulation:
    """Config-4 adversary model: ``n_forkers`` equivocating members serving
    divergent branches; honest nodes must stay live and prefix-consistent
    (within the BFT bound ``n > 3f``).

    ``node_config(i, base)`` may override an honest member's config (e.g.
    switch one node to ``backend="tpu"``); ``on_turn(turn, honest_nodes)``
    runs after every gossip turn (checkpoint hooks, assertions, ...).
    ``metrics=`` / ``tracer=`` (see :func:`attach_obs`) instrument the
    *honest* nodes — the adversary's branch nodes stay unobserved.
    ``transport_factory`` as in :func:`make_simulation`: honest nodes AND
    the forkers' branch nodes all route through the one transport, so
    byzantine and network faults compose in one scenario.
    """
    config = SwirldConfig(n_members=n_nodes, seed=seed)
    pop = build_population(n_nodes, seed, transport_factory)
    rng, members, clock = pop.rng, pop.members, pop.clock
    network, network_want, transport = (
        pop.network, pop.network_want, pop.transport
    )
    forkers: List[DivergentForker] = []
    honest: List[Node] = []
    for i, (pk, sk) in enumerate(pop.keys):
        if i < n_forkers:
            f = DivergentForker(
                sk, pk, members, network, network_want, config,
                lambda: clock[0], rng, transport=transport,
            )
            network[pk] = f.ask_sync
            network_want[pk] = f.ask_events
            forkers.append(f)
        else:
            cfg_i = node_config(i, config) if node_config else config
            node = Node(
                sk=sk, pk=pk, network=network, members=members,
                config=cfg_i, clock=lambda: clock[0],
                network_want=network_want, transport=transport,
            )
            attach_obs(node, metrics, tracer)
            network[pk] = node.ask_sync
            network_want[pk] = node.ask_events
            honest.append(node)
    honest_pks = [n.pk for n in honest]
    for turn in range(n_turns):
        clock[0] += 1
        node = honest[rng.randrange(len(honest))]
        peers = [pk for pk in members if pk != node.pk]
        peer = peers[rng.randrange(len(peers))]
        new_ids = node.sync(peer, b"tx:%d" % turn)
        node.consensus_pass(new_ids)
        if turn % fork_every == 0:
            for f in forkers:
                f.step(honest_pks)
        if on_turn is not None:
            on_turn(turn, honest)
    return DivergentSimulation(
        config=config, nodes=honest, forkers=forkers, network=network,
        rng=rng, clock=clock, members=members, transport=transport,
    )


def make_straggler_event(
    node: Node,
    pk: bytes,
    sk: bytes,
    *,
    at_round: int,
    payload: bytes = b"straggler",
) -> Event:
    """Forge the event a lagging member's stale tail produces: an event by
    ``pk`` whose parents sit deep in ``node``'s history, landing as a
    WITNESS at (roughly) ``at_round`` — typically far below the committed
    frontier, i.e. the deterministic-expiry-horizon corner.

    Self-parent: ``pk``'s earliest event with round < ``at_round``;
    other-parent: the earliest event by another member with round exactly
    ``at_round`` (so the new event's round is ``at_round`` + at most one
    promotion, and exceeds the self-parent's round — the witness
    condition).  When ``pk``'s real chain continued past the chosen
    self-parent this is also a fork pair, exactly as an equivocating or
    amnesiac member would produce.  Raises ``ValueError`` when the DAG has
    no suitable parents yet.
    """
    sp = None
    for eid in node.member_events[pk]:
        if node.round[eid] < at_round:
            sp = eid
            break
    if sp is None:
        raise ValueError(f"{pk[:4].hex()} has no event below round {at_round}")
    op = None
    for eid in node.order_added:
        ev = node.hg[eid]
        if ev.c != pk and node.round[eid] == at_round:
            op = eid
            break
    if op is None:
        raise ValueError(f"no other-member event at round {at_round}")
    t = max(node.hg[sp].t, node.hg[op].t) + 1
    return Event(d=payload, p=(sp, op), t=t, c=pk).signed(sk)


def chunked_ingest_schedule(
    events,
    chunk_size: int,
    *,
    delay_prob: float = 0.0,
    max_delay: int = 3,
    seed: int = 0,
):
    """Split a topo-ordered event stream into ingest chunks.

    With ``delay_prob`` > 0, individual events are held back by up to
    ``max_delay`` chunks (children are always pulled along so every chunk
    stays topologically valid) — an orphan-heavy/straggler arrival
    schedule for exercising :class:`tpu_swirld.tpu.pipeline.
    IncrementalConsensus` window-exit paths (events referencing old
    parents force its documented full-recompute fallbacks).
    Returns a list of event lists, each in topo order.
    """
    rng = random.Random(seed)
    idx = {ev.id: j for j, ev in enumerate(events)}
    chunk_of = [0] * len(events)
    for j, ev in enumerate(events):
        c = j // chunk_size
        if delay_prob and rng.random() < delay_prob:
            c += rng.randrange(1, max_delay + 1)
        for p in ev.p:
            c = max(c, chunk_of[idx[p]])
        chunk_of[j] = c
    n_chunks = max(chunk_of) + 1 if events else 0
    out: List[List[Event]] = [[] for _ in range(n_chunks)]
    for j, ev in enumerate(events):
        out[chunk_of[j]].append(ev)
    return out


def stream_gossip_dag(
    n_members: int,
    n_events: int,
    chunk: int,
    seed: int = 0,
    stake: Optional[List[int]] = None,
    n_forkers: int = 0,
    fork_prob: float = 0.05,
):
    """Streaming variant of :func:`generate_gossip_dag`: returns
    ``(members, stake, keys, chunks)`` where ``chunks`` is a *generator*
    of topo-ordered event lists of size ``chunk``.

    Identical event stream to :func:`generate_gossip_dag` for the same
    arguments (same RNG call pattern), but host memory stays
    O(members + chunk): only the per-member branch heads are retained, so
    a config-5-shaped feed (256 members / 100k events) never holds the
    full history — the shape ``bench.py --stream`` ingests.
    """
    rng = random.Random(seed)
    keys = [crypto.keypair(b"dag-%d-%d" % (seed, i)) for i in range(n_members)]
    members = [pk for pk, _ in keys]
    stake = list(stake) if stake is not None else [1] * n_members

    def chunks():
        branches: List[List[Event]] = []
        buf: List[Event] = []
        n_done = 0
        t = 0
        for pk, sk in keys:
            t += 1
            ev = Event(d=b"", p=(), t=t, c=pk).signed(sk)
            buf.append(ev)
            branches.append([ev])
        n_total = n_done + len(buf)
        while n_total < n_events:
            ci = rng.randrange(n_members)
            pi = rng.randrange(n_members - 1)
            if pi >= ci:
                pi += 1
            pk, sk = keys[ci]
            other = branches[pi][rng.randrange(len(branches[pi]))]
            bi = rng.randrange(len(branches[ci]))
            head = branches[ci][bi]
            t += 1
            fork_now = (
                ci < n_forkers and head.p and rng.random() < fork_prob
            )
            if fork_now:
                sp = head.p[0]
                ev = Event(
                    d=b"fork:%d" % n_total, p=(sp, other.id), t=t, c=pk
                ).signed(sk)
                branches[ci].append(ev)
            else:
                ev = Event(
                    d=b"tx:%d" % n_total, p=(head.id, other.id), t=t, c=pk
                ).signed(sk)
                branches[ci][bi] = ev
            buf.append(ev)
            n_total += 1
            if len(buf) >= chunk:
                yield buf
                n_done += len(buf)
                buf = []
        if buf:
            yield buf

    return members, stake, keys, chunks()


def generate_gossip_dag(
    n_members: int,
    n_events: int,
    seed: int = 0,
    stake: Optional[List[int]] = None,
    n_forkers: int = 0,
    fork_prob: float = 0.05,
):
    """Directly synthesize a valid random-gossip DAG (no per-node stores).

    Produces the same *shape* of history as the in-process sim — per-member
    self-chains stitched by random cross-member other-parents — but in
    O(n_events) work, so BASELINE configs 3+ (64 members / 10k events) can
    be generated in seconds.  Used by ``bench.py`` and the graft entry.

    With ``n_forkers`` the first f members equivocate: with probability
    ``fork_prob`` a forker's new event is a *sibling* of its current head
    (same self-parent — a fork pair), and its chain thereafter extends a
    randomly chosen branch, producing realistic fork trees for BASELINE
    config 4 (64 members, f=21, fork-detection parity).

    Returns ``(members, stake, events, keys)`` with ``events`` in topo
    order and ``keys`` the (pk, sk) pairs (so callers can build observer or
    member nodes for the same population).
    """
    rng = random.Random(seed)
    keys = [crypto.keypair(b"dag-%d-%d" % (seed, i)) for i in range(n_members)]
    members = [pk for pk, _ in keys]
    stake = list(stake) if stake is not None else [1] * n_members
    events: List[Event] = []
    branches: List[List[Event]] = []     # per member: branch heads
    t = 0
    for pk, sk in keys:
        t += 1
        ev = Event(d=b"", p=(), t=t, c=pk).signed(sk)
        events.append(ev)
        branches.append([ev])
    while len(events) < n_events:
        ci = rng.randrange(n_members)
        pi = rng.randrange(n_members - 1)
        if pi >= ci:
            pi += 1
        pk, sk = keys[ci]
        other = branches[pi][rng.randrange(len(branches[pi]))]
        bi = rng.randrange(len(branches[ci]))
        head = branches[ci][bi]
        t += 1
        fork_now = (
            ci < n_forkers and head.p and rng.random() < fork_prob
        )
        if fork_now:
            # sibling of the current head: same self-parent, new branch
            sp = head.p[0]
            ev = Event(
                d=b"fork:%d" % len(events), p=(sp, other.id), t=t, c=pk
            ).signed(sk)
            events.append(ev)
            branches[ci].append(ev)
        else:
            ev = Event(
                d=b"tx:%d" % len(events),
                p=(head.id, other.id),
                t=t,
                c=pk,
            ).signed(sk)
            events.append(ev)
            branches[ci][bi] = ev
    return members, stake, events, keys
