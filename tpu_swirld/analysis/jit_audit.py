"""Jit-boundary auditor: host syncs, recompiles, and signature drift.

The batch and streaming throughput numbers rest on three jit-boundary
facts: stage bodies never sync to the host, the shape buckets keep the
steady-state loop at zero recompiles, and every stage is called with a
stable abstract signature (a ``weak_type`` or dtype flip on an argument
is a silent recompile even at identical shapes).  This module audits all
three:

- :func:`static_audit` — AST pass over the kernel modules flagging
  host-sync calls *inside jit-decorated bodies*: ``.item()``,
  ``.block_until_ready()``, ``jax.device_get``, ``float()/int()/bool()``
  on tracers, and ``np.asarray``/``np.array`` (a silent device→host
  pull).
- :func:`runtime_audit` — drives a real windowed driver (``--engine``:
  :class:`IncrementalConsensus`, the slab-store
  :class:`StreamingConsensus`, or the row-sharded
  :class:`MeshStreamingConsensus` from the mesh streaming soak) over a
  generated gossip DAG with a signature observer installed on
  ``obs.stage_call``, then reports per-stage steady-state compile counts
  (cross-checked against :func:`tpu_swirld.obs.compile_counts`) and
  abstract-value drift: stages called with the same shapes/statics but
  differing dtype or ``weak_type``.

CLI: ``python -m tpu_swirld.analysis jit-audit`` (exit 1 on any host
sync, steady recompile, or drift).
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: kernel modules the static pass covers (relative to the repo root)
_KERNEL_MODULES = (
    "tpu_swirld/tpu/pipeline.py",
    "tpu_swirld/tpu/pallas_kernels.py",
    "tpu_swirld/parallel.py",
)

#: attribute calls that synchronize device→host
_SYNC_ATTRS = {"item", "block_until_ready", "tolist", "copy_to_host_async"}
#: ``mod.fn`` calls that synchronize (or silently pull) device values
_SYNC_MODULE_FNS = {
    ("jax", "device_get"),
    ("np", "asarray"), ("np", "array"),
    ("numpy", "asarray"), ("numpy", "array"),
}


def _is_jitted(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Attribute) and target.attr in (
            "jit", "pmap", "pjit",
        ):
            return True
        if isinstance(target, ast.Name) and target.id in ("jit", "pjit"):
            return True
        # functools.partial(jax.jit, ...)
        if (
            isinstance(dec, ast.Call)
            and dec.args
            and isinstance(dec.args[0], ast.Attribute)
            and dec.args[0].attr == "jit"
        ):
            return True
    return False


def static_audit(root: str = ".") -> List[Dict]:
    """Host-sync calls inside jit-decorated function bodies in the
    kernel modules.  Returns ``[]`` on a clean tree."""
    findings: List[Dict] = []
    for rel in _KERNEL_MODULES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef) or not _is_jitted(fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                c = node.func
                msg = None
                if isinstance(c, ast.Attribute) and c.attr in _SYNC_ATTRS:
                    msg = f".{c.attr}() inside jitted {fn.name}()"
                elif (
                    isinstance(c, ast.Attribute)
                    and isinstance(c.value, ast.Name)
                    and (c.value.id, c.attr) in _SYNC_MODULE_FNS
                ):
                    msg = (
                        f"{c.value.id}.{c.attr}(...) inside jitted "
                        f"{fn.name}() pulls the tracer to host"
                    )
                elif isinstance(c, ast.Name) and c.id in (
                    "float", "int", "bool",
                ) and node.args:
                    msg = (
                        f"{c.id}(...) on a value inside jitted "
                        f"{fn.name}() forces a host sync"
                    )
                if msg:
                    findings.append({
                        "path": rel, "line": node.lineno,
                        "stage": fn.name, "message": msg,
                    })
    return findings


# ------------------------------------------------------------ signatures


def _abstract(v) -> Tuple:
    """Hashable abstract value of one stage argument: arrays become
    (shape, dtype, weak_type), everything else its static repr."""
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        weak = bool(getattr(v, "weak_type", False))
        return ("arr", tuple(shape), str(dtype), weak)
    return ("static", repr(v))


def _signature(args, kw) -> Tuple[Tuple, ...]:
    sig = tuple(_abstract(a) for a in args)
    if kw:
        sig += tuple(
            (k, _abstract(v)) for k, v in sorted(kw.items())
        )
    return sig


def _shape_key(sig: Tuple[Tuple, ...]) -> Tuple:
    """Signature with dtype/weak_type erased — two signatures sharing a
    shape key but differing overall are recompile-triggering drift."""
    out = []
    for part in sig:
        if part and part[0] == "arr":
            out.append(("arr", part[1]))
        else:
            out.append(part)
    return tuple(out)


def _find_drift(records: Dict[str, List[Tuple]]) -> List[Dict]:
    """Stages called with identical shapes/statics but differing
    dtype/weak_type — each such cluster is a silent recompile."""
    drift: List[Dict] = []
    for stage, sigs in sorted(records.items()):
        by_shape: Dict[Tuple, set] = {}
        for sig in sigs:
            by_shape.setdefault(_shape_key(sig), set()).add(sig)
        for key, variants in sorted(by_shape.items()):
            if len(variants) > 1:
                drift.append({
                    "stage": stage,
                    "variants": sorted(str(v) for v in variants),
                })
    return drift


def runtime_audit(
    *,
    n_members: int = 8,
    n_events: int = 1200,
    seed: int = 5,
    chunk: int = 128,
    window_bucket: int = 512,
    prune_min: int = 128,
    engine: str = "incremental",
) -> Dict[str, Any]:
    """Drive a real windowed-consensus run with the stage observer
    installed; report steady-state compile counts and signature drift.

    ``engine`` picks the driver under audit: ``"incremental"``
    (:class:`~tpu_swirld.tpu.pipeline.IncrementalConsensus`),
    ``"streaming"`` (:class:`~tpu_swirld.store.streaming.
    StreamingConsensus` — the slab-store retire/fetch stages join the
    observed set), or ``"mesh"`` (:class:`~tpu_swirld.parallel.
    MeshStreamingConsensus` — the row-sharded mesh driver from the
    streaming soak, so halo-exchange and sharded widening stages are
    covered; simulate devices with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``).

    Warmup covers the first two thirds of the chunks (shape buckets fill
    there); the audit window is the remainder under a fresh ``Obs`` so
    ``compile_counts`` isolates steady-state recompiles, exactly like the
    tier-1 recompile regression."""
    import functools

    from tpu_swirld import obs as obslib
    from tpu_swirld.config import SwirldConfig
    from tpu_swirld.sim import generate_gossip_dag
    from tpu_swirld.tpu.pipeline import IncrementalConsensus

    if engine == "streaming":
        from tpu_swirld.store.streaming import StreamingConsensus as _Driver
    elif engine == "mesh":
        import jax

        from tpu_swirld.parallel import MeshStreamingConsensus, make_mesh

        mesh = make_mesh(min(8, len(jax.devices())))
        _Driver = functools.partial(MeshStreamingConsensus, mesh)
    elif engine == "incremental":
        _Driver = IncrementalConsensus
    else:
        raise ValueError(f"unknown engine {engine!r}")

    members, stake, events, _keys = generate_gossip_dag(
        n_members, n_events, seed=seed
    )
    cfg = SwirldConfig(n_members=n_members)
    inc = _Driver(
        members, stake, cfg, chunk=chunk,
        window_bucket=window_bucket, prune_min=prune_min,
    )
    chunks = [events[i : i + 250] for i in range(0, len(events), 250)]
    warmup = (2 * len(chunks)) // 3
    for c in chunks[:warmup]:
        inc.ingest(c)

    records: Dict[str, List[Tuple]] = {}

    def observer(name, fn, args, kw):
        records.setdefault(name, []).append(_signature(args, kw))

    o = obslib.Obs()
    obslib.set_stage_observer(observer)
    try:
        with obslib.enabled(o):
            for c in chunks[warmup:]:
                inc.ingest(c)
    finally:
        obslib.set_stage_observer(None)

    steady = obslib.compile_counts(o.registry)
    drift = _find_drift(records)
    # the fused rounds span (stage_call_fused megadispatch) feeds the
    # same observer seam as stage_call, so when fuse_chunks > 1 (the
    # resolved default) the audit's recompile/drift verdict covers the
    # K-chunk scan path — surface that coverage in the report so a
    # config that silently fell back to per-chunk dispatch is visible
    fused_audited = "pipeline.rounds_span_stage" in records
    return {
        "engine": engine,
        "stages_observed": sorted(records),
        "steady_calls": {k: len(v) for k, v in sorted(records.items())},
        "steady_compiles": steady,
        "signature_drift": drift,
        "fused_span_audited": fused_audited,
        "fuse_chunks": inc._fuse,
        "ok": not steady and not drift,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m tpu_swirld.analysis jit-audit",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--root", default=".", help="repo root for the static pass")
    ap.add_argument("--static-only", action="store_true")
    ap.add_argument("--members", type=int, default=8)
    ap.add_argument("--events", type=int, default=1200)
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument(
        "--engine", choices=("incremental", "streaming", "mesh"),
        default="incremental",
        help="windowed driver for the runtime pass: incremental "
        "(default), streaming (slab store), or mesh (row-sharded "
        "MeshStreamingConsensus)",
    )
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    report: Dict[str, Any] = {"static": static_audit(args.root)}
    ok = not report["static"]
    if not args.static_only:
        rt = runtime_audit(
            n_members=args.members, n_events=args.events, seed=args.seed,
            engine=args.engine,
        )
        report["runtime"] = rt
        ok = ok and rt["ok"]
    report["ok"] = ok
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for f in report["static"]:
            print(f"{f['path']}:{f['line']}: {f['message']}")
        if "runtime" in report:
            rt = report["runtime"]
            print(f"stages observed: {len(rt['stages_observed'])}")
            print(f"fused span audited: {rt['fused_span_audited']} "
                  f"(fuse_chunks={rt['fuse_chunks']})")
            print(f"steady-state compiles: {rt['steady_compiles'] or 'none'}")
            for d in rt["signature_drift"]:
                print(f"drift in {d['stage']}: {d['variants']}")
        print("OK" if ok else "FAIL")
    return 0 if ok else 1
