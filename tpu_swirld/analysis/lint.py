"""AST-based invariant linter for the consensus core.

The linter walks every ``.py`` file under the given paths, parses it once,
and runs each registered rule (:mod:`tpu_swirld.analysis.rules`) whose
scope covers the module.  Rules are *project-specific invariants*, not
style: every finding names a concrete consensus-safety, jit-discipline, or
thread-safety hazard and carries a fix-it message.

Suppression syntax
------------------

A finding is suppressed by a comment on the flagged line::

    for tip in self.branch_tips[m]:   # swirld-lint: disable=SW002

Multiple ids separate with commas (``disable=SW002,SW005``); rule *names*
work too (``disable=unordered-iter``); ``disable=all`` silences the line.
A file-level escape hatch — ``# swirld-lint: disable-file=SW004`` within
the first ten lines — exists for generated or vendored code; the package
itself must not need it.

Programmatic use::

    from tpu_swirld.analysis import lint_paths
    findings = lint_paths(["tpu_swirld"])      # [] == clean tree

``check_source(source, module_path=...)`` lints a string against a
virtual module path (the per-rule fixture tests use this to place bad
snippets inside consensus-critical scopes).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import tokenize
from typing import Dict, List, Optional, Sequence, Tuple

#: the package directory name that anchors rule scopes
_PKG = "tpu_swirld"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str          # rule id, e.g. "SW002"
    name: str          # rule slug, e.g. "unordered-iter"
    path: str          # file path as given to the linter
    line: int
    col: int
    message: str       # what is wrong + the fix-it

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule}[{self.name}] {self.message}"
        )

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class FileContext:
    """Everything a rule needs about one file: parsed tree, source lines,
    the module path used for scoping, and the cross-file package index."""

    def __init__(
        self,
        path: str,
        source: str,
        module_path: str,
        index: "PackageIndex",
    ):
        self.path = path
        self.source = source
        self.module_path = module_path
        self.index = index
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()


class PackageIndex:
    """Cross-file facts collected before per-file rule checks.

    ``donations`` maps a function name to the tuple of its
    ``donate_argnums`` positions; ``donation_factories`` maps a factory
    function name (``make_*`` returning a jitted inner def) to the inner
    def's donated positions.  The donation-discipline rule resolves call
    sites against both, so a buffer donated through a factory-produced
    stage is tracked exactly like a module-level one.
    """

    def __init__(self):
        self.donations: Dict[str, Tuple[int, ...]] = {}
        self.donation_factories: Dict[str, Tuple[int, ...]] = {}

    def scan(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            pos = _donated_positions(node)
            if pos:
                self.donations[node.name] = pos
            else:
                inner = [
                    n for n in node.body
                    if isinstance(n, ast.FunctionDef)
                    and _donated_positions(n)
                ]
                if inner:
                    self.donation_factories[node.name] = (
                        _donated_positions(inner[0])
                    )


def _donated_positions(fn: ast.FunctionDef) -> Tuple[int, ...]:
    """``donate_argnums`` positions from a ``@jax.jit`` /
    ``@functools.partial(jax.jit, donate_argnums=...)`` decorator."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        is_partial = (
            isinstance(dec.func, ast.Attribute)
            and dec.func.attr == "partial"
        ) or (isinstance(dec.func, ast.Name) and dec.func.id == "partial")
        is_jit = (
            isinstance(dec.func, ast.Attribute) and dec.func.attr == "jit"
        )
        if not (is_partial or is_jit):
            continue
        for kw in dec.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Tuple):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, int
                    ):
                        out.append(e.value)
                return tuple(out)
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
    return ()


# ----------------------------------------------------------- suppression


def _suppressions(source: str) -> Tuple[Dict[int, set], set]:
    """``(per_line, per_file)`` suppression sets parsed from
    ``# swirld-lint:`` comments (rule ids, rule names, or ``all``).

    The id list is the first whitespace-delimited token after
    ``disable=``; anything after it is a free-form justification
    (``# swirld-lint: disable=SW008 -- tally < 2**24 by config cap``).
    The scale auditor *requires* that justification text
    (:func:`suppression_notes`); plain lint ignores it."""
    per_line: Dict[int, set] = {}
    per_file: set = set()
    for lineno, kind, ids, _note in _suppression_comments(source):
        if kind == "file":
            if lineno <= 10:
                per_file.update(ids)
        else:
            per_line.setdefault(lineno, set()).update(ids)
    return per_line, per_file


def _suppression_comments(source: str):
    """Yields ``(lineno, kind, ids, note)`` for every ``# swirld-lint:``
    comment; ``kind`` is ``"line"`` or ``"file"``, ``note`` the
    justification text following the id list (leading ``--`` stripped)."""
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string.lstrip("#").strip()
        if not text.startswith("swirld-lint:"):
            continue
        body = text[len("swirld-lint:"):].strip()
        for prefix, kind in (("disable-file=", "file"), ("disable=", "line")):
            if body.startswith(prefix):
                spec = body[len(prefix):]
                ids_part, _, note = spec.partition(" ")
                ids = {x.strip() for x in ids_part.split(",") if x.strip()}
                note = note.strip()
                if note.startswith("--"):
                    note = note[2:].strip()
                yield tok.start[0], kind, ids, note
                break


def suppression_notes(source: str) -> Dict[int, Tuple[set, str]]:
    """Per-line suppressions *with* their justification text, for
    auditors that refuse an unjustified suppression."""
    out: Dict[int, Tuple[set, str]] = {}
    for lineno, kind, ids, note in _suppression_comments(source):
        if kind != "line":
            continue
        prev_ids, prev_note = out.get(lineno, (set(), ""))
        out[lineno] = (prev_ids | ids, note or prev_note)
    return out


def _suppressed(
    f: Finding,
    per_line: Dict[int, set],
    per_file: set,
    notes: Optional[Dict[int, Tuple[set, str]]] = None,
    require_note: bool = False,
) -> bool:
    if require_note:
        # justified-suppression scope (Rule.note_scope): only a line
        # suppression carrying a non-empty ``-- why`` note counts; bare
        # disables and file-wide disables stay findings
        ids, note = (notes or {}).get(f.line, (set(), ""))
        return bool(
            note and (f.rule in ids or f.name in ids or "all" in ids)
        )
    for ids in (per_file, per_line.get(f.line, ())):
        if ids and (f.rule in ids or f.name in ids or "all" in ids):
            return True
    return False


# ---------------------------------------------------------------- driver


def module_path(path: str) -> str:
    """Scope key for a file: its posix path relative to the ``tpu_swirld``
    package root (``oracle/node.py``), or the bare filename for files
    outside the package (scripts, tests)."""
    parts = path.replace(os.sep, "/").split("/")
    if _PKG in parts:
        i = len(parts) - 1 - parts[::-1].index(_PKG)
        rel = "/".join(parts[i + 1:])
        if rel:
            return rel
    return parts[-1]


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _load_rules(only: Optional[Sequence[str]] = None):
    from tpu_swirld.analysis.rules import all_rules

    rules = all_rules()
    if only:
        sel = set(only)
        rules = [r for r in rules if r.id in sel or r.name in sel]
    return rules


def lint_paths(
    paths: Sequence[str],
    *,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint every ``.py`` file under ``paths``; returns all unsuppressed
    findings sorted by location."""
    files = collect_files(paths)
    index = PackageIndex()
    parsed: List[Tuple[str, str, ast.AST]] = []
    findings: List[Finding] = []
    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(
                Finding(
                    "SW000", "syntax", path, exc.lineno or 0, 0,
                    f"file does not parse: {exc.msg}",
                )
            )
            continue
        index.scan(tree)
        parsed.append((path, source, tree))
    rule_objs = _load_rules(rules)
    for path, source, tree in parsed:
        ctx = FileContext(path, source, module_path(path), index)
        per_line, per_file = _suppressions(source)
        notes = suppression_notes(source)
        for rule in rule_objs:
            if not rule.applies(ctx.module_path):
                continue
            require_note = rule.requires_note(ctx.module_path)
            for f in rule.check(ctx):
                if not _suppressed(f, per_line, per_file, notes=notes,
                                   require_note=require_note):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def check_source(
    source: str,
    *,
    module_path: str = "module.py",
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
    index: Optional[PackageIndex] = None,
) -> List[Finding]:
    """Lint a source string against a virtual module path (fixture
    helper: place a snippet "inside" ``oracle/node.py`` to hit scoped
    rules).  The donation index is built from the snippet itself unless
    an explicit ``index`` is passed."""
    if index is None:
        index = PackageIndex()
        index.scan(ast.parse(source))
    ctx = FileContext(path, source, module_path, index)
    per_line, per_file = _suppressions(source)
    notes = suppression_notes(source)
    out = []
    for rule in _load_rules(rules):
        if not rule.applies(ctx.module_path):
            continue
        require_note = rule.requires_note(ctx.module_path)
        for f in rule.check(ctx):
            if not _suppressed(f, per_line, per_file, notes=notes,
                               require_note=require_note):
                out.append(f)
    out.sort(key=lambda f: (f.line, f.col, f.rule))
    return out


def lint_summary(findings: Sequence[Finding]) -> Dict:
    """The shape stamped into bench JSON artifacts (``bench_compare.py``
    refuses to gate a run produced from a tree with findings)."""
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "findings": len(findings),
        "clean": not findings,
        "by_rule": dict(sorted(by_rule.items())),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m tpu_swirld.analysis lint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*", default=[_PKG])
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument(
        "--rules", help="comma-separated rule ids/names to run (default all)"
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for r in _load_rules():
            print(f"{r.id} {r.name:<22} scope={','.join(r.scope) or '*'}")
            print(f"      {r.describe}")
        return 0
    only = args.rules.split(",") if args.rules else None
    findings = lint_paths(args.paths or [_PKG], rules=only)
    if args.json:
        print(
            json.dumps(
                {
                    **lint_summary(findings),
                    "items": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"{n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0
