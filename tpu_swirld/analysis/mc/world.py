"""Small-world driver for the explicit-state model checker.

A :class:`World` owns the member keys, the protocol config, and a global
append-only table of every event any exploration branch has minted.  A
:class:`MCState` is the per-role *local ingest history*: for every role,
the exact sequence of event ids in the order the role ingested them,
plus each attacker branch's own chain tip.  Arrival ORDER is
deliberately part of the state — the horizon/late-witness machinery is
order-sensitive — so two schedules merge exactly when every node's local
arrival order is identical (Mazurkiewicz-trace equivalence of the
delivery schedule at event granularity).

Everything else — rounds, fame, the decided order, counters — is
recomputed from the history through the REAL oracle node: materializing
a role replays its arrivals through ``Node.add_event`` +
``Node.consensus_pass``, one event per pass (the checker's delivery
granularity), so what the checker explores is exactly the code that
ships.  Transitions likewise go through the real gossip path:
``Node.pull`` / ``Node.sync`` over a reliable
:class:`~tpu_swirld.transport.Transport` with the ``on_call`` step hook
installed to record wire activity for replay determinism.

Roles
-----
- honest role ``i`` (``0 <= i < n_honest``) is member index ``i``;
- attacker branch role: forker ``k`` (member ``n_honest + k``) owns two
  branch roles holding divergent views of its own chain, mirroring
  :class:`tpu_swirld.sim.DivergentForker`.  Both branches share the
  member's single deterministic genesis.

Actions (all JSON-serializable tuples)
--------------------------------------
- ``("pull", i, j)`` — honest role i pulls role j's delta (no creation;
  free).  When j is a branch role this IS the equivocation seam: the
  action chooses which branch serves the forker's endpoint.
- ``("sync", i, j)`` — pull + create one event (other-parent = j's
  member head as known to i).  Consumes one unit of the event budget.
- ``("ext", b, j)`` — branch role b pulls from honest role j and
  extends its own chain (payload tags the branch, so sibling branches
  mint distinct events at equal seq — fork pairs).  Consumes budget.
- ``("wext", b)`` — withhold-extend: branch b extends WITHOUT pulling,
  other-parent = the earliest event of the lowest-indexed other member
  it knows (maximally stale straggler shape).  Only enumerated with
  ``withhold=True``.  Consumes budget.

Determinism: every action's outcome is a pure function of
``(state, action)`` — timestamps come from the lamport clock (a function
of the local store) or ``max(parent timestamps) + 1``, payloads are
fixed tags, and the transport is reliable — which is what makes
memoization on state keys sound.  (One deliberate abstraction: an
empty pull is a true no-op — over a reliable transport serving honestly
no counter moves on an empty delta — so delivery-free actions are
pruned rather than folded into the state.)

Performance: actors are cloned (``deepcopy``) from the per-``(role,
history)`` node cache and stepped by ONE action, then unwired and
cached at the successor history — exploration is incremental, and full
replay-from-history only happens on cache eviction.  The clone/replay
equivalence is exact because materialization replays the identical
``add_event``/``consensus_pass([event])`` sequence.
"""

from __future__ import annotations

import dataclasses
import random
from collections import OrderedDict
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from tpu_swirld import crypto
from tpu_swirld.config import SwirldConfig
from tpu_swirld.oracle.event import Event
from tpu_swirld.oracle.graph import toposort
from tpu_swirld.oracle.node import Node
from tpu_swirld.transport import Transport

#: materialization cache entries kept per world (nodes are rebuilt on miss)
_CACHE_CAP = 16384


class Role(NamedTuple):
    kind: str      # "honest" | "branch"
    member: int    # member index
    branch: int    # branch index within the forker (-1 for honest)


@dataclasses.dataclass(frozen=True)
class MCState:
    """Immutable world state: per-role ingest histories + branch tips.

    ``histories[r]`` is the flat tuple of event ids role r ingested, in
    arrival order (the first entry is always the role's own genesis).
    ``heads[q]`` is branch role q's own chain tip (branch order as in
    ``World.branch_roles``).  ``created`` counts non-genesis events
    minted so far (the budget).
    """

    histories: Tuple[Tuple[bytes, ...], ...]
    heads: Tuple[bytes, ...]
    created: int

    def view(self, role_index: int) -> Tuple[bytes, ...]:
        return self.histories[role_index]


class ActionResult(NamedTuple):
    state: "MCState"
    batch: Tuple[bytes, ...]
    noop: bool
    actor_role: int
    trace: Tuple[Tuple[int, int, str], ...]   # (src member, dst member, chan)


class World:
    """One bounded exploration universe (keys, config, event table)."""

    def __init__(
        self,
        n_honest: int = 3,
        n_forkers: int = 0,
        events: int = 5,
        seed: int = 0,
        withhold: bool = False,
        node_cls: Optional[type] = None,
        config: Optional[SwirldConfig] = None,
        observer_cls: Optional[type] = None,
        genesis_mtx: Optional[Dict[int, tuple]] = None,
    ):
        if n_honest < 1:
            raise ValueError("need at least one honest role")
        self.n_honest = n_honest
        self.n_forkers = n_forkers
        self.events_budget = events
        self.seed = seed
        self.withhold = withhold
        self.node_cls = node_cls or Node
        # ground-truth observer for the union replay: vanilla by default;
        # dynamic-membership worlds pass DynamicNode so the observer
        # interprets membership transactions the way honest nodes do
        # (NEVER the mutated class — the observer is the reference)
        self.observer_cls = observer_cls or Node
        # genesis-carried membership transactions, carrier member index ->
        # ("restake", member, stake) | ("leave", member) | ("join", stake);
        # riding the geneses keeps them in every exploration branch's
        # history, so the transition memo stays sound
        self.genesis_mtx = dict(genesis_mtx or {})
        n_members = n_honest + n_forkers
        self.config = config or SwirldConfig(n_members=n_members, seed=seed)
        if self.config.n_members != n_members:
            raise ValueError("config.n_members != n_honest + n_forkers")
        self.keys: List[Tuple[bytes, bytes]] = [
            crypto.keypair(b"mc-member-%d-%d" % (seed, i))
            for i in range(n_members)
        ]
        self.members: List[bytes] = [pk for pk, _sk in self.keys]
        self.byz_members = frozenset(self.members[n_honest:])
        # role table: honest roles first (role index == member index),
        # then two branch roles per forker
        self.roles: List[Role] = [
            Role("honest", i, -1) for i in range(n_honest)
        ]
        for k in range(n_forkers):
            self.roles.append(Role("branch", n_honest + k, 0))
            self.roles.append(Role("branch", n_honest + k, 1))
        self.honest_roles = list(range(n_honest))
        self.branch_roles = list(range(n_honest, len(self.roles)))
        # global append-only event table; idempotent across exploration
        # branches (equal mints hash to equal ids)
        self.events: Dict[bytes, Event] = {}
        self._geneses: List[bytes] = []
        for i in range(n_members):
            pk, sk = self.keys[i]
            g = Event(
                d=self._genesis_payload(i), p=(), t=0, c=pk
            ).signed(sk)
            self.events[g.id] = g
            self._geneses.append(g.id)
        self._cache: "OrderedDict[Tuple[int, tuple], Node]" = OrderedDict()
        self._union_cache: "OrderedDict[tuple, Node]" = OrderedDict()
        # transition memo: an action's outcome (batch, trace) is a pure
        # function of the actor's and server's local histories (plus the
        # branch tip for branch actors) — global states sharing those
        # locals share the transition, so re-executions are table hits
        self._tmemo: Dict[tuple, Tuple[tuple, tuple]] = {}

    def _genesis_payload(self, i: int) -> bytes:
        spec = self.genesis_mtx.get(i)
        if spec is None:
            return b""
        from tpu_swirld.membership import txs as mtx

        kind = spec[0]
        if kind == "restake":
            return mtx.restake_payload(
                self.members[int(spec[1])], int(spec[2])
            )
        if kind == "leave":
            return mtx.leave_payload(self.members[int(spec[1])])
        if kind == "join":
            jpk, _sk = crypto.keypair(
                b"mc-joiner-%d-%d" % (self.seed, i)
            )
            return mtx.join_payload(jpk, int(spec[1]))
        raise ValueError(f"unknown genesis_mtx kind {kind!r}")

    # ------------------------------------------------------------- state

    def initial_state(self) -> MCState:
        histories = tuple(
            (self._geneses[role.member],) for role in self.roles
        )
        heads = tuple(
            self._geneses[self.roles[q].member] for q in self.branch_roles
        )
        return MCState(histories=histories, heads=heads, created=0)

    def budget_left(self, state: MCState) -> int:
        return self.events_budget - state.created

    def head_of(self, state: MCState, branch_role: int) -> bytes:
        return state.heads[branch_role - self.n_honest]

    # ----------------------------------------------------------- actions

    def enabled_actions(self, state: MCState) -> List[tuple]:
        acts: List[tuple] = []
        budget = self.budget_left(state) > 0
        views = [frozenset(h) for h in state.histories]
        for i in self.honest_roles:
            for j, role in enumerate(self.roles):
                if j == i or role.member == i:
                    continue
                # a pull from a server whose view we already contain is
                # a guaranteed no-op (reliable transport, honest serve):
                # prune it here instead of paying the transition
                if not views[j] <= views[i]:
                    acts.append(("pull", i, j))
                if budget:
                    acts.append(("sync", i, j))
        if budget:
            for b in self.branch_roles:
                for j in self.honest_roles:
                    acts.append(("ext", b, j))
                if self.withhold and self._stale_parent(state, b) is not None:
                    acts.append(("wext", b))
        return acts

    def hunt_weight(self, state: MCState, action: tuple) -> float:
        """Sampling weight for the random-walk hunt: creations beat
        pulls, denser sources beat sparse ones (gossip ladders build
        rounds), and branch extensions rotate toward the branch that
        has minted least (fork pairs need both siblings to move)."""
        kind = action[0]
        if kind == "pull":
            return 1.0
        if kind == "sync":
            return 2.0 * len(state.histories[action[2]])
        # ext / wext: count the branch's own mints (history entries by
        # its own member beyond the genesis)
        b = action[1]
        own_pk = self.members[self.roles[b].member]
        own = sum(
            1 for eid in state.histories[b] if self.events[eid].c == own_pk
        ) - 1
        return 6.0 / (1.0 + own)

    @staticmethod
    def action_writes_reads(action: tuple) -> Tuple[frozenset, frozenset]:
        """(writes, reads) role sets — the POR independence footprint."""
        kind = action[0]
        if kind in ("pull", "sync", "ext"):
            return frozenset((action[1],)), frozenset((action[2],))
        return frozenset((action[1],)), frozenset()       # wext

    @classmethod
    def independent(cls, a: tuple, b: tuple) -> bool:
        """Actions commute iff neither writes what the other touches.
        (The shared event budget also commutes: both orders decrement it
        identically, and co-enabledness is symmetric in it.)"""
        wa, ra = cls.action_writes_reads(a)
        wb, rb = cls.action_writes_reads(b)
        return not (wa & (wb | rb)) and not (wb & (wa | ra))

    # ------------------------------------------------------ materialize

    def materialize(self, role_index: int, history: tuple) -> Node:
        """Fresh node replaying ``history`` through the real code path.

        Honest roles run one ``consensus_pass`` per arrival (the
        checker's delivery granularity); branch nodes only store
        (mirroring ``DivergentForker`` — branches never decide)."""
        role = self.roles[role_index]
        pk, sk = self.keys[role.member]
        cls = self.node_cls if role.kind == "honest" else Node
        node = cls(
            sk=sk, pk=pk, network={}, members=self.members,
            config=self.config, create_genesis=False, network_want={},
        )
        honest = role.kind == "honest"
        for eid in history:
            if node.add_event(self.events[eid]) and honest:
                node.consensus_pass([eid])
        return node

    def node_for(self, role_index: int, history: tuple) -> Node:
        """Cached read-only node for ``(role, history)`` — the serving
        side of pulls, invariant evaluation, and the clone source for
        actors.  Never mutate a node returned from here."""
        key = (role_index, history)
        node = self._cache.get(key)
        if node is None:
            node = self.materialize(role_index, history)
            self._cache[key] = node
            if len(self._cache) > _CACHE_CAP:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(key)
        return node

    def _fresh_actor(self, role_index: int, history: tuple) -> Node:
        """Mutable clone of the cached node — exploration steps clones,
        never cached originals."""
        return self._clone_node(self.node_for(role_index, history))

    @staticmethod
    def _clone_node(src: Node) -> Node:
        """Structure-aware clone, ~20x cheaper than ``copy.deepcopy``:
        immutable leaves (Events, keys, config, members) are shared,
        every mutable container is copied at its actual nesting depth,
        and the per-node machinery (transport over the clone's own
        network dicts, breaker, lamport clock binding, jitter rng) is
        rebuilt against the clone.  Covers ``Node`` and the attribute-
        free mutation subclasses; the counterexample replay equality
        test pins clone/replay equivalence."""
        dst = object.__new__(type(src))
        nd = dst.__dict__
        for k, v in src.__dict__.items():
            t = type(v)
            if t is dict:
                nd[k] = dict(v)
            elif t is list:
                nd[k] = list(v)
            elif t is set:
                nd[k] = set(v)
            else:
                nd[k] = v
        # containers nested deeper than one level (a shallow dict copy
        # would alias the inner lists/sets/dicts)
        for k in ("member_events", "member_chain", "wit_list"):
            nd[k] = {a: list(b) for a, b in src.__dict__[k].items()}
        nd["branch_tips"] = {a: set(b) for a, b in src.branch_tips.items()}
        for k in ("by_seq", "fork_groups", "witnesses"):
            nd[k] = {
                a: {s: list(g) for s, g in inner.items()}
                for a, inner in src.__dict__[k].items()
            }
        # per-clone identity
        nd["network"] = {}
        nd["network_want"] = {}
        nd["transport"] = Transport(nd["network"], nd["network_want"])
        if src._clock == src._lamport_clock:
            nd["_clock"] = dst._lamport_clock
        br = src.breaker
        if br is not None:
            nb = object.__new__(type(br))
            nb.__dict__.update(br.__dict__)
            nb._failures = dict(br._failures)
            nb._misbehavior = dict(br._misbehavior)
            nb._opened_at = dict(br._opened_at)
            nb._probing = set(br._probing)
            nb._clock = nd["_clock"]
            nd["breaker"] = nb
        rng = random.Random(0)   # state overwritten from the source rng
        rng.setstate(src._retry_rng.getstate())
        nd["_retry_rng"] = rng
        return dst

    def union_observer(self, state: MCState) -> Node:
        """Fresh-observer union replay over the honest stores (the
        chaos harness's ``oracle_order`` ground truth): canonical
        ``(t, id)`` toposort of the union, one batch, one pass."""
        views = tuple(sorted(
            set().union(*(state.view(i) for i in self.honest_roles))
        ))
        node = self._union_cache.get(views)
        if node is None:
            ordered = toposort(
                sorted(views, key=lambda e: (self.events[e].t, e)),
                lambda e: [p for p in self.events[e].p],
            )
            pk, sk = self.keys[0]
            node = self.observer_cls(
                sk=sk, pk=pk, network={}, members=self.members,
                config=self.config, create_genesis=False, network_want={},
            )
            new_ids = [
                eid for eid in ordered
                if node.add_event(self.events[eid])
            ]
            node.consensus_pass(new_ids)
            self._union_cache[views] = node
            if len(self._union_cache) > _CACHE_CAP:
                self._union_cache.popitem(last=False)
        return node

    # ----------------------------------------------------------- execute

    def _stale_parent(self, state: MCState, branch_role: int) -> Optional[bytes]:
        """Deterministic maximally-stale other-parent for ``wext``: the
        EARLIEST event of the lowest-indexed other member in the branch's
        view (arrival order; first arrival of a member is its genesis)."""
        role = self.roles[branch_role]
        own_pk = self.members[role.member]
        firsts: Dict[bytes, bytes] = {}
        for eid in state.view(branch_role):
            c = self.events[eid].c
            if c != own_pk and c not in firsts:
                firsts[c] = eid
        for m in self.members:
            if m in firsts:
                return firsts[m]
        return None

    def _wire(self, actor: Node, server: Node) -> List[Tuple[int, int, str]]:
        """Point the actor's network dicts at the server and install the
        transport step-hook recorder."""
        actor.network[server.pk] = server.ask_sync
        actor.network_want[server.pk] = server.ask_events
        trace: List[Tuple[int, int, str]] = []
        mi = {m: i for i, m in enumerate(self.members)}
        actor.transport.on_call = lambda src, dst, chan: trace.append(
            (mi[src], mi[dst], chan)
        )
        return trace

    @staticmethod
    def _unwire(actor: Node) -> None:
        actor.network.clear()
        actor.network_want.clear()
        actor.transport.on_call = None

    def transition_key(self, state: MCState, action: tuple) -> tuple:
        """Hashable identity of a transition: everything its outcome is
        a function of.  Also the dedup key for per-edge invariant
        checks — equal keys imply identical parent/child node pairs."""
        ai = action[1]
        key = [action, state.histories[ai]]
        if action[0] in ("pull", "sync", "ext"):
            key.append(state.histories[action[2]])
        if self.roles[ai].kind == "branch":
            key.append(self.head_of(state, ai))
        return tuple(key)

    def _successor(self, state: MCState, action: tuple,
                   batch: Tuple[bytes, ...]) -> MCState:
        kind = action[0]
        ai = action[1]
        histories = list(state.histories)
        histories[ai] = histories[ai] + batch
        heads = state.heads
        created = state.created
        if kind in ("sync", "ext", "wext"):
            created += 1
        if kind in ("ext", "wext"):
            heads = list(heads)
            heads[ai - self.n_honest] = batch[-1]
            heads = tuple(heads)
        return MCState(
            histories=tuple(histories), heads=heads, created=created,
        )

    def apply(self, state: MCState, action: tuple,
              actor: Optional[Node] = None,
              server: Optional[Node] = None,
              cache_child: bool = True) -> ActionResult:
        """Execute ``action`` from ``state``; returns the successor.

        ``actor`` must be a MUTABLE node for the acting role (a live
        node in schedule replay; cloned from the cache when omitted);
        ``server`` may be a cached node — the happy-path serve is
        read-only.  The returned batch is exactly what the actor
        ingested+created, in order — appending it to the actor's history
        reproduces the actor bit-for-bit."""
        kind = action[0]
        ai = action[1]
        role = self.roles[ai]
        own_actor = actor is None
        tkey = None
        if own_actor:
            tkey = self.transition_key(state, action)
            memo = self._tmemo.get(tkey)
            if memo is not None:
                batch, trace = memo
                if not batch:
                    return ActionResult(state, batch, True, ai, trace)
                return ActionResult(
                    self._successor(state, action, batch),
                    batch, False, ai, trace,
                )
            actor = self._fresh_actor(ai, state.histories[ai])
        trace: Tuple[Tuple[int, int, str], ...] = ()
        if kind in ("pull", "sync", "ext"):
            j = action[2]
            if server is None:
                server = self.node_for(j, state.histories[j])
            tr = self._wire(actor, server)
            src_pk = server.pk
            if kind == "pull":
                new_ids = actor.pull(src_pk)
                if role.kind == "honest":
                    for eid in new_ids:
                        actor.consensus_pass([eid])
                batch = tuple(new_ids)
            elif kind == "sync":
                new_ids = actor.sync(src_pk, b"")
                for eid in new_ids:
                    actor.consensus_pass([eid])
                batch = tuple(new_ids)
            else:                                   # ext
                pulled = actor.pull(src_pk)
                op = (
                    actor.member_events[src_pk][-1]
                    if actor.member_events[src_pk] else None
                )
                batch = tuple(pulled)
                if op is not None:
                    batch += (self._mint_branch_event(state, ai, actor, op),)
            if own_actor:
                self._unwire(actor)
            trace = tuple(tr)
        elif kind == "wext":
            op = self._stale_parent(state, ai)
            batch = ()
            if op is not None and op in actor.hg:
                batch = (self._mint_branch_event(state, ai, actor, op),)
        else:
            raise ValueError(f"unknown action {action!r}")
        noop = not batch
        if tkey is not None:
            self._tmemo[tkey] = (batch, trace)
        if noop:
            return ActionResult(state, batch, True, ai, trace)
        for eid in batch:
            # register freshly-minted events (sync creates inside the
            # node) in the global table; identical re-creations on other
            # exploration branches dedupe by id
            if eid not in self.events:
                self.events[eid] = actor.hg[eid]
        new_state = self._successor(state, action, batch)
        if own_actor and cache_child:
            key = (ai, new_state.histories[ai])
            if key not in self._cache:
                self._cache[key] = actor
                if len(self._cache) > _CACHE_CAP:
                    self._cache.popitem(last=False)
        return ActionResult(new_state, batch, False, ai, trace)

    def _mint_branch_event(
        self, state: MCState, branch_role: int, actor: Node, op: bytes
    ) -> bytes:
        """Extend a fork branch: self-parent is the RECORDED branch tip
        (never ``actor.head`` — sibling-branch events learned through
        honest gossip must not move this branch's own tip), timestamp is
        ``max(parent timestamps) + 1`` (a pure function of the parents),
        payload tags the branch so sibling branches mint distinct events
        at equal seq — the fork pair."""
        role = self.roles[branch_role]
        pk, sk = self.keys[role.member]
        sp = self.head_of(state, branch_role)
        ev = Event(
            d=b"mc-branch:%d" % role.branch,
            p=(sp, op),
            t=max(self.events[sp].t, self.events[op].t) + 1,
            c=pk,
        ).signed(sk)
        self.events.setdefault(ev.id, ev)
        actor.add_event(ev)
        return ev.id

    # --------------------------------------------------- live schedules

    def run_schedule(
        self,
        schedule: List[tuple],
        on_step: Optional[Callable[[int, MCState, ActionResult, Node, Node], None]] = None,
    ) -> Dict[int, Node]:
        """Faithful live execution of an explicit schedule (the
        counterexample replay path): one persistent node per role,
        mutated in place — byte-identical to the exploration semantics
        because materialization IS replay of these same arrivals.

        ``on_step(step, state_after, result, parent_actor, actor)`` is
        invoked after each non-noop action (parent_actor is the acting
        node's state *before* the step, freshly materialized for edge
        invariants).  Returns the final role -> Node map.
        """
        state = self.initial_state()
        nodes: Dict[int, Node] = {
            r: self.materialize(r, state.histories[r])
            for r in range(len(self.roles))
        }
        for step, action in enumerate(schedule):
            ai = action[1]
            parent_hist = state.histories[ai]
            server = None
            if action[0] in ("pull", "sync", "ext"):
                server = nodes[action[2]]
            res = self.apply(state, action, actor=nodes[ai], server=server)
            self._unwire(nodes[ai])
            if res.noop:
                continue
            if on_step is not None:
                parent_actor = self.node_for(ai, parent_hist)
                on_step(step, res.state, res, parent_actor, nodes[ai])
            state = res.state
        return nodes
