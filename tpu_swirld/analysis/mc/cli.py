"""``python -m tpu_swirld.analysis mc`` — the model-checker front end.

Vanilla runs are exhaustive proofs: explore every schedule of a small
world under the event budget, evaluate the invariant catalog everywhere,
and report the partial-order/symmetry reduction ratio against a naive
twin run.  ``--mutate <name>`` seeds a known bug and hunts (seeded
weighted random walks) for a witness, then minimizes it with ddmin and
proves the minimized counterexample replays to the identical violation
and state digests; ``--out`` saves the replayable JSON document.

Exit status: 0 = explored clean, 1 = violation found (including the
expected violation of a mutation run), 2 = state cap hit before the
space was exhausted (nothing proven either way).

The checker always runs on the ``sim`` crypto backend (deterministic
blake2b signatures — exploration mints thousands of events); the prior
backend is restored on exit and the counterexample document records the
backend so replays stay bit-identical.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

from tpu_swirld import crypto

from tpu_swirld.analysis.mc import counterexample as ce
from tpu_swirld.analysis.mc.explore import explore, hunt as hunt_walks
from tpu_swirld.analysis.mc.mutations import MUTATIONS, make_world

_DEFAULTS = dict(n_honest=3, n_forkers=0, events=3)


def run_mc(
    n: Optional[int] = None,
    forkers: Optional[int] = None,
    events: Optional[int] = None,
    mutate: Optional[str] = None,
    hunt: Optional[bool] = None,
    max_states: int = 200_000,
    withhold: bool = False,
    compare: bool = True,
    out: Optional[str] = None,
) -> dict:
    """Run one checker invocation; returns the JSON-ready report."""
    if mutate is not None and mutate not in MUTATIONS:
        raise SystemExit(
            f"unknown mutation {mutate!r}; have: {sorted(MUTATIONS)}"
        )
    base = dict(MUTATIONS[mutate].world_kwargs) if mutate else dict(_DEFAULTS)
    kw = dict(
        n_honest=n if n is not None else base["n_honest"],
        n_forkers=forkers if forkers is not None else base["n_forkers"],
        events=events if events is not None else base["events"],
        withhold=withhold,
    )
    if hunt is None:
        hunt = mutate is not None
    mode = "hunt" if hunt else "bfs"
    prev_backend = crypto.backend_name()
    crypto.set_backend("sim")
    try:
        world = make_world(mutate, **kw)
        t0 = time.perf_counter()
        if hunt:
            res = hunt_walks(world, seed=world.seed)
        else:
            res = explore(world, mode="bfs", max_states=max_states)
        elapsed = time.perf_counter() - t0
        report = {
            "mode": mode,
            "mutate": mutate,
            "world": {**kw, "seed": world.seed},
            "explore": res.to_dict(),
            "elapsed_s": round(elapsed, 3),
            "states_per_sec": round(res.states / elapsed) if elapsed else 0,
        }
        if res.violation is not None:
            confirm = ce.run_checked(world, res.schedule)
            if confirm["violation"] is None:
                raise RuntimeError(
                    "explorer violation did not reproduce through the "
                    "live schedule replay — checker bug"
                )
            minimized = ce.minimize(
                world, res.schedule, confirm["violation"].invariant
            )
            min_report = ce.run_checked(world, minimized)
            doc = ce.emit(world, minimized, min_report, mutate=mutate)
            replayed = ce.replay(doc)
            report["counterexample"] = {
                "schedule_len": len(res.schedule),
                "minimized_len": len(minimized),
                "violation": doc["violation"],
                "replay_reproduced": replayed["reproduced"],
                "replay_digests_match": replayed["digests_match"],
                "replay_trace_match": replayed["trace_match"],
                "document": doc,
            }
            if mutate is not None:
                report["counterexample"]["expected_invariant"] = (
                    MUTATIONS[mutate].expected_invariant
                )
                report["counterexample"]["caught_expected"] = (
                    doc["violation"]["invariant"]
                    == MUTATIONS[mutate].expected_invariant
                )
            if out:
                ce.save(doc, out)
                report["counterexample"]["saved_to"] = out
        elif compare and res.exhaustive and mutate is None:
            naive = explore(
                make_world(None, **kw), por=False, symmetry=False,
                mode=mode, max_states=max_states, check_invariants=False,
            )
            report["reduction"] = {
                "naive_states": naive.states,
                "naive_transitions": naive.transitions,
                "state_ratio": round(naive.states / max(res.states, 1), 2),
                "transition_ratio": round(
                    naive.transitions / max(res.transitions, 1), 2
                ),
            }
        return report
    finally:
        crypto.set_backend(prev_backend)


def mc_smoke(n: int = 3, events: int = 2, compare: bool = True) -> dict:
    """Small exhaustive run stamped into bench verdicts: explored
    states, states/sec, reduction ratio, and a clean/dirty flag."""
    rep = run_mc(n=n, forkers=0, events=events, compare=compare)
    red = rep.get("reduction", {})
    return {
        "n": n,
        "events": events,
        "states": rep["explore"]["states"],
        "transitions": rep["explore"]["transitions"],
        "states_per_sec": rep["states_per_sec"],
        "exhaustive": rep["explore"]["exhaustive"],
        "violations": rep["explore"]["violations_found"],
        "state_ratio": red.get("state_ratio"),
        "transition_ratio": red.get("transition_ratio"),
        "ok": (
            rep["explore"]["exhaustive"]
            and rep["explore"]["violations_found"] == 0
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_swirld.analysis mc",
        description="explicit-state model checker for the consensus core",
    )
    ap.add_argument("--n", type=int, default=None,
                    help="honest members (default 3, or the mutation's)")
    ap.add_argument("--forkers", type=int, default=None,
                    help="attacker members, two branches each")
    ap.add_argument("--events", type=int, default=None,
                    help="non-genesis event budget")
    ap.add_argument("--mutate", choices=sorted(MUTATIONS), default=None,
                    help="seed a known bug and hunt for its witness")
    ap.add_argument("--hunt", action="store_true",
                    help="random-walk hunt (default for --mutate; "
                         "exhaustive BFS otherwise)")
    ap.add_argument("--withhold", action="store_true",
                    help="enable the stale-parent withhold-extend action")
    ap.add_argument("--max-states", type=int, default=200_000)
    ap.add_argument("--no-compare", action="store_true",
                    help="skip the naive baseline / reduction report")
    ap.add_argument("--out", default=None,
                    help="write the minimized counterexample JSON here")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    args = ap.parse_args(argv)

    report = run_mc(
        n=args.n, forkers=args.forkers, events=args.events,
        mutate=args.mutate, hunt=args.hunt or None,
        max_states=args.max_states, withhold=args.withhold,
        compare=not args.no_compare, out=args.out,
    )
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        ex = report["explore"]
        print(
            f"mc: {report['mode']} n={report['world']['n_honest']} "
            f"forkers={report['world']['n_forkers']} "
            f"events={report['world']['events']} -> "
            f"{ex['states']} states, {ex['transitions']} transitions "
            f"({report['states_per_sec']}/s), "
            f"exhaustive={ex['exhaustive']}"
        )
        if "reduction" in report:
            r = report["reduction"]
            print(
                f"mc: reduction vs naive: {r['state_ratio']}x states "
                f"({r['naive_states']}), {r['transition_ratio']}x "
                f"transitions ({r['naive_transitions']})"
            )
        cex = report.get("counterexample")
        if cex:
            v = cex["violation"]
            print(
                f"mc: VIOLATION {v['invariant']} at role {v['role']} "
                f"(step {v['step']}): {v['message']}"
            )
            print(
                f"mc: counterexample minimized {cex['schedule_len']} -> "
                f"{cex['minimized_len']} actions; replay reproduced="
                f"{cex['replay_reproduced']} digests_match="
                f"{cex['replay_digests_match']}"
            )
            if "caught_expected" in cex:
                print(
                    f"mc: mutation {report['mutate']} expected "
                    f"{cex['expected_invariant']}: caught="
                    f"{cex['caught_expected']}"
                )
        elif ex["violations_found"] == 0 and ex["exhaustive"]:
            print("mc: all invariants hold over the explored space")
    if report["explore"]["violations_found"]:
        return 1
    if not report["explore"]["exhaustive"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
