"""Counterexample pipeline: confirm, delta-debug, emit, replay.

A violation found by the explorer comes with the schedule (action list)
that reached it.  This module (1) re-confirms the violation through the
live ``World.run_schedule`` path, (2) ddmin-minimizes the schedule to a
locally-irreducible witness that still triggers the SAME invariant,
(3) emits it as a self-contained JSON document (world parameters,
schedule, violation, per-node state digests, wire-trace digest, crypto
backend), and (4) replays such a document deterministically —
re-building the world from the recorded parameters and asserting the
replay reproduces the identical violation and identical
``Node.state_digest()`` bytes.  The chaos harness's
``replay_counterexample`` builds on :func:`replay` and adds the
cross-engine parity rows.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

from tpu_swirld import crypto

from tpu_swirld.analysis.mc.invariants import (
    Violation, check_edge, check_state,
)
from tpu_swirld.analysis.mc.world import World


def _trace_digest(traces: List[tuple]) -> str:
    parts = [
        b"%d:%d:%s" % (s, d, c.encode()) for tr in traces for (s, d, c) in tr
    ]
    return crypto.hash_bytes(b"|".join(parts)).hex()[:32]


def run_checked(world: World, schedule: List[tuple]) -> Dict:
    """Live replay of ``schedule`` with the full invariant catalog
    evaluated after every step; stops at the first violation.

    Returns ``{"violation", "step", "digests", "trace_digest"}`` —
    digests are the honest roles' ``Node.state_digest()`` at the point
    the run stopped (violation or schedule end)."""
    found: List[Tuple[int, Violation]] = []
    traces: List[tuple] = []

    class _Stop(Exception):
        pass

    def on_step(step, state_after, result, parent_actor, actor):
        traces.append(result.trace)
        if world.roles[result.actor_role].kind == "honest":
            evs = check_edge(world, schedule[step], parent_actor, actor)
            if evs:
                found.append((step, evs[0]))
                raise _Stop
        vs = check_state(world, state_after)
        if vs:
            found.append((step, vs[0]))
            raise _Stop

    try:
        nodes = world.run_schedule(schedule, on_step=on_step)
    except _Stop:
        nodes = None
    if nodes is None:
        # re-run without checks to recover the node map at the stop
        # point (cheap: materialization caches are hot)
        stop = found[0][0] + 1
        nodes = world.run_schedule(schedule[:stop])
    digests = {
        str(i): nodes[i].state_digest().hex() for i in world.honest_roles
    }
    violation = found[0][1] if found else None
    return {
        "violation": violation,
        "step": found[0][0] if found else None,
        "digests": digests,
        "trace_digest": _trace_digest(traces),
        "_nodes": nodes,   # live role -> Node map; not JSON-serializable
    }


def ddmin(
    schedule: List[tuple],
    test: Callable[[List[tuple]], bool],
) -> List[tuple]:
    """Zeller/Hildebrandt ddmin over the action list: returns a
    1-minimal subsequence for which ``test`` still holds."""
    if not test(schedule):
        raise ValueError("ddmin: full schedule does not satisfy the test")
    n = 2
    while len(schedule) >= 2:
        size = len(schedule) // n
        reduced = False
        for i in range(n):
            lo, hi = i * size, (i + 1) * size if i < n - 1 else len(schedule)
            cand = schedule[:lo] + schedule[hi:]
            if cand and test(cand):
                schedule = cand
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(schedule):
                break
            n = min(len(schedule), n * 2)
    return schedule


def minimize(world: World, schedule: List[tuple],
             invariant_id: str) -> List[tuple]:
    """ddmin the schedule down to a witness that still fires
    ``invariant_id``.  Reuses ``world`` across probes — the event table
    is append-only and the materialization caches stay hot, and actions
    whose prerequisites were removed degrade to no-ops, so every
    subsequence is a valid schedule."""

    def still_fails(cand: List[tuple]) -> bool:
        r = run_checked(world, list(cand))
        return r["violation"] is not None and (
            r["violation"].invariant == invariant_id
        )

    return list(ddmin(list(schedule), still_fails))


# ----------------------------------------------------------------- JSON


def emit(world: World, schedule: List[tuple], report: Dict,
         mutate: Optional[str] = None) -> Dict:
    """Self-contained replayable scenario document (the
    ``ChaosSimulation``-style JSON the chaos harness ingests).  With a
    violation in ``report`` this is a counterexample; with none it is a
    clean replayable schedule (the chaos ``--mc`` parity probe uses
    those), and replaying asserts it STAYS clean and bit-identical."""
    v: Optional[Violation] = report["violation"]
    return {
        "kind": "mc-counterexample",
        "version": 1,
        "world": {
            "n_honest": world.n_honest,
            "n_forkers": world.n_forkers,
            "events": world.events_budget,
            "seed": world.seed,
            "withhold": world.withhold,
            "stake": list(world.config.stakes()),
            "mutate": mutate,
            "crypto_backend": crypto.backend_name(),
        },
        "schedule": [list(a) for a in schedule],
        "violation": None if v is None else {
            **v.to_dict(),
            "step": report["step"],
        },
        "digests": report["digests"],
        "trace_digest": report["trace_digest"],
    }


def load_schedule(doc: Dict) -> List[tuple]:
    return [tuple(a) for a in doc["schedule"]]


def world_from_doc(doc: Dict) -> World:
    from tpu_swirld.config import SwirldConfig

    from tpu_swirld.analysis.mc.mutations import MUTATIONS, make_world

    w = doc["world"]
    kw = dict(
        n_honest=w["n_honest"],
        n_forkers=w["n_forkers"],
        events=w["events"],
        seed=w["seed"],
        withhold=w.get("withhold", False),
    )
    mutate = w.get("mutate")
    if w.get("stake") is not None:
        # a recorded stake distribution overrides even the mutation's
        # default config — the doc must replay in ITS world, not the
        # current default for that mutation
        default = None
        if mutate is not None:
            default = MUTATIONS[mutate].world_kwargs.get("config")
        stake = tuple(w["stake"])
        if default is None or default.stakes() != stake:
            kw["config"] = SwirldConfig(
                n_members=kw["n_honest"] + kw["n_forkers"],
                stake=stake, seed=w["seed"],
            )
    return make_world(mutate=mutate, **kw)


def replay(doc: Dict) -> Dict:
    """Replay a counterexample document from scratch and compare against
    its recorded violation and state digests, bit for bit.

    Returns a report with ``reproduced`` (violation id/role/message all
    match), ``digests_match`` and ``trace_match`` (exact determinism of
    the rebuilt world), and the fresh observations."""
    if doc.get("kind") != "mc-counterexample":
        raise ValueError("not an mc-counterexample document")
    want_backend = doc["world"].get("crypto_backend", "sim")
    prev = crypto.backend_name()
    crypto.set_backend(want_backend)
    try:
        world = world_from_doc(doc)
        report = run_checked(world, load_schedule(doc))
    finally:
        crypto.set_backend(prev)
    got_v = report["violation"]
    want_v = doc["violation"]
    if want_v is None:
        reproduced = got_v is None
    else:
        reproduced = (
            got_v is not None
            and got_v.invariant == want_v["invariant"]
            and got_v.role == want_v["role"]
            and got_v.message == want_v["message"]
            and report["step"] == want_v["step"]
        )
    return {
        "reproduced": reproduced,
        "digests_match": report["digests"] == doc["digests"],
        "trace_match": report["trace_digest"] == doc["trace_digest"],
        "violation": None if got_v is None else {
            **got_v.to_dict(), "step": report["step"],
        },
        "digests": report["digests"],
        "_world": world,           # not JSON-serializable
        "_nodes": report["_nodes"],
    }


def save(doc: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def load(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)
