"""Explicit-state exploration with partial-order and symmetry reduction.

The search enumerates every reachable interleaving of the world's
actions up to the event budget, deduplicating on the canonical state key
(see :mod:`encode`) and evaluating the full invariant catalog at every
state and transition.

Partial-order reduction (sleep-set style, adjacent-rule):
from a state reached by last action ``b``, an enabled action ``a`` is
skipped iff ``a`` is independent of ``b`` (disjoint read/write role
footprints — see ``World.independent``) and ``a < b`` in the fixed total
order on actions.  The words that survive are exactly those with no
descending adjacent independent pair, i.e. the lexicographically
normal forms of Mazurkiewicz traces; the set of normal forms is
prefix-closed and contains one representative per trace, so every
reachable STATE is still visited — only redundant commuting orders are
pruned.  (Independence here is exact, not approximate: independent
actions touch disjoint node states and the budget decrement commutes.)

Bookkeeping makes the pruning sound under dedup: each visited canonical
key remembers which actions it has expanded; when a state is re-reached
through a different last action, only the newly-allowed actions run, and
when it is reached as a symmetry-equivalent twin (same canonical key,
different concrete digest), the full enabled set is re-offered
(conservative — the permutation need not respect the last-action
order).

``symmetry=False, por=False`` gives the naive baseline used for the
reduction-ratio report; both modes explore the same reachable state
space, which the smoke test asserts.
"""

from __future__ import annotations

import dataclasses
import random
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from tpu_swirld.analysis.mc.encode import StateEncoder
from tpu_swirld.analysis.mc.invariants import (
    Violation, check_edge, check_state,
)
from tpu_swirld.analysis.mc.world import MCState, World


@dataclasses.dataclass
class ExploreResult:
    states: int = 0
    transitions: int = 0
    noops: int = 0
    dedup_hits: int = 0
    symmetry_hits: int = 0
    por_skips: int = 0
    max_depth: int = 0
    exhaustive: bool = True
    violation: Optional[Violation] = None
    #: schedule (list of actions) reaching the violating state
    schedule: Optional[List[tuple]] = None
    violation_step: Optional[int] = None

    def to_dict(self) -> dict:
        d = {
            "states": self.states,
            "transitions": self.transitions,
            "noops": self.noops,
            "dedup_hits": self.dedup_hits,
            "symmetry_hits": self.symmetry_hits,
            "por_skips": self.por_skips,
            "max_depth": self.max_depth,
            "exhaustive": self.exhaustive,
            "violations_found": 0 if self.violation is None else 1,
        }
        if self.violation is not None:
            d["violation"] = self.violation.to_dict()
            d["schedule_len"] = len(self.schedule or [])
        return d


class _Record:
    __slots__ = ("concrete", "expanded")

    def __init__(self, concrete: bytes):
        self.concrete = concrete
        self.expanded: Set[tuple] = set()


def _act_key(action: tuple) -> tuple:
    return (action[0],) + tuple(action[1:])


def explore(
    world: World,
    *,
    por: bool = True,
    symmetry: bool = True,
    mode: str = "bfs",
    max_states: int = 200_000,
    check_invariants: bool = True,
) -> ExploreResult:
    """Explore ``world`` from its initial state.

    ``mode="bfs"`` is the exhaustive proof search (shortest
    counterexamples); ``mode="dfs"`` is the hunt mode used for mutation
    runs — creations first, stops at the first violation.  Exceeding
    ``max_states`` clears ``exhaustive`` and returns what was proven.
    """
    enc = StateEncoder(world, symmetry=symmetry)
    res = ExploreResult()
    visited: Dict[bytes, _Record] = {}
    edge_checked: Set[tuple] = set()

    init = world.initial_state()
    init_concrete, init_key = enc.state_keys(init)
    visited[init_key] = _Record(init_concrete)
    res.states = 1
    if check_invariants:
        vs = check_state(world, init)
        if vs:
            res.violation, res.schedule, res.violation_step = vs[0], [], -1
            return res

    # queue entries: (state, key, path, last_action or None)
    Item = Tuple[MCState, bytes, Tuple[tuple, ...], Optional[tuple]]
    queue: deque = deque()
    queue.append((init, init_key, (), None))
    pop = queue.popleft if mode == "bfs" else queue.pop

    while queue:
        state, key, path, last = pop()
        rec = visited[key]
        enabled = world.enabled_actions(state)
        if por and last is not None:
            kept = []
            for a in enabled:
                if (
                    World.independent(a, last)
                    and _act_key(a) < _act_key(last)
                ):
                    res.por_skips += 1
                else:
                    kept.append(a)
            enabled = kept
        if mode == "dfs":
            # hunt heuristic: expand event-creating actions last so the
            # DFS stack pops them first
            enabled.sort(key=lambda a: a[0] in ("sync", "ext", "wext"))
        for action in enabled:
            if action in rec.expanded:
                res.dedup_hits += 1
                continue
            rec.expanded.add(action)
            result = world.apply(state, action)
            if result.noop:
                res.noops += 1
                continue
            res.transitions += 1
            child, child_path = result.state, path + (action,)
            res.max_depth = max(res.max_depth, len(child_path))
            if check_invariants:
                actor_role = action[1]
                tkey = world.transition_key(state, action)
                if (
                    world.roles[actor_role].kind == "honest"
                    and tkey not in edge_checked
                ):
                    edge_checked.add(tkey)
                    parent_node = world.node_for(
                        actor_role, state.histories[actor_role])
                    child_node = world.node_for(
                        actor_role, child.histories[actor_role])
                    evs = check_edge(world, action, parent_node, child_node)
                    if evs:
                        res.violation = evs[0]
                        res.schedule = list(child_path)
                        res.violation_step = len(child_path) - 1
                        return res
            child_concrete, child_key = enc.state_keys(child)
            crec = visited.get(child_key)
            if crec is None:
                visited[child_key] = _Record(child_concrete)
                res.states += 1
                if check_invariants:
                    vs = check_state(world, child)
                    if vs:
                        res.violation = vs[0]
                        res.schedule = list(child_path)
                        res.violation_step = len(child_path) - 1
                        return res
                if res.states >= max_states:
                    res.exhaustive = False
                    return res
                queue.append((child, child_key, child_path, action))
            else:
                res.dedup_hits += 1
                if crec.concrete != child_concrete:
                    # symmetry-equivalent twin: the recorded expansions
                    # were made under a different labeling, so re-offer
                    # everything not yet expanded, with POR disabled for
                    # this arrival (conservative)
                    res.symmetry_hits += 1
                    queue.append((child, child_key, child_path, None))
                else:
                    # same state via a different last action: its sleep
                    # set differs, so re-offer — the expanded set on the
                    # record keeps this from re-running transitions, and
                    # every enqueue is paid for by one executed
                    # transition, so the loop terminates
                    queue.append((child, child_key, child_path, action))
    return res


def hunt(
    world: World,
    *,
    walks: int = 4000,
    max_steps: Optional[int] = None,
    seed: int = 0,
) -> ExploreResult:
    """Violation hunt by seeded weighted random walks (the mutation
    mode).  Each walk samples actions with ``World.hunt_weight`` bias
    (creation-heavy, gossip-ladder-friendly) and evaluates the full
    invariant catalog after every step; distinct states and transitions
    are only checked once across all walks (the transition memo makes
    revisits near-free).  Deterministic for a fixed ``(world, seed)``.
    Returns on the first violation with the reaching schedule;
    ``exhaustive`` is always False — this is a search, not a proof."""
    rng = random.Random(seed ^ 0x5EED)
    cap = max_steps if max_steps is not None else world.events_budget + 10
    res = ExploreResult(exhaustive=False)
    edge_checked: Set[tuple] = set()
    state_checked: Set[MCState] = set()

    init = world.initial_state()
    state_checked.add(init)
    res.states = 1
    vs = check_state(world, init)
    if vs:
        res.violation, res.schedule, res.violation_step = vs[0], [], -1
        return res

    for _ in range(walks):
        state = init
        path: List[tuple] = []
        for _step in range(cap):
            enabled = world.enabled_actions(state)
            if not enabled:
                break
            weights = [world.hunt_weight(state, a) for a in enabled]
            action = rng.choices(enabled, weights=weights)[0]
            result = world.apply(state, action)
            if result.noop:
                res.noops += 1
                continue
            path.append(action)
            child = result.state
            res.max_depth = max(res.max_depth, len(path))
            tkey = world.transition_key(state, action)
            if tkey not in edge_checked:
                edge_checked.add(tkey)
                res.transitions += 1
                if world.roles[action[1]].kind == "honest":
                    evs = check_edge(
                        world, action,
                        world.node_for(action[1], state.histories[action[1]]),
                        world.node_for(action[1], child.histories[action[1]]),
                    )
                    if evs:
                        res.violation = evs[0]
                        res.schedule = list(path)
                        res.violation_step = len(path) - 1
                        return res
            if child not in state_checked:
                state_checked.add(child)
                res.states += 1
                vs = check_state(world, child)
                if vs:
                    res.violation = vs[0]
                    res.schedule = list(path)
                    res.violation_step = len(path) - 1
                    return res
            else:
                res.dedup_hits += 1
            state = child
    return res


def compare_reductions(world_factory, **kw) -> dict:
    """Run reduced vs naive exploration on twin worlds and report the
    state/transition reduction ratios.  ``world_factory`` must build a
    fresh, identically-parameterized world per call."""
    reduced = explore(world_factory(), por=True, symmetry=True, **kw)
    naive = explore(world_factory(), por=False, symmetry=False, **kw)
    out = {
        "reduced": reduced.to_dict(),
        "naive": naive.to_dict(),
        "state_ratio": (
            naive.states / reduced.states if reduced.states else 0.0
        ),
        "transition_ratio": (
            naive.transitions / reduced.transitions
            if reduced.transitions else 0.0
        ),
        "same_coverage": (
            reduced.exhaustive and naive.exhaustive
            and reduced.violation is None and naive.violation is None
        ),
    }
    return out
