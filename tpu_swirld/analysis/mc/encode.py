"""Canonical state encoding: dedup digests + creator-symmetry reduction.

Two schedules that leave every role with the same ingest history are the
same state — the *concrete digest* hashes the histories directly (event
ids are already content hashes).  On top of that, honest members with
equal stake are interchangeable: relabeling honest creators by a
permutation maps reachable states to reachable states, violations to
violations.  The *canonical key* is the minimum over all honest-member
permutations of a structural digest in which each event is encoded by
``(permuted creator slot, timestamp, parent codes, payload tag)``
instead of its id, and the honest history slots are permuted to match.
Attacker members keep their identity (they are parameterized separately
by the world), but their events re-encode through the permuted honest
ancestry.

Soundness: invariants are role-symmetric (they quantify over honest
nodes) and enabled actions permute bijectively, so exploring only the
lexicographically-least representative of each orbit covers every
violation up to renaming.  The naive baseline (``symmetry=False``)
uses the concrete digest as the key, which is what the reduction-ratio
report compares against.
"""

from __future__ import annotations

from itertools import permutations
from typing import Dict, List, Tuple

from tpu_swirld import crypto

from tpu_swirld.analysis.mc.world import MCState, World

_SEP = b"\x00"


def _digest(parts: List[bytes]) -> bytes:
    return crypto.hash_bytes(_SEP.join(parts))[:16]


class StateEncoder:
    """Per-world encoder with memoized structural event codes.

    Event codes are memoized per ``(perm, event id)`` — the event table
    is append-only and codes of shared ancestry are reused across the
    whole exploration, so canonicalization stays cheap even with
    ``n_honest!`` permutations in play.
    """

    def __init__(self, world: World, symmetry: bool = True):
        self.world = world
        self._member_index = {m: i for i, m in enumerate(world.members)}
        self._codes: Dict[Tuple[tuple, bytes], bytes] = {}
        self._state_keys: Dict[MCState, Tuple[bytes, bytes]] = {}
        if symmetry and world.n_honest > 1 and self._honest_stakes_equal():
            self.perms: List[tuple] = [
                p + tuple(range(world.n_honest, len(world.members)))
                for p in permutations(range(world.n_honest))
            ]
        else:
            self.perms = [tuple(range(len(world.members)))]

    def _honest_stakes_equal(self) -> bool:
        stakes = self.world.config.stakes()
        honest = {stakes[i] for i in range(self.world.n_honest)}
        return len(honest) == 1

    # ------------------------------------------------------------ codes

    def _code(self, perm: tuple, eid: bytes) -> bytes:
        memo = self._codes
        key = (perm, eid)
        got = memo.get(key)
        if got is not None:
            return got
        # iterative post-order: events reference strictly earlier mints,
        # so the stack is bounded by the table size
        stack = [eid]
        while stack:
            top = stack[-1]
            if (perm, top) in memo:
                stack.pop()
                continue
            ev = self.world.events[top]
            missing = [p for p in ev.p if (perm, p) not in memo]
            if missing:
                stack.extend(missing)
                continue
            stack.pop()
            parts = [
                b"%d" % perm[self._member_index[ev.c]],
                b"%d" % ev.t,
                ev.d,
            ] + [memo[(perm, p)] for p in ev.p]
            memo[(perm, top)] = _digest(parts)
        return memo[key]

    # ------------------------------------------------------------- keys

    def _encode(self, state: MCState, perm: tuple) -> bytes:
        world = self.world
        n_h = world.n_honest
        # honest slots travel with the permutation; branch slots are
        # fixed (forker members are identity under perm) but their
        # contents re-encode through the permuted honest ancestry
        slots: List[bytes] = [b""] * len(world.roles)
        for r, hist in enumerate(state.histories):
            slot = perm[r] if r < n_h else r
            slots[slot] = _digest([self._code(perm, eid) for eid in hist])
        heads = [self._code(perm, h) for h in state.heads]
        return _digest(slots + heads + [b"%d" % state.created])

    def state_keys(self, state: MCState) -> Tuple[bytes, bytes]:
        """(concrete digest, canonical key) in one pass, memoized per
        state — the identity permutation's encoding is the concrete
        digest, the orbit minimum is the canonical key."""
        got = self._state_keys.get(state)
        if got is not None:
            return got
        concrete = self._encode(state, self.perms[0])
        if len(self.perms) == 1:
            keys = (concrete, concrete)
        else:
            keys = (concrete, min(
                [concrete]
                + [self._encode(state, p) for p in self.perms[1:]]
            ))
        self._state_keys[state] = keys
        return keys

    def concrete_digest(self, state: MCState) -> bytes:
        return self.state_keys(state)[0]

    def canonical_key(self, state: MCState) -> bytes:
        return self.state_keys(state)[1]
